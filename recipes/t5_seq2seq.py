"""Recipe 6 (beyond-reference): T5 seq2seq on a synthetic transduction task.

The five blueprint recipes cover decoder-only, encoder-only, and vision;
this one exercises the encoder-decoder family end to end through the SAME
Trainer/Strategy machinery: T5 learns to REVERSE (or copy) token
sequences — a task with an exact-match answer, so the end-of-run
generation check is a real measurement, not a smoke print.

Offline by construction (synthetic data; random-init model). The eval
reports teacher-forced token accuracy during training and greedy
``generate_encdec`` exact-match at the end.

Run:
    python recipes/t5_seq2seq.py --size tiny --steps-per-epoch 3
    # learns reversal to exact-match ~1.0 in ~1500 steps (~90 s on the
    # 1-core CPU box; measured r4):
    python recipes/t5_seq2seq.py --size tiny --epochs 50 --steps-per-epoch 30
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
from pytorch_distributed_tpu.models import (
    T5Config,
    T5ForConditionalGeneration,
    generate_encdec,
    t5_partition_rules,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    fit_elastic,
    seq2seq_eval_step,
    seq2seq_lm_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0

SIZES = {"tiny": T5Config.tiny, "small": T5Config.small}


def make_task(n, seq_len, vocab, task, eos_id, seed):
    """input [n, S] of random tokens (ids >= 2), labels = transformed
    input + EOS; fixed [n, S+1] label rows, all positions real."""
    rng = np.random.default_rng(seed)
    src = rng.integers(2, vocab, size=(n, seq_len)).astype(np.int32)
    out = src[:, ::-1] if task == "reverse" else src
    labels = np.concatenate(
        [out, np.full((n, 1), eos_id, np.int32)], axis=1
    )
    return ArrayDataset(
        input_ids=src,
        labels=labels,
        label_mask=np.ones_like(labels, dtype=bool),
    )


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None)
    p.add_argument("--size", choices=SIZES, default="tiny")
    p.add_argument("--task", choices=("reverse", "copy"), default="reverse")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=8)
    p.add_argument(
        "--vocab", type=int, default=64,
        help="task vocab (shrinks the model's table to match; the "
        "transduction is learnable at tiny scale with a small vocab — "
        "64 tokens reaches exact-match ~1.0, the config default 32k "
        "would need a bigger model)",
    )
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--eval-rows", type=int, default=64)
    p.add_argument("--dropout", type=float, default=0.0)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    ptd.seed_all(args.seed)
    ptd.init_process_group(
        args.backend, mesh_spec=MeshSpec(dp=args.dp, tp=args.tp)
    )
    log_rank0("world=%d backend=%s", ptd.get_world_size(), ptd.get_backend())

    import dataclasses

    # a synthetic transduction task has no overfitting to regularize
    # away — dropout only slows the point of the demo (learning the
    # task); --dropout restores it for realistic-data runs
    cfg = dataclasses.replace(
        SIZES[args.size](), dropout_rate=args.dropout,
        vocab_size=args.vocab,
    )
    model = T5ForConditionalGeneration(cfg)
    n = (args.steps_per_epoch or 50) * args.batch_size
    ds = make_task(
        n, args.seq_len, cfg.vocab_size, args.task, cfg.eos_token_id,
        args.seed,
    )
    eval_ds = make_task(
        max(args.batch_size, args.eval_rows), args.seq_len,
        cfg.vocab_size, args.task, cfg.eos_token_id, args.seed + 1,
    )

    dummy = jnp.zeros((1, args.seq_len), jnp.int32)
    variables = model.init(
        jax.random.key(args.seed), dummy,
        jnp.zeros((1, args.seq_len + 1), jnp.int32),
    )
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=optax.chain(
            optax.clip_by_global_norm(1.0), optax.adamw(args.lr)
        ),
    )
    strategy = DataParallel(extra_rules=t5_partition_rules())
    trainer = Trainer(
        state,
        strategy,
        build_train_step(seq2seq_lm_loss_fn(model)),
        DataLoader(
            ds, args.batch_size, seed=args.seed,
            sharding=strategy.batch_sharding(),
        ),
        eval_step=seq2seq_eval_step(model),
        eval_loader=DataLoader(
            eval_ds, args.batch_size, shuffle=False,
            sharding=strategy.batch_sharding(),
        ),
        config=TrainerConfig(
            epochs=args.epochs, log_every=args.log_every,
            ckpt_dir=args.ckpt_dir, samples_axis="input_ids",
        ),
    )
    trainer.restore_checkpoint()
    state = fit_elastic(trainer)
    log_rank0("done: step=%d eval=%s", int(state.step),
              trainer.last_eval_metrics)

    # the task has an exact answer: greedy decode and score it
    k = min(args.eval_rows, args.batch_size)
    batch = [eval_ds[i] for i in range(k)]
    enc = jnp.asarray(np.stack([b["input_ids"] for b in batch]))
    want = np.stack([b["labels"] for b in batch])
    out = np.asarray(
        jax.jit(
            lambda p, ids: generate_encdec(
                model, p, ids, max_new_tokens=want.shape[1], eos_id=-1
            )
        )(state.params, enc)
    )
    exact = float((out == want).all(axis=1).mean())
    tok = float((out == want).mean())
    log_rank0(
        "%s exact-match %.3f  token-match %.3f over %d rows",
        args.task, exact, tok, k,
    )
    return state


if __name__ == "__main__":
    main()
