"""Recipe 1: ResNet-18 / CIFAR-10 — single-process smoke test.

Mirrors the reference's first recipe (BASELINE.json:7: "ResNet-18 /
CIFAR-10, single-process gloo backend (CPU smoke test)"): the same script
runs on host CPU (``--backend gloo``) or on TPU, and scales to any mesh by
changing only ``--dp`` — the "same training scripts" property the north
star asks for (BASELINE.json:5).

Run:
    python recipes/resnet18_cifar10.py --epochs 1 --batch-size 128
    python recipes/resnet18_cifar10.py --backend gloo --synthetic \
        --steps-per-epoch 5   # pure smoke
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import (
    DataLoader,
    ImageBatchPipeline,
    SyntheticImageDataset,
    load_cifar10,
)
from pytorch_distributed_tpu.models import ResNet18
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    classification_eval_step,
    classification_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None, help="ici|gloo (default: auto)")
    p.add_argument("--grad-compress", default=None,
                   choices=("bf16", "fp16", "int8"),
                   help="compress multi-process gradient sync on the wire")
    p.add_argument("--epochs", type=int, default=2)
    p.add_argument("--batch-size", type=int, default=128, help="global batch")
    p.add_argument("--lr", type=float, default=0.1)
    p.add_argument("--momentum", type=float, default=0.9)
    p.add_argument("--weight-decay", type=float, default=5e-4)
    p.add_argument("--dp", type=int, default=-1, help="data-parallel width")
    p.add_argument("--data-dir", default="/tmp/data")
    p.add_argument("--synthetic", action="store_true", help="skip real CIFAR")
    p.add_argument("--steps-per-epoch", type=int, default=None,
                   help="truncate epochs (smoke testing)")
    p.add_argument("--no-device-normalize", dest="device_normalize",
                   action="store_false",
                   help="host f32 normalize instead of the default "
                   "uint8-over-the-wire + on-device normalize ingest")
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=50)
    p.add_argument("--metrics-path", default=None,
                   help="JSONL scalar metrics log (rank 0)")
    p.add_argument("--trace-dir", default=None,
                   help="span-tracer output dir: Perfetto-loadable "
                   "trace.json + JSONL rollups (runtime/tracing.py)")
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    ptd.seed_all(args.seed)
    ptd.init_process_group(args.backend, mesh_spec=MeshSpec(dp=args.dp))
    log_rank0(
        "world=%d backend=%s", ptd.get_world_size(), ptd.get_backend()
    )

    train_ds = None if args.synthetic else load_cifar10(
        args.data_dir, train=True, raw_uint8=True
    )
    eval_ds = None if args.synthetic else load_cifar10(
        args.data_dir, train=False, raw_uint8=True
    )
    # real data goes through the native augmenting pipeline (pad-4 random
    # crop + flip — the reference recipe's torchvision transforms,
    # assembled in C++ threads), shipping raw uint8 by default with the
    # normalize fused into the jitted step; synthetic stays on the plain
    # gather path (uint8 by default too, same wire profile)
    cifar_mean, cifar_std = (0.4914, 0.4822, 0.4465), (0.247, 0.243, 0.262)
    train_fetch = eval_fetch = None
    train_normalizer = eval_normalizer = None
    if train_ds is not None:
        train_fetch = ImageBatchPipeline(
            32, train=True, pad=4, mean=cifar_mean, std=cifar_std,
            seed=args.seed, device_normalize=args.device_normalize,
        )
        eval_fetch = ImageBatchPipeline(
            32, train=False, mean=cifar_mean, std=cifar_std,
            device_normalize=args.device_normalize,
        )
        if args.device_normalize:
            train_normalizer = train_fetch.device_normalizer()
            eval_normalizer = eval_fetch.device_normalizer()
    if train_ds is None:
        log_rank0("CIFAR-10 files not found — using synthetic data")
        dtype = np.uint8 if args.device_normalize else np.float32
        train_ds = SyntheticImageDataset(
            n=50_000, seed=args.seed, dtype=dtype
        )
        eval_ds = SyntheticImageDataset(
            n=10_000, seed=args.seed + 1, dtype=dtype
        )
        if args.device_normalize:
            from pytorch_distributed_tpu.data import device_normalizer_for

            train_normalizer = device_normalizer_for(cifar_mean, cifar_std)
            eval_normalizer = device_normalizer_for(cifar_mean, cifar_std)

    if args.steps_per_epoch:
        n = args.steps_per_epoch * args.batch_size
        train_ds = _truncate(train_ds, n)
        eval_ds = _truncate(eval_ds, min(len(eval_ds), args.batch_size * 2))

    model = ResNet18(num_classes=10, stem="cifar")
    variables = model.init(
        jax.random.key(args.seed),
        jax.numpy.zeros((1, 32, 32, 3)),
        train=False,
    )
    steps_per_epoch = len(train_ds) // args.batch_size
    schedule = optax.cosine_decay_schedule(
        args.lr, decay_steps=max(args.epochs * steps_per_epoch, 1)
    )
    tx = optax.sgd(schedule, momentum=args.momentum, nesterov=True)
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables["batch_stats"],
    )

    strategy = DataParallel()
    train_loader = DataLoader(
        train_ds, args.batch_size, seed=args.seed,
        sharding=strategy.batch_sharding(), fetch=train_fetch,
    )
    eval_loader = DataLoader(
        eval_ds, args.batch_size, shuffle=False, drop_last=False,
        sharding=strategy.batch_sharding(), fetch=eval_fetch,
    )

    trainer = Trainer(
        state,
        strategy,
        build_train_step(
            classification_loss_fn(model, weight_decay=args.weight_decay),
            grad_compression=args.grad_compress,
            batch_transform=train_normalizer,
        ),
        train_loader,
        eval_step=classification_eval_step(
            model, batch_transform=eval_normalizer
        ),
        eval_loader=eval_loader,
        config=TrainerConfig(
            epochs=args.epochs,
            log_every=args.log_every,
            ckpt_dir=args.ckpt_dir,
            metrics_path=args.metrics_path,
            trace=args.trace_dir,
        ),
    )
    trainer.restore_checkpoint()
    state = fit_elastic(trainer)  # fit() already evaluates the final epoch
    metrics = trainer.last_eval_metrics
    log_rank0("done: step=%d %s", int(state.step), metrics)
    return metrics


def _truncate(ds, n):
    from pytorch_distributed_tpu.data import ArrayDataset

    if hasattr(ds, "arrays"):
        return ArrayDataset(**{k: v[:n] for k, v in ds.arrays.items()})
    ds = type(ds)(n=min(n, len(ds)), seed=ds.seed, dtype=ds.dtype)
    return ds


if __name__ == "__main__":
    main()
