"""Recipe 7 (beyond-reference): Mixtral sparse-MoE LM on a successor task.

Exercises the expert-parallel family end to end through the SAME
Trainer/Strategy machinery: a tiny Mixtral learns a deterministic
successor chain ``next = (a * tok + b) mod vocab`` — every next token is
exactly predictable from the current one, so the end-of-run greedy
continuation check is a real measurement (exact-match), not a smoke
print. The router's load-balance auxiliary loss rides the task loss
(``causal_lm_loss_fn(moe_aux_weight=...)``), and the expert tensors
shard over the ``ep`` mesh axis (``--ep``), composing with dp/tp.

Offline by construction (synthetic data; random-init model). Measured on
the 1-core CPU box (r5): ``--epochs 30`` (1500 steps) reaches
exact-match 1.000 in ~90 s.

Run:
    python recipes/mixtral_moe.py --epochs 2 --steps-per-epoch 5  # smoke
    python recipes/mixtral_moe.py --epochs 30                     # learns
    python recipes/mixtral_moe.py --ep 2 --dp -1                  # EP mesh
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
from pytorch_distributed_tpu.models import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_partition_rules,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
    fit_elastic,
)
from pytorch_distributed_tpu.utils import log_rank0


def successor_chain(tok, steps, a, b, vocab):
    out = [tok]
    for _ in range(steps):
        out.append((out[-1] * a + b) % vocab)
    return np.stack(out, axis=-1)


def make_task(n, seq_len, vocab, a, b, seed):
    rng = np.random.default_rng(seed)
    start = rng.integers(0, vocab, size=(n,)).astype(np.int64)
    ids = successor_chain(start, seq_len - 1, a, b, vocab)
    return ArrayDataset(input_ids=ids.astype(np.int32))


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=16)
    p.add_argument(
        "--vocab", type=int, default=64,
        help="successor-task vocab (shrinks the model's table to match)",
    )
    p.add_argument("--lr", type=float, default=3e-3)
    p.add_argument("--aux-weight", type=float, default=0.01)
    p.add_argument(
        "--capacity-factor", type=float, default=1.25,
        help="Switch bounded-capacity training dispatch; pass 0 for the "
        "drop-free (serving/parity) mode",
    )
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--ep", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument("--eval-rows", type=int, default=32)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    ptd.seed_all(args.seed)
    ptd.init_process_group(
        args.backend,
        mesh_spec=MeshSpec(dp=args.dp, ep=args.ep, tp=args.tp),
    )
    log_rank0("world=%d backend=%s", ptd.get_world_size(), ptd.get_backend())

    import dataclasses

    cfg = dataclasses.replace(
        MixtralConfig.tiny(),
        vocab_size=args.vocab,
        max_seq_len=max(args.seq_len * 2, 32),
        capacity_factor=args.capacity_factor or None,
    )
    model = MixtralForCausalLM(cfg)
    a_mult, b_add = 5, 7  # coprime with vocab=64 -> full wander
    n = (args.steps_per_epoch or 50) * args.batch_size
    ds = make_task(n, args.seq_len, cfg.vocab_size, a_mult, b_add, args.seed)

    dummy = jnp.zeros((1, args.seq_len), jnp.int32)
    variables = model.init(jax.random.key(args.seed), dummy)
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=optax.chain(
            optax.clip_by_global_norm(1.0), optax.adamw(args.lr)
        ),
    )
    strategy = DataParallel(extra_rules=mixtral_partition_rules())
    trainer = Trainer(
        state,
        strategy,
        build_train_step(
            causal_lm_loss_fn(model, moe_aux_weight=args.aux_weight)
        ),
        DataLoader(
            ds, args.batch_size, seed=args.seed,
            sharding=strategy.batch_sharding(),
        ),
        config=TrainerConfig(
            epochs=args.epochs, log_every=args.log_every,
            ckpt_dir=args.ckpt_dir, samples_axis="input_ids",
        ),
    )
    trainer.restore_checkpoint()
    state = fit_elastic(trainer)
    log_rank0("done: step=%d", int(state.step))

    # the successor function has an exact answer: greedy-continue fresh
    # starts and score every generated token against the true chain.
    # Serve DROP-FREE (capacity_factor=None): the bounded-capacity
    # training dispatch can zero an overflowing row's FFN contribution,
    # making row i's tokens depend on which rows share the eval batch —
    # the same checkpoint serves both modes (ops/moe.py)
    model = MixtralForCausalLM(
        dataclasses.replace(cfg, capacity_factor=None)
    )
    k = args.eval_rows
    rng = np.random.default_rng(args.seed + 1)
    start = rng.integers(0, cfg.vocab_size, size=(k,)).astype(np.int64)
    prompt_len, new = 2, args.seq_len - 2
    chain = successor_chain(start, prompt_len + new - 1, a_mult, b_add,
                            cfg.vocab_size)
    prompt = jnp.asarray(chain[:, :prompt_len].astype(np.int32))
    out = np.asarray(
        ptd.generate(model, state.params, prompt, max_new_tokens=new,
                     temperature=0.0)
    )
    want = chain[:, : prompt_len + new]
    exact = float((out == want).all(axis=1).mean())
    tok = float((out[:, prompt_len:] == want[:, prompt_len:]).mean())
    log_rank0(
        "successor exact-match %.3f  token-match %.3f over %d rows",
        exact, tok, k,
    )
    return state


if __name__ == "__main__":
    main()
