"""Recipe 2: ResNet-50 / ImageNet — DDP data-parallel (the north star).

Mirrors the reference's flagship recipe (BASELINE.json:8: "ResNet-50 /
ImageNet, DDP 8-way data parallel"; the north-star metric is its
images/sec/chip, BASELINE.json:2). The TPU-native shape: one process, a
``dp``-axis mesh over all chips, params replicated, batch sharded — XLA
emits the fused gradient allreduce the reference gets from DDP's bucketed
NCCL hooks.

ImageNet itself is not on disk in this environment (no network); the
recipe trains on a synthetic ImageNet-shaped stream (224x224x3, 1000
classes) unless ``--data-dir`` points at preprocessed arrays. Accuracy
targets therefore only mean something on real data; throughput (the
benchmark, bench.py) does not care.

Run:
    python recipes/resnet50_imagenet.py --dp 8 --batch-size 2048
    python recipes/resnet50_imagenet.py --backend gloo --synthetic \
        --steps-per-epoch 3 --batch-size 16 --image-size 64   # smoke
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import (
    DataLoader,
    SyntheticImageDataset,
    device_normalizer_for,
    host_flip_transform,
)
from pytorch_distributed_tpu.models import ResNet50
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    classification_eval_step,
    classification_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0, maybe_trace
from pytorch_distributed_tpu.utils.config import RecipeConfig, parse_cli


@dataclasses.dataclass
class Config(RecipeConfig):
    epochs: int = 90  # doc: standard ImageNet schedule
    batch_size: int = 1024  # doc: global batch (split over dp)
    lr: float = 0.4  # doc: peak LR (linear-scaling rule: 0.1 * batch/256)
    momentum: float = 0.9  # doc: SGD momentum
    weight_decay: float = 1e-4  # doc: L2 on conv/linear kernels
    label_smoothing: float = 0.1  # doc: softmax label smoothing
    warmup_epochs: int = 5  # doc: linear LR warmup epochs
    image_size: int = 224  # doc: square input resolution
    train_samples: int = 1_281_167  # doc: synthetic train-set size
    eval_samples: int = 50_000  # doc: synthetic eval-set size
    flip_augment: bool = True  # doc: random horizontal flip augmentation
    stem: str = "imagenet"  # doc: stem variant: imagenet | s2d (MXU-friendly)
    log_mfu: bool = False  # doc: append achieved TFLOP/s + MFU to step logs
    device_normalize: bool = True  # doc: ship uint8 batches, normalize on-chip (default ingest path; --no-device-normalize restores host f32)
    ema_decay: float = 0.0  # doc: ModelEMA decay (0 disables); evals use the shadow
    tensorboard_dir: str = ""  # doc: TensorBoard event-file dir (rank 0)
    io_retries: int = 2  # doc: transient read retries per sample (real-data path)
    bad_sample_budget: int = 100  # doc: max quarantined (undecodable) samples before hard error
    strategy: str = "dp"  # doc: parallel strategy: dp | zero1 | auto (cost-model planner, autoplan/)
    plan_path: str = "plan.json"  # doc: --strategy auto: ranked candidate report output
    costmodel: str = "costmodel.json"  # doc: --strategy auto: calibrated comms model (collective_bench --fit); missing -> analytic fallback, flagged


def main(argv=None):
    cfg: Config = parse_cli(Config, argv, description=__doc__)
    ptd.seed_all(cfg.seed)
    mesh_spec = MeshSpec(dp=cfg.dp)
    chosen = None
    if cfg.strategy == "auto":
        # plan BEFORE the group exists: one eval_shape, zero compiles;
        # the chosen candidate's mesh spec is what the group builds
        if "RANK" in os.environ:
            raise SystemExit(
                "--strategy auto plans the single-controller SPMD "
                "mesh; it is not supported under a per-rank launch"
            )
        if cfg.dp != -1:
            raise SystemExit(
                "--strategy auto chooses the mesh shape itself; drop "
                "--dp or pick a strategy explicitly"
            )
        from pytorch_distributed_tpu import autoplan

        pshape = (cfg.image_size, cfg.image_size, 3)
        plan_model = ResNet50(num_classes=1000, stem=cfg.stem)
        # constant-lr stand-in for the scheduled optimizer: the state
        # SHAPES (the only thing planning reads) are identical
        plan_tx = optax.sgd(cfg.lr, momentum=cfg.momentum, nesterov=True)

        def make_plan_state(key):
            variables = plan_model.init(
                key, jnp.zeros((1,) + pshape), train=False
            )
            return TrainState.create(
                apply_fn=plan_model.apply, params=variables["params"],
                tx=plan_tx, batch_stats=variables["batch_stats"],
                ema=cfg.ema_decay > 0,
            )

        plan_report = autoplan.plan(
            profile=autoplan.image_profile(
                # ResNet-50 at 224^2: ~4.1 GFLOPs forward (x3 trained),
                # ~64 MB of f32 feature maps; both scale with area
                flops_per_sample=3 * 4.1e9 * (cfg.image_size / 224) ** 2,
                activation_bytes_per_sample=(
                    64e6 * (cfg.image_size / 224) ** 2
                ),
            ),
            global_batch=cfg.batch_size,
            make_state_fn=make_plan_state,
            state_args=(jax.random.key(cfg.seed),),
            max_tp=1,  # no TP rule set for the conv net
            cost_model_path=cfg.costmodel,
            # single-controller SPMD collectives on this platform — a
            # hostring-calibrated model must not silently price them
            transport=f"spmd:{ptd.platform()}",
        )
        chosen = plan_report.best()
        plan_report.save(cfg.plan_path)
        log_rank0(
            "auto-parallel plan (full report: %s):\n%s",
            cfg.plan_path, plan_report.table(),
        )
        mesh_spec = chosen.mesh_spec()
    ptd.init_process_group(cfg.backend, mesh_spec=mesh_spec)
    log_rank0(
        "resnet50/imagenet: world=%d backend=%s batch=%d image=%d",
        ptd.get_world_size(), ptd.get_backend(), cfg.batch_size, cfg.image_size,
    )

    shape = (cfg.image_size, cfg.image_size, 3)
    # real ImageNet layout on disk (root/{train,val}/<class>/<img>)?
    real_root = (
        None if cfg.synthetic else
        cfg.data_dir if os.path.isdir(os.path.join(cfg.data_dir, "train"))
        else None
    )
    train_fetch = eval_fetch = None
    if real_root is not None:
        from pytorch_distributed_tpu.data import (
            FolderImagePipeline,
            ImageFolderDataset,
        )

        train_ds = ImageFolderDataset(os.path.join(real_root, "train"))
        eval_ds = ImageFolderDataset(os.path.join(real_root, "val"))
        # one quarantine (and one bad-sample budget) across train+eval:
        # both pipelines read the same disk
        from pytorch_distributed_tpu.data import SampleQuarantine

        quarantine = SampleQuarantine(cfg.bad_sample_budget)
        train_fetch = FolderImagePipeline(
            cfg.image_size, train=True, seed=cfg.seed,
            device_normalize=cfg.device_normalize,
            io_retries=cfg.io_retries, quarantine=quarantine,
        )
        eval_fetch = FolderImagePipeline(
            cfg.image_size, train=False,
            device_normalize=cfg.device_normalize,
            io_retries=cfg.io_retries, quarantine=quarantine,
        )
        n_train = len(train_ds)
        log_rank0(
            "real data: %d train / %d eval images, %d classes",
            n_train, len(eval_ds), len(train_ds.classes),
        )
    else:
        n_train = cfg.train_samples
        n_eval = cfg.eval_samples
        if cfg.steps_per_epoch:
            n_train = cfg.steps_per_epoch * cfg.batch_size
            n_eval = min(n_eval, cfg.batch_size * 2)
        # default ingest path: raw uint8 over the wire, normalize (and
        # flip) fused into the jitted step — same bytes-on-the-link
        # profile as the real-data path, so synthetic throughput numbers
        # mean something for deployment
        dtype = np.uint8 if cfg.device_normalize else np.float32
        train_ds = SyntheticImageDataset(
            n=n_train, image_shape=shape, num_classes=1000, seed=cfg.seed,
            dtype=dtype,
        )
        eval_ds = SyntheticImageDataset(
            n=n_eval, image_shape=shape, num_classes=1000, seed=cfg.seed + 1,
            dtype=dtype,
        )

    model = ResNet50(num_classes=1000, stem=cfg.stem)
    variables = model.init(
        jax.random.key(cfg.seed), jnp.zeros((1,) + shape), train=False
    )

    steps_per_epoch = max(n_train // cfg.batch_size, 1)
    total_steps = max(cfg.epochs * steps_per_epoch, 1)
    # smoke runs can be shorter than the nominal warmup; clamp so the
    # cosine phase keeps at least one step (optax rejects decay <= warmup)
    warmup_steps = min(cfg.warmup_epochs * steps_per_epoch, total_steps - 1)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
    )
    tx = optax.sgd(schedule, momentum=cfg.momentum, nesterov=True)
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
        batch_stats=variables["batch_stats"],
        ema=cfg.ema_decay > 0,
    )

    if chosen is not None:  # --strategy auto: the planner's pick
        strategy = chosen.build_strategy()
        log_rank0("auto strategy: %s -> %s", chosen.name,
                  strategy.describe())
    elif cfg.strategy == "zero1":
        from pytorch_distributed_tpu.parallel import ZeRO1

        strategy = ZeRO1()
    else:
        strategy = DataParallel()
    train_loader = DataLoader(
        train_ds, cfg.batch_size, seed=cfg.seed,
        sharding=strategy.batch_sharding(),
        fetch=train_fetch,
        transform=(
            host_flip_transform(cfg.seed)
            if cfg.flip_augment and train_fetch is None
            and not cfg.device_normalize else None
        ),  # the folder pipeline flips at decode; the u8 synthetic path
        # flips on-device inside the jitted step (see below)
    )
    eval_loader = DataLoader(
        eval_ds, cfg.batch_size, shuffle=False, drop_last=False,
        sharding=strategy.batch_sharding(),
        fetch=eval_fetch,
    )

    train_normalizer = eval_normalizer = None
    if cfg.device_normalize:
        if train_fetch is not None:
            # folder pipelines flip/crop at decode; only the normalize
            # moves on-device
            train_normalizer = train_fetch.device_normalizer()
            eval_normalizer = eval_fetch.device_normalizer()
        else:
            # synthetic u8 path: normalize AND flip fused into the
            # jitted step (the host never touches the pixels)
            mean, std = (0.485, 0.456, 0.406), (0.229, 0.224, 0.225)
            train_normalizer = device_normalizer_for(
                mean, std, flip=cfg.flip_augment
            )
            eval_normalizer = device_normalizer_for(mean, std)
    trainer = Trainer(
        state,
        strategy,
        build_train_step(
            classification_loss_fn(
                model,
                weight_decay=cfg.weight_decay,
                label_smoothing=cfg.label_smoothing,
            ),
            batch_transform=train_normalizer,
            ema_decay=cfg.ema_decay if cfg.ema_decay > 0 else None,
        ),
        train_loader,
        eval_step=classification_eval_step(
            model, batch_transform=eval_normalizer
        ),
        eval_loader=eval_loader,
        config=TrainerConfig(
            epochs=cfg.epochs,
            log_every=cfg.log_every,
            ckpt_dir=cfg.ckpt_dir,
            ckpt_every_steps=cfg.ckpt_every_steps,
            keep_checkpoints=cfg.keep_checkpoints,
            keep_best=cfg.keep_best,
            best_mode=cfg.best_mode,
            async_checkpoint=cfg.async_checkpoint,
            metrics_path=cfg.metrics_path,
            tensorboard_dir=cfg.tensorboard_dir or None,
            eval_with_ema=cfg.ema_decay > 0,
            log_mfu=cfg.log_mfu,
            trace=cfg.trace_dir,
        ),
    )
    trainer.restore_checkpoint()
    with maybe_trace(cfg.profile_dir):
        state = fit_elastic(trainer)
    metrics = trainer.last_eval_metrics
    log_rank0("done: step=%d %s", int(state.step), metrics)
    return metrics


if __name__ == "__main__":
    main()
