"""Recipe 4: GPT-2 causal LM — ZeRO-1 + gradient accumulation.

Mirrors the reference recipe (BASELINE.json:10: "GPT-2-medium, DDP +
grad-accum + torch.distributed.optim ZeRO-1"): optimizer state is sharded
over the dp axis (each device updates 1/dp-th of the Adam moments, XLA
allgathers the updated params — the ZeroRedundancyOptimizer equivalent),
and the global batch is scanned in ``--accum-steps`` microbatches inside
the jitted step (no ``no_sync()`` needed: the grad allreduce happens once
after the scan by construction).

``--pp N`` switches to GPipe pipeline parallelism (beyond-reference
capability): the scanned block stack is sharded over N stages and the
microbatches tick through a ppermute schedule (parallel/pipeline_lm.py).

Run:
    python recipes/gpt2_zero1.py --size tiny --steps-per-epoch 3
    python recipes/gpt2_zero1.py --size tiny --pp 2 --steps-per-epoch 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import DataLoader, SyntheticTextDataset
from pytorch_distributed_tpu.models import GPT2Config, GPT2LMHead, gpt2_partition_rules
from pytorch_distributed_tpu.parallel import ZeRO1
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    causal_lm_eval_step,
    causal_lm_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0

SIZES = {
    "tiny": GPT2Config.tiny,
    "small": GPT2Config.small,
    "medium": GPT2Config.medium,  # the reference's size
}


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None)
    p.add_argument("--size", choices=SIZES, default="medium")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32, help="global batch")
    p.add_argument("--accum-steps", type=int, default=4)
    p.add_argument("--seq-len", type=int, default=512)
    p.add_argument("--lr", type=float, default=3e-4)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--pp", type=int, default=1, help="pipeline stages")
    p.add_argument("--remat", action="store_true",
                   help="recompute block activations in backward")
    p.add_argument("--remat-policy", choices=("full", "dots",
                   "dots_no_batch"), default="full",
                   help="what remat saves (implies --remat when not full)")
    p.add_argument("--pack", action="store_true",
                   help="pack paragraph documents into fixed rows with "
                        "segment-masked attention (needs --text-file)")
    p.add_argument("--vocab-chunk", type=int, default=None,
                   help="chunked-vocab loss: never materialize [B,S,V] "
                        "logits (ops/lm_loss.py); ZeRO-1 path only")
    p.add_argument(
        "--strategy", choices=("zero1", "dp", "auto"), default="zero1",
        help="parallel strategy; 'auto' runs the cost-model planner "
             "(pytorch_distributed_tpu/autoplan/) over mesh shapes x "
             "strategy classes and picks the cheapest feasible one",
    )
    p.add_argument(
        "--plan-path", default="plan.json",
        help="--strategy auto: write the ranked candidate report here",
    )
    p.add_argument(
        "--costmodel", default="costmodel.json",
        help="--strategy auto: calibrated comms cost model "
             "(scripts/collective_bench.py --fit); a missing file "
             "degrades to an analytic guess, loudly flagged uncalibrated",
    )
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    p.add_argument(
        "--sample", type=int, default=0, metavar="N",
        help="generate N tokens from the trained model at the end",
    )
    p.add_argument(
        "--text-file", default=None,
        help="train on this local text corpus (native BPE tokenizer) "
        "instead of the synthetic stream",
    )
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    if args.pp > 1 and args.vocab_chunk is not None:
        # fail BEFORE corpus/tokenizer/model setup burns minutes
        raise SystemExit(
            "--vocab-chunk is not supported with --pp > 1: the "
            "pipelined loss builds its own head projection; drop one "
            "of the flags"
        )
    if args.pack and not args.text_file:
        raise SystemExit("--pack needs --text-file (documents to pack)")
    if args.pack and args.pp > 1:
        raise SystemExit(
            "--pack is not combinable with --pp yet (the pipelined loss "
            "refuses packed batches); --pack + --vocab-chunk is supported"
        )
    ptd.seed_all(args.seed)
    cfg = SIZES[args.size]()
    if args.remat or args.remat_policy != "full":
        import dataclasses as _dc

        cfg = _dc.replace(
            cfg, remat=True, remat_policy=args.remat_policy
        )
    seq_len = min(args.seq_len, cfg.n_positions)
    tx = optax.chain(optax.clip_by_global_norm(1.0), optax.adamw(args.lr))

    mesh_spec = MeshSpec(dp=args.dp, tp=args.tp, pp=args.pp)
    chosen = None
    if args.strategy == "auto":
        # plan BEFORE the group exists: the planner reads only device
        # count + abstract shapes (eval_shape — zero compiles), and the
        # chosen candidate's mesh spec is what init_process_group gets
        if "RANK" in os.environ:
            raise SystemExit(
                "--strategy auto plans the single-controller SPMD "
                "mesh; it is not supported under a per-rank launch — "
                "unset RANK or pick --strategy dp/zero1 explicitly"
            )
        if args.dp != -1 or args.tp != 1:
            raise SystemExit(
                "--strategy auto chooses the mesh shape itself; drop "
                "--dp/--tp (--pp N is allowed: it OPENS the pipeline "
                "dimension so the planner ranks dp x tp x pp meshes up "
                "to N stages) or pick a strategy explicitly"
            )
        from pytorch_distributed_tpu import autoplan

        plan_model = GPT2LMHead(cfg)

        def make_state(key):
            variables = plan_model.init(
                key, jnp.zeros((1, seq_len), jnp.int32)
            )
            return TrainState.create(
                apply_fn=plan_model.apply, params=variables["params"],
                tx=tx,
            )

        abstract = jax.eval_shape(make_state, jax.random.key(args.seed))
        plan_report = autoplan.plan(
            profile=autoplan.transformer_profile(
                num_layers=cfg.num_layers, hidden_size=cfg.hidden_size,
                seq_len=seq_len,
                param_count=autoplan.param_count(abstract.params),
            ),
            global_batch=args.batch_size,
            abstract_state=abstract,
            extra_rules=gpt2_partition_rules(),
            tp_candidates=autoplan.max_divisible_tp(
                [cfg.num_heads], len(jax.devices())
            ),
            cost_model_path=args.costmodel,
            # single-controller SPMD collectives on this platform — a
            # hostring-calibrated model must not silently price them
            transport=f"spmd:{ptd.platform()}",
            accum_steps=args.accum_steps,
            # --pp N under auto is the pipeline opt-in (r20): the
            # planner prices dp x tp x pp meshes up to N stages, each
            # with its bubble + per-link handoff terms, and every
            # losing pipeline row names them in the table
            max_pp=args.pp if args.pp > 1 else None,
        )
        chosen = plan_report.best()
        plan_report.save(args.plan_path)
        log_rank0(
            "auto-parallel plan (full report: %s):\n%s",
            args.plan_path, plan_report.table(),
        )
        mesh_spec = chosen.mesh_spec()
    ptd.init_process_group(args.backend, mesh_spec=mesh_spec)
    log_rank0("world=%d backend=%s", ptd.get_world_size(), ptd.get_backend())
    tokenizer = None
    if args.text_file:
        import dataclasses

        from pytorch_distributed_tpu.data import (
            TokenizedTextDataset,
            Tokenizer,
        )

        with open(args.text_file, encoding="utf-8") as f:
            corpus = f.read()
        tokenizer = Tokenizer.train(
            corpus, vocab_size=min(cfg.vocab_size, 8192)
        )
        # shrink the model's vocab to what the corpus actually needs
        cfg = dataclasses.replace(cfg, vocab_size=tokenizer.vocab_size)
        if args.pack:
            # paragraph-level documents packed into fixed rows with
            # segment-masked attention — no FLOPs on sliding-window
            # overlap, no cross-document attention (data/packing.py)
            from pytorch_distributed_tpu.data import (
                ArrayDataset,
                pack_documents,
            )

            docs = [
                tokenizer.encode(p)
                for p in corpus.split("\n\n") if p.strip()
            ]
            packed = pack_documents(docs, seq_len)
            if args.steps_per_epoch:  # same data cap as the window path
                keep = args.steps_per_epoch * args.batch_size
                packed = {k: v[:keep] for k, v in packed.items()}
            n_rows = packed["input_ids"].shape[0]
            if n_rows < args.batch_size:
                raise SystemExit(
                    f"corpus packs into only {n_rows} row(s) of "
                    f"{seq_len} — fewer than --batch-size "
                    f"{args.batch_size}, so the drop-last loader would "
                    f"train zero steps; use a larger corpus or smaller "
                    f"batch/seq-len"
                )
            ds = ArrayDataset(**packed)
            log_rank0(
                "packed corpus: %d documents into %d rows of %d "
                "(vocab=%d)", len(docs), n_rows,
                seq_len, tokenizer.vocab_size,
            )
        else:
            ds = TokenizedTextDataset(
                corpus, tokenizer, seq_len, stride=seq_len // 2,
                max_windows=(
                    args.steps_per_epoch * args.batch_size
                    if args.steps_per_epoch else None
                ),
            )
            log_rank0(
                "text corpus: %d tokens vocab=%d windows=%d",
                ds.num_tokens, tokenizer.vocab_size, len(ds),
            )
    else:
        n = (args.steps_per_epoch or 100) * args.batch_size
        ds = SyntheticTextDataset(
            n=n, seq_len=seq_len, vocab_size=cfg.vocab_size, seed=args.seed
        )

    model = GPT2LMHead(cfg)
    variables = model.init(
        jax.random.key(args.seed), jnp.zeros((1, seq_len), jnp.int32)
    )
    state = TrainState.create(
        apply_fn=model.apply,
        params=variables["params"],
        tx=tx,
    )
    # under --strategy auto the PLAN decides whether the run pipelines:
    # --pp N only opened the search space, chosen.spec.pp is the answer
    # (and carries the microbatch count the bubble was priced at)
    effective_pp = args.pp
    pipeline_microbatches = max(args.accum_steps, 2 * max(args.pp, 1))
    if chosen is not None:
        effective_pp = chosen.spec.pp
        if chosen.pipeline is not None:
            pipeline_microbatches = chosen.pipeline["num_microbatches"]
    if effective_pp > 1:
        from pytorch_distributed_tpu.parallel.pipeline_lm import (
            PipelineParallel,
            pipelined_causal_lm_loss_fn,
        )

        strategy = PipelineParallel(extra_rules=gpt2_partition_rules())
        loss_fn = pipelined_causal_lm_loss_fn(
            cfg, num_microbatches=pipeline_microbatches
        )
        # microbatching lives inside the pipeline schedule here
        accum_steps = 1
        if chosen is not None:
            log_rank0("auto strategy: %s -> %s", chosen.name,
                      strategy.describe())
    else:
        if chosen is not None:  # --strategy auto: the planner's pick
            strategy = chosen.build_strategy(
                extra_rules=gpt2_partition_rules()
            )
            log_rank0("auto strategy: %s -> %s", chosen.name,
                      strategy.describe())
        elif args.strategy == "dp":
            from pytorch_distributed_tpu.parallel import DataParallel

            strategy = DataParallel(extra_rules=gpt2_partition_rules())
        else:
            strategy = ZeRO1(extra_rules=gpt2_partition_rules())
        loss_fn = causal_lm_loss_fn(
            model, vocab_chunk_size=args.vocab_chunk
        )
        accum_steps = args.accum_steps
    if tokenizer is not None:
        eval_ds = ds  # token-level held-out split is the user's concern;
        # the recipe reports training-distribution perplexity
    else:
        eval_ds = SyntheticTextDataset(
            n=max(args.batch_size, 64), seq_len=seq_len,
            vocab_size=cfg.vocab_size, seed=args.seed + 1,  # held out
        )
    trainer = Trainer(
        state,
        strategy,
        build_train_step(loss_fn, accum_steps=accum_steps),
        DataLoader(
            ds, args.batch_size, seed=args.seed,
            sharding=strategy.batch_sharding(),
        ),
        eval_step=causal_lm_eval_step(
            model, vocab_chunk_size=args.vocab_chunk
        ),
        eval_loader=DataLoader(
            eval_ds, args.batch_size, shuffle=False,
            sharding=strategy.batch_sharding(),
        ),
        config=TrainerConfig(
            epochs=args.epochs, log_every=args.log_every,
            ckpt_dir=args.ckpt_dir, samples_axis="input_ids",
        ),
    )
    trainer.restore_checkpoint()
    state = fit_elastic(trainer)
    log_rank0("done: step=%d eval=%s", int(state.step),
              trainer.last_eval_metrics)
    if args.sample:
        import numpy as np

        prompt = jnp.asarray(
            np.stack([eval_ds[i]["input_ids"] for i in range(2)])[:, :8]
        )
        out = ptd.generate(
            model, state.params, prompt, max_new_tokens=args.sample,
            temperature=0.8, top_k=40, rng=jax.random.key(args.seed),
        )
        if tokenizer is not None:
            log_rank0("sample: %r", tokenizer.decode(np.asarray(out)[0]))
        else:
            log_rank0(
                "sampled continuation ids: %s", np.asarray(out)[0].tolist()
            )
    return state


if __name__ == "__main__":
    main()
