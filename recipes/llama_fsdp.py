"""Recipe 5: Llama-3 — FSDP full-shard (+ optional TP/SP), the stretch goal.

Mirrors the reference recipe (BASELINE.json:11: "Llama-3-8B, FSDP
full-shard -> XLA SPMD on v5p-64"): parameters AND optimizer state shard
over the fsdp axis; XLA inserts the per-layer allgather / grad
reduce-scatter that torch FSDP implements with FlatParameter hooks. The
8B configuration needs a pod-scale mesh — on a single chip use ``--size
tiny`` (smoke) or supply ``--fsdp/--tp`` matching your slice.

Long context: ``--sp N`` shards the sequence axis over N devices with
ring attention (``--sp-mode ulysses`` for the all-to-all head-sharding
variant) — the attention dispatcher handles it model-transparently; add
``--remat`` to recompute block activations in backward so sequence
length trades FLOPs for HBM instead of OOMing.

Run:
    python recipes/llama_fsdp.py --size tiny --fsdp 2 --tp 2 --steps-per-epoch 2
    python recipes/llama_fsdp.py --size tiny --sp 4 --remat --seq-len 8192 \\
        --steps-per-epoch 2   # long-context shape
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import DataLoader, SyntheticTextDataset
from pytorch_distributed_tpu.models import (
    LlamaConfig,
    LlamaForCausalLM,
    llama_partition_rules,
)
from pytorch_distributed_tpu.parallel import FSDP
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0

SIZES = {"tiny": LlamaConfig.tiny, "8b": LlamaConfig.llama3_8b}


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None)
    p.add_argument("--size", choices=SIZES, default="tiny")
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=8, help="global batch")
    p.add_argument("--accum-steps", type=int, default=1)
    p.add_argument("--seq-len", type=int, default=2048)
    p.add_argument("--lr", type=float, default=1e-4)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--fsdp", type=int, default=1)
    p.add_argument("--tp", type=int, default=1)
    p.add_argument("--sp", type=int, default=1, help="sequence-parallel ways")
    p.add_argument("--sp-mode", choices=("ring", "ulysses"), default="ring")
    p.add_argument("--remat", action="store_true",
                   help="recompute block activations in backward")
    p.add_argument("--remat-policy", choices=("full", "dots",
                   "dots_no_batch"), default="full",
                   help="what remat saves: full recompute, or keep matmul "
                        "results and recompute only cheap elementwise work")
    p.add_argument("--vocab-chunk", type=int, default=None,
                   help="chunked-vocab loss: never materialize [B,S,V] "
                        "logits (ops/lm_loss.py; try 8192 at 128K vocab)")
    p.add_argument("--optimizer", choices=("adamw", "adafactor"),
                   default="adamw",
                   help="adafactor factors the second moment: ~1/2 the "
                        "optimizer-state HBM at 8B scale")
    p.add_argument(
        "--strategy", choices=("fsdp", "dp", "zero1", "auto"),
        default="fsdp",
        help="parallel strategy; 'auto' runs the cost-model planner "
             "(pytorch_distributed_tpu/autoplan/) over mesh shapes x "
             "strategy classes and picks the cheapest feasible one",
    )
    p.add_argument(
        "--plan-path", default="plan.json",
        help="--strategy auto: write the ranked candidate report here",
    )
    p.add_argument(
        "--costmodel", default="costmodel.json",
        help="--strategy auto: calibrated comms cost model "
             "(scripts/collective_bench.py --fit); a missing file "
             "degrades to an analytic guess, loudly flagged uncalibrated",
    )
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=5)
    return p.parse_args(argv)


def main(argv=None):
    import contextlib
    import dataclasses

    args = parse_args(argv)
    ptd.seed_all(args.seed)
    cfg = SIZES[args.size]()
    if args.remat or args.remat_policy != "full":
        # a non-default policy implies remat: silently ignoring
        # --remat-policy without --remat would train unrematerialized
        cfg = dataclasses.replace(
            cfg, remat=True, remat_policy=args.remat_policy
        )
    seq_len = min(args.seq_len, cfg.max_seq_len)
    model = LlamaForCausalLM(cfg)
    if args.optimizer == "adafactor":
        # adafactor clips its own updates; factored second moment halves
        # the optimizer-state HBM (the difference that fits 8B on fewer
        # chips — see tests/test_llama8b.py)
        tx = ptd.optim.Adafactor(args.lr)
    else:
        tx = optax.chain(
            optax.clip_by_global_norm(1.0), optax.adamw(args.lr)
        )

    # init directly onto shards — an 8B model never exists replicated
    def make_state(key):
        variables = model.init(key, jnp.zeros((1, seq_len), jnp.int32))
        return TrainState.create(
            apply_fn=model.apply, params=variables["params"], tx=tx
        )

    mesh_spec = MeshSpec(
        dp=args.dp, fsdp=args.fsdp, tp=args.tp, sp=args.sp
    )
    chosen = None
    if args.strategy == "auto":
        # plan BEFORE the group exists: device count + abstract shapes
        # only (one eval_shape, zero compiles); the chosen candidate's
        # mesh spec is what init_process_group then builds
        if args.sp > 1:
            raise SystemExit(
                "--strategy auto does not enumerate sequence-parallel "
                "candidates; drop --sp or pick a strategy explicitly"
            )
        if "RANK" in os.environ:
            raise SystemExit(
                "--strategy auto plans the single-controller SPMD "
                "mesh; it is not supported under a per-rank launch"
            )
        if args.dp != -1 or args.fsdp != 1 or args.tp != 1:
            raise SystemExit(
                "--strategy auto chooses the mesh shape itself; drop "
                "--dp/--fsdp/--tp or pick a strategy explicitly"
            )
        from pytorch_distributed_tpu import autoplan

        abstract = jax.eval_shape(make_state, jax.random.key(args.seed))
        plan_report = autoplan.plan(
            profile=autoplan.transformer_profile(
                num_layers=cfg.num_layers, hidden_size=cfg.hidden_size,
                seq_len=seq_len,
                param_count=autoplan.param_count(abstract.params),
            ),
            global_batch=args.batch_size,
            abstract_state=abstract,
            extra_rules=llama_partition_rules(),
            tp_candidates=autoplan.max_divisible_tp(
                [cfg.num_heads], len(jax.devices())
            ),
            cost_model_path=args.costmodel,
            # single-controller SPMD collectives on this platform — a
            # hostring-calibrated model must not silently price them
            transport=f"spmd:{ptd.platform()}",
            accum_steps=args.accum_steps,
        )
        chosen = plan_report.best()
        plan_report.save(args.plan_path)
        log_rank0(
            "auto-parallel plan (full report: %s):\n%s",
            args.plan_path, plan_report.table(),
        )
        mesh_spec = chosen.mesh_spec()
    ptd.init_process_group(args.backend, mesh_spec=mesh_spec)
    log_rank0("world=%d backend=%s", ptd.get_world_size(), ptd.get_backend())

    sp_ctx = contextlib.nullcontext()
    if args.sp > 1:
        from pytorch_distributed_tpu.parallel import sequence_parallel

        sp_ctx = sequence_parallel("sp", args.sp_mode)
    n = (args.steps_per_epoch or 50) * args.batch_size
    ds = SyntheticTextDataset(
        n=n, seq_len=seq_len, vocab_size=cfg.vocab_size, seed=args.seed
    )

    if chosen is not None:  # --strategy auto: the planner's pick
        strategy = chosen.build_strategy(
            extra_rules=llama_partition_rules()
        )
        log_rank0("auto strategy: %s -> %s", chosen.name,
                  strategy.describe())
    elif args.strategy == "dp":
        from pytorch_distributed_tpu.parallel import DataParallel

        strategy = DataParallel(extra_rules=llama_partition_rules())
    elif args.strategy == "zero1":
        from pytorch_distributed_tpu.parallel import ZeRO1

        strategy = ZeRO1(extra_rules=llama_partition_rules())
    else:
        strategy = FSDP(extra_rules=llama_partition_rules())

    state = strategy.create_sharded(make_state, jax.random.key(args.seed))
    trainer = Trainer(
        state,
        strategy,
        build_train_step(
            causal_lm_loss_fn(model, vocab_chunk_size=args.vocab_chunk),
            accum_steps=args.accum_steps,
        ),
        DataLoader(
            ds, args.batch_size, seed=args.seed,
            sharding=strategy.batch_sharding(),
        ),
        config=TrainerConfig(
            epochs=args.epochs, log_every=args.log_every,
            ckpt_dir=args.ckpt_dir, samples_axis="input_ids",
        ),
    )
    trainer.restore_checkpoint()
    with sp_ctx:  # ring/ulysses attention while the step traces+runs
        state = fit_elastic(trainer)
    log_rank0("done: step=%d", int(state.step))
    return state


if __name__ == "__main__":
    main()
