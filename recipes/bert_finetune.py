"""Recipe 3: BERT-base fine-tune — DDP + mixed precision.

Mirrors the reference recipe (BASELINE.json:9: "BERT-base fine-tune,
DDP + amp.GradScaler -> XLA bf16"): the AMP scaffolding is kept —
``autocast()`` selects bf16 compute and the GradScaler is an exact no-op
(bf16 needs no loss scaling; pass ``--fp16`` to see real dynamic scaling).

Run:
    python recipes/bert_finetune.py --tiny --steps-per-epoch 3
"""

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import DataLoader, SyntheticTextDataset
from pytorch_distributed_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    bert_partition_rules,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    text_classification_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0


def parse_args(argv=None):
    p = argparse.ArgumentParser(description=__doc__)
    p.add_argument("--backend", default=None)
    p.add_argument("--epochs", type=int, default=1)
    p.add_argument("--batch-size", type=int, default=32)
    p.add_argument("--seq-len", type=int, default=128)
    p.add_argument("--lr", type=float, default=2e-5)
    p.add_argument("--num-labels", type=int, default=2)
    p.add_argument("--dp", type=int, default=-1)
    p.add_argument("--tiny", action="store_true", help="tiny config (smoke)")
    p.add_argument("--mlm", action="store_true",
                   help="masked-LM pretraining objective instead of the "
                        "classification fine-tune (dynamic 80/10/10 "
                        "masking on device)")
    p.add_argument("--mask-prob", type=float, default=0.15)
    p.add_argument("--fp16", action="store_true",
                   help="fp16 + real dynamic loss scaling instead of bf16")
    p.add_argument("--lora", type=int, default=0, metavar="RANK",
                   help="LoRA fine-tune at this rank: base weights frozen, "
                        "only rank-R adapters (attention + MLP) train — "
                        "optimizer state shrinks to adapter size")
    p.add_argument("--steps-per-epoch", type=int, default=None)
    p.add_argument("--ckpt-dir", default=None)
    p.add_argument("--seed", type=int, default=0)
    p.add_argument("--log-every", type=int, default=10)
    return p.parse_args(argv)


def main(argv=None):
    args = parse_args(argv)
    ptd.seed_all(args.seed)
    ptd.init_process_group(args.backend, mesh_spec=MeshSpec(dp=args.dp))
    log_rank0("world=%d backend=%s", ptd.get_world_size(), ptd.get_backend())

    cfg = BertConfig.tiny() if args.tiny else BertConfig.base()
    seq_len = min(args.seq_len, cfg.max_position_embeddings)
    n = (args.steps_per_epoch or 100) * args.batch_size
    train_ds = SyntheticTextDataset(
        n=n, seq_len=seq_len, vocab_size=cfg.vocab_size,
        num_classes=args.num_labels, seed=args.seed,
    )

    amp_dtype = jnp.float16 if args.fp16 else jnp.bfloat16
    scaler = ptd.GradScaler(dtype=amp_dtype)
    with ptd.autocast(dtype=amp_dtype):
        if args.mlm:
            from pytorch_distributed_tpu.models import BertForMaskedLM

            model = BertForMaskedLM(cfg)
        else:
            model = BertForSequenceClassification(
                cfg, num_labels=args.num_labels
            )
        variables = model.init(
            jax.random.key(args.seed),
            jnp.zeros((1, seq_len), jnp.int32),
        )
        train_params = variables["params"]
        if args.lora:
            # freeze the base; the trainable tree (and therefore the
            # optimizer state, the grads, the checkpoints) is the
            # adapter tree. The wrapped .apply slots into loss_fn
            # construction below unchanged.
            train_params = ptd.lora_init(
                jax.random.key(args.seed + 1), variables["params"],
                rank=args.lora,
            )
            model = ptd.LoRAModel(model, variables["params"])
            log_rank0(
                "lora rank=%d: %d trainable / %d frozen params",
                args.lora, ptd.lora_param_count(train_params),
                sum(x.size
                    for x in jax.tree_util.tree_leaves(variables["params"])),
            )
        # loss_fn built exactly once, from the (possibly wrapped) model
        if args.mlm:
            from pytorch_distributed_tpu.train import masked_lm_loss_fn

            loss_fn = masked_lm_loss_fn(
                model, mask_token_id=min(103, cfg.vocab_size - 1),
                vocab_size=cfg.vocab_size, mask_prob=args.mask_prob,
            )
        else:
            loss_fn = text_classification_loss_fn(model)
        state = TrainState.create(
            apply_fn=model.apply,
            params=train_params,
            # HF fine-tuning convention: biases + LayerNorm exempt from
            # weight decay (the reference's two-param-group AdamW)
            tx=ptd.optim.AdamW(
                args.lr, weight_decay=0.01,
                no_decay=ptd.optim.DEFAULT_NO_DECAY,
            ),
            scaler_state=scaler.init_state(),
        )
        # LoRA: the trainable tree is adapters whose array ranks differ
        # from the kernels the BERT TP rules target — and at ~0.1% of
        # model size they replicate for free
        strategy = (
            DataParallel() if args.lora
            else DataParallel(extra_rules=bert_partition_rules())
        )
        train_step = build_train_step(loss_fn, scaler=scaler)
        trainer = Trainer(
            state,
            strategy,
            train_step,
            DataLoader(
                train_ds, args.batch_size, seed=args.seed,
                sharding=strategy.batch_sharding(),
            ),
            config=TrainerConfig(
                epochs=args.epochs, log_every=args.log_every,
                ckpt_dir=args.ckpt_dir, samples_axis="input_ids",
            ),
        )
        # fit() must stay inside autocast: jit traces lazily at the first
        # step, and the policy is read at trace time
        trainer.restore_checkpoint()
        state = fit_elastic(trainer)
    log_rank0("done: step=%d", int(state.step))
    return state


if __name__ == "__main__":
    main()
