"""Recipe 2b: ViT-Base / ImageNet — DDP data-parallel, transformer vision.

Same training scaffold as ``resnet50_imagenet.py`` (one ``dp``-axis mesh,
params replicated, batch sharded) with the transformer classifier — the
AdamW + cosine schedule the ViT papers use instead of ResNet's SGD.

Ingest uses the DEFAULT uint8 fast path (docs/DESIGN.md §3d): raw uint8
batches over the host->device link, normalization (and the synthetic
path's horizontal flip) fused into the jitted step. ``--no-device-
normalize`` restores the host-f32 reference-parity path.

Run:
    python recipes/vit_imagenet.py --dp 8 --batch-size 1024
    python recipes/vit_imagenet.py --backend gloo --synthetic --variant tiny \
        --steps-per-epoch 3 --batch-size 16   # smoke
"""

import dataclasses
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))

import jax
import jax.numpy as jnp
import numpy as np
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import (
    DataLoader,
    SyntheticImageDataset,
    device_normalizer_for,
    host_flip_transform,
)
from pytorch_distributed_tpu.models import ViT, ViTConfig
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    fit_elastic,
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    classification_eval_step,
    classification_loss_fn,
)
from pytorch_distributed_tpu.utils import log_rank0, maybe_trace
from pytorch_distributed_tpu.utils.config import RecipeConfig, parse_cli

IMAGENET_MEAN = (0.485, 0.456, 0.406)
IMAGENET_STD = (0.229, 0.224, 0.225)


@dataclasses.dataclass
class Config(RecipeConfig):
    epochs: int = 90  # doc: training epochs
    batch_size: int = 1024  # doc: global batch (split over dp)
    lr: float = 3e-3  # doc: peak AdamW LR
    weight_decay: float = 0.3  # doc: decoupled AdamW weight decay
    label_smoothing: float = 0.1  # doc: softmax label smoothing
    warmup_epochs: int = 10  # doc: linear LR warmup epochs
    variant: str = "base"  # doc: ViT variant: base | tiny (smoke)
    image_size: int = 0  # doc: square input resolution (0: the variant's default)
    dropout: float = 0.1  # doc: dropout rate
    train_samples: int = 1_281_167  # doc: synthetic train-set size
    eval_samples: int = 50_000  # doc: synthetic eval-set size
    flip_augment: bool = True  # doc: random horizontal flip augmentation
    device_normalize: bool = True  # doc: ship uint8 batches, normalize on-chip (default ingest path; --no-device-normalize restores host f32)
    tensorboard_dir: str = ""  # doc: TensorBoard event-file dir (rank 0)
    io_retries: int = 2  # doc: transient read retries per sample (real-data path)
    bad_sample_budget: int = 100  # doc: max quarantined (undecodable) samples before hard error


def main(argv=None):
    cfg: Config = parse_cli(Config, argv, description=__doc__)
    ptd.seed_all(cfg.seed)
    ptd.init_process_group(cfg.backend, mesh_spec=MeshSpec(dp=cfg.dp))

    base = {"base": ViTConfig.base, "tiny": ViTConfig.tiny}[cfg.variant]()
    vcfg = dataclasses.replace(
        base,
        dropout_rate=cfg.dropout,
        **({"image_size": cfg.image_size} if cfg.image_size else {}),
    )
    shape = (vcfg.image_size, vcfg.image_size, 3)
    log_rank0(
        "vit/%s: world=%d backend=%s batch=%d image=%d u8_ingest=%s",
        cfg.variant, ptd.get_world_size(), ptd.get_backend(),
        cfg.batch_size, vcfg.image_size, cfg.device_normalize,
    )

    # real ImageNet layout on disk (root/{train,val}/<class>/<img>)?
    real_root = (
        None if cfg.synthetic else
        cfg.data_dir if os.path.isdir(os.path.join(cfg.data_dir, "train"))
        else None
    )
    train_fetch = eval_fetch = None
    train_normalizer = eval_normalizer = None
    if real_root is not None:
        from pytorch_distributed_tpu.data import (
            FolderImagePipeline,
            ImageFolderDataset,
        )

        train_ds = ImageFolderDataset(os.path.join(real_root, "train"))
        eval_ds = ImageFolderDataset(os.path.join(real_root, "val"))
        # one quarantine (and one bad-sample budget) across train+eval:
        # both pipelines read the same disk
        from pytorch_distributed_tpu.data import SampleQuarantine

        quarantine = SampleQuarantine(cfg.bad_sample_budget)
        train_fetch = FolderImagePipeline(
            vcfg.image_size, train=True, seed=cfg.seed,
            mean=IMAGENET_MEAN, std=IMAGENET_STD,
            device_normalize=cfg.device_normalize,
            io_retries=cfg.io_retries, quarantine=quarantine,
        )
        eval_fetch = FolderImagePipeline(
            vcfg.image_size, train=False,
            mean=IMAGENET_MEAN, std=IMAGENET_STD,
            device_normalize=cfg.device_normalize,
            io_retries=cfg.io_retries, quarantine=quarantine,
        )
        if cfg.device_normalize:
            # the folder pipeline flips/crops at decode; only the
            # normalize moves on-device
            train_normalizer = train_fetch.device_normalizer()
            eval_normalizer = eval_fetch.device_normalizer()
        n_train = len(train_ds)
        num_classes = len(train_ds.classes)
        if num_classes != vcfg.num_classes:
            vcfg = dataclasses.replace(vcfg, num_classes=num_classes)
    else:
        n_train = cfg.train_samples
        n_eval = cfg.eval_samples
        if cfg.steps_per_epoch:
            n_train = cfg.steps_per_epoch * cfg.batch_size
            n_eval = min(n_eval, cfg.batch_size * 2)
        dtype = np.uint8 if cfg.device_normalize else np.float32
        train_ds = SyntheticImageDataset(
            n=n_train, image_shape=shape, num_classes=vcfg.num_classes,
            seed=cfg.seed, dtype=dtype,
        )
        eval_ds = SyntheticImageDataset(
            n=n_eval, image_shape=shape, num_classes=vcfg.num_classes,
            seed=cfg.seed + 1, dtype=dtype,
        )
        if cfg.device_normalize:
            # normalize AND flip fused into the jitted step
            train_normalizer = device_normalizer_for(
                IMAGENET_MEAN, IMAGENET_STD, flip=cfg.flip_augment
            )
            eval_normalizer = device_normalizer_for(
                IMAGENET_MEAN, IMAGENET_STD
            )

    model = ViT(vcfg)
    variables = model.init(
        jax.random.key(cfg.seed), jnp.zeros((1,) + shape), train=False
    )

    steps_per_epoch = max(n_train // cfg.batch_size, 1)
    total_steps = max(cfg.epochs * steps_per_epoch, 1)
    warmup_steps = min(cfg.warmup_epochs * steps_per_epoch, total_steps - 1)
    schedule = optax.warmup_cosine_decay_schedule(
        init_value=0.0,
        peak_value=cfg.lr,
        warmup_steps=warmup_steps,
        decay_steps=total_steps,
    )
    tx = optax.adamw(schedule, weight_decay=cfg.weight_decay)
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"], tx=tx
    )

    strategy = DataParallel()
    train_loader = DataLoader(
        train_ds, cfg.batch_size, seed=cfg.seed,
        sharding=strategy.batch_sharding(), fetch=train_fetch,
        transform=(
            host_flip_transform(cfg.seed)
            if cfg.flip_augment and train_fetch is None
            and not cfg.device_normalize else None
        ),  # the folder pipeline flips at decode
    )
    eval_loader = DataLoader(
        eval_ds, cfg.batch_size, shuffle=False, drop_last=False,
        sharding=strategy.batch_sharding(), fetch=eval_fetch,
    )

    trainer = Trainer(
        state,
        strategy,
        build_train_step(
            classification_loss_fn(
                model, label_smoothing=cfg.label_smoothing
            ),
            batch_transform=train_normalizer,
        ),
        train_loader,
        eval_step=classification_eval_step(
            model, batch_transform=eval_normalizer
        ),
        eval_loader=eval_loader,
        config=TrainerConfig(
            epochs=cfg.epochs,
            log_every=cfg.log_every,
            ckpt_dir=cfg.ckpt_dir,
            ckpt_every_steps=cfg.ckpt_every_steps,
            keep_checkpoints=cfg.keep_checkpoints,
            keep_best=cfg.keep_best,
            best_mode=cfg.best_mode,
            async_checkpoint=cfg.async_checkpoint,
            metrics_path=cfg.metrics_path,
            tensorboard_dir=cfg.tensorboard_dir or None,
            trace=cfg.trace_dir,
        ),
    )
    trainer.restore_checkpoint()
    with maybe_trace(cfg.profile_dir):
        state = fit_elastic(trainer)
    metrics = trainer.last_eval_metrics
    log_rank0("done: step=%d %s", int(state.step), metrics)
    return metrics


if __name__ == "__main__":
    main()
