"""Llama-3-8B FSDP feasibility proof (BASELINE.json:11, VERDICT r1 #8).

No pod is available offline, so feasibility is proven abstractly — and
cheaply — with the tools XLA itself uses:

* ``jax.eval_shape`` builds the full 8B TrainState (params + AdamW moments)
  as shapes only;
* the FSDP strategy's shardings are computed against a *v5p-64-shaped*
  ``AbstractMesh`` (dp=4, fsdp=16);
* per-device bytes are summed from ``NamedSharding.shard_shape`` — the
  exact shard math the runtime would use — and asserted under HBM;
* the full train step is AOT-lowered for the ``tpu`` platform against
  those shardings, proving the sharded program traces and lowers
  end-to-end.

If someone regresses the FSDP rules (e.g. a new param stops sharding),
the byte budget assertion fails.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from pytorch_distributed_tpu.runtime.compat import abstract_mesh

from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.parallel import FSDP
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
)

SEQ = 2048
GLOBAL_BATCH = 64
V4_HBM_BYTES = 32e9  # per chip; v5p has 95GB — assert against the smaller


@pytest.fixture(scope="module")
def abstract_8b_state():
    cfg = LlamaConfig.llama3_8b()
    model = LlamaForCausalLM(cfg)

    def make_state(key):
        params = model.init(key, jnp.zeros((1, SEQ), jnp.int32))["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adamw(1e-4)
        )

    abstract = jax.eval_shape(make_state, jax.random.key(0))
    return cfg, model, abstract


def test_8b_param_count(abstract_8b_state):
    _, _, abstract = abstract_8b_state
    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(abstract.params)
    )
    assert 7.9e9 < n_params < 8.2e9, f"{n_params/1e9:.2f}B params"


def _per_device_bytes(abstract, strategy):
    per_device = 0
    replicated_big = []
    for (path, leaf), sh in zip(
        jax.tree_util.tree_leaves_with_path(abstract),
        jax.tree_util.tree_leaves(strategy.state_shardings(abstract)),
    ):
        if not hasattr(leaf, "shape"):
            continue
        shard_elems = int(np.prod(sh.shard_shape(tuple(leaf.shape))))
        per_device += shard_elems * leaf.dtype.itemsize
        if shard_elems == int(np.prod(leaf.shape)) and shard_elems > 1e6:
            replicated_big.append(jax.tree_util.keystr(path))
    return per_device, replicated_big


def test_8b_fsdp_state_fits_v5p64(abstract_8b_state):
    """Static state (params f32 + AdamW m/v f32 = ~96 GB total) per device,
    under the two realistic 64-chip layouts. A broken FSDP rule that leaves
    an 8B-scale tensor replicated blows straight past either ceiling."""
    _, _, abstract = abstract_8b_state

    # full-shard over all 64 chips (the reference FSDP full-shard shape):
    # 96 GB / 64 = ~1.5 GB/device
    per_device, replicated_big = _per_device_bytes(
        abstract, FSDP(abstract_mesh((1, 64), ("dp", "fsdp")))
    )
    assert not replicated_big, (
        f"large tensors left fully replicated: {replicated_big[:5]}"
    )
    assert per_device < 2e9, f"{per_device/1e9:.2f} GB static state/device"
    assert per_device * 64 > 80e9, "state no longer 8B-sized — test stale?"

    # hybrid dp=4 x fsdp=16 (params replicate across dp): 96/16 = 6 GB —
    # still comfortably inside even v4's 32 GB HBM, leaving >3x headroom
    # for grads + activations at seq 2048
    per_device, _ = _per_device_bytes(
        abstract, FSDP(abstract_mesh((4, 16), ("dp", "fsdp")))
    )
    assert per_device < 8e9, f"{per_device/1e9:.2f} GB static state/device"
    assert per_device < V4_HBM_BYTES / 3


def test_8b_adafactor_halves_optimizer_state(abstract_8b_state):
    """Adafactor's factored second moment: the 8B TrainState's total bytes
    drop from ~3x params (AdamW m+v) to ~2x (one momentum-free factored
    state) — the difference that fits 8B training on fewer chips."""
    cfg, model, adamw_abstract = abstract_8b_state
    from pytorch_distributed_tpu import optim as po

    def make_state(key):
        params = model.init(key, jnp.zeros((1, SEQ), jnp.int32))["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=po.Adafactor(1e-4)
        )

    abstract = jax.eval_shape(make_state, jax.random.key(0))

    def total_bytes(a):
        return sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(a)
            if hasattr(l, "shape")
        )

    params_b = total_bytes(adamw_abstract.params)
    adamw_b = total_bytes(adamw_abstract)
    adafactor_b = total_bytes(abstract)
    assert adamw_b > 2.9 * params_b  # params + m + v
    # factored stats are O(rows+cols); whole state well under 2.2x params
    assert adafactor_b < 2.2 * params_b, (
        f"adafactor state {adafactor_b/1e9:.1f} GB vs params "
        f"{params_b/1e9:.1f} GB"
    )
    # and it still shards under FSDP without leaving big replicas
    per_device, replicated_big = _per_device_bytes(
        abstract, FSDP(abstract_mesh((1, 64), ("dp", "fsdp")))
    )
    assert not replicated_big, replicated_big[:5]
    assert per_device < 1.5e9, f"{per_device/1e9:.2f} GB/device"


def test_8b_decode_cache_bytes_bounded_by_cache_len(abstract_8b_state):
    """8B KV-cache decode traces via eval_shape, and the generation-sized
    cache (generation.py passes cache_len = prompt+new) is ~27x smaller
    than naively caching to max_seq_len — the difference between fitting
    on one chip and not."""
    cfg, model, abstract = abstract_8b_state
    B, P, NEW = 8, 128, 128

    def cache_bytes(cache_len):
        def prefill(params):
            _, state = model.apply(
                {"params": params},
                jnp.zeros((B, P), jnp.int32),
                decode=True,
                cache_len=cache_len,
                mutable=["cache"],
            )
            return state["cache"]

        cache = jax.eval_shape(prefill, abstract.params)
        return sum(
            int(np.prod(l.shape)) * l.dtype.itemsize
            for l in jax.tree_util.tree_leaves(cache)
        )

    bounded = cache_bytes(P + NEW)
    naive = cache_bytes(cfg.max_seq_len)
    # 2 (K,V) x 32 layers x [8, 256, 8kv, 128] bf16 ~= 2.1 GB
    assert bounded < 3e9, f"{bounded/1e9:.2f} GB"
    assert naive > 20 * bounded  # the cache_len bound is load-bearing


def _lower_8b_step(model, abstract, loss_fn, *, packed=False):
    mesh = abstract_mesh((4, 16), ("dp", "fsdp"))
    strategy = FSDP(mesh)
    shardings = strategy.state_shardings(abstract)
    state_shapes = jax.tree_util.tree_map(
        lambda l, s: jax.ShapeDtypeStruct(l.shape, l.dtype, sharding=s),
        abstract,
        shardings,
    )
    bsh = strategy.batch_sharding()
    batch_shapes = {
        "input_ids": jax.ShapeDtypeStruct(
            (GLOBAL_BATCH, SEQ), jnp.int32, sharding=bsh
        )
    }
    if packed:
        batch_shapes["segment_ids"] = jax.ShapeDtypeStruct(
            (GLOBAL_BATCH, SEQ), jnp.int32, sharding=bsh
        )
        batch_shapes["positions"] = jax.ShapeDtypeStruct(
            (GLOBAL_BATCH, SEQ), jnp.int32, sharding=bsh
        )
    step = build_train_step(loss_fn)
    return (
        jax.jit(step, donate_argnums=(0,))
        .trace(state_shapes, batch_shapes)
        .lower(lowering_platforms=("tpu",))
    )


@pytest.mark.slow
def test_8b_chunked_loss_step_lowers_and_sheds_the_logits(abstract_8b_state):
    """The chunked-vocab loss (ops/lm_loss.py) lowers for the same 8B FSDP
    mesh, and its HLO carries no [tokens, V] logits-sized buffer — the
    full-logits step provably does."""
    cfg, model, abstract = abstract_8b_state
    tokens_per_shard = GLOBAL_BATCH * (SEQ - 1) // 64  # dp*fsdp shards
    logits_marker = f"{tokens_per_shard}x{cfg.vocab_size}"
    full = _lower_8b_step(
        model, abstract, causal_lm_loss_fn(model)
    ).as_text()
    chunked = _lower_8b_step(
        model, abstract, causal_lm_loss_fn(model, vocab_chunk_size=8192)
    ).as_text()
    assert logits_marker in full  # sanity: the marker detects full logits
    assert logits_marker not in chunked, (
        "chunked-loss HLO still materializes per-shard full logits"
    )


@pytest.mark.slow
def test_8b_projected_step_time_v5p64(abstract_8b_state):
    """VERDICT r2 #6: turn 8B feasibility into a throughput projection.

    FLOPs come from XLA's own cost analysis of the AOT-lowered 8B FSDP
    train step. One correction is load-bearing: the transformer stack is
    a ``lax.scan`` over layers, and HLO cost analysis prices a while-loop
    BODY once, not times its trip count — so the scanned-layer flops are
    multiplied by num_layers. That corrected total is cross-checked
    against the standard analytic count (6*N*T dense + 12*L*B*S^2*D
    attention); if a refactor unrolls the scan (double count) or changes
    the program, the cross-check fails loudly rather than projecting
    nonsense.

    The projection itself is arithmetic, pinned here so BASELINE.md's row
    stays tied to the real lowered program: on a v5p-64 mesh
    (459 TFLOP/s/chip peak bf16) at an assumed 40% MFU — mid-range of
    publicly reported 7-8B FSDP training MFU — step time and
    tokens/s/chip follow from per-chip FLOPs.
    """
    cfg, model, abstract = abstract_8b_state
    vocab_chunk = 8192
    lowered = _lower_8b_step(
        model, abstract, causal_lm_loss_fn(model, vocab_chunk_size=vocab_chunk)
    )
    ca = lowered.cost_analysis()
    if isinstance(ca, (list, tuple)):
        ca = ca[0] if ca else None
    assert ca and "flops" in ca, "cost analysis lost its flops key"
    ca_flops = float(ca["flops"])

    # -- analytic model (fwd+bwd = 3x fwd), decomposed by program region --
    tokens = GLOBAL_BATCH * SEQ
    d_model = cfg.num_heads * cfg.head_dim
    head_params = cfg.vocab_size * d_model  # untied lm head
    n_params = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(abstract.params)
    )
    block_params = n_params - 2 * head_params  # minus embed + head
    layers_flops = (
        6 * block_params * tokens
        + 12 * cfg.num_layers * GLOBAL_BATCH * SEQ**2 * d_model
    )
    head_flops = 6 * head_params * tokens
    analytic_total = layers_flops + head_flops  # embedding gather ~ 0 flops

    # -- validate the lowered program against cost analysis --------------
    # HLO cost analysis prices each lax.scan BODY once, not x trip count:
    # the layer stack is a scan over num_layers and the chunked loss a
    # scan over vocab chunks, so the aggregate it should report is
    n_chunks = -(-cfg.vocab_size // vocab_chunk)
    expected_ca = (
        layers_flops / cfg.num_layers + head_flops / n_chunks
    )
    ratio = ca_flops / expected_ca
    assert 0.8 < ratio < 1.25, (
        f"cost-analysis flops {ca_flops:.3e} vs scan-aware expectation "
        f"{expected_ca:.3e} (ratio {ratio:.2f}) — program structure "
        f"changed (scan unrolled? loss restructured?); re-derive the "
        f"expectation before trusting the projection"
    )

    # -- projection: v5p-64, dp=4 x fsdp=16 (the lowered mesh above) -----
    V5P_PEAK = 459e12
    ASSUMED_MFU = 0.40
    step_s = (analytic_total / 64) / (V5P_PEAK * ASSUMED_MFU)
    tok_per_sec_chip = tokens / 64 / step_s
    print(
        f"\n8B v5p-64 projection: {analytic_total/1e15:.2f} PFLOP/step "
        f"(cost-analysis ratio {ratio:.2f}), step {step_s*1e3:.0f} ms @ "
        f"{ASSUMED_MFU:.0%} MFU -> {tok_per_sec_chip:.0f} tokens/s/chip"
    )
    # pin the projection so BASELINE.md's row can't silently drift from
    # the program it describes (tok/s/chip = 2048/step_s is implied)
    assert 0.4 < step_s < 0.8, f"step_s={step_s:.3f}"


@pytest.mark.slow
def test_8b_packed_chunked_step_lowers_for_tpu(abstract_8b_state):
    """The full round-3 training configuration at the stretch-goal scale:
    packed sequences (segment-masked attention + per-document positions)
    + chunked-vocab loss + FSDP on the v5p-64 mesh — traces and lowers
    end to end for TPU."""
    cfg, model, abstract = abstract_8b_state
    lowered = _lower_8b_step(
        model, abstract,
        causal_lm_loss_fn(model, vocab_chunk_size=8192),
        packed=True,
    )
    text = lowered.as_text()
    assert "stablehlo" in text or "module" in text
    # still sheds the [tokens, V] logits with packing in play
    tokens_per_shard = GLOBAL_BATCH * (SEQ - 1) // 64
    assert f"{tokens_per_shard}x{cfg.vocab_size}" not in text


@pytest.mark.slow
def test_8b_fsdp_train_step_lowers_for_tpu(abstract_8b_state):
    cfg, model, abstract = abstract_8b_state
    lowered = _lower_8b_step(model, abstract, causal_lm_loss_fn(model))
    # the lowered module exists and is genuinely the sharded 8B program
    text = lowered.as_text()
    assert "stablehlo" in text or "module" in text
    out_state, _ = lowered.out_info
    n_out = sum(
        int(np.prod(l.shape))
        for l in jax.tree_util.tree_leaves(out_state.params)
    )
    assert n_out > 7.9e9


def test_8b_int4_tree_fits_one_v5e(abstract_8b_state):
    """The serving-capacity claim behind ops/quant.py, made concrete at
    8B scale from abstract shapes: the groupwise-int4 tree (packed q4
    bytes + f32 scales, computed by the quantizer's own sizing rules
    over the real 8B param shapes) rests well inside ONE v5e's 15.75 GB
    HBM. Scope stated honestly: this is the AT-REST footprint —
    `quantized_apply_fn` dequantizes the whole tree inside the step, so
    a full 8B decode additionally materializes the bf16 weights
    (~16 GB) transiently; single-chip 8B *serving* therefore needs
    per-layer dequantization under the scan (a known follow-up), while
    2 chips clear it today."""
    GROUP = 128
    V5E_HBM = 15.75e9  # usable, from the measured XLA OOM report (r3)
    total = 0
    skipped = 0
    for path, leaf in jax.tree_util.tree_flatten_with_path(
        abstract_8b_state[2].params
    )[0]:
        shape = leaf.shape
        if len(shape) < 2 or int(np.prod(shape)) < 4096 or shape[-1] % 2:
            skipped += int(np.prod(shape)) * 4  # stays f32
            continue
        in_last, out = shape[-2], shape[-1]
        g = GROUP if in_last % GROUP == 0 else in_last
        lead = int(np.prod(shape[:-2], dtype=np.int64))
        total += lead * in_last * (out // 2)          # packed q4 bytes
        total += lead * (in_last // g) * out * 4      # f32 scales
    int4_bytes = total + skipped
    # ~8B params at ~0.56 byte/weight incl. scales and f32 stragglers
    assert 4.0e9 < int4_bytes < 6.0e9, int4_bytes / 1e9
    assert int4_bytes < V5E_HBM / 3  # at rest: fits with 3x headroom


@pytest.mark.slow
def test_llama8b_decode_script_rehearses_on_cpu():
    """The chip-bound 8B decode script (scripts/llama8b_decode.py) must
    EXECUTE end to end on the CPU backend at the tiny preset — the same
    guard class as test_bench_contract's tpu-only-phases test: the r3
    chip window lost two captures to configs that had never run
    anywhere, and this script's first real invocation is ON the chip.
    The tiny preset also asserts the on-device builder's tree is
    structurally identical to init + quantize_for_scan_dequant (the
    layout contract that makes the 8b measurement representative)."""
    import os
    import subprocess
    import sys

    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env.pop("XLA_FLAGS", None)
    env.pop("PTD_PROBE_BUDGET_S", None)  # a chip-probe budget exported
    # in the shell would make the tiny run trip over_budget() spuriously
    proc = subprocess.run(
        [sys.executable, "scripts/llama8b_decode.py", "--preset", "tiny"],
        cwd=repo, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "layout pin OK" in proc.stdout
    assert "llama_tiny_int4_scan_decode_tokens_per_sec" in proc.stdout
