"""Native byte-level BPE (native/bpe.cpp via data/tokenizer.py)."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data.tokenizer import (
    TokenizedTextDataset,
    Tokenizer,
)

CORPUS = (
    "the quick brown fox jumps over the lazy dog. "
    "the quick brown fox jumps over the lazy dog again. "
    "pack my box with five dozen liquor jugs. "
) * 40


def _py_train(data: bytes, num_merges: int):
    """Slow reference trainer: same greedy rule, ties to smallest pair."""
    toks = list(data)
    merges = []
    for k in range(num_merges):
        counts = {}
        for a, b in zip(toks, toks[1:]):
            counts[(a, b)] = counts.get((a, b), 0) + 1
        best = None
        for pair, c in counts.items():
            if c < 2:
                continue
            if best is None or c > counts[best] or (
                c == counts[best] and pair < best
            ):
                best = pair
        if best is None:
            break
        new_id = 256 + k
        merges.append(best)
        out, i = [], 0
        while i < len(toks):
            if i + 1 < len(toks) and (toks[i], toks[i + 1]) == best:
                out.append(new_id)
                i += 2
            else:
                out.append(toks[i])
                i += 1
        toks = out
    return merges


def _py_encode(data: bytes, merges):
    rank = {pair: 256 + i for i, pair in enumerate(merges)}
    toks = list(data)
    while True:
        best_i, best_rank = None, None
        for i, pair in enumerate(zip(toks, toks[1:])):
            r = rank.get(pair)
            if r is not None and (best_rank is None or r < best_rank):
                best_i, best_rank = i, r
        if best_i is None:
            return toks
        toks = toks[:best_i] + [best_rank] + toks[best_i + 2:]


def test_train_matches_python_reference():
    data = CORPUS[:400].encode()
    tok = Tokenizer.train(data, vocab_size=256 + 24)
    want = _py_train(data, 24)
    got = [tuple(m) for m in tok.merges]
    assert got == want


def test_encode_matches_python_reference():
    tok = Tokenizer.train(CORPUS, vocab_size=512)
    for text in ("the quick brown fox", "zebra!?", "dozen liquor jugs"):
        got = tok.encode(text).tolist()
        want = _py_encode(text.encode(), [tuple(m) for m in tok.merges])
        assert got == want, text


def test_roundtrip_lossless_any_text():
    tok = Tokenizer.train(CORPUS, vocab_size=400)
    for text in (
        "the quick brown fox",
        "bytes the trainer never saw: \x00\x7f ütf-8 ✓ 日本語",
        "",
    ):
        assert tok.decode(tok.encode(text)) == text


def test_compression_actually_happens():
    tok = Tokenizer.train(CORPUS, vocab_size=768)
    ids = tok.encode(CORPUS)
    assert len(ids) < len(CORPUS.encode()) * 0.5  # >2x on its own corpus
    assert tok.vocab_size <= 768


def test_save_load_roundtrip(tmp_path):
    tok = Tokenizer.train(CORPUS, vocab_size=300)
    tok.save(str(tmp_path / "tok"))
    tok2 = Tokenizer.load(str(tmp_path / "tok"))
    np.testing.assert_array_equal(tok.merges, tok2.merges)
    s = "the lazy dog"
    np.testing.assert_array_equal(tok.encode(s), tok2.encode(s))


def test_decode_rejects_bad_ids():
    tok = Tokenizer.train(CORPUS, vocab_size=300)
    with pytest.raises(ValueError):
        tok.decode(np.asarray([tok.vocab_size], np.int32))


def test_tokenized_dataset_windows():
    tok = Tokenizer.train(CORPUS, vocab_size=320)
    ds = TokenizedTextDataset(CORPUS, tok, seq_len=32)
    assert len(ds) > 4
    item = ds[0]
    assert item["input_ids"].shape == (32,)
    assert item["input_ids"].dtype == np.int32
    # windows tile the corpus: decoding the first window gives real text
    text = tok.decode(ds[0]["input_ids"])
    assert "the" in text
    with pytest.raises(ValueError):
        TokenizedTextDataset("tiny", tok, seq_len=512)


@pytest.mark.slow
def test_gpt2_recipe_trains_on_text_file(tmp_path):
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "recipes")
    )
    import gpt2_zero1

    corpus = tmp_path / "corpus.txt"
    # varied text: an exact-repeat corpus BPE-compresses to a handful of
    # tokens (merges absorb whole sentences) and can't fill a window
    corpus.write_text(
        "".join(
            f"line {i}: the {i % 7} quick foxes jumped {i * 13} times.\n"
            for i in range(400)
        )
    )
    state = gpt2_zero1.main(
        [
            "--size", "tiny", "--text-file", str(corpus), "--epochs", "1",
            "--batch-size", "8", "--seq-len", "16", "--log-every", "0",
            "--sample", "4",
        ]
    )
    assert int(state.step) >= 1


def test_roundtrip_fuzz_random_bytes():
    """decode_bytes(encode(x)) == x for arbitrary binary input."""
    rng = np.random.default_rng(0)
    train_bytes = rng.integers(0, 256, size=4000, dtype=np.uint8).tobytes()
    tok = Tokenizer.train(train_bytes + CORPUS.encode(), vocab_size=384)
    for n in (0, 1, 7, 257, 1024):
        data = rng.integers(0, 256, size=n, dtype=np.uint8).tobytes()
        assert tok.decode_bytes(tok.encode(data)) == data
