"""Gemma family: HF logit parity exercises all four deviations at once
(the (1+scale) norm, the gelu gate, the sqrt(hidden) embed scaling, and
the decoupled head_dim — any one wrong and logits diverge), plus the
tied-head layout and decode."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import GemmaConfig, GemmaForCausalLM
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _pair():
    torch.manual_seed(0)
    hf_cfg = transformers.GemmaConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=1,
        head_dim=16,  # != hidden/heads = 12: the decoupling is binding
        rope_theta=10_000.0, rms_norm_eps=1e-6,
        max_position_embeddings=128, attn_implementation="eager",
    )
    hf = transformers.GemmaForCausalLM(hf_cfg).eval()
    cfg = GemmaConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=1, override_head_dim=16,
        max_seq_len=128, rope_theta=10_000.0, rms_eps=1e-6,
    )
    return hf, cfg


def test_gemma_logits_match_hf():
    from pytorch_distributed_tpu.interop import load_gemma_weights

    hf, cfg = _pair()
    params = load_gemma_weights(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, cfg
    )
    assert "lm_head" not in params  # Gemma is always tied
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 10)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = GemmaForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=5e-4, rtol=3e-4)


@pytest.mark.slow  # the gpt2/mistral decode pins cover the machinery fast
def test_gemma_cache_decode_equals_recompute():
    cfg = GemmaConfig.tiny()
    model = GemmaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 6)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    got = ptd.generate(model, params, ids, max_new_tokens=4, temperature=0.0)
    seq = np.asarray(ids)
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)


def test_gemma_mqa_generate_with_tp_sharded_params():
    """MQA + TP: with one kv head, k/v must REPLICATE (a size-1 axis
    cannot shard over tp) while q/o and the MLP still shard — and
    decoding stays token-identical."""
    import optax

    from pytorch_distributed_tpu.models import gemma_partition_rules
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, tp=4))
    cfg = GemmaConfig.tiny()  # num_kv_heads=1
    model = GemmaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    want = ptd.generate(model, params, ids, max_new_tokens=5,
                        temperature=0.0)
    strategy = DataParallel(
        extra_rules=gemma_partition_rules(num_kv_heads=cfg.num_kv_heads)
    )
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    block = state.params["layers"]["block"]
    assert "tp" in str(block["q"]["kernel"].sharding.spec)
    assert "tp" not in str(block["k"]["kernel"].sharding.spec)
    got = ptd.generate(
        model, state.params, ids, max_new_tokens=5, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
