"""GPT-NeoX/Pythia: HF logit parity in BOTH residual topologies (the
parallel form is the family's defining deviation), partial-rotary
semantics, export roundtrip, decode."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import NeoXConfig, NeoXForCausalLM
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def _pair(parallel: bool, scan_layers: bool = True):
    torch.manual_seed(0)
    hf_cfg = transformers.GPTNeoXConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4,
        rotary_pct=0.5,  # head_dim 12 -> rotary dim 6: PARTIAL rotation
        rotary_emb_base=10_000, max_position_embeddings=128,
        layer_norm_eps=1e-5, use_parallel_residual=parallel,
        attn_implementation="eager",
    )
    hf = transformers.GPTNeoXForCausalLM(hf_cfg).eval()
    cfg = NeoXConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, rotary_pct=0.5, max_seq_len=128,
        use_parallel_residual=parallel, scan_layers=scan_layers,
    )
    return hf, cfg


def _logits_match(hf, cfg, atol=3e-4):
    from pytorch_distributed_tpu.interop import load_neox_weights

    params = load_neox_weights(_sd(hf), cfg)
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 11)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = NeoXForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=atol, rtol=2e-4)


def test_neox_logits_match_hf_parallel_residual():
    hf, cfg = _pair(parallel=True)
    _logits_match(hf, cfg)


@pytest.mark.slow  # budget: the parallel-residual (defining) variant stays fast
def test_neox_logits_match_hf_sequential_residual():
    hf, cfg = _pair(parallel=False, scan_layers=False)
    _logits_match(hf, cfg)


@pytest.mark.slow  # budget: parity (both topologies) pins the mapping fast
def test_neox_export_roundtrips_into_hf():
    from pytorch_distributed_tpu.interop import (
        export_neox_weights,
        load_neox_weights,
    )

    hf, cfg = _pair(parallel=True)
    params = load_neox_weights(_sd(hf), cfg)
    sd = export_neox_weights(params, cfg)
    hf2 = transformers.GPTNeoXForCausalLM(hf.config).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(1).integers(2, 211, size=(1, 9)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )


def test_neox_rejects_bad_rotary_dim():
    with pytest.raises(ValueError, match="rotary"):
        NeoXConfig(
            vocab_size=64, hidden_size=24, num_layers=1, num_heads=4,
            rotary_pct=0.25,  # head_dim 6 -> rotary dim 1: odd, refused
        )


@pytest.mark.slow  # the gpt2/mistral decode pins cover the machinery fast
def test_neox_cache_decode_equals_recompute():
    cfg = NeoXConfig.tiny()
    model = NeoXForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 6)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    got = ptd.generate(model, params, ids, max_new_tokens=4, temperature=0.0)
    seq = np.asarray(ids)
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)
