"""Speculative decoding (speculative.py): the self-pinning property.

Greedy speculative decoding is EXACTLY the target model's greedy decode
— the draft only changes how many target forward passes it takes, never
which tokens come out. Every test here pins ``generate_speculative``
token-for-token against ``generate(target, temperature=0)`` (itself
pinned against full recompute in test_generation.py), across draft
quality (random independent draft = low acceptance; draft == target =
full acceptance), eos early exit, batch raggedness over rounds, and
both model families' decode contracts.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.generation import generate
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.speculative import generate_speculative


def _gpt2_pair(vocab=97, n_positions=96):
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    tcfg = GPT2Config(
        vocab_size=vocab, n_positions=n_positions, hidden_size=32,
        num_layers=2, num_heads=2, dropout_rate=0.0,
    )
    dcfg = GPT2Config(
        vocab_size=vocab, n_positions=n_positions, hidden_size=16,
        num_layers=1, num_heads=2, dropout_rate=0.0,
    )
    target = GPT2LMHead(tcfg)
    draft = GPT2LMHead(dcfg)
    rng = np.random.default_rng(7)
    ids = jnp.asarray(rng.integers(vocab, size=(3, 6)).astype(np.int32))
    tparams = target.init(jax.random.key(0), ids)["params"]
    dparams = draft.init(jax.random.key(1), ids)["params"]
    return target, tparams, draft, dparams, ids


def test_speculative_equals_target_greedy():
    # an independently-initialized draft agrees with the target only by
    # chance — acceptance is mixed, so rounds exercise partial-accept,
    # zero-accept, and (occasionally) full-accept slot bookkeeping
    target, tp, draft, dp, ids = _gpt2_pair()
    want = generate(target, tp, ids, max_new_tokens=12, temperature=0.0)
    got, stats = generate_speculative(
        target, tp, draft, dp, ids,
        max_new_tokens=12, num_draft_tokens=3, return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert 1 <= stats["rounds"] <= 11  # prefill emits token 1 of 12
    assert 0 <= stats["accepted"] <= stats["drafted"]


@pytest.mark.slow
@pytest.mark.parametrize("k", [1, 5])
def test_speculative_equals_target_greedy_draft_widths(k):
    target, tp, draft, dp, ids = _gpt2_pair()
    want = generate(target, tp, ids, max_new_tokens=8, temperature=0.0)
    got = generate_speculative(
        target, tp, draft, dp, ids,
        max_new_tokens=8, num_draft_tokens=k,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_perfect_draft_accepts_everything():
    # draft == target: every proposal matches, so each round emits k+1
    # tokens and the loop finishes in ceil((max_new - 1) / (k + 1))
    # rounds after the prefill token — the whole point of speculation
    target, tp, _, _, ids = _gpt2_pair()
    max_new, k = 13, 3
    want = generate(target, tp, ids, max_new_tokens=max_new,
                    temperature=0.0)
    got, stats = generate_speculative(
        target, tp, target, tp, ids,
        max_new_tokens=max_new, num_draft_tokens=k, return_stats=True,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))
    assert stats["rounds"] == -(-(max_new - 1) // (k + 1))  # ceil div
    assert stats["accepted"] == stats["drafted"]


@pytest.mark.slow
def test_speculative_eos_padding_matches():
    # pick the eos from the target's own output so at least one row
    # actually terminates early; both paths must then pad identically
    target, tp, draft, dp, ids = _gpt2_pair()
    plain = generate(target, tp, ids, max_new_tokens=10, temperature=0.0)
    eos = int(np.asarray(plain)[0, ids.shape[1] + 4])  # a token row 0 emits
    want = generate(target, tp, ids, max_new_tokens=10, temperature=0.0,
                    eos_id=eos, pad_id=0)
    got = generate_speculative(
        target, tp, draft, dp, ids,
        max_new_tokens=10, num_draft_tokens=3, eos_id=eos, pad_id=0,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_speculative_single_token():
    # max_new_tokens=1 never enters the verify loop: prefill emits it
    target, tp, draft, dp, ids = _gpt2_pair()
    want = generate(target, tp, ids, max_new_tokens=1, temperature=0.0)
    got = generate_speculative(
        target, tp, draft, dp, ids, max_new_tokens=1, num_draft_tokens=4,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_speculative_llama_pair():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    vocab = 89
    tcfg = LlamaConfig(
        vocab_size=vocab, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, max_seq_len=128,
    )
    dcfg = LlamaConfig(
        vocab_size=vocab, hidden_size=16, num_layers=1, num_heads=2,
        num_kv_heads=1, intermediate_size=32, max_seq_len=128,
    )
    target, draft = LlamaForCausalLM(tcfg), LlamaForCausalLM(dcfg)
    rng = np.random.default_rng(3)
    ids = jnp.asarray(rng.integers(vocab, size=(2, 5)).astype(np.int32))
    tp = target.init(jax.random.key(0), ids)["params"]
    dp = draft.init(jax.random.key(1), ids)["params"]
    want = generate(target, tp, ids, max_new_tokens=9, temperature=0.0)
    got = generate_speculative(
        target, tp, draft, dp, ids, max_new_tokens=9, num_draft_tokens=3,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_speculative_accept_distribution():
    """Monte-Carlo pin of the rejection-sampling core (Leviathan Thm 1):
    with proposals drawn from q, the first emitted token (proposal if
    accepted, residual draw if not) must be distributed as p — for p and
    q that genuinely disagree."""
    from pytorch_distributed_tpu.speculative import speculative_accept

    V, B, k = 12, 16384, 2
    rng = np.random.default_rng(0)
    p_row = rng.dirichlet(np.ones(V) * 0.7)
    q_row = rng.dirichlet(np.ones(V) * 0.7)  # independent => p != q
    p = jnp.asarray(np.tile(p_row, (B, k + 1, 1)), jnp.float32)
    q = jnp.asarray(np.tile(q_row, (B, k, 1)), jnp.float32)
    key = jax.random.key(42)
    kq, kacc = jax.random.split(key)
    # proposals ~ q, independently per row/slot
    proposals = jax.random.categorical(
        kq, jnp.log(q), axis=-1
    ).astype(jnp.int32)
    a, corr = speculative_accept(p, q, proposals, kacc)
    a, corr, proposals = map(np.asarray, (a, corr, proposals))
    first = np.where(a >= 1, proposals[:, 0], corr)
    emp = np.bincount(first, minlength=V) / B
    tv = 0.5 * np.abs(emp - p_row).sum()
    # sampling noise at B=16384, V=12 is ~0.01 TV; a wrong residual or
    # acceptance rule shifts mass by O(TV(p,q)) ~ 0.4
    assert tv < 0.03, f"TV(emitted, p) = {tv:.4f}"
    # bonus path: rows that accepted everything draw corr from p_k
    bonus = corr[a == k]
    assert len(bonus) > 200  # enough mass to test
    emp_b = np.bincount(bonus, minlength=V) / len(bonus)
    assert 0.5 * np.abs(emp_b - p_row).sum() < 0.06


def test_speculative_accept_all_accepted_edge():
    """p == q on every proposal position accepts surely (coins < 1
    strictly), a == k, and the round's token comes from the BONUS
    distribution p_k — pinned exactly with a one-hot bonus row."""
    from pytorch_distributed_tpu.speculative import speculative_accept

    B, k, V = 4, 3, 7
    rng = np.random.default_rng(1)
    q_rows = rng.dirichlet(np.ones(V), size=(B, k)).astype(np.float32)
    q = jnp.asarray(q_rows)
    bonus = np.zeros((B, 1, V), np.float32)
    bonus[:, 0, 5] = 1.0  # deterministic bonus draw
    p = jnp.concatenate([q, jnp.asarray(bonus)], axis=1)
    proposals = jax.random.categorical(
        jax.random.key(0), jnp.log(q), axis=-1
    ).astype(jnp.int32)
    a, corr = speculative_accept(p, q, proposals, jax.random.key(1))
    assert (np.asarray(a) == k).all()
    assert (np.asarray(corr) == 5).all()


def test_speculative_accept_all_rejected_edge():
    """p putting ZERO mass on every proposal rejects at position 0
    (accept prob p(x)/q(x) = 0), and the correction samples the
    residual norm(max(p - q, 0)) — which, with q one-hot on the
    proposal, is exactly p_0; pinned with a one-hot p_0."""
    from pytorch_distributed_tpu.speculative import speculative_accept

    B, k, V = 4, 3, 7
    proposals = jnp.zeros((B, k), jnp.int32)  # every proposal = token 0
    q = jnp.zeros((B, k, V)).at[:, :, 0].set(1.0)  # q one-hot on it
    p_np = np.zeros((B, k + 1, V), np.float32)
    p_np[:, :, 3] = 1.0  # target mass entirely on token 3 != proposal
    a, corr = speculative_accept(
        jnp.asarray(p_np), q, proposals, jax.random.key(2)
    )
    assert (np.asarray(a) == 0).all()
    assert (np.asarray(corr) == 3).all()


def test_speculative_accept_partial_prefix_stops_at_first_reject():
    """Acceptance is a PREFIX: a later agreeing position cannot resurrect
    a row after its first rejection (the cumprod form)."""
    from pytorch_distributed_tpu.speculative import speculative_accept

    B, k, V = 1, 3, 5
    proposals = jnp.asarray([[1, 2, 1]], jnp.int32)
    q = jnp.zeros((B, k, V))
    q = q.at[0, 0, 1].set(1.0).at[0, 1, 2].set(1.0).at[0, 2, 1].set(1.0)
    p_np = np.zeros((B, k + 1, V), np.float32)
    p_np[0, 0, 1] = 1.0   # position 0: agrees surely
    p_np[0, 1, 4] = 1.0   # position 1: zero mass on proposal -> reject
    p_np[0, 2, 1] = 1.0   # position 2 agrees — but must never be reached
    p_np[0, 3, 0] = 1.0
    a, corr = speculative_accept(
        jnp.asarray(p_np), q, proposals, jax.random.key(3)
    )
    assert int(a[0]) == 1
    assert int(corr[0]) == 4  # residual at the REJECTED position = p_1


@pytest.mark.slow
def test_sampled_speculative_marginals_match_generate():
    """End-to-end distribution pin: over many same-prompt rows, each
    emitted position's marginal under sampled speculative decoding must
    match generate's (both sample the target's filtered distribution).
    Deterministic given the fixed seeds."""
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    vocab, B, max_new = 32, 2048, 3
    tcfg = GPT2Config(
        vocab_size=vocab, n_positions=32, hidden_size=16, num_layers=1,
        num_heads=2, dropout_rate=0.0,
    )
    dcfg = GPT2Config(
        vocab_size=vocab, n_positions=32, hidden_size=8, num_layers=1,
        num_heads=1, dropout_rate=0.0,
    )
    target, draft = GPT2LMHead(tcfg), GPT2LMHead(dcfg)
    prompt = jnp.tile(
        jnp.asarray([[5, 11, 2]], jnp.int32), (B, 1)
    )  # identical rows -> each row is an independent sample
    tp = target.init(jax.random.key(0), prompt[:1])["params"]
    dp = draft.init(jax.random.key(1), prompt[:1])["params"]
    ref = np.asarray(generate(
        target, tp, prompt, max_new_tokens=max_new, temperature=1.0,
        rng=jax.random.key(7),
    ))[:, 3:]
    got = np.asarray(generate_speculative(
        target, tp, draft, dp, prompt, max_new_tokens=max_new,
        num_draft_tokens=2, temperature=1.0, rng=jax.random.key(8),
    ))[:, 3:]
    for pos in range(max_new):
        e1 = np.bincount(ref[:, pos], minlength=vocab) / B
        e2 = np.bincount(got[:, pos], minlength=vocab) / B
        tv = 0.5 * np.abs(e1 - e2).sum()
        # two empirical draws of the same law at B=2048, V<=32: ~0.04 TV
        assert tv < 0.1, f"position {pos}: TV = {tv:.4f}"


@pytest.mark.slow
def test_sampled_perfect_draft_accepts_nearly_everything():
    # p == q makes the acceptance ratio 1 up to chunk-vs-single-step
    # float noise; coins ~ U[0,1) then accept (near-)surely
    target, tp, _, _, ids = _gpt2_pair()
    _, stats = generate_speculative(
        target, tp, target, tp, ids, max_new_tokens=10,
        num_draft_tokens=3, temperature=1.0, rng=jax.random.key(3),
        return_stats=True,
    )
    assert stats["accepted"] >= 0.9 * stats["drafted"]


def test_speculative_validation():
    target, tp, draft, dp, ids = _gpt2_pair()
    with pytest.raises(ValueError, match="temperature"):
        generate_speculative(
            target, tp, draft, dp, ids,
            max_new_tokens=4, temperature=-0.5,
        )
    with pytest.raises(ValueError, match="top_k/top_p"):
        generate_speculative(
            target, tp, draft, dp, ids,
            max_new_tokens=4, top_k=5,  # greedy has no distribution
        )
    with pytest.raises(ValueError, match="cache slots"):
        # worst-case append-only sizing exceeds n_positions=96
        generate_speculative(
            target, tp, draft, dp, ids,
            max_new_tokens=40, num_draft_tokens=4,
        )
    with pytest.raises(ValueError, match="num_draft_tokens"):
        generate_speculative(
            target, tp, draft, dp, ids, max_new_tokens=4,
            num_draft_tokens=0,
        )


@pytest.mark.slow  # r5 final refit: speculative greedy==target pin stays fast
def test_ragged_prompts_match_ragged_generate():
    """Left-padded batches decode identically to generate's ragged path
    (itself pinned equal to unpadded solo runs) — prompt pads are just
    pre-existing invalid slots to the bubble machinery."""
    target, tp, draft, dp, _ = _gpt2_pair()
    # rows with real lengths 6, 4, 2, left-padded to width 6
    rng = np.random.default_rng(11)
    ids = jnp.asarray(rng.integers(1, 97, size=(3, 6)).astype(np.int32))
    mask = jnp.asarray(
        [[True] * 6, [False] * 2 + [True] * 4, [False] * 4 + [True] * 2]
    )
    ids = jnp.where(mask, ids, 0)
    want = generate(target, tp, ids, max_new_tokens=8, temperature=0.0,
                    prompt_mask=mask)
    got = generate_speculative(
        target, tp, draft, dp, ids, max_new_tokens=8,
        num_draft_tokens=3, prompt_mask=mask,
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_ragged_rejects_right_padding():
    target, tp, draft, dp, ids = _gpt2_pair()
    bad = jnp.asarray([[True, True, True, True, False, False]] * 3)
    with pytest.raises(ValueError, match="LEFT-padded"):
        generate_speculative(
            target, tp, draft, dp, ids, max_new_tokens=4,
            num_draft_tokens=2, prompt_mask=bad,
        )
