"""Disaggregated serving (serve/, round 18): tiers, KV migration,
router, prefix registry.

Contracts under test, on top of test_serve.py / test_serve_paged.py:

* roles change which programs a request REACHES, never their math: a
  prefill-role engine fills pages and ships ``MigrationFrame``s, a
  decode-role engine takes work via ``inject_migration`` only, and the
  finished stream — greedy AND sampled — is bit-identical to the solo
  engine's (the page-table splice plus the rng re-derivation on the
  receiver reproduce the solo tick state exactly);
* the wire format is fingerprint-guarded end to end: a receiver whose
  pool geometry disagrees (page size, cache dtype, model shape), or a
  payload damaged in flight, is refused BEFORE any bytes are used —
  at the codec layer and again at ``inject_migration``;
* int8 pools migrate their native payload (int8 K/V + f32 scale
  sidecars) with exact byte accounting: ``payload.nbytes == n_pages *
  frame_nbytes(cache)``, and the native frame costs <= 0.55x its f32
  equivalent;
* the cross-engine prefix registry prefills a shared system prompt
  ONCE per fleet (put counts pinned), peers adopt published pages
  instead of recomputing, refcounts survive engine churn
  (``release_holder``), and adoption never changes tokens;
* the router is a deterministic pure function of the telemetry record
  stream: total-order picks, evict-and-replay on ``serve.engine_loss``
  with final streams bit-identical to the no-fault run, and a fleet
  that stays duck-compatible with ``loadgen.drive``.

The 2-process worker (``hostring_workers.disagg_migration_worker``)
runs the same hand-off over the ring's REAL P2P mailboxes.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.serve import (
    EngineConfig,
    GaugeBoard,
    InProcPrefixStore,
    MigrationError,
    Request,
    RequestStatus,
    Router,
    ServeEngine,
    SpecConfig,
    decode_frame,
    encode_frame,
    extract_frames,
    frame_f32_nbytes,
    frame_nbytes,
    frame_signature,
    roundtrip_frame,
)
from tests import hostring_workers

pytestmark = pytest.mark.disagg


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def gpt2_int8():
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0, kv_cache_quantize="int8",
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft(gpt2):
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=16, num_layers=1,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


ECFG = dict(num_slots=4, max_len=96, prefill_chunk=8)


def _requests(n=6, seed=7, vocab=97, new=8):
    """Mixed greedy/sampled requests with ragged prompt lengths."""
    rng = np.random.default_rng(seed)
    out = []
    for i in range(n):
        plen = int(rng.integers(3, 25))
        out.append(Request(
            rng.integers(1, vocab, size=plen).astype(np.int32),
            max_new_tokens=new, request_id=f"r{seed}-{i}",
            temperature=(0.9 if i % 2 else 0.0),
            top_k=(20 if i % 2 else None), seed=1000 + i,
        ))
    return out


def _solo_streams(model, params, reqs, **cfg):
    eng = ServeEngine(model, params, EngineConfig(**(ECFG | cfg)))
    hs = [eng.submit(r) for r in reqs]
    eng.run_until_drained()
    assert all(h.status is RequestStatus.COMPLETED for h in hs)
    return {r.request_id: h.tokens for r, h in zip(reqs, hs)}


def _migrate_all(pre, dec):
    """Hand every prefill outbox frame to the decode engine through the
    full wire codec, then drain — the router's loop, unrolled. Returns
    the decode-side handles by request id."""
    pre.run_until_drained()
    got = {}
    while pre.outbox:
        frame = pre.outbox.popleft()
        wire, _ = roundtrip_frame(frame, dec.migration_signature)
        got[frame.request_id] = dec.inject_migration(wire)
    dec.run_until_drained()
    return got


# -- roles -----------------------------------------------------------------
class TestRoles:
    def test_bad_role_refused(self):
        with pytest.raises(ValueError, match="role"):
            EngineConfig(role="mixed")

    def test_decode_role_refuses_submit(self, gpt2):
        eng = ServeEngine(*gpt2, EngineConfig(role="decode", **ECFG))
        with pytest.raises(RuntimeError, match="decode"):
            eng.submit(_requests(1)[0])

    def test_prefill_role_refuses_inject(self, gpt2):
        pre = ServeEngine(*gpt2, EngineConfig(role="prefill", **ECFG))
        h = pre.submit(_requests(1, seed=11)[0])
        pre.run_until_drained()
        assert h.status is RequestStatus.MIGRATED
        frame = pre.outbox.popleft()
        with pytest.raises(RuntimeError, match="prefill"):
            pre.inject_migration(frame)

    def test_spec_with_role_refused(self, gpt2, draft):
        spec = SpecConfig(*draft, num_draft_tokens=2)
        with pytest.raises(ValueError, match="spec"):
            ServeEngine(
                *gpt2, EngineConfig(role="prefill", **ECFG), spec=spec
            )

    def test_spec_with_store_refused(self, gpt2, draft):
        spec = SpecConfig(*draft, num_draft_tokens=2)
        with pytest.raises(ValueError, match="spec"):
            ServeEngine(
                *gpt2, EngineConfig(**ECFG), spec=spec,
                prefix_store=InProcPrefixStore(),
            )

    def test_migration_parity_greedy_and_sampled(self, gpt2):
        """THE correctness gate: prefill -> wire -> decode streams are
        bit-identical to the solo engine's, greedy and sampled alike."""
        reqs = _requests(6, seed=21)
        want = _solo_streams(*gpt2, reqs)
        pre = ServeEngine(*gpt2, EngineConfig(role="prefill", **ECFG))
        dec = ServeEngine(*gpt2, EngineConfig(role="decode", **ECFG))
        hs = {r.request_id: pre.submit(r) for r in reqs}
        pre.run_until_drained()
        assert all(
            h.status is RequestStatus.MIGRATED for h in hs.values()
        )
        assert pre.migrated_out == len(reqs)
        got = {}
        while pre.outbox:
            frame = pre.outbox.popleft()
            wire, _ = roundtrip_frame(frame, dec.migration_signature)
            got[frame.request_id] = dec.inject_migration(wire)
        dec.run_until_drained()
        assert dec.migrated_in == len(reqs)
        for rid, h in got.items():
            assert h.status is RequestStatus.COMPLETED, (rid, h.error)
            assert h.tokens == want[rid], rid
        # the shipped first token heads the decode stream: emission is
        # exactly-once across the hand-off
        for rid, h in got.items():
            assert len(h.tokens) == len(want[rid])


# -- wire format -----------------------------------------------------------
class TestWire:
    def _frame(self, gpt2, seed=31):
        pre = ServeEngine(*gpt2, EngineConfig(role="prefill", **ECFG))
        pre.submit(_requests(1, seed=seed)[0])
        pre.run_until_drained()
        return pre, pre.outbox.popleft()

    def test_codec_roundtrip(self, gpt2):
        pre, frame = self._frame(gpt2)
        arrays = encode_frame(frame)
        back = decode_frame(
            arrays[1], arrays[2], arrays[3], pre.migration_signature
        )
        assert back.request == frame.request
        assert back.first_token == frame.first_token
        assert back.prompt_len == frame.prompt_len
        assert back.n_pages == frame.n_pages
        assert back.signature == frame.signature
        assert np.array_equal(back.payload, frame.payload)

    def test_codec_refuses_wrong_signature(self, gpt2):
        _, frame = self._frame(gpt2, seed=32)
        arrays = encode_frame(frame)
        with pytest.raises(MigrationError, match="fingerprint"):
            decode_frame(
                arrays[1], arrays[2], arrays[3], "ps=1|bogus:(1,):int8"
            )

    def test_codec_refuses_damaged_payload(self, gpt2):
        pre, frame = self._frame(gpt2, seed=33)
        arrays = encode_frame(frame)
        arrays[3] = arrays[3].copy()
        arrays[3][0] ^= 0xFF
        with pytest.raises(MigrationError, match="fingerprint"):
            decode_frame(
                arrays[1], arrays[2], arrays[3], pre.migration_signature
            )

    def test_inject_refuses_mixed_geometry(self, gpt2):
        """A fleet mixing page sizes is refused at inject time even when
        the frame object is handed over directly (no codec hop)."""
        pre, frame = self._frame(gpt2, seed=34)
        dec = ServeEngine(
            *gpt2, EngineConfig(role="decode", **(ECFG | {"page_size": 4}))
        )
        assert dec.migration_signature != pre.migration_signature
        with pytest.raises(MigrationError, match="geometry"):
            dec.inject_migration(frame)

    def test_inject_refuses_inconsistent_page_count(self, gpt2):
        pre, frame = self._frame(gpt2, seed=35)
        dec = ServeEngine(*gpt2, EngineConfig(role="decode", **ECFG))
        bad = dataclasses.replace(frame, n_pages=frame.n_pages + 1)
        with pytest.raises(MigrationError, match="page"):
            dec.inject_migration(bad)

    def test_int8_payload_accounting(self, gpt2_int8):
        """int8 pools ship native bytes with EXACT accounting: the
        payload is n_pages frames, each frame_nbytes long, and the
        native frame undercuts the f32 frame by the pinned ratio."""
        pre = ServeEngine(
            *gpt2_int8, EngineConfig(role="prefill", **ECFG)
        )
        per_page = frame_nbytes(pre.pool.cache)
        f32_page = frame_f32_nbytes(pre.pool.cache)
        # D=16: (1 + 4/16) / 4 = 0.3125x — comfortably under 0.55
        assert per_page * 100 <= 55 * f32_page, (per_page, f32_page)
        reqs = _requests(3, seed=41)
        for r in reqs:
            pre.submit(r)
        pre.run_until_drained()
        dec = ServeEngine(
            *gpt2_int8, EngineConfig(role="decode", **ECFG)
        )
        ps = pre.pool.page_size
        while pre.outbox:
            frame = pre.outbox.popleft()
            assert frame.n_pages == -(-frame.prompt_len // ps)
            assert frame.payload.nbytes == frame.n_pages * per_page
            wire, nbytes = roundtrip_frame(
                frame, dec.migration_signature
            )
            assert nbytes > frame.payload.nbytes  # framing overhead
            h = dec.inject_migration(wire)
            dec._drain_inject_backlog()
            # splice landed the wire bytes verbatim (pre-tick)
            got = extract_frames(
                dec.pool.cache, list(h._lease.page_row[: frame.n_pages])
            )
            assert got.tobytes() == np.asarray(
                frame.payload, np.uint8
            ).tobytes()
        dec.run_until_drained()

    def test_int8_migration_parity(self, gpt2_int8):
        """Lossless codec + splice: int8 caches migrate bit-exactly."""
        reqs = _requests(4, seed=42)
        want = _solo_streams(*gpt2_int8, reqs)
        pre = ServeEngine(
            *gpt2_int8, EngineConfig(role="prefill", **ECFG)
        )
        dec = ServeEngine(
            *gpt2_int8, EngineConfig(role="decode", **ECFG)
        )
        for r in reqs:
            pre.submit(r)
        got = _migrate_all(pre, dec)
        for rid, toks in want.items():
            assert got[rid].status is RequestStatus.COMPLETED, rid
            assert got[rid].tokens == toks, rid

    def test_signature_names_geometry(self, gpt2, gpt2_int8):
        s_f32 = frame_signature(
            ServeEngine(*gpt2, EngineConfig(**ECFG)).pool.cache, 8
        )
        s_int8 = frame_signature(
            ServeEngine(*gpt2_int8, EngineConfig(**ECFG)).pool.cache, 8
        )
        assert s_f32 != s_int8
        assert "ps=8" in s_f32


# -- prefix registry -------------------------------------------------------
class TestPrefixStore:
    def test_first_writer_wins(self):
        store = InProcPrefixStore(signature="sig")
        a = np.arange(16, dtype=np.uint8)
        assert store.put(b"k1", a, "e0", "sig")
        assert not store.put(b"k1", a * 0, "e1", "sig")  # dup: a no-op
        assert store.stats()["dup_puts"] == 1
        got = store.get(b"k1", "e1")
        assert np.array_equal(got, a)  # first writer stays canonical
        assert store.stats()["hits"] == 1

    def test_signature_mismatch_refused(self):
        store = InProcPrefixStore(signature="sig")
        with pytest.raises(ValueError, match="geometry"):
            store.put(b"k", np.zeros(4, np.uint8), "e0", "other-sig")

    def test_holder_pins_survive_pressure(self):
        """Pinned entries are never evicted; releasing the holder frees
        them for LRU reclaim — refcounts across engine churn."""
        store = InProcPrefixStore(capacity_pages=2, signature="sig")
        store.put(b"a", np.zeros(32, np.uint8), "e0", "sig")
        store.put(b"b", np.zeros(32, np.uint8), "e1", "sig")
        # every entry pinned: a third put must refuse, never evict a pin
        assert not store.put(b"c", np.zeros(32, np.uint8), "e2", "sig")
        assert b"a" in store and b"b" in store
        assert store.pinned(b"a") == 1
        assert store.release_holder("e0") == 1
        assert store.pinned(b"a") == 0
        assert store.put(b"c", np.zeros(32, np.uint8), "e2", "sig")
        assert b"a" not in store  # the unpinned LRU entry made room
        assert b"b" in store
        assert store.stats()["evictions"] == 1

    def test_store_with_spec_refused(self, gpt2, draft):
        spec = SpecConfig(*draft, num_draft_tokens=2)
        with pytest.raises(ValueError):
            ServeEngine(
                *gpt2, EngineConfig(**ECFG), spec=spec,
                prefix_store=InProcPrefixStore(),
            )

    def test_fleet_prefix_once(self, gpt2):
        """The headline registry contract: one shared system prompt is
        prefilled by ONE engine; a peer ADOPTS the published pages
        (puts stay at the shared page count) and tokens never change."""
        store = InProcPrefixStore()
        shared = np.arange(1, 17, dtype=np.int32)  # 2 full pages @ ps=8
        rng = np.random.default_rng(5)
        reqs = [
            Request(
                np.concatenate([
                    shared, rng.integers(1, 97, size=5).astype(np.int32)
                ]),
                max_new_tokens=6, request_id=f"shared-{i}",
            )
            for i in range(4)
        ]
        want = _solo_streams(*gpt2, reqs)
        engines = [
            ServeEngine(
                *gpt2,
                # Explicit page_size: the auto default picks 32 at
                # max_len=96, leaving the 16-token prefix with ZERO
                # full pages and nothing to publish.
                EngineConfig(
                    role="solo", engine_id=f"e{i}", page_size=8, **ECFG
                ),
                prefix_store=store,
            )
            for i in range(2)
        ]
        # e0 serves the first two requests and publishes the shared
        # pages...
        h0 = [engines[0].submit(r) for r in reqs[:2]]
        engines[0].run_until_drained()
        assert store.stats()["puts"] == 2  # once per FLEET, exactly
        assert engines[0].store_published_pages == 2
        # ...then e1 must adopt them instead of recomputing: its first
        # shared request splices from the store, the second shares the
        # adopted pages through the normal LOCAL registry
        h1 = [engines[1].submit(r) for r in reqs[2:]]
        engines[1].run_until_drained()
        assert engines[1].store_adopted_pages == 2
        assert engines[1].store_published_pages == 0  # never re-put
        assert store.stats()["puts"] == 2  # STILL once per fleet
        assert store.stats()["hits"] >= 2
        for r, h in zip(reqs, h0 + h1):
            assert h.status is RequestStatus.COMPLETED
            assert h.tokens == want[r.request_id], r.request_id
        # churn: the router's loss hook releases e1's pins; entries
        # stay resident (canonical for the fleet) but become evictable
        store.release_holder("e1")
        assert len(store) == 2


# -- router ----------------------------------------------------------------
class _FakeTelemetry:
    def __init__(self):
        self.engine_id = None
        self.writer = None


class _FakeEngine:
    def __init__(self, role="solo", engine_id=None, sig="sig"):
        self.role = role
        self.engine_id = engine_id
        self.migration_signature = sig
        self.telemetry = _FakeTelemetry()
        self._store = None


class TestRouterConstruction:
    def test_engines_xor_tiers(self):
        with pytest.raises(ValueError, match="not both"):
            Router(
                engines=[_FakeEngine()],
                prefill=[_FakeEngine("prefill")],
                decode=[_FakeEngine("decode")],
            )

    def test_tier_needs_both_sides(self):
        with pytest.raises(ValueError, match="BOTH"):
            Router(prefill=[_FakeEngine("prefill")], decode=[])

    def test_role_mismatch_refused(self):
        with pytest.raises(ValueError, match="role"):
            Router(engines=[_FakeEngine(role="prefill")])

    def test_duplicate_ids_refused(self):
        with pytest.raises(ValueError, match="duplicate"):
            Router(engines=[
                _FakeEngine(engine_id="e0"), _FakeEngine(engine_id="e0"),
            ])

    def test_mixed_geometry_refused(self):
        with pytest.raises(ValueError, match="mixed-geometry"):
            Router(engines=[
                _FakeEngine(sig="a"), _FakeEngine(sig="b"),
            ])

    def test_ids_assigned_and_telemetry_teed(self):
        a, b = _FakeEngine(), _FakeEngine()
        r = Router(engines=[a, b])
        assert [a.engine_id, b.engine_id] == ["e0", "e1"]
        assert a.telemetry.writer is not None
        assert a.telemetry.writer.board is r.board


class TestGaugeBoard:
    def test_rank_total_order(self):
        b = GaugeBoard()
        b.note_routed("e0")
        # fewer outstanding wins; equal load tiebreaks on the id
        assert min(["e0", "e1"], key=b.rank) == "e1"
        b.note_routed("e1")
        assert min(["e0", "e1"], key=b.rank) == "e0"

    def test_request_records_decrement(self):
        b = GaugeBoard(ema=0.5)
        b.note_routed("e0")
        b.ingest("e0", {"event": "request", "ttft_ms": 10.0})
        st = b.snapshot()["e0"]
        assert st["outstanding"] == 0
        assert st["ttft_ewma_ms"] == 10.0  # first sample seeds the EWMA
        b.note_routed("e0")
        b.ingest("e0", {"event": "request", "ttft_ms": 20.0})
        assert b.snapshot()["e0"]["ttft_ewma_ms"] == 15.0

    def test_snapshot_occupancy(self):
        b = GaugeBoard()
        b.ingest("e0", {"event": "snapshot", "slot_occupancy": 0.75})
        assert b.snapshot()["e0"]["slot_occupancy"] == 0.75


class TestRouterFleet:
    def _fleet(self, gpt2, n=2):
        return [
            ServeEngine(
                *gpt2,
                EngineConfig(role="solo", engine_id=f"e{i}", **ECFG),
            )
            for i in range(n)
        ]

    def test_solo_fleet_storm_parity(self, gpt2):
        reqs = _requests(10, seed=51)
        want = _solo_streams(*gpt2, reqs)
        router = Router(engines=self._fleet(gpt2))
        hs = [router.submit(r) for r in reqs]
        router.run_until_drained()
        for r, h in zip(reqs, hs):
            assert h.status is RequestStatus.COMPLETED
            assert h.tokens == want[r.request_id], r.request_id
        s = router.summary()
        assert s["replays"] == 0 and not s["lost_engines"]
        assert sum(
            e.get("completed", 0) for e in s["engines"].values()
        ) == len(reqs)

    def test_disagg_fleet_storm_parity(self, gpt2):
        """1 prefill + 1 decode through the router's outbox drain: every
        stream matches solo, and the migration accounting is exact."""
        reqs = _requests(8, seed=52)
        want = _solo_streams(*gpt2, reqs)
        pre = ServeEngine(
            *gpt2, EngineConfig(role="prefill", engine_id="p0", **ECFG)
        )
        dec = ServeEngine(
            *gpt2, EngineConfig(role="decode", engine_id="d0", **ECFG)
        )
        router = Router(prefill=[pre], decode=[dec])
        hs = [router.submit(r) for r in reqs]
        router.run_until_drained()
        for r, h in zip(reqs, hs):
            assert h.status is RequestStatus.COMPLETED
            assert h.tokens == want[r.request_id], r.request_id
        assert router.migration_frames == len(reqs)
        per_page = frame_nbytes(pre.pool.cache)
        ps = pre.pool.page_size
        pages = sum(-(-r.prompt_len // ps) for r in reqs)
        # EXACT payload accounting: every migrated page, nothing else
        assert router.migration_payload_bytes == pages * per_page
        assert router.migration_bytes > router.migration_payload_bytes

    def test_engine_loss_replay_parity(self, gpt2):
        """Evict-and-replay: kill e1 mid-storm; its in-flight requests
        replay on the survivor and every FINAL stream matches the
        no-fault run bit for bit."""
        reqs = _requests(10, seed=53)
        want = _solo_streams(*gpt2, reqs)
        router = Router(engines=self._fleet(gpt2))
        hs = [router.submit(r) for r in reqs]
        with faults.injected("serve.engine_loss:mode=raise,match=e1,after=2"):
            router.run_until_drained()
        assert router.lost_engines == ["e1"]
        assert router.replays >= 1
        for r, h in zip(reqs, hs):
            assert h.status is RequestStatus.COMPLETED, (
                r.request_id, h.status,
            )
            assert h.tokens == want[r.request_id], r.request_id
        # every replayed handle landed on the survivor
        assert all(
            h.engine_id == "e0" for h in hs if h.replays
        )

    def test_losing_the_last_tier_member_is_loud(self, gpt2):
        reqs = _requests(4, seed=54)
        router = Router(engines=self._fleet(gpt2, n=1))
        for r in reqs:
            router.submit(r)
        with faults.injected("serve.engine_loss:mode=raise,match=e0"):
            with pytest.raises(RuntimeError, match="surviving"):
                router.run_until_drained()

    def test_drive_duck_compat(self, gpt2):
        from pytorch_distributed_tpu.serve import drive, uniform_arrivals

        reqs = _requests(6, seed=55)
        want = _solo_streams(*gpt2, reqs)
        router = Router(engines=self._fleet(gpt2))
        wall = drive(router, reqs, uniform_arrivals(len(reqs), 0.0))
        assert wall > 0
        for r in reqs:
            rh = router._live[r.request_id]
            assert rh.tokens == want[r.request_id]

    def test_router_records_migrations(self, gpt2):
        from pytorch_distributed_tpu.train.metrics import (
            MetricsWriter,
            read_metrics,
        )

        reqs = _requests(3, seed=56)
        import tempfile

        with tempfile.TemporaryDirectory() as d:
            path = f"{d}/m.jsonl"
            writer = MetricsWriter(path)
            pre = ServeEngine(
                *gpt2,
                EngineConfig(role="prefill", engine_id="p0", **ECFG),
            )
            dec = ServeEngine(
                *gpt2,
                EngineConfig(role="decode", engine_id="d0", **ECFG),
            )
            router = Router(
                prefill=[pre], decode=[dec], writer=writer
            )
            for r in reqs:
                router.submit(r)
            router.run_until_drained()
            writer.close()
            recs = [
                m for m in read_metrics(path)
                if m.get("split") == "serve"
                and m.get("event") == "migrate"
            ]
        assert len(recs) == len(reqs)
        assert all(r["engine_id"] == "p0" and r["dst"] == "d0"
                   for r in recs)
        assert sum(int(r["payload_nbytes"]) for r in recs) == (
            router.migration_payload_bytes
        )


# -- multi-process ---------------------------------------------------------
def test_migration_over_ring():
    """The same hand-off over the ring's REAL P2P mailboxes, int8
    payloads included — 2 spawned processes, parity pinned receiver-side."""
    world = 2
    results = hostring_workers.run_ring_workers(
        world, hostring_workers.disagg_migration_worker, timeout=420.0
    )
    assert results == [(r, "ok") for r in range(world)], results


@pytest.mark.slow
def test_storm_with_loss_drill(gpt2):
    """The big drill: 2 prefill + 2 decode under a 32-request storm with
    a decode engine killed mid-flight — every stream still matches the
    solo reference, and the fleet's accounting stays exact."""
    from pytorch_distributed_tpu.serve import prefix_shared_requests

    rng = np.random.default_rng(9)
    reqs = prefix_shared_requests(
        rng, 32, 97, prompt_len=(4, 24), new_tokens=(4, 12),
        prefix_share=0.5, shared_prefix_len=8,
    )
    want = _solo_streams(*gpt2, reqs)

    def fleet():
        pre = [
            ServeEngine(
                *gpt2,
                EngineConfig(role="prefill", engine_id=f"p{i}", **ECFG),
            )
            for i in range(2)
        ]
        dec = [
            ServeEngine(
                *gpt2,
                EngineConfig(role="decode", engine_id=f"d{i}", **ECFG),
            )
            for i in range(2)
        ]
        return Router(prefill=pre, decode=dec)

    router = fleet()
    hs = [router.submit(r) for r in reqs]
    with faults.injected("serve.engine_loss:mode=raise,match=d1,after=4"):
        router.run_until_drained()
    assert router.lost_engines == ["d1"]
    for r, h in zip(reqs, hs):
        assert h.status is RequestStatus.COMPLETED, (r.request_id, h.status)
        assert h.tokens == want[r.request_id], r.request_id
    s = router.summary()
    assert s["migration_frames"] >= len(reqs)  # replays re-migrate
    assert "ttft_ms_p99" in s
