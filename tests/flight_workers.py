"""Spawn targets for the flight-recorder hang tests (r19).

Same contract as ``transport_workers``: importable by
``multiprocessing`` spawn, every worker reports ``(rank, payload)``
through the queue with ``payload["err"]`` carrying a traceback string
on failure, and the workers stay JAX-free — they exercise the
always-on recorder exactly the way a real training rank does.

``hang_worker`` is the drill body shared by ``tests/test_flightrec.py``
and ``scripts/chaos_drill.py --drill hang``: N ranks run a few clean
collective rounds, then the victim arms ``comm.hang:mode=skip``
in-process and silently drops out of the next all_reduce (skip returns
its LOCAL data and leaves NO flight record — exactly the evidence
shape a desynced rank produces).  The survivors block until the ring
deadline fires, at which point ``hostring._check`` dumps their flight
rings and raises with the last-completed clause.  Faults are armed via
``faults.configure`` rather than ``PTD_FAULTS`` because spawn gives
every child the same environment — per-rank arming has to happen after
the fork, keyed on the rank argument.
"""

import os
import sys
import time
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

#: clean alternating rounds every rank completes before the hang round —
#: enough history that the autopsy's "last completed" view is non-trivial
WARMUP_ROUNDS = 3


def hang_worker(rank: int, world: int, name: str, q, out_dir: str,
                victim: int, spec: str) -> None:
    """One rank of the hang drill; see the module docstring."""
    try:
        from pytorch_distributed_tpu.runtime import faults, flightrec
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        flightrec.configure(out_dir=out_dir, rank=rank, world=world)
        g = HostRingGroup(name, rank, world, slot_bytes=4096, timeout_s=2.0)
        try:
            x = np.ones(256, np.float32) * (rank + 1)
            for _ in range(WARMUP_ROUNDS):
                g.all_reduce(x)
                g.all_gather(x)
            if rank == victim:
                # silent desync: skip returns local data, records nothing
                faults.configure(spec)
                g.all_reduce(x)
                # outlive the survivors' deadline so they fail on their
                # own -110 timeout, not on this process tearing down the
                # shared ring under them
                time.sleep(4.0)
                q.put((rank, {"role": "victim", "dump": None, "err": None}))
                return
            err = None
            try:
                g.all_reduce(x)
            except RuntimeError as e:
                err = str(e)
            assert err is not None, "survivor's collective did not deadline"
            assert "last completed flight" in err, err
            dump = os.path.join(out_dir,
                                f"{flightrec.DUMP_PREFIX}{rank}.json")
            assert os.path.exists(dump), f"survivor {rank} left no dump"
            q.put((rank, {"role": "survivor", "dump": dump, "err": err}))
        finally:
            g.close()
    except Exception as e:
        q.put((rank, {"role": "?", "dump": None,
                      "err": f"{type(e).__name__}: {e}\n"
                             f"{traceback.format_exc()}"}))


def env_dump_worker(out_dir: str) -> None:
    """Subprocess body for the ``PTD_FLIGHT_DUMP`` env-arming test: run
    with the env var set, log one completed record, then SIGTERM
    yourself — the installed handler must dump before the process dies.
    Spawned via ``python -c`` (not mp) so the import-time
    ``_install_from_env`` path is the one under test."""
    import signal

    from pytorch_distributed_tpu.runtime import flightrec

    seq = flightrec.RECORDER.begin("all_reduce", "sum", "float32",
                                   64, 512, "shm", "env_world")
    flightrec.RECORDER.start(seq)
    flightrec.RECORDER.complete(seq)
    os.kill(os.getpid(), signal.SIGTERM)
    time.sleep(10.0)  # never reached: SIGTERM handler re-kills
    raise SystemExit(f"SIGTERM did not terminate; dir was {out_dir}")
