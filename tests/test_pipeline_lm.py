"""Pipeline-parallel GPT-2: the real-transformer bridge (parallel/pipeline_lm.py).

Validates that the GPipe schedule over the scanned block stack reproduces
the plain (non-pipelined) forward, and that a full Strategy-compiled train
step through ``pipelined_causal_lm_loss_fn`` learns, with the block stack
genuinely sharded over the ``pp`` mesh axis.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.parallel.pipeline_lm import (
    PipelineParallel,
    gpt2_pipeline_logits,
    pipelined_causal_lm_loss_fn,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
)

CFG = GPT2Config(
    vocab_size=128, n_positions=32, hidden_size=32, num_layers=4, num_heads=2,
    dropout_rate=0.0,
)


def _init(seed=0, B=4, S=16):
    model = GPT2LMHead(CFG)
    rng = np.random.default_rng(seed)
    ids = jnp.asarray(rng.integers(CFG.vocab_size, size=(B, S)).astype(np.int32))
    params = model.init(jax.random.key(0), ids[:1])["params"]
    return model, params, ids


@pytest.mark.slow
def test_gpt2_pipeline_logits_match_plain_forward():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=2))
    model, params, ids = _init()
    want = model.apply({"params": params}, ids, train=False)
    got = gpt2_pipeline_logits(CFG, params, ids, num_microbatches=2)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2, rtol=1e-2
    )


@pytest.mark.slow
def test_gpt2_pipeline_four_stages_one_layer_each():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=4))
    model, params, ids = _init()
    want = model.apply({"params": params}, ids, train=False)
    got = gpt2_pipeline_logits(CFG, params, ids, num_microbatches=4)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), atol=2e-2, rtol=1e-2
    )


@pytest.mark.slow
def test_pipelined_loss_matches_plain_loss():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=2))
    model, params, ids = _init()
    plain = causal_lm_loss_fn(model)
    piped = pipelined_causal_lm_loss_fn(CFG, num_microbatches=2)
    rng = jax.random.key(1)
    l_plain, _ = plain(params, {}, {"input_ids": ids}, rng)
    l_piped, _ = piped(params, {}, {"input_ids": ids}, rng)
    np.testing.assert_allclose(
        float(l_piped), float(l_plain), rtol=2e-2
    )


@pytest.mark.slow
def test_pipeline_parallel_strategy_trains_gpt2():
    """Strategy-compiled train step: blocks sharded over pp, loss decreases."""
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=2))
    model, params, ids = _init(B=8)
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-2)
    )
    strategy = PipelineParallel()
    state = strategy.place(state)

    # the stacked block params must actually live sharded over pp
    block_leaf = state.params["blocks"]["block"]["attn_qkv"]["kernel"]
    spec = block_leaf.sharding.spec
    assert spec and spec[0] == "pp", spec
    # embeddings/head stay replicated
    wte = state.params["wte"]["embedding"]
    assert wte.sharding.is_fully_replicated

    step = strategy.compile(
        build_train_step(
            pipelined_causal_lm_loss_fn(CFG, num_microbatches=4)
        ),
        state,
    )
    batch = strategy.shard_batch({"input_ids": np.asarray(ids)})
    losses = []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
    assert losses[-1] < losses[0] * 0.7, losses


@pytest.mark.slow
def test_pipeline_composes_with_tensor_parallel_rules():
    """TP extra_rules must not evict the pp stage sharding (r2 review)."""
    from pytorch_distributed_tpu.models.gpt2 import gpt2_partition_rules

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=2, tp=2))
    model, params, ids = _init()
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-2)
    )
    strategy = PipelineParallel(extra_rules=gpt2_partition_rules())
    state = strategy.place(state)
    qkv = state.params["blocks"]["block"]["attn_qkv"]["kernel"]
    spec = qkv.sharding.spec
    assert spec[0] == "pp", spec              # stage sharding kept
    assert "tp" in jax.tree_util.tree_leaves(tuple(spec)), spec  # TP kept
    mlp = state.params["blocks"]["block"]["mlp_up"]["kernel"].sharding.spec
    assert mlp[0] == "pp" and "tp" in tuple(mlp), mlp
    # embeddings: TP rule applies, no pp
    wte = state.params["wte"]["embedding"].sharding.spec
    assert "pp" not in tuple(wte), wte

    step = strategy.compile(
        build_train_step(
            pipelined_causal_lm_loss_fn(CFG, num_microbatches=2)
        ),
        state,
    )
    batch = strategy.shard_batch({"input_ids": np.asarray(ids)})
    state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


@pytest.mark.slow  # r5 profile refit: the pipeline convergence + schedule tests stay fast
def test_pipeline_layer_count_mismatch_raises():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=2))
    cfg = GPT2Config(
        vocab_size=64, n_positions=16, hidden_size=16, num_layers=3,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    ids = jnp.zeros((2, 8), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    with pytest.raises(ValueError, match="divisible"):
        gpt2_pipeline_logits(cfg, params, ids, num_microbatches=2)
