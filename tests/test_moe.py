"""Mixture-of-Experts layer + expert parallelism (ops/moe.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.ops.moe import (
    MoEMLP,
    collect_aux_loss,
    moe_partition_rules,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec

B, T, D, E, F = 2, 16, 8, 4, 16


def _init(k=2, capacity_factor=1.25, num_experts=E):
    model = MoEMLP(num_experts=num_experts, d_ff=F, k=k,
                   capacity_factor=capacity_factor)
    x = jax.random.normal(jax.random.key(0), (B, T, D), jnp.float32)
    params = model.init(jax.random.key(1), x)["params"]
    return model, params, x


@pytest.mark.slow
def test_forward_shape_and_finite():
    model, params, x = _init()
    y = model.apply({"params": params}, x)
    assert y.shape == x.shape and y.dtype == x.dtype
    assert bool(jnp.all(jnp.isfinite(y)))


def test_single_expert_equals_dense_ffn():
    """E=1, k=1, ample capacity: MoE must reduce to a plain gelu FFN."""
    model, params, x = _init(k=1, capacity_factor=float(E) * 2,
                             num_experts=1)
    y = model.apply({"params": params}, x)
    w_in, w_out = params["w_in"][0], params["w_out"][0]
    tokens = x.reshape(-1, D)
    want = (jax.nn.gelu(tokens @ w_in) @ w_out).reshape(x.shape)
    # compute path is bf16 (precision policy), reference math is f32
    np.testing.assert_allclose(
        np.asarray(y), np.asarray(want), atol=5e-2, rtol=5e-2
    )


def test_aux_loss_sown_and_differentiable():
    model, params, x = _init()

    def loss(p):
        y, state = model.apply(
            {"params": p}, x, mutable=["intermediates"]
        )
        aux = collect_aux_loss(state["intermediates"], weight=0.01)
        return jnp.mean(y**2) + aux

    l, g = jax.value_and_grad(loss)(params)
    assert bool(jnp.isfinite(l))
    assert all(
        bool(jnp.all(jnp.isfinite(leaf)))
        for leaf in jax.tree_util.tree_leaves(g)
    )
    # router must receive gradient (it only gets one through the gates)
    assert float(jnp.max(jnp.abs(g["router"]["kernel"]))) > 0.0


def test_tight_capacity_drops_tokens_gracefully():
    model, params, x = _init(capacity_factor=0.25)
    y = model.apply({"params": params}, x)
    assert bool(jnp.all(jnp.isfinite(y)))
    # dropped tokens produce strictly smaller outputs, not garbage
    assert float(jnp.max(jnp.abs(y))) < 1e3


def test_expert_parallel_sharded_execution():
    """Experts sharded over ep: jit executes with all-to-all routing."""
    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, ep=4))
    from jax.sharding import NamedSharding, PartitionSpec as P

    from pytorch_distributed_tpu.runtime.mesh import current_mesh

    model, params, x = _init()
    mesh = current_mesh()
    rules = dict(moe_partition_rules())
    placed = {
        "router": {
            "kernel": jax.device_put(
                params["router"]["kernel"], NamedSharding(mesh, P())
            )
        },
        "w_in": jax.device_put(
            params["w_in"], NamedSharding(mesh, P("ep", None, "tp"))
        ),
        "w_out": jax.device_put(
            params["w_out"], NamedSharding(mesh, P("ep", "tp", None))
        ),
    }
    xs = jax.device_put(x, NamedSharding(mesh, P("dp")))

    @jax.jit
    def fwd(p, x):
        return model.apply({"params": p}, x)

    y = fwd(placed, xs)
    # sharded vs unsharded differ only by bf16 reduction order
    np.testing.assert_allclose(
        np.asarray(y).astype(np.float32),
        np.asarray(model.apply({"params": params}, x)).astype(np.float32),
        atol=5e-2, rtol=5e-2,
    )