"""Spawn targets for the host-dispatched pipeline tests (r20).

Own importable module (``multiprocessing`` spawn pickles targets by
reference). Each worker is one pipeline STAGE: rank == stage, neighbor
handoffs over the shm hostring. Every rank derives the same initial
params / batches from the shared seed, so the final stage trees can be
merged and compared against the in-process dp reference without any
extra broadcast.

``run_pipeline_world`` is the harness the tests, the chaos drill
(``scripts/chaos_drill.py --drill pipeline``) and the bench ``pipeline``
phase all reuse — one implementation of "spawn S stage workers and
collect their reports".
"""

import os
import sys
import time

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_pipeline_world(world, target, extra_args=(), timeout=300.0,
                       expect=None):
    """Spawn one ``(rank, world, name, q, *extra_args)`` worker per stage
    on the CPU backend; returns the rank-sorted queue reports. ``expect``
    caps how many reports to wait for (default ``world``) — the drill's
    SIGKILLed victim never reports."""
    import multiprocessing as mp
    import uuid

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    name = f"ptdpipe_{uuid.uuid4().hex[:8]}"
    procs = [
        ctx.Process(target=target,
                    args=(r, world, name, q) + tuple(extra_args))
        for r in range(world)
    ]
    old = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        for p in procs:
            p.start()
    finally:
        if old is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = old
    try:
        results = [
            q.get(timeout=timeout)
            for _ in range(world if expect is None else expect)
        ]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return sorted(results)


def _tiny_cfg(opts=None):
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config

    opts = opts or {}
    return GPT2Config(
        vocab_size=opts.get("vocab", 128),
        n_positions=opts.get("n_positions", 32),
        hidden_size=opts.get("hidden", 32),
        num_layers=opts.get("layers", 4),
        num_heads=2,
        dropout_rate=0.0,
    )


def make_batches(steps, batch, seq, vocab, seed):
    """The shared synthetic stream: every stage derives the same batches
    from the seed (stage 0 embeds them, the last stage reads labels)."""
    rng = np.random.default_rng(seed)
    return [
        {"input_ids": rng.integers(0, vocab, (batch, seq)).astype(np.int32)}
        for _ in range(steps)
    ]


def _crc_tree(tree):
    import zlib

    import jax

    crc = 0
    for leaf in jax.tree_util.tree_leaves(tree):
        crc = zlib.crc32(np.ascontiguousarray(leaf).tobytes(), crc)
    return crc


def pipeline_train_worker(rank, world, name, q, opts) -> None:
    """One stage of an S-deep host 1F1B (or gpipe) pipeline on the real
    ring. ``opts`` keys (all optional beyond defaults): steps, batch,
    seq, microbatches, seed, schedule, delay_s, trace_dir, faults,
    lr, depths, timeout_s.

    Reports final stage params (+ CRC), per-step losses from the last
    stage, steady-state wall seconds, and compile counts — everything
    the parity tests, the bench phase, and the drill assert on.
    """
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_distributed_tpu.models.gpt2 import GPT2LMHead
        from pytorch_distributed_tpu.parallel.pipeline_lm import (
            GPT2HostStagePrograms,
            host_act_template,
            host_stage_params,
        )
        from pytorch_distributed_tpu.parallel.pipeline_schedule import (
            HostPipelineStep,
        )
        from pytorch_distributed_tpu.runtime import faults, tracing
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        steps = opts.get("steps", 3)
        batch = opts.get("batch", 8)
        seq = opts.get("seq", 16)
        M = opts.get("microbatches", 4)
        seed = opts.get("seed", 0)
        cfg = _tiny_cfg(opts)
        trace_dir = opts.get("trace_dir")
        if trace_dir:
            tracing.configure(trace_dir)
        if opts.get("faults"):
            faults.configure(opts["faults"])
        model = GPT2LMHead(cfg)
        variables = model.init(
            jax.random.key(seed), jnp.zeros((1, seq), jnp.int32)
        )
        tx = optax.sgd(opts.get("lr", 0.1))
        depths = opts.get("depths")
        sp, buffers = host_stage_params(
            variables["params"], stage=rank, num_stages=world,
            depths=depths,
        )
        group = None
        if world > 1:
            group = HostRingGroup(
                name, rank, world,
                timeout_s=opts.get("timeout_s", 60.0),
            )
        host = HostPipelineStep(
            GPT2HostStagePrograms(cfg, stage=rank, num_stages=world),
            stage=rank, num_stages=world, num_microbatches=M, tx=tx,
            group=group, schedule=opts.get("schedule", "1f1b"),
            act_template=host_act_template(cfg, batch // M, seq),
            delay_s=opts.get("delay_s", 0.0),
        )
        params, opt_state = sp, tx.init(sp)
        batches = make_batches(steps, batch, seq, cfg.vocab_size, seed + 1)
        losses = []
        # step 0 pays the compiles; time the warm steady state only
        t0 = None
        for i, b in enumerate(batches):
            if i == 1:
                t0 = time.perf_counter()
            params, opt_state, met = host.step(
                params, opt_state, b, buffers
            )
            if "loss" in met:
                losses.append(met["loss"])
        wall = time.perf_counter() - t0 if t0 is not None else 0.0
        if trace_dir:
            fname = (
                "trace.json" if rank == 0 else f"trace-rank{rank}.json"
            )
            tracing.get().export(os.path.join(trace_dir, fname))
        np_params = jax.tree_util.tree_map(np.asarray, params)
        q.put((rank, {
            "stage_params": np_params,
            "crc": _crc_tree(np_params),
            "losses": losses,
            "steady_wall_s": wall,
            "compile_counts": host.compile_counts(),
        }))
        if group is not None:
            group.close()
    except Exception as e:  # pragma: no cover - failure reporting
        q.put((rank, {"error": f"{type(e).__name__}: {e}"}))


def spmd_gpipe_main() -> None:
    """SPMD GPipe baseline for the bench ``pipeline`` phase.

    Runs the EXISTING single-process GPipe (parallel/pipeline.py via
    ``pipelined_causal_lm_loss_fn``) over two forced host devices and
    prints a JSON report. The parent must set
    ``XLA_FLAGS=--xla_force_host_platform_device_count=2`` (and
    ``JAX_PLATFORMS=cpu``) in the subprocess env BEFORE this runs — XLA
    reads the flag at first jax import. Opts come as a JSON blob in
    ``sys.argv[1]`` (same keys as ``pipeline_train_worker``).

    This is the honest bench baseline: the SPMD schedule pays
    ``(M+S-1)/M`` garbage-tick compute per step (every stage runs every
    tick, pre-fill and drain ticks included), which is exactly the FLOP
    overhead the host-dispatched 1F1B avoids on a core-bound box.
    """
    import json

    opts = json.loads(sys.argv[1]) if len(sys.argv) > 1 else {}
    import jax
    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.models.gpt2 import GPT2LMHead
    from pytorch_distributed_tpu.parallel.pipeline_lm import (
        PipelineParallel,
        pipelined_causal_lm_loss_fn,
    )
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import TrainState, build_train_step

    steps = opts.get("steps", 3)
    batch = opts.get("batch", 8)
    seq = opts.get("seq", 16)
    M = opts.get("microbatches", 4)
    seed = opts.get("seed", 0)
    world = opts.get("world", 2)
    assert len(jax.devices()) >= world, (
        f"need XLA_FLAGS forcing >= {world} host devices, "
        f"got {len(jax.devices())}"
    )
    cfg = _tiny_cfg(opts)
    model = GPT2LMHead(cfg)
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=world))
    params = model.init(
        jax.random.key(seed), np.zeros((1, seq), np.int32)
    )["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params,
        tx=optax.sgd(opts.get("lr", 0.1)),
    )
    strategy = PipelineParallel()
    state = strategy.place(state)
    step = strategy.compile(
        build_train_step(pipelined_causal_lm_loss_fn(cfg, num_microbatches=M)),
        state,
    )
    losses = []
    t0 = None
    for i, b in enumerate(make_batches(steps, batch, seq, cfg.vocab_size,
                                       seed + 1)):
        if i == 1:
            t0 = time.perf_counter()
        state, metrics = step(state, strategy.shard_batch(b))
        losses.append(float(metrics["loss"]))
    wall = time.perf_counter() - t0 if t0 is not None else 0.0
    print(json.dumps({
        "schedule": "spmd_gpipe",
        "steady_wall_s": wall,
        "losses": losses,
    }))


def pipeline_mismatch_worker(rank, world, name, q) -> None:
    """DETAIL-debug handoff desync: both ends present DIFFERENT
    (microbatch, stage, direction) tags for the same-shape transfer —
    the fingerprint handshake must raise on BOTH ranks naming both
    descriptions (instead of silently delivering the wrong message)."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(
            name, rank, world, timeout_s=30.0, debug=True
        ) as g:
            a = np.full((4, 8), float(rank), np.float32)
            # a matched tagged pair works under DETAIL
            if rank == 0:
                g.send(a, 1, tag="act.m0.s1")
            else:
                got = g.recv(a, 0, tag="act.m0.s1")
                assert np.all(got == 0.0), got
            # then the schedule desyncs: sender ships act.m1, receiver
            # expects act.m2
            err = None
            try:
                if rank == 0:
                    g.send(a, 1, tag="act.m1.s1")
                else:
                    g.recv(a, 0, tag="act.m2.s1")
            except RuntimeError as e:
                err = str(e)
            q.put((rank, {"mismatch_error": err}))
    except Exception as e:  # pragma: no cover - failure reporting
        q.put((rank, {"error": f"{type(e).__name__}: {e}"}))


def pipeline_drill_worker(rank, world, name, q, out_dir, victim,
                          spec) -> None:
    """The ``--drill pipeline`` stage: run the real 1F1B executor with
    the flight recorder armed; the victim stage arms ``spec``
    (``pipeline.stage_stall:mode=kill,...``) and dies mid-schedule, the
    survivors block at the ring deadline, dump their flight rings, and
    report — ``scripts/hang_autopsy.py`` must then convict the victim
    stage from the survivors' dumps alone."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_distributed_tpu.models.gpt2 import GPT2LMHead
        from pytorch_distributed_tpu.parallel.pipeline_lm import (
            GPT2HostStagePrograms,
            host_act_template,
            host_stage_params,
        )
        from pytorch_distributed_tpu.parallel.pipeline_schedule import (
            HostPipelineStep,
        )
        from pytorch_distributed_tpu.runtime import faults, flightrec
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        flightrec.configure(out_dir=out_dir, rank=rank, world=world)
        if rank == victim:
            faults.configure(spec)
        steps, batch, seq, M = 4, 8, 16, 4
        cfg = _tiny_cfg()
        model = GPT2LMHead(cfg)
        variables = model.init(
            jax.random.key(0), jnp.zeros((1, seq), jnp.int32)
        )
        tx = optax.sgd(0.1)
        sp, buffers = host_stage_params(
            variables["params"], stage=rank, num_stages=world
        )
        group = HostRingGroup(name, rank, world, timeout_s=2.0)
        host = HostPipelineStep(
            GPT2HostStagePrograms(cfg, stage=rank, num_stages=world),
            stage=rank, num_stages=world, num_microbatches=M, tx=tx,
            group=group,
            act_template=host_act_template(cfg, batch // M, seq),
        )
        params, opt_state = sp, tx.init(sp)
        try:
            for b in make_batches(steps, batch, seq, cfg.vocab_size, 1):
                params, opt_state, _ = host.step(
                    params, opt_state, b, buffers
                )
            q.put((rank, {"role": "no_hang"}))
        except RuntimeError as e:
            dump = os.path.join(
                out_dir, f"{flightrec.DUMP_PREFIX}{rank}.json"
            )
            q.put((rank, {
                "role": "survivor",
                "err": str(e)[:300],
                "dumped": os.path.exists(dump),
            }))
    except Exception as e:  # pragma: no cover - failure reporting
        q.put((rank, {"error": f"{type(e).__name__}: {e}"}))
