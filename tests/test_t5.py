"""T5 encoder-decoder: HF parity, cache-decode equality, seq2seq loss.

The family's correctness pins, in order of strength:

* HF ``T5ForConditionalGeneration`` logit parity through converted
  weights (both the relu/tied t5-small layout and the
  gated-gelu/untied v1.1 layout) — the relative-bucket arithmetic,
  the unscaled attention, and the tied-head rescale all have to be
  exact for this to pass;
* KV-cache greedy decode == full-recompute argmax (the same pin every
  decoder-only family carries);
* export -> HF load -> logits match (the mapping is invertible).
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.models.t5 import (
    T5Config,
    T5ForConditionalGeneration,
    generate_encdec,
    shift_right,
)
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def _pair(scan_layers: bool, gated: bool):
    hf_cfg = transformers.T5Config(
        vocab_size=211, d_model=48, d_kv=12, d_ff=96, num_layers=2,
        num_decoder_layers=2, num_heads=4,
        relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=not gated,
    )
    hf = transformers.T5ForConditionalGeneration(hf_cfg).eval()
    cfg = T5Config(
        vocab_size=211, d_model=48, d_kv=12, d_ff=96, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=20, dropout_rate=0.0,
        feed_forward_proj="gated-gelu" if gated else "relu",
        tie_word_embeddings=not gated, scan_layers=scan_layers,
    )
    return hf, cfg


def _logits_match(hf, cfg, atol=2e-4):
    from pytorch_distributed_tpu.interop import load_t5_weights

    params = load_t5_weights(_sd(hf), cfg)
    rng = np.random.default_rng(0)
    enc = rng.integers(2, 211, size=(2, 13)).astype(np.int32)
    dec = rng.integers(2, 211, size=(2, 7)).astype(np.int32)
    mask = np.ones((2, 13), np.int64)
    mask[1, 9:] = 0
    with torch.no_grad():
        want = hf(
            input_ids=torch.tensor(enc.astype(np.int64)),
            attention_mask=torch.tensor(mask),
            decoder_input_ids=torch.tensor(dec.astype(np.int64)),
        ).logits.numpy()
    with autocast(enabled=False):
        got = T5ForConditionalGeneration(cfg).apply(
            {"params": params}, jnp.asarray(enc), jnp.asarray(dec),
            input_mask=jnp.asarray(mask.astype(bool)),
        )
    np.testing.assert_allclose(np.asarray(got), want, atol=atol, rtol=2e-4)
    return params


def test_t5_logits_match_hf_scan_relu_tied():
    hf, cfg = _pair(scan_layers=True, gated=False)
    _logits_match(hf, cfg)


@pytest.mark.slow  # r5 final refit: the scan/relu/tied parity variant stays fast
def test_t5_logits_match_hf_unrolled_gated_untied():
    hf, cfg = _pair(scan_layers=False, gated=True)
    _logits_match(hf, cfg)


def test_t5_export_roundtrips_into_hf():
    from pytorch_distributed_tpu.interop import (
        export_t5_weights,
        load_t5_weights,
    )

    hf, cfg = _pair(scan_layers=True, gated=False)
    params = load_t5_weights(_sd(hf), cfg)
    sd2 = export_t5_weights(params, cfg)
    hf2 = transformers.T5ForConditionalGeneration(hf.config).eval()
    result = hf2.load_state_dict(
        {k: torch.tensor(v.copy()) for k, v in sd2.items()}, strict=False
    )
    # rel-bias lives only on block 0 in HF; nothing else may be missing
    assert not result.unexpected_keys, result.unexpected_keys
    rng = np.random.default_rng(3)
    enc = rng.integers(2, 211, size=(2, 9)).astype(np.int64)
    dec = rng.integers(2, 211, size=(2, 5)).astype(np.int64)
    with torch.no_grad():
        a = hf(input_ids=torch.tensor(enc),
               decoder_input_ids=torch.tensor(dec)).logits.numpy()
        b = hf2(input_ids=torch.tensor(enc),
                decoder_input_ids=torch.tensor(dec)).logits.numpy()
    np.testing.assert_allclose(a, b, atol=1e-6)


@pytest.mark.slow  # r5 profile refit: gpt2 greedy==recompute + t5 HF parity stay fast
def test_t5_cache_decode_equals_recompute():
    """Greedy generate through the static KV cache + once-projected
    cross K/V must reproduce full-recompute argmax token-for-token."""
    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(1)
    enc = jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 11)).astype(np.int32))
    dec0 = shift_right(
        jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 4)).astype(np.int32))
    )
    params = model.init(jax.random.key(0), enc, dec0)["params"]
    out = jax.jit(
        lambda p, ids: generate_encdec(
            model, p, ids, max_new_tokens=9, eos_id=-1
        )
    )(params, enc)
    full = model.apply(
        {"params": params}, enc, shift_right(out, cfg.pad_token_id)
    )
    np.testing.assert_array_equal(
        np.asarray(jnp.argmax(full, axis=-1)), np.asarray(out)
    )


@pytest.mark.slow  # r5 profile refit: HF logit parity (masked rows included) pins the mask math fast
def test_t5_encoder_mask_changes_nothing_for_pad_free_rows():
    """A padded encoder row must not perturb an unpadded row's logits
    (the cross-attention mask isolates rows)."""
    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(2)
    enc = jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 8)).astype(np.int32))
    dec = jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 5)).astype(np.int32))
    params = model.init(jax.random.key(0), enc, dec)["params"]
    mask = jnp.asarray(np.array([[1] * 8, [1] * 5 + [0] * 3], bool))
    both = model.apply(
        {"params": params}, enc, dec, input_mask=mask
    )
    solo = model.apply(
        {"params": params}, enc[:1], dec[:1], input_mask=mask[:1]
    )
    np.testing.assert_allclose(
        np.asarray(both[0]), np.asarray(solo[0]), atol=1e-5
    )


@pytest.mark.slow  # r5 final refit: HF parity + decode pins stay fast; recipe smoke (slow) trains e2e
def test_t5_seq2seq_loss_trains():
    """One optimizer step on the seq2seq loss reduces it (wiring test:
    shift_right teacher forcing + label-masked CE through the Trainer
    machinery)."""
    import optax

    from pytorch_distributed_tpu.train import seq2seq_lm_loss_fn

    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(4)
    batch = {
        "input_ids": jnp.asarray(
            rng.integers(2, cfg.vocab_size, (4, 10)).astype(np.int32)
        ),
        "labels": jnp.asarray(
            rng.integers(2, cfg.vocab_size, (4, 6)).astype(np.int32)
        ),
        "label_mask": jnp.asarray(
            np.array([[1] * 6, [1] * 6, [1] * 4 + [0] * 2, [1] * 6], bool)
        ),
    }
    dec0 = shift_right(batch["labels"])
    params = model.init(jax.random.key(0), batch["input_ids"], dec0)[
        "params"
    ]
    loss_fn = seq2seq_lm_loss_fn(model)
    tx = optax.adam(1e-2)
    opt = tx.init(params)

    @jax.jit
    def step(params, opt):
        (loss, _), g = jax.value_and_grad(
            lambda p: loss_fn(p, None, batch, jax.random.key(1)),
            has_aux=True,
        )(params)
        upd, opt = tx.update(g, opt, params)
        return optax.apply_updates(params, upd), opt, loss

    params, opt, l0 = step(params, opt)
    for _ in range(5):
        params, opt, ln = step(params, opt)
    assert float(ln) < float(l0)


@pytest.mark.slow  # r5 profile refit: gpt2 TP-generate + mixtral EP+TP-generate pin sharded decode fast
def test_t5_generate_with_tp_sharded_params():
    """TP serving for the encoder-decoder: params sharded by
    t5_partition_rules decode through the SAME generate_encdec call,
    token-identically — and every TP rule actually matches (regex rules
    fail silently otherwise)."""
    import optax
    import re

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.models.t5 import t5_partition_rules
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.parallel.sharding import path_str
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, tp=4))
    cfg = T5Config.tiny()
    model = T5ForConditionalGeneration(cfg)
    rng = np.random.default_rng(7)
    enc = jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 9)).astype(np.int32))
    dec0 = shift_right(
        jnp.asarray(rng.integers(2, cfg.vocab_size, (2, 3)).astype(np.int32))
    )
    params = model.init(jax.random.key(0), enc, dec0)["params"]

    # every rule must hit at least one param path
    paths = [
        "/" + path_str(p)
        for p, _ in jax.tree_util.tree_flatten_with_path(params)[0]
    ]
    for pattern, _ in t5_partition_rules():
        assert any(re.search(pattern, path) for path in paths), pattern

    want = generate_encdec(model, params, enc, max_new_tokens=6, eos_id=-1)
    strategy = DataParallel(extra_rules=t5_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    q = state.params["decoder"]["layers"]["block"]["attn"]["q"]["kernel"]
    assert "tp" in str(q.sharding.spec)  # heads really shard
    got = generate_encdec(
        model, state.params, enc, max_new_tokens=6, eos_id=-1
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_t5_dropout_sites_active_in_training_mode():
    """HF T5 has THREE dropout applications per sublayer family: the
    block-level residual dropout, the attention-WEIGHT dropout
    (post-softmax, inside T5Attention), and the FFN inner dropout
    (between activation and wo). The latter two were missing until
    ADVICE r4 — this pins them: module-level outputs must move when
    deterministic=False with a live dropout stream, be reproducible
    under the same rng, and be untouched when deterministic=True
    (eval/parity paths)."""
    from pytorch_distributed_tpu.models.t5 import T5Attention, T5FFN

    cfg = T5Config.tiny()
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(2, 6, cfg.d_model)),
        jnp.float32,
    )

    ffn = T5FFN(cfg)
    fp = ffn.init(jax.random.key(0), x)
    f_det = ffn.apply(fp, x)
    f_a = ffn.apply(fp, x, False, rngs={"dropout": jax.random.key(1)})
    f_b = ffn.apply(fp, x, False, rngs={"dropout": jax.random.key(2)})
    f_a2 = ffn.apply(fp, x, False, rngs={"dropout": jax.random.key(1)})
    assert not np.allclose(f_det, f_a)  # inner dropout fires
    assert not np.allclose(f_a, f_b)  # stream-dependent
    np.testing.assert_array_equal(f_a, f_a2)  # reproducible
    np.testing.assert_array_equal(
        f_det, ffn.apply(fp, x, True)
    )  # deterministic is a no-op path

    attn = T5Attention(cfg)
    ap = attn.init(jax.random.key(0), x)
    a_det = attn.apply(ap, x)
    a_a = attn.apply(
        ap, x, deterministic=False, rngs={"dropout": jax.random.key(1)}
    )
    a_b = attn.apply(
        ap, x, deterministic=False, rngs={"dropout": jax.random.key(2)}
    )
    assert not np.allclose(a_det, a_a)  # weight dropout fires
    assert not np.allclose(a_a, a_b)
