"""Data layer tests: sampler determinism/coverage, loader prefetch, datasets."""

import numpy as np
import pytest
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.data import (
    ArrayDataset,
    DataLoader,
    DistributedSampler,
    GlobalBatchSampler,
    SyntheticImageDataset,
    SyntheticTextDataset,
    load_cifar10,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh


class TestDistributedSampler:
    def test_partition_coverage_no_overlap(self):
        world = 4
        samplers = [
            DistributedSampler(103, num_replicas=world, rank=r, seed=7)
            for r in range(world)
        ]
        shards = [list(s) for s in samplers]
        assert all(len(sh) == samplers[0].num_samples for sh in shards)
        # union covers the dataset (with padding duplicates allowed)
        union = set().union(*[set(sh) for sh in shards])
        assert union == set(range(103))

    def test_deterministic_per_epoch(self):
        a = DistributedSampler(50, num_replicas=2, rank=0, seed=3)
        b = DistributedSampler(50, num_replicas=2, rank=0, seed=3)
        assert list(a) == list(b)
        a.set_epoch(1)
        assert list(a) != list(b)  # epoch changes order

    def test_drop_last(self):
        s = DistributedSampler(103, num_replicas=4, rank=0, drop_last=True)
        assert len(s) == 25
        assert len(list(s)) == 25

    def test_bad_rank_raises(self):
        with pytest.raises(ValueError):
            DistributedSampler(10, num_replicas=2, rank=5)

    def test_drop_last_tiny_dataset_equal_counts(self):
        # len < replicas with drop_last: every rank gets 0 — unequal counts
        # would desync lockstep multi-host feeding
        counts = {
            r: len(list(DistributedSampler(3, num_replicas=4, rank=r, drop_last=True)))
            for r in range(4)
        }
        assert set(counts.values()) == {0}


class TestGlobalBatchSampler:
    def test_static_batch_shapes(self):
        s = GlobalBatchSampler(103, 16, drop_last=False, shuffle=False)
        batches = list(s)
        assert all(len(b) == 16 for b in batches)
        assert len(batches) == len(s) == 7

    def test_drop_last_counts(self):
        s = GlobalBatchSampler(103, 16, drop_last=True)
        assert len(list(s)) == len(s) == 6

    def test_tail_pad_dataset_smaller_than_batch(self):
        s = GlobalBatchSampler(10, 32, drop_last=False, shuffle=False)
        batches = list(s)
        assert len(batches) == 1
        assert len(batches[0]) == 32  # static shape even when len < batch

    def test_epoch_reshuffle_deterministic(self):
        s = GlobalBatchSampler(64, 8, seed=1)
        e0 = np.concatenate(list(s))
        s.set_epoch(1)
        e1 = np.concatenate(list(s))
        assert not np.array_equal(e0, e1)
        s.set_epoch(0)
        np.testing.assert_array_equal(np.concatenate(list(s)), e0)
        # every epoch is a permutation
        np.testing.assert_array_equal(np.sort(e1), np.arange(64))


class TestDatasets:
    def test_array_dataset(self):
        ds = ArrayDataset(x=np.arange(10), y=np.arange(10) * 2)
        assert len(ds) == 10
        assert ds[3]["y"] == 6
        batch = ds[np.array([1, 2])]
        np.testing.assert_array_equal(batch["x"], [1, 2])

    def test_array_dataset_mismatch_raises(self):
        with pytest.raises(ValueError):
            ArrayDataset(x=np.arange(10), y=np.arange(5))

    def test_synthetic_images_deterministic(self):
        ds = SyntheticImageDataset(n=100, seed=1)
        a, b = ds[42], ds[42]
        np.testing.assert_array_equal(a["image"], b["image"])
        assert ds[0]["image"].shape == (32, 32, 3)
        assert 0 <= int(ds[0]["label"]) < 10
        from pytorch_distributed_tpu.data.loader import _default_fetch

        batch = _default_fetch(ds, np.arange(4))
        assert batch["image"].shape == (4, 32, 32, 3)

    def test_synthetic_text(self):
        ds = SyntheticTextDataset(n=10, seq_len=16, vocab_size=100, num_classes=2)
        item = ds[0]
        assert item["input_ids"].shape == (16,)
        assert item["input_ids"].max() < 100
        assert "label" in item

    def test_cifar10_missing_returns_none(self, tmp_path):
        assert load_cifar10(str(tmp_path)) is None


class TestDataLoader:
    def test_host_batches(self):
        ds = SyntheticImageDataset(n=64, seed=0)
        dl = DataLoader(ds, batch_size=16, shuffle=False)
        batches = list(dl)
        assert len(batches) == 4
        assert batches[0]["image"].shape == (16, 32, 32, 3)

    def test_sharded_batches_on_mesh(self):
        mesh = make_mesh(MeshSpec(dp=4, fsdp=2, tp=1))
        strategy = DataParallel(mesh)
        ds = SyntheticImageDataset(n=64, seed=0)
        dl = DataLoader(ds, batch_size=16, sharding=strategy.batch_sharding())
        batch = next(iter(dl))
        assert batch["image"].sharding.spec == P(("dp", "fsdp"))
        assert batch["image"].shape == (16, 32, 32, 3)

    def test_worker_error_propagates(self):
        class Bad:
            def __len__(self):
                return 8

            def __getitem__(self, i):
                raise RuntimeError("boom")

        dl = DataLoader(Bad(), batch_size=4)
        with pytest.raises(RuntimeError, match="boom"):
            list(dl)

    def test_early_exit_cleans_up(self):
        ds = SyntheticImageDataset(n=256, seed=0)
        dl = DataLoader(ds, batch_size=8, prefetch=2)
        it = iter(dl)
        next(it)
        it.close()  # generator close must not hang

    def test_transform_applied(self):
        ds = ArrayDataset(x=np.arange(8, dtype=np.float32))
        dl = DataLoader(
            ds, batch_size=4, shuffle=False,
            transform=lambda b: {"x": b["x"] * 2},
        )
        np.testing.assert_array_equal(next(iter(dl))["x"], [0, 2, 4, 6])

    def test_loader_epoch_determinism(self):
        ds = ArrayDataset(x=np.arange(32))
        dl = DataLoader(ds, batch_size=8, seed=5)
        e0 = [b["x"].copy() for b in dl]
        dl.set_epoch(0)
        e0_again = [b["x"].copy() for b in dl]
        for a, b in zip(e0, e0_again):
            np.testing.assert_array_equal(a, b)


class TestDatasetUtilities:
    def test_subset_view_and_fancy_index(self):
        from pytorch_distributed_tpu.data import Subset

        ds = ArrayDataset(x=np.arange(10, dtype=np.float32))
        sub = Subset(ds, [7, 2, 5])
        assert len(sub) == 3
        assert sub[0]["x"] == 7.0 and sub[2]["x"] == 5.0
        np.testing.assert_array_equal(sub[[0, 2]]["x"], [7.0, 5.0])
        import pytest

        with pytest.raises(IndexError):
            Subset(ds, [10])

    def test_concat_chains_and_locates(self):
        from pytorch_distributed_tpu.data import ConcatDataset

        a = ArrayDataset(x=np.arange(4, dtype=np.float32))
        b = ArrayDataset(x=np.arange(100, 103, dtype=np.float32))
        cat = ConcatDataset([a, b])
        assert len(cat) == 7
        assert cat[3]["x"] == 3.0
        assert cat[4]["x"] == 100.0
        assert cat[-1]["x"] == 102.0
        # fancy indexing crosses the source boundary and yields a stacked
        # batch dict (the DataLoader fetch contract), not a list
        got = cat[[3, 4, 6]]
        np.testing.assert_array_equal(got["x"], [3.0, 100.0, 102.0])
        dl = DataLoader(cat, batch_size=4, shuffle=False, drop_last=False)
        batches = list(dl)
        # the sampler pads the tail batch (lockstep contract), so every
        # source element appears and batch shapes stay uniform
        assert [len(b["x"]) for b in batches] == [4, 4]
        seen = set(np.concatenate([b["x"] for b in batches]).tolist())
        assert seen == {0.0, 1.0, 2.0, 3.0, 100.0, 101.0, 102.0}
        import pytest

        with pytest.raises(IndexError):
            cat[7]

    def test_random_split_disjoint_and_loadable(self):
        from pytorch_distributed_tpu.data import random_split

        ds = ArrayDataset(x=np.arange(20, dtype=np.float32))
        tr, va = random_split(ds, [15, 5], seed=3)
        assert len(tr) == 15 and len(va) == 5
        seen = sorted(
            float(tr[i]["x"]) for i in range(15)
        ) + sorted(float(va[i]["x"]) for i in range(5))
        assert sorted(seen) == list(np.arange(20.0))
        # fractional spec with rounding remainder to the first split
        tr, va = random_split(ds, [0.7, 0.3], seed=3)
        assert len(tr) == 14 and len(va) == 6
        # splits feed the DataLoader like any dataset
        dl = DataLoader(va, batch_size=3, shuffle=False, drop_last=False)
        got = np.concatenate([b["x"] for b in dl])
        assert len(got) == 6

    def test_random_split_fractional_remainder_matches_torch(self):
        """Rounding remainder is distributed round-robin like torch
        (ADVICE r2: first-split-takes-all gave 9/7/7 where torch gives
        8/8/7)."""
        import torch
        from pytorch_distributed_tpu.data import random_split

        for n, fracs in [(23, [1 / 3, 1 / 3, 1 / 3]), (10, [0.55, 0.45]),
                         (17, [0.25, 0.25, 0.25, 0.25])]:
            ds = ArrayDataset(x=np.arange(n, dtype=np.float32))
            ours = [len(s) for s in random_split(ds, fracs, seed=0)]
            theirs = [
                len(s) for s in torch.utils.data.random_split(range(n), fracs)
            ]
            assert ours == theirs, (n, fracs, ours, theirs)
        # fractions that floor to a total ABOVE n (sum = 1 + ~5e-7, inside
        # the 1e-6 tolerance) must still yield valid splits, not raise
        ds = ArrayDataset(x=np.zeros(10_000_000, dtype=np.float32))
        parts = random_split(ds, [0.3 + 5e-7, 0.7 + 5e-7])
        assert sum(len(p) for p in parts) == 10_000_000

    def test_random_split_bad_lengths(self):
        import pytest

        from pytorch_distributed_tpu.data import random_split

        ds = ArrayDataset(x=np.arange(10, dtype=np.float32))
        with pytest.raises(ValueError):
            random_split(ds, [4, 4])


class TestIterableDataset:
    class Stream(object):
        """Yields n dict samples; counts epochs via set_epoch."""

        def __init__(self, n):
            self.n = n
            self.epoch = 0

        def set_epoch(self, epoch):
            self.epoch = epoch

        def __iter__(self):
            base = self.epoch * 1000
            for i in range(self.n):
                yield {"x": np.float32(base + i), "y": np.int32(i % 3)}

    def test_stream_batches_and_tail(self):
        from pytorch_distributed_tpu.data import DataLoader

        dl = DataLoader(self.Stream(10), 4, drop_last=False, shard=False)
        batches = list(dl)
        assert [len(b["x"]) for b in batches] == [4, 4, 2]
        assert [float(v) for v in batches[0]["x"]] == [0, 1, 2, 3]
        dl2 = DataLoader(self.Stream(10), 4, drop_last=True, shard=False)
        assert [len(b["x"]) for b in list(dl2)] == [4, 4]

    def test_no_len_and_set_epoch_forwarded(self):
        import pytest

        from pytorch_distributed_tpu.data import DataLoader

        ds = self.Stream(8)
        dl = DataLoader(ds, 4, shard=False)
        with pytest.raises(TypeError):
            len(dl)
        dl.set_epoch(3)
        assert ds.epoch == 3
        batches = list(dl)
        assert float(batches[0]["x"][0]) == 3000.0  # epoch reshuffle seen

    def test_sampler_and_fetch_rejected(self):
        import pytest

        from pytorch_distributed_tpu.data import DataLoader, GlobalBatchSampler

        with pytest.raises(ValueError, match="sampler"):
            DataLoader(
                self.Stream(8), 4,
                sampler=GlobalBatchSampler(8, 4),
            )
        with pytest.raises(ValueError, match="fetch"):
            DataLoader(self.Stream(8), 4, fetch=lambda d, i: None)

    def test_shuffle_and_one_shot_iterators_rejected(self):
        import pytest

        from pytorch_distributed_tpu.data import DataLoader

        with pytest.raises(ValueError, match="shuffle"):
            DataLoader(self.Stream(8), 4, shuffle=True)
        gen = ({"x": np.float32(i)} for i in range(8))
        with pytest.raises(ValueError, match="re-iterable"):
            DataLoader(gen, 4)

    def test_streamed_batches_place_on_mesh(self, mesh8):
        from jax.sharding import NamedSharding, PartitionSpec as P

        from pytorch_distributed_tpu.data import DataLoader

        sharding = NamedSharding(mesh8, P(("dp", "fsdp", "tp")))
        dl = DataLoader(self.Stream(16), 8, sharding=sharding)
        batches = list(dl)
        assert len(batches) == 2
        assert batches[0]["x"].sharding.is_equivalent_to(sharding, 1)
        np.testing.assert_array_equal(
            np.asarray(batches[0]["x"]), np.arange(8, dtype=np.float32)
        )

    def test_base_class_is_abstract(self):
        import pytest

        from pytorch_distributed_tpu.data import IterableDataset

        with pytest.raises(NotImplementedError):
            iter(IterableDataset()).__next__()


class TestPacking:
    def test_pack_documents_invariants(self):
        from pytorch_distributed_tpu.data import pack_documents

        docs = [[1, 2, 3], [4, 5, 6, 7, 8], [9], [10, 11, 12, 13]]
        out = pack_documents(docs, 8, pad_id=0)
        ids, seg, pos = (
            out["input_ids"], out["segment_ids"], out["positions"]
        )
        assert ids.shape == seg.shape == pos.shape
        assert ids.shape[1] == 8
        # every token survives, in order, under its own segment
        recovered = []
        for r in range(ids.shape[0]):
            for s in range(1, seg[r].max() + 1):
                recovered.append(list(ids[r][seg[r] == s]))
        assert sorted(map(tuple, recovered)) == sorted(
            map(tuple, docs)
        )
        # positions restart per document
        for r in range(ids.shape[0]):
            for s in range(1, seg[r].max() + 1):
                p = pos[r][seg[r] == s]
                assert list(p) == list(range(len(p)))
        # padding is segment 0 / pad_id
        assert np.all(ids[seg == 0] == 0)

    def test_pack_long_document_splits(self):
        from pytorch_distributed_tpu.data import pack_documents

        out = pack_documents([list(range(1, 20))], 8)
        seg = out["segment_ids"]
        # 19 tokens -> pieces of 8, 8, 3; all tokens kept
        total = int((seg != 0).sum())
        assert total == 19

    def test_packed_loss_mask(self):
        from pytorch_distributed_tpu.data import (
            pack_documents,
            packed_loss_mask,
        )

        out = pack_documents([[1, 2, 3], [4, 5]], 8)
        m = packed_loss_mask(out["segment_ids"])
        seg = out["segment_ids"][0]
        # boundary (seg 1 -> seg 2) and pad targets are masked out
        for t in range(7):
            expect = seg[t + 1] == seg[t] and seg[t + 1] != 0
            assert m[0, t] == expect, (t, seg)


class TestWeightedRandomSampler:
    def test_zero_weight_never_drawn_heavy_dominates(self):
        from pytorch_distributed_tpu.data import WeightedRandomSampler

        w = np.array([0.0, 1.0, 8.0, 1.0])
        s = WeightedRandomSampler(w, num_samples=400, batch_size=40, seed=1)
        idx = np.concatenate(list(s))
        assert len(idx) == 400
        counts = np.bincount(idx, minlength=4)
        assert counts[0] == 0
        assert counts[2] > counts[1] and counts[2] > counts[3]
        assert counts[2] > 200  # ~80% expected mass

    def test_epoch_seeded_determinism(self):
        from pytorch_distributed_tpu.data import WeightedRandomSampler

        s = WeightedRandomSampler(
            np.ones(16), num_samples=32, batch_size=8, seed=5
        )
        e0 = [b.copy() for b in s]
        s.set_epoch(0)
        again = [b.copy() for b in s]
        for a, b in zip(e0, again):
            np.testing.assert_array_equal(a, b)
        s.set_epoch(1)
        assert any(
            not np.array_equal(a, b) for a, b in zip(e0, list(s))
        )

    def test_without_replacement_and_validation(self):
        import pytest

        from pytorch_distributed_tpu.data import WeightedRandomSampler

        s = WeightedRandomSampler(
            np.ones(10), num_samples=10, batch_size=5, replacement=False,
        )
        idx = np.concatenate(list(s))
        assert sorted(idx.tolist()) == list(range(10))
        with pytest.raises(ValueError):
            WeightedRandomSampler(np.ones(4), 8, 4, replacement=False)
        with pytest.raises(ValueError):
            WeightedRandomSampler(np.zeros(4), 2, 2)

    def test_feeds_dataloader(self):
        from pytorch_distributed_tpu.data import WeightedRandomSampler

        ds = ArrayDataset(x=np.arange(10, dtype=np.float32))
        s = WeightedRandomSampler(
            np.r_[np.zeros(5), np.ones(5)], num_samples=12, batch_size=4,
        )
        dl = DataLoader(ds, 4, sampler=s)
        got = np.concatenate([b["x"] for b in dl])
        assert len(got) == 12 and got.min() >= 5.0


class TestShuffleBuffer:
    def _stream(self, n=50):
        from pytorch_distributed_tpu.data import IterableDataset

        class S(IterableDataset):
            def __iter__(self):
                yield from ({"x": np.int32(i)} for i in range(n))

        return S()

    def test_same_multiset_different_order(self):
        from pytorch_distributed_tpu.data import ShuffleBuffer

        sb = ShuffleBuffer(self._stream(), buffer_size=16, seed=3)
        got = [int(s["x"]) for s in sb]
        assert sorted(got) == list(range(50))  # nothing lost or repeated
        assert got != list(range(50))  # actually shuffled

    def test_deterministic_per_seed_and_epoch(self):
        from pytorch_distributed_tpu.data import ShuffleBuffer

        sb = ShuffleBuffer(self._stream(), buffer_size=8, seed=7)
        a = [int(s["x"]) for s in sb]
        b = [int(s["x"]) for s in sb]  # same (seed, epoch): identical
        assert a == b
        sb.set_epoch(1)
        c = [int(s["x"]) for s in sb]
        assert sorted(c) == sorted(a) and c != a  # epoch reshuffles

    def test_loader_integration(self):
        from pytorch_distributed_tpu.data import DataLoader, ShuffleBuffer

        sb = ShuffleBuffer(self._stream(48), buffer_size=16, seed=0)
        loader = DataLoader(sb, 8)
        seen = []
        for batch in loader:
            assert batch["x"].shape == (8,)
            seen.extend(np.asarray(batch["x"]).tolist())
        assert sorted(seen) == list(range(48))


class TestCollateFn:
    def test_map_style_custom_collate(self):
        from pytorch_distributed_tpu.data import DataLoader

        class VarLen:
            lengths = [2, 4, 3, 5, 1, 2, 4, 3]

            def __len__(self):
                return len(self.lengths)

            def __getitem__(self, i):
                return np.arange(self.lengths[i], dtype=np.int32)

        def pad_collate(samples):
            width = max(len(s) for s in samples)
            out = np.zeros((len(samples), width), np.int32)
            mask = np.zeros((len(samples), width), bool)
            for j, s in enumerate(samples):
                out[j, : len(s)] = s
                mask[j, : len(s)] = True
            return {"tokens": out, "mask": mask}

        loader = DataLoader(
            VarLen(), 4, shuffle=False, collate_fn=pad_collate
        )
        batches = list(loader)
        assert len(batches) == 2
        assert batches[0]["tokens"].shape[0] == 4
        # first batch holds lengths 2,4,3,5 -> padded to 5
        assert batches[0]["tokens"].shape[1] == 5
        assert batches[0]["mask"].sum() == 2 + 4 + 3 + 5

    def test_stream_collate(self):
        from pytorch_distributed_tpu.data import DataLoader, IterableDataset

        class S(IterableDataset):
            def __iter__(self):
                for i in range(8):
                    yield [i] * (i % 3 + 1)  # ragged python lists

        def pad(samples):
            w = max(len(s) for s in samples)
            return np.asarray(
                [s + [0] * (w - len(s)) for s in samples], np.int32
            )

        loader = DataLoader(S(), 4, collate_fn=pad)
        batches = list(loader)
        assert len(batches) == 2
        assert all(b.shape[0] == 4 for b in batches)

    def test_collate_and_fetch_exclusive(self):
        from pytorch_distributed_tpu.data import ArrayDataset, DataLoader

        ds = ArrayDataset(x=np.zeros((8, 2), np.float32))
        with pytest.raises(ValueError, match="own batch assembly"):
            DataLoader(ds, 4, collate_fn=lambda s: s,
                       fetch=lambda d, i: d[i])


class TestSamplerCursors:
    """state_dict()/load_state_dict() (epoch + intra-epoch offset): resume
    and elastic resize replay from the exact batch, not the epoch
    boundary — and the global order is reconstructible at ANY world size,
    which is what makes the resize replay well-defined at all."""

    def test_global_batch_cursor_resumes_exact_batch(self):
        full = [list(b) for b in GlobalBatchSampler(96, 8, seed=5)]
        s = GlobalBatchSampler(96, 8, seed=5)
        it = iter(s)
        consumed = [list(next(it)) for _ in range(5)]
        cursor = s.state_dict()
        assert cursor == {"epoch": 0, "offset": 5}
        fresh = GlobalBatchSampler(96, 8, seed=5)
        fresh.load_state_dict(cursor)
        rest = [list(b) for b in fresh]
        assert consumed + rest == full
        # the one-shot skip does not leak: the NEXT iteration is a full
        # epoch again (existing determinism contracts hold)
        assert [list(b) for b in fresh] == full

    def test_cursor_resets_on_set_epoch(self):
        s = GlobalBatchSampler(64, 8, seed=1)
        it = iter(s)
        next(it)
        assert s.state_dict()["offset"] == 1
        s.set_epoch(1)
        assert s.state_dict() == {"epoch": 1, "offset": 0}

    def test_cursor_mid_second_epoch(self):
        s = GlobalBatchSampler(64, 8, seed=2)
        s.set_epoch(3)
        it = iter(s)
        next(it), next(it), next(it)
        cur = s.state_dict()
        assert cur == {"epoch": 3, "offset": 3}
        t = GlobalBatchSampler(64, 8, seed=2)
        t.load_state_dict(cur)
        ref = GlobalBatchSampler(64, 8, seed=2)
        ref.set_epoch(3)
        ref_batches = [list(b) for b in ref]
        assert [list(b) for b in t] == ref_batches[3:]

    def test_bad_cursor_offset_rejected(self):
        s = GlobalBatchSampler(64, 8)
        with pytest.raises(ValueError):
            s.load_state_dict({"epoch": 0, "offset": -1})

    def test_distributed_cursor_counts_samples(self):
        s = DistributedSampler(60, num_replicas=2, rank=1, seed=4)
        full = list(s)
        it = iter(s)
        first = [next(it) for _ in range(7)]
        cur = s.state_dict()
        assert cur["offset"] == 7
        t = DistributedSampler(60, num_replicas=2, rank=1, seed=4)
        t.load_state_dict(cur)
        assert first + list(t) == full

    def test_weighted_cursor_resumes_exact_batch(self):
        from pytorch_distributed_tpu.data import WeightedRandomSampler

        kw = dict(num_samples=80, batch_size=8, seed=9)
        w = np.ones(40)
        full = [list(b) for b in WeightedRandomSampler(w, **kw)]
        s = WeightedRandomSampler(w, **kw)
        it = iter(s)
        head = [list(next(it)) for _ in range(4)]
        t = WeightedRandomSampler(w, **kw)
        t.load_state_dict(s.state_dict())
        assert head + [list(b) for b in t] == full

    def test_cross_world_size_replay_equivalence(self):
        """The resize-replay precondition: at ANY world size the ranks'
        strided streams interleave back to the SAME global order, and a
        cursor taken at world w replays the identical global stream when
        reloaded at world w' — the data a resized world consumes is the
        data the unresized reference consumed."""
        n, seed = 120, 11
        reference = list(
            DistributedSampler(n, num_replicas=1, rank=0, seed=seed)
        )
        for world in (2, 3, 4):
            shards = [
                list(DistributedSampler(
                    n, num_replicas=world, rank=r, seed=seed
                ))
                for r in range(world)
            ]
            merged = []
            for i in range(sum(len(sh) for sh in shards)):
                merged.append(shards[i % world][i // world])
            assert merged[:n] == reference[:n], world
        # GlobalBatchSampler's stream is world-independent by
        # construction; a cursor taken after k batches replays batch k
        # onward regardless of how many ranks will split each batch
        g = GlobalBatchSampler(n, 12, seed=seed)
        it = iter(g)
        for _ in range(4):
            next(it)
        cur = g.state_dict()
        for _world in (2, 3):  # any later split sees the same stream
            t = GlobalBatchSampler(n, 12, seed=seed)
            t.load_state_dict(dict(cur))
            first = next(iter(t))
            ref = [list(b) for b in GlobalBatchSampler(n, 12, seed=seed)]
            assert list(first) == ref[4]
