"""Auto-parallel planner tests (marker: plan).

Covers the four planner layers plus their contracts: the shape-aware
rule engine (the generalized gemma/qwen2 kv-head fallback), eval-shape
memory accounting, cost-model pricing (synthetic recovery against
hand-computed prices, q8 wire occupancy), ranking determinism, the
plan.json schema, the no-compile guarantee, the cost-model failure UX
(actionable error naming the calibration command, analytic fallback
flagged uncalibrated) and ``--strategy auto`` end to end in a
subprocess on the 8-device CPU mesh.
"""

import contextlib
import dataclasses
import json
import logging
import os
import subprocess
import sys

import jax
import jax.numpy as jnp
import optax
import pytest

import flax.linen as nn

from pytorch_distributed_tpu import autoplan
from pytorch_distributed_tpu.autoplan import rules as ap_rules
from pytorch_distributed_tpu.autoplan.memory import PlanMesh
from pytorch_distributed_tpu.autoplan.pricing import (
    grad_comm_terms,
    price_comm_terms,
)
from pytorch_distributed_tpu.parallel.sharding import PartitionRules
from pytorch_distributed_tpu.runtime import costmodel
from pytorch_distributed_tpu.runtime.hostring import (
    algo_wire_bytes,
    q8_wire_payload,
)
from pytorch_distributed_tpu.train import TrainState
from jax.sharding import PartitionSpec as P

pytestmark = pytest.mark.plan

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@contextlib.contextmanager
def ptd_caplog(caplog, level="WARNING"):
    """Package loggers don't propagate to root; attach caplog directly."""
    ns = logging.getLogger("pytorch_distributed_tpu")
    ns.addHandler(caplog.handler)
    try:
        with caplog.at_level(level, logger="pytorch_distributed_tpu"):
            yield caplog
    finally:
        ns.removeHandler(caplog.handler)


# -- fixtures ---------------------------------------------------------------
class _Tiny(nn.Module):
    @nn.compact
    def __call__(self, x):
        x = nn.Dense(64, name="d1")(x)
        return nn.Dense(8, name="d2")(x)


@pytest.fixture(scope="module")
def abstract_state():
    model = _Tiny()

    def make(key):
        params = model.init(key, jnp.zeros((1, 16)))["params"]
        return TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
        )

    return jax.eval_shape(make, jax.random.key(0))


def hand_model(ar_beta, rsag_beta, *, alpha=0.0, worlds=(2, 4, 8),
               p2p_beta=None):
    """Hand-built α–β model: prices are exactly computable on paper.
    ``p2p_beta`` adds world-2 send/recv fits (the pp handoff links)."""
    fits = {}
    ops = [
        ("all_reduce", ar_beta),
        ("all_reduce_q8", ar_beta),
        ("reduce_scatter", rsag_beta),
        ("all_gather", rsag_beta),
    ]
    for op, beta in ops:
        for w in worlds:
            fits[(op, w)] = costmodel.OpFit(
                op=op, world_size=w, alpha_s=alpha,
                beta_s_per_byte=beta, r2=1.0, n_samples=4,
                wire_bytes_min=0, wire_bytes_max=1 << 62,
            )
    if p2p_beta is not None:
        for op in ("send", "recv"):
            fits[(op, 2)] = costmodel.OpFit(
                op=op, world_size=2, alpha_s=alpha,
                beta_s_per_byte=p2p_beta, r2=1.0, n_samples=4,
                wire_bytes_min=0, wire_bytes_max=1 << 62,
            )
    return costmodel.CostModel("test", fits)


NO_COMPUTE = autoplan.ModelProfile(
    flops_per_sample=0.0, activation_bytes_per_sample=0.0
)
MEASURED = autoplan.ComputeModel(1e9, "measured-step")


def run_plan(abstract_state, model, **kw):
    kw.setdefault("strategies", ("dp", "zero1"))
    kw.setdefault("max_tp", 1)
    kw.setdefault("n_devices", 8)
    kw.setdefault("budget_bytes", None)
    return autoplan.plan(
        profile=NO_COMPUTE, global_batch=8,
        abstract_state=abstract_state, cost_model=model,
        compute=MEASURED, **kw,
    )


# -- rule engine ------------------------------------------------------------
class TestRuleEngine:
    def test_divisibility_fallback_replicates_and_warns_once(self, caplog):
        ap_rules.reset_warned()
        rules = PartitionRules(ap_rules.engine_rules([
            ap_rules.TensorRule(r"w/kernel", (None, "tp", None),
                                note="test axis"),
        ]))
        mesh = PlanMesh({"tp": 8})
        with ptd_caplog(caplog):
            # 4 does not divide tp=8 -> that dim replicates
            assert rules.spec_for("w/kernel", (64, 4, 16), mesh) == \
                P(None, None, None)
            # warned exactly once for repeated identical shapes
            assert rules.spec_for("w/kernel", (64, 4, 16), mesh) == \
                P(None, None, None)
        warns = [r for r in caplog.records if "replicating" in r.message]
        assert len(warns) == 1
        assert "test axis" in warns[0].message

    def test_stacked_prepends_exactly_one_layer_dim(self):
        rules = PartitionRules(ap_rules.engine_rules([
            ap_rules.TensorRule(r"w", (None, "tp", None)),
        ]))
        mesh = PlanMesh({"tp": 2})
        # +1 rank: scan layer dim prepended
        assert rules.spec_for("w", (3, 64, 4, 16), mesh) == \
            P(None, None, "tp", None)
        # exact rank: applied as-is
        assert rules.spec_for("w", (64, 4, 16), mesh) == \
            P(None, "tp", None)

    def test_size_one_axes_stay_in_spec(self):
        # axes of size 1 are kept (they exist in every mesh; XLA elides
        # the no-op) — matches the old stacked() passthrough exactly
        rules = PartitionRules(ap_rules.engine_rules([
            ap_rules.TensorRule(r"w", (None, "tp")),
        ]))
        assert rules.spec_for("w", (8, 4), PlanMesh({"tp": 1})) == \
            P(None, "tp")

    def test_gpt2_rules_ride_the_engine(self):
        from pytorch_distributed_tpu.models.gpt2 import (
            gpt2_partition_rules,
        )

        rules = PartitionRules(gpt2_partition_rules())
        mesh = PlanMesh({"tp": 2, "ep": 1})
        # scan-stacked qkv kernel [L, hidden, 3, heads, hd]
        assert rules.spec_for(
            "layers/attn_qkv/kernel", (2, 64, 3, 4, 16), mesh
        ) == P(None, None, None, "tp", None)
        # embedding is never stacked
        assert rules.spec_for("wte/embedding", (512, 64), mesh) == \
            P(None, "tp")

    def test_max_divisible_tp(self):
        assert ap_rules.max_divisible_tp([12], 8) == [1, 2, 4]
        assert ap_rules.max_divisible_tp([], 4) == [1, 2, 4]
        assert ap_rules.max_divisible_tp([5], 8) == [1]


# -- candidates -------------------------------------------------------------
class TestCandidates:
    def test_enumeration_deterministic_and_deduped(self):
        a = autoplan.enumerate_candidates(8)
        b = autoplan.enumerate_candidates(8)
        assert [c.name for c in a] == [c.name for c in b]
        names = [c.name for c in a]
        assert len(names) == len(set(names))
        # data==1 (pure tp or single device) collapses to the dp form
        assert not any(
            c.data == 1 and c.strategy != "dp" for c in a
        )

    def test_mesh_spec_matches_axes(self):
        c = autoplan.CandidateSpec("fsdp", 4, tp=2)
        spec = c.mesh_spec()
        assert (spec.fsdp, spec.dp, spec.tp) == (4, 1, 2)
        assert c.name == "fsdp/dp4xtp2"
        assert c.n_devices == 8

    def test_q8_variants_only_for_dp(self):
        cands = autoplan.enumerate_candidates(8, include_q8=True)
        q8 = [c for c in cands if c.compress]
        assert q8 and all(c.strategy == "dp" for c in q8)


# -- memory accounting ------------------------------------------------------
class TestMemory:
    def test_leaf_device_bytes(self):
        from pytorch_distributed_tpu.autoplan.memory import (
            leaf_device_bytes,
        )

        sizes = {"dp": 4, "tp": 2}
        assert leaf_device_bytes((64, 8), 4, P("dp", None), sizes) == \
            64 * 8 * 4 // 4
        assert leaf_device_bytes((64, 8), 4, P(("dp", "tp"), None),
                                 sizes) == 64 * 8 * 4 // 8
        # non-divisible dim conservatively counts full size
        assert leaf_device_bytes((6, 8), 4, P("dp", None), sizes) == \
            6 * 8 * 4

    def test_strategy_accounting_relationships(self, abstract_state):
        m = hand_model(1e-9, 1e-9)
        plan = run_plan(abstract_state, m,
                        strategies=("dp", "zero1", "fsdp"))
        by = {c.name: c for c in plan.candidates}
        dp, z1, fs = by["dp/dp8"], by["zero1/dp8"], by["fsdp/dp8"]
        # dp replicates everything; zero1 shards only optimizer state;
        # fsdp shards params and optimizer state
        assert dp.memory.param_bytes == z1.memory.param_bytes
        assert z1.memory.opt_bytes < dp.memory.opt_bytes
        assert fs.memory.param_bytes < dp.memory.param_bytes
        assert fs.memory.opt_bytes <= z1.memory.opt_bytes
        # grads mirror the params placement
        assert dp.memory.grad_bytes == dp.memory.param_bytes
        assert fs.memory.grad_bytes == fs.memory.param_bytes

    def test_infeasible_filtered_but_reported(self, abstract_state):
        m = hand_model(1e-9, 1e-9)
        free = run_plan(abstract_state, m, strategies=("dp", "zero1"))
        by = {c.name: c for c in free.candidates}
        # budget between the two candidates' needs
        budget = (by["zero1/dp8"].memory.total_bytes
                  + by["dp/dp8"].memory.total_bytes) // 2
        assert by["zero1/dp8"].memory.total_bytes < budget \
            < by["dp/dp8"].memory.total_bytes
        plan = run_plan(abstract_state, m, strategies=("dp", "zero1"),
                        budget_bytes=budget)
        assert plan.best().name == "zero1/dp8"
        dp = next(c for c in plan.candidates if c.name == "dp/dp8")
        assert not dp.feasible and "budget" in dp.reason
        assert dp.rank is None
        # the infeasible candidate still carries its full breakdown
        assert dp.memory.total_bytes > 0 and dp.comm_seconds > 0

    def test_no_feasible_candidate_raises_actionably(self, abstract_state):
        plan = run_plan(abstract_state, hand_model(1e-9, 1e-9),
                        budget_bytes=16)
        with pytest.raises(autoplan.PlanError, match="no feasible"):
            plan.best()

    def test_batch_indivisible_is_infeasible(self, abstract_state):
        plan = autoplan.plan(
            profile=NO_COMPUTE, global_batch=6,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9), compute=MEASURED,
            strategies=("dp",), max_tp=1, n_devices=4,
            budget_bytes=None,
        )
        dp4 = next(c for c in plan.candidates if c.name == "dp/dp4")
        assert not dp4.feasible and "batch" in dp4.reason
        # the all-rejected error names the REAL reason, not a budget
        with pytest.raises(autoplan.PlanError) as ei:
            plan.best()
        assert "batch" in str(ei.value)
        assert "budget" not in str(ei.value)


# -- pricing ----------------------------------------------------------------
class TestPricing:
    def test_synthetic_recovery_picks_hand_computed_cheapest(
        self, abstract_state
    ):
        # expensive all_reduce, cheap reduce_scatter/all_gather:
        # zero1's two cheap collectives beat dp's one expensive one
        m = hand_model(ar_beta=10e-9, rsag_beta=1e-9)
        plan = run_plan(abstract_state, m)
        assert plan.best().name == "zero1/dp8"
        # and the winner's price IS the hand-computed prediction
        z1 = plan.best()
        payload = z1.memory.params_global_bytes
        want = (
            m.predict("reduce_scatter", payload, 8).seconds
            + m.predict("all_gather", payload, 8).seconds
        )
        assert z1.comm_seconds == pytest.approx(want, rel=1e-9)
        # flipped betas flip the choice
        plan2 = run_plan(abstract_state,
                         hand_model(ar_beta=1e-9, rsag_beta=10e-9))
        assert plan2.best().name == "dp/dp8"

    def test_alpha_breaks_equal_volume_ties(self, abstract_state):
        # equal betas: dp (1 call) and zero1 (2 calls) move the same
        # wire bytes; a per-call alpha must rank dp first
        plan = run_plan(abstract_state,
                        hand_model(1e-9, 1e-9, alpha=1e-3))
        assert plan.best().name == "dp/dp8"

    def test_q8_wire_occupancy_priced(self):
        # gradient-sized payload: q8 moves <= 0.3x the f32 wire bytes
        # (the EQuARX-direction number the comms phase pins end to end)
        m = hand_model(1e-9, 1e-9)
        elems = 6_400_000
        f32 = price_comm_terms(
            grad_comm_terms("dp", elems * 4, elems, 8), m
        )
        q8 = price_comm_terms(
            grad_comm_terms("dp", elems * 4, elems, 8, compress="int8"),
            m,
        )
        assert q8[0].op == "all_reduce_q8"
        ratio = q8[0].wire_bytes / f32[0].wire_bytes
        assert 0.2 < ratio <= 0.3
        assert q8[0].wire_bytes == algo_wire_bytes(
            "all_reduce_q8", q8_wire_payload(elems), 8
        )

    def test_q8_fallback_to_f32_fit_is_flagged(self):
        # a model never calibrated on all_reduce_q8 prices the q8
        # payload on the all_reduce fit and says so
        fits = {
            ("all_reduce", 8): costmodel.OpFit(
                "all_reduce", 8, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            )
        }
        m = costmodel.CostModel("test", fits)
        terms = price_comm_terms(
            grad_comm_terms("dp", 4096 * 4, 4096, 8, compress="int8"), m
        )
        assert "no q8 calibration" in terms[0].note

    def test_partially_calibrated_model_degrades_per_term(
        self, abstract_state
    ):
        # collective_bench keeps later ops running when one fails, so a
        # model missing reduce_scatter is reachable: zero1 pricing must
        # degrade to the analytic fallback per term, flagged, not crash
        fits = {
            ("all_reduce", 8): costmodel.OpFit(
                "all_reduce", 8, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            ),
            ("all_gather", 8): costmodel.OpFit(
                "all_gather", 8, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            ),
        }
        plan = run_plan(abstract_state,
                        costmodel.CostModel("test", fits))
        z1 = next(c for c in plan.candidates if c.name == "zero1/dp8")
        rs = next(t for t in z1.comm_terms if t.op == "reduce_scatter")
        assert "priced analytically" in rs.note
        assert rs.extrapolated and z1.extrapolated
        # ...and with NO fallback available the error is actionable
        with pytest.raises(costmodel.CostModelUnavailable,
                           match="collective_bench"):
            price_comm_terms(
                [autoplan.CommTerm("reduce_scatter", 1000, 8, 1)],
                costmodel.CostModel("test", {}),
            )

    def test_accum_steps_shrinks_activation_memory(self, abstract_state):
        profile = autoplan.ModelProfile(
            flops_per_sample=0.0, activation_bytes_per_sample=1000.0
        )
        kw = dict(
            profile=profile, global_batch=64,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9), compute=MEASURED,
            strategies=("dp",), max_tp=1, n_devices=8,
            budget_bytes=None,
        )
        flat = autoplan.plan(**kw)
        acc = autoplan.plan(accum_steps=4, **kw)
        a = flat.best().memory.activation_bytes
        b = acc.best().memory.activation_bytes
        assert a == 8 * 1000  # 64/8 samples resident
        assert b == 2 * 1000  # one 2-sample microbatch resident

    def test_fsdp_term_structure(self):
        terms = grad_comm_terms("fsdp", 1000, 250, 4)
        assert [(t.op, t.count) for t in terms] == [
            ("all_gather", 2), ("reduce_scatter", 1)
        ]

    def test_extrapolation_flag_propagates(self, abstract_state):
        # fits exist only at world 2: pricing world 8 extrapolates
        m = hand_model(1e-9, 1e-9, worlds=(2,))
        plan = run_plan(abstract_state, m)
        assert all(c.extrapolated for c in plan.candidates)
        assert plan.to_dict()["candidates"][0]["extrapolated"] is True


# -- plan artifact ----------------------------------------------------------
class TestPlanArtifact:
    def test_ranking_deterministic(self, abstract_state):
        m = hand_model(2e-9, 1e-9)
        a = run_plan(abstract_state, m,
                     strategies=("dp", "zero1", "fsdp"),
                     tp_candidates=(1, 2, 4, 8))
        b = run_plan(abstract_state, m,
                     strategies=("dp", "zero1", "fsdp"),
                     tp_candidates=(1, 2, 4, 8))
        assert json.dumps(a.to_dict(), sort_keys=True) == \
            json.dumps(b.to_dict(), sort_keys=True)

    def test_plan_json_schema(self, abstract_state, tmp_path):
        plan = run_plan(abstract_state, hand_model(1e-9, 1e-9))
        path = plan.save(str(tmp_path / "plan.json"))
        doc = json.load(open(path))
        assert doc["format_version"] == 1
        assert set(doc) >= {
            "format_version", "generated_by", "n_devices",
            "global_batch", "budget_bytes_per_device", "cost_model",
            "compute_model", "uncalibrated", "chosen", "candidates",
        }
        assert doc["chosen"] == plan.best().name
        assert doc["uncalibrated"] is False  # hand model + measured
        for c in doc["candidates"]:
            assert set(c) >= {
                "name", "strategy", "mesh", "feasible", "rank",
                "memory", "comms", "compute_seconds", "step_seconds",
                "extrapolated",
            }
            assert set(c["memory"]) >= {
                "param_bytes", "opt_bytes", "grad_bytes",
                "activation_bytes", "total_bytes",
            }
            for t in c["comms"]["terms"]:
                assert set(t) >= {"op", "payload_bytes", "world",
                                  "count", "seconds", "wire_bytes",
                                  "extrapolated"}
        # ranked feasible candidates are price-sorted
        ranked = [c for c in doc["candidates"] if c["rank"]]
        assert ranked == sorted(ranked, key=lambda c: c["rank"])
        steps = [c["step_seconds"] for c in ranked]
        assert steps == sorted(steps)
        # losers say why they lost
        assert all(c["why_not"] for c in ranked[1:])

    def test_write_metrics_protocol(self, abstract_state, tmp_path):
        from pytorch_distributed_tpu.train.metrics import (
            MetricsWriter,
            read_metrics,
        )

        plan = run_plan(abstract_state, hand_model(1e-9, 1e-9))
        path = str(tmp_path / "m.jsonl")
        with MetricsWriter(path) as w:
            plan.write_metrics(w)
        recs = [r for r in read_metrics(path) if r["split"] == "plan"]
        cands = [r for r in recs if r["event"] == "candidate"]
        assert len(cands) == len(plan.candidates)
        assert sum(int(r["chosen"]) for r in cands) == 1
        summary = [r for r in recs if r["event"] == "plan_summary"]
        assert len(summary) == 1
        assert summary[0]["chosen"] == plan.best().name

    def test_planning_never_compiles(self, abstract_state, monkeypatch):
        def boom(*a, **k):
            raise AssertionError("planning must never call jax.jit")

        monkeypatch.setattr(jax, "jit", boom)
        plan = run_plan(abstract_state, hand_model(1e-9, 1e-9),
                        strategies=("dp", "zero1", "fsdp"),
                        tp_candidates=(1, 2, 4, 8))
        assert plan.best() is not None


# -- cost-model failure UX --------------------------------------------------
class TestCostModelFailureUX:
    def test_missing_file_names_the_calibration_command(self, tmp_path):
        with pytest.raises(costmodel.CostModelUnavailable) as ei:
            costmodel.CostModel.load(str(tmp_path / "nope.json"))
        assert "collective_bench" in str(ei.value)
        assert "--fit" in str(ei.value)

    def test_transport_mismatch_names_the_command(self, tmp_path):
        m = hand_model(1e-9, 1e-9)
        path = m.save(str(tmp_path / "cm.json"))
        assert costmodel.CostModel.load(
            path, expected_transport="test"
        ).transport == "test"
        with pytest.raises(costmodel.CostModelUnavailable) as ei:
            costmodel.CostModel.load(path, expected_transport="hostring")
        msg = str(ei.value)
        assert "'test'" in msg and "'hostring'" in msg
        assert "collective_bench" in msg

    def test_garbage_file_names_the_command(self, tmp_path):
        p = tmp_path / "cm.json"
        p.write_text("{not json")
        with pytest.raises(costmodel.CostModelUnavailable,
                           match="collective_bench"):
            costmodel.CostModel.load(str(p))

    def test_planner_degrades_to_analytic_loudly(
        self, abstract_state, tmp_path, caplog
    ):
        with ptd_caplog(caplog):
            plan = autoplan.plan(
                profile=NO_COMPUTE, global_batch=8,
                abstract_state=abstract_state,
                cost_model_path=str(tmp_path / "missing.json"),
                compute=MEASURED, strategies=("dp",), max_tp=1,
                n_devices=8, budget_bytes=None,
            )
        assert plan.uncalibrated
        assert plan.cost_model_transport == costmodel.ANALYTIC_TRANSPORT
        assert plan.to_dict()["cost_model"]["source"] == "analytic-guess"
        assert any(
            "uncalibrated" in r.message for r in caplog.records
        )
        # and the rendered table carries the warning + the fix
        assert "UNCALIBRATED" in plan.table()
        assert "collective_bench" in plan.table()

    def test_tp_needs_explicit_opt_in(self, abstract_state):
        # without model-dimension info the planner must not enumerate
        # tp widths whose grad pricing assumes sharding the rule engine
        # may not deliver — tp stays 1 unless tp_candidates/max_tp say
        # otherwise
        plan = autoplan.plan(
            profile=NO_COMPUTE, global_batch=8,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9), compute=MEASURED,
            strategies=("dp",), n_devices=8, budget_bytes=None,
        )
        assert [c.name for c in plan.candidates] == ["dp/dp8"]

    def test_fallback_plan_does_not_record_the_unused_path(
        self, abstract_state, tmp_path
    ):
        plan = autoplan.plan(
            profile=NO_COMPUTE, global_batch=8,
            abstract_state=abstract_state,
            cost_model_path=str(tmp_path / "missing.json"),
            compute=MEASURED, strategies=("dp",), max_tp=1,
            n_devices=8, budget_bytes=None,
        )
        # the audit artifact must not imply the never-read file was used
        assert plan.to_dict()["cost_model"]["path"] is None
        assert plan.to_dict()["cost_model"]["source"] == "analytic-guess"

    def test_assumed_compute_marks_uncalibrated(self, abstract_state):
        plan = autoplan.plan(
            profile=NO_COMPUTE, global_batch=8,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9),
            strategies=("dp",), max_tp=1, n_devices=8,
            budget_bytes=None,  # compute=None -> assumed platform model
        )
        assert plan.uncalibrated


# -- end to end -------------------------------------------------------------
def test_strategy_auto_end_to_end(tmp_path):
    """``--strategy auto`` on the 8-device CPU mesh: the recipe plans,
    writes plan.json, builds the chosen strategy and trains."""
    plan_path = str(tmp_path / "plan.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "recipes", "gpt2_zero1.py"),
         "--strategy", "auto", "--size", "tiny", "--epochs", "1",
         "--steps-per-epoch", "2", "--batch-size", "8",
         "--seq-len", "32", "--accum-steps", "1", "--log-every", "1",
         "--plan-path", plan_path,
         "--costmodel", str(tmp_path / "absent.json")],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    blob = proc.stdout + proc.stderr
    assert "auto-parallel plan" in blob
    assert "auto strategy:" in blob
    doc = json.load(open(plan_path))
    assert doc["chosen"]
    assert doc["uncalibrated"] is True  # no costmodel.json supplied
    assert len(doc["candidates"]) > 1
    chosen = next(
        c for c in doc["candidates"] if c["name"] == doc["chosen"]
    )
    assert chosen["rank"] == 1 and chosen["feasible"]
    # the chosen mesh covers all 8 devices
    import math

    assert math.prod(chosen["mesh"].values()) == 8


def test_obs_report_renders_plan_section(abstract_state, tmp_path):
    plan = run_plan(abstract_state, hand_model(1e-9, 1e-9))
    plan.save(str(tmp_path / "plan.json"))
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "scripts", "obs_report.py"),
         str(tmp_path)],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "== Plan ==" in proc.stdout
    assert plan.best().name in proc.stdout
    assert "CHOSEN" in proc.stdout


# -- round-14: q8 quantize-cost + overlap-aware pricing ---------------------
class TestRound14Pricing:
    def test_q8_fallback_carries_quantize_cost(self):
        """The mispricing fix: with NO q8 calibration, q8 must price
        wire bytes + the analytic quantize passes — on a β where f32
        moves X seconds, q8 must come out SLOWER than f32 (the measured
        shm fact), not 0.25x."""
        from pytorch_distributed_tpu.autoplan.pricing import (
            Q8_QUANTIZE_PASSES,
            grad_comm_terms,
            price_comm_terms,
        )

        beta = 1e-9
        fits = {
            ("all_reduce", 4): costmodel.OpFit(
                "all_reduce", 4, 0.0, beta, 1.0, 4, 0, 1 << 62
            )
        }
        m = costmodel.CostModel("test", fits)
        elems = 1_600_000  # the 6.4 MB measured regime
        f32 = price_comm_terms(
            grad_comm_terms("dp", elems * 4, elems, 4), m
        )
        q8 = price_comm_terms(
            grad_comm_terms("dp", elems * 4, elems, 4, compress="int8"),
            m,
        )
        # hand arithmetic: wire(q8) x β + PASSES x f32_bytes x β
        wire = algo_wire_bytes("all_reduce_q8",
                               q8_wire_payload(elems), 4)
        want = wire * beta + Q8_QUANTIZE_PASSES * elems * 4 * beta
        assert abs(q8[0].seconds - want) < 1e-12
        assert q8[0].seconds > f32[0].seconds  # the measured direction
        assert q8[0].extrapolated
        assert "quantize cost" in q8[0].note
        assert "no q8 calibration" in q8[0].note

    def test_calibrated_q8_fit_bypasses_the_analytic_term(self):
        m = hand_model(1e-9, 1e-9)  # has a real all_reduce_q8 fit
        from pytorch_distributed_tpu.autoplan.pricing import (
            grad_comm_terms,
            price_comm_terms,
        )

        q8 = price_comm_terms(
            grad_comm_terms("dp", 4096 * 4, 4096, 8, compress="int8"), m
        )
        assert "quantize cost" not in q8[0].note
        assert q8[0].seconds == pytest.approx(
            algo_wire_bytes("all_reduce_q8", q8_wire_payload(4096), 8)
            * 1e-9
        )

    def test_auto_stops_preferring_uncalibrated_q8(self, abstract_state):
        """End to end: with only an all_reduce fit, include_q8 candidates
        must now LOSE to plain f32 dp on the shm-shaped transport —
        `--strategy auto` stops picking a measured regression."""
        fits = {}
        for w in (2, 4, 8):
            fits[("all_reduce", w)] = costmodel.OpFit(
                "all_reduce", w, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            )
            fits[("reduce_scatter", w)] = costmodel.OpFit(
                "reduce_scatter", w, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            )
            fits[("all_gather", w)] = costmodel.OpFit(
                "all_gather", w, 0.0, 1e-9, 1.0, 4, 0, 1 << 62
            )
        m = costmodel.CostModel("test", fits)
        p = run_plan(abstract_state, m, strategies=("dp",),
                     include_q8=True)
        assert p.best().spec.compress is None, p.best().name
        q8_row = next(c for c in p.candidates
                      if c.spec.compress == "int8")
        assert q8_row.comm_seconds > p.best().comm_seconds

    def test_overlap_pricing_hides_grad_comm(self, abstract_state):
        """exposed-comm = max(0, comm - overlappable compute): with
        accum 4, 3/4 of the compute window can hide the dp allreduce —
        hand-computed hidden seconds land on the candidate and
        step_seconds drops by exactly that amount."""
        m = hand_model(1e-6, 1e-6)
        profile = autoplan.ModelProfile(
            flops_per_sample=1e9, activation_bytes_per_sample=0.0
        )

        def one(overlap):
            return autoplan.plan(
                profile=profile, global_batch=8, accum_steps=4,
                abstract_state=abstract_state, cost_model=m,
                compute=MEASURED, strategies=("dp",), max_tp=1,
                n_devices=8, budget_bytes=None,
                overlap_grad_sync=overlap,
            ).best()

        serial = one(False)
        ovl = one(True)
        assert serial.hidden_comm_seconds == 0.0
        grad_s = serial.comm_seconds
        overlappable = serial.compute_seconds * 3 / 4
        want_hidden = min(grad_s, overlappable)
        assert ovl.hidden_comm_seconds == pytest.approx(want_hidden)
        assert ovl.step_seconds == pytest.approx(
            serial.step_seconds - want_hidden
        )

    def test_overlap_never_hides_tp_activation_collectives(self):
        """tp activation allreduces sit ON the forward/backward critical
        path — only the grad-exchange terms may hide."""
        model = nn.Dense(64)
        state = jax.eval_shape(lambda: TrainState.create(
            apply_fn=model.apply,
            params=model.init(jax.random.key(0),
                              jnp.zeros((1, 64)))["params"],
            tx=optax.sgd(0.1),
        ))
        profile = autoplan.ModelProfile(
            flops_per_sample=1e9, activation_bytes_per_sample=0.0,
            layers=2, hidden=64, seq_len=8,
        )
        m = hand_model(1e-6, 1e-6)
        p = autoplan.plan(
            profile=profile, global_batch=8, accum_steps=2,
            abstract_state=state, cost_model=m, compute=MEASURED,
            strategies=("dp",), tp_candidates=(2,), n_devices=8,
            budget_bytes=None, overlap_grad_sync=True,
        )
        tp_cand = next(c for c in p.candidates if c.spec.tp == 2
                       and c.feasible)
        grad_s = sum(t.seconds for t in tp_cand.comm_terms
                     if "tp activation" not in t.note)
        assert tp_cand.hidden_comm_seconds <= grad_s + 1e-15

    def test_plan_json_records_overlap(self, abstract_state, tmp_path):
        p = run_plan(abstract_state, hand_model(1e-9, 1e-9),
                     overlap_grad_sync=True)
        doc = json.load(open(p.save(str(tmp_path / "plan.json"))))
        assert doc["overlap_grad_sync"] is True
        c = doc["candidates"][0]
        assert "hidden_seconds" in c["comms"]
        assert "exposed_seconds" in c["comms"]
        assert c["comms"]["exposed_seconds"] == pytest.approx(
            c["comms"]["seconds"] - c["comms"]["hidden_seconds"]
        )


class TestRound15HeteroPricing:
    """r15: pricing mixed-speed fleets with the engine's OWN discrete
    apportionment — hand-computed prices throughout, so the planner's
    balanced-vs-even ordering is a checked arithmetic fact, not a
    trend."""

    PROFILE = autoplan.ModelProfile(
        flops_per_sample=1e9, activation_bytes_per_sample=0.0
    )

    def test_hand_computed_balanced_and_even(self):
        from pytorch_distributed_tpu.autoplan.pricing import (
            hetero_compute_seconds,
        )

        # rates [1, 1, 0.5], 12 shards -> counts [5, 5, 2]
        # (tests/test_balance.py pins the same apportionment);
        # flops = 12e9 at 1e9 f/s/dev:
        #   balanced: max(5, 5, (2/12*12e9)/(0.5e9)=4) = 5 s
        #   even [4,4,4]: max(4, 4, 8) = 8 s
        bal = hetero_compute_seconds(
            self.PROFILE, 12, MEASURED, [1.0, 1.0, 0.5], balanced=True
        )
        even = hetero_compute_seconds(
            self.PROFILE, 12, MEASURED, [1.0, 1.0, 0.5], balanced=False
        )
        assert bal == pytest.approx(5.0)
        assert even == pytest.approx(8.0)

    def test_homogeneous_rates_match_the_flat_term(self):
        from pytorch_distributed_tpu.autoplan.pricing import (
            compute_seconds,
            hetero_compute_seconds,
        )

        flat = compute_seconds(self.PROFILE, 12, 3, MEASURED)
        for balanced in (True, False):
            assert hetero_compute_seconds(
                self.PROFILE, 12, MEASURED, [1.0] * 3, balanced=balanced
            ) == pytest.approx(flat)

    def test_tp_group_rate_is_the_min_member(self):
        from pytorch_distributed_tpu.autoplan.pricing import (
            hetero_compute_seconds,
        )

        # tp=2 groups: ways = [min(1, .5), min(1, 1)] = [.5, 1]; 8
        # shards -> counts [3, 5]; flops 8e9, per-way rate 2e9:
        #   balanced: max((3/8*8e9)/(2e9*.5), (5/8*8e9)/2e9) = 3 s
        #   even [4,4]: max(4e9/1e9, 4e9/2e9) = 4 s
        bal = hetero_compute_seconds(
            self.PROFILE, 8, MEASURED, [1.0, 0.5, 1.0, 1.0],
            tp=2, balanced=True,
        )
        even = hetero_compute_seconds(
            self.PROFILE, 8, MEASURED, [1.0, 0.5, 1.0, 1.0],
            tp=2, balanced=False,
        )
        assert bal == pytest.approx(3.0)
        assert even == pytest.approx(4.0)
        with pytest.raises(ValueError, match="tp=3"):
            hetero_compute_seconds(
                self.PROFILE, 8, MEASURED, [1.0] * 4, tp=3
            )

    def _bench_shape_plan(self, abstract_state, **kw):
        # the bench `hetero` phase's shape: 3 ranks, one at half speed,
        # 12 microshards, dp only
        return autoplan.plan(
            profile=self.PROFILE, global_batch=24,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9, worlds=(3,)),
            compute=MEASURED, strategies=("dp",), max_tp=1,
            n_devices=3, budget_bytes=None,
            rank_rates=[1.0, 1.0, 0.5], microshards=12, **kw,
        )

    def test_plan_reproduces_the_bench_ordering(self, abstract_state):
        """The acceptance pin: on the bench workload's shape the plan
        prices balanced at 1.6x the even split — the same ordering the
        measured phase enforces (>= 1.25x with overheads), with the
        numbers hand-computable: counts [5,5,2] -> 10 s vs even
        [4,4,4] -> 16 s at flops 24e9."""
        p = self._bench_shape_plan(abstract_state)
        c = p.best()
        assert c.compute_seconds == pytest.approx(10.0)
        assert c.compute_seconds_even == pytest.approx(16.0)
        d = c.to_dict()["hetero"]
        assert d["balance_gain"] == pytest.approx(1.6)
        assert d["compute_seconds_balanced"] == pytest.approx(10.0)
        # balanced=False prices the balance=off baseline — but the
        # hetero record must still carry the TRUE balanced price and
        # gain (the whole point of pricing the baseline is seeing what
        # turning balancing on would buy; review catch: it reported
        # its own even price as "balanced" and a 1.00x gain)
        off = self._bench_shape_plan(abstract_state, balanced=False)
        assert off.best().compute_seconds == pytest.approx(16.0)
        assert off.best().step_seconds > c.step_seconds
        d_off = off.best().to_dict()["hetero"]
        assert d_off["compute_seconds_balanced"] == pytest.approx(10.0)
        assert d_off["balance_gain"] == pytest.approx(1.6)

    def test_plan_json_records_rates_and_table_renders(
        self, abstract_state, tmp_path
    ):
        from pytorch_distributed_tpu.autoplan.planner import format_plan

        p = self._bench_shape_plan(abstract_state)
        doc = json.load(open(p.save(str(tmp_path / "plan.json"))))
        assert doc["rank_rates"] == [1.0, 1.0, 0.5]
        assert doc["balanced"] is True
        text = "\n".join(format_plan(doc))
        assert "heterogeneous" in text
        assert "[bal 1.60x]" in text
        # a homogeneous plan records neither (no schema noise)
        q = run_plan(abstract_state, hand_model(1e-9, 1e-9))
        qdoc = json.load(open(q.save(str(tmp_path / "plan2.json"))))
        assert "rank_rates" not in qdoc
        assert "hetero" not in qdoc["candidates"][0]

    def test_rate_vector_validated(self, abstract_state):
        with pytest.raises(ValueError, match="one relative rate"):
            autoplan.plan(
                profile=self.PROFILE, global_batch=24,
                abstract_state=abstract_state,
                cost_model=hand_model(1e-9, 1e-9, worlds=(3,)),
                compute=MEASURED, strategies=("dp",), max_tp=1,
                n_devices=3, budget_bytes=None,
                rank_rates=[1.0, 1.0],
            )
        with pytest.raises(ValueError, match="positive"):
            autoplan.plan(
                profile=self.PROFILE, global_batch=24,
                abstract_state=abstract_state,
                cost_model=hand_model(1e-9, 1e-9, worlds=(3,)),
                compute=MEASURED, strategies=("dp",), max_tp=1,
                n_devices=3, budget_bytes=None,
                rank_rates=[1.0, 1.0, -0.5],
            )


# -- round 20: pipeline-parallel candidates ---------------------------------
class TestPipelinePlanning:
    """The pp dimension of the plan: opt-in enumeration, hand-computed
    bubble + link pricing, hetero stage depths via the balancer, and
    the audit record on plan.json."""

    PROFILE = autoplan.ModelProfile(
        flops_per_sample=1e9, activation_bytes_per_sample=1024.0,
        layers=4, hidden=64, seq_len=16, act_dtype_bytes=4,
    )

    def pp_plan(self, abstract_state, model, **kw):
        kw.setdefault("strategies", ("dp",))
        kw.setdefault("max_tp", 1)
        kw.setdefault("n_devices", 2)
        kw.setdefault("budget_bytes", None)
        kw.setdefault("max_pp", 2)
        kw.setdefault("profile", self.PROFILE)
        return autoplan.plan(
            global_batch=kw.pop("global_batch", 8),
            abstract_state=abstract_state, cost_model=model,
            compute=MEASURED, **kw,
        )

    def test_pp_needs_explicit_opt_in(self, abstract_state):
        # same discipline as tp: no pp_candidates/max_pp -> the search
        # space stays unpipelined
        plan = autoplan.plan(
            profile=self.PROFILE, global_batch=8,
            abstract_state=abstract_state,
            cost_model=hand_model(1e-9, 1e-9), compute=MEASURED,
            strategies=("dp",), max_tp=1, n_devices=2,
            budget_bytes=None,
        )
        assert [c.name for c in plan.candidates] == ["dp/dp2"]

    def test_pp_enumeration_dp_only_no_q8_no_duplicates(self):
        cands = autoplan.enumerate_candidates(
            8, max_pp=8, include_q8=True
        )
        names = [c.name for c in cands]
        assert len(names) == len(set(names))
        pp = [c for c in cands if c.pp > 1]
        assert pp, names
        assert all(c.strategy == "dp" and c.compress is None for c in pp)
        # pp == 1 rows are EXACTLY the unpipelined enumeration — the pp
        # dimension never re-emits a renamed duplicate of dp/dpN
        base = [c.name for c in autoplan.enumerate_candidates(
            8, max_pp=1, include_q8=True)]
        assert [c.name for c in cands if c.pp == 1] == base
        # and the mesh shape carries the pp axis
        two = next(c for c in pp if c.pp == 2 and c.data == 4)
        assert two.mesh_spec().pp == 2
        assert two.n_devices == 8

    def test_pp_bubble_and_links_hand_computed(self, abstract_state):
        m = hand_model(1e-9, 1e-9, p2p_beta=1e-9)
        plan = self.pp_plan(abstract_state, m)
        by = {c.name: c for c in plan.candidates}
        pp2 = by["dp/dp1xpp2"]
        assert pp2.feasible
        # S=2, M=max(accum 1, 2*pp)=4, per-dev batch 8 -> microbatch 2.
        # compute: the slowest stage's 2/4 layer share of
        # 8 samples x 1e9 flops at the 1e9 flops/s measured rate = 4 s
        assert pp2.compute_seconds == pytest.approx(4.0, rel=1e-9)
        # bubble: slowest_stage x (S-1)/M = 4.0 / 4 = 1 s, and the
        # analytic fraction is (S-1)/(M+S-1)
        assert pp2.bubble_seconds == pytest.approx(1.0, rel=1e-9)
        assert pp2.pipeline["bubble_fraction"] == \
            pytest.approx(1 / 5, rel=1e-9)
        # links: one act + one grad slab per microbatch per boundary =
        # 2 x M x (S-1) = 8 transfers of microbatch x seq x hidden x 4
        # = 2*16*64*4 = 8192 bytes at the world-2 send fit
        slab = 2 * 16 * 64 * 4
        want_links = 8 * m.predict("send", slab, 2).seconds
        assert pp2.pipeline["link_seconds"] == \
            pytest.approx(want_links, rel=1e-9)
        assert not pp2.extrapolated  # the send fit priced it, no guess
        # the step price carries the bubble ON the critical path
        assert pp2.step_seconds == pytest.approx(
            pp2.comm_seconds + 4.0 + 1.0, rel=1e-9
        )
        # data=1 inside each stage: NO grad exchange — the handoff
        # link is the candidate's whole comm bill
        assert [t.op for t in pp2.comm_terms] == ["send"]
        assert pp2.comm_seconds == pytest.approx(want_links, rel=1e-9)
        # the losing pipeline row names its OWN price
        assert pp2.why_not and "bubble" in pp2.why_not \
            and "links" in pp2.why_not

    def test_pp_even_split_matches_flat_compute(self, abstract_state):
        # homogeneous even depths reproduce the flat flops/n term
        # exactly: pp "costs" only the bubble and the links
        m = hand_model(1e-9, 1e-9, p2p_beta=1e-9)
        plan = self.pp_plan(abstract_state, m)
        by = {c.name: c for c in plan.candidates}
        assert by["dp/dp1xpp2"].compute_seconds == \
            pytest.approx(by["dp/dp2"].compute_seconds, rel=1e-9)
        assert by["dp/dp1xpp2"].pipeline["stage_depths"] == [2, 2]

    def test_pp_hetero_depths_pin(self, abstract_state):
        # 8 layers over 2 stages at rates [1.0, 0.5]: the balancer's
        # apportionment gives the slow stage the SHALLOWER split — the
        # hand-computed (5, 3), the same depths
        # pipeline_schedule.stage_depths hands the executor
        prof = dataclasses.replace(self.PROFILE, layers=8)
        m = hand_model(1e-9, 1e-9, p2p_beta=1e-9)
        plan = self.pp_plan(abstract_state, m, profile=prof,
                            rank_rates=[1.0, 0.5])
        pp2 = next(c for c in plan.candidates if c.spec.pp == 2)
        assert pp2.feasible
        assert pp2.pipeline["stage_depths"] == [5, 3]
        # priced at the split it would BUILD: slowest stage is the slow
        # one, 3/8 of 8e9 flops at 0.5e9 flops/s = 6 s
        assert pp2.compute_seconds == pytest.approx(6.0, rel=1e-9)

    def test_pp_infeasibility_reasons(self, abstract_state):
        m = hand_model(1e-9, 1e-9, p2p_beta=1e-9)
        # layers that cannot fill the stages: 4 devices pp=4 over a
        # 2-layer model (floor=1 layer per stage)
        prof = dataclasses.replace(self.PROFILE, layers=2)
        plan = self.pp_plan(
            abstract_state,
            hand_model(1e-9, 1e-9, worlds=(2, 4), p2p_beta=1e-9),
            profile=prof, n_devices=4, max_pp=4, global_batch=16,
        )
        pp4 = next(c for c in plan.candidates if c.spec.pp == 4)
        assert not pp4.feasible
        assert "cannot fill" in pp4.reason or "divide" in pp4.reason
        # batch that cannot split into the microbatch count: M=4 needs
        # per-device batch % 4 == 0
        plan2 = self.pp_plan(abstract_state, m, global_batch=6)
        pp2 = next(c for c in plan2.candidates if c.spec.pp == 2)
        assert not pp2.feasible and "microbatch" in pp2.reason

    def test_pp_plan_json_schema(self, abstract_state, tmp_path):
        m = hand_model(1e-9, 1e-9, p2p_beta=1e-9)
        plan = self.pp_plan(abstract_state, m)
        doc = json.load(open(plan.save(str(tmp_path / "plan.json"))))
        pp2 = next(c for c in doc["candidates"]
                   if c["name"] == "dp/dp1xpp2")
        pl = pp2["pipeline"]
        assert set(pl) == {"pp", "num_microbatches", "bubble_fraction",
                           "bubble_seconds", "link_seconds",
                           "stage_depths"}
        assert pl["pp"] == 2 and pl["num_microbatches"] == 4
        assert pp2["mesh"]["pp"] == 2
        # unpipelined rows carry no pipeline key (no schema noise)
        dp = next(c for c in doc["candidates"] if c["name"] == "dp/dp2")
        assert "pipeline" not in dp
        # microbatch override flows through
        plan8 = self.pp_plan(abstract_state, m, pp_microbatches=8,
                             global_batch=16)
        pp2b = next(c for c in plan8.candidates if c.spec.pp == 2)
        assert pp2b.pipeline["num_microbatches"] == 8

    @pytest.mark.slow
    def test_strategy_auto_ranks_pp_end_to_end(self, tmp_path):
        """``--strategy auto --pp 2`` on a 2-device CPU mesh: the
        recipe opens the pipeline dimension, the plan ranks the
        dp x pp space, the pp row carries its pipeline audit record,
        and the run trains with the chosen strategy."""
        plan_path = str(tmp_path / "plan.json")
        env = dict(os.environ, JAX_PLATFORMS="cpu",
                   PALLAS_AXON_POOL_IPS="")
        env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=2"
        proc = subprocess.run(
            [sys.executable,
             os.path.join(REPO, "recipes", "gpt2_zero1.py"),
             "--strategy", "auto", "--pp", "2", "--size", "tiny",
             "--epochs", "1", "--steps-per-epoch", "2",
             "--batch-size", "8", "--seq-len", "32",
             "--accum-steps", "1", "--log-every", "1",
             "--plan-path", plan_path,
             "--costmodel", str(tmp_path / "absent.json")],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=600,
        )
        assert proc.returncode == 0, proc.stderr[-3000:]
        doc = json.load(open(plan_path))
        names = [c["name"] for c in doc["candidates"]]
        assert "dp/dp1xpp2" in names, names
        pp2 = next(c for c in doc["candidates"]
                   if c["name"] == "dp/dp1xpp2")
        assert pp2["pipeline"]["pp"] == 2
        assert pp2["pipeline"]["stage_depths"]
        # wherever it ranked, the pipeline row's verdict is priced:
        # either it won or its why_not names the bubble/link price
        assert pp2["feasible"]
        if doc["chosen"] != "dp/dp1xpp2" and pp2["rank"] is not None:
            assert "bubble" in pp2["why_not"]
        assert "auto strategy:" in proc.stdout + proc.stderr
