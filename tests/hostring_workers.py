"""Spawn targets for the multi-process hostring tests.

Lives in its own importable module because ``multiprocessing`` spawn needs
to pickle the target by reference. Workers must stay lightweight: the raw
worker is JAX-free; the facade worker imports the framework (JAX on CPU).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def raw_worker(rank: int, world: int, name: str, q) -> None:
    """Exercise the ctypes layer directly (no JAX in the child)."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60) as g:
            ar = g.all_reduce(np.full(1000, rank + 1.0, np.float32))
            assert np.all(ar == world * (world + 1) / 2), ar[:4]
            ag = g.all_gather(np.array([rank], np.int32))
            assert list(ag.ravel()) == list(range(world))
            rs = g.reduce_scatter(
                np.ones((world, 4), np.float64) * (rank + 1)
            )
            assert np.all(rs == world * (world + 1) / 2)
            bc = g.broadcast(np.full(3, rank, np.int64), src=1)
            assert np.all(bc == 1)
            mx = g.all_reduce(np.array([rank], np.int32), op="max")
            assert mx[0] == world - 1
            # big payload: crosses the chunking path
            big = g.all_reduce(np.ones(3_000_000, np.float32))
            assert np.all(big == world)
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def facade_worker(rank: int, world: int, name: str, q) -> None:
    """Exercise the torch-shaped facade in true multi-process mode."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import pytorch_distributed_tpu as ptd

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)
        assert ptd.get_backend() == "hostring"
        assert ptd.get_rank() == rank
        assert ptd.get_world_size() == world
        out = ptd.all_reduce(np.full(8, float(rank), np.float32))
        expect = sum(range(world))
        assert np.all(np.asarray(out) == expect), out
        g = ptd.all_gather(np.array([rank], np.int32))
        assert list(np.asarray(g).ravel()) == list(range(world))
        b = ptd.broadcast(np.array([rank * 10.0], np.float32), src=2)
        assert float(np.asarray(b)[0]) == 20.0
        ptd.barrier()
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))
