"""Spawn targets for the multi-process hostring tests.

Lives in its own importable module because ``multiprocessing`` spawn needs
to pickle the target by reference. Workers must stay lightweight: the raw
worker is JAX-free; the facade worker imports the framework (JAX on CPU).
"""

import os
import sys

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def run_ring_workers(world, target, extra_args=(), timeout=180.0):
    """Spawn one ``(rank, world, name, q, *extra_args)``-shaped worker
    per rank on the CPU backend and collect one queue result per rank,
    sorted. THE test-side multi-process harness (test_hostring and
    test_comms_obs both use it; bench.py carries its own copy because
    the bench must not import from tests/): env is pinned before
    spawning since children inherit it at interpreter start, and
    join/terminate runs even when a rank dies without reporting."""
    import multiprocessing as mp
    import uuid

    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    name = f"ptdtest_{uuid.uuid4().hex[:8]}"
    procs = [
        ctx.Process(target=target,
                    args=(r, world, name, q) + tuple(extra_args))
        for r in range(world)
    ]
    # Children must never touch the (single, shared) TPU: contending for
    # it serializes their startup past the collective timeouts.
    old = os.environ.get("JAX_PLATFORMS")
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        for p in procs:
            p.start()
    finally:
        if old is None:
            os.environ.pop("JAX_PLATFORMS", None)
        else:
            os.environ["JAX_PLATFORMS"] = old
    try:
        results = [q.get(timeout=timeout) for _ in range(world)]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
    return sorted(results)


def raw_worker(rank: int, world: int, name: str, q) -> None:
    """Exercise the ctypes layer directly (no JAX in the child)."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60) as g:
            src = np.full(1000, rank + 1.0, np.float32)
            ar = g.all_reduce(src)
            assert np.all(ar == world * (world + 1) / 2), ar[:4]
            assert np.all(src == rank + 1.0)  # functional: input untouched
            ip = np.full(1000, rank + 1.0, np.float32)
            out = g.all_reduce(ip, inplace=True)
            assert out is ip  # torch dist.all_reduce semantics: in place
            assert np.all(ip == world * (world + 1) / 2), ip[:4]
            try:  # inplace that can't be honored must raise, not
                g.all_reduce(ip[::2], inplace=True)  # reduce a copy
                raise AssertionError("non-contiguous inplace accepted")
            except ValueError:
                pass
            ag = g.all_gather(np.array([rank], np.int32))
            assert list(ag.ravel()) == list(range(world))
            rs = g.reduce_scatter(
                np.ones((world, 4), np.float64) * (rank + 1)
            )
            assert np.all(rs == world * (world + 1) / 2)
            bc = g.broadcast(np.full(3, rank, np.int64), src=1)
            assert np.all(bc == 1)
            mx = g.all_reduce(np.array([rank], np.int32), op="max")
            assert mx[0] == world - 1
            # all_to_all: rank r sends chunk j = [r, j]; receives [j, r]
            a2a_in = np.array(
                [[rank, j] for j in range(world)], np.float32
            ).reshape(world, 2)
            a2a = g.all_to_all(a2a_in.reshape(world * 2))
            want = np.array(
                [[j, rank] for j in range(world)], np.float32
            ).reshape(world * 2)
            assert np.array_equal(a2a, want), (a2a, want)
            sc = g.scatter(
                np.arange(world * 3, dtype=np.float32).reshape(world, 3),
                src=0,
            )
            assert np.array_equal(sc, np.arange(3) + rank * 3.0), sc
            # big payload: crosses the chunking path
            big = g.all_reduce(np.ones(3_000_000, np.float32))
            assert np.all(big == world)
            # segmented-allreduce edges: n < world (all segments empty but
            # the last rank's) and a ragged n = world + 1 tail
            tiny = g.all_reduce(np.array([rank + 1.0], np.float32))
            assert tiny[0] == world * (world + 1) / 2, tiny
            ragged = g.all_reduce(
                np.full(world + 1, rank + 1.0, np.float32)
            )
            assert np.all(ragged == world * (world + 1) / 2), ragged
            # native half allreduce: ships 2-byte, accumulates f32, rounds
            # ONCE — the result must equal f32-sum-then-round exactly
            import ml_dtypes

            allh = (
                np.arange(world * 3, dtype=np.float32).reshape(world, 3)
                + 0.33
            ).astype(ml_dtypes.bfloat16)
            got = g.all_reduce(allh[rank].copy())
            want = allh.astype(np.float32).sum(axis=0).astype(
                ml_dtypes.bfloat16
            )
            assert got.dtype == allh.dtype, got.dtype
            assert np.array_equal(
                got.astype(np.float32), want.astype(np.float32)
            ), (got, want)
            hm = g.all_reduce(np.array([rank], np.float16), op="max")
            assert hm.dtype == np.float16 and hm[0] == world - 1, hm
            # avg on halves divides BEFORE the single rounding: an f16 sum
            # of 30000.0 x world overflows f16, the average must not
            ha = g.all_reduce(np.array([30000.0, -2.5], np.float16),
                              op="avg")
            assert ha[0] == np.float16(30000.0), ha
            assert ha[1] == np.float16(-2.5), ha
            ba = g.all_reduce(
                np.full(5, rank + 1.0, np.float32).astype(ml_dtypes.bfloat16),
                op="avg",
            )
            want_avg = np.float32((world + 1) / 2).astype(ml_dtypes.bfloat16)
            assert np.all(ba == want_avg), (ba, want_avg)
            # int8 block-quantized allreduce: bounded error vs the exact
            # mean, and bit-identical results on every rank (lockstep)
            rng_q = np.random.default_rng(5)
            allq = (rng_q.normal(size=(world, 10_000)) * 7).astype(
                np.float32
            )
            got_q = g.all_reduce_q8(allq[rank].copy(), op="avg")
            exact = allq.mean(axis=0)
            atol = (world + 1) * np.abs(allq).max() / 127
            assert np.all(np.abs(got_q - exact) <= atol), (
                np.abs(got_q - exact).max(), atol
            )
            rows = g.all_gather(got_q)
            assert all(
                np.array_equal(rows[0], rows[i]) for i in range(world)
            ), "q8 results diverged across ranks"
            # non-finite gradients must propagate loudly, not quantize
            # to garbage or silently zero
            bad = np.ones(6000, np.float32)
            if rank == 0:
                bad[100] = np.inf
            got_bad = g.all_reduce_q8(bad, op="sum")
            assert not np.all(np.isfinite(got_bad)), "inf was swallowed"
            # f16 software conversions agree with numpy's, including
            # subnormals and values that round up across an exponent
            probe = np.array(
                [6e-8, 6.1e-5, 65504.0, 65520.0, 2048.2, -0.0],
                np.float32,
            ).astype(np.float16)
            conv = g.all_reduce(probe, op="max")  # world-identical: the
            assert np.array_equal(                # round trip is the test
                conv, probe, equal_nan=True
            ), (conv, probe)
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def spawn_worker(rank: int, path: str) -> None:
    """Target for launch.spawn: env is pre-set by the launcher."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pytorch_distributed_tpu as ptd

    ptd.init_process_group("gloo")
    world = ptd.get_world_size()
    out = ptd.all_reduce(np.array([1.0], np.float32))
    assert float(np.asarray(out)[0]) == world
    assert int(os.environ["LOCAL_RANK"]) == rank
    with open(os.path.join(path, f"rank{rank}.ok"), "w") as f:
        f.write(str(world))
    ptd.destroy_process_group()


def ddp_train_worker(rank: int, path: str) -> None:
    """Two train steps on per-rank data shards; params must stay identical
    across ranks (the DDP invariant: averaged grads -> lockstep updates)."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.train import (
        TrainState,
        build_train_step,
        classification_loss_fn,
    )

    ptd.init_process_group("gloo")
    world = ptd.get_world_size()
    model = ResNet(stage_sizes=[1], block_cls=BasicBlock, num_classes=4,
                   width=8, stem="cifar")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8, 8, 3)),
                           train=False)
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"],
        tx=optax.sgd(0.1), batch_stats=variables["batch_stats"],
    )
    rng = np.random.default_rng(7)
    ds = ArrayDataset(
        image=rng.normal(size=(32, 8, 8, 3)).astype(np.float32),
        label=rng.integers(4, size=(32,)).astype(np.int32),
    )
    strategy = DataParallel()
    state = strategy.place(state)
    step = strategy.compile(
        build_train_step(classification_loss_fn(model)), state
    )
    loader = DataLoader(ds, 16, seed=1, sharding=strategy.batch_sharding())
    for batch in loader:
        # per-rank shard: loader slices the global batch by rank
        assert batch["image"].shape[0] == 16 // world, batch["image"].shape
        state, _ = step(state, batch)
    flat = jnp.concatenate([
        jnp.ravel(x).astype(jnp.float32)
        for x in jax.tree_util.tree_leaves(state.params)
    ])
    # the invariant check itself runs over the ring: gather every rank's
    # param vector and require exact agreement
    allp = np.asarray(ptd.all_gather(np.asarray(flat)))
    assert np.array_equal(allp[0], allp[rank]), "params diverged across ranks"
    with open(os.path.join(path, f"ddp{rank}.ok"), "w") as f:
        f.write("ok")
    ptd.destroy_process_group()


class _Stream:
    """Module-level (picklable) sample stream for iterable-loader tests."""

    def __init__(self, n):
        self.n = n

    def __iter__(self):
        for i in range(self.n):
            yield {"x": np.float32(i)}


def iterable_loader_worker(rank: int, path: str) -> None:
    """Streaming loader under the 2-proc hostring world: each rank gets
    the strided half of every global batch, in lockstep."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.data import DataLoader

    ptd.init_process_group("gloo")
    world = ptd.get_world_size()
    dl = DataLoader(_Stream(12), 4, drop_last=False)
    got = [b["x"].tolist() for b in dl]
    # global groups [0..3] [4..7] [8..11]; rank r keeps indices r::world
    want = [
        [float(g * 4 + i) for i in range(rank, 4, world)] for g in range(3)
    ]
    assert got == want, (got, want)
    with open(os.path.join(path, f"it{rank}.ok"), "w") as f:
        f.write("ok")
    ptd.destroy_process_group()


def subgroup_worker(rank: int, path: str) -> None:
    """new_group over a 3-proc hostring world: members {0, 2} allreduce on
    a dedicated ring, the bystander (1) is refused, everyone stays live."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import pytorch_distributed_tpu as ptd

    ptd.init_process_group("gloo")
    sub = ptd.new_group([0, 2])
    if rank in (0, 2):
        out = ptd.all_reduce(
            np.array([rank + 1.0], np.float32), group=sub
        )
        assert out[0] == 4.0, out  # 1 + 3
        ptd.barrier(group=sub)
    else:
        try:
            ptd.all_reduce(np.array([0.0], np.float32), group=sub)
            raise AssertionError("bystander collective must refuse")
        except RuntimeError:
            pass
    # the WORLD still works after subgroup traffic
    world_sum = ptd.all_reduce(np.array([rank + 1.0], np.float32))
    assert world_sum[0] == 6.0, world_sum
    sub.close()
    with open(os.path.join(path, f"sg{rank}.ok"), "w") as f:
        f.write("ok")
    ptd.destroy_process_group()


def grad_compress_worker(rank: int, path: str) -> None:
    """sync_grads(compress='bf16') ships bf16 and must equal the exact
    reference: bf16(mean_f32(bf16(g_r))) upcast back to f32."""
    import jax

    jax.config.update("jax_platforms", "cpu")
    import jax.numpy as jnp
    import ml_dtypes

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.parallel.ddp import sync_grads

    ptd.init_process_group("gloo")
    world = ptd.get_world_size()
    rng = np.random.default_rng(42)
    allg = (rng.normal(size=(world, 33)) * 100).astype(np.float32)

    @jax.jit
    def compressed(g):
        return sync_grads(g, compress="bf16")

    @jax.jit
    def plain(g):
        return sync_grads(g)

    out = np.asarray(compressed({"w": jnp.asarray(allg[rank])})["w"])
    assert out.dtype == np.float32, out.dtype
    cast = allg.astype(ml_dtypes.bfloat16).astype(np.float32)
    want = (
        (cast.sum(axis=0) / world)
        .astype(ml_dtypes.bfloat16)
        .astype(np.float32)
    )
    np.testing.assert_array_equal(out, want)
    # uncompressed stays the exact f32 mean
    out32 = np.asarray(plain({"w": jnp.asarray(allg[rank])})["w"])
    np.testing.assert_allclose(out32, allg.mean(axis=0), rtol=1e-6)
    # and the compressed result is close to it (bf16 has ~3 decimal digits)
    np.testing.assert_allclose(out, out32, rtol=1e-2)
    with open(os.path.join(path, f"gc{rank}.ok"), "w") as f:
        f.write("ok")
    ptd.destroy_process_group()


def mismatch_worker(rank: int, world: int, name: str, q) -> None:
    """Debug mode must catch ranks issuing different collectives."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60,
                           debug=True) as g:
            # uniform call passes
            g.all_reduce(np.ones(4, np.float32))
            # divergent shapes must raise on every rank
            try:
                g.all_reduce(np.ones(4 + rank, np.float32))
            except RuntimeError as e:
                assert "collective mismatch" in str(e), e
                q.put((rank, "ok"))
                return
            q.put((rank, "no error raised"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def p2p_worker(rank: int, world: int, name: str, q) -> None:
    """True P2P: transfers between rank pairs with BYSTANDER ranks that never
    enter the call — the case the old barrier-based sendrecv deadlocked on.
    Also exercises multi-chunk payloads and a bidirectional exchange."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60) as g:
            # 0 -> world-1 while ranks in between do nothing
            big = 1_000_003  # odd size: crosses the mailbox chunking path
            if rank == 0:
                g.send(np.arange(big, dtype=np.float32), dst=world - 1)
            elif rank == world - 1:
                out = g.recv(np.empty(big, np.float32), src=0)
                assert np.array_equal(out, np.arange(big, dtype=np.float32))
            # bidirectional pair exchange on (0, 1): distinct channels per
            # direction, so ordering between the two sends is free
            if rank == 0:
                g.send(np.full(5, 10.0, np.float32), dst=1)
                got = g.recv(np.empty(5, np.float32), src=1)
                assert np.all(got == 20.0), got
            elif rank == 1:
                got = g.recv(np.empty(5, np.float32), src=0)
                assert np.all(got == 10.0), got
                g.send(np.full(5, 20.0, np.float32), dst=0)
            # group still healthy for collectives afterwards
            ar = g.all_reduce(np.ones(8, np.float32))
            assert np.all(ar == world), ar
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def failing_worker(rank: int) -> None:
    """Deliberate crash target for failure-propagation tests (no JAX)."""
    raise SystemExit(3)


def comm_span_worker(rank: int, world: int, name: str, q) -> None:
    """Every collective lands a ``comm.*`` span with EXACT wire-byte
    accounting (NCCL convention; q8 counts its real int8+scales bytes),
    cumulative counter tracks, GB/s rollups, and clock-sync metadata —
    all verified in-process, no JAX in the child."""
    try:
        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.hostring import (
            HostRingGroup,
            algo_wire_bytes,
            q8_wire_payload,
        )

        tracing.configure(None)
        with HostRingGroup(name, rank, world, timeout_s=60,
                           clock_sync=True) as g:
            g.all_reduce(np.ones(1000, np.float32))
            g.all_reduce_q8(np.ones(5000, np.float32))
            g.all_gather(np.full(500, rank, np.int32))
            g.reduce_scatter(np.ones((world, 6), np.float64))
            g.broadcast(np.ones(7, np.float32), src=0)
            g.barrier()
            if rank == 0:
                g.send(np.ones(16, np.float32), dst=1)
            elif rank == 1:
                g.recv(np.empty(16, np.float32), src=0)
        t = tracing.get()
        evs = {}
        for e in t._events:
            if e["ph"] == "X":
                evs.setdefault(e["name"], []).append(e)
        want_wire = {
            "comm.all_reduce": algo_wire_bytes("all_reduce", 4000, world),
            "comm.all_reduce_q8": algo_wire_bytes(
                "all_reduce_q8", q8_wire_payload(5000), world
            ),
            "comm.all_gather": algo_wire_bytes(
                "all_gather", world * 2000, world
            ),
            "comm.reduce_scatter": algo_wire_bytes(
                "reduce_scatter", world * 48, world
            ),
            "comm.broadcast": 28,
            "comm.barrier": 0,
        }
        if rank == 0:
            want_wire["comm.send"] = 64
        elif rank == 1:
            want_wire["comm.recv"] = 64
        for span_name, wire in want_wire.items():
            assert span_name in evs, (span_name, sorted(evs))
            a = evs[span_name][0]["args"]
            assert a["wire_bytes"] == wire, (span_name, a, wire)
            assert a["world"] == world
            for key in ("op", "dtype", "count", "payload_bytes"):
                assert key in a, (span_name, a)
        # the q8 span records the REAL wire payload AND the f32 bytes it
        # replaced, so the ~4x reduction is computable from one event
        q8a = evs["comm.all_reduce_q8"][0]["args"]
        assert q8a["payload_bytes"] == q8_wire_payload(5000)
        assert q8a["f32_bytes"] == 20000
        assert q8a["payload_bytes"] / q8a["f32_bytes"] < 0.26
        # cumulative counter tracks rode the same stream
        counters = {
            e["name"]: e["args"]["value"]
            for e in t._events if e["ph"] == "C"
        }
        assert counters.get("comm.all_reduce.calls") == 1
        assert counters.get("comm.all_reduce.bytes_moved") == want_wire[
            "comm.all_reduce"
        ]
        assert counters.get("comm.all_reduce.seconds", 0) > 0
        # rollups report exact bytes and achieved GB/s per op
        roll = t.rollups()["comm.all_reduce"]
        assert roll["bytes_total"] == want_wire["comm.all_reduce"]
        assert roll["gb_per_s"] > 0
        # clock handshake stamped process-level metadata for trace_merge
        meta = tracing.get_meta()
        assert meta["rank"] == rank and meta["world_size"] == world
        assert len(meta["clock_offsets_s"]) == world
        assert meta["clock_offsets_s"][0] == 0.0  # offsets are vs rank 0
        assert abs(meta["clock_offset_s"]) < 5.0  # same host: ~jitter
        tracing.clear()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def trace_export_worker(rank: int, world: int, name: str, q,
                        trace_dir: str) -> None:
    """Per-rank traced run for the trace_merge test: staggered ranks,
    lockstep collectives, per-rank trace files (the trainer's naming)."""
    try:
        import time as _time

        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        tracer = tracing.configure(trace_dir)
        with HostRingGroup(name, rank, world, timeout_s=60,
                           clock_sync=True) as g:
            for i in range(4):
                _time.sleep(0.002 * rank)  # real straggle, visible skew
                g.all_reduce(np.ones(2000, np.float32))
                g.barrier()
        fname = "trace.json" if rank == 0 else f"trace-rank{rank}.json"
        tracer.export(os.path.join(trace_dir, fname))
        tracing.clear()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def debug_barrier_mismatch_worker(rank: int, world: int, name: str,
                                  q) -> None:
    """DETAIL debug mode covers barrier(): a barrier/collective
    interleave mismatch must RAISE on every rank naming the divergence,
    not hang until the group deadline."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60,
                           debug=True) as g:
            g.barrier()  # uniform barrier passes
            try:
                if rank == 0:
                    # deliberate divergence: this worker EXISTS to prove
                    # DETAIL raises on exactly the hazard PTD001 flags
                    # ptdlint: disable=PTD001
                    g.barrier()  # rank 0 thinks "barrier"...
                else:
                    # ptdlint: disable=PTD001
                    g.all_reduce(np.ones(4, np.float32))  # ...peers don't
            except RuntimeError as e:
                assert "collective mismatch" in str(e), e
                assert "barrier" in str(e), e
                q.put((rank, "ok"))
                return
            q.put((rank, "no error raised"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def debug_p2p_worker(rank: int, world: int, name: str, q) -> None:
    """DETAIL debug mode covers send/recv: matching transfers pass, a
    shape mismatch raises on BOTH endpoints naming both descriptions."""
    try:
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        with HostRingGroup(name, rank, world, timeout_s=60,
                           debug=True) as g:
            # matching pair passes, payload intact
            if rank == 0:
                g.send(np.arange(8, dtype=np.float32), dst=1)
            elif rank == 1:
                got = g.recv(np.empty(8, np.float32), src=0)
                assert np.array_equal(
                    got, np.arange(8, dtype=np.float32)
                ), got
            # mismatched shapes must raise on both sides
            if rank in (0, 1):
                try:
                    if rank == 0:
                        g.send(np.ones(4, np.float32), dst=1)
                    else:
                        g.recv(np.empty(5, np.float32), src=0)
                except RuntimeError as e:
                    assert "P2P mismatch" in str(e), e
                    q.put((rank, "ok"))
                    return
                q.put((rank, "no error raised"))
                return
            q.put((rank, "ok"))  # bystander ranks stay untouched
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def coalesce_worker(rank: int, world: int, name: str, q) -> None:
    """sync_grads coalesces sub-4096-elem f32 leaves into ONE flat
    allreduce: the comm.* spans prove the collective-count drop, and at
    world 2 the result is bit-identical to the per-leaf reference
    (two-operand f32 addition commutes, so the segment-rotation of the
    summation order cannot change a single bit)."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.ddp import sync_grads
        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.distributed import (
            multiprocess_ring,
        )

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)
        ring = multiprocess_ring()
        rng = np.random.default_rng(11 + rank)  # per-rank gradients
        # 6 tiny leaves + 1 big: per-leaf would issue 7 collectives,
        # coalesced issues 2 (the flat + the big)
        tiny = {
            f"t{i}": (rng.normal(size=(17 + i,)) * 3).astype(np.float32)
            for i in range(6)
        }
        big = (rng.normal(size=(5000,)) * 3).astype(np.float32)
        grads = {**tiny, "big": big}

        synced_fn = jax.jit(lambda g: sync_grads(g))
        tracing.configure(None)
        out = jax.tree_util.tree_map(np.asarray, synced_fn(grads))
        t = tracing.get()
        ar_spans = [
            e for e in t._events
            if e["ph"] == "X" and e["name"] == "comm.all_reduce"
        ]
        assert len(ar_spans) == 2, [e["args"] for e in ar_spans]
        sg = [
            e for e in t._events
            if e["ph"] == "X" and e["name"] == "comm.sync_grads"
        ]
        assert len(sg) == 1, sg
        assert sg[0]["args"]["leaves"] == 7
        assert sg[0]["args"]["collectives"] == 2
        assert sg[0]["args"]["coalesced_leaves"] == 6
        assert sg[0]["args"]["pre_bytes"] == sum(
            v.nbytes for v in grads.values()
        )
        tracing.clear()

        # bit-identical to the per-leaf reference at world 2: same ring,
        # one explicit all_reduce per leaf, leaf order (every rank runs
        # the identical sequence, so the ring stays in lockstep)
        for key in sorted(grads):
            ref = ring.all_reduce(grads[key], op="avg")
            assert np.array_equal(
                np.asarray(out[key]), ref
            ), (key, np.asarray(out[key])[:4], ref[:4])

        # ...and under int8 compression the flat buffer stays EXACT f32
        # while the big leaf takes the q8 path
        tracing.configure(None)
        out_q = jax.tree_util.tree_map(
            np.asarray, jax.jit(lambda g: sync_grads(g, compress="int8"))(grads)
        )
        t = tracing.get()
        names = [
            e["name"] for e in t._events
            if e["ph"] == "X" and e["name"].startswith("comm.all_reduce")
        ]
        assert sorted(names) == ["comm.all_reduce",
                                 "comm.all_reduce_q8"], names
        tracing.clear()
        for key in sorted(tiny):  # tiny leaves: exact, bit-identical
            ref = ring.all_reduce(grads[key], op="avg")
            assert np.array_equal(np.asarray(out_q[key]), ref), key
        # big leaf went quantized: close, not exact
        ref_big = ring.all_reduce(grads["big"], op="avg")
        atol = (world + 1) * np.abs(big).max() / 127
        assert np.all(np.abs(np.asarray(out_q["big"]) - ref_big) <= atol)

        # ...and under bf16 compression the tiny leaves STILL coalesce
        # (grouping keys on the ON-THE-WIRE dtype, after the cast):
        # 7 leaves -> 2 bf16 collectives, bit-identical to the per-leaf
        # bf16 reference at world 2
        import ml_dtypes

        tracing.configure(None)
        out_h = jax.tree_util.tree_map(
            np.asarray,
            jax.jit(lambda g: sync_grads(g, compress="bf16"))(grads),
        )
        t = tracing.get()
        ar_h = [
            e for e in t._events
            if e["ph"] == "X" and e["name"] == "comm.all_reduce"
        ]
        assert len(ar_h) == 2, [e["args"] for e in ar_h]
        assert all(e["args"]["dtype"] == "bfloat16" for e in ar_h), ar_h
        sg_h = [
            e for e in t._events
            if e["ph"] == "X" and e["name"] == "comm.sync_grads"
        ]
        assert sg_h[0]["args"]["coalesced_leaves"] == 6, sg_h[0]["args"]
        tracing.clear()
        for key in sorted(grads):
            cast = grads[key].astype(ml_dtypes.bfloat16)
            ref = ring.all_reduce(cast, op="avg").astype(np.float32)
            assert np.array_equal(np.asarray(out_h[key]), ref), key

        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def facade_worker(rank: int, world: int, name: str, q) -> None:
    """Exercise the torch-shaped facade in true multi-process mode."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import pytorch_distributed_tpu as ptd

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)
        assert ptd.get_backend() == "hostring"
        assert ptd.get_rank() == rank
        assert ptd.get_world_size() == world
        out = ptd.all_reduce(np.full(8, float(rank), np.float32))
        expect = sum(range(world))
        assert np.all(np.asarray(out) == expect), out
        g = ptd.all_gather(np.array([rank], np.int32))
        assert list(np.asarray(g).ravel()) == list(range(world))
        # torch>=1.13 flat variants: concatenation / per-rank chunk
        flat = ptd.all_gather_into_tensor(
            np.array([rank * 2, rank * 2 + 1], np.int32)
        )
        assert list(np.asarray(flat)) == list(range(2 * world)), flat
        rs = ptd.reduce_scatter_tensor(
            np.arange(world * 2, dtype=np.float32)
        )
        want = np.array([rank * 2, rank * 2 + 1], np.float32) * world
        assert np.array_equal(np.asarray(rs), want), (rs, want)
        b = ptd.broadcast(np.array([rank * 10.0], np.float32), src=2)
        assert float(np.asarray(b)[0]) == 20.0
        # object collectives: variable-size payloads per rank
        objs = ptd.all_gather_object({"rank": rank, "pad": "x" * (rank * 37)})
        assert [o["rank"] for o in objs] == list(range(world)), objs
        assert all(len(o["pad"]) == r * 37 for r, o in enumerate(objs))
        # non-src ranks may hold unpicklable locals — only src serializes
        local = ["from", rank] if rank == 1 else [lambda: None]
        got = ptd.broadcast_object_list(local, src=1)
        assert got == ["from", 1], got
        rd = ptd.reduce(np.full(4, float(rank), np.float32), dst=0)
        assert np.all(np.asarray(rd) == sum(range(world))), rd
        mine = ptd.scatter_object_list(
            [f"obj-{r}" for r in range(world)] if rank == 2 else None,
            src=2,
        )
        assert mine == f"obj-{rank}", mine
        ptd.monitored_barrier()  # group deadline applies
        try:  # tighter-than-group per-call timeout is a loud refusal
            ptd.monitored_barrier(timeout_s=0.001)
            raise AssertionError("tight monitored_barrier did not raise")
        except NotImplementedError:
            pass
        ptd.barrier()
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def _single_cpu_device_bootstrap():
    """Pin this process to ONE CPU device, before jax's first use.

    Every multihost worker needs the same dance: each "host" must expose
    exactly one local device, so scrub any inherited virtual-device-count
    flag (pytest's conftest sets 8) and force the cpu platform. Returns
    the configured jax module.
    """
    import re

    os.environ["JAX_PLATFORMS"] = "cpu"
    flags = re.sub(
        r"--xla_force_host_platform_device_count=\d+", "",
        os.environ.get("XLA_FLAGS", ""),
    ).strip()
    # a whitespace-only XLA_FLAGS FATALLY aborts XLA's flag parser
    # (it treats non--- tokens as flag-file names) — drop it instead
    if flags:
        os.environ["XLA_FLAGS"] = flags
    else:
        os.environ.pop("XLA_FLAGS", None)
    import jax

    jax.config.update("jax_platforms", "cpu")
    return jax


def multihost_worker(rank: int, world: int, port: int, q) -> None:
    """REAL jax.distributed rendezvous: N controller processes, each with
    one CPU device, forming a single global device world (the pod story
    on DCN, minus the TPUs)."""
    try:
        jax = _single_cpu_device_bootstrap()
        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.launch import init_multihost

        init_multihost(
            coordinator_address=f"localhost:{port}",
            num_processes=world,
            process_id=rank,
        )
        assert jax.process_count() == world, jax.process_count()
        assert jax.device_count() == world, jax.device_count()
        assert jax.local_device_count() == 1
        assert ptd.get_rank() == rank

        # a global computation over the pod-wide mesh: every process
        # contributes its local shard, jit emits the cross-process psum
        import jax.numpy as jnp
        from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

        mesh = Mesh(np.array(jax.devices()), ("dp",))
        sharding = NamedSharding(mesh, P("dp"))
        global_shape = (world, 4)
        local = np.full((1, 4), float(rank + 1), np.float32)
        arr = jax.make_array_from_single_device_arrays(
            global_shape, sharding,
            [jax.device_put(local, jax.local_devices()[0])],
        )
        total = jax.jit(
            lambda x: jnp.sum(x, axis=0),
            out_shardings=NamedSharding(mesh, P()),
        )(arr)
        want = sum(range(1, world + 1))
        # replicated output: this process's addressable shard IS the value
        got = np.asarray(total.addressable_shards[0].data)
        assert np.all(got == want), (got, want)

        # object collectives over the pod (process_allgather transport)
        objs = ptd.all_gather_object({"proc": rank, "pad": "y" * (rank * 13)})
        assert [o["proc"] for o in objs] == list(range(world)), objs
        got = ptd.broadcast_object_list([rank, "meta"], src=0)
        assert got == [0, "meta"], got

        # DataLoader pod assembly: shard=True fetches only this process's
        # contiguous block; shard=False fetches the FULL batch on every
        # process and must still yield the correct (not duplicated) global
        # batch. Either way this process's device shard of the global
        # array must be rows [rank*per:(rank+1)*per] of the global batch.
        from pytorch_distributed_tpu.data import ArrayDataset, DataLoader

        n, batch = 8, 4
        ds = ArrayDataset(x=np.arange(n * 3, dtype=np.float32).reshape(n, 3))
        for shard in (True, False):
            loader = DataLoader(
                ds, batch, shuffle=False, sharding=sharding, shard=shard,
            )
            b = next(iter(loader))["x"]
            assert b.shape == (batch, 3), (shard, b.shape)
            per = batch // world
            mine = np.asarray(b.addressable_shards[0].data)
            expect = np.arange(n * 3, dtype=np.float32).reshape(n, 3)[
                rank * per:(rank + 1) * per
            ]
            assert np.array_equal(mine, expect), (shard, mine, expect)

        jax.distributed.shutdown()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def multihost_ddp_worker(rank: int, world: int, port: int, q) -> None:
    """Pod-story DDP: each controller process ("host") feeds its local
    slice of the global batch; training must stay in lockstep — the same
    losses and bit-identical params on every host."""
    try:
        jax = _single_cpu_device_bootstrap()
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.launch import init_multihost
        from pytorch_distributed_tpu.parallel import DataParallel
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        init_multihost(
            coordinator_address=f"localhost:{port}",
            num_processes=world,
            process_id=rank,
        )
        ptd.init_process_group(mesh_spec=MeshSpec(dp=world))

        def apply_fn(params, x):
            return jnp.tanh(x @ params["w"]) @ params["v"]

        params = {
            "w": jnp.ones((4, 8)) * 0.1,
            "v": jnp.ones((8, 2)) * 0.1,
        }
        state = TrainState.create(
            apply_fn=apply_fn, params=params, tx=optax.sgd(0.1)
        )
        strategy = DataParallel()
        state = strategy.place(state)

        def step_fn(state, batch):
            def loss_fn(p):
                pred = state.apply_fn(p, batch["x"])
                return jnp.mean((pred - batch["y"]) ** 2)

            loss, grads = jax.value_and_grad(loss_fn)(state.params)
            return state.apply_gradients(grads), {"loss": loss}

        step = strategy.compile(step_fn, state)
        rng = np.random.default_rng(0)  # same stream on all hosts
        w_true = rng.normal(size=(4, 2)).astype(np.float32)
        losses = []
        for i in range(12):
            gx = rng.normal(size=(8, 4)).astype(np.float32)
            gy = (gx @ w_true).astype(np.float32)  # learnable target
            # this host's slice of the global batch (sampler contract)
            lo, hi = rank * 4, (rank + 1) * 4
            batch = strategy.shard_batch({"x": gx[lo:hi], "y": gy[lo:hi]})
            state, metrics = step(state, batch)
            losses.append(
                float(np.asarray(metrics["loss"].addressable_shards[0].data)
                      if hasattr(metrics["loss"], "addressable_shards")
                      else metrics["loss"])
            )
        w = np.asarray(state.params["w"].addressable_shards[0].data)
        q.put((rank, "ok", losses, w.tobytes()))
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
               None, None))


def multihost_ckpt_worker(rank: int, world: int, port: int, ckpt_dir: str,
                          q) -> None:
    """Pod-story checkpointing: every process writes ITS shards of the
    dp-sharded state; process 0 merges manifests and commits; restore
    reassembles each host's slice through make_array_from_callback."""
    try:
        jax = _single_cpu_device_bootstrap()
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.launch import init_multihost
        from pytorch_distributed_tpu.parallel import FSDP
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec
        from pytorch_distributed_tpu.train import TrainState
        from pytorch_distributed_tpu.train.checkpoint import (
            restore_checkpoint,
            save_checkpoint,
        )

        init_multihost(
            coordinator_address=f"localhost:{port}",
            num_processes=world,
            process_id=rank,
        )
        ptd.init_process_group(mesh_spec=MeshSpec(dp=world))

        def make_state(fill):
            params = {
                "big": jnp.full((8, 6), fill, jnp.float32)
                + jnp.arange(48.0).reshape(8, 6),
                "small": jnp.full((3,), fill, jnp.float32),
            }
            return TrainState.create(
                apply_fn=lambda p, x: x, params=params, tx=optax.sgd(0.1)
            )

        strategy = FSDP(axis="dp")
        state = strategy.place(make_state(1.0))
        save_checkpoint(ckpt_dir, state)

        template = strategy.place(make_state(0.0))
        restored = restore_checkpoint(
            ckpt_dir, template, strategy.state_shardings(template)
        )
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(state.params),
            jax.tree_util.tree_leaves_with_path(restored.params),
        ):
            ga = np.asarray(a.addressable_shards[0].data)
            gb = np.asarray(b.addressable_shards[0].data)
            assert np.array_equal(ga, gb), (pa, ga, gb)
        # both processes' shard files landed in the committed dir
        files = os.listdir(os.path.join(ckpt_dir, "latest"))
        has_p = {p for p in range(world)
                 if any(f".p{p}s" in f for f in files)}
        q.put((rank, "ok", sorted(has_p)))
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
               None))


def multihost_trainer_worker(rank: int, world: int, port: int, out_dir: str,
                             q) -> None:
    """The COMPLETE pod story through the stock stack: Trainer + DataLoader
    (per-process batch slices), eval, JSONL metrics, checkpoint —
    two controller processes, zero recipe-code changes."""
    try:
        jax = _single_cpu_device_bootstrap()
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
        from pytorch_distributed_tpu.launch import init_multihost
        from pytorch_distributed_tpu.parallel import DataParallel
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec
        from pytorch_distributed_tpu.train import (
            Trainer,
            TrainerConfig,
            TrainState,
            build_train_step,
        )

        init_multihost(
            coordinator_address=f"localhost:{port}",
            num_processes=world,
            process_id=rank,
        )
        ptd.init_process_group(mesh_spec=MeshSpec(dp=world))

        import flax.linen as nn

        class MLP(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Dense(2)(nn.tanh(nn.Dense(8)(x)))

        model = MLP()
        rng = np.random.default_rng(0)  # identical datasets on all hosts
        w_true = rng.normal(size=(4, 2)).astype(np.float32)
        x = rng.normal(size=(64, 4)).astype(np.float32)
        ds = ArrayDataset(image=x, label=(x @ w_true).astype(np.float32))

        def loss_fn(params, batch_stats, batch, _rng):
            pred = model.apply({"params": params}, batch["image"])
            loss = jnp.mean((pred - batch["label"]) ** 2)
            return loss, {"metrics": {"loss": loss}, "batch_stats": batch_stats}

        state = TrainState.create(
            apply_fn=model.apply,
            params=model.init(jax.random.key(0), x[:1])["params"],
            tx=optax.adam(1e-2),
        )
        strategy = DataParallel()

        def eval_step(state, batch):
            pred = model.apply({"params": state.params}, batch["image"])
            return {"loss": jnp.mean((pred - batch["label"]) ** 2)}

        trainer = Trainer(
            state,
            strategy,
            build_train_step(loss_fn),
            DataLoader(ds, 16, seed=3, sharding=strategy.batch_sharding()),
            eval_step=eval_step,
            eval_loader=DataLoader(
                ds, 16, shuffle=False, sharding=strategy.batch_sharding()
            ),
            config=TrainerConfig(
                epochs=8, log_every=2, handle_preemption=False,
                ckpt_dir=os.path.join(out_dir, "ckpt"),
                metrics_path=(
                    os.path.join(out_dir, f"metrics-p{rank}.jsonl")
                ),
            ),
        )
        final = trainer.fit()
        w = np.asarray(
            jax.tree_util.tree_leaves(final.params)[0]
            .addressable_shards[0].data
        )
        q.put((rank, "ok", trainer.last_eval_metrics["loss"],
               int(trainer.host_step), w.tobytes()))
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
               None, None, None))


def multihost_2d_fsdp_worker(rank: int, world: int, port: int, q) -> None:
    """A 2-D (dp x fsdp) mesh SPANNING processes: 4 single-device hosts
    form dp=2 x fsdp=2. Params shard over fsdp (cross-host all-gathers
    inside the step), batch shards over dp x fsdp — the real pod topology
    story beyond 1-D data parallelism. Trains two steps and checks the
    params stay in lockstep across every host's shard view."""
    try:
        jax = _single_cpu_device_bootstrap()
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.launch import init_multihost
        from pytorch_distributed_tpu.parallel import FSDP
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        init_multihost(
            coordinator_address=f"localhost:{port}",
            num_processes=world,
            process_id=rank,
        )
        assert world == 4
        ptd.init_process_group(mesh_spec=MeshSpec(dp=2, fsdp=2))

        def loss_fn(params, batch_stats, batch, rng):
            pred = jnp.tanh(batch["x"] @ params["w1"]) @ params["w2"]
            loss = jnp.mean((pred - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        rngs = np.random.default_rng(0)
        state = TrainState.create(
            apply_fn=lambda p, x: x,
            params={
                "w1": jnp.asarray(
                    rngs.normal(size=(8, 16)).astype(np.float32)
                ),
                "w2": jnp.asarray(
                    rngs.normal(size=(16, 2)).astype(np.float32)
                ),
            },
            tx=optax.sgd(0.05),
        )
        strategy = FSDP()
        state = strategy.place(state)
        # every param leaf must be genuinely sharded over fsdp: its
        # addressable shard is SMALLER than the global shape
        w1 = state.params["w1"]
        assert not w1.is_fully_addressable
        local = w1.addressable_shards[0].data.shape
        assert np.prod(local) < 8 * 16, local
        step = strategy.compile(build_train_step(loss_fn), state)

        # per-process CONTIGUOUS block of the dp x fsdp-sharded batch
        gb = 8
        x = rngs.normal(size=(gb, 8)).astype(np.float32)
        y = rngs.normal(size=(gb, 2)).astype(np.float32)
        per = gb // world
        batch = strategy.shard_batch(
            {"x": x[rank * per:(rank + 1) * per],
             "y": y[rank * per:(rank + 1) * per]}
        )
        for _ in range(2):
            state, metrics = step(state, batch)
        from pytorch_distributed_tpu.runtime.device import host_scalar

        loss = host_scalar(metrics["loss"])
        my_shard = np.asarray(
            state.params["w1"].addressable_shards[0].data
        )
        q.put((rank, "ok", loss, my_shard.tobytes(), my_shard.shape))
        jax.distributed.shutdown()
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}",
               None, None, None))


def reinit_worker(rank: int, world: int, name: str, q) -> None:
    """Rapid destroy + re-init cycles on the SAME group name: the
    per-init generation suffix must give every rendezvous a fresh shm
    segment (ADVICE r1 #2 — without it, a fast peer could attach the old
    segment before rank 0 unlinks it and split the group)."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import pytorch_distributed_tpu as ptd

        for cycle in range(3):
            ptd.init_process_group(
                "gloo", group_name=name, timeout_s=60.0
            )
            out = ptd.all_reduce(np.array([float(cycle + rank)], np.float32))
            want = world * cycle + sum(range(world))
            assert float(np.asarray(out)[0]) == want, (cycle, out)
            # NO barrier between cycles: destroy+init immediately, the
            # exact window the generation suffix exists for
            ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        q.put((rank, f"{type(e).__name__}: {e}"))


def overlap_parity_worker(rank: int, world: int, name: str, q) -> None:
    """The bucketed pipeline (sync_grads overlap=True, the default) is
    bit-identical to the legacy synchronous path — per leaf, including a
    slot-CHUNKED multi-MB leaf (split at exactly the ring's slot
    boundaries, so the per-element reduce order is the C loop's own) —
    and its comm.* spans land on a named comm-thread track with the
    exposed/hidden accounting wired. Also pins the q8 error-feedback
    mechanism: residuals make the k-call mean converge on the exact
    mean, which the legacy (residual-free) path cannot do."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp  # noqa: F401

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.ddp import sync_grads
        from pytorch_distributed_tpu.parallel.overlap import (
            get_engine,
            reset_engine,
        )
        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.distributed import (
            multiprocess_ring,
        )

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)
        ring = multiprocess_ring()
        rng = np.random.default_rng(3 + rank)
        grads = {
            f"t{i}": (rng.normal(size=(11 + i,)) * 2).astype(np.float32)
            for i in range(4)
        }
        grads["big"] = (rng.normal(size=(6000,)) * 2).astype(np.float32)
        # > one ring slot (4 MB): exercises the slot-aligned chunk items
        grads["huge"] = (rng.normal(size=(1_200_000,)) * 2).astype(
            np.float32
        )

        legacy_fn = jax.jit(lambda g: sync_grads(g, overlap=False))
        overlap_fn = jax.jit(lambda g: sync_grads(g, overlap=True))
        out_legacy = jax.tree_util.tree_map(np.asarray, legacy_fn(grads))
        tracing.configure(None)
        out_overlap = jax.tree_util.tree_map(np.asarray, overlap_fn(grads))
        t = tracing.get()
        for k in grads:
            assert np.array_equal(out_legacy[k], out_overlap[k]), k
        evs = [e for e in t._events if e.get("ph") == "X"]
        ar = [e for e in evs if e["name"] == "comm.all_reduce"]
        # 1 coalesced flat + big solo + huge as 2 slot chunks = 4
        assert len(ar) == 4, [e["args"]["count"] for e in ar]
        main_tid = None
        sg = [e for e in evs if e["name"] == "comm.sync_grads"]
        assert len(sg) == 1 and sg[0]["args"]["overlap"] is True, sg
        assert sg[0]["args"]["leaves"] == 6
        main_tid = sg[0]["tid"]
        # collectives issue from the comm thread, on a NAMED track
        assert all(e["tid"] != main_tid for e in ar), "ring on main thread"
        thread_names = {
            e["tid"]: e["args"]["name"] for e in t._events
            if e.get("ph") == "M" and e["name"] == "thread_name"
        }
        assert thread_names.get(ar[0]["tid"]) == "grad-sync-comm"
        drains = [e for e in evs if e["name"] == "comm.sync_drain"]
        assert len(drains) == 1 and drains[0]["tid"] == main_tid
        counters = {
            e["name"] for e in t._events if e.get("ph") == "C"
        }
        assert "comm.sync.exposed_s" in counters, counters
        assert "comm.sync.hidden_s" in counters, counters
        tracing.clear()
        stats = get_engine(ring).stats()
        assert stats["syncs"] == 1 and stats["comm_s"] > 0, stats
        assert stats["exposed_s"] <= stats["comm_s"] + 1e-9, stats

        # q8 first call: zero residual, overlap == legacy exactly
        q_legacy = jax.jit(
            lambda g: sync_grads(g, compress="int8", overlap=False)
        )
        q_overlap = jax.jit(
            lambda g: sync_grads(g, compress="int8", overlap=True)
        )
        reset_engine()  # fresh residuals
        o1 = np.asarray(q_overlap(grads)["big"])
        l1 = np.asarray(q_legacy(grads)["big"])
        assert np.array_equal(o1, l1), "first q8 call must match legacy"
        # error feedback: over k CONSTANT-gradient calls the mean of the
        # reduced outputs telescopes toward the exact mean (residual
        # carries each call's quantization error into the next), while
        # the legacy path repeats the same biased value forever
        rows = ring.all_gather(grads["big"])
        exact = rows.astype(np.float64).mean(axis=0)
        outs = [o1] + [
            np.asarray(q_overlap(grads)["big"]) for _ in range(7)
        ]
        ef_err = np.abs(np.mean(outs, axis=0) - exact).max()
        # the legacy path returns the IDENTICAL biased value every call
        # (no residual), so its k-call mean never improves; EF's mean
        # error floors at the UNCOMPENSATED second-stage requantization
        # of the reduced segment (DESIGN.md §19) — better, not zero
        legacy_err = np.abs(l1 - exact).max()
        assert ef_err < legacy_err * 0.8, (ef_err, legacy_err)
        assert not np.array_equal(outs[1], o1), "residual never engaged"
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def overlap_accum_worker(rank: int, world: int, name: str, q) -> None:
    """build_train_step(overlap_accum=True): the hoisted host loop is
    BIT-IDENTICAL to the scanned step + synchronous sync (same left-fold
    accumulation, same power-of-two scale, same ring calls), the
    microbatch schedule stays lockstep across ranks and last-ulp close,
    and each of the three programs compiles exactly once."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.overlap import reset_engine
        from pytorch_distributed_tpu.runtime.distributed import (
            multiprocess_ring,
        )
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)

        def loss_fn(params, batch_stats, batch, rng):
            pred = jnp.tanh(batch["x"] @ params["w"]) @ params["v"]
            loss = jnp.mean((pred - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        ri = np.random.default_rng(0)  # same init on every rank
        init = {
            "w": ri.normal(size=(16, 40)).astype(np.float32),
            "v": ri.normal(size=(40, 4)).astype(np.float32),
        }

        def mkstate():
            return TrainState.create(
                apply_fn=lambda p, x: x,
                params={k: jnp.asarray(v) for k, v in init.items()},
                tx=optax.sgd(0.125),  # power-of-two lr: every
                # contractible multiply is exact, so bit-identity holds
                # across differently-fused programs (DESIGN.md §19)
            )

        def batch_for(step):  # per-rank shard of a global batch
            r = np.random.default_rng(100 + step * world + rank)
            return {
                "x": r.normal(size=(8, 16)).astype(np.float32),
                "y": r.normal(size=(8, 4)).astype(np.float32),
            }

        def run(step_fn, steps=4):
            s = mkstate()
            for t in range(steps):
                s, m = step_fn(s, batch_for(t))
            return np.concatenate([
                np.asarray(s.params[k]).ravel() for k in sorted(init)
            ]), float(np.asarray(m["loss"]))

        os.environ["PTD_GRAD_SYNC"] = "legacy"
        scan_params, scan_loss = run(
            jax.jit(build_train_step(loss_fn, accum_steps=4))
        )
        del os.environ["PTD_GRAD_SYNC"]
        host = build_train_step(loss_fn, accum_steps=4,
                                overlap_accum=True)
        host_params, host_loss = run(host)
        assert np.array_equal(scan_params, host_params), (
            np.abs(scan_params - host_params).max()
        )
        assert host.compile_counts() == {"prep": 1, "grad": 1,
                                         "apply": 1}
        assert host.last_sync_stats is not None
        st = host.last_sync_stats
        assert st["comm_s"] > 0
        assert st["exposed_s"] <= st["comm_s"] + 1e-9

        reset_engine()
        mb = build_train_step(loss_fn, accum_steps=4,
                              overlap_accum=True,
                              reduce_schedule="microbatch")
        mb_params, _ = run(mb)
        # different summation association (per-mb ring then fixed-order
        # host fold): last-ulp close, never bit-guaranteed
        np.testing.assert_allclose(mb_params, scan_params,
                                   rtol=2e-5, atol=2e-6)
        # ...but STRICTLY lockstep across ranks
        ring = multiprocess_ring()
        rows = ring.all_gather(mb_params)
        assert all(np.array_equal(rows[0], rows[i])
                   for i in range(world)), "mb schedule diverged"
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def overlap_chaos_worker(rank: int, world: int, name: str, q) -> None:
    """A rank SIGKILLed MID-PIPELINE (the comm.overlap_stall fault site,
    mode=kill between bucket reduces) must leave the survivors
    recoverable: their next drain raises instead of hanging forever, the
    poisoned engine refuses further work, and after re-meshing onto a
    fresh ring + reset_engine() the survivors train on in lockstep —
    the same fresh-ring recovery shape the elastic membership layer
    commits (runtime/membership.py)."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.overlap import (
            get_engine,
            reset_engine,
        )
        from pytorch_distributed_tpu.runtime import faults
        from pytorch_distributed_tpu.runtime.distributed import (
            multiprocess_ring,
        )

        victim = world - 1
        if rank == victim:
            # die between the 2nd sync's bucket reduces — deterministic
            faults.configure(
                "comm.overlap_stall:mode=kill,after=2", seed=0
            )
        ptd.init_process_group("gloo", group_name=name, timeout_s=6.0)
        ring = multiprocess_ring()
        engine = get_engine(ring)
        rng = np.random.default_rng(5 + rank)
        leaves = [
            (rng.normal(size=(200_000,)) * 2).astype(np.float32),
            np.ones(64, np.float32) * rank,
        ]
        specs = [(x.shape, x.dtype) for x in leaves]

        def one_sync(eng):
            sess = eng.begin_accum(specs)
            sess.finish(leaves, scale=1.0)
            return sess.drain()

        one_sync(engine)  # sync 1 completes everywhere
        try:
            one_sync(engine)  # victim dies mid-sync-2
            # on a lucky schedule the victim's death can land after the
            # survivors' sync 2 completed; the NEXT sync must then fail
            one_sync(engine)
            raise AssertionError("survivor never saw the peer death")
        except RuntimeError as e:
            assert "re-mesh" in str(e) or "pipeline" in str(e), e
        # the poisoned pipeline refuses further work LOUDLY
        try:
            one_sync(engine)
            raise AssertionError("poisoned engine accepted work")
        except RuntimeError as e:
            assert "poisoned" in str(e), e
        # re-mesh the survivors on a fresh ring (what the elastic
        # membership commit does) + a fresh engine
        ptd.destroy_process_group()
        reset_engine()
        os.environ["RANK"] = str(rank)  # survivors keep their ranks:
        os.environ["WORLD_SIZE"] = str(world - 1)  # victim was last
        ptd.init_process_group(
            "gloo", group_name=name + "_b", timeout_s=60.0
        )
        ring2 = multiprocess_ring()
        engine2 = get_engine(ring2)
        out, _ = one_sync(engine2)
        rows = ring2.all_gather(out[0])
        assert all(
            np.array_equal(rows[0], rows[i]) for i in range(world - 1)
        ), "survivors diverged after re-mesh"
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def overlap_ef_worker(rank: int, world: int, name: str, q) -> None:
    """Loss-curve parity (ROADMAP item 1): training with
    sync_grads(compress='int8') + error feedback tracks the f32 run's
    loss curve at a pinned tolerance over a real descent."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.overlap import reset_engine
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)

        ri = np.random.default_rng(0)
        w_true = ri.normal(size=(12, 3)).astype(np.float32)
        init = {"w": np.zeros((12, 3), np.float32)}

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean(
                (batch["x"] @ params["w"] - batch["y"]) ** 2
            )
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        def batch_for(step):
            r = np.random.default_rng(50 + step * world + rank)
            x = r.normal(size=(16, 12)).astype(np.float32)
            return {"x": x, "y": (x @ w_true).astype(np.float32)}

        def run(compress):
            reset_engine()  # residuals must not leak across runs
            step = jax.jit(build_train_step(
                loss_fn, grad_compression=compress
            ))
            s = TrainState.create(
                apply_fn=lambda p, x: x,
                params={"w": jnp.asarray(init["w"])},
                tx=optax.sgd(0.05),
            )
            losses = []
            for t in range(30):
                s, m = step(s, batch_for(t))
                losses.append(float(np.asarray(m["loss"])))
            return np.asarray(losses)

        f32 = run(None)
        q8 = run("int8")
        assert f32[-1] < f32[0] * 0.2, "reference run failed to descend"
        # pinned parity: the compressed curve tracks f32 within 3%
        # relative at every step past the first few
        rel = np.abs(q8[3:] - f32[3:]) / np.maximum(f32[3:], 1e-6)
        assert rel.max() < 0.03, (rel.max(), q8[-3:], f32[-3:])
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def overlap_trace_worker(rank: int, world: int, name: str, q,
                         trace_dir: str) -> None:
    """Traced overlapped syncs for the trace_merge alignment test: the
    comm thread's collectives keep lockstep ISSUE order across ranks
    (the deterministic bucket queue), so the k-th comm.* occurrence per
    rank is the same collective — straggler skew stays computable."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import time as _time

        import jax

        jax.config.update("jax_platforms", "cpu")

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel.overlap import get_engine
        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.distributed import (
            multiprocess_ring,
        )

        tracer = tracing.configure(trace_dir)
        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)
        ring = multiprocess_ring()
        engine = get_engine(ring)
        rng = np.random.default_rng(9)
        leaves = [
            rng.normal(size=(150_000,)).astype(np.float32),
            rng.normal(size=(30_000,)).astype(np.float32),
        ]
        specs = [(x.shape, x.dtype) for x in leaves]
        for i in range(4):
            _time.sleep(0.002 * rank)  # real straggle, visible skew
            sess = engine.begin_accum(specs)
            sess.finish(leaves, scale=1.0)
            sess.drain()
        ptd.destroy_process_group()
        fname = "trace.json" if rank == 0 else f"trace-rank{rank}.json"
        tracer.export(os.path.join(trace_dir, fname))
        tracing.clear()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def hetero_microbatch_worker(rank: int, world: int, name: str, q) -> None:
    """r15 HostLoopStep.set_microbatch_plan over a live 2-proc ring:
    (a) an EVEN plan (local == total/world, contiguous offsets) is
    bit-identical to the default path — the plan machinery itself adds
    no arithmetic; (b) an UNEVEN plan (balance.microbatch_counts over a
    2:1 rate skew -> [4, 2] of 6) over the SAME global microbatches is
    deterministic (two runs, identical bits) and last-ulp close to the
    even split (per-rank partial sums regroup the summation — the
    documented non-bit-exact scope); (c) the collective sequence stays
    lockstep with uneven counts: both ranks finish every step."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        os.environ["RANK"] = str(rank)
        os.environ["WORLD_SIZE"] = str(world)
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp
        import optax

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )
        from pytorch_distributed_tpu.train import balance

        ptd.init_process_group("gloo", group_name=name, timeout_s=120.0)

        def loss_fn(params, batch_stats, batch, rng):
            pred = jnp.tanh(batch["x"] @ params["w"]) @ params["v"]
            loss = jnp.mean((pred - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        ri = np.random.default_rng(0)  # same init on every rank
        init = {
            "w": ri.normal(size=(16, 40)).astype(np.float32),
            "v": ri.normal(size=(40, 4)).astype(np.float32),
        }

        def mkstate():
            return TrainState.create(
                apply_fn=lambda p, x: x,
                params={k: jnp.asarray(v) for k, v in init.items()},
                tx=optax.sgd(0.125),  # power-of-two lr (DESIGN.md §19)
            )

        TOTAL, MB = 6, 8  # 6 global microbatches of 8 rows each

        def global_mb(step, j):  # microbatch j is the same whoever
            r = np.random.default_rng(1000 + step * TOTAL + j)  # owns it
            return {
                "x": r.normal(size=(MB, 16)).astype(np.float32),
                "y": r.normal(size=(MB, 4)).astype(np.float32),
            }

        def batch_for(step, offset, local):
            mbs = [global_mb(step, offset + i) for i in range(local)]
            return {
                k: np.concatenate([m[k] for m in mbs]) for k in ("x", "y")
            }

        def run(counts, accum_build):
            offset = sum(counts[:rank])
            local = counts[rank]
            host = build_train_step(loss_fn, accum_steps=accum_build,
                                    overlap_accum=True)
            host.set_microbatch_plan(local, TOTAL, offset)
            s = mkstate()
            for t in range(3):
                s, _ = host(s, batch_for(t, offset, local))
            return np.concatenate([
                np.asarray(s.params[k]).ravel() for k in sorted(init)
            ])

        even = [TOTAL // world] * world
        # default path (no plan): rank covers its contiguous run via the
        # SAME per-rank batches, keyed 0..local-1 — the plan's even form
        # must be bit-identical to it
        host0 = build_train_step(loss_fn, accum_steps=TOTAL // world,
                                 overlap_accum=True)
        s = mkstate()
        for t in range(3):
            s, _ = host0(
                s, batch_for(t, sum(even[:rank]), even[rank])
            )
        default_params = np.concatenate([
            np.asarray(s.params[k]).ravel() for k in sorted(init)
        ])
        even_params = run(even, TOTAL // world)
        # loss_fn ignores rng, so the offset-keyed grads match the
        # index-keyed default bit for bit
        assert np.array_equal(default_params, even_params), (
            np.abs(default_params - even_params).max()
        )
        uneven = balance.microbatch_counts(TOTAL, [2.0, 1.0])
        assert uneven == [4, 2], uneven
        u1 = run(uneven, TOTAL // world)
        u2 = run(uneven, TOTAL // world)
        assert np.array_equal(u1, u2)  # deterministic
        # same global microbatches, regrouped partial sums: last-ulp
        np.testing.assert_allclose(u1, even_params, rtol=2e-5, atol=2e-6)
        assert not np.array_equal(u1, np.zeros_like(u1))
        ptd.destroy_process_group()
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def disagg_migration_worker(rank: int, world: int, name: str, q) -> None:
    """r18 cross-process KV migration: a prefill-role engine on rank 0
    ships MigrationFrames over the ring's REAL P2P mailboxes to a
    decode-role engine on rank 1. The receiving side pins the whole
    wire contract: the page-table splice lands the exact payload bytes
    in the adopted pages, int8 payloads carry their native (int8 +
    f32-scale) accounting at <= 0.55x the f32 frame cost, a signature
    mismatch is REFUSED before anything is used, and every finished
    stream is bit-identical to the solo engine's."""
    try:
        os.environ["JAX_PLATFORMS"] = "cpu"
        import jax

        jax.config.update("jax_platforms", "cpu")
        import jax.numpy as jnp

        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config,
            GPT2LMHead,
        )
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup
        from pytorch_distributed_tpu.serve import (
            EngineConfig,
            MigrationError,
            Request,
            RequestStatus,
            ServeEngine,
            extract_frames,
            frame_f32_nbytes,
            frame_nbytes,
            recv_frame,
            send_frame,
        )

        cfg = GPT2Config(
            vocab_size=211, n_positions=96, hidden_size=32, num_layers=2,
            num_heads=2, dropout_rate=0.0, kv_cache_quantize="int8",
        )
        model = GPT2LMHead(cfg)
        # identical init on both ranks: key(0) is the shared-model story
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        ecfg = dict(num_slots=4, max_len=96, prefill_chunk=8)
        prng = np.random.default_rng(7)
        prompts = [
            prng.integers(1, 211, size=n).astype(np.int32)
            for n in (5, 8, 13, 21)  # mixes page-aligned + ragged tails
        ]
        reqs = [
            Request(
                p, max_new_tokens=10, request_id=f"mig-{i}",
                temperature=(0.8 if i % 2 else 0.0),
                top_k=(20 if i % 2 else None), seed=100 + i,
            )
            for i, p in enumerate(prompts)
        ]
        with HostRingGroup(name, rank, world, timeout_s=120) as ring:
            if rank == 0:
                eng = ServeEngine(
                    model, params,
                    EngineConfig(role="prefill", engine_id="p0", **ecfg),
                )
                hs = [eng.submit(r) for r in reqs]
                eng.run_until_drained()
                assert all(
                    h.status is RequestStatus.MIGRATED for h in hs
                ), [h.status for h in hs]
                frames = list(eng.outbox)
                assert len(frames) == len(reqs), len(frames)
                ring.send(np.array([len(frames)], np.int64), dst=1)
                for fr in frames:
                    send_frame(ring, fr, dst=1)
                # one duplicate for the receiver's wrong-signature check
                send_frame(ring, frames[0], dst=1)
            else:
                eng = ServeEngine(
                    model, params,
                    EngineConfig(role="decode", engine_id="d0", **ecfg),
                )
                per_page = frame_nbytes(eng.pool.cache)
                # int8 payload accounting: native frame <= 0.55x the f32
                # frame (this model: (1 + 4/16) / 4 = 0.3125x)
                assert per_page * 100 <= 55 * frame_f32_nbytes(
                    eng.pool.cache
                ), (per_page, frame_f32_nbytes(eng.pool.cache))
                n = int(ring.recv(np.zeros(1, np.int64), src=0)[0])
                assert n == len(reqs), n
                handles = {}
                for _ in range(n):
                    fr = recv_frame(ring, 0, eng.migration_signature)
                    assert fr.payload.nbytes == fr.n_pages * per_page
                    h = eng.inject_migration(fr)
                    eng._drain_inject_backlog()  # splice NOW, pre-tick
                    # page-table splice: the adopted pages hold the wire
                    # bytes verbatim (no decode has touched them yet)
                    lease = h._lease
                    got = extract_frames(
                        eng.pool.cache,
                        list(lease.page_row[: fr.n_pages]),
                    )
                    assert got.tobytes() == np.asarray(
                        fr.payload, np.uint8
                    ).tobytes(), fr.request_id
                    handles[fr.request_id] = h
                # fingerprint refusal over the real wire: a receiver
                # expecting different pool geometry never uses the frame
                try:
                    recv_frame(ring, 0, "ps=1|bogus:(1,):int8")
                    raise AssertionError("signature mismatch accepted")
                except MigrationError:
                    pass
                eng.run_until_drained()
                # parity: every migrated stream == the solo engine's
                solo = ServeEngine(model, params, EngineConfig(**ecfg))
                solo_hs = [solo.submit(r) for r in reqs]
                solo.run_until_drained()
                for r, sh in zip(reqs, solo_hs):
                    h = handles[r.request_id]
                    assert h.status is RequestStatus.COMPLETED, (
                        r.request_id, h.status, h.error,
                    )
                    assert h.tokens == sh.tokens, (
                        r.request_id, h.tokens, sh.tokens,
                    )
            ring.barrier()  # neither side exits before the other checks
        q.put((rank, "ok"))
    except Exception as e:  # pragma: no cover - reported via queue
        import traceback

        q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))
