"""Failure detection & elastic recovery (SURVEY.md §5): preemption
checkpoint-restart and the hang watchdog."""

import os
import signal
import threading
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_tpu.train.elastic import (
    Preempted,
    PreemptionHandler,
    Watchdog,
)


def test_preemption_handler_latches_sigterm():
    with PreemptionHandler() as h:
        assert not h.requested
        os.kill(os.getpid(), signal.SIGTERM)
        deadline = time.monotonic() + 5
        while not h.requested and time.monotonic() < deadline:
            time.sleep(0.01)
        assert h.requested
        h.reset()
        assert not h.requested
    # uninstalled: default disposition restored (we can't raise SIGTERM to
    # prove it without dying; check the registered handler instead)
    assert signal.getsignal(signal.SIGTERM) is not h._on_signal


def test_watchdog_fires_on_stall_and_rearms():
    fired = []
    wd = Watchdog(0.2, on_stall=fired.append, poll_s=0.05,
                  first_grace_s=0.2)
    with wd:
        time.sleep(0.5)
        assert wd.stalled and len(fired) >= 1
        n = len(fired)
        wd.tick()
        time.sleep(0.1)
        assert len(fired) == n  # re-armed, not spamming


def test_watchdog_quiet_while_ticking():
    fired = []
    wd = Watchdog(0.4, on_stall=fired.append, poll_s=0.05)
    with wd:
        for _ in range(10):
            wd.tick()
            time.sleep(0.05)
    assert not fired and not wd.stalled


def _tiny_trainer(tmp_path, epochs, **cfg_kw):
    from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.train import (
        Trainer,
        TrainerConfig,
        TrainState,
        build_train_step,
        classification_loss_fn,
    )
    import pytorch_distributed_tpu as ptd

    if not ptd.is_initialized():
        ptd.init_process_group()
    model = ResNet(stage_sizes=[1], block_cls=BasicBlock, num_classes=4,
                   width=8, stem="cifar")
    variables = model.init(jax.random.key(0), jnp.zeros((1, 8, 8, 3)),
                           train=False)
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"],
        tx=optax.sgd(0.05), batch_stats=variables["batch_stats"],
    )
    rng = np.random.default_rng(3)
    ds = ArrayDataset(
        image=rng.normal(size=(64, 8, 8, 3)).astype(np.float32),
        label=rng.integers(4, size=(64,)).astype(np.int32),
    )
    strategy = DataParallel()
    return Trainer(
        state, strategy,
        build_train_step(classification_loss_fn(model)),
        DataLoader(ds, 8, seed=0),
        config=TrainerConfig(
            epochs=epochs, log_every=0, ckpt_dir=str(tmp_path), **cfg_kw
        ),
    )


def _kill_at_step(trainer, min_step):
    """Progress-gated SIGTERM thread: fires as soon as ``min_step`` train
    steps have completed so fit() can neither finish first nor be killed
    before starting. Polls ``trainer.host_step`` (plain int) — reading
    trainer.state.step from this thread would touch buffers donated into
    the in-flight compiled step and raise."""

    def kill():
        deadline = time.monotonic() + 120
        while time.monotonic() < deadline:
            if trainer.host_step >= min_step:
                os.kill(os.getpid(), signal.SIGTERM)
                return
            time.sleep(0.02)

    t = threading.Thread(target=kill, daemon=True)
    t.start()
    return t


@pytest.mark.slow
def test_trainer_preempt_checkpoint_resume(tmp_path):
    """SIGTERM mid-fit -> checkpoint written + Preempted raised; a fresh
    trainer resumes from the checkpoint and completes the run."""
    trainer = _tiny_trainer(tmp_path, epochs=50)
    killer = _kill_at_step(trainer, 1)
    try:
        with pytest.raises(Preempted) as ei:
            trainer.fit()
    finally:
        killer.join(timeout=5)
    stopped_at = ei.value.step
    assert stopped_at >= 1

    from pytorch_distributed_tpu.train.checkpoint import checkpoint_step

    assert checkpoint_step(str(tmp_path)) == stopped_at

    # resume: few epochs total so it finishes quickly
    resumed = _tiny_trainer(tmp_path, epochs=(stopped_at // 8) + 1)
    assert resumed.restore_checkpoint()
    state = resumed.fit()
    assert int(state.step) >= stopped_at


def test_fit_elastic_exit_code(tmp_path, monkeypatch):
    from pytorch_distributed_tpu.train.elastic import EX_TEMPFAIL, fit_elastic

    class FakeTrainer:
        def fit(self):
            raise Preempted(7)

    with pytest.raises(SystemExit) as ei:
        fit_elastic(FakeTrainer())
    assert ei.value.code == EX_TEMPFAIL


@pytest.mark.slow
def test_trainer_watchdog_wired(tmp_path):
    """stall_timeout_s config plumbs a live watchdog through fit()."""
    trainer = _tiny_trainer(tmp_path, epochs=1, stall_timeout_s=300.0)
    trainer.fit()
    assert trainer._watchdog is not None
    assert not trainer._watchdog.stalled

@pytest.mark.slow
def test_preempt_preserves_retention_and_best(tmp_path):
    """Preemption composes with retention + best tracking: SIGTERM mid-run,
    restart, and (a) resume picks the NEWEST checkpoint on disk, (b) the
    persisted best record stops the post-resume eval from demoting 'best',
    (c) retention pruning never left a zero-checkpoint window."""
    import json

    from pytorch_distributed_tpu.train import step_tags

    # seed a pre-crash best record with an unbeatable value; a resumed
    # trainer must load it and refuse to overwrite 'best'
    trainer = _tiny_trainer(
        tmp_path, epochs=50,
        ckpt_every_steps=2, keep_checkpoints=2,
        keep_best="loss", best_mode="min",
    )
    (tmp_path / "best_metric.json").write_text(json.dumps(
        {"metric": "loss", "mode": "min", "value": -1e9, "step": 0}
    ))

    killer = _kill_at_step(trainer, 3)  # past >= one retention save
    try:
        with pytest.raises(Preempted) as ei:
            trainer.fit()
    finally:
        killer.join(timeout=5)
    stopped_at = ei.value.step
    tags = step_tags(str(tmp_path))
    assert tags, "retention left no step checkpoints"

    resumed = _tiny_trainer(
        tmp_path, epochs=(stopped_at // 8) + 1,
        ckpt_every_steps=2, keep_checkpoints=2,
        keep_best="loss", best_mode="min",
    )
    assert resumed.restore_checkpoint()
    # (a) resumed from the newest checkpoint on disk (preemption 'latest'
    # is written at stopped_at, newer than any step tag)
    assert resumed.host_step == stopped_at
    # (b) the unbeatable pre-crash best survived the restore: a worse
    # post-resume eval must NOT demote it (no eval loader here, so drive
    # the eval hook directly with a worse value)
    assert resumed._best_value == -1e9
    resumed._maybe_save_best({"loss": 0.1})
    assert resumed._best_value == -1e9
    assert not (tmp_path / "best").exists()  # never wrote a worse one
    resumed.fit()
    rec = json.loads((tmp_path / "best_metric.json").read_text())
    assert rec["value"] == -1e9
