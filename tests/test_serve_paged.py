"""Paged KV pool + engine-tick speculative decoding (serve/, round 11).

Contracts under test, on top of test_serve.py's parity suite:

* page bookkeeping — refcounts, the shared free list, strict-FIFO
  head-of-line admission under page pressure, registry eviction — stays
  consistent through every lifecycle storm (``check_consistency`` after
  each), and shared pages are bitwise READ-ONLY (the copy-on-write
  discipline, checked by checksumming the device pages);
* prefix sharing changes memory and compute, never tokens: a request
  admitted onto shared pages emits exactly its solo ``generate`` stream;
* the bounded-compile-count invariant holds with pages AND speculation:
  one prefill program and one tick program per OCCUPIED length bucket
  (round 12's static bucket widths), each compiled exactly once, for
  any workload mix;
* greedy speculative output is BIT-IDENTICAL to solo generate (the
  verify accepts exactly the target's own argmax chain), sampled rows
  are deterministic given seeds, and mid-speculation eviction /
  cancellation / fault leaves both pools refcount-consistent.
"""

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.generation import generate
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.serve import (
    EngineConfig,
    PagedKVPool,
    Request,
    RequestStatus,
    ServeEngine,
    ServeTelemetry,
    SpecConfig,
    auto_page_size,
    prefix_shared_requests,
)
from pytorch_distributed_tpu.train.metrics import MetricsWriter, read_metrics

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


@pytest.fixture(scope="module")
def draft(gpt2):
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=16, num_layers=1,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _solo(model, params, req: Request):
    out = np.asarray(generate(
        model, params, jnp.asarray(req.prompt_ids[None]),
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k, top_p=req.top_p,
        rng=jax.random.PRNGKey(req.seed), eos_id=req.eos_id,
    ))[0, req.prompt_len:]
    toks = [int(x) for x in out]
    if req.eos_id is not None and req.eos_id in toks:
        toks = toks[: toks.index(req.eos_id) + 1]
    return toks


def _assert_bucketed_compiles(engine):
    """Round-12 bounded-compile contract: one program per OCCUPIED
    length bucket, each compiled exactly once, at most
    log2(max_pages) + 1 buckets per program kind."""
    assert engine.decode_compiles == len(engine.decode_buckets)
    assert engine.prefill_compiles == len(engine.prefill_buckets)
    cap = len(engine._buckets)
    assert 1 <= len(engine.decode_buckets) <= cap
    assert 1 <= len(engine.prefill_buckets) <= cap
    assert all(
        v == 1 for v in engine._decode_bucket_compiles.values()
    )
    assert all(
        v == 1 for v in engine._prefill_bucket_compiles.values()
    )


def _page_bytes(pool, pages):
    """Concatenated bytes of the given page frames across every
    KV-payload leaf — the read-only checksum for CoW tests."""
    from pytorch_distributed_tpu.generation import cache_batch_axis

    chunks = []
    for path, leaf in jax.tree_util.tree_leaves_with_path(pool.cache):
        ax = cache_batch_axis(path, leaf)
        if ax is None:
            continue
        arr = np.asarray(jnp.moveaxis(leaf, ax, 0)[np.array(pages)])
        chunks.append(arr.tobytes())
    return b"".join(chunks)


def test_auto_page_size():
    assert auto_page_size(256) == 32
    assert auto_page_size(48) == 16
    assert auto_page_size(40) == 8
    assert auto_page_size(63) == 1  # odd degenerates, still valid
    with pytest.raises(ValueError, match="page_size"):
        EngineConfig(num_slots=1, max_len=64, page_size=24)


def test_prefix_sharing_is_copy_free_and_exact(gpt2):
    """Second request with the same system prompt shares pages
    (refcount, zero prefill for the shared span), its tokens equal the
    solo run, and the shared pages' device bytes never change."""
    model, params = gpt2
    rng = np.random.default_rng(3)
    sys_p = rng.integers(1, 97, size=12).astype(np.int32)
    r1 = Request(
        np.concatenate([sys_p, rng.integers(1, 97, size=3).astype(np.int32)]),
        max_new_tokens=4,
    )
    r2 = Request(
        np.concatenate([sys_p, rng.integers(1, 97, size=5).astype(np.int32)]),
        max_new_tokens=5, temperature=0.8, top_k=9, seed=5,
    )
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=2, max_len=32, prefill_chunk=4, page_size=4,
    ))
    h1 = engine.submit(r1)
    engine.step()  # r1 admitted: capture its page row before release
    r1_pages = list(engine.scheduler.by_slot[h1.slot]._lease.page_row[:3])
    engine.run_until_drained()
    assert h1.tokens == _solo(model, params, r1)
    # r1 retired, but its three full prompt pages (12 tokens / 4) stay
    # registry-held for sharing
    shared_pages = r1_pages
    assert all(engine.pool._ref[pg] == 1 for pg in shared_pages)
    before = _page_bytes(engine.pool, shared_pages)
    h2 = engine.submit(r2)
    # admission must have mapped the registered pages into r2's table
    engine.step()
    lease = engine.scheduler.by_slot[h2.slot]._lease
    assert lease.shared_pages == 3 and lease.skip == 12
    assert list(lease.page_row[:3]) == shared_pages
    engine.run_until_drained()
    assert h2.status is RequestStatus.COMPLETED
    assert h2.tokens == _solo(model, params, r2)
    assert engine.pool.prefix_hits == 1
    assert engine.pool.shared_tokens == 12
    # copy-on-write discipline: the shared pages were never written
    assert _page_bytes(engine.pool, shared_pages) == before
    _assert_bucketed_compiles(engine)
    engine.pool.check_consistency()


def test_page_exhaustion_blocks_head_of_line(gpt2):
    """With pages for only one request in flight, the second queues
    (strict FIFO) until the first retires — and both stay solo-exact."""
    model, params = gpt2
    rng = np.random.default_rng(4)
    r1 = Request(rng.integers(1, 97, size=8).astype(np.int32),
                 max_new_tokens=8)
    r2 = Request(rng.integers(1, 97, size=8).astype(np.int32),
                 max_new_tokens=4)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=2, max_len=16, prefill_chunk=8, page_size=4,
        num_pages=5,  # one 16-slot request needs 4; two don't fit
    ))
    h1, h2 = engine.submit(r1), engine.submit(r2)
    engine.step()
    assert h1.status is RequestStatus.PREFILLING or h1.tokens
    assert h2.status is RequestStatus.QUEUED  # blocked on pages, not slots
    assert engine.pool.num_free >= 1
    engine.run_until_drained()
    assert h1.tokens == _solo(model, params, r1)
    assert h2.tokens == _solo(model, params, r2)
    engine.pool.check_consistency()


def test_registry_eviction_under_page_pressure(gpt2):
    """Registered prefix pages are evicted LRU when a new admission
    needs their frames — bookkeeping stays consistent throughout."""
    model, params = gpt2
    pool = PagedKVPool(
        model, params, num_slots=2, max_len=16, page_size=4,
        num_pages=6,
    )
    rng = np.random.default_rng(5)
    prompts = []
    # each retiree: P=9 -> span max(9+4, 12) = 13 -> 4 pages, 2 of them
    # full prompt pages that stay registry-held after free()
    for i in range(3):
        ids = rng.integers(1, 97, size=9).astype(np.int32)
        lease = pool.allocate(ids, max_new=4, chunk=4)
        assert lease is not None and lease.shared_pages == 0
        assert lease.n_pages == 4
        pool.register_prefix(lease, ids)   # as if prefill completed
        pool.free(lease.slot)
        prompts.append(ids)
        pool.check_consistency()
        if i == 1:
            # two retirees x 2 registered pages held; 2 frames free
            assert pool.pages_in_use == 4
    # the third retiree's allocate had only 2 free frames for its 4
    # needed and evicted exactly the OLDEST retiree's 2 registry
    # entries (LRU); the two newer retirees' pages remain held
    assert pool.pages_in_use == 4
    again = pool.allocate(prompts[2], max_new=4, chunk=4)
    assert again is not None and again.shared_pages == 2
    pool.check_consistency()
    # the evicted oldest prefix is gone — same prompt, no share (and
    # with `again` holding the last free frames, no pages either)
    gone = pool.allocate(prompts[0], max_new=4, chunk=4)
    assert gone is None
    pool.check_consistency()


def test_mid_flight_eviction_releases_only_private_pages(gpt2):
    """Cancelling one of two prefix-sharing requests mid-decode drops
    its private pages but the shared frames survive for the sibling."""
    model, params = gpt2
    rng = np.random.default_rng(6)
    sys_p = rng.integers(1, 97, size=8).astype(np.int32)

    def mk(new, **kw):
        return Request(
            np.concatenate(
                [sys_p, rng.integers(1, 97, size=3).astype(np.int32)]
            ),
            max_new_tokens=new, **kw,
        )

    engine = ServeEngine(model, params, EngineConfig(
        num_slots=3, max_len=32, prefill_chunk=4, page_size=4,
    ))
    seed_req = mk(2)
    hs = engine.submit(seed_req)
    engine.run_until_drained()  # registers the 2-page system prefix
    assert hs.status is RequestStatus.COMPLETED
    doomed = mk(20, request_id="doomed-paged")
    keeper = mk(6, temperature=0.7, top_p=0.9, seed=8)
    hd, hk = engine.submit(doomed), engine.submit(keeper)
    for _ in range(4):
        engine.step()
    assert hd.status is RequestStatus.DECODING
    shared = [
        pg for pg in engine.scheduler.by_slot[hd.slot]._lease.page_row[:2]
    ]
    assert engine.cancel("doomed-paged")
    engine.run_until_drained()
    assert hd.status is RequestStatus.CANCELLED
    assert hk.status is RequestStatus.COMPLETED
    assert hk.tokens == _solo(model, params, keeper)
    engine.pool.check_consistency()
    # the shared frames are still registry-held (refcount >= 1)
    for pg in shared:
        assert engine.pool._ref[pg] >= 1


def test_spec_greedy_parity_mixed_workload(gpt2, draft):
    """THE speculative acceptance test: greedy requests under a fused
    draft+verify tick emit bit-identical streams to solo generate,
    across slot reuse, chunked prefill, a cancellation and a
    fault-evicted victim — with ONE prefill and ONE tick compile."""
    model, params = gpt2
    dmodel, dparams = draft
    rng = np.random.default_rng(7)
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=3, max_len=64, prefill_chunk=4,
                     page_size=4),
        spec=SpecConfig(dmodel, dparams, num_draft_tokens=3),
    )

    def mk(p_len, new, **kw):
        return Request(
            prompt_ids=rng.integers(1, 97, size=p_len).astype(np.int32),
            max_new_tokens=new, **kw,
        )

    wave1 = [mk(5, 9), mk(9, 6), mk(3, 12), mk(7, 5)]
    victim = mk(6, 12, request_id="spec-victim")
    doomed = mk(6, 40, request_id="spec-doomed")
    wave2 = [mk(11, 6), mk(2, 7)]
    handles = {}
    with faults.injected(
        "serve.decode:mode=raise,count=1,match=spec-victim"
    ):
        for r in wave1 + [victim, doomed]:
            handles[r.request_id] = engine.submit(r)
        for _ in range(6):
            engine.step()
        for r in wave2:
            handles[r.request_id] = engine.submit(r)
        for _ in range(2):
            engine.step()
        assert engine.cancel("spec-doomed")
        engine.run_until_drained()
    assert handles["spec-victim"].status is RequestStatus.FAILED
    assert handles["spec-doomed"].status is RequestStatus.CANCELLED
    for r in wave1 + wave2:
        h = handles[r.request_id]
        assert h.status is RequestStatus.COMPLETED, h
        assert h.tokens == _solo(model, params, r), r.request_id
    # bounded compile count with pages + speculation: one prefill
    # program (target+draft fused) and one tick program (draft scan +
    # verify fused) per OCCUPIED length bucket, each compiled once
    _assert_bucketed_compiles(engine)
    assert engine.spec_verifies > 0
    assert 0 <= engine.spec_accepted <= engine.spec_drafted
    engine.pool.check_consistency()
    engine.draft_pool.check_consistency()


def test_spec_eos_truncates_inside_accepted_run(gpt2, draft):
    """A request whose eos lands mid-round stops at eos exactly like
    the solo stream (host-side truncation retires the row)."""
    model, params = gpt2
    dmodel, dparams = draft
    rng = np.random.default_rng(8)
    prompt = rng.integers(1, 97, size=5).astype(np.int32)
    ref = _solo(model, params, Request(prompt, max_new_tokens=10))
    eos = ref[4]  # fifth greedy token becomes the stop token
    req = Request(prompt, max_new_tokens=10, eos_id=eos)
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=1, max_len=32, prefill_chunk=8,
                     page_size=4),
        spec=SpecConfig(dmodel, dparams, num_draft_tokens=3),
    )
    h = engine.submit(req)
    engine.run_until_drained()
    assert h.status is RequestStatus.COMPLETED
    assert h.tokens == _solo(model, params, req)
    assert h.tokens[-1] == eos
    engine.pool.check_consistency()


def test_spec_sampled_rows_deterministic(gpt2, draft):
    """Sampled requests under speculation follow rejection sampling —
    not token-comparable to generate, but fully deterministic given
    seeds, completing with consistent pools."""
    model, params = gpt2
    dmodel, dparams = draft
    rng = np.random.default_rng(9)
    protos = [
        (rng.integers(1, 97, size=5).astype(np.int32), 8, 0.8, 12, None, 3),
        (rng.integers(1, 97, size=4).astype(np.int32), 6, 0.7, None, 0.9, 11),
        (rng.integers(1, 97, size=6).astype(np.int32), 7, 0.0, None, None, 0),
    ]
    runs = []
    for _ in range(2):
        engine = ServeEngine(
            model, params,
            EngineConfig(num_slots=2, max_len=64, prefill_chunk=4,
                         page_size=4),
            spec=SpecConfig(dmodel, dparams, num_draft_tokens=2),
        )
        hs = [
            engine.submit(Request(
                p, max_new_tokens=n, temperature=t, top_k=k, top_p=tp,
                seed=s,
            ))
            for p, n, t, k, tp, s in protos
        ]
        engine.run_until_drained()
        assert all(h.status is RequestStatus.COMPLETED for h in hs)
        runs.append([h.tokens for h in hs])
        engine.pool.check_consistency()
        engine.draft_pool.check_consistency()
    assert runs[0] == runs[1]
    # the greedy row rides the same tick and must STILL be solo-exact
    p, n = protos[2][0], protos[2][1]
    assert runs[0][2] == _solo(model, params, Request(p, max_new_tokens=n))


def test_spec_full_accept_round_leaves_no_draft_cache_hole():
    """A fully accepted round advances past position L+k — the final
    proposal's K/V must have been cached by the draft fill feed, or the
    draft attends a permanent zero hole forever after (the offline
    loop's documented dfill hazard; acceptance degrades silently while
    emitted tokens stay correct, so only this structural check — every
    position below the write cursor is written — catches it."""
    from pytorch_distributed_tpu.generation import cache_batch_axis
    from pytorch_distributed_tpu.serve import gather_pages

    # damped-tail target + first-block draft (the bench construction):
    # near-perfect agreement makes full-accept rounds routine
    cfg = GPT2Config(
        vocab_size=128, n_positions=96, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    blocks = params["blocks"]["block"]

    def damp(x):
        if x.ndim < 1 or x.shape[0] != cfg.num_layers:
            return x
        return x.at[1:].multiply(1e-3)

    db = dict(blocks)
    for name in ("attn_out", "mlp_down"):
        db[name] = jax.tree_util.tree_map(damp, blocks[name])
    params = dict(params)
    params["blocks"] = {"block": db}
    dcfg = dataclasses.replace(cfg, num_layers=1)
    dparams = dict(params)
    dparams["blocks"] = {
        "block": jax.tree_util.tree_map(lambda x: x[:1], db)
    }
    dmodel = GPT2LMHead(dcfg)

    k = 3
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=1, max_len=48, prefill_chunk=8,
                     page_size=4),
        spec=SpecConfig(dmodel, dparams, num_draft_tokens=k),
    )
    rng = np.random.default_rng(11)
    h = engine.submit(Request(
        rng.integers(1, 128, size=8).astype(np.int32),
        max_new_tokens=20,
    ))
    full_seen = False
    while not h.done and len(h.tokens) < 14:
        before = engine.spec_accepted
        engine.step()
        if engine.spec_accepted - before == k:
            full_seen = True
    assert full_seen, "no fully-accepted round — raise agreement"
    assert not h.done  # the slot (and its pages) must still be live
    slot = h.slot
    L = int(np.asarray(engine._lengths)[slot])
    dense = gather_pages(engine.draft_pool.cache, engine._dpt)
    for path, leaf in jax.tree_util.tree_leaves_with_path(dense):
        name = getattr(path[-1], "key", None) or str(path[-1])
        if name not in ("cached_key", "cached_value"):
            continue
        ax = cache_batch_axis(path, leaf)
        row = np.moveaxis(np.asarray(leaf), ax, 0)[slot]
        # row: [..., T, H, D] with T now the (ax-removed) leading+1 —
        # reduce every axis except the position axis
        pos_axis = ax  # after removing the batch axis, T sits at ax
        norms = np.abs(row).sum(
            axis=tuple(i for i in range(row.ndim) if i != pos_axis)
        )
        # every position below the write cursor holds REAL draft KV;
        # an unfixed engine leaves position L_old+k all-zero after a
        # full-accept round
        assert (norms[:L] > 0).all(), (
            name, np.nonzero(norms[:L] == 0)[0],
        )
    engine.run_until_drained()
    assert h.status is RequestStatus.COMPLETED


def test_spec_submit_validation(gpt2, draft):
    model, params = gpt2
    dmodel, dparams = draft
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=1, max_len=16, prefill_chunk=8,
                     page_size=4),
        spec=SpecConfig(dmodel, dparams, num_draft_tokens=4),
    )
    # 8 + 5 fits max_len 16, but the verify's 4 rejected-draft slots
    # past the horizon do not — refused up front, naming the tail
    with pytest.raises(ValueError, match="speculative-verify"):
        engine.submit(Request(np.ones(8, np.int32), max_new_tokens=5))
    with pytest.raises(ValueError, match="num_draft_tokens"):
        SpecConfig(dmodel, dparams, num_draft_tokens=0)


def test_snapshot_gauges_flow_through_writer(gpt2, draft, tmp_path):
    """Pool occupancy / prefix-hit / speculation gauges ride the same
    split='serve' snapshot records the engine always emitted."""
    model, params = gpt2
    dmodel, dparams = draft
    rng = np.random.default_rng(10)
    path = str(tmp_path / "serve.jsonl")
    writer = MetricsWriter(path)
    sys_p = rng.integers(1, 97, size=8).astype(np.int32)
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=2, max_len=32, prefill_chunk=4,
                     page_size=4, telemetry_every=2),
        spec=SpecConfig(dmodel, dparams, num_draft_tokens=2),
        telemetry=ServeTelemetry(writer=writer),
    )
    reqs = [
        Request(
            np.concatenate(
                [sys_p, rng.integers(1, 97, size=3).astype(np.int32)]
            ),
            max_new_tokens=6,
        )
        for _ in range(3)
    ]
    hs = [engine.submit(r) for r in reqs]
    engine.run_until_drained()
    writer.close()
    assert all(h.status is RequestStatus.COMPLETED for h in hs)
    snaps = [
        r for r in read_metrics(path) if r.get("event") == "snapshot"
    ]
    assert snaps
    last = snaps[-1]
    for key in ("pages_in_use", "pages_total", "page_occupancy",
                "prefix_hit_rate", "spec_verifies", "spec_drafted",
                "spec_accepted", "decode_gather_bytes",
                "decode_hbm_bytes_per_token"):
        assert key in last, key
    assert last["pages_total"] == engine.pool.num_pages
    # the last snapshot precedes any ticks after its cadence boundary
    assert 0 < last["spec_verifies"] <= engine.spec_verifies
    # later requests shared the seeded system prompt
    assert engine.pool.prefix_hits >= 1
    # ...and obs_report's Serving section renders the same gauges
    import io
    import sys as _sys

    _sys.path.insert(0, str(__import__("pathlib").Path(
        __file__).resolve().parent.parent / "scripts"))
    import obs_report

    buf = io.StringIO()
    obs_report.report(None, [path], out=buf)
    text = buf.getvalue()
    assert "== Serving ==" in text
    assert "kv pool: peak" in text and "prefix hit rate" in text
    assert "speculation:" in text and "accepted" in text
    assert "decode HBM:" in text and "bytes/token" in text


def test_prefix_shared_requests_builder():
    rng = np.random.default_rng(0)
    reqs = prefix_shared_requests(
        rng, 40, 97, prompt_len=(4, 8), new_tokens=(2, 4),
        prefix_share=0.5, shared_prefix_len=6,
    )
    assert len(reqs) == 40
    heads = {tuple(r.prompt_ids[:6]) for r in reqs if r.prompt_len >= 10}
    # the shared system prompt is ONE head repeated across sharers
    counts = {}
    for r in reqs:
        counts[tuple(r.prompt_ids[:6])] = counts.get(
            tuple(r.prompt_ids[:6]), 0
        ) + 1
    assert max(counts.values()) >= 10  # ~half of 40 share one prefix
    assert heads  # mixed lengths actually got the prefix
    with pytest.raises(ValueError, match="prefix_share"):
        prefix_shared_requests(rng, 2, 97, prefix_share=1.5)
    with pytest.raises(ValueError, match="shared_prefix_len"):
        prefix_shared_requests(rng, 2, 97, prefix_share=0.5)
