"""Config/CLI, profiler, and recipe-entry tests."""

import dataclasses
import os
import sys
from typing import Optional

import pytest

from pytorch_distributed_tpu.utils.config import RecipeConfig, parse_cli
from pytorch_distributed_tpu.utils.profiler import StepTimer, annotate, maybe_trace

RECIPES = os.path.join(os.path.dirname(__file__), "..", "recipes")
sys.path.insert(0, RECIPES)


# -- config ----------------------------------------------------------------


def test_parse_cli_defaults():
    cfg = parse_cli(RecipeConfig, [])
    assert cfg.epochs == 1
    assert cfg.backend is None
    assert cfg.dp == -1
    assert cfg.synthetic is False


def test_parse_cli_overrides():
    cfg = parse_cli(
        RecipeConfig,
        ["--epochs", "3", "--lr", "0.5", "--backend", "gloo", "--synthetic"],
    )
    assert cfg.epochs == 3
    assert cfg.lr == 0.5
    assert cfg.backend == "gloo"
    assert cfg.synthetic is True


def test_parse_cli_subclass_and_bool_negation():
    @dataclasses.dataclass
    class C(RecipeConfig):
        width: int = 64  # doc: model width
        flip: bool = True  # doc: flip augmentation

    cfg = parse_cli(C, ["--width", "128", "--no-flip"])
    assert cfg.width == 128
    assert cfg.flip is False
    assert cfg.epochs == 1  # inherited field still parsed


def test_parse_cli_optional_fields():
    cfg = parse_cli(RecipeConfig, ["--steps-per-epoch", "5"])
    assert cfg.steps_per_epoch == 5
    assert cfg.ckpt_dir is None


# -- profiler --------------------------------------------------------------


def test_step_timer_window():
    t = StepTimer(window=4)
    assert t.tick() is None  # first tick has no interval
    for _ in range(6):
        dt = t.tick()
        assert dt is not None and dt >= 0
    assert len(t.times) == 4  # window bound
    assert t.mean > 0
    assert t.percentile(0.5) >= 0
    s = t.summary()
    assert s["steps_timed"] == 4


def test_maybe_trace_noop_and_annotate():
    with maybe_trace(None):  # must be a no-op without a logdir
        with annotate("step"):
            pass


def test_maybe_trace_writes(tmp_path):
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jnp.ones((8, 8)).sum().block_until_ready()
    # a plugins/profile/<ts>/ dir with trace artifacts appears
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "profiler produced no trace files"


# -- recipe 2 entry --------------------------------------------------------


@pytest.mark.slow
def test_resnet50_imagenet_recipe_smoke():
    import resnet50_imagenet

    metrics = resnet50_imagenet.main(
        [
            "--backend", "gloo", "--synthetic", "--epochs", "1",
            "--steps-per-epoch", "2", "--batch-size", "16",
            "--image-size", "32", "--dp", "8", "--log-every", "1",
            "--warmup-epochs", "0", "--eval-samples", "32",
        ]
    )
    assert "accuracy" in metrics and "loss" in metrics


def test_imports_never_initialize_a_backend():
    """Importing the framework must not touch a device.

    On the axon relay a backend init dials the single-chip tunnel and can
    block for minutes when another process holds the lease; an import-time
    init (e.g. a module-level logger resolving jax.process_index(), the
    r2 regression this test pins) hangs every importer — including the
    driver's dryrun parent whose only job is to re-exec a CPU child.
    """
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import jax._src.xla_bridge as xb\n"
        # fail loudly if jax renames the internal this tripwire patches —
        # otherwise the assignment silently tests nothing
        "assert callable(getattr(xb, '_init_backend', None)), "
        "'jax moved _init_backend; update this tripwire'\n"
        "def _bomb(p):\n"
        "    print('INIT-BACKEND:', p, file=sys.stderr, flush=True)\n"
        "    raise SystemExit(7)\n"
        "xb._init_backend = _bomb\n"
        "import pytorch_distributed_tpu\n"
        "import pytorch_distributed_tpu.train\n"
        "import pytorch_distributed_tpu.parallel\n"
        "import pytorch_distributed_tpu.data\n"
        "import pytorch_distributed_tpu.models\n"
        "import pytorch_distributed_tpu.utils.profiler\n"
        "import pytorch_distributed_tpu.utils.config\n"
        "import pytorch_distributed_tpu.launch\n"
        "import pytorch_distributed_tpu.run\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0 and "CLEAN" in proc.stdout, proc.stderr[-2000:]


@pytest.mark.slow
def test_gpt2_recipe_pipeline_parallel_smoke():
    """Recipe 4 with --pp 2: a real transformer trains through the GPipe
    schedule from the recipe entry point (VERDICT r1 weak #5)."""
    import gpt2_zero1

    state = gpt2_zero1.main(
        [
            "--size", "tiny", "--pp", "2", "--epochs", "1",
            "--steps-per-epoch", "2", "--batch-size", "8",
            "--seq-len", "16", "--log-every", "1", "--sample", "4",
        ]
    )
    assert int(state.step) == 2


# -- torch.optim-shaped facade ---------------------------------------------


def test_optim_facade_matches_torch_sgd():
    """SGD with momentum+weight_decay+nesterov: trajectories match torch."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    w0 = np.random.default_rng(0).normal(size=(5,)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 1).normal(size=(5,)).astype(np.float32)
        for i in range(6)
    ]

    # torch reference
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.SGD(
        [tw], lr=0.1, momentum=0.9, weight_decay=0.01, nesterov=True
    )
    for g in grads:
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    tx = po.SGD(lr=0.1, momentum=0.9, weight_decay=0.01, nesterov=True)
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-5, atol=1e-6
    )


def test_optim_rmsprop_matches_torch():
    """RMSprop (centered + momentum + weight_decay): trajectories match
    torch — incl. torch's eps-outside-sqrt and zero-initialized v."""
    import jax
    import jax.numpy as jnp
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    w0 = np.random.default_rng(3).normal(size=(7,)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 10).normal(size=(7,)).astype(np.float32)
        for i in range(8)
    ]
    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = torch.optim.RMSprop(
        [tw], lr=0.05, alpha=0.95, eps=1e-7, weight_decay=0.02,
        momentum=0.8, centered=True,
    )
    for g in grads:
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()

    tx = po.RMSprop(
        lr=0.05, alpha=0.95, eps=1e-7, weight_decay=0.02, momentum=0.8,
        centered=True,
    )
    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    np.testing.assert_allclose(
        np.asarray(params["w"]), tw.detach().numpy(), rtol=1e-4, atol=1e-5
    )


@pytest.mark.slow  # r5 profile refit: the torch-pinned schedule trajectory tests stay fast
def test_optim_reduce_lr_on_plateau():
    """Stalled loss scales updates by factor after patience; an improving
    metric (mode='max') does not."""
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po

    tx = po.ReduceLROnPlateau(
        po.SGD(lr=0.1), factor=0.5, patience=2, accumulation_size=1
    )
    params = {"w": jnp.ones(3)}
    state = tx.init(params)
    mags = []
    for _ in range(8):
        updates, state = tx.update(
            {"w": jnp.ones(3)}, state, params, value=jnp.float32(1.0)
        )
        mags.append(abs(float(updates["w"][0])))
    np.testing.assert_allclose(mags[0], 0.1, rtol=1e-5)
    assert mags[-1] < 0.02, mags  # halved >= 3 times

    txm = po.ReduceLROnPlateau(
        po.SGD(lr=0.1), mode="max", factor=0.5, patience=2,
        accumulation_size=1,
    )
    state = txm.init(params)
    for i in range(8):  # steadily improving accuracy: never reduce
        updates, state = txm.update(
            {"w": jnp.ones(3)}, state, params, value=jnp.float32(i)
        )
    np.testing.assert_allclose(abs(float(updates["w"][0])), 0.1, rtol=1e-5)
    # a PLATEAUED max-metric must reduce (the abs-threshold max mode —
    # a negated rel threshold would misread near-constant as improving)
    state = txm.init(params)
    for _ in range(8):
        updates, state = txm.update(
            {"w": jnp.ones(3)}, state, params, value=jnp.float32(0.9)
        )
    assert abs(float(updates["w"][0])) < 0.05

    with np.testing.assert_raises(Exception):
        po.ReduceLROnPlateau(po.SGD(lr=0.1), mode="sideways")
    with np.testing.assert_raises_regex(ValueError, "loss"):
        tx.update({"w": jnp.ones(3)}, tx.init(params), params)


def test_plateau_loss_threads_through_train_step():
    """build_train_step feeds the loss into metric-driven optimizers: a
    constant-loss objective shrinks update magnitudes mid-training."""
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po
    from pytorch_distributed_tpu.train import TrainState

    tx = po.ReduceLROnPlateau(
        po.SGD(lr=0.1), factor=0.5, patience=1, accumulation_size=1
    )
    state = TrainState.create(
        apply_fn=None, params={"w": jnp.ones(3)}, tx=tx
    )
    deltas = []
    for _ in range(8):
        prev = np.asarray(state.params["w"]).copy()
        state = state.apply_gradients(
            {"w": jnp.ones(3)}, loss_value=jnp.float32(2.5)
        )
        deltas.append(abs(float(np.asarray(state.params["w"])[0] - prev[0])))
    np.testing.assert_allclose(deltas[0], 0.1, rtol=1e-5)
    assert deltas[-1] < 0.05, deltas


def test_optim_warm_restarts_matches_torch():
    """SGDR (T_mult 1 and 2) pinned against torch's scheduler."""
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    for t_mult in (1, 2):
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=0.3)
        sch = torch.optim.lr_scheduler.CosineAnnealingWarmRestarts(
            opt, T_0=4, T_mult=t_mult, eta_min=0.01
        )
        torch_lrs = []
        for _ in range(20):
            torch_lrs.append(opt.param_groups[0]["lr"])
            opt.step()
            sch.step()
        ours = po.CosineAnnealingWarmRestarts(
            0.3, T_0=4, T_mult=t_mult, eta_min=0.01
        )
        our_lrs = [float(ours(i)) for i in range(20)]
        np.testing.assert_allclose(
            our_lrs, torch_lrs, rtol=1e-5, atol=1e-7,
            err_msg=f"T_mult={t_mult}",
        )


def test_optim_clip_grad_value():
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po

    tx = po.clip_grad_value(po.SGD(lr=1.0), 0.5)
    params = {"w": jnp.zeros(3)}
    state = tx.init(params)
    updates, _ = tx.update(
        {"w": jnp.asarray([2.0, -3.0, 0.1])}, state, params
    )
    np.testing.assert_allclose(
        np.asarray(updates["w"]), [-0.5, 0.5, -0.1], rtol=1e-6
    )


def test_optim_schedules_shapes():
    from pytorch_distributed_tpu import optim as po

    s = po.StepLR(0.1, step_size=10, gamma=0.5)
    assert float(s(0)) == pytest.approx(0.1)
    assert float(s(10)) == pytest.approx(0.05)
    assert float(s(25)) == pytest.approx(0.025)
    c = po.CosineAnnealingLR(0.1, T_max=100)
    assert float(c(0)) == pytest.approx(0.1)
    assert float(c(100)) == pytest.approx(0.0, abs=1e-6)
    w = po.WarmupCosine(0.4, warmup_steps=5, total_steps=50)
    assert float(w(0)) == pytest.approx(0.0)
    assert float(w(5)) == pytest.approx(0.4)
    m = po.MultiStepLR(0.1, milestones=[3, 6])
    assert float(m(4)) == pytest.approx(0.01)
    assert float(m(7)) == pytest.approx(0.001)


def test_optim_adamw_trains():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import optim as po

    tx = po.clip_grad_norm(po.AdamW(lr=0.05), max_norm=1.0)
    params = {"w": jnp.ones((3,))}
    state = tx.init(params)

    def loss(p):
        return jnp.sum(p["w"] ** 2)

    for _ in range(60):
        g = jax.grad(loss)(params)
        updates, state = tx.update(g, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    assert float(loss(params)) < 0.2


def test_lr_schedules_match_torch():
    """ExponentialLR / LambdaLR / OneCycleLR against torch's schedulers."""
    import jax.numpy as jnp
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    # ExponentialLR
    ours = po.ExponentialLR(0.5, gamma=0.9)
    p = torch.nn.Parameter(torch.zeros(1))
    opt = torch.optim.SGD([p], lr=0.5)
    sch = torch.optim.lr_scheduler.ExponentialLR(opt, gamma=0.9)
    for step in range(5):
        np.testing.assert_allclose(
            float(ours(step)), opt.param_groups[0]["lr"], rtol=1e-6
        )
        opt.step()
        sch.step()

    # LambdaLR (a traceable warmup ramp)
    ours = po.LambdaLR(1.0, lambda c: jnp.minimum(1.0, (c + 1) / 4.0))
    opt = torch.optim.SGD([p], lr=1.0)
    sch = torch.optim.lr_scheduler.LambdaLR(
        opt, lambda c: min(1.0, (c + 1) / 4.0)
    )
    for step in range(6):
        np.testing.assert_allclose(
            float(ours(step)), opt.param_groups[0]["lr"], rtol=1e-6
        )
        opt.step()
        sch.step()

    # OneCycleLR: endpoints + peak vs torch (interpolation shapes differ
    # slightly: torch cos-anneals the warmup, ours is linear — same
    # envelope, identical start/peak/final values)
    total = 20
    ours = po.OneCycleLR(0.4, total, pct_start=0.25)
    vals = [float(ours(s)) for s in range(total + 1)]
    opt = torch.optim.SGD([p], lr=0.4)
    sch = torch.optim.lr_scheduler.OneCycleLR(
        opt, max_lr=0.4, total_steps=total, pct_start=0.25
    )
    torch_start = opt.param_groups[0]["lr"]
    for _ in range(total - 1):  # torch's last in-schedule index is total-1
        opt.step()
        sch.step()
    torch_final = opt.param_groups[0]["lr"]
    np.testing.assert_allclose(vals[0], torch_start, rtol=1e-5)
    # ours spends `total` steps reaching the same floor torch reaches at
    # total-1 (one-index phase offset; same start/peak/floor values)
    np.testing.assert_allclose(vals[-1], torch_final, rtol=1e-3)
    assert abs(max(vals) - 0.4) < 1e-6
    assert np.argmax(vals) == 5  # peak ends the pct_start warmup


def _torch_traj(make_opt, w0, grads):
    import torch

    tw = torch.nn.Parameter(torch.tensor(w0.copy()))
    opt = make_opt([tw])
    for g in grads:
        opt.zero_grad()
        tw.grad = torch.tensor(g.copy())
        opt.step()
    return tw.detach().numpy()


def _ours_traj(tx, w0, grads):
    import jax
    import jax.numpy as jnp
    import numpy as np

    params = {"w": jnp.asarray(w0)}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update({"w": jnp.asarray(g)}, state, params)
        params = jax.tree_util.tree_map(lambda p, u: p + u, params, updates)
    return np.asarray(params["w"])


def test_optim_adagrad_adadelta_radam_nadam_match_torch():
    """The second-tier torch.optim family, trajectory-pinned — incl.
    Adagrad's lr_decay schedule and NAdam's momentum_decay (psi)
    annealing, the part optax.nadam lacks."""
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    w0 = np.random.default_rng(0).normal(size=(5,)).astype(np.float32)
    grads = [
        np.random.default_rng(i + 1).normal(size=(5,)).astype(np.float32)
        for i in range(8)
    ]

    cases = [
        (
            lambda ps: torch.optim.Adagrad(
                ps, lr=0.1, lr_decay=0.05, weight_decay=0.01, eps=1e-10
            ),
            po.Adagrad(lr=0.1, lr_decay=0.05, weight_decay=0.01, eps=1e-10),
        ),
        (
            # non-tiny eps: distinguishes torch's sqrt(acc)+eps from
            # optax's rsqrt(acc+eps) — ~5x different first steps when
            # eps ~ acc
            lambda ps: torch.optim.Adagrad(
                ps, lr=0.1, eps=1e-2, initial_accumulator_value=0.1
            ),
            po.Adagrad(lr=0.1, eps=1e-2, initial_accumulator_value=0.1),
        ),
        (
            lambda ps: torch.optim.Adadelta(
                ps, lr=0.7, rho=0.85, eps=1e-6, weight_decay=0.02
            ),
            po.Adadelta(lr=0.7, rho=0.85, eps=1e-6, weight_decay=0.02),
        ),
        (
            lambda ps: torch.optim.RAdam(
                ps, lr=0.02, betas=(0.9, 0.99), eps=1e-8, weight_decay=0.01
            ),
            po.RAdam(lr=0.02, betas=(0.9, 0.99), eps=1e-8, weight_decay=0.01),
        ),
        (
            lambda ps: torch.optim.NAdam(
                ps, lr=0.01, betas=(0.9, 0.999), eps=1e-8,
                weight_decay=0.01, momentum_decay=4e-3,
            ),
            po.NAdam(lr=0.01, betas=(0.9, 0.999), eps=1e-8,
                     weight_decay=0.01, momentum_decay=4e-3),
        ),
    ]
    for make_topt, tx in cases:
        t = _torch_traj(make_topt, w0, grads)
        o = _ours_traj(tx, w0, grads)
        np.testing.assert_allclose(o, t, rtol=1e-4, atol=1e-5)


def test_optim_lars_matches_paper_reference():
    """LARS pinned against a NumPy transliteration of You et al. 2017's
    update; the no_decay mask keeps exempt tensors on plain SGD."""
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po

    rng = np.random.default_rng(7)
    w0 = {"kernel": rng.normal(size=(4, 3)).astype(np.float32),
          "bias": rng.normal(size=(3,)).astype(np.float32)}
    grads = [
        {"kernel": rng.normal(size=(4, 3)).astype(np.float32),
         "bias": rng.normal(size=(3,)).astype(np.float32)}
        for _ in range(5)
    ]
    lr, mom, wd, trust = 0.5, 0.9, 1e-4, 0.02

    # NumPy reference (per-tensor trust ratio; bias exempt -> plain SGD)
    ref = {k: v.copy() for k, v in w0.items()}
    vel = {k: np.zeros_like(v) for k, v in w0.items()}
    for g in grads:
        for k in ref:
            if k == "bias":
                local, adj = 1.0, g[k]
            else:
                wn = np.linalg.norm(ref[k])
                gn = np.linalg.norm(g[k])
                local = trust * wn / (gn + wd * wn)
                adj = g[k] + wd * ref[k]
            vel[k] = mom * vel[k] + lr * local * adj
            ref[k] = ref[k] - vel[k]

    tx = po.LARS(lr=lr, momentum=mom, weight_decay=wd,
                 trust_coefficient=trust, no_decay=(r"(^|/)bias$",))
    params = {k: jnp.asarray(v) for k, v in w0.items()}
    state = tx.init(params)
    for g in grads:
        updates, state = tx.update(
            {k: jnp.asarray(v) for k, v in g.items()}, state, params
        )
        params = {k: params[k] + updates[k] for k in params}
    for k in ref:
        np.testing.assert_allclose(
            np.asarray(params[k]), ref[k], rtol=1e-5, atol=1e-6
        )


def test_optim_lamb_matches_paper_reference():
    """LAMB pinned against a NumPy transliteration of You et al. 2019
    (Adam moments, bias correction, trust ratio over r + wd*w)."""
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po

    rng = np.random.default_rng(11)
    w0 = rng.normal(size=(6,)).astype(np.float32)
    grads = [rng.normal(size=(6,)).astype(np.float32) for _ in range(6)]
    lr, b1, b2, eps, wd = 0.1, 0.9, 0.99, 1e-6, 0.01

    ref = w0.copy()
    m = np.zeros_like(ref)
    v = np.zeros_like(ref)
    for t, g in enumerate(grads, start=1):
        m = b1 * m + (1 - b1) * g
        v = b2 * v + (1 - b2) * g * g
        m_hat = m / (1 - b1 ** t)
        v_hat = v / (1 - b2 ** t)
        r = m_hat / (np.sqrt(v_hat) + eps) + wd * ref
        wn = np.linalg.norm(ref)
        rn = np.linalg.norm(r)
        phi = wn / rn if (wn > 0 and rn > 0) else 1.0
        ref = ref - lr * phi * r

    tx = po.LAMB(lr=lr, betas=(b1, b2), eps=eps, weight_decay=wd)
    o = _ours_traj(tx, w0, grads)
    np.testing.assert_allclose(o, ref, rtol=1e-4, atol=1e-5)


def test_lr_schedules_second_tier_match_torch():
    """ConstantLR / MultiplicativeLR / PolynomialLR / CyclicLR /
    SequentialLR / ChainedScheduler pinned against torch step-for-step."""
    import jax.numpy as jnp
    import numpy as np
    import torch

    from pytorch_distributed_tpu import optim as po

    def torch_lrs(make_sch, lr, steps):
        p = torch.nn.Parameter(torch.zeros(1))
        opt = torch.optim.SGD([p], lr=lr)
        sch = make_sch(opt)
        out = []
        for _ in range(steps):
            out.append(opt.param_groups[0]["lr"])
            opt.step()
            sch.step()
        return np.asarray(out)

    def ours_lrs(schedule, steps):
        return np.asarray([float(schedule(s)) for s in range(steps)])

    cases = [
        (
            po.ConstantLR(0.3, factor=0.25, total_iters=4),
            lambda o: torch.optim.lr_scheduler.ConstantLR(
                o, factor=0.25, total_iters=4
            ),
            0.3,
        ),
        (
            po.MultiplicativeLR(0.2, lambda t: 0.9),
            lambda o: torch.optim.lr_scheduler.MultiplicativeLR(
                o, lambda t: 0.9
            ),
            0.2,
        ),
        (
            po.PolynomialLR(0.5, total_iters=6, power=2.0),
            lambda o: torch.optim.lr_scheduler.PolynomialLR(
                o, total_iters=6, power=2.0
            ),
            0.5,
        ),
        (
            po.CyclicLR(0.01, 0.1, step_size_up=3, step_size_down=5),
            lambda o: torch.optim.lr_scheduler.CyclicLR(
                o, base_lr=0.01, max_lr=0.1, step_size_up=3,
                step_size_down=5,
            ),
            0.01,
        ),
        (
            po.CyclicLR(0.01, 0.1, step_size_up=4, mode="triangular2"),
            lambda o: torch.optim.lr_scheduler.CyclicLR(
                o, base_lr=0.01, max_lr=0.1, step_size_up=4,
                mode="triangular2",
            ),
            0.01,
        ),
        (
            po.CyclicLR(0.01, 0.1, step_size_up=4, mode="exp_range",
                        gamma=0.95),
            lambda o: torch.optim.lr_scheduler.CyclicLR(
                o, base_lr=0.01, max_lr=0.1, step_size_up=4,
                mode="exp_range", gamma=0.95,
            ),
            0.01,
        ),
        (
            po.SequentialLR(
                [po.ConstantLR(0.4, factor=0.1, total_iters=3),
                 po.ExponentialLR(0.4, gamma=0.9)],
                milestones=[5],
            ),
            lambda o: torch.optim.lr_scheduler.SequentialLR(
                o,
                [torch.optim.lr_scheduler.ConstantLR(
                    o, factor=0.1, total_iters=3),
                 torch.optim.lr_scheduler.ExponentialLR(o, gamma=0.9)],
                milestones=[5],
            ),
            0.4,
        ),
        (
            po.ChainedScheduler(
                [po.ConstantLR(0.4, factor=0.5, total_iters=4),
                 po.ExponentialLR(1.0, gamma=0.9)]
            ),
            lambda o: torch.optim.lr_scheduler.ChainedScheduler(
                [torch.optim.lr_scheduler.ConstantLR(
                    o, factor=0.5, total_iters=4),
                 torch.optim.lr_scheduler.ExponentialLR(o, gamma=0.9)]
            ),
            0.4,
        ),
    ]
    for ours, make_t, lr in cases:
        t = torch_lrs(make_t, lr, 12)
        o = ours_lrs(ours, 12)
        np.testing.assert_allclose(o, t, rtol=1e-5, atol=1e-7)

    # jit-traceability: every schedule must work on a traced count
    import jax

    for ours, _, _ in cases:
        val = jax.jit(ours)(jnp.int32(7))
        assert np.isfinite(float(val))

    with np.testing.assert_raises(ValueError):
        po.CyclicLR(0.01, 0.1, mode="sawtooth")
    with np.testing.assert_raises(ValueError):
        po.SequentialLR([po.ExponentialLR(0.1, 0.9)], milestones=[2])
    with np.testing.assert_raises(ValueError):
        po.ChainedScheduler([])


def test_optim_param_groups_and_freezing():
    import jax
    import jax.numpy as jnp
    import numpy as np

    from pytorch_distributed_tpu import optim as po

    params = {
        "trunk": {"kernel": jnp.ones((2, 2))},
        "head": {"kernel": jnp.ones((2, 3)), "bias": jnp.ones((3,))},
    }
    ones = jax.tree_util.tree_map(jnp.ones_like, params)

    # two groups, different lrs; catch-all last
    tx = po.param_groups([
        ((r"head/",), po.SGD(0.5)),
        ((r".*",), po.SGD(0.1)),
    ])
    state = tx.init(params)
    updates, _ = tx.update(ones, state, params)
    np.testing.assert_allclose(np.asarray(updates["head"]["kernel"]), -0.5)
    np.testing.assert_allclose(np.asarray(updates["head"]["bias"]), -0.5)
    np.testing.assert_allclose(np.asarray(updates["trunk"]["kernel"]), -0.1)

    # torch semantics: params in NO group are never updated (frozen trunk)
    tx = po.param_groups([((r"head/",), po.SGD(0.5))])
    state = tx.init(params)
    updates, _ = tx.update(ones, state, params)
    np.testing.assert_allclose(np.asarray(updates["trunk"]["kernel"]), 0.0)
    np.testing.assert_allclose(np.asarray(updates["head"]["kernel"]), -0.5)

    # a single pattern string is accepted (common call shape)
    tx = po.param_groups([("head/", po.SGD(1.0))])
    tx.init(params)


def test_optim_no_decay_mask_exempts_bias_and_scale():
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu import optim as po

    params = {
        "dense": {"kernel": jnp.ones((2, 2)), "bias": jnp.ones((2,))},
        "ln": {"scale": jnp.ones((2,)), "bias": jnp.ones((2,))},
    }
    mask = po.no_decay_mask()(params)
    assert mask["dense"]["kernel"] is True
    assert mask["dense"]["bias"] is False
    assert mask["ln"]["scale"] is False and mask["ln"]["bias"] is False

    # with zero grads, one AdamW step moves ONLY decayed params
    tx = po.AdamW(lr=0.1, weight_decay=0.5, no_decay=po.DEFAULT_NO_DECAY)
    state = tx.init(params)
    zeros = jax.tree_util.tree_map(jnp.zeros_like, params)
    updates, _ = tx.update(zeros, state, params)
    assert float(jnp.abs(updates["dense"]["kernel"]).sum()) > 0
    assert float(jnp.abs(updates["dense"]["bias"]).sum()) == 0
    assert float(jnp.abs(updates["ln"]["scale"]).sum()) == 0


@pytest.mark.slow
def test_bert_recipe_smoke_fp16_scaler():
    """Recipe 3 end-to-end with the REAL fp16 dynamic loss scaling path
    (the reference's amp.GradScaler texture, BASELINE.json:9)."""
    import bert_finetune

    state = bert_finetune.main(
        [
            "--tiny", "--fp16", "--epochs", "1", "--steps-per-epoch", "2",
            "--batch-size", "8", "--seq-len", "16", "--log-every", "1",
        ]
    )
    assert int(state.step) == 2


def test_memory_api_surface():
    # torch.cuda.memory_* call shapes; CPU backends report nothing, so
    # this pins graceful degradation (zeros / '?' table, never raising)
    import pytorch_distributed_tpu as ptd

    assert ptd.memory_allocated() >= 0
    assert ptd.max_memory_allocated() >= 0
    summary = ptd.memory_summary()
    assert "device" in summary and "peak" in summary
    assert isinstance(ptd.memory_stats(), dict)
