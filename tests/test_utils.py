"""Config/CLI, profiler, and recipe-entry tests."""

import dataclasses
import os
import sys
from typing import Optional

import pytest

from pytorch_distributed_tpu.utils.config import RecipeConfig, parse_cli
from pytorch_distributed_tpu.utils.profiler import StepTimer, annotate, maybe_trace

RECIPES = os.path.join(os.path.dirname(__file__), "..", "recipes")
sys.path.insert(0, RECIPES)


# -- config ----------------------------------------------------------------


def test_parse_cli_defaults():
    cfg = parse_cli(RecipeConfig, [])
    assert cfg.epochs == 1
    assert cfg.backend is None
    assert cfg.dp == -1
    assert cfg.synthetic is False


def test_parse_cli_overrides():
    cfg = parse_cli(
        RecipeConfig,
        ["--epochs", "3", "--lr", "0.5", "--backend", "gloo", "--synthetic"],
    )
    assert cfg.epochs == 3
    assert cfg.lr == 0.5
    assert cfg.backend == "gloo"
    assert cfg.synthetic is True


def test_parse_cli_subclass_and_bool_negation():
    @dataclasses.dataclass
    class C(RecipeConfig):
        width: int = 64  # doc: model width
        flip: bool = True  # doc: flip augmentation

    cfg = parse_cli(C, ["--width", "128", "--no-flip"])
    assert cfg.width == 128
    assert cfg.flip is False
    assert cfg.epochs == 1  # inherited field still parsed


def test_parse_cli_optional_fields():
    cfg = parse_cli(RecipeConfig, ["--steps-per-epoch", "5"])
    assert cfg.steps_per_epoch == 5
    assert cfg.ckpt_dir is None


# -- profiler --------------------------------------------------------------


def test_step_timer_window():
    t = StepTimer(window=4)
    assert t.tick() is None  # first tick has no interval
    for _ in range(6):
        dt = t.tick()
        assert dt is not None and dt >= 0
    assert len(t.times) == 4  # window bound
    assert t.mean > 0
    assert t.percentile(0.5) >= 0
    s = t.summary()
    assert s["steps_timed"] == 4


def test_maybe_trace_noop_and_annotate():
    with maybe_trace(None):  # must be a no-op without a logdir
        with annotate("step"):
            pass


def test_maybe_trace_writes(tmp_path):
    import jax.numpy as jnp

    with maybe_trace(str(tmp_path)):
        jnp.ones((8, 8)).sum().block_until_ready()
    # a plugins/profile/<ts>/ dir with trace artifacts appears
    found = []
    for root, _dirs, files in os.walk(tmp_path):
        found.extend(files)
    assert found, "profiler produced no trace files"


# -- recipe 2 entry --------------------------------------------------------


@pytest.mark.slow
def test_resnet50_imagenet_recipe_smoke():
    import resnet50_imagenet

    metrics = resnet50_imagenet.main(
        [
            "--backend", "gloo", "--synthetic", "--epochs", "1",
            "--steps-per-epoch", "2", "--batch-size", "16",
            "--image-size", "32", "--dp", "8", "--log-every", "1",
            "--warmup-epochs", "0", "--eval-samples", "32",
        ]
    )
    assert "accuracy" in metrics and "loss" in metrics


def test_imports_never_initialize_a_backend():
    """Importing the framework must not touch a device.

    On the axon relay a backend init dials the single-chip tunnel and can
    block for minutes when another process holds the lease; an import-time
    init (e.g. a module-level logger resolving jax.process_index(), the
    r2 regression this test pins) hangs every importer — including the
    driver's dryrun parent whose only job is to re-exec a CPU child.
    """
    import subprocess
    import sys

    code = (
        "import sys\n"
        "import jax._src.xla_bridge as xb\n"
        # fail loudly if jax renames the internal this tripwire patches —
        # otherwise the assignment silently tests nothing
        "assert callable(getattr(xb, '_init_backend', None)), "
        "'jax moved _init_backend; update this tripwire'\n"
        "def _bomb(p):\n"
        "    print('INIT-BACKEND:', p, file=sys.stderr, flush=True)\n"
        "    raise SystemExit(7)\n"
        "xb._init_backend = _bomb\n"
        "import pytorch_distributed_tpu\n"
        "import pytorch_distributed_tpu.train\n"
        "import pytorch_distributed_tpu.parallel\n"
        "import pytorch_distributed_tpu.data\n"
        "import pytorch_distributed_tpu.models\n"
        "import pytorch_distributed_tpu.utils.profiler\n"
        "import pytorch_distributed_tpu.utils.config\n"
        "import pytorch_distributed_tpu.launch\n"
        "import pytorch_distributed_tpu.run\n"
        "print('CLEAN')\n"
    )
    proc = subprocess.run(
        [sys.executable, "-c", code], capture_output=True, text=True,
        timeout=120,
    )
    assert proc.returncode == 0 and "CLEAN" in proc.stdout, proc.stderr[-2000:]


@pytest.mark.slow
def test_gpt2_recipe_pipeline_parallel_smoke():
    """Recipe 4 with --pp 2: a real transformer trains through the GPipe
    schedule from the recipe entry point (VERDICT r1 weak #5)."""
    import gpt2_zero1

    state = gpt2_zero1.main(
        [
            "--size", "tiny", "--pp", "2", "--epochs", "1",
            "--steps-per-epoch", "2", "--batch-size", "8",
            "--seq-len", "16", "--log-every", "1", "--sample", "4",
        ]
    )
    assert int(state.step) == 2
