"""Qwen3: HF logit parity with the QK norms made BINDING (HF inits the
norm scales to ones — identity — so they are randomized first; a
mis-wired norm then fails parity), roundtrip, decode."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import Qwen3Config, Qwen3ForCausalLM
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def _pair():
    torch.manual_seed(0)
    hf_cfg = transformers.Qwen3Config(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        head_dim=16,  # != hidden/heads = 12: the decoupling is binding
        rope_theta=1e6, rms_norm_eps=1e-6, max_position_embeddings=128,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    hf = transformers.Qwen3ForCausalLM(hf_cfg).eval()
    # the q/k norm scales init to ONES (identity) — randomize so the
    # parity check actually exercises the normalization wiring
    with torch.no_grad():
        for n, p in hf.named_parameters():
            if "q_norm" in n or "k_norm" in n:
                p.normal_(1.0, 0.5)
    cfg = Qwen3Config(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, override_head_dim=16,
        max_seq_len=128, rope_theta=1e6, rms_eps=1e-6,
    )
    return hf, cfg


def test_qwen3_logits_match_hf():
    from pytorch_distributed_tpu.interop import load_qwen3_weights

    hf, cfg = _pair()
    params = load_qwen3_weights(_sd(hf), cfg)
    block = params["layers"]["block"]
    assert block["q_norm"]["scale"].shape == (2, 16)  # [L, head_dim]
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 10)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = Qwen3ForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)


@pytest.mark.slow  # budget: parity pins the mapping fast
def test_qwen3_export_roundtrips_into_hf():
    from pytorch_distributed_tpu.interop import (
        export_qwen3_weights,
        load_qwen3_weights,
    )

    hf, cfg = _pair()
    params = load_qwen3_weights(_sd(hf), cfg)
    sd = export_qwen3_weights(params, cfg)
    hf2 = transformers.Qwen3ForCausalLM(hf.config).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(1).integers(2, 211, size=(1, 8)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )


@pytest.mark.slow  # the gpt2/mistral decode pins cover the machinery fast
def test_qwen3_cache_decode_equals_recompute():
    cfg = Qwen3Config.tiny()
    model = Qwen3ForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 6)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    got = ptd.generate(model, params, ids, max_new_tokens=4, temperature=0.0)
    seq = np.asarray(ids)
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)


def test_mismatched_config_refused_not_dropped():
    """A Qwen3 checkpoint under qk_norm=False (and a Qwen2 one under
    attention_bias=False) must refuse loudly — silently dropping the
    extra attention structure diverges from HF."""
    import dataclasses

    from pytorch_distributed_tpu.interop import load_llama_weights

    hf, cfg = _pair()
    sd = _sd(hf)
    with pytest.raises(ValueError, match="qk_norm"):
        load_llama_weights(sd, dataclasses.replace(cfg, qk_norm=False))

    torch.manual_seed(1)
    q2 = transformers.Qwen2ForCausalLM(
        transformers.Qwen2Config(
            vocab_size=211, hidden_size=48, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, tie_word_embeddings=False,
        )
    ).eval()
    from pytorch_distributed_tpu.models import Qwen2Config as OurQwen2

    bad = dataclasses.replace(
        OurQwen2(
            vocab_size=211, hidden_size=48, intermediate_size=96,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
        ),
        attention_bias=False,
    )
    with pytest.raises(ValueError, match="attention_bias"):
        load_llama_weights(_sd(q2), bad)


@pytest.mark.slow  # sharded-serving pin; parity runs fast
def test_qwen3_tp_sharded_logits_match():
    """QK-norm under tensor parallelism: q shards over heads while the
    [head_dim] norm scales replicate — sharded logits match unsharded
    to numerical tolerance in f32. (Token-identity is NOT asserted:
    under the default bf16 compute policy, GSPMD's differently-ordered
    reductions move logits by ~1e-2 — enough to flip near-tie argmaxes
    on a random-init 512-vocab model, observed 3/20; in f32 the sharded
    logits agree to ~1e-5, which is what this pins.)"""
    import optax

    from pytorch_distributed_tpu.models import qwen3_partition_rules
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import TrainState

    # tp must divide the 2 kv heads of the tiny config
    ptd.init_process_group(mesh_spec=MeshSpec(dp=4, tp=2))
    cfg = Qwen3Config.tiny()
    model = Qwen3ForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 8)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    with autocast(enabled=False):  # f32: isolate sharding effects from
        want = model.apply({"params": params}, ids)  # bf16 reorder noise
    strategy = DataParallel(extra_rules=qwen3_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    block = state.params["layers"]["block"]
    assert "tp" in str(block["q"]["kernel"].sharding.spec)
    assert "tp" not in str(block["q_norm"]["scale"].sharding.spec)
    with autocast(enabled=False):
        got = model.apply({"params": state.params}, ids)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=2e-4, atol=2e-5
    )
