"""Serve engine (serve/): continuous batching must be invisible per request.

The contract under test: whatever mix of requests shares the slot batch
— staggered arrivals, ragged lengths, chunked prefill splits,
cancellations, fault evictions, slot reuse — every COMPLETED request's
token stream is bit-identical to a solo offline ``generate()`` with the
same seed and sampling params, and the decode step compiles exactly
once for the whole workload (the static-shape invariant).
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.generation import generate
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.serve import (
    EngineConfig,
    PagedKVPool,
    Request,
    RequestStatus,
    ServeEngine,
    ServeTelemetry,
    sample_logits_rows,
)
from pytorch_distributed_tpu.train.metrics import MetricsWriter, read_metrics

pytestmark = pytest.mark.serve


@pytest.fixture(scope="module")
def gpt2():
    cfg = GPT2Config(
        vocab_size=97, n_positions=96, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    return model, params


def _solo(model, params, req: Request):
    """The offline reference: one generate() call with the request's
    exact seed/params, truncated at eos like the engine's stream."""
    out = np.asarray(generate(
        model, params, jnp.asarray(req.prompt_ids[None]),
        max_new_tokens=req.max_new_tokens,
        temperature=req.temperature, top_k=req.top_k, top_p=req.top_p,
        rng=jax.random.PRNGKey(req.seed), eos_id=req.eos_id,
    ))[0, req.prompt_len:]
    toks = [int(x) for x in out]
    if req.eos_id is not None and req.eos_id in toks:
        toks = toks[: toks.index(req.eos_id) + 1]
    return toks


def test_mixed_workload_parity_single_compile(gpt2):
    """THE acceptance test: staggered arrivals, ragged prompt/new
    lengths, heterogeneous sampling params, one cancellation, one
    fault-evicted request, more requests than slots (slot reuse) — and
    every completed stream equals its solo generate bit for bit, with
    ONE decode compile and ONE prefill compile."""
    model, params = gpt2
    rng = np.random.default_rng(7)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=3, max_len=64, prefill_chunk=4,
    ))

    def mk(p_len, new, **kw):
        return Request(
            prompt_ids=rng.integers(1, 97, size=p_len).astype(np.int32),
            max_new_tokens=new, **kw,
        )

    wave1 = [
        mk(5, 6),                                     # greedy
        mk(9, 4, temperature=0.9, top_k=12, seed=3),  # temp + top-k
        mk(3, 8, temperature=0.7, top_p=0.9, seed=11),
        mk(7, 5, temperature=1.1, top_k=20, top_p=0.8, seed=42),
    ]
    victim = mk(6, 12, request_id="victim")      # fault-evicted
    doomed = mk(6, 40, request_id="doomed")      # cancelled mid-decode
    wave2 = [mk(11, 6, temperature=0.8, seed=5), mk(2, 7)]

    handles = {}
    with faults.injected("serve.decode:mode=raise,count=1,match=victim"):
        for r in wave1 + [victim, doomed]:
            handles[r.request_id] = engine.submit(r)
        for _ in range(6):
            engine.step()
        # staggered arrivals: wave2 lands mid-flight
        for r in wave2:
            handles[r.request_id] = engine.submit(r)
        for _ in range(4):
            engine.step()
        assert engine.cancel(doomed.request_id)
        engine.run_until_drained()

    assert handles["victim"].status is RequestStatus.FAILED
    assert isinstance(handles["victim"].error, faults.InjectedFault)
    assert handles["doomed"].status is RequestStatus.CANCELLED
    completed = [r for r in wave1 + wave2]
    for r in completed:
        h = handles[r.request_id]
        assert h.status is RequestStatus.COMPLETED, h
        assert h.tokens == _solo(model, params, r), r.request_id
    # the static-shape invariant: one compile per program, ever
    assert engine.decode_compiles == 1
    assert engine.prefill_compiles == 1


def test_eos_completes_early_and_frees_slot(gpt2):
    """A request hitting eos retires immediately (generate would pad to
    max_new_tokens; the engine's slot goes back to work instead)."""
    model, params = gpt2
    rng = np.random.default_rng(1)
    # find an (eos, prompt) pair the greedy path actually emits
    prompt = rng.integers(1, 97, size=5).astype(np.int32)
    ref = _solo(model, params, Request(prompt, max_new_tokens=8))
    eos = ref[2]  # third greedy token becomes the stop token
    req = Request(prompt, max_new_tokens=8, eos_id=eos)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=1, max_len=32, prefill_chunk=8,
    ))
    h = engine.submit(req)
    # a second request queued behind the only slot — it can only
    # complete because eos freed the slot early
    r2 = Request(rng.integers(1, 97, size=4).astype(np.int32),
                 max_new_tokens=3)
    h2 = engine.submit(r2)
    engine.run_until_drained()
    assert h.status is RequestStatus.COMPLETED
    assert h.tokens == _solo(model, params, req)
    assert h.tokens[-1] == eos and len(h.tokens) < 8
    assert h2.status is RequestStatus.COMPLETED
    assert h2.tokens == _solo(model, params, r2)


def test_chunked_prefill_does_not_stall_decode(gpt2):
    """A long prompt prefills in chunks while an already-decoding
    request keeps emitting — the chunked-prefill fairness claim, plus
    parity for both sides."""
    model, params = gpt2
    rng = np.random.default_rng(3)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=2, max_len=96, prefill_chunk=4,
        prefill_chunks_per_step=1,
    ))
    short = Request(rng.integers(1, 97, size=3).astype(np.int32),
                    max_new_tokens=12)
    h_short = engine.submit(short)
    engine.step()  # short is through prefill and decoding
    emitted_before = len(h_short.tokens)
    assert emitted_before >= 1
    long = Request(rng.integers(1, 97, size=26).astype(np.int32),
                   max_new_tokens=4, temperature=0.5, seed=9)
    h_long = engine.submit(long)
    # the long prompt needs ceil(26/4) = 7 chunks; the short request
    # must make decode progress during them
    progressed = 0
    for _ in range(5):
        engine.step()
        if len(h_short.tokens) > emitted_before:
            progressed += 1
            emitted_before = len(h_short.tokens)
        if h_short.done:
            break
    assert progressed >= 3, "decode stalled behind a long prefill"
    engine.run_until_drained()
    assert h_short.tokens == _solo(model, params, short)
    assert h_long.tokens == _solo(model, params, long)


@pytest.mark.parametrize("family", ["llama", "qwen2"])
def test_llama_family_parity(gpt2, family):
    """The engine works with any cache-bearing Llama-body model (GQA,
    RoPE, Qwen2's attention biases) through the same write_pos path."""
    if family == "llama":
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig as Cfg, LlamaForCausalLM as Model,
        )
    else:
        from pytorch_distributed_tpu.models.qwen2 import (
            Qwen2Config as Cfg, Qwen2ForCausalLM as Model,
        )
    cfg = Cfg.tiny()
    model = Model(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    rng = np.random.default_rng(4)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=2, max_len=48, prefill_chunk=4,
    ))
    reqs = [
        Request(rng.integers(1, 512, size=5).astype(np.int32),
                max_new_tokens=5),
        Request(rng.integers(1, 512, size=9).astype(np.int32),
                max_new_tokens=4, temperature=0.8, top_k=16, seed=2),
        Request(rng.integers(1, 512, size=3).astype(np.int32),
                max_new_tokens=6, temperature=0.6, top_p=0.85, seed=8),
    ]
    handles = [engine.submit(r) for r in reqs]
    engine.run_until_drained()
    for r, h in zip(reqs, handles):
        assert h.status is RequestStatus.COMPLETED
        assert h.tokens == _solo(model, params, r)
    assert engine.decode_compiles == 1


def test_deadlines_expire_queued_and_inflight(gpt2):
    """Deadline eviction on both sides of admission, on a fake clock:
    a queued request expires waiting, an in-flight one is evicted
    mid-decode, and the engine keeps serving afterward."""
    model, params = gpt2
    rng = np.random.default_rng(5)
    now = [0.0]
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=1, max_len=32, prefill_chunk=8),
        clock=lambda: now[0],
    )
    hog = engine.submit(Request(
        rng.integers(1, 97, size=4).astype(np.int32),
        max_new_tokens=20, deadline_s=10.0,
    ))
    starved = engine.submit(Request(
        rng.integers(1, 97, size=4).astype(np.int32),
        max_new_tokens=2, deadline_s=3.0,
    ))
    for _ in range(3):
        engine.step()
    assert hog.status is RequestStatus.DECODING
    now[0] = 5.0  # starved's deadline passes while queued
    engine.step()
    assert starved.status is RequestStatus.EXPIRED
    assert starved.tokens == []
    now[0] = 11.0  # hog's deadline passes mid-decode
    engine.step()
    assert hog.status is RequestStatus.EXPIRED
    assert engine.pool.num_free == 1
    # the engine is still healthy: a fresh request completes
    fresh = Request(rng.integers(1, 97, size=4).astype(np.int32),
                    max_new_tokens=3)
    h = engine.submit(fresh)
    engine.run_until_drained()
    assert h.status is RequestStatus.COMPLETED
    assert h.tokens == _solo(model, params, fresh)


def test_prefill_fault_evicts_only_poisoned(gpt2):
    """serve.prefill degrade-don't-crash: the poisoned request fails,
    its neighbors complete with parity."""
    model, params = gpt2
    rng = np.random.default_rng(6)
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=2, max_len=32, prefill_chunk=4,
    ))
    bad = Request(rng.integers(1, 97, size=6).astype(np.int32),
                  max_new_tokens=4, request_id="poisoned")
    good = Request(rng.integers(1, 97, size=6).astype(np.int32),
                   max_new_tokens=4)
    with faults.injected("serve.prefill:mode=raise,count=1,match=poisoned"):
        hb = engine.submit(bad)
        hg = engine.submit(good)
        engine.run_until_drained()
    assert hb.status is RequestStatus.FAILED
    assert hb.tokens == []
    assert hg.status is RequestStatus.COMPLETED
    assert hg.tokens == _solo(model, params, good)


def test_submit_validation(gpt2):
    model, params = gpt2
    engine = ServeEngine(model, params, EngineConfig(
        num_slots=1, max_len=16, prefill_chunk=8,
    ))
    ids = np.ones(9, np.int32)
    with pytest.raises(ValueError, match="chunked-prefill"):
        # 17 tokens round up to 3 chunks = 24 buffer slots > max_len 16:
        # the final chunk's write would clamp and corrupt — refused
        engine.submit(Request(np.ones(17, np.int32), max_new_tokens=1))
    with pytest.raises(ValueError, match="max_len"):
        engine.submit(Request(ids, max_new_tokens=8))
    with pytest.raises(ValueError, match="temperature"):
        Request(ids, max_new_tokens=1, temperature=-0.1)
    with pytest.raises(ValueError, match="top_p"):
        Request(ids, max_new_tokens=1, top_p=1.5)
    with pytest.raises(ValueError, match="at least one token"):
        Request(np.zeros(0, np.int32), max_new_tokens=1)
    # model-limit guard at engine construction
    with pytest.raises(ValueError, match="maximum sequence length"):
        ServeEngine(model, params, EngineConfig(num_slots=1, max_len=512))
    # a chunk wider than the buffer could never admit anything — the
    # config, not each prompt, is the culprit and fails at construction
    with pytest.raises(ValueError, match="no request could ever"):
        EngineConfig(num_slots=1, max_len=16, prefill_chunk=32)


def test_telemetry_flows_through_metrics_writer(gpt2, tmp_path):
    """TTFT/throughput/occupancy land in the standard MetricsWriter
    JSONL stream under split='serve'."""
    model, params = gpt2
    rng = np.random.default_rng(8)
    path = str(tmp_path / "serve.jsonl")
    writer = MetricsWriter(path)
    engine = ServeEngine(
        model, params,
        EngineConfig(num_slots=2, max_len=32, prefill_chunk=4,
                     telemetry_every=2),
        telemetry=ServeTelemetry(writer=writer),
    )
    reqs = [
        Request(rng.integers(1, 97, size=5).astype(np.int32),
                max_new_tokens=4)
        for _ in range(3)
    ]
    handles = [engine.submit(r) for r in reqs]
    engine.run_until_drained()
    writer.close()
    records = read_metrics(path)
    assert all(r["split"] == "serve" for r in records)
    reqs_recs = [r for r in records if r.get("event") == "request"]
    assert len(reqs_recs) == 3
    for rec in reqs_recs:
        assert rec["status"] == "completed"
        assert rec["ttft_ms"] > 0
        assert rec["new_tokens"] == 4
        assert rec["tokens_per_sec"] > 0
    snaps = [r for r in records if r.get("event") == "snapshot"]
    assert snaps and all(
        0 <= s["slot_occupancy"] <= 1 and s["queue_depth"] >= 0
        and s["slots_total"] == 2 for s in snaps
    )
    s = engine.telemetry.summary()
    assert s["completed"] == 3 and s["completed_tokens"] == 12
    assert s["ttft_ms_p50"] > 0 and s["ttft_ms_p99"] >= s["ttft_ms_p50"]
    assert all(h.done for h in handles)


def test_engine_with_tp_sharded_params():
    """Serving with TP-sharded params: the engine's jitted programs
    follow the committed shardings, token streams unchanged."""
    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.models.gpt2 import gpt2_partition_rules
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, tp=4))
    cfg = GPT2Config(
        vocab_size=128, n_positions=64, hidden_size=32, num_layers=2,
        num_heads=4, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 6), jnp.int32)
    )["params"]
    rng = np.random.default_rng(9)
    req = Request(rng.integers(1, 128, size=6).astype(np.int32),
                  max_new_tokens=6)
    want = _solo(model, params, req)
    strategy = DataParallel(extra_rules=gpt2_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    engine = ServeEngine(model, state.params, EngineConfig(
        num_slots=2, max_len=32, prefill_chunk=4,
    ))
    h = engine.submit(req)
    engine.run_until_drained()
    assert h.status is RequestStatus.COMPLETED
    assert h.tokens == want


# -- unit layers ----------------------------------------------------------

def test_kv_slot_pool_lifecycle(gpt2):
    model, params = gpt2
    pool = PagedKVPool(
        model, params, num_slots=3, max_len=16, page_size=4,
    )
    a = pool.allocate(np.ones(5, np.int32), max_new=3, chunk=4)
    b = pool.allocate(np.ones(3, np.int32), max_new=2, chunk=4)
    assert (a.slot, b.slot) == (0, 1)  # deterministic lowest-first
    # pages: a spans max(5+3, 8)=8 -> 2 pages; b spans max(3+2, 4) -> 2
    # pages (chunk roundup); both from the shared free list, lowest first
    assert a.n_pages == 2 and list(a.page_row[:2]) == [1, 2]
    assert b.n_pages == 2 and list(b.page_row[:2]) == [3, 4]
    assert pool.pages_in_use == 4
    pool.lengths[a.slot] = 5
    pool.free(a.slot)
    assert pool.num_free == 2 and pool.lengths[a.slot] == 0
    assert pool.pages_in_use == 2  # a's pages returned to the free list
    c = pool.allocate(np.ones(4, np.int32), max_new=4, chunk=4)
    assert c.slot == 0  # lowest free slot, reused
    assert list(c.page_row[:c.n_pages]) == [1, 2]  # lowest pages, reused
    with pytest.raises(ValueError, match="already free"):
        pool.free(2)
    pool.lengths[0] = 3
    mask = pool.valid_mask()
    assert mask[0, :3].all() and not mask[0, 3:].any()
    assert not mask[2].any()  # free slot: nothing valid
    pool.check_consistency()


def test_sample_logits_rows_matches_static_sampler():
    """Row-wise sampler == generation.sample_logits per row, for every
    (greedy/temp/top-k/top-p/off) combination — the transcript that
    makes engine-vs-generate parity possible."""
    from pytorch_distributed_tpu.generation import sample_logits

    rng = np.random.default_rng(0)
    V = 101
    logits = jnp.asarray(rng.normal(size=(5, V)).astype(np.float32) * 3)
    rows = [
        dict(temperature=0.0, top_k=None, top_p=None),
        dict(temperature=1.0, top_k=None, top_p=None),
        dict(temperature=0.7, top_k=7, top_p=None),
        dict(temperature=1.3, top_k=None, top_p=0.6),
        dict(temperature=0.9, top_k=25, top_p=0.9),
    ]
    keys = jnp.stack([jax.random.PRNGKey(i) for i in range(5)])
    want = [
        int(sample_logits(
            logits[i][None], keys[i], **rows[i]
        )[0])
        for i in range(5)
    ]
    got = sample_logits_rows(
        logits, keys,
        jnp.asarray([r["temperature"] for r in rows], jnp.float32),
        jnp.asarray([r["top_k"] or 0 for r in rows], jnp.int32),
        jnp.asarray(
            [np.inf if r["top_p"] is None else r["top_p"] for r in rows],
            jnp.float32,
        ),
    )
    assert [int(x) for x in got] == want
