"""ImageFolder dataset + decode pipeline (data/image_folder.py)."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    DataLoader,
    FolderImagePipeline,
    ImageFolderDataset,
)

PIL = pytest.importorskip("PIL")


@pytest.fixture
def image_root(tmp_path):
    from PIL import Image

    rng = np.random.default_rng(0)
    for split in ("train", "val"):
        for ci, cls in enumerate(["ants", "bees", "wasps"]):
            d = tmp_path / split / cls
            d.mkdir(parents=True)
            for i in range(4):
                # varied sizes exercise resize paths; encode solid-ish
                # color per class so labels are checkable after decode
                h, w = int(rng.integers(40, 80)), int(rng.integers(40, 80))
                arr = np.full((h, w, 3), 60 * ci + 40, np.uint8)
                arr += rng.integers(0, 8, size=arr.shape, dtype=np.uint8)
                Image.fromarray(arr).save(d / f"img{i}.jpg", quality=95)
    return tmp_path


def test_index_and_classes(image_root):
    ds = ImageFolderDataset(str(image_root / "train"))
    assert ds.classes == ["ants", "bees", "wasps"]
    assert len(ds) == 12
    item = ds[0]
    assert item["image"].dtype == np.uint8
    assert item["label"] == 0


def test_train_pipeline_batches(image_root):
    ds = ImageFolderDataset(str(image_root / "train"))
    pipe = FolderImagePipeline(
        32, train=True, seed=1, device_normalize=False
    )
    batch = pipe(ds, np.arange(12))
    assert batch["image"].shape == (12, 32, 32, 3)
    assert batch["image"].dtype == np.float32
    assert set(batch["label"].tolist()) == {0, 1, 2}
    # normalized: roughly zero-centered, not raw uint8 range
    assert abs(batch["image"].mean()) < 5.0


def test_eval_pipeline_deterministic(image_root):
    ds = ImageFolderDataset(str(image_root / "val"))
    pipe = FolderImagePipeline(32, train=False, resize=48)
    a = pipe(ds, np.arange(6))["image"]
    b = pipe(ds, np.arange(6))["image"]
    np.testing.assert_array_equal(a, b)


def test_train_augmentation_varies_by_epoch(image_root):
    ds = ImageFolderDataset(str(image_root / "train"))
    pipe = FolderImagePipeline(32, train=True, seed=1)
    a = pipe(ds, np.arange(6))["image"]
    pipe.set_epoch(1)
    b = pipe(ds, np.arange(6))["image"]
    assert not np.array_equal(a, b)
    # same epoch + same indices replays identically (resume contract)
    pipe.set_epoch(0)
    c = pipe(ds, np.arange(6))["image"]
    np.testing.assert_array_equal(a, c)


def test_dataloader_end_to_end(image_root):
    ds = ImageFolderDataset(str(image_root / "train"))
    loader = DataLoader(
        ds, 4, seed=0, fetch=FolderImagePipeline(24, train=True)
    )
    batches = list(loader)
    assert len(batches) == 3
    for b in batches:
        assert b["image"].shape == (4, 24, 24, 3)


def test_threaded_decode_matches_sequential(image_root):
    # thread-pool decode must be bit-identical to sequential: per-sample
    # spawned generators make augmentation independent of thread order
    ds = ImageFolderDataset(str(image_root / "train"))
    seq = FolderImagePipeline(24, train=True, seed=3, num_threads=1)
    par = FolderImagePipeline(24, train=True, seed=3, num_threads=4)
    idx = np.arange(len(ds))
    a = seq(ds, idx)
    b = par(ds, idx)
    np.testing.assert_array_equal(a["image"], b["image"])
    np.testing.assert_array_equal(a["label"], b["label"])


def test_device_normalize_matches_host_path(image_root):
    import jax

    ds = ImageFolderDataset(str(image_root / "val"))
    host = FolderImagePipeline(
        32, train=False, resize=48, device_normalize=False
    )
    dev = FolderImagePipeline(
        32, train=False, resize=48, device_normalize=True
    )
    a = host(ds, np.arange(6))
    b = dev(ds, np.arange(6))
    assert b["image"].dtype == np.uint8
    normed = jax.jit(dev.device_normalizer())(
        {k: np.asarray(v) for k, v in b.items()}
    )
    np.testing.assert_allclose(
        np.asarray(normed["image"]), a["image"], atol=1e-4
    )
    np.testing.assert_array_equal(np.asarray(normed["label"]), a["label"])


@pytest.mark.slow
def test_resnet50_recipe_trains_on_image_folder_default_u8(image_root):
    """Default ingest: uint8 ship + on-device normalize (no flag)."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "recipes")
    )
    import resnet50_imagenet

    metrics = resnet50_imagenet.main(
        [
            "--data-dir", str(image_root), "--epochs", "1",
            "--batch-size", "8", "--image-size", "32", "--dp", "-1",
            "--log-every", "1", "--warmup-epochs", "0",
        ]
    )
    assert "accuracy" in metrics


@pytest.mark.slow
def test_resnet50_recipe_trains_on_image_folder_host_f32(image_root):
    """The --no-device-normalize escape hatch still trains."""
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "recipes")
    )
    import resnet50_imagenet

    metrics = resnet50_imagenet.main(
        [
            "--data-dir", str(image_root), "--epochs", "1",
            "--batch-size", "8", "--image-size", "32", "--dp", "-1",
            "--log-every", "1", "--warmup-epochs", "0",
            "--no-device-normalize",
        ]
    )
    assert "accuracy" in metrics
