"""Driver-contract test for bench.py's CPU-fallback mode.

VERDICT r2 #7: a fallback run must emit only host-meaningful metrics —
stdout carries exactly one JSON line (the driver contract) whose metric is
a real host measurement, and the consumption-bound TPU metric names must
not appear anywhere in the output.
"""

import json
import os
import re
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

# metrics whose value on a CPU is only "how fast is this CPU at running
# the model" — must be suppressed in fallback runs
CONSUMPTION_BOUND = [
    "resnet50_imagenet_images_per_sec_per_chip",
    "resnet50_e2e_dataloader_images_per_sec_per_chip",
    "resnet50_e2e_u8_device_normalize_images_per_sec_per_chip",
    "gpt2_medium_tokens_per_sec_per_chip",
    "gpt2_decode_tokens_per_sec",
    "dp_allreduce_step_ms",
    "dp_step_overhead_ms",
]


@pytest.mark.slow
def test_bench_cpu_fallback_is_host_meaningful(tmp_path):
    env = dict(os.environ)
    env["PALLAS_AXON_POOL_IPS"] = ""  # no relay plugin registration
    env["JAX_PLATFORMS"] = "cpu"
    # private lock: a suite runner may HOLD the real machine-wide lock
    # around this very test — the child must not deadlock against it
    env["PTD_BENCH_LOCK_PATH"] = str(tmp_path / "bench.lock")
    # the driver runs bench with a 1-device env; the test-suite conftest
    # exports an 8-device XLA_FLAGS that would inflate the child's world
    # (8x the batch on a CPU) — strip it
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, "bench.py"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]

    # stdout: exactly one JSON line, a host-side measurement, platform cpu
    stdout_lines = [l for l in proc.stdout.splitlines() if l.strip()]
    assert len(stdout_lines) == 1, stdout_lines
    primary = json.loads(stdout_lines[0])
    assert primary["metric"] == "input_pipeline_feed_images_per_sec"
    assert primary["platform"] == "cpu"
    assert primary["value"] > 0

    # stderr secondary metrics: all host-meaningful, none consumption-bound
    for line in proc.stderr.splitlines():
        if not line.startswith("{"):
            continue
        rec = json.loads(line)
        assert rec["metric"] not in CONSUMPTION_BOUND, rec
        assert rec["platform"] == "cpu"
    assert "hostring_allreduce_ms" in proc.stderr
    assert "input_pipeline_u8_feed_images_per_sec" in proc.stderr
    # the f32 escape hatch stays tracked as the reference-parity number
    assert "input_pipeline_f32_feed_images_per_sec" in proc.stderr
    # the DEFAULT ingest path must also be tracked END TO END (uint8
    # loader -> fused on-device normalize -> train step), not feed-only
    e2e = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "input_pipeline_u8_e2e_images_per_sec"
    ]
    assert len(e2e) == 1, proc.stderr[-2000:]
    assert e2e[0]["value"] > 0
    # CPU fallback: small-shape smoke — must not wear a chip-claim ratio
    assert e2e[0]["vs_baseline"] is None
    # the checkpoint save path (now carrying per-shard CRC + COMMIT) is
    # tracked so an integrity-layer regression shows up as a number, not
    # a mystery slowdown in a production preemption window
    ckpt = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "checkpoint_save_mb_per_sec"
    ]
    assert len(ckpt) == 1, proc.stderr[-2000:]
    assert ckpt[0]["value"] > 0 and ckpt[0]["integrity"] == "crc+commit"

    # serving: the continuous-batching engine must BEAT the naive
    # sequential-generate baseline on the same offered workload —
    # vs_baseline carries the engine/sequential tokens-per-sec ratio
    # (the one relative metric that stays honest on a CPU), and the SLO
    # percentiles must be present
    srv = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "serving_tokens_per_sec"
    ]
    assert len(srv) == 1, proc.stderr[-2000:]
    assert srv[0]["value"] > 0
    assert srv[0]["vs_baseline"] is not None, srv[0]
    assert srv[0]["vs_baseline"] > 1.0, (
        f"continuous batching lost to sequential generate: {srv[0]}"
    )
    assert "serving_ttft_ms_p50" in proc.stderr
    assert "serving_ttft_ms_p99" in proc.stderr

    def one_metric(name):
        recs = [
            json.loads(l) for l in proc.stderr.splitlines()
            if l.startswith("{") and json.loads(l)["metric"] == name
        ]
        assert len(recs) == 1, (name, proc.stderr[-2000:])
        return recs[0]

    # the paged KV pool must serve the mixed-length prefix-shared
    # workload (all requests completing — the phase raises otherwise)
    # at >= 2x concurrent slots per byte of resident KV vs the fixed
    # [S, max_len] pool it replaced — ROADMAP item 3's memory target
    kv = one_metric("serving_kv_bytes_ratio")
    assert kv["value"] >= 2.0, kv
    assert kv["prefix_hit_rate"] > 0, kv  # the sharing path actually ran
    # admit cost must stay flat as the pool grows (the old allocate
    # sorted its free list every call — O(S log S) scaled ~x40 over
    # this size range; the heap free list measures ~x1 with generous
    # headroom for a contended 1-core box)
    flat = one_metric("serving_admit_flatness")
    assert 0 < flat["value"] < 16, flat
    # speculative decode must BEAT the plain paged engine on the same
    # greedy workload — with output parity enforced inside the phase
    # (it raises on divergence), so this ratio can never come from
    # wrong tokens
    spec = one_metric("serving_spec_tokens_per_sec")
    assert spec["value"] > 0
    assert spec["vs_baseline"] is not None and spec["vs_baseline"] >= 1.0, (
        f"speculative decode lost to plain decode: {spec}"
    )
    assert spec["accepted_per_verify"] > 0, spec  # drafts actually land
    # paged-attention decode (round 12): the decode tick must at least
    # MATCH the dense-gather tick on tokens/sec (output parity enforced
    # in-phase — the phase raises on divergence, and on any bucket
    # compiled more than once) while the analytic decode HBM
    # bytes/token shrinks >= 1.5x at the long-context mix (in practice
    # ~16x: a 1-2 page live bucket vs the 16-page max_len gather)
    pa = one_metric("serving_paged_attn_tokens_per_sec")
    assert pa["value"] > 0
    assert pa["vs_baseline"] is not None and pa["vs_baseline"] >= 1.0, (
        f"paged attention lost to the dense-gather tick: {pa}"
    )
    pr = one_metric("serving_paged_attn_bytes_per_token_ratio")
    assert pr["value"] >= 1.5, pr
    assert pr["paged_bytes_per_token"] > 0, pr
    assert pr["dense_bytes_per_token"] > pr["paged_bytes_per_token"], pr
    assert pr["decode_buckets"], pr

    # the input_pipeline phases must stay inside their time budget (the
    # r3 starvation incident: the feed phase alone ran >25 min and ate
    # every later phase's budget). Phase durations are printed as
    # "# phase <name> done in <sec>s".
    durations = {}
    for line in proc.stderr.splitlines():
        m = re.match(r"# phase (\S+) done in ([0-9.]+)s", line)
        if m:
            durations[m.group(1)] = float(m.group(2))
    assert "input_pipeline_feed" in durations, sorted(durations)
    assert durations["input_pipeline_feed"] < 300, durations
    assert durations.get("input_pipeline_u8_e2e", 0) < 300, durations
    assert "serving" in durations, sorted(durations)
    assert durations["serving"] < 300, durations
    assert durations.get("serving_paged", 999) < 300, durations
    assert durations.get("serving_spec", 999) < 300, durations
    assert durations.get("serving_paged_attn", 999) < 300, durations
    assert durations.get("elastic", 999) < 300, durations

    # ...and the same numbers must land as DATA: one phase_durations_s
    # record (the print-only stderr notes were unparseable by the
    # driver's JSON tail)
    pd = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "phase_durations_s"
    ]
    assert len(pd) == 1, proc.stderr[-2000:]
    for phase in ("input_pipeline_feed", "serving", "serving_paged",
                  "serving_spec", "serving_paged_attn",
                  "observability", "flightrec", "planning", "elastic"):
        assert phase in pd[0]["value"], pd[0]
    assert pd[0]["value"] == pytest.approx(durations, abs=0.2)

    # the observability micro-phase: tracing a hot loop must cost < 2%
    # vs the untraced loop (the tracer's zero-overhead claim, measured)
    # — and it must stay green now that the comm sites exist (disarmed
    # comm collectives pay the same one is-None test as every span site)
    obs = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "observability_trace_overhead_pct"
    ]
    assert len(obs) == 1, proc.stderr[-2000:]
    assert obs[0]["value"] < 2.0, obs[0]

    # the flightrec micro-phase: the ALWAYS-ON recorder's
    # begin/start/complete triple must stay allocation-free cheap
    # (measured ~1-3us on this box; 25us budget guards against dict
    # churn or allocation creeping onto the hot path, not the box), and
    # the 2-proc injected-hang smoke must end in an autopsy verdict
    # naming the victim (the phase raises otherwise, so the metric's
    # presence IS the assertion — value 1.0 by construction)
    frec = one_metric("flightrec_record_overhead_us")
    assert 0 < frec["value"] < 25.0, frec
    hang = one_metric("flightrec_hang_verdict")
    assert hang["value"] == 1.0, hang
    assert "missing_rank" in hang["unit"], hang
    assert durations.get("flightrec", 999) < 120, durations

    # the planning micro-phase: the auto-parallel planner must sweep
    # the two reference configs in host-arithmetic time (it is
    # eval_shape only — the child stubs jax.jit to prove planning never
    # compiles; a compile would also blow this budget by itself)
    plan_rec = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "planning_wall_s"
    ]
    assert len(plan_rec) == 1, proc.stderr[-2000:]
    assert 0 < plan_rec[0]["value"] < 30, plan_rec[0]
    assert set(plan_rec[0]["chosen"]) == {"gpt2_tiny", "resnet50"}
    assert "planning" in durations, sorted(durations)
    assert durations["planning"] < 180, durations

    # the elastic phase: in-process resize must BEAT die-and-restore on
    # wall-clock downtime — same workers, same SIGKILLed victim, same
    # detection deadline, and BOTH paths verified bit-identical to the
    # unresized reference inside the phase (a fast recovery to wrong
    # params raises there, so this ratio can never come from bad math)
    el = one_metric("elastic_resize_downtime_s")
    assert el["value"] > 0, el
    assert el["resize_goodput_s"] > 0, el
    ratio = one_metric("elastic_vs_restart_ratio")
    assert 0 < ratio["value"] < 1.0, (
        f"in-process resize lost to die-and-restore: {ratio}"
    )
    assert ratio["restart_downtime_s"] > el["value"], ratio

    # the hetero phase (r15): one rank throttled 2x on a 3-proc world —
    # proportional microshard balancing must recover >= 1.25x over the
    # even split (even-split ceiling ~1.5x; the pin leaves room for the
    # telemetry warm-up and the rebalance collectives), with final
    # params verified bit-identical INSIDE the phase between both modes
    # and the unthrottled solo reference (it raises on divergence, so
    # this ratio can never come from different math), and ownership
    # must actually have moved off the even split
    het = one_metric("hetero_balanced_tokens_per_sec")
    assert het["value"] > 0, het
    assert het["vs_baseline"] is not None and het["vs_baseline"] >= 1.25, (
        f"balanced split lost its speedup over the even split: {het}"
    )
    assert het["even_tokens_per_sec"] > 0, het
    counts = het["assignment_counts"]
    assert counts != [4, 4, 4], het  # the even split over 12 shards
    assert sum(counts) == 12 and min(counts) >= 1, het
    assert het["rebalances"] > 0, het
    assert "hetero" in pd[0]["value"], pd[0]
    assert durations.get("hetero", 999) < 300, durations

    # the pipeline phase (r20): the host-dispatched 1F1B executor must
    # beat the SPMD GPipe schedule >= 1.15x at the same (S=2, M=4) on
    # identical model/seed/batches (GPipe's garbage-tick floor is
    # (M+S-1)/M = 1.25x compute; the pin leaves room for ring handoff
    # overhead), with loss-curve agreement and compile-count==1
    # enforced INSIDE the phase (it raises, so the ratio can never
    # come from different math or a recompiling warm path)
    pl = one_metric("pipeline_1f1b_tokens_per_sec")
    assert pl["value"] > 0, pl
    assert pl["vs_baseline"] is not None and pl["vs_baseline"] >= 1.15, (
        f"1f1b lost its edge over the SPMD GPipe schedule: {pl}"
    )
    assert pl["spmd_gpipe_tokens_per_sec"] > 0, pl
    # ...and the measured steady-state bubble of a delay-shaped run
    # must land within +-0.12 of the analytic (S-1)/(M+S-1) = 0.2 the
    # planner prices, with the exposed-link ratio <= 0.40 and
    # delay-vs-plain CRC bit-identity enforced inside the phase
    bub = one_metric("pipeline_bubble_fraction")
    assert abs(bub["value"] - 0.2) <= 0.12, bub
    assert 0 <= bub["exposed_link_ratio"] <= 0.40, bub
    assert "pipeline" in pd[0]["value"], pd[0]
    assert durations.get("pipeline", 999) < 300, durations

    # the multihost phase (r16): 4 ranks in 2 shm domains with a TCP
    # inter-host leg throttled identically under both paths — the
    # hierarchical allreduce must beat flat-over-TCP >= 1.3x (analytic
    # ceiling 1.5x at H=2: it moves P vs flat's 1.5P over the slow
    # link), with bit-identity across ranks/paths/numpy and the EXACT
    # byte accounting both enforced INSIDE the phase (it raises, so
    # the ratio can never come from wrong math or miscounted bytes)
    mh = one_metric("multihost_hier_vs_flat_ratio")
    assert mh["value"] >= 1.3, (
        f"hierarchical allreduce lost its edge over flat-over-TCP: {mh}"
    )
    assert 0 < mh["wall_hier_s"] < mh["wall_flat_s"], mh
    mhb = one_metric("multihost_slow_link_bytes_per_step")
    # leader moves exactly 2(H-1)/H x payload = 4 MB at the bench shape;
    # flat moves exactly 2(w-1)/w x payload = 6 MB per rank
    assert mhb["value"] == 4 * (1 << 20), mhb
    assert mhb["flat_bytes_per_rank_per_step"] == 6 * (1 << 20), mhb
    assert mhb["bytes_exact"] is True, mhb
    assert "multihost" in pd[0]["value"], pd[0]
    assert durations.get("multihost", 999) < 120, durations

    # the disagg phase (r18): 2 prefill + 2 decode shipping int8 KV
    # frames over the real P2P ring, placement by the router's LPT —
    # must beat the BEST static independent split (indep-4 AND indep-2
    # both measured) >= 1.2x on the pinned heavy-tailed storm (priced
    # ceiling ~1.37x), with every stream verified bit-identical to the
    # delay-free solo reference INSIDE the phase (it raises, so the
    # ratio can never come from wrong tokens)
    dg = one_metric("disagg_fleet_tokens_per_sec")
    assert dg["value"] > 0, dg
    assert dg["vs_baseline"] is not None and dg["vs_baseline"] >= 1.2, (
        f"fleet lost its edge over the best independent split: {dg}"
    )
    assert 0 < dg["fleet_wall_s"] < min(
        dg["indep4_wall_s"], dg["indep2_wall_s"]
    ), dg
    # EXACT migration accounting: 32 requests x 3 pages each (24-token
    # prompts, 8-token pages), payload == pages x per-page bytes, and
    # the int8 (+ f32 scale sidecar) page <= 0.55x its f32 cost
    assert dg["migration_pages"] == 96, dg
    assert dg["migration_payload_bytes"] == (
        dg["migration_pages"] * dg["page_nbytes"]
    ), dg
    assert dg["bytes_exact"] is True, dg
    assert dg["int8_byte_ratio"] <= 0.55, dg
    # the in-process router storm: p99 TTFT under its pinned budget,
    # the shared system prompt prefilled once per FLEET (8 pages, the
    # peer prefill engine adopts from the store), and the engine-loss
    # drill replaying bit-identically (checked inside the phase)
    ttft = one_metric("disagg_storm_ttft_ms_p99")
    assert 0 < ttft["value"] <= 2500.0, ttft
    assert ttft["prefix_store_puts"] == 8, ttft
    assert ttft["prefix_store_hits"] >= 8, ttft
    assert ttft["loss_drill_replays"] >= 1, ttft
    assert ttft["storm_tokens_per_sec"] > 0, ttft
    assert "disagg" in pd[0]["value"], pd[0]
    assert durations.get("disagg", 999) < 300, durations

    # the ckpt_shard phase (r17): at replication=1 every rank of the
    # sharded save must write <= 1.2x its fair share of the full
    # checkpoint's bytes (the acceptance pin; replication=2 carries two
    # copies of every leaf, so its bound is the same pin scaled by 2),
    # with restore CRC-equality vs the source state enforced INSIDE the
    # phase — and the mid-distributed-save kill drill must pass: torn
    # epoch reads as absent, restart restores the newest world-COMPLETE
    # epoch, final params bit-identical to the uninterrupted reference
    cs = one_metric("ckpt_shard_rank_bytes_ratio")
    assert 0 < cs["value"] <= 1.2, (
        f"sharded save wrote more than its fair share per rank: {cs}"
    )
    assert 0 < cs["replication2_ratio"] <= 2.4, cs
    assert cs["manifest_shrink_r1"] >= 2, cs
    assert cs["full_bytes"] > 0 and len(cs["rank_bytes_r1"]) == 3, cs
    drill = one_metric("ckpt_shard_drill_wall_s")
    assert drill["passed"] is True, drill
    assert drill["torn_reads_absent"] is True, drill
    assert drill["newest_complete_step"] == 3, drill
    assert drill["bit_exact_vs_reference"] is True, drill
    assert "ckpt_shard" in pd[0]["value"], pd[0]
    assert durations.get("ckpt_shard", 999) < 120, durations

    # the comms phase: q8's RECORDED wire bytes at gradient size must be
    # <= 0.3x f32 (the encoding is int8 + one f32 scale per 256 elems,
    # ~0.254 — ROADMAP item 1's bytes-moved-reduction number, measured
    # off the comm.* span counters over a real 4-proc ring)
    comms = [
        json.loads(l) for l in proc.stderr.splitlines()
        if l.startswith("{")
        and json.loads(l)["metric"] == "comms_q8_wire_bytes_ratio"
    ]
    assert len(comms) == 1, proc.stderr[-2000:]
    assert 0.2 < comms[0]["value"] <= 0.3, comms[0]
    assert comms[0]["f32_busbw_gbps"] > 0, comms[0]
    assert comms[0]["q8_busbw_gbps"] > 0, comms[0]
    assert "comms" in pd[0]["value"], pd[0]
    assert durations.get("comms", 999) < 120, durations

    # the overlap phase (round 14): the bucketed pipelined grad sync
    # must beat the synchronous path >= 1.15x on the comm-heavy 3-proc
    # DDP config — with final params BIT-IDENTICAL and per-program
    # compile counts pinned INSIDE the phase (it raises on either, so
    # this ratio can never come from different math or a recompile) —
    # and the microbatch reduce schedule must hide >= half its comm
    # under in-flight compute (comm_exposed/comm_total <= 0.5, from the
    # engine's drain-block accounting)
    ov = one_metric("overlap_step_speedup")
    assert ov["value"] >= 1.15, (
        f"overlapped grad sync lost its speedup: {ov}"
    )
    assert ov["sync_step_ms"] > ov["overlap_step_ms"] > 0, ov
    assert ov["attempts"] <= 2, ov  # documented retry-once, never more
    ox = one_metric("overlap_comm_exposed_ratio")
    assert 0 <= ox["value"] <= 0.5, (
        f"microbatch schedule exposed too much comm: {ox}"
    )
    assert ox["mb_step_ms"] > 0, ox
    assert "overlap" in pd[0]["value"], pd[0]
    assert durations.get("overlap", 999) < 600, durations


@pytest.mark.slow
def test_bench_lock_serializes_runs(tmp_path):
    """Two benches may never overlap (VERDICT r4 weak #2: the driver's
    round-end bench contended with the capture loop and halved the feed
    metric). A second bench must block on the flock until the first
    exits, and say so on stderr. Runs on a PRIVATE lock path (env
    override) so the test neither queues behind a real bench nor
    deadlocks when a suite runner holds the machine-wide lock."""
    import fcntl

    lock_path = str(tmp_path / "bench.lock")
    lock_fd = os.open(lock_path, os.O_CREAT | os.O_RDWR, 0o666)
    proc = None
    try:
        fcntl.flock(lock_fd, fcntl.LOCK_EX)  # impersonate a running bench
        code = (
            f"import sys; sys.path.insert(0, {REPO!r}); import bench; "
            "bench._acquire_bench_lock(); print('LOCKED', flush=True)"
        )
        env = dict(
            os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
            PTD_BENCH_LOCK_PATH=lock_path,
        )
        proc = subprocess.Popen(
            [sys.executable, "-u", "-c", code], cwd=REPO, env=env,
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
        )
        # the waiting line is printed BEFORE the child's wait loop, so
        # reading it is the race-free "child is now queued" signal (a
        # fixed sleep loses on this contended 1-core rig)
        waiting_line = proc.stderr.readline()
        assert "bench lock held" in waiting_line, waiting_line
        assert proc.poll() is None, "second bench did not block on the lock"
        fcntl.flock(lock_fd, fcntl.LOCK_UN)
        out, err = proc.communicate(timeout=120)
        assert "LOCKED" in out
        assert "bench lock acquired" in err, err[-500:]
    finally:
        os.close(lock_fd)
        if proc is not None and proc.poll() is None:
            proc.kill()


@pytest.mark.slow
def test_tpu_only_phases_run_on_cpu_backend():
    """The phases the driver only exercises on the chip (gpt2 train-step
    tokens/s, dp-step overhead, decode incl. bf16-at-rest) must at least
    EXECUTE on the CPU backend — the r3 chip window lost both to bugs
    (donated shared init buffers; missing remat) that a CPU run of the
    same code paths would have caught first."""
    code = """
import jax
jax.config.update("jax_platforms", "cpu")
import bench
import pytorch_distributed_tpu as ptd
ptd.init_process_group()
bench.bench_dp_step_overhead(False)
bench.bench_gpt2(False)
bench.bench_generate(False)
print("PHASES-OK")
"""
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="")
    proc = subprocess.run(
        [sys.executable, "-c", code], cwd=REPO, env=env,
        capture_output=True, text=True, timeout=900,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    assert "PHASES-OK" in proc.stdout
    # each phase emitted its metric line (stdout or stderr notes)
    blob = proc.stdout + proc.stderr
    for metric in (
        "dp_step_overhead_ms",
        "gpt2_medium_tokens_per_sec_per_chip",
        "gpt2_decode_bf16_params_tokens_per_sec",
        "gpt2_decode_int4_scan_tokens_per_sec",
    ):
        assert metric in blob, metric
