"""Weight-only int8 quantization: error bounds, size, LM logit parity."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.quant import (
    dequantize_tree,
    quantize_tree_int8,
    quantized_apply_fn,
    quantized_bytes,
)


def test_roundtrip_error_bounded_and_selective():
    rng = np.random.default_rng(0)
    params = {
        "dense": {"kernel": jnp.asarray(
            rng.normal(size=(128, 64)).astype(np.float32)) * 0.1,
            "bias": jnp.asarray(rng.normal(size=(64,)).astype(np.float32))},
        "tiny": {"kernel": jnp.asarray(
            rng.normal(size=(4, 4)).astype(np.float32))},
        "ln": {"scale": jnp.ones((128,), jnp.float32)},
    }
    q = quantize_tree_int8(params)
    # 2-D large kernel quantized; bias/scale/tiny untouched
    assert set(q["dense"]["kernel"].keys()) == {"q8", "scale"}
    assert q["dense"]["kernel"]["q8"].dtype == jnp.int8
    assert q["tiny"]["kernel"].dtype == jnp.float32  # < min_size
    assert q["ln"]["scale"].dtype == jnp.float32
    d = dequantize_tree(q)
    k, dk = np.asarray(params["dense"]["kernel"]), np.asarray(d["dense"]["kernel"])
    # symmetric per-channel: error <= scale/2 elementwise
    half_scale = np.asarray(q["dense"]["kernel"]["scale"])[0] / 2
    assert (np.abs(k - dk) <= half_scale[None, :] + 1e-8).all()
    np.testing.assert_array_equal(
        np.asarray(d["dense"]["bias"]), np.asarray(params["dense"]["bias"])
    )
    # ~4x smaller than f32 for the quantized leaf
    nbytes = quantized_bytes(q)
    full = sum(x.size * 4 for x in jax.tree_util.tree_leaves(params))
    assert nbytes < full * 0.35, (nbytes, full)

    # include= restricts by path
    q2 = quantize_tree_int8(params, include=(r"nothing-matches",))
    assert q2["dense"]["kernel"].dtype == jnp.float32


@pytest.mark.slow
def test_gpt2_int8_logits_close_and_generates():
    from pytorch_distributed_tpu.models import GPT2Config, GPT2LMHead
    from pytorch_distributed_tpu import generation

    cfg = GPT2Config.tiny()
    model = GPT2LMHead(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(1, cfg.vocab_size, size=(2, 12))
    ).astype(jnp.int32)
    v = model.init(jax.random.key(0), ids)
    logits = model.apply(v, ids)

    qparams = quantize_tree_int8(v["params"], min_size=1024)
    apply8 = quantized_apply_fn(model)
    logits8 = jax.jit(apply8)({"params": qparams}, ids)
    # logit error small relative to logit scale
    err = float(jnp.max(jnp.abs(logits8 - logits)))
    spread = float(jnp.std(logits))
    assert err < 0.25 * spread, (err, spread)

    # generation end-to-end on the quantized tree: int8 at rest, the
    # bf16 kernels exist only inside the jitted call
    @jax.jit
    def gen(qp, prompt):
        return generation.generate(
            model, dequantize_tree(qp), prompt, max_new_tokens=4,
        )

    out = gen(qparams, ids[:, :4])
    assert out.shape == (2, 8)
    # greedy tokens from the quantized model match the full-precision
    # model on this tiny config (logit gaps >> quantization error)
    full = generation.generate(
        model, v["params"], ids[:, :4], max_new_tokens=4
    )
    assert (np.asarray(out) == np.asarray(full)).mean() > 0.7, (
        out, full,
    )


def test_quantize_idempotent():
    rng = np.random.default_rng(2)
    params = {"k": jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32))}
    q1 = quantize_tree_int8(params)
    q2 = quantize_tree_int8(q1)
    assert set(q2["k"].keys()) == {"q8", "scale"}
    np.testing.assert_array_equal(
        np.asarray(q1["k"]["q8"]), np.asarray(q2["k"]["q8"])
    )
    dequantize_tree(q2)  # no crash on the (non-)nested tree


class TestInt4:
    def test_roundtrip_error_bounded_groupwise(self):
        from pytorch_distributed_tpu.ops import (
            dequantize_tree,
            quantize_tree_int4,
        )
        from pytorch_distributed_tpu.ops.quant import quantized_bytes

        rng = np.random.default_rng(0)
        w = jnp.asarray(rng.normal(size=(256, 128)).astype(np.float32))
        tree = {"k": {"kernel": w}}
        q = quantize_tree_int4(tree, group_size=64)
        leaf = q["k"]["kernel"]
        assert leaf["q4"].shape == (256, 64)  # out pairs packed
        assert leaf["scale"].shape == (4, 1, 128)  # 256/64 groups
        back = dequantize_tree(q)["k"]["kernel"]
        # per-group bound: |err| <= scale/2 for that (group, out channel)
        err = np.abs(np.asarray(back - w))
        bound = np.repeat(np.asarray(leaf["scale"])[:, 0, :], 64, axis=0)
        assert (err <= bound / 2 + 1e-6).all()
        # ~0.5 byte/weight + scales
        assert quantized_bytes(q) < w.size * 0.6 + leaf["scale"].size * 4

    def test_groupwise_beats_global_scale_on_outliers(self):
        from pytorch_distributed_tpu.ops import (
            dequantize_tree,
            quantize_tree_int4,
        )

        rng = np.random.default_rng(1)
        w = rng.normal(size=(256, 64)).astype(np.float32)
        w[:8] *= 100.0  # one group of outlier rows
        tree = {"kernel": jnp.asarray(w)}
        fine = dequantize_tree(quantize_tree_int4(tree, group_size=8))
        coarse = dequantize_tree(
            quantize_tree_int4(tree, group_size=256)
        )
        clean = slice(8, None)
        err_fine = np.abs(np.asarray(fine["kernel"])[clean] - w[clean]).max()
        err_coarse = np.abs(
            np.asarray(coarse["kernel"])[clean] - w[clean]
        ).max()
        # with one global group the outlier rows stretch every scale;
        # groupwise isolates them
        assert err_fine < err_coarse / 10

    def test_odd_out_and_small_leaves_skipped(self):
        from pytorch_distributed_tpu.ops import quantize_tree_int4

        tree = {
            "odd": jnp.ones((128, 65)),   # odd out axis: can't pack pairs
            "tiny": jnp.ones((4, 4)),     # < min_size
            "bias": jnp.ones((128,)),     # 1-D
        }
        q = quantize_tree_int4(tree)
        assert q["odd"] is tree["odd"]
        assert q["tiny"] is tree["tiny"]
        assert q["bias"] is tree["bias"]

    def test_int4_idempotent_and_mixed_with_int8(self):
        from pytorch_distributed_tpu.ops import (
            dequantize_tree,
            quantize_tree_int4,
            quantize_tree_int8,
        )

        rng = np.random.default_rng(2)
        tree = {
            "a": jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32)),
            "b": jnp.asarray(rng.normal(size=(128, 64)).astype(np.float32)),
        }
        q8 = quantize_tree_int8({"b": tree["b"]})
        mixed = {"a": quantize_tree_int4({"a": tree["a"]})["a"], **q8}
        again = quantize_tree_int4(mixed)  # both leaf kinds pass through
        assert again["a"] is mixed["a"]
        assert again["b"] is mixed["b"]
        back = dequantize_tree(mixed)
        assert back["a"].shape == (128, 64)
        assert back["b"].shape == (128, 64)

    @pytest.mark.slow
    def test_gpt2_int4_decode_mostly_agrees(self):
        from pytorch_distributed_tpu.generation import generate
        from pytorch_distributed_tpu.models import GPT2Config, GPT2LMHead
        from pytorch_distributed_tpu.ops import (
            QuantizedModel,
            quantize_tree_int4,
        )

        cfg = GPT2Config.tiny()
        model = GPT2LMHead(cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(
                1, cfg.vocab_size, size=(2, 8)
            )
        ).astype(jnp.int32)
        params = model.init(jax.random.key(0), ids)["params"]
        q = quantize_tree_int4(params, group_size=32, min_size=512)
        full = generate(model, params, ids, max_new_tokens=12,
                        temperature=0.0)
        quant = generate(QuantizedModel(model), q, ids, max_new_tokens=12,
                         temperature=0.0)
        agree = (
            np.asarray(full)[:, ids.shape[1]:]
            == np.asarray(quant)[:, ids.shape[1]:]
        ).mean()
        # int4 is lossier than int8; random tiny weights are the worst
        # case, yet the argmax chain should still mostly hold
        assert agree > 0.4, agree


def _n_quantized(tree):
    from pytorch_distributed_tpu.ops.quant import _is_qleaf

    return sum(
        1 for leaf in jax.tree_util.tree_leaves(tree, is_leaf=_is_qleaf)
        if _is_qleaf(leaf)
    )


class TestScanDequant:
    """Per-layer dequantization inside the scan (models/scan.py): the
    single-chip big-model serving path. The stored tree is the ordinary
    quantizer output on the stacked kernels; map_variables dequantizes
    one layer's slice per scan tick, so peak weight residency is
    quantized-tree + one layer — and the result is BITWISE the
    whole-tree dequant wrapper's."""

    def _gpt2(self):
        import dataclasses

        from pytorch_distributed_tpu.models import GPT2Config, GPT2LMHead

        cfg = GPT2Config(
            vocab_size=128, n_positions=64, hidden_size=64, num_layers=3,
            num_heads=4, dropout_rate=0.0,
        )
        model = GPT2LMHead(cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(
                128, size=(2, 10)
            ).astype(np.int32)
        )
        params = model.init(jax.random.key(0), ids)["params"]
        qmodel = GPT2LMHead(dataclasses.replace(cfg, scan_dequant=True))
        return model, qmodel, params, ids

    @pytest.mark.slow  # r5 profile refit: llama8b rehearsal (slow) + decode-agreement tests cover scan_dequant
    def test_gpt2_per_layer_equals_whole_tree(self):
        from pytorch_distributed_tpu.ops import (
            QuantizedModel,
            quantize_tree_int4,
        )

        model, qmodel, params, ids = self._gpt2()
        from pytorch_distributed_tpu.ops import quantize_for_scan_dequant

        q = quantize_for_scan_dequant(params, "int4", min_size=512)
        assert _n_quantized(q) > 0  # a stale include regex would make
        # every equality below vacuous (unquantized == unquantized)
        a = QuantizedModel(model).apply({"params": q}, ids)
        b = qmodel.apply({"params": q}, ids)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
        # plain trees pass through the mapped scan unchanged
        c = qmodel.apply({"params": params}, ids)
        d = model.apply({"params": params}, ids)
        np.testing.assert_array_equal(np.asarray(c), np.asarray(d))

    @pytest.mark.slow
    def test_gpt2_decode_through_per_layer_dequant(self):
        from pytorch_distributed_tpu import generation
        from pytorch_distributed_tpu.ops import (
            QuantizedModel,
            quantize_tree_int8,
        )

        model, qmodel, params, ids = self._gpt2()
        from pytorch_distributed_tpu.ops import quantize_for_scan_dequant

        q = quantize_for_scan_dequant(params, "int8", min_size=512)
        assert _n_quantized(q) > 0
        a = generation.generate(
            qmodel, q, ids[:, :5], max_new_tokens=6, temperature=0.0
        )
        b = generation.generate(
            QuantizedModel(model), q, ids[:, :5],
            max_new_tokens=6, temperature=0.0,
        )
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    @pytest.mark.slow
    def test_llama_per_layer_equals_whole_tree(self):
        import dataclasses

        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig,
            LlamaForCausalLM,
        )
        from pytorch_distributed_tpu.ops import (
            QuantizedModel,
            quantize_tree_int4,
        )

        cfg = LlamaConfig(
            vocab_size=96, hidden_size=64, num_layers=3, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=64,
        )
        model = LlamaForCausalLM(cfg)
        ids = jnp.asarray(
            np.random.default_rng(1).integers(
                96, size=(2, 8)
            ).astype(np.int32)
        )
        params = model.init(jax.random.key(0), ids)["params"]
        from pytorch_distributed_tpu.ops import quantize_for_scan_dequant

        q = quantize_for_scan_dequant(params, "int4", min_size=512)
        assert _n_quantized(q) > 0
        a = QuantizedModel(model).apply({"params": q}, ids)
        qmodel = LlamaForCausalLM(
            dataclasses.replace(cfg, scan_dequant=True)
        )
        b = qmodel.apply({"params": q}, ids)
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))

    def test_scan_dequant_requires_scan_layers(self):
        import dataclasses

        from pytorch_distributed_tpu.models import GPT2Config

        with pytest.raises(ValueError, match="requires scan_layers"):
            GPT2Config(scan_layers=False, scan_dequant=True)

    def test_stacked_bias_quantization_is_loud(self):
        from pytorch_distributed_tpu.ops import (
            dequantize_tree,
            quantize_tree_int4,
        )

        # a stacked [L, n] bias is indistinguishable from a matrix at
        # quantize time; slicing it per layer must fail with guidance,
        # not an opaque index error
        stacked_bias = {"b": jnp.ones((4, 512), jnp.float32)}
        q = quantize_tree_int4(stacked_bias, min_size=256)
        sliced = {"b": jax.tree_util.tree_map(lambda x: x[0], q["b"])}
        with pytest.raises(ValueError, match="STACKED BIAS"):
            dequantize_tree(sliced)


@pytest.mark.slow
def test_scan_dequant_peak_memory_is_per_layer():
    """The residency claim, MEASURED: XLA's own memory analysis shows the
    per-layer path's temp allocation is a small fraction of the
    whole-tree dequant's (which materializes every reconstructed layer at
    once). At L=8 the measured ratio is ~8.5x; the pin at 4x leaves
    headroom for scheduler changes while still proving the mechanism."""
    import dataclasses

    from pytorch_distributed_tpu.models import GPT2Config, GPT2LMHead
    from pytorch_distributed_tpu.ops import (
        QuantizedModel,
        quantize_for_scan_dequant,
    )

    cfg = GPT2Config(
        vocab_size=256, n_positions=64, hidden_size=256, num_layers=8,
        num_heads=4, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    ids = jnp.zeros((1, 16), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    q = quantize_for_scan_dequant(params, "int4")
    assert _n_quantized(q) > 0
    qmodel = GPT2LMHead(dataclasses.replace(cfg, scan_dequant=True))

    def temp_bytes(f):
        stats = jax.jit(f).lower(q).compile().memory_analysis()
        if stats is None:  # backend without analysis: nothing to pin
            pytest.skip("backend exposes no memory analysis")
        return stats.temp_size_in_bytes

    per_layer = temp_bytes(lambda p: qmodel.apply({"params": p}, ids))
    whole = temp_bytes(
        lambda p: QuantizedModel(model).apply({"params": p}, ids)
    )
    assert per_layer * 4 < whole, (per_layer, whole)
