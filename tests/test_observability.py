"""Observability subsystem (runtime/tracing.py + the unified timers).

The contracts under test: spans nest and order correctly in a
Perfetto-loadable trace.json; the recompile sentinel fires on a
steady-state recompile and stays silent on a steady loop; goodput
buckets always sum to wall time (including under injected faults); the
disarmed path is a single is-None test returning one shared no-op
object; the torn-final-line chaos scenario no longer breaks
``read_metrics``; and ScalarMeter/StepTimer/ServeTelemetry all report
percentiles through the one shared helper.
"""

import contextlib
import json
import logging
import os
import subprocess
import sys
import time

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime import faults, tracing
from pytorch_distributed_tpu.runtime.compat import (
    jit_cache_size,
    live_buffer_bytes,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import (
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
)
from pytorch_distributed_tpu.train.metrics import (
    MeterState,
    MetricsWriter,
    ScalarMeter,
    read_metrics,
)
from pytorch_distributed_tpu.utils.profiler import StepTimer
from pytorch_distributed_tpu.utils.timing import WindowTimer, percentile

pytestmark = pytest.mark.obs


@contextlib.contextmanager
def ptd_caplog(caplog, level="WARNING"):
    """Route the repo's namespace logger (propagate=False, own handler)
    into caplog, which only listens on the root logger."""
    ns = logging.getLogger("pytorch_distributed_tpu")
    ns.addHandler(caplog.handler)
    try:
        with caplog.at_level(level, logger="pytorch_distributed_tpu"):
            yield caplog
    finally:
        ns.removeHandler(caplog.handler)


# -- the disarmed path -----------------------------------------------------
class TestDisarmed:
    def test_disabled_span_is_one_shared_noop(self):
        tracing.clear()
        assert not tracing.active()
        s1 = tracing.span("train.step")
        s2 = tracing.span("serve.decode_tick", active=3)
        # the faults.py discipline: a single module-global is-None test,
        # then ONE shared object — no allocation per site
        assert s1 is s2 is tracing._NULL_SPAN
        with s1:
            pass  # reentrant, no-op
        assert tracing.instant("x", a=1) is None
        assert tracing.counter("x", 1.0) is None
        assert tracing.note_compiles("x", 5) is None

    def test_disabled_sites_are_cheap(self):
        tracing.clear()
        t0 = time.perf_counter()
        for _ in range(100_000):
            with tracing.span("hot"):
                pass
        dt = time.perf_counter() - t0
        # generous bound (contended 1-core box): the point is "no clock
        # read, no dict, no allocation per call", not a microbenchmark
        assert dt < 1.0, f"{dt:.3f}s for 100k disarmed spans"


# -- recording -------------------------------------------------------------
class TestSpans:
    def test_nesting_and_ordering(self):
        with tracing.enabled() as t:
            with tracing.span("outer", phase="a"):
                time.sleep(0.002)
                with tracing.span("inner"):
                    time.sleep(0.002)
                time.sleep(0.002)
        ev = {e["name"]: e for e in t._events}
        inner, outer = ev["inner"], ev["outer"]
        # inner completes first, so it lands in the buffer first
        assert [e["name"] for e in t._events] == ["inner", "outer"]
        # and its interval is contained in outer's
        assert outer["ts"] <= inner["ts"]
        assert (inner["ts"] + inner["dur"]) <= (outer["ts"] + outer["dur"])
        assert outer["args"] == {"phase": "a"}
        assert inner["tid"] == outer["tid"]

    def test_trace_json_schema(self, tmp_path):
        with tracing.enabled(str(tmp_path)) as t:
            with tracing.span("a", k=1):
                pass
            tracing.instant("marker", why="test")
            tracing.counter("gauge", 42.0)
            path = t.export()
        assert path == str(tmp_path / "trace.json")
        doc = json.load(open(path))
        assert isinstance(doc["traceEvents"], list)
        assert doc["displayTimeUnit"] == "ms"
        assert doc["otherData"]["dropped_events"] == 0
        phs = sorted(e["ph"] for e in doc["traceEvents"])
        assert phs == ["C", "X", "i"]
        for e in doc["traceEvents"]:
            for key in ("name", "ph", "ts", "pid", "tid"):
                assert key in e, e
            if e["ph"] == "X":
                assert e["dur"] >= 0

    def test_buffer_cap_drops_loudly_but_rollups_keep_counting(self):
        with tracing.enabled(max_events=10) as t:
            for _ in range(25):
                with tracing.span("spin"):
                    pass
        assert len(t._events) == 10
        assert t.dropped == 15
        assert t.rollups()["spin"]["count"] == 25  # aggregates uncapped

    def test_rollup_memory_bounded_but_aggregates_exact(self):
        """A run longer than sample_cap keeps exact count/total/max
        (scalars) while the percentile sample stays bounded."""
        t = tracing.Tracer(max_events=10, sample_cap=8)
        durs = [0.001 * i for i in range(1, 21)]
        for d in durs:
            t.complete("x", None, 0.0, d)
        assert len(t._samples["x"]) == 8  # bounded (the newest 8)
        roll = t.rollups()["x"]
        assert roll["count"] == 20
        assert roll["total_ms"] == pytest.approx(sum(durs) * 1e3)
        assert roll["max_ms"] == pytest.approx(max(durs) * 1e3)
        # percentiles come from the retained window
        assert roll["p50_ms"] == pytest.approx(
            percentile(durs[-8:], 50) * 1e3
        )

    def test_rollup_percentiles_match_shared_helper(self):
        t = tracing.Tracer()
        durs = [0.001 * i for i in range(1, 21)]
        for d in durs:
            t.complete("x", None, 0.0, d)
        roll = t.rollups()["x"]
        assert roll["count"] == 20
        assert roll["p95_ms"] == pytest.approx(percentile(durs, 95) * 1e3)
        assert roll["p50_ms"] == pytest.approx(percentile(durs, 50) * 1e3)
        assert roll["max_ms"] == pytest.approx(max(durs) * 1e3)

    def test_write_rollups_speaks_metrics_protocol(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        t = tracing.Tracer()
        t.complete("a", None, 0.0, 0.5)
        t.note_compiles("f", 1)
        t.note_compiles("f", 3)  # 2 recompiles after warm-up
        with MetricsWriter(path) as w:
            t.write_rollups(w, step=7)
        recs = read_metrics(path)
        spans = [r for r in recs if r.get("event") == "span_rollup"]
        assert [r["span"] for r in spans] == ["a"]
        assert all(r["split"] == "trace" for r in recs)
        rc = [r for r in recs if r.get("event") == "recompiles"]
        assert rc[0]["recompiles_total"] == 2
        assert rc[0]["recompiles.f"] == 2


# -- recompile sentinel ----------------------------------------------------
class TestRecompileSentinel:
    def test_fires_on_shape_change_silent_on_steady_loop(self, caplog):
        f = jax.jit(lambda x: x * 2.0)
        with tracing.enabled() as t:
            f(jnp.ones(4))
            n = jit_cache_size(f)
            assert n is not None and n >= 1  # the poll works on this jax
            tracing.note_compiles("f", n)  # warm-up baseline
            with ptd_caplog(caplog):
                for _ in range(5):  # steady loop: same shape, no firing
                    f(jnp.ones(4))
                    tracing.note_compiles("f", jit_cache_size(f))
                assert t.recompiles == {}
                assert not any(
                    "RECOMPILE" in r.message for r in caplog.records
                )
                f(jnp.ones(5))  # the classic silent regression
                tracing.note_compiles("f", jit_cache_size(f))
            assert t.recompiles == {"f": 1}
            assert any("RECOMPILE" in r.message for r in caplog.records)
            # and it is marked on the timeline
            marks = [e for e in t._events if e["name"] == "recompile"]
            assert marks and marks[0]["args"]["callable"] == "f"

    def test_serve_engine_counters_wired(self):
        """A steady serve workload reports its compile counters through
        the sentinel (baseline only — no recompile), and the engine tick
        lands serve.* spans on the timeline."""
        from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
        from pytorch_distributed_tpu.serve import (
            EngineConfig,
            Request,
            ServeEngine,
        )

        cfg = GPT2Config(
            vocab_size=61, n_positions=32, hidden_size=16, num_layers=1,
            num_heads=2, dropout_rate=0.0,
        )
        model = GPT2LMHead(cfg)
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
        )["params"]
        rng = np.random.default_rng(0)
        with tracing.enabled() as t:
            engine = ServeEngine(model, params, EngineConfig(
                num_slots=2, max_len=16, prefill_chunk=4,
            ))
            for _ in range(3):
                engine.submit(Request(
                    rng.integers(1, 61, size=5).astype(np.int32),
                    max_new_tokens=4,
                ))
            engine.run_until_drained()
            names = {e["name"] for e in t._events}
            assert {"serve.prefill_chunk", "serve.decode_tick",
                    "serve.token_fetch", "serve.admit",
                    "serve.evict"} <= names
            # one compile per program (the engine invariant) -> baseline
            # recorded, zero recompiles
            assert t._compiles["serve.decode"] == 1
            assert t._compiles["serve.prefill"] == 1
            assert t.recompiles == {}


# -- goodput ---------------------------------------------------------------
class TestGoodput:
    def test_buckets_sum_to_wall_fake_clock(self):
        now = [100.0]
        g = tracing.GoodputAccount(clock=lambda: now[0])
        now[0] += 10.0
        g.add("productive", 6.0)
        g.add("recovering", 1.5)
        g.add("stalled", 0.5)
        s = g.summary()
        total = sum(
            v for k, v in s.items()
            if k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(s["wall_s"])
        assert s["goodput_pct"] == pytest.approx(60.0)
        assert s["other_s"] == pytest.approx(2.0)

    def test_resize_bucket_reported_and_sums_to_wall(self):
        """The elastic-world bucket (r13): ``resize`` is a first-class
        goodput bucket — always present in the summary (0.0 when no
        resize happened), and the sum-to-wall invariant holds with it
        charged."""
        assert "resize" in tracing.GOODPUT_BUCKETS
        now = [0.0]
        g = tracing.GoodputAccount(clock=lambda: now[0])
        now[0] += 20.0
        g.add("productive", 12.0)
        g.add("resize", 3.0)
        g.add("recovering", 2.0)
        g.add("checkpoint", 1.0)
        s = g.summary()
        assert s["resize_s"] == pytest.approx(3.0)
        total = sum(
            v for k, v in s.items()
            if k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(s["wall_s"])
        assert s["other_s"] == pytest.approx(2.0)
        # an account that never resized still REPORTS the bucket: a
        # dashboard diffing runs must not see a schema change
        empty = tracing.GoodputAccount(clock=lambda: now[0]).summary()
        assert empty["resize_s"] == 0.0

    def test_rebalance_bucket_reported_and_sums_to_wall(self):
        """The heterogeneity-balancer bucket (r15): ``rebalance`` is a
        first-class goodput bucket — the rate-allgather + assignment
        derivation at each boundary is priced separately, so the bench
        ``hetero`` phase's balancing win is net of what the balancer
        itself costs — and the sum-to-wall invariant holds with it
        charged."""
        assert "rebalance" in tracing.GOODPUT_BUCKETS
        now = [0.0]
        g = tracing.GoodputAccount(clock=lambda: now[0])
        now[0] += 10.0
        g.add("productive", 7.0)
        g.add("rebalance", 0.5)
        g.add("resize", 1.5)
        s = g.summary()
        assert s["rebalance_s"] == pytest.approx(0.5)
        total = sum(
            v for k, v in s.items()
            if k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(s["wall_s"])
        assert s["other_s"] == pytest.approx(1.0)
        # never-rebalanced accounts still report the bucket (schema)
        empty = tracing.GoodputAccount(clock=lambda: now[0]).summary()
        assert empty["rebalance_s"] == 0.0
        # ...and summarize_goodput carries it through the JSONL account
        summ = tracing.summarize_goodput(
            [{"split": "goodput", "rebalance_s": 0.25, "wall_s": 1.0,
              "productive_s": 0.75}]
        )
        assert summ["rebalance_s"] == pytest.approx(0.25)

    def test_buckets_sum_to_wall_under_injected_faults(self, tmp_path):
        """End to end: a Trainer run with PTD_FAULTS armed (a step.nan
        injection plus a checkpoint cadence) still accounts every wall
        second into a bucket."""
        make_mesh(MeshSpec(dp=8))
        dp = DataParallel()

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        state = TrainState.create(
            apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.ones((4, 2))}, tx=optax.sgd(0.05),
        )
        rng = np.random.default_rng(0)
        ds = ArrayDataset(
            x=rng.normal(size=(64, 4)).astype(np.float32),
            y=rng.normal(size=(64, 2)).astype(np.float32),
        )
        metrics_path = str(tmp_path / "m.jsonl")
        trainer = Trainer(
            state, dp, build_train_step(loss_fn),
            DataLoader(ds, 16, sharding=dp.batch_sharding()),
            config=TrainerConfig(
                epochs=2, log_every=1, metrics_path=metrics_path,
                ckpt_dir=str(tmp_path / "ckpt"), ckpt_every_steps=3,
                halt_on_nonfinite=0,  # survive the injected NaN
            ),
        )
        with faults.injected("step.nan:mode=raise,count=1"):
            trainer.fit()
        recs = read_metrics(metrics_path)
        g = [r for r in recs if r["split"] == "goodput"]
        assert len(g) == 1
        s = g[0]
        total = sum(
            v for k, v in s.items()
            if isinstance(v, float) and k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(s["wall_s"], rel=0.02)
        assert s["productive_s"] > 0
        assert s["checkpoint_s"] > 0  # the ckpt cadence was attributed
        # every train log record carries the running goodput_pct
        train_recs = [r for r in recs if r["split"] == "train"]
        assert train_recs and all("goodput_pct" in r for r in train_recs)

    def test_retract_reclassifies_resolved_stall(self):
        """A watchdog stall that resolves inside an attributed section
        (a slow-but-progressing op) must not be double-billed: the
        section's bucket covers its wall, the stalled seconds retract,
        and the buckets keep summing to wall."""
        now = [0.0]
        g = tracing.GoodputAccount(clock=lambda: now[0])
        now[0] += 10.0
        g.add("stalled", 3.0)  # watchdog fired mid-fetch...
        g.add("productive", 9.0)  # ...but the fetch returned
        g.retract("stalled", 3.0)
        s = g.summary()
        assert s["stalled_s"] == 0.0
        assert s["productive_s"] == 9.0
        total = sum(
            v for k, v in s.items()
            if k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(s["wall_s"])
        g.retract("stalled", 99.0)  # clamped at balance, never negative
        assert g.buckets["stalled"] == 0.0

    def test_summarize_goodput_across_attempts(self):
        recs = [
            {"split": "goodput", "wall_s": 10.0, "productive_s": 6.0,
             "recovering_s": 1.0},
            {"split": "goodput", "wall_s": 5.0, "productive_s": 4.0,
             "checkpoint_s": 0.5},
            {"split": "train", "loss": 1.0},
        ]
        g = tracing.summarize_goodput(recs)
        assert g["attempts_recorded"] == 2
        assert g["productive_s"] == pytest.approx(10.0)
        assert g["goodput_pct"] == pytest.approx(100 * 10.0 / 15.0, abs=0.01)
        # a drill passes its own wall (restart gaps included)
        g2 = tracing.summarize_goodput(recs, wall_s=20.0)
        assert g2["goodput_pct"] == pytest.approx(50.0)
        assert g2["wall_s"] == 20.0


# -- the one-flag trainer path --------------------------------------------
class TestTrainerTraceFlag:
    def test_trace_flag_produces_timeline_and_rollups(self, tmp_path):
        make_mesh(MeshSpec(dp=8))
        dp = DataParallel()

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        state = TrainState.create(
            apply_fn=lambda p, x: x @ p["w"],
            params={"w": jnp.ones((4, 2))}, tx=optax.sgd(0.05),
        )
        rng = np.random.default_rng(0)
        ds = ArrayDataset(
            x=rng.normal(size=(64, 4)).astype(np.float32),
            y=rng.normal(size=(64, 2)).astype(np.float32),
        )
        metrics_path = str(tmp_path / "m.jsonl")
        trainer = Trainer(
            state, dp, build_train_step(loss_fn),
            DataLoader(ds, 16, sharding=dp.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=2, metrics_path=metrics_path,
                ckpt_dir=str(tmp_path / "ckpt"),
                trace=str(tmp_path),
            ),
        )
        # armed at CONSTRUCTION, not fit(): every recipe restores before
        # fitting, and the train.restore span must land on the timeline
        assert tracing.active()
        trainer.restore_checkpoint()  # nothing on disk — span still lands
        trainer.fit()
        assert not tracing.active()  # fit() disarms its own tracer
        doc = json.load(open(tmp_path / "trace.json"))
        names = {e["name"] for e in doc["traceEvents"]}
        # trainer spans AND ingest spans (producer thread) on one timeline
        assert {"train.step", "train.data_wait", "train.metric_fetch",
                "train.checkpoint", "train.restore", "ingest.fetch",
                "ingest.place"} <= names
        # ingest spans really ride the producer thread's own track
        tids = {
            e["name"]: e["tid"] for e in doc["traceEvents"]
            if e["ph"] == "X"
        }
        assert tids["ingest.fetch"] != tids["train.step"]
        # rollups + device memory gauge landed in the metrics stream
        recs = read_metrics(metrics_path)
        spans = {
            r["span"] for r in recs if r.get("event") == "span_rollup"
        }
        assert "train.step" in spans and "ingest.fetch" in spans
        train_recs = [r for r in recs if r["split"] == "train"]
        assert any("device_bytes_in_use" in r for r in train_recs)

    def test_obs_report_renders_run_dir(self, tmp_path, capsys):
        """scripts/obs_report.py turns the flag's output into the
        breakdown + goodput report."""
        with tracing.enabled(str(tmp_path)) as t:
            with tracing.span("train.step"):
                time.sleep(0.001)
            t.note_compiles("train.step", 1)
            t.note_compiles("train.step", 2)
            t.export()
        with MetricsWriter(str(tmp_path / "m.jsonl")) as w:
            g = tracing.GoodputAccount()
            g.add("productive", 0.5)
            w.write(1, {"event": "goodput", **g.summary()},
                    split="goodput")
            # two attempts' recompile records SUM (each fit() has a
            # fresh tracer); trace.json duplicates the last attempt's
            # count (1) and must merge by max, not add
            w.write(1, {"event": "recompiles", "recompiles_total": 2,
                        "recompiles.train.step": 2}, split="trace")
            w.write(2, {"event": "recompiles", "recompiles_total": 1,
                        "recompiles.train.step": 1}, split="trace")
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        rc = obs_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Step-phase breakdown" in out
        assert "train.step" in out
        assert "INVESTIGATE" in out  # the recompile was surfaced
        # summed across attempt records (2+1), trace's 1 merged by max
        assert "train.step: 3 steady-state" in out
        assert "Goodput" in out

    def test_obs_report_stragglers_section(self, tmp_path, capsys):
        """r15: the Stragglers section renders all three inputs — the
        per-rank step skew from a merged trace (pid = rank after
        trace_merge), the ``train.rank_skew`` gauge the rebalancer
        emits, and the ``split="elastic"`` rebalance audit records —
        and a run with none of them prints no section at all."""
        # a merged-trace shape: rank 1's steps take 2x rank 0's
        events = []
        for rank, dur_us in ((0, 10_000.0), (1, 20_000.0)):
            for k in range(3):
                events.append({
                    "name": "elastic.step", "ph": "X", "pid": rank,
                    "tid": 0, "ts": k * 30_000.0, "dur": dur_us,
                })
        events.append({
            "name": "train.rank_skew", "ph": "C", "pid": 0, "tid": 0,
            "ts": 0.0, "args": {"value": 2.0},
        })
        (tmp_path / "trace.json").write_text(json.dumps(
            {"traceEvents": events, "otherData": {}}
        ))
        with MetricsWriter(str(tmp_path / "m.jsonl")) as w:
            w.write(8, {"event": "rebalance", "reason": "interval",
                        "counts": [8, 4], "skew": 2.0,
                        "changed": True}, split="elastic")
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        rc = obs_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Stragglers" in out
        assert "step-time skew (slowest/fastest rank): 2.00x" in out
        assert "train.rank_skew gauge: last 2.00x" in out
        assert "counts=[8, 4]" in out and "moved" in out
        # silent when a run carries none of the three inputs
        solo = tmp_path / "solo"
        solo.mkdir()
        with MetricsWriter(str(solo / "m.jsonl")) as w:
            w.write(1, {"loss": 1.0}, split="train")
        assert obs_report.main([str(solo)]) == 0
        assert "Stragglers" not in capsys.readouterr().out

    def test_obs_report_fleet_section(self, tmp_path, capsys):
        """r18: the Fleet section renders per-engine request/TTFT/
        occupancy lines from engine_id-labeled serve records plus the
        router's migrate/replay audit — and a single-engine run (no
        engine_id label, no router records) keeps the old Serving
        section and prints no Fleet section at all."""
        with MetricsWriter(str(tmp_path / "m.jsonl")) as w:
            for eid, ttft in (("d0", 40.0), ("d0", 60.0), ("d1", 90.0)):
                w.write(1, {"event": "request", "engine_id": eid,
                            "request_id": "r", "status": "completed",
                            "prompt_tokens": 8, "new_tokens": 4,
                            "ttft_ms": ttft}, split="serve")
            w.write(2, {"event": "snapshot", "engine_id": "d0",
                        "queue_depth": 0, "slots_occupied": 2,
                        "slots_total": 4, "slot_occupancy": 0.5,
                        "decode_ticks": 9}, split="serve")
            w.write(3, {"event": "migrate", "engine_id": "p0",
                        "dst": "d0", "request_id": "r", "nbytes": 2000,
                        "payload_nbytes": 1280, "n_pages": 1},
                    split="serve")
            w.write(4, {"event": "replay", "engine_id": "d1",
                        "dst": "d0", "request_id": "r"}, split="serve")
        sys.path.insert(0, os.path.join(
            os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
            "scripts",
        ))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        rc = obs_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "Fleet" in out
        assert "2 engine(s)" in out
        assert "d0" in out and "d1" in out
        assert "2 completed" in out  # d0's two requests grouped
        assert "occupancy last 0.50" in out
        assert "1 frame(s), 1 page(s)" in out
        assert "re-admitted after losing d1" in out
        # single-engine runs (no engine_id label) stay Serving-only
        solo = tmp_path / "solo"
        solo.mkdir()
        with MetricsWriter(str(solo / "m.jsonl")) as w:
            w.write(1, {"event": "request", "request_id": "r",
                        "status": "completed", "prompt_tokens": 8,
                        "new_tokens": 4, "ttft_ms": 12.0},
                    split="serve")
        assert obs_report.main([str(solo)]) == 0
        solo_out = capsys.readouterr().out
        assert "Fleet" not in solo_out
        assert "Serving" in solo_out


# -- torn metrics (the PR 2 chaos scenario) --------------------------------
class TestTornMetrics:
    def test_read_metrics_skips_torn_final_line(self, tmp_path, caplog):
        """A writer SIGKILLed mid-record (os._exit: no flush ordering,
        no atexit) leaves a truncated final line; read_metrics must keep
        every durable record and warn, not raise."""
        path = str(tmp_path / "m.jsonl")
        code = (
            "import json, os\n"
            f"f = open({path!r}, 'w')\n"
            "for i in range(3):\n"
            "    f.write(json.dumps({'step': i, 'split': 'train',"
            " 'loss': 1.0}) + '\\n')\n"
            "f.write('{\"step\": 3, \"split\": \"train\", \"lo')\n"
            "f.flush()\n"
            "os._exit(113)\n"  # the mid-write kill
        )
        proc = subprocess.run([sys.executable, "-c", code])
        assert proc.returncode == 113
        with ptd_caplog(caplog):
            recs = read_metrics(path)
        assert [r["step"] for r in recs] == [0, 1, 2]
        assert any("torn" in r.message for r in caplog.records)
        with pytest.raises(ValueError):
            read_metrics(path, strict=True)

    def test_metrics_writer_context_manager_and_flush(self, tmp_path):
        path = str(tmp_path / "m.jsonl")
        with MetricsWriter(path) as w:
            w.write(1, {"loss": 2.0})
            w.flush()
            assert read_metrics(path)[0]["loss"] == 2.0  # durable pre-close
        assert w._f is None  # __exit__ closed it
        w.write(2, {"loss": 1.0})  # reopen-on-reuse contract still holds
        w.close()
        assert len(read_metrics(path)) == 2


# -- unified timers --------------------------------------------------------
class TestUnifiedTimers:
    def test_percentile_matches_numpy_linear(self):
        vals = list(np.random.default_rng(0).normal(size=37))
        for q in (0, 10, 50, 95, 99, 100):
            assert percentile(vals, q) == pytest.approx(
                float(np.percentile(vals, q))
            )
        with pytest.raises(ValueError):
            percentile(vals, 101)

    def test_scalar_meter_and_step_timer_share_window_timer(self):
        assert isinstance(StepTimer(), WindowTimer)
        m = ScalarMeter(window=4)
        assert isinstance(m._timer, WindowTimer)
        for st in (0.1, 0.2, 0.3, 0.4):
            m.update(MeterState(step_time=st, samples_per_sec=10.0 / st))
        s = m.summary()
        assert s["step_time_ms"] == pytest.approx(250.0)
        assert s["step_time_p50_ms"] == pytest.approx(
            percentile([100, 200, 300, 400], 50)
        )
        assert s["step_time_p95_ms"] == pytest.approx(
            percentile([100, 200, 300, 400], 95)
        )
        # StepTimer keeps its historical fraction-q call shape
        t = StepTimer(window=8)
        t.add(1.0)
        t.add(3.0)
        assert t.percentile(0.5) == pytest.approx(percentile([1.0, 3.0], 50))
        assert t.summary()["steps_timed"] == 2

    def test_serve_telemetry_routes_shared_percentile(self):
        from pytorch_distributed_tpu.serve import ServeTelemetry

        tel = ServeTelemetry(clock=lambda: 0.0)
        tel.ttfts_s = [0.010, 0.020, 0.100]
        assert tel.ttft_percentile_ms(50) == pytest.approx(
            percentile([10.0, 20.0, 100.0], 50)
        )
        assert tel.ttft_percentile_ms(99) == pytest.approx(
            percentile([10.0, 20.0, 100.0], 99)
        )
        s = tel.summary()
        assert s["ttft_ms_p50"] == pytest.approx(20.0)


# -- memory gauge ----------------------------------------------------------
def test_live_buffer_bytes_sees_a_big_allocation():
    base = live_buffer_bytes()
    assert base is not None and base >= 0
    big = jnp.ones((1 << 20,), jnp.float32)  # 4 MB, held live
    big.block_until_ready()
    grown = live_buffer_bytes()
    assert grown >= base + 4 * (1 << 20) * 0.9
    del big
