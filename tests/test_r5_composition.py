"""r5 feature-composition pins: the new families ride the EXISTING
serving machinery without special cases — speculative decoding over a
sliding-window target, LoRA adapters over a sparse-MoE base."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import (
    MistralConfig,
    MistralForCausalLM,
    MixtralConfig,
    MixtralForCausalLM,
)


@pytest.mark.slow  # composition pin; each side's own suite runs fast
def test_speculative_refuses_windowed_models():
    """Speculative decoding over a sliding-window model must REFUSE:
    the band mask measures distance in cache slots, and the bubbled
    append-only caches make slot distance != token distance — writing
    this test against equality first PROVED the silent divergence
    (tokens split from target-only greedy exactly at the window
    boundary), so the guard exists because of a measured wrong answer,
    not caution."""
    from pytorch_distributed_tpu.speculative import generate_speculative

    t_cfg = MistralConfig.tiny()  # window=8
    target = MistralForCausalLM(t_cfg)
    draft = MistralForCausalLM(t_cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 5)), jnp.int32
    )
    tp = target.init(jax.random.key(0), ids)["params"]
    dp = draft.init(jax.random.key(1), ids)["params"]
    with pytest.raises(NotImplementedError, match="sliding-window"):
        generate_speculative(
            target, tp, draft, dp, ids, max_new_tokens=8,
            num_draft_tokens=3,
        )


@pytest.mark.slow  # composition pin
def test_lora_identity_at_init_on_moe_base():
    """LoRA over a Mixtral base: adapters attach to the attention/router
    DenseGeneral kernels (expert tensors are not kernels and stay
    frozen), and zero-init B keeps the wrapped model bitwise identical
    at init — the invariant every dense family pins, now on sparse."""
    from pytorch_distributed_tpu.lora import LoRAModel, lora_init

    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 8)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    adapters = lora_init(jax.random.key(1), params, rank=4)
    assert len(jax.tree_util.tree_leaves(adapters)) > 0
    # expert tensors are untouched by the adapter tree
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(adapters)[0]
    }
    assert not any("w_in" in p or "w_out" in p or "w_gate" in p
                   for p in flat), sorted(flat)[:5]
    wrapped = LoRAModel(model, params)
    base = model.apply({"params": params}, ids)
    lora_out = wrapped.apply({"params": adapters}, ids)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(lora_out))
