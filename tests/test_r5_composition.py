"""r5 feature-composition pins: the new families ride the EXISTING
serving machinery without special cases — speculative decoding over a
sliding-window target, LoRA adapters over a sparse-MoE base."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import (
    MistralConfig,
    MistralForCausalLM,
    MixtralConfig,
    MixtralForCausalLM,
)


@pytest.mark.slow  # composition pin; each side's own suite runs fast
def test_speculative_refuses_windowed_models():
    """Speculative decoding over a sliding-window model must REFUSE:
    the band mask measures distance in cache slots, and the bubbled
    append-only caches make slot distance != token distance — writing
    this test against equality first PROVED the silent divergence
    (tokens split from target-only greedy exactly at the window
    boundary), so the guard exists because of a measured wrong answer,
    not caution."""
    from pytorch_distributed_tpu.speculative import generate_speculative

    t_cfg = MistralConfig.tiny()  # window=8
    target = MistralForCausalLM(t_cfg)
    draft = MistralForCausalLM(t_cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 5)), jnp.int32
    )
    tp = target.init(jax.random.key(0), ids)["params"]
    dp = draft.init(jax.random.key(1), ids)["params"]
    with pytest.raises(NotImplementedError, match="sliding-window"):
        generate_speculative(
            target, tp, draft, dp, ids, max_new_tokens=8,
            num_draft_tokens=3,
        )


@pytest.mark.slow  # composition pin
def test_lora_identity_at_init_on_moe_base():
    """LoRA over a Mixtral base: adapters attach to the attention/router
    DenseGeneral kernels (expert tensors are not kernels and stay
    frozen), and zero-init B keeps the wrapped model bitwise identical
    at init — the invariant every dense family pins, now on sparse."""
    from pytorch_distributed_tpu.lora import LoRAModel, lora_init

    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 8)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    adapters = lora_init(jax.random.key(1), params, rank=4)
    assert len(jax.tree_util.tree_leaves(adapters)) > 0
    # expert tensors are untouched by the adapter tree
    flat = {
        "/".join(str(getattr(k, "key", k)) for k in path)
        for path, _ in jax.tree_util.tree_flatten_with_path(adapters)[0]
    }
    assert not any("w_in" in p or "w_out" in p or "w_gate" in p
                   for p in flat), sorted(flat)[:5]
    wrapped = LoRAModel(model, params)
    base = model.apply({"params": params}, ids)
    lora_out = wrapped.apply({"params": adapters}, ids)
    np.testing.assert_array_equal(np.asarray(base), np.asarray(lora_out))


@pytest.mark.slow  # composition pin
def test_ragged_windowed_generate_matches_solo_rows():
    """Left-padded ragged batches under a BINDING sliding window: the
    band mask measures slot distance, and with left padding every
    real token's slot is its true position plus a per-row constant —
    so slot differences equal token differences and each row must
    reproduce its unpadded solo continuation exactly, window included."""
    cfg = MistralConfig.tiny()  # window=8
    model = MistralForCausalLM(cfg)
    rng = np.random.default_rng(5)
    p1 = rng.integers(1, cfg.vocab_size, size=4).astype(np.int32)
    p2 = rng.integers(1, cfg.vocab_size, size=7).astype(np.int32)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 7), jnp.int32)
    )["params"]

    NEW = 6  # 7 + 6 > window=8: the band is binding for the long row
    solo = [
        np.asarray(
            ptd.generate(
                model, params, jnp.asarray(p[None, :]),
                max_new_tokens=NEW, temperature=0.0,
            )
        )[0, len(p):]
        for p in (p1, p2)
    ]
    P = 7
    ids = np.zeros((2, P), np.int32)
    mask = np.zeros((2, P), bool)
    ids[0, P - 4:] = p1
    mask[0, P - 4:] = True
    ids[1, :] = p2
    mask[1, :] = True
    out = np.asarray(
        ptd.generate(
            model, params, jnp.asarray(ids), max_new_tokens=NEW,
            temperature=0.0, prompt_mask=jnp.asarray(mask),
        )
    )
    np.testing.assert_array_equal(out[0, P:], solo[0])
    np.testing.assert_array_equal(out[1, P:], solo[1])


@pytest.mark.slow  # composition pin
def test_beam_over_windowed_model_matches_naive_reference():
    """Beam search over a sliding-window model: beam caches are
    CONTIGUOUS (no bubbles — the reorder gathers whole rows), so slot
    distance == token distance and the band mask is valid; pinned
    against the exact full-recompute beam reference, crossing the
    window boundary."""
    from pytorch_distributed_tpu.generation import generate_beam
    from tests.test_generation import _naive_beam

    cfg = MistralConfig.tiny()  # window=8
    model = MistralForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(2, 500, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    NEW, K = 6, 3  # 5 + 6 > 8: the band is binding
    got = np.asarray(
        generate_beam(model, params, ids, max_new_tokens=NEW, num_beams=K)
    )
    for r in range(2):
        want = _naive_beam(model, params, np.asarray(ids)[r], NEW, K)
        np.testing.assert_array_equal(got[r], want)  # prompt + new
