"""Elastic worlds: membership views, in-process resize, re-shard, replay.

The tier-1 subset here keeps the multi-process cases small (3-4 numpy
workers, short ring deadlines); the full shrink/grow chaos drill lives in
``scripts/chaos_drill.py --drill resize`` (exercised by the slow test at
the bottom) and the downtime-vs-restart comparison in bench.py's
``elastic`` phase, pinned by test_bench_contract.
"""

import json
import os
import subprocess
import sys
import time

import numpy as np
import pytest

from pytorch_distributed_tpu.launch import ElasticWorldLauncher
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.train.elastic_world import (
    ElasticConfig,
    ElasticWorldEngine,
    TaskConfig,
    host_checkpoint_exists,
    leaf_owners,
    load_host_checkpoint,
    params_crc,
    reference_run,
    save_host_checkpoint,
)

pytestmark = pytest.mark.elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _launcher(tmp_path, **overrides):
    defaults = {
        "--total-steps": "12",
        "--global-batch": "16",
        "--microshards": "4",
        "--ckpt-dir": str(tmp_path / "ckpt"),
        "--ckpt-every": "5",
        "--ring-timeout-s": "2.0",
        "--step-delay-s": "0.05",
        "--metrics-path": str(tmp_path / "metrics.jsonl"),
    }
    defaults.update(overrides)
    args = []
    for k, v in defaults.items():
        if v is not None:
            args += [k, str(v)]
    return ElasticWorldLauncher(str(tmp_path / "rdv"), worker_args=args)


def _cfg(**kw):
    base = dict(total_steps=12, global_batch=16, microshards=4)
    base.update(kw)
    return ElasticConfig(**base)


# -- pure pieces -----------------------------------------------------------


class TestOwnership:
    def test_replication_and_coverage(self):
        for world in (1, 2, 3, 5):
            for leaf in range(8):
                owners = leaf_owners(leaf, world, 2)
                assert len(owners) == min(2, world)
                assert all(0 <= r < world for r in owners)
                # the primary owner is deterministic round-robin
                assert leaf % world in owners

    def test_single_replication_is_sole_copy(self):
        assert leaf_owners(3, 4, 1) == (3,)

    def test_every_rank_owns_something_when_leaves_cover(self):
        world = 3
        owned = {r: 0 for r in range(world)}
        for leaf in range(6):
            for r in leaf_owners(leaf, world, 2):
                owned[r] += 1
        assert all(owned.values())


class TestHostCheckpoint:
    def test_roundtrip_and_standard_verify(self, tmp_path):
        leaves = {
            "params_w": np.arange(12, dtype=np.float32).reshape(3, 4),
            "momentum_w": np.ones(5, np.float32),
            "elastic_cursor": np.array([1, 2, 0, 7, 0], np.int64),
        }
        save_host_checkpoint(str(tmp_path), leaves, step=7)
        # the jax-side machinery accepts the host-written format as-is
        from pytorch_distributed_tpu.train.checkpoint import (
            checkpoint_step,
            verify_checkpoint,
        )

        assert verify_checkpoint(str(tmp_path)) == []
        assert checkpoint_step(str(tmp_path)) == 7
        back, step = load_host_checkpoint(str(tmp_path))
        assert step == 7
        for k in leaves:
            np.testing.assert_array_equal(back[k], leaves[k])

    def test_corruption_is_detected(self, tmp_path):
        save_host_checkpoint(
            str(tmp_path), {"params_w": np.ones(64, np.float32)}, step=1
        )
        from pytorch_distributed_tpu.train.checkpoint import (
            verify_checkpoint,
        )

        shard = next(
            p for p in (tmp_path / "latest").iterdir()
            if p.suffix == ".npy"
        )
        raw = bytearray(shard.read_bytes())
        raw[len(raw) // 2] ^= 0xFF
        shard.write_bytes(bytes(raw))
        assert verify_checkpoint(str(tmp_path))

    def test_exists_helper(self, tmp_path):
        assert not host_checkpoint_exists(str(tmp_path))
        assert not host_checkpoint_exists(None)
        save_host_checkpoint(
            str(tmp_path), {"params_w": np.ones(2, np.float32)}, step=0
        )
        assert host_checkpoint_exists(str(tmp_path))


class TestSoloEngine:
    def test_deterministic_and_goodput_sums_to_wall(self):
        r1 = reference_run(_cfg())
        r2 = reference_run(_cfg())
        assert r1["params_crc"] == r2["params_crc"]
        assert r1["final_step"] == 12
        g = r1["goodput"]
        assert "resize_s" in g  # the new bucket reports even when 0
        total = sum(
            v for k, v in g.items()
            if k.endswith("_s") and k != "wall_s"
        )
        assert total == pytest.approx(g["wall_s"], rel=0.05)

    def test_loss_decreases(self):
        r = reference_run(_cfg(total_steps=30))
        eng = ElasticWorldEngine(_cfg(total_steps=30))
        eng.start()
        res = eng.run()
        assert res["params_crc"] == r["params_crc"]
        assert eng.losses[-1] < eng.losses[0]

    def test_world_size_invariant_microshard_order(self):
        """The invariance argument itself, in miniature: summing the
        per-microshard gradient sums in shard order is independent of
        which rank computed which shard."""
        from pytorch_distributed_tpu.train.elastic_world import (
            grad_sums,
            init_task_params,
            task_data,
        )

        task = TaskConfig()
        params = init_task_params(task)
        x, y = task_data(task)
        per_shard = [
            grad_sums(params, x[s * 4:(s + 1) * 4], y[s * 4:(s + 1) * 4])[0]
            for s in range(4)
        ]
        ref = {
            k: per_shard[0][k] + per_shard[1][k] + per_shard[2][k]
            + per_shard[3][k]
            for k in per_shard[0]
        }
        # any ownership split reduces in the SAME fixed order
        again = {
            k: per_shard[0][k] + per_shard[1][k] + per_shard[2][k]
            + per_shard[3][k]
            for k in per_shard[0]
        }
        for k in ref:
            np.testing.assert_array_equal(ref[k], again[k])

    def test_solo_checkpoint_resume_is_bit_exact(self, tmp_path):
        full = reference_run(_cfg(total_steps=10))
        eng = ElasticWorldEngine(
            _cfg(total_steps=6, ckpt_dir=str(tmp_path), ckpt_every=6)
        )
        eng.start()
        eng.run()
        # a fresh engine restores at step 6 and replays 4 more steps
        eng2 = ElasticWorldEngine(
            _cfg(total_steps=10, ckpt_dir=str(tmp_path), ckpt_every=0)
        )
        eng2.start()
        assert eng2.step == 6
        res = eng2.run()
        assert res["params_crc"] == full["params_crc"]


class TestRebuildProcessGroup:
    """The re-mesh-in-place facade path: swap the world without tearing
    the process down. SPMD branch only here — the hostring branch is the
    multi-process engine's job (exercised by the resize tests below via
    the membership ring swap)."""

    def test_spmd_shrink_and_remesh(self):
        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.runtime import distributed as dist
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec

        ptd.init_process_group(mesh_spec=MeshSpec(dp=8))
        try:
            g = dist.rebuild_process_group(
                mesh_spec=MeshSpec(dp=4), world_size=4
            )
            assert g.size == 4
            assert g.mesh.shape["dp"] == 4
            # collectives work over the rebuilt (smaller) world
            out = np.asarray(
                ptd.all_reduce(np.ones((4, 3), np.float32))
            )
            assert np.all(out == 4.0)
            # growing past the surviving device set is refused loudly
            with pytest.raises(ValueError):
                dist.rebuild_process_group(world_size=8)
        finally:
            ptd.init_process_group(mesh_spec=MeshSpec(dp=8))

    def test_rebuild_without_group_refuses(self):
        from pytorch_distributed_tpu.runtime import distributed as dist

        prev = dist._GROUP
        dist._GROUP = None
        try:
            with pytest.raises(RuntimeError):
                dist.rebuild_process_group(world_size=2)
        finally:
            dist._GROUP = prev

    def test_remesh_replaces_current_mesh(self):
        import jax

        from pytorch_distributed_tpu.runtime import mesh as mesh_mod

        before = mesh_mod.current_mesh()
        try:
            m = mesh_mod.remesh(
                mesh_mod.MeshSpec(dp=2),
                devices=jax.devices("cpu")[:2],
            )
            assert mesh_mod.current_mesh() is m
            assert m.shape["dp"] == 2
        finally:
            mesh_mod.set_current_mesh(before)


class TestFaultSites:
    def test_elastic_sites_registered(self):
        for site in ("elastic.peer_lost", "elastic.resize",
                     "elastic.rejoin"):
            assert site in faults.KNOWN_SITES

    def test_peer_lost_site_fires_deterministically(self):
        with faults.injected("elastic.peer_lost:after=2,count=1"):
            hits = [faults.fires("elastic.peer_lost") for _ in range(5)]
        assert hits == [False, False, True, False, False]


# -- multi-process: the real ring ------------------------------------------


def _wait_results(launcher, codes_expect, timeout=120):
    codes = launcher.wait(timeout)
    results = launcher.results()
    for wid, want in codes_expect.items():
        assert codes.get(wid) == want, (wid, codes)
    return results


def test_shrink_is_in_process_and_bit_exact(tmp_path):
    """THE headline invariant, tier-1: one rank SIGKILLed mid-run,
    survivors re-mesh without process restart (exit code 0, views
    spanning two epochs) and finish bit-identical to the unresized
    reference world on the same global data order — and the membership
    transition + resize cost land in the metrics stream for obs_report.
    """
    launcher = _launcher(tmp_path)
    launcher.start_world(["w0", "w1", "w2"], env_overrides={
        "w2": {"PTD_FAULTS": "elastic.peer_lost:mode=kill,after=4"},
    })
    results = _wait_results(
        launcher, {"w0": 0, "w1": 0, "w2": faults.KILLED_EXIT}
    )
    ref = reference_run(_cfg())
    for wid in ("w0", "w1"):
        r = results[wid]
        assert r["final_step"] == 12
        assert r["params_crc"] == ref["params_crc"]
        assert [v["world_size"] for v in r["views"]] == [3, 2]
        assert r["resizes"] and r["resizes"][0]["world_size"] == 2
        assert r["goodput"]["resize_s"] > 0
    recs = [
        json.loads(line)
        for line in (tmp_path / "metrics.jsonl").read_text().splitlines()
        if line.strip()
    ]
    views = [
        r for r in recs
        if r.get("split") == "elastic" and r.get("event") == "view_change"
    ]
    assert views and views[0]["world_size"] == 2
    assert views[0]["resize_s"] > 0
    good = [r for r in recs if r.get("split") == "goodput"]
    assert good and good[-1]["resize_s"] > 0
    # obs_report renders the membership transitions from this stream
    sys.path.insert(0, os.path.join(REPO, "scripts"))
    try:
        import importlib

        obs_report = importlib.import_module("obs_report")
    finally:
        sys.path.pop(0)
    import io

    out = io.StringIO()
    summary = obs_report.report(
        None, [str(tmp_path / "metrics.jsonl")], out=out
    )
    text = out.getvalue()
    assert "membership:" in text and "epoch 1 -> 2" in text
    assert summary["goodput"]["view_changes"] == 1


@pytest.mark.slow
class TestElasticWorldMultiproc:
    def test_grow_joiner_lands_on_the_same_bits(self, tmp_path):
        launcher = _launcher(tmp_path, **{"--total-steps": "30",
                                          "--step-delay-s": "0.08"})
        launcher.start_world(["w0", "w1"])
        time.sleep(2.0)  # join lands mid-run (steps are paced)
        launcher.add_worker("w2")
        results = _wait_results(launcher, {"w0": 0, "w1": 0, "w2": 0})
        ref = reference_run(_cfg(total_steps=30))
        for wid in ("w0", "w1", "w2"):
            assert results[wid]["params_crc"] == ref["params_crc"]
        assert [v["world_size"]
                for v in results["w0"]["views"]] == [2, 3]
        assert results["w2"]["views"][0]["world_size"] == 3

    def test_sole_copy_loss_falls_back_to_disk_and_replays(self, tmp_path):
        """replication=1 makes every momentum leaf a sole copy: losing a
        rank forces the checkpoint fallback + cursor replay — and the
        result is STILL bit-exact (replay is deterministic)."""
        launcher = _launcher(tmp_path, **{"--replication": "1",
                                          "--ckpt-every": "4"})
        launcher.start_world(["w0", "w1", "w2"], env_overrides={
            "w1": {"PTD_FAULTS": "elastic.peer_lost:mode=kill,after=6"},
        })
        results = _wait_results(
            launcher, {"w0": 0, "w2": 0, "w1": faults.KILLED_EXIT}
        )
        ref = reference_run(_cfg(replication=1))
        for wid in ("w0", "w2"):
            r = results[wid]
            assert r["params_crc"] == ref["params_crc"]
            assert r["final_step"] == 12
            # the fallback path actually ran: recovery time was booked
            assert r["goodput"]["recovering_s"] > 0

    def test_resize_during_resize_converges(self, tmp_path):
        """The double-failure drill: one rank dies mid-run, and a SECOND
        rank dies during the resulting resize (the elastic.resize fault
        site, mode=kill). The remaining survivors must burn the epoch,
        re-settle, and still finish bit-exact — resize is re-entrant."""
        launcher = _launcher(tmp_path, **{"--total-steps": "14"})
        launcher.start_world(["w0", "w1", "w2", "w3"], env_overrides={
            "w3": {"PTD_FAULTS": "elastic.peer_lost:mode=kill,after=4"},
            "w2": {"PTD_FAULTS": "elastic.resize:mode=kill,count=1"},
        })
        results = _wait_results(
            launcher,
            {"w0": 0, "w1": 0,
             "w2": faults.KILLED_EXIT, "w3": faults.KILLED_EXIT},
            timeout=180,
        )
        ref = reference_run(_cfg(total_steps=14))
        for wid in ("w0", "w1"):
            r = results[wid]
            assert r["final_step"] == 14
            assert r["params_crc"] == ref["params_crc"]
            # both departures ended up reflected in the final world
            assert r["views"][-1]["world_size"] == 2

    def test_die_and_restore_baseline_exits_tempfail(self, tmp_path):
        from pytorch_distributed_tpu.train.elastic import EX_TEMPFAIL

        launcher = _launcher(tmp_path, **{"--on-peer-loss": "exit"})
        launcher.start_world(["w0", "w1", "w2"], env_overrides={
            "w2": {"PTD_FAULTS": "elastic.peer_lost:mode=kill,after=4"},
        })
        codes = launcher.wait(120)
        assert codes["w2"] == faults.KILLED_EXIT
        assert codes["w0"] == EX_TEMPFAIL
        assert codes["w1"] == EX_TEMPFAIL


@pytest.mark.slow
def test_resize_drill_end_to_end(tmp_path):
    """The acceptance drill: SIGKILL one rank mid-run, survivors re-mesh
    in-process and finish bit-identical to the unresized reference, then
    the world grows back to full size and lands on the same bits."""
    proc = subprocess.run(
        [
            sys.executable, os.path.join(REPO, "scripts", "chaos_drill.py"),
            "--drill", "resize", "--ckpt-dir", str(tmp_path),
            "--total-steps", "30", "--kill-after", "6",
            "--step-delay-s", "0.1",
        ],
        cwd=REPO, capture_output=True, text=True, timeout=300,
        env={**os.environ, "JAX_PLATFORMS": "cpu",
             "PALLAS_AXON_POOL_IPS": ""},
    )
    assert proc.returncode == 0, proc.stderr[-3000:]
    verdict = json.loads(proc.stdout.splitlines()[-1])
    assert verdict["passed"] is True
    assert verdict["shrank"] and verdict["regrew"]
    assert verdict["bit_exact_vs_reference"] is True
    assert verdict["victim_rc"] == faults.KILLED_EXIT
    assert all(v > 0 for w, v in verdict["resize_goodput"].items()
               if w in ("w0", "w1"))
