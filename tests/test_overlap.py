"""Overlapped gradient sync: the bucketed, pipelined host-ring engine.

Covers the round-14 tentpole (DESIGN.md §19):

* ``ShipPlan`` — deterministic coalesce/chunk/bucket structure (shared
  by the legacy and pipelined paths, so they can never drift);
* the q8 error-feedback quantizer replication and residual mechanics;
* ``sync_grads(overlap=True)`` bit-parity with the legacy path over a
  live ring, comm-thread span tracks, exposed/hidden accounting;
* ``build_train_step(overlap_accum=True)`` — bit-identity with the
  scanned step (world 1 in-process; world 2 over the ring), the
  microbatch reduce schedule's lockstep + last-ulp closeness, compile
  counts, Trainer integration;
* the ``comm.overlap_stall`` chaos case: a rank SIGKILLed mid-pipeline
  leaves survivors recoverable by a fresh-ring re-mesh + reset_engine;
* trace_merge's k-th-occurrence straggler alignment over comm-thread
  traces.
"""

import json
import os
import sys

import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import overlap as ov
from pytorch_distributed_tpu.runtime import faults
from tests import hostring_workers

SCRIPTS = os.path.join(
    os.path.dirname(os.path.dirname(os.path.abspath(__file__))), "scripts"
)

pytestmark = pytest.mark.overlap


def _run(world, target, extra_args=(), timeout=420.0):
    return hostring_workers.run_ring_workers(
        world, target, extra_args=extra_args, timeout=timeout
    )


# --------------------------------------------------------------------------
# ShipPlan structure (pure host, no ring, no jax)
# --------------------------------------------------------------------------
class TestShipPlan:
    def specs(self):
        return [
            ((17,), np.float32),    # coalesces
            ((23,), np.float32),    # coalesces
            ((5000,), np.float32),  # solo
            ((3_000_000,), np.float32),  # 12 MB: chunks at 4 MB
            ((9,), np.int32),       # non-float: solo, never coalesced
        ]

    def test_structure_and_determinism(self):
        a = ov.ShipPlan(self.specs(), quantize=True)
        b = ov.ShipPlan(self.specs(), quantize=True)
        assert a.signature() == b.signature()
        assert [list(x) for x in a.buckets] == [list(x) for x in b.buckets]
        kinds = [(i.kind, i.leaf_ids, i.q8) for i in a.items]
        # flat FIRST (the degenerate first bucket), then solos/chunks in
        # leaf order; q8 on the big f32 solos (which then never split —
        # the native q8 path chunks at its own scale-adjusted stride),
        # never on flats
        assert kinds[0] == ("flat", (0, 1), False)
        assert kinds[1] == ("solo", (2,), True)
        assert kinds[2] == ("solo", (3,), True)  # q8: whole, unsplit
        assert kinds[-1] == ("solo", (4,), False)

    def test_uncompressed_big_leaf_chunks_at_slot_boundaries(self):
        a = ov.ShipPlan(self.specs(), quantize=False)
        # 12 MB f32 leaf: 3 slot chunks sharing one parent buffer, so
        # the reduced leaf is contiguous with no reassembly copy
        chunks = [i for i in a.items if i.kind == "chunk"]
        assert [c.leaf_ids for c in chunks] == [(3,)] * 3
        assert len({c.parent for c in chunks}) == 1
        assert [c.start for c in chunks] == [0, 1 << 20, 2 << 20]
        assert sum(c.elems for c in chunks) == 3_000_000
        assert not any(c.q8 for c in chunks)

    def test_chunk_boundaries_follow_chunk_bytes(self):
        plan = ov.ShipPlan([((1_000_000,), np.float32)],
                           chunk_bytes=1 << 20)
        chunks = [i for i in plan.items if i.kind == "chunk"]
        assert [c.start for c in chunks] == [0, 262144, 524288, 786432]

    def test_buckets_cover_items_in_order(self):
        plan = ov.ShipPlan(self.specs())
        flat = [j for b in plan.buckets for j in b]
        assert flat == list(range(len(plan.items)))
        for b in plan.buckets[:-1]:
            assert b  # no empty buckets

    def test_pre_shipped_never_recoalesces(self):
        # two tiny arrays that WOULD coalesce as leaves must stay one
        # item each when they arrive pre-packed through io_callback
        plan = ov.ShipPlan.pre_shipped(
            [((40,), np.float32), ((41,), np.float32)], [False, False]
        )
        assert [i.kind for i in plan.items] == ["solo", "solo"]

    def test_grouping_is_shared_with_ddp(self):
        # the tentpole's no-drift guarantee: ddp re-exports THE constant
        from pytorch_distributed_tpu.parallel import ddp

        assert ddp._COALESCE_MAX_ELEMS is ov.COALESCE_MAX_ELEMS


class TestQ8ErrorFeedback:
    def test_roundtrip_matches_native_bound(self):
        x = (np.random.default_rng(0).normal(size=10_000) * 5).astype(
            np.float32
        )
        rt = ov.q8_local_roundtrip(x)
        # per-256-block bound: |err| <= scale/2 = amax/254
        x = x[:9984]  # whole blocks
        rt = rt[:9984]
        xb = x.reshape(-1, 256)
        bound = np.abs(xb).max(axis=1) / 127.0 * 0.5 + 1e-7
        err = np.abs((rt - x).reshape(-1, 256)).max(axis=1)
        assert np.all(err <= bound)

    def test_roundtrip_edge_blocks(self):
        zeros = np.zeros(300, np.float32)
        assert np.array_equal(ov.q8_local_roundtrip(zeros), zeros)
        bad = np.ones(300, np.float32)
        bad[5] = np.inf
        rt = ov.q8_local_roundtrip(bad)
        assert np.all(np.isnan(rt[:256]))  # poisoned block is LOUD
        assert np.all(np.isfinite(rt[256:]))  # later blocks untouched

    def test_site_registered(self):
        assert "comm.overlap_stall" in faults.KNOWN_SITES


class TestEngineLocal:
    def test_reset_engine_idempotent(self):
        ov.reset_engine()
        ov.reset_engine()

    def test_build_guards(self):
        from pytorch_distributed_tpu.train import build_train_step

        def loss_fn(p, bs, b, r):
            return 0.0, {}

        with pytest.raises(ValueError, match="bf16"):
            build_train_step(loss_fn, overlap_accum=True,
                             grad_compression="bf16")
        with pytest.raises(ValueError, match="reduce_schedule"):
            build_train_step(loss_fn, overlap_accum=True,
                             reduce_schedule="eager")
        with pytest.raises(ValueError, match="microbatch"):
            build_train_step(loss_fn, overlap_accum=True,
                             reduce_schedule="microbatch",
                             grad_compression="int8")
        with pytest.raises(ValueError, match="scanned step"):
            build_train_step(loss_fn, reduce_schedule="microbatch")


# --------------------------------------------------------------------------
# world-1 bit-identity: the fixed-order argument, in-process
# --------------------------------------------------------------------------
class TestHostLoopWorldOne:
    def _parts(self):
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_distributed_tpu.train import TrainState

        def loss_fn(params, batch_stats, batch, rng):
            pred = jnp.tanh(batch["x"] @ params["w"]) @ params["v"]
            loss = jnp.mean((pred - batch["y"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        ri = np.random.default_rng(0)
        init = {
            "w": ri.normal(size=(8, 24)).astype(np.float32),
            "v": ri.normal(size=(24, 4)).astype(np.float32),
        }

        def mkstate(tx):
            return TrainState.create(
                apply_fn=lambda p, x: x,
                params={k: jnp.asarray(v) for k, v in init.items()},
                tx=tx,
            )

        def batch_for(t):
            r = np.random.default_rng(10 + t)
            return {"x": r.normal(size=(16, 8)).astype(np.float32),
                    "y": r.normal(size=(16, 4)).astype(np.float32)}

        return loss_fn, init, mkstate, batch_for

    def _params(self, s, init):
        return np.concatenate(
            [np.asarray(s.params[k]).ravel() for k in sorted(init)]
        )

    def test_bitwise_vs_scanned_multistep(self):
        """The tentpole claim: host-loop accumulation + apply equals the
        scanned path to the BIT over several steps. Power-of-two lr so
        every contractible multiply is exact — bit-identity then holds
        regardless of XLA's per-program fusion choices (§19)."""
        import jax
        import optax

        from pytorch_distributed_tpu.train import build_train_step

        loss_fn, init, mkstate, batch_for = self._parts()
        scan = jax.jit(build_train_step(loss_fn, accum_steps=4))
        host = build_train_step(loss_fn, accum_steps=4,
                                overlap_accum=True)
        s1, s2 = mkstate(optax.sgd(0.125)), mkstate(optax.sgd(0.125))
        for t in range(5):
            s1, m1 = scan(s1, batch_for(t))
            s2, m2 = host(s2, batch_for(t))
            assert abs(float(np.asarray(m1["loss"]))
                       - float(np.asarray(m2["loss"]))) < 1e-6
        assert np.array_equal(self._params(s1, init),
                              self._params(s2, init))
        assert host.compile_counts() == {"prep": 1, "grad": 1,
                                         "apply": 1}

    def test_single_step_bitwise_with_momentum(self):
        """With momentum the cross-program FMA-contraction caveat kicks
        in from step 2 (§19 documents it); step 1 — zero momentum, so
        every contraction multiplies by zero or the exact grads — is
        bitwise, which pins the accumulation order itself."""
        import jax
        import optax

        from pytorch_distributed_tpu.train import build_train_step

        loss_fn, init, mkstate, batch_for = self._parts()
        scan = jax.jit(build_train_step(loss_fn, accum_steps=2))
        host = build_train_step(loss_fn, accum_steps=2,
                                overlap_accum=True)
        tx = lambda: __import__("optax").sgd(0.1, momentum=0.9)  # noqa
        s1, _ = scan(mkstate(tx()), batch_for(0))
        s2, _ = host(mkstate(tx()), batch_for(0))
        assert np.array_equal(self._params(s1, init),
                              self._params(s2, init))

    def test_accum_one_matches_plain(self):
        import jax
        import optax

        from pytorch_distributed_tpu.train import build_train_step

        loss_fn, init, mkstate, batch_for = self._parts()
        plain = jax.jit(build_train_step(loss_fn))
        host = build_train_step(loss_fn, overlap_accum=True)
        s1, _ = plain(mkstate(optax.sgd(0.125)), batch_for(0))
        s2, _ = host(mkstate(optax.sgd(0.125)), batch_for(0))
        assert np.array_equal(self._params(s1, init),
                              self._params(s2, init))

    def test_begin_finish_split(self):
        import optax

        from pytorch_distributed_tpu.train import build_train_step

        loss_fn, init, mkstate, batch_for = self._parts()
        host = build_train_step(loss_fn, accum_steps=2,
                                overlap_accum=True)
        s = mkstate(optax.sgd(0.125))
        pending = host.begin(s, batch_for(0))
        s2, metrics = host.finish(pending)
        assert "loss" in metrics
        whole = build_train_step(loss_fn, accum_steps=2,
                                 overlap_accum=True)
        s3, _ = whole(mkstate(optax.sgd(0.125)), batch_for(0))
        assert np.array_equal(self._params(s2, init),
                              self._params(s3, init))

    def test_scaler_and_ema_ride_the_apply_program(self):
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_distributed_tpu.runtime.precision import GradScaler
        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        loss_fn, init, mkstate, batch_for = self._parts()

        scaler = GradScaler(dtype=jnp.float16)

        def mk(ema):
            return TrainState.create(
                apply_fn=lambda p, x: x,
                params={k: jnp.asarray(v) for k, v in init.items()},
                tx=optax.sgd(0.125), ema=ema,
                scaler_state=scaler.init_state(),
            )
        scan = jax.jit(build_train_step(
            loss_fn, accum_steps=2, scaler=scaler, ema_decay=0.5
        ))
        host = build_train_step(
            loss_fn, accum_steps=2, scaler=scaler, ema_decay=0.5,
            overlap_accum=True,
        )
        s1, m1 = scan(mk(True), batch_for(0))
        s2, m2 = host(mk(True), batch_for(0))
        assert float(np.asarray(m2["grads_finite"])) == 1.0
        assert float(np.asarray(m1["loss_scale"])) == float(
            np.asarray(m2["loss_scale"])
        )
        assert np.array_equal(self._params(s1, init),
                              self._params(s2, init))
        for k in init:
            assert np.array_equal(np.asarray(s1.ema_params[k]),
                                  np.asarray(s2.ema_params[k])), k


# --------------------------------------------------------------------------
# live multi-process coverage
# --------------------------------------------------------------------------
class TestOverRing:
    def test_overlap_parity_spans_and_error_feedback(self):
        world = 2
        results = _run(world, hostring_workers.overlap_parity_worker)
        assert results == [(r, "ok") for r in range(world)], results

    def test_overlap_accum_bitwise_and_microbatch_lockstep(self):
        world = 2
        results = _run(world, hostring_workers.overlap_accum_worker)
        assert results == [(r, "ok") for r in range(world)], results

    def test_ef_loss_curve_parity(self):
        world = 2
        results = _run(world, hostring_workers.overlap_ef_worker)
        assert results == [(r, "ok") for r in range(world)], results

    def test_chaos_kill_mid_pipeline_recovers(self):
        """The comm.overlap_stall drill: the victim dies between bucket
        reduces; every SURVIVOR must report ok (poisoned-engine refusal
        + fresh-ring re-mesh + lockstep after), and the victim's exit
        status must be the injected-kill code, not a clean exit."""
        import multiprocessing as mp
        import uuid

        world = 3
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        name = f"ptdovl_{uuid.uuid4().hex[:8]}"
        procs = [
            ctx.Process(
                target=hostring_workers.overlap_chaos_worker,
                args=(r, world, name, q),
            )
            for r in range(world)
        ]
        old = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            for p in procs:
                p.start()
        finally:
            if old is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = old
        try:
            results = sorted(q.get(timeout=420.0)
                             for _ in range(world - 1))
        finally:
            for p in procs:
                p.join(timeout=60)
                if p.is_alive():
                    p.terminate()
        assert results == [(r, "ok") for r in range(world - 1)], results
        assert procs[world - 1].exitcode == faults.KILLED_EXIT

    def test_trace_merge_alignment_with_comm_thread(self, tmp_path):
        world = 3
        results = _run(
            world, hostring_workers.overlap_trace_worker,
            extra_args=(str(tmp_path),),
        )
        assert results == [(r, "ok") for r in range(world)], results
        sys.path.insert(0, SCRIPTS)
        try:
            import trace_merge
        finally:
            sys.path.pop(0)
        rc = trace_merge.main([str(tmp_path)])
        assert rc == 0
        doc = json.load(
            open(os.path.join(str(tmp_path), "merged_trace.json"))
        )
        events = doc["traceEvents"]
        # the k-th comm.all_reduce per rank is the same collective:
        # every rank must have issued the SAME count, in lockstep order
        per_rank = {}
        for e in events:
            if e.get("ph") == "X" and e["name"] == "comm.all_reduce":
                per_rank.setdefault(e["pid"], []).append(e)
        assert set(per_rank) == set(range(world))
        counts = {r: len(v) for r, v in per_rank.items()}
        assert len(set(counts.values())) == 1, counts
        # 4 syncs x 2 ship items each
        assert counts[0] == 8, counts
        # straggler summary computed over the comm spans
        skew = doc["otherData"]["comm_skew"]
        assert "comm.all_reduce" in skew
        # the comm thread's track is NAMED in each rank's process
        tnames = [
            e for e in events
            if e.get("ph") == "M" and e["name"] == "thread_name"
            and e["args"]["name"] == "grad-sync-comm"
        ]
        assert {e["pid"] for e in tnames} == set(range(world))

    def test_obs_report_renders_exposed_hidden(self, tmp_path, capsys):
        """obs_report's Comms section surfaces the engine's cumulative
        exposed/hidden counters — the comm_hidden-vs-comm_exposed
        account the overlap work is judged by. (Counter PRODUCTION over
        a live ring is pinned by overlap_parity_worker; this renders a
        locally-built trace, no ring needed.)"""
        import time as _time

        from pytorch_distributed_tpu.runtime import tracing

        with tracing.enabled(str(tmp_path)) as t:
            with tracing.span("comm.all_reduce", wire_bytes=1048576,
                              payload_bytes=1048576, world=2):
                _time.sleep(0.001)
            # cumulative within an engine's life; the drop to 0.05/0.15
            # is an engine REBUILD (elastic re-mesh) whose fresh
            # readings must count in full, not clobber the total
            t.counter("comm.sync.exposed_s", 0.10)
            t.counter("comm.sync.hidden_s", 0.30)
            t.counter("comm.sync.exposed_s", 0.20)
            t.counter("comm.sync.hidden_s", 0.60)
            t.counter("comm.sync.exposed_s", 0.05)
            t.counter("comm.sync.hidden_s", 0.15)
            t.export()
        sys.path.insert(0, SCRIPTS)
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        rc = obs_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "grad-sync overlap: comm exposed 0.250s" in out
        assert "exposed ratio 0.25" in out


class TestTrainerIntegration:
    def test_trainer_refuses_host_step_on_multidevice_mesh(self):
        """The conftest runs an 8-device CPU mesh: a host-loop step
        cannot carry SPMD shardings, and the Trainer must say so loudly
        instead of silently mis-sharding."""
        import jax
        import jax.numpy as jnp
        import optax

        from pytorch_distributed_tpu.parallel import DataParallel
        from pytorch_distributed_tpu.train import (
            Trainer,
            TrainState,
            build_train_step,
        )

        assert jax.device_count() > 1  # the conftest's virtual mesh

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean((batch["x"] @ params["w"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        state = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w": jnp.ones((4, 2))}, tx=optax.sgd(0.1),
        )
        step = build_train_step(loss_fn, overlap_accum=True)
        with pytest.raises(ValueError, match="overlap_accum"):
            Trainer(state, DataParallel(), step, train_loader=[])
