"""LoRA adapters (lora.py): identity at init, adapter-only training.

The two contracts that make LoRA trustworthy: (1) zero-init B means the
wrapped model starts EXACTLY at the base checkpoint (bitwise logits);
(2) training moves ONLY the adapter tree — the base is closed over, the
optimizer state is adapter-sized, and the model still learns.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.lora import (
    LoRAModel,
    lora_init,
    lora_merge,
    lora_param_count,
)
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.runtime.mesh import MeshSpec


def _gpt2():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    cfg = GPT2Config(
        vocab_size=97, n_positions=48, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(97, size=(2, 8)).astype(np.int32))
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params, ids


def test_identity_at_init_gpt2():
    model, params, ids = _gpt2()
    adapters = lora_init(jax.random.key(1), params, rank=4)
    wrapped = LoRAModel(model, params)
    base_logits = model.apply({"params": params}, ids)
    lora_logits = wrapped.apply({"params": adapters}, ids)
    np.testing.assert_array_equal(
        np.asarray(base_logits), np.asarray(lora_logits)
    )
    # and the merged tree is the base tree, bitwise
    merged = lora_merge(params, adapters)
    for (p1, x), (p2, y) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(merged),
    ):
        np.testing.assert_array_equal(np.asarray(x), np.asarray(y))


def test_identity_at_init_llama():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    cfg = LlamaConfig(
        vocab_size=89, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, max_seq_len=64,
    )
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(1).integers(89, size=(2, 6)).astype(np.int32)
    )
    params = model.init(jax.random.key(0), ids)["params"]
    adapters = lora_init(jax.random.key(1), params, rank=2)
    # q/k/v/o + gate/up/down matched across the scanned stack
    assert lora_param_count(adapters) > 0
    got = LoRAModel(model, params).apply({"params": adapters}, ids)
    want = model.apply({"params": params}, ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_adapter_only_training_learns():
    model, params, ids = _gpt2()
    adapters = lora_init(jax.random.key(1), params, rank=4)
    n_base = sum(x.size for x in jax.tree_util.tree_leaves(params))
    n_lora = lora_param_count(adapters)
    # on a real model the ratio is ~1000x; this 30k-param test model
    # still shows the shape of the win
    assert n_lora < n_base / 5

    wrapped = LoRAModel(model, params)

    def loss_fn(adapters):
        logits = wrapped.apply({"params": adapters}, ids[:, :-1])
        tgt = ids[:, 1:]
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(lp, tgt[..., None], axis=-1).mean()

    tx = optax.adam(3e-2)
    opt_state = tx.init(adapters)
    # optimizer state is adapter-sized, not base-sized
    n_opt = sum(
        x.size for x in jax.tree_util.tree_leaves(opt_state)
        if hasattr(x, "size")
    )
    assert n_opt <= 2 * n_lora + 16

    @jax.jit
    def step(adapters, opt_state):
        loss, g = jax.value_and_grad(loss_fn)(adapters)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(adapters, updates), opt_state, loss

    base_logits_before = np.asarray(
        model.apply({"params": wrapped.base_params}, ids)
    )
    first = None
    for _ in range(60):
        adapters, opt_state, loss = step(adapters, opt_state)
        first = float(loss) if first is None else first
    assert float(loss) < first * 0.5, (first, float(loss))
    # the base the wrapper actually uses never moved: its raw forward
    # (no adapters) is bitwise what it was before training
    base_logits_after = np.asarray(
        model.apply({"params": wrapped.base_params}, ids)
    )
    np.testing.assert_array_equal(base_logits_before, base_logits_after)


@pytest.mark.slow
def test_generate_through_lora_wrapper():
    model, params, ids = _gpt2()
    adapters = lora_init(jax.random.key(1), params, rank=4)
    wrapped = LoRAModel(model, params)
    want = ptd.generate(model, params, ids, max_new_tokens=5,
                        temperature=0.0)
    got = ptd.generate(wrapped, adapters, ids, max_new_tokens=5,
                       temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_lora_validation():
    model, params, _ = _gpt2()
    with pytest.raises(ValueError, match="rank"):
        lora_init(jax.random.key(0), params, rank=0)
    with pytest.raises(ValueError, match="no kernel matched"):
        lora_init(jax.random.key(0), params, rank=4,
                  targets={r"does_not_exist/kernel$": 1})


def test_lora_merge_rejects_layout_mismatch():
    # adapters built against one layout must not silently no-op when
    # merged onto another (scanned adapters -> renamed/unrolled params)
    model, params, _ = _gpt2()
    adapters = lora_init(jax.random.key(0), params, rank=2)
    renamed = {"prefix": params}  # every adapter path now misses
    with pytest.raises(ValueError, match="layouts disagree"):
        lora_merge(renamed, adapters)


@pytest.mark.slow  # r5 profile refit: identity_at_init_llama pins the same invariant fast
def test_identity_at_init_bert():
    # unrolled (layer{i}) stack: no scan axis; query/key/value out=2 and
    # attn/out multi-dim in are covered by the BERT default targets
    from pytorch_distributed_tpu.models.bert import (
        BertConfig,
        BertForSequenceClassification,
    )

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    model = BertForSequenceClassification(BertConfig.tiny(), num_labels=2)
    ids = jnp.asarray(
        np.random.default_rng(2).integers(
            1024, size=(2, 10)
        ).astype(np.int32)
    )
    params = model.init(jax.random.key(0), ids)["params"]
    adapters = lora_init(jax.random.key(1), params, rank=2)
    # every layer's attention (q/k/v/out) and MLP matched
    n_layers = BertConfig.tiny().num_layers
    n_adapted = sum(1 for _ in _adapter_leaves(adapters))
    assert n_adapted == n_layers * 6  # q,k,v,out,mlp_up,mlp_down
    got = LoRAModel(model, params).apply({"params": adapters}, ids)
    want = model.apply({"params": params}, ids)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def _adapter_leaves(tree):
    for v in tree.values():
        if isinstance(v, dict):
            if "a" in v and not isinstance(v["a"], dict):
                yield v
            else:
                yield from _adapter_leaves(v)


@pytest.mark.slow  # r5 profile refit: identity_at_init_llama pins the invariant fast
def test_identity_at_init_vit():
    # ViT names its projections query/key/value/out directly in the
    # block (no attn parent): the out-projection must be adapted too —
    # a 3-of-4-attention-matrices LoRA would train silently crippled
    from pytorch_distributed_tpu.models.vit import ViT, ViTConfig

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    model = ViT(ViTConfig.tiny())
    x = jnp.asarray(
        np.random.default_rng(3).normal(size=(2, 32, 32, 3)).astype(
            np.float32
        )
    )
    params = model.init(jax.random.key(0), x)["params"]
    adapters = lora_init(jax.random.key(1), params, rank=2)
    adapted_paths = []

    def collect(tree, pre=""):
        for k, v in tree.items():
            if isinstance(v, dict):
                if "a" in v and not isinstance(v["a"], dict):
                    adapted_paths.append(pre + k)
                else:
                    collect(v, pre + k + "/")

    collect(adapters)
    per_block = [p for p in adapted_paths if p.startswith("block_0/")]
    assert sorted(per_block) == [
        "block_0/key/kernel", "block_0/mlp_down/kernel",
        "block_0/mlp_up/kernel", "block_0/out/kernel",
        "block_0/query/kernel", "block_0/value/kernel",
    ]
    got = LoRAModel(model, params).apply({"params": adapters}, x)
    want = model.apply({"params": params}, x)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_qlora_int8_base_identity_and_dtype():
    # the q8 branch of shape reconstruction + merge, and the bf16
    # reconstruction knob (halves the transient merged tree at scale)
    from pytorch_distributed_tpu.ops import QuantizedModel
    from pytorch_distributed_tpu.ops.quant import quantize_tree_int8

    model, params, ids = _gpt2()
    qbase = quantize_tree_int8(params, min_size=512)
    adapters = lora_init(jax.random.key(1), qbase, rank=2)
    assert lora_param_count(adapters) > 0
    want = QuantizedModel(model).apply({"params": qbase}, ids)
    got = LoRAModel(model, qbase).apply({"params": adapters}, ids)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))
    merged16 = lora_merge(qbase, adapters, dtype=jnp.bfloat16)
    kernels = [
        x for _, x in jax.tree_util.tree_leaves_with_path(merged16)
        if x.ndim >= 2 and x.dtype == jnp.bfloat16
    ]
    assert kernels  # quantized leaves reconstructed at the asked dtype


@pytest.mark.slow  # r5 profile refit: identity-at-init + adapter-only-training pin LoRA fast; quant has its own pins
def test_qlora_int4_base():
    """QLoRA: adapters over a FROZEN int4 base. Zero-init B means the
    wrapped model starts exactly at the quantized base's outputs, and
    training moves only the (full-precision) adapters while the base
    stays 0.5 byte/weight at rest."""
    import optax

    from pytorch_distributed_tpu.ops import QuantizedModel
    from pytorch_distributed_tpu.ops.quant import quantize_tree_int4

    model, params, ids = _gpt2()
    qbase = quantize_tree_int4(params, min_size=512)
    adapters = lora_init(jax.random.key(1), qbase, rank=4)
    assert lora_param_count(adapters) > 0
    wrapped = LoRAModel(model, qbase)
    # identity at init vs the quantized base (NOT the f32 original)
    want = QuantizedModel(model).apply({"params": qbase}, ids)
    got = wrapped.apply({"params": adapters}, ids)
    np.testing.assert_array_equal(np.asarray(want), np.asarray(got))

    # adapter-only training on the frozen quantized base learns
    def loss_fn(adapters):
        logits = wrapped.apply({"params": adapters}, ids[:, :-1])
        lp = jax.nn.log_softmax(logits.astype(jnp.float32))
        return -jnp.take_along_axis(
            lp, ids[:, 1:][..., None], axis=-1
        ).mean()

    tx = optax.adam(3e-2)
    opt_state = tx.init(adapters)

    @jax.jit
    def step(adapters, opt_state):
        loss, g = jax.value_and_grad(loss_fn)(adapters)
        updates, opt_state = tx.update(g, opt_state)
        return optax.apply_updates(adapters, updates), opt_state, loss

    first = None
    for _ in range(40):
        adapters, opt_state, loss = step(adapters, opt_state)
        first = float(loss) if first is None else first
    assert float(loss) < first * 0.7, (first, float(loss))
    # adapter shapes came from the reconstructed kernel shapes: the
    # same init on the plain tree matches leaf-for-leaf
    plain = lora_init(jax.random.key(1), params, rank=4)
    for (pq, xq), (pp, xp) in zip(
        jax.tree_util.tree_leaves_with_path(adapters),
        jax.tree_util.tree_leaves_with_path(plain),
    ):
        assert xq.shape == xp.shape, (pq, xq.shape, xp.shape)
