"""MoE transformer end-to-end: GPT-2 with expert FFNs over the ep axis."""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.gpt2 import (
    GPT2Config,
    GPT2LMHead,
    gpt2_partition_rules,
)
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
)

CFG = GPT2Config(
    vocab_size=128, n_positions=32, hidden_size=32, num_layers=2,
    num_heads=2, dropout_rate=0.0, moe_experts=4, moe_k=2,
)


def _init(B=8, S=16):
    model = GPT2LMHead(CFG)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(CFG.vocab_size, size=(B, S)).astype(np.int32))
    params = model.init(jax.random.key(0), ids[:1])["params"]
    return model, params, ids


@pytest.mark.slow
def test_moe_gpt2_forward_shapes_and_params():
    model, params, ids = _init()
    # expert weights exist stacked [L, E, ...] in the scanned tree
    w_in = params["blocks"]["block"]["moe"]["w_in"]
    assert w_in.shape == (2, 4, 32, 128), w_in.shape
    assert "mlp_up" not in params["blocks"]["block"]
    logits = model.apply({"params": params}, ids)
    assert logits.shape == (*ids.shape, CFG.vocab_size)


@pytest.mark.slow  # r5 profile refit: mixtral aux-grads + moe aux-sown tests pin the surface fast
def test_moe_gpt2_trains_with_aux_loss_on_ep_mesh():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, ep=2, tp=2))
    model, params, ids = _init()
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(1e-2)
    )
    strategy = DataParallel(extra_rules=gpt2_partition_rules())
    state = strategy.place(state)
    # experts genuinely sharded over ep (and FFN dim over tp)
    spec = state.params["blocks"]["block"]["moe"]["w_in"].sharding.spec
    assert "ep" in jax.tree_util.tree_leaves(tuple(spec)), spec
    step = strategy.compile(
        build_train_step(causal_lm_loss_fn(model, moe_aux_weight=0.01)),
        state,
    )
    batch = strategy.shard_batch({"input_ids": np.asarray(ids)})
    losses, aux = [], []
    for _ in range(8):
        state, metrics = step(state, batch)
        losses.append(float(metrics["loss"]))
        aux.append(float(metrics["moe_aux_loss"]))
    assert losses[-1] < losses[0] * 0.8, losses
    # the load-balance penalty is present and order-1 x weight
    assert 0 < aux[0] < 1.0, aux


@pytest.mark.slow
def test_moe_gpt2_decode_generates():
    """KV-cache decode works through MoE blocks too.

    Compared in the no-drop regime (ample capacity): with finite capacity,
    routing depends on how many tokens share the call, so decode (1-token
    steps) and full recompute legitimately diverge — see
    GPT2Config.moe_capacity_factor.
    """
    import dataclasses

    cfg = dataclasses.replace(CFG, moe_capacity_factor=8.0)
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(cfg.vocab_size, size=(2, 6)).astype(np.int32))
    params = model.init(jax.random.key(0), ids[:1])["params"]
    out = ptd.generate(
        model, params, ids, max_new_tokens=4, temperature=0.0
    )
    assert out.shape == (2, 10)
    # matches the naive full-recompute greedy
    cur = ids
    for _ in range(4):
        logits = model.apply({"params": params}, cur)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(cur.dtype)
        cur = jnp.concatenate([cur, nxt[:, None]], axis=1)
    np.testing.assert_array_equal(np.asarray(out), np.asarray(cur))


@pytest.mark.slow  # r5 profile refit: chunked-loss equivalences pinned in test_lm_loss
def test_moe_chunked_loss_matches_full():
    """MoE aux + chunked-vocab loss combined: CE and aux must both equal
    the full-logits MoE path."""
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    model, params, ids = _init()
    batch = {"input_ids": np.asarray(ids)}
    full, faux = causal_lm_loss_fn(model, moe_aux_weight=0.01)(
        params, None, batch, jax.random.key(0)
    )
    chunked, caux = causal_lm_loss_fn(
        model, moe_aux_weight=0.01, vocab_chunk_size=32
    )(params, None, batch, jax.random.key(0))
    np.testing.assert_allclose(float(chunked), float(full), rtol=2e-5)
    np.testing.assert_allclose(
        float(caux["metrics"]["moe_aux_loss"]),
        float(faux["metrics"]["moe_aux_loss"]),
        rtol=2e-5,
    )
