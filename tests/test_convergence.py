"""Convergence + multi-step equivalence evidence (VERDICT r1 missing #5).

The north star is throughput *at reference accuracy* (BASELINE.json:5).
With no real dataset reachable offline, the strongest honest substitutes:

* strategies are trajectory-equivalent to single-device training over MANY
  steps (not just the 4-step check in test_parallel.py),
* the full Trainer/DataLoader/eval stack *converges* on learnable synthetic
  tasks — a CNN reaching high accuracy on a separable image task, and a
  transformer memorizing sequences to near-zero loss.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import ArrayDataset, DataLoader
from pytorch_distributed_tpu.parallel import DataParallel, FSDP, ZeRO1
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import (
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    classification_eval_step,
    classification_loss_fn,
)


# ---------------------------------------------------------------------------
# 50-step trajectory equivalence: SPMD strategies == single device
# ---------------------------------------------------------------------------

def _mlp_apply(params, x):
    h = jnp.tanh(x @ params["w1"] + params["b1"])
    return h @ params["w2"] + params["b2"]


def _mlp_state():
    k1, k2 = jax.random.split(jax.random.key(0))
    params = {
        "w1": jax.random.normal(k1, (16, 32)) * 0.2,
        "b1": jnp.zeros((32,)),
        "w2": jax.random.normal(k2, (32, 4)) * 0.2,
        "b2": jnp.zeros((4,)),
    }
    return TrainState.create(
        apply_fn=_mlp_apply, params=params, tx=optax.adam(1e-2)
    )


def _mse_step(state, batch):
    def loss_fn(params):
        pred = state.apply_fn(params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads), {"loss": loss}


def _batches(n=80, b=32):
    rng = np.random.default_rng(7)
    w = rng.normal(size=(16, 4)).astype(np.float32)
    out = []
    for _ in range(n):
        x = rng.normal(size=(b, 16)).astype(np.float32)
        out.append({"x": x, "y": (x @ w).astype(np.float32)})
    return out


@pytest.mark.parametrize(
    "strategy_cls", [DataParallel, ZeRO1, FSDP], ids=["ddp", "zero1", "fsdp"]
)
def test_strategy_matches_single_device_over_80_steps(strategy_cls):
    batches = _batches()

    # single-device reference
    make_mesh(MeshSpec(dp=1, fsdp=1, tp=1), devices=jax.devices()[:1])
    ref_state = _mlp_state()
    ref_step = jax.jit(_mse_step)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
    strategy = strategy_cls(mesh)
    state = strategy.place(_mlp_state())
    step = strategy.compile(_mse_step, state)
    losses = []
    for b in batches:
        state, m = step(state, strategy.shard_batch(b))
        losses.append(float(m["loss"]))

    # the task is learnable: the reference itself must have converged
    assert ref_losses[-1] < ref_losses[0] * 0.2, ref_losses[::10]
    np.testing.assert_allclose(losses, ref_losses, rtol=5e-4)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(state.params),
        jax.tree_util.tree_leaves_with_path(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), rtol=2e-3, atol=1e-4,
            err_msg=str(path),
        )


# ---------------------------------------------------------------------------
# Full-stack convergence: Trainer + DataLoader + eval on a learnable task
# ---------------------------------------------------------------------------

def _separable_images(n, classes=4, size=8, seed=0):
    """Images whose class is the brightest quadrant — CNN-learnable fast."""
    rng = np.random.default_rng(seed)
    imgs = rng.normal(0.0, 0.3, size=(n, size, size, 3)).astype(np.float32)
    labels = rng.integers(classes, size=n).astype(np.int32)
    h = size // 2
    sl = [(slice(0, h), slice(0, h)), (slice(0, h), slice(h, None)),
          (slice(h, None), slice(0, h)), (slice(h, None), slice(h, None))]
    for i, c in enumerate(labels):
        ys, xs = sl[c]
        imgs[i, ys, xs, :] += 1.0
    return imgs, labels


@pytest.mark.slow
def test_trainer_converges_cnn_on_separable_task(tmp_path):
    import flax.linen as nn

    class TinyCNN(nn.Module):
        @nn.compact
        def __call__(self, x, train=False):
            x = nn.Conv(16, (3, 3))(x)
            x = nn.relu(x)
            x = nn.avg_pool(x, (2, 2), (2, 2))
            x = nn.Conv(32, (3, 3))(x)
            x = nn.relu(x)
            x = jnp.mean(x, axis=(1, 2))
            return nn.Dense(4, name="head")(x)

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    imgs, labels = _separable_images(512)
    eval_imgs, eval_labels = _separable_images(128, seed=1)
    model = TinyCNN()
    variables = model.init(jax.random.key(0), imgs[:1])
    state = TrainState.create(
        apply_fn=model.apply, params=variables["params"], tx=optax.adam(3e-3)
    )
    strategy = DataParallel()
    train_loader = DataLoader(
        ArrayDataset(image=imgs, label=labels), 64,
        sharding=strategy.batch_sharding(),
    )
    eval_loader = DataLoader(
        ArrayDataset(image=eval_imgs, label=eval_labels), 64, shuffle=False,
        sharding=strategy.batch_sharding(),
    )
    trainer = Trainer(
        state, strategy,
        build_train_step(classification_loss_fn(model)),
        train_loader,
        eval_step=classification_eval_step(model),
        eval_loader=eval_loader,
        config=TrainerConfig(
            epochs=8, log_every=0, ckpt_dir=str(tmp_path),
            handle_preemption=False,
        ),
    )
    trainer.fit()
    assert trainer.last_eval_metrics["accuracy"] > 0.95, (
        trainer.last_eval_metrics
    )


@pytest.mark.slow
def test_gpt2_tiny_memorizes_sequences():
    """The transformer path *learns*: loss on a fixed corpus -> near zero."""
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    cfg = GPT2Config(
        vocab_size=64, n_positions=16, hidden_size=64, num_layers=2,
        num_heads=4, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(cfg.vocab_size, size=(16, 16)).astype(np.int32)
    params = model.init(jax.random.key(0), jnp.asarray(ids[:1]))["params"]
    state = TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.adam(3e-3)
    )
    strategy = DataParallel()
    state = strategy.place(state)
    step = strategy.compile(
        build_train_step(causal_lm_loss_fn(model)), state
    )
    batch = strategy.shard_batch({"input_ids": ids})
    first = last = None
    for i in range(300):
        state, metrics = step(state, batch)
        # periodic sync: don't let 300 donated steps pile up in flight
        if i == 0:
            first = float(metrics["loss"])
        elif i % 25 == 0:
            float(metrics["loss"])
    last = float(metrics["loss"])
    assert first > 3.0, first          # starts near ln(64) ~ 4.16
    assert last < 0.3, (first, last)   # memorized


# ---------------------------------------------------------------------------
# MoE trajectory equivalence: expert-parallel sharding == single device
# ---------------------------------------------------------------------------

@pytest.mark.slow  # r5: the dense-strategy equivalences above stay fast
def test_moe_dp_ep_matches_single_device_over_30_steps():
    """The r5 sparse-MoE family earns a trust anchor next to the dense
    strategies': a tiny Mixtral trained 30 steps under dp=2 x ep=2 x
    tp=2 sharding (experts over ep) tracks the single-device trajectory
    — losses (task + aux) to 1e-3 and params loosely. NOT the dense
    families' near-bitwise pin, deliberately: top-k routing is
    DISCRETE, and the sharded compilation's differently-ordered f32
    reductions can flip near-tie routes; a handful of flips over 30
    adam steps measurably moves a few embed rows (observed: ~1.5% of
    elements by <=5e-3) while the loss curve stays glued. A real
    sharding bug produces gross divergence, which these tolerances
    still catch. Drop-free dispatch so routing is
    batch-composition-independent."""
    import dataclasses

    from pytorch_distributed_tpu.models import (
        MixtralConfig,
        MixtralForCausalLM,
        mixtral_partition_rules,
    )
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = dataclasses.replace(
        MixtralConfig.tiny(), capacity_factor=None, vocab_size=64,
    )
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(3)
    batches = [
        {"input_ids": rng.integers(2, 64, size=(8, 12)).astype(np.int32)}
        for _ in range(30)
    ]
    ids0 = jnp.asarray(batches[0]["input_ids"])

    def fresh_state():
        return TrainState.create(
            apply_fn=model.apply,
            params=model.init(jax.random.key(0), ids0)["params"],
            tx=optax.adam(1e-3),
        )

    step_fn = build_train_step(
        causal_lm_loss_fn(model, moe_aux_weight=0.01)
    )

    make_mesh(MeshSpec(dp=1, fsdp=1, tp=1), devices=jax.devices()[:1])
    ref_state = fresh_state()
    ref_step = jax.jit(step_fn)
    ref_losses = []
    for b in batches:
        ref_state, m = ref_step(ref_state, b)
        ref_losses.append(float(m["loss"]))

    mesh = make_mesh(MeshSpec(dp=2, ep=2, tp=2))
    strategy = DataParallel(mesh, extra_rules=mixtral_partition_rules())
    state = strategy.place(fresh_state())
    step = strategy.compile(step_fn, state)
    losses = []
    for b in batches:
        state, m = step(state, strategy.shard_batch(b))
        losses.append(float(m["loss"]))

    assert ref_losses[-1] < ref_losses[0], ref_losses[::10]  # it learns
    np.testing.assert_allclose(losses, ref_losses, rtol=1e-3)
    for (path, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(state.params),
        jax.tree_util.tree_leaves_with_path(ref_state.params),
    ):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(b), atol=2e-2,
            err_msg=str(path),
        )
