"""Torch-weight interop parity: HF state_dict -> our params, same logits.

The strongest offline evidence that the model families are faithful
re-implementations: random-initialized Hugging Face torch models and our
models produce matching outputs through the converted weights (f32, eval
mode). Tolerances are f32-accumulation loose-ness only.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from pytorch_distributed_tpu.interop import (
    load_bert_weights,
    load_gpt2_weights,
    load_llama_weights,
)
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def test_gpt2_logits_match_hf():
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    hf_cfg = transformers.GPT2Config(
        vocab_size=211, n_positions=32, n_embd=48, n_layer=3, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(
        vocab_size=211, n_positions=32, hidden_size=48, num_layers=3,
        num_heads=4, dropout_rate=0.0,
    )
    params = load_gpt2_weights(_sd(hf), cfg)

    rng = np.random.default_rng(0)
    ids = rng.integers(211, size=(2, 17)).astype(np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = GPT2LMHead(cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


def test_gpt2_unrolled_layout_matches_hf():
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    hf_cfg = transformers.GPT2Config(
        vocab_size=97, n_positions=16, n_embd=32, n_layer=2, n_head=2,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    cfg = GPT2Config(
        vocab_size=97, n_positions=16, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0, scan_layers=False,
    )
    params = load_gpt2_weights(_sd(hf), cfg)
    ids = np.random.default_rng(1).integers(97, size=(2, 9)).astype(np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = GPT2LMHead(cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_llama_logits_match_hf():
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    hf_cfg = transformers.LlamaConfig(
        vocab_size=151, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        max_position_embeddings=32, rope_theta=500_000.0,
        rms_norm_eps=1e-5, attention_dropout=0.0, tie_word_embeddings=False,
    )
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=151, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=32,
    )
    params = load_llama_weights(_sd(hf), cfg)
    ids = np.random.default_rng(2).integers(151, size=(2, 11)).astype(np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = LlamaForCausalLM(cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=3e-4)


def test_llama_unrolled_layout_loads_and_matches():
    """Unrolled llama uses 'layer{i}' keys (r2 review: prefix mismatch)."""
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    hf_cfg = transformers.LlamaConfig(
        vocab_size=73, hidden_size=32, intermediate_size=64,
        num_hidden_layers=2, num_attention_heads=2, num_key_value_heads=2,
        max_position_embeddings=16, rope_theta=500_000.0,
        rms_norm_eps=1e-5, tie_word_embeddings=False,
    )
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=73, hidden_size=32, intermediate_size=64, num_layers=2,
        num_heads=2, num_kv_heads=2, max_seq_len=16, scan_layers=False,
    )
    params = load_llama_weights(_sd(hf), cfg)
    ids = np.random.default_rng(4).integers(73, size=(1, 6)).astype(np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = LlamaForCausalLM(cfg).apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=3e-4)


@pytest.mark.slow
def test_bert_classifier_matches_hf():
    from pytorch_distributed_tpu.models.bert import (
        BertConfig,
        BertForSequenceClassification,
    )

    hf_cfg = transformers.BertConfig(
        vocab_size=119, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=96,
        max_position_embeddings=32, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
        num_labels=3,
    )
    hf = transformers.BertForSequenceClassification(hf_cfg).eval()
    cfg = BertConfig(
        vocab_size=119, hidden_size=48, num_layers=2, num_heads=4,
        intermediate_size=96, max_position_embeddings=32,
        dropout_rate=0.0,
    )
    params = load_bert_weights(_sd(hf), cfg, num_labels=3)

    rng = np.random.default_rng(3)
    ids = rng.integers(119, size=(2, 13)).astype(np.int32)
    mask = np.ones((2, 13), np.int64)
    mask[1, 9:] = 0  # padding on one row exercises the mask path
    with torch.no_grad():
        want = hf(
            torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask),
        ).logits.numpy()
    with autocast(enabled=False):
        got = BertForSequenceClassification(cfg, num_labels=3).apply(
            {"params": params}, ids, attention_mask=mask.astype(bool)
        )
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=3e-4)


def test_gpt2_export_roundtrips_into_torch():
    """Our trained params -> torch state_dict -> HF forward matches ours."""
    from pytorch_distributed_tpu.interop import export_gpt2_weights
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    cfg = GPT2Config(
        vocab_size=83, n_positions=16, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    import jax.numpy as jnp

    model = GPT2LMHead(cfg)
    ids = np.random.default_rng(5).integers(83, size=(2, 9)).astype(np.int32)
    params = model.init(
        __import__("jax").random.key(3), jnp.asarray(ids[:1])
    )["params"]
    sd = export_gpt2_weights(params, cfg)
    hf = transformers.GPT2LMHeadModel(
        transformers.GPT2Config(
            vocab_size=83, n_positions=16, n_embd=32, n_layer=2, n_head=2,
            resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
        )
    )
    missing, unexpected = hf.load_state_dict(
        {k: torch.tensor(v) for k, v in sd.items()}, strict=False
    )
    # HF keeps non-param buffers (attn.bias causal masks) — those may be
    # "missing" from our export; no exported key may be unexpected
    assert not unexpected, unexpected
    assert all("attn.bias" in k or "masked_bias" in k for k in missing), missing
    hf.eval()
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = model.apply({"params": params}, ids)
    np.testing.assert_allclose(np.asarray(got), want, atol=2e-4, rtol=2e-4)


@pytest.mark.slow
def test_llama_export_import_roundtrip():
    """export -> import is the identity on every leaf (both layouts)."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.interop import export_llama_weights
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )

    for scan in (True, False):
        cfg = LlamaConfig(
            vocab_size=51, hidden_size=32, intermediate_size=48,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=16,
            scan_layers=scan,
        )
        params = LlamaForCausalLM(cfg).init(
            jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
        )["params"]
        back = load_llama_weights(export_llama_weights(params, cfg), cfg)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(back),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, err_msg=str(pa)
            )


@pytest.mark.slow  # r5 profile refit: bert classifier HF parity stays fast
def test_bert_export_import_roundtrip():
    """export -> import is the identity on every leaf, trunk and
    classification trees both; exported keys load into HF exactly."""
    import jax
    import jax.numpy as jnp

    from pytorch_distributed_tpu.interop import (
        export_bert_weights,
        load_bert_weights,
    )
    from pytorch_distributed_tpu.models.bert import (
        BertConfig,
        BertForSequenceClassification,
        BertModel,
    )

    cfg = BertConfig(
        vocab_size=67, hidden_size=32, intermediate_size=48, num_layers=2,
        num_heads=4, max_position_embeddings=16,
    )
    ids = jnp.zeros((1, 8), jnp.int32)
    for num_labels in (None, 3):
        if num_labels is None:
            params = BertModel(cfg).init(jax.random.key(0), ids)["params"]
        else:
            params = BertForSequenceClassification(
                cfg, num_labels=num_labels
            ).init(jax.random.key(0), ids)["params"]
        sd = export_bert_weights(params, cfg)
        back = load_bert_weights(sd, cfg, num_labels=num_labels)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(params),
            jax.tree_util.tree_leaves_with_path(back),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), atol=1e-6, err_msg=str(pa)
            )

    # key-set parity with a real HF module (classification layout)
    hf_cfg = transformers.BertConfig(
        vocab_size=67, hidden_size=32, intermediate_size=48,
        num_hidden_layers=2, num_attention_heads=4,
        max_position_embeddings=16, num_labels=3,
    )
    hf = transformers.BertForSequenceClassification(hf_cfg)
    params = BertForSequenceClassification(cfg, num_labels=3).init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    sd = export_bert_weights(params, cfg)
    import torch

    missing, unexpected = hf.load_state_dict(
        {k: torch.tensor(v) for k, v in sd.items()}, strict=False
    )
    assert not unexpected, unexpected
    # HF-side-only leaves we legitimately don't model
    assert all(
        "position_ids" in k for k in missing
    ), missing


def test_converted_tree_structure_matches_init():
    """Converter output must be loadable exactly where init puts params."""
    import jax.numpy as jnp

    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    hf_cfg = transformers.GPT2Config(
        vocab_size=61, n_positions=8, n_embd=16, n_layer=2, n_head=2,
    )
    hf = transformers.GPT2LMHeadModel(hf_cfg)
    cfg = GPT2Config(
        vocab_size=61, n_positions=8, hidden_size=16, num_layers=2,
        num_heads=2,
    )
    params = load_gpt2_weights(_sd(hf), cfg)
    ref = GPT2LMHead(cfg).init(
        jax.random.key(0), jnp.zeros((1, 4), jnp.int32)
    )["params"]
    ref_paths = {
        jax.tree_util.keystr(p): v.shape
        for p, v in jax.tree_util.tree_leaves_with_path(ref)
    }
    got_paths = {
        jax.tree_util.keystr(p): np.asarray(v).shape
        for p, v in jax.tree_util.tree_leaves_with_path(params)
    }
    assert ref_paths == got_paths


def test_vit_logits_match_hf():
    """Converted HF ViT weights produce the same logits as HF's forward."""
    from pytorch_distributed_tpu.interop import load_vit_weights
    from pytorch_distributed_tpu.models.vit import ViT, ViTConfig

    hf_cfg = transformers.ViTConfig(
        image_size=32, patch_size=8, num_labels=7, hidden_size=48,
        num_hidden_layers=2, num_attention_heads=4, intermediate_size=96,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    torch.manual_seed(0)
    hf = transformers.ViTForImageClassification(hf_cfg).eval()
    images = np.random.default_rng(0).normal(size=(2, 32, 32, 3)).astype(
        np.float32
    )
    with torch.no_grad():
        want = hf(
            torch.tensor(images.transpose(0, 3, 1, 2))
        ).logits.numpy()

    cfg = ViTConfig(
        image_size=32, patch_size=8, num_classes=7, hidden_size=48,
        num_layers=2, num_heads=4, mlp_dim=96,
        layer_norm_eps=hf_cfg.layer_norm_eps,
    )
    params = load_vit_weights(_sd(hf), cfg)
    with autocast(enabled=False):
        got = ViT(cfg).apply({"params": params}, jnp.asarray(images))
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-5)


@pytest.mark.slow  # r5 profile refit: vit_logits_match_hf stays fast
def test_vit_export_import_roundtrip():
    from pytorch_distributed_tpu.interop import (
        export_vit_weights,
        load_vit_weights,
    )
    from pytorch_distributed_tpu.models.vit import ViT, ViTConfig

    cfg = ViTConfig.tiny()
    params = ViT(cfg).init(
        jax.random.key(0), jnp.zeros((1, 32, 32, 3))
    )["params"]
    back = load_vit_weights(export_vit_weights(params, cfg), cfg)
    for (pa, a), (_, b) in zip(
        jax.tree_util.tree_leaves_with_path(params),
        jax.tree_util.tree_leaves_with_path(back),
    ):
        np.testing.assert_array_equal(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            err_msg=str(pa),
        )


def test_gpt2_generate_matches_hf_token_for_token():
    """Greedy decode through converted weights equals transformers' own
    ``generate`` — plain AND with repetition_penalty (our presence-mask
    implementation vs HF's RepetitionPenaltyLogitsProcessor)."""
    from pytorch_distributed_tpu.generation import generate
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    hf_cfg = transformers.GPT2Config(
        vocab_size=211, n_positions=64, n_embd=48, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(0)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    ids = np.random.default_rng(0).integers(
        1, 211, size=(2, 7)
    ).astype(np.int64)
    cfg = GPT2Config(
        vocab_size=211, n_positions=64, hidden_size=48, num_layers=2,
        num_heads=4, dropout_rate=0.0,
    )
    params = load_gpt2_weights(_sd(hf), cfg)
    model = GPT2LMHead(cfg)

    for pen in (1.0, 1.7):
        with torch.no_grad():
            want = hf.generate(
                torch.tensor(ids), max_new_tokens=8, do_sample=False,
                repetition_penalty=pen, pad_token_id=0,
            ).numpy()
        with autocast(enabled=False):
            got = np.asarray(
                generate(
                    model, params, jnp.asarray(ids.astype(np.int32)),
                    max_new_tokens=8, temperature=0.0,
                    repetition_penalty=pen,
                )
            )
        np.testing.assert_array_equal(got, want, err_msg=f"penalty={pen}")


def test_gpt2_no_repeat_ngram_matches_hf():
    """no_repeat_ngram_size bans match HF's NoRepeatNGramLogitsProcessor
    token-for-token through converted weights (greedy)."""
    from pytorch_distributed_tpu.generation import generate
    from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead

    hf_cfg = transformers.GPT2Config(
        vocab_size=53, n_positions=64, n_embd=32, n_layer=2, n_head=4,
        resid_pdrop=0.0, embd_pdrop=0.0, attn_pdrop=0.0,
    )
    torch.manual_seed(1)
    hf = transformers.GPT2LMHeadModel(hf_cfg).eval()
    # tiny vocab forces repeats quickly, so the ban path actually fires
    ids = np.random.default_rng(2).integers(
        1, 53, size=(2, 6)
    ).astype(np.int64)
    cfg = GPT2Config(
        vocab_size=53, n_positions=64, hidden_size=32, num_layers=2,
        num_heads=4, dropout_rate=0.0,
    )
    params = load_gpt2_weights(_sd(hf), cfg)
    model = GPT2LMHead(cfg)
    for ngram in (1, 2, 3):
        with torch.no_grad():
            want = hf.generate(
                torch.tensor(ids), max_new_tokens=16, do_sample=False,
                no_repeat_ngram_size=ngram, pad_token_id=0,
            ).numpy()
        with autocast(enabled=False):
            got = np.asarray(
                generate(
                    model, params, jnp.asarray(ids.astype(np.int32)),
                    max_new_tokens=16, temperature=0.0,
                    no_repeat_ngram_size=ngram,
                )
            )
        np.testing.assert_array_equal(got, want, err_msg=f"ngram={ngram}")


@pytest.mark.slow  # r5 profile refit: bert classifier HF parity + bert export roundtrip stay fast
def test_bert_mlm_matches_hf_and_roundtrips():
    """HF BertForMaskedLM import: logit parity (tied decoder via the
    trunk embedding), and export -> import is the identity."""
    from pytorch_distributed_tpu.interop import (
        export_bert_weights,
        load_bert_weights,
    )
    from pytorch_distributed_tpu.models.bert import BertConfig, BertForMaskedLM

    hf_cfg = transformers.BertConfig(
        vocab_size=119, hidden_size=48, num_hidden_layers=2,
        num_attention_heads=4, intermediate_size=96,
        max_position_embeddings=32, type_vocab_size=2,
        hidden_dropout_prob=0.0, attention_probs_dropout_prob=0.0,
    )
    hf = transformers.BertForMaskedLM(hf_cfg).eval()
    cfg = BertConfig(
        vocab_size=119, hidden_size=48, num_layers=2, num_heads=4,
        intermediate_size=96, max_position_embeddings=32,
        dropout_rate=0.0,
    )
    params = load_bert_weights(_sd(hf), cfg)
    assert "mlm_dense" in params and "mlm_bias" in params

    rng = np.random.default_rng(5)
    ids = rng.integers(119, size=(2, 11)).astype(np.int32)
    mask = np.ones((2, 11), np.int64)
    mask[0, 8:] = 0
    with torch.no_grad():
        want = hf(
            torch.tensor(ids.astype(np.int64)),
            attention_mask=torch.tensor(mask),
        ).logits.numpy()
    with autocast(enabled=False):
        model = BertForMaskedLM(cfg)
        got = model.apply(
            {"params": params}, jnp.asarray(ids),
            jnp.asarray(mask.astype(np.int32)),
        )
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-4, atol=2e-4)

    sd2 = export_bert_weights(params, cfg)
    # loads into HF (strict=False: HF's MLM is poolerless, so the two
    # pooler keys are the ONLY unexpected ones; tied decoder + alias
    # emitted so nothing is missing), and re-import is the identity
    result = hf.load_state_dict(
        {k: torch.tensor(v) for k, v in sd2.items()}, strict=False
    )
    assert not result.missing_keys, result.missing_keys
    assert all("pooler" in k for k in result.unexpected_keys), (
        result.unexpected_keys
    )
    params2 = load_bert_weights(sd2, cfg)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_array_equal(np.asarray(a), np.asarray(b)),
        params, params2,
    )

    # natively-initialized MLM params (real random pooler) roundtrip
    # exactly too — the pooler is carried, not zeroed
    native = BertForMaskedLM(cfg).init(
        jax.random.key(1), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    native3 = load_bert_weights(export_bert_weights(native, cfg), cfg)
    jax.tree_util.tree_map(
        lambda a, b: np.testing.assert_allclose(
            np.asarray(a, np.float32), np.asarray(b, np.float32),
            rtol=1e-6, atol=0,
        ),
        native, native3,
    )
    assert np.abs(
        np.asarray(native3["bert"]["pooler"]["kernel"])
    ).max() > 0  # the roundtripped pooler is the real one, not zeros

    # a NON-MLM poolerless state_dict still fails loudly
    bad = {k: v for k, v in _sd(hf).items()
           if "pooler" not in k and "cls.predictions" not in k}
    with pytest.raises(KeyError, match="pooler"):
        load_bert_weights(bad, cfg)


def test_attention_extras_on_later_layers_still_refuse():
    """ADVICE r5: the refuse-don't-drop guards must scan EVERY layer
    prefix — a checkpoint carrying biases/norms only on layer 1 used to
    slip past the layer-0-only check into silent HF divergence."""
    from pytorch_distributed_tpu.models.llama import LlamaConfig

    cfg = LlamaConfig.tiny()  # attention_bias=False, qk_norm=False
    with pytest.raises(ValueError, match="attention projection biases"):
        load_llama_weights(
            {"model.layers.1.self_attn.q_proj.bias": np.zeros(16)}, cfg
        )
    with pytest.raises(ValueError, match="q_norm/k_norm"):
        load_llama_weights(
            {"model.layers.1.self_attn.k_norm.weight": np.zeros(16)}, cfg
        )
