"""Heterogeneity-aware microshard balancing (marker: hetero).

Three layers:

* the pure pieces — ``train/balance.py``'s integer apportionment
  (hand-computed counts, determinism, the zero-shard rejection, the
  granularity guard) and the ``mode=throttle`` fault injector;
* the engine — THE invariance proof live on a 3-proc ring: even split,
  rate-skewed split, and mid-run reassignments all land bit-identical
  to the solo reference (same shards, same fixed fold order — only
  ownership moves), plus the chaos case (the throttled rank SIGKILLed
  mid-run; the rebalanced survivors still match the solo CRC);
* the HostLoopStep half — ``set_microbatch_plan`` validation and the
  2-proc uneven-counts parity worker (deterministic, lockstep,
  last-ulp vs the even split — the documented non-bit-exact scope).

The bench ``hetero`` phase (throughput ratio + three-way CRC equality,
pinned by test_bench_contract) is the performance half of the claim;
everything here is correctness.
"""

import logging
import os

import numpy as np
import pytest

from pytorch_distributed_tpu.launch import ElasticWorldLauncher
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.train import balance
from pytorch_distributed_tpu.train.balance import BalanceError
from pytorch_distributed_tpu.train.elastic_world import (
    ElasticConfig,
    reference_run,
)

from tests import hostring_workers

pytestmark = pytest.mark.hetero

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# -- the pure assignment function ------------------------------------------


class TestApportion:
    def test_hand_computed_two_to_one(self):
        # rates [1, 1, 0.5] -> quantized [65536, 65536, 32768]; exact
        # integer quotas 4.8/4.8/2.4 of 12 -> base [4, 4, 2], two
        # remainder seats to the two largest remainders (ranks 0, 1)
        assert balance.assign(12, [1.0, 1.0, 0.5]) == (
            0, 0, 0, 0, 0, 1, 1, 1, 1, 1, 2, 2
        )

    def test_equal_rates_recover_the_even_counts(self):
        for world in (1, 2, 3, 4):
            a = balance.assign(4 * world, [1.0] * world)
            assert balance.counts_of(a, world) == [4] * world

    def test_deterministic_and_scale_invariant(self):
        rates = [3.1, 1.7, 2.4, 0.9]
        a = balance.assign(16, rates)
        assert a == balance.assign(16, rates)
        # rates are relative: scaling the vector changes nothing
        assert a == balance.assign(16, [r * 7.3 for r in rates])

    def test_every_rank_keeps_at_least_one_shard(self):
        # a 100x skew must not starve the slow rank: zero-shard ranks
        # still pay every collective, so dropping one is a MEMBERSHIP
        # decision, never a balancing side effect
        counts = balance.counts_of(
            balance.assign(8, [100.0, 100.0, 1.0]), 3
        )
        assert min(counts) >= 1 and sum(counts) == 8

    def test_fewer_shards_than_ranks_rejected(self):
        with pytest.raises(BalanceError, match="zero shards"):
            balance.assign(2, [1.0, 1.0, 1.0])

    def test_bad_rates_rejected(self):
        for bad in ([], [1.0, 0.0], [1.0, -2.0], [1.0, float("nan")],
                    [1.0, float("inf")]):
            with pytest.raises(BalanceError):
                balance.assign(8, bad)

    def test_apportion_floor_lifts_from_largest_holder(self):
        # 5 units, weights heavily skewed: the floor seat comes out of
        # the largest count, deterministically
        counts = balance.apportion(5, [1000, 1000, 1], floor=1)
        assert counts == [2, 2, 1]
        with pytest.raises(BalanceError):
            balance.apportion(2, [1, 1, 1], floor=1)

    def test_row_bookkeeping_consistent(self):
        a = balance.assign(12, [1.0, 2.0, 0.5])
        world = 3
        rowidx = balance.row_index(a)
        for rank in range(world):
            owned = balance.owned_shards(a, rank)
            assert owned == sorted(owned)
            # shard s sits at row rowidx[s] of its owner's contribution
            for j, s in enumerate(owned):
                assert rowidx[s] == j
        assert sorted(
            s for r in range(world) for s in balance.owned_shards(a, r)
        ) == list(range(12))

    def test_microbatch_counts_same_apportionment(self):
        assert balance.microbatch_counts(6, [2.0, 1.0]) == [4, 2]
        assert balance.microbatch_counts(4, [1.0, 1.0]) == [2, 2]


class TestTelemetry:
    def test_rate_ema_tracks_and_rides_out_noise(self):
        r = balance.RateEMA(alpha=0.5)
        assert r.update(4, 0.4) == pytest.approx(0.1)  # first: exact
        r.update(4, 0.4)
        assert r.per_unit_s == pytest.approx(0.1)
        r.update(4, 0.8)  # one slow step moves it halfway
        assert r.per_unit_s == pytest.approx(0.15)
        # zero/negative observations are ignored, not folded
        before = r.per_unit_s
        r.update(0, 1.0)
        r.update(4, 0.0)
        assert r.per_unit_s == before

    def test_fill_unknown_uses_fleet_mean(self):
        assert balance.fill_unknown([0.2, 0.0, 0.4]) == pytest.approx(
            [0.2, 0.3, 0.4]
        )
        # all-unknown (genesis) degrades to all-equal -> the even split
        assert balance.fill_unknown([0.0, 0.0]) == [1.0, 1.0]

    def test_skew_gauge(self):
        assert balance.skew([0.1, 0.2, 0.1]) == pytest.approx(2.0)
        assert balance.skew([0.1]) == 1.0
        assert balance.skew([0.0, 0.0]) == 1.0

    def test_derive_assignment_genesis_is_even(self):
        # no telemetry anywhere -> exactly the even split's counts
        a = balance.derive_assignment(12, [0.0, 0.0, 0.0])
        assert balance.counts_of(a, 3) == [4, 4, 4]

    def test_derive_assignment_s_below_world_falls_back_loudly(
        self, caplog
    ):
        ns = logging.getLogger("pytorch_distributed_tpu")
        ns.addHandler(caplog.handler)
        try:
            with caplog.at_level(
                logging.WARNING, logger="pytorch_distributed_tpu"
            ):
                a = balance.derive_assignment(2, [0.1, 0.2, 0.3])
        finally:
            ns.removeHandler(caplog.handler)
        assert a == balance.even_assignment(2, 3)
        assert any("even split" in r.message for r in caplog.records)

    def test_granularity_guard(self, caplog):
        assert balance.granularity_ok(12, 3)
        assert not balance.granularity_ok(11, 3)
        ns = logging.getLogger("pytorch_distributed_tpu")
        ns.addHandler(caplog.handler)
        try:
            with caplog.at_level(
                logging.WARNING, logger="pytorch_distributed_tpu"
            ):
                balance.derive_assignment(4, [0.1, 0.2, 0.1])
                balance.derive_assignment(4, [0.1, 0.2, 0.1],
                                          warn_coarse=False)
        finally:
            ns.removeHandler(caplog.handler)
        warns = [r for r in caplog.records if "coarse" in r.message]
        assert len(warns) == 1  # warn_coarse=False suppresses


class TestElasticConfigGuards:
    def test_balance_flag_validated(self):
        with pytest.raises(ValueError, match="balance"):
            ElasticConfig(total_steps=1, global_batch=4, microshards=4,
                          balance="maybe")
        with pytest.raises(ValueError, match="rebalance_every"):
            ElasticConfig(total_steps=1, global_batch=4, microshards=4,
                          rebalance_every=-1)
        with pytest.raises(ValueError, match="rate_ema"):
            ElasticConfig(total_steps=1, global_batch=4, microshards=4,
                          rate_ema=0.0)
        with pytest.raises(ValueError, match="shard_delay_s"):
            ElasticConfig(total_steps=1, global_batch=4, microshards=4,
                          shard_delay_s=-0.1)


# -- the throttle injector -------------------------------------------------


class TestThrottleSite:
    def test_site_registered(self):
        assert "elastic.slow_rank" in faults.KNOWN_SITES

    def test_disarmed_is_unit_factor(self):
        assert not faults.active()
        assert faults.throttle("elastic.slow_rank") == 1.0

    def test_armed_factor_and_after_budget(self):
        spec = "elastic.slow_rank:mode=throttle,factor=2.5,after=2"
        with faults.injected(spec):
            got = [faults.throttle("elastic.slow_rank") for _ in range(5)]
        assert got == [1.0, 1.0, 2.5, 2.5, 2.5]

    def test_check_ignores_throttle_sites(self):
        # a throttle-mode site must never raise/kill through check():
        # the same site name polled by both forms cannot double-fire
        with faults.injected("elastic.slow_rank:mode=throttle,factor=3"):
            faults.check("elastic.slow_rank")  # no raise
            assert faults.throttle("elastic.slow_rank") == 3.0

    def test_non_throttle_site_reports_unit_factor(self):
        with faults.injected("elastic.peer_lost:mode=kill,after=99"):
            assert faults.throttle("elastic.peer_lost") == 1.0

    def test_factor_validated(self):
        with pytest.raises(ValueError, match="factor"):
            faults.FaultPlan.parse(
                "elastic.slow_rank:mode=throttle,factor=0"
            )


# -- the invariance proof, live --------------------------------------------


def _launcher(tmp_path, tag, **overrides):
    defaults = {
        "--total-steps": "10",
        "--global-batch": "24",
        "--microshards": "12",
        "--shard-delay-s": "0.005",
        "--rebalance-every": "3",
        "--ring-timeout-s": "3.0",
        "--metrics-path": str(tmp_path / f"{tag}.jsonl"),
    }
    defaults.update(overrides)
    args = []
    for k, v in defaults.items():
        if v is not None:
            args += [k, str(v)]
    return ElasticWorldLauncher(
        str(tmp_path / f"rdv_{tag}"), worker_args=args
    )


THROTTLE = "elastic.slow_rank:mode=throttle,factor=2"


def test_assignment_invariance_even_skewed_and_midrun(tmp_path):
    """THE bit-exactness proof: the same 3-proc world with one rank
    throttled 2x runs under balance=off (even split, every step) and
    balance=on (telemetry-skewed split, committed MID-RUN at the
    rebalance boundaries — steps before the first boundary run even,
    after it skewed, so one run covers even, skewed, AND the
    reassignment transition at step k) — and both land bit-identical
    to the solo reference. Same shards, same fixed fold order; only
    ownership moved."""
    ref = reference_run(ElasticConfig(
        total_steps=10, global_batch=24, microshards=12
    ))
    results = {}
    for mode in ("off", "on"):
        launcher = _launcher(tmp_path, mode, **{"--balance": mode})
        launcher.start_world(
            ["w0", "w1", "w2"],
            env_overrides={"w2": {"PTD_FAULTS": THROTTLE}},
        )
        codes = launcher.wait(120)
        assert all(c == 0 for c in codes.values()), codes
        results[mode] = launcher.results()
    for mode in ("off", "on"):
        for wid in ("w0", "w1", "w2"):
            r = results[mode][wid]
            assert r["final_step"] == 10, (mode, wid)
            assert r["params_crc"] == ref["params_crc"], (mode, wid)
    # balance=off never moved off round-robin
    assert results["off"]["w0"]["assignment_counts"] == [4, 4, 4]
    assert results["off"]["w0"]["rebalances"] == []
    # balance=on measured the skew and moved ownership mid-run (the
    # genesis view-commit has no telemetry and stays at even counts;
    # the first INTERVAL boundary carries the measured skew)
    on = results["on"]["w0"]
    assert on["rebalances"], on
    moved = [
        r for r in on["rebalances"]
        if r["changed"] and r["counts"] != [4, 4, 4]
    ]
    assert moved, on["rebalances"]
    assert moved[0]["skew"] > 1.3, moved[0]
    assert moved[0]["step"] > 0, moved[0]  # committed MID-run
    counts = on["assignment_counts"]
    assert counts != [4, 4, 4] and sum(counts) == 12
    assert counts[2] < 4, counts  # the throttled rank sheds shards
    # every rank committed the identical final assignment
    for wid in ("w1", "w2"):
        assert results["on"][wid]["assignment_counts"] == counts


def test_chaos_throttled_rank_killed_midrun(tmp_path):
    """The chaos case: the 2x-throttled rank is SIGKILLed mid-run. The
    survivors re-mesh in-process (the r13 path), the post-resize
    rebalance re-derives ownership over the 2-rank world, and the
    finishers STILL match the solo reference CRC — a resize and a
    rebalance are the same kind of event, and neither moves the
    math."""
    launcher = _launcher(tmp_path, "chaos", **{
        "--total-steps": "12", "--balance": "on",
        "--ring-timeout-s": "2.0",
    })
    launcher.start_world(
        ["w0", "w1", "w2"],
        env_overrides={"w2": {
            "PTD_FAULTS": THROTTLE + ";elastic.peer_lost:mode=kill,after=5"
        }},
    )
    codes = launcher.wait(120)
    assert codes["w2"] == faults.KILLED_EXIT, codes
    results = launcher.results()
    ref = reference_run(ElasticConfig(
        total_steps=12, global_batch=24, microshards=12
    ))
    for wid in ("w0", "w1"):
        r = results[wid]
        assert codes[wid] == 0, codes
        assert r["final_step"] == 12
        assert r["params_crc"] == ref["params_crc"], wid
        assert [v["world_size"] for v in r["views"]] == [3, 2]
        # the view commit IS a rebalance boundary: the 2-rank world
        # re-derived a full-coverage assignment
        counts = r["assignment_counts"]
        assert len(counts) == 2 and sum(counts) == 12
        assert min(counts) >= 1


# -- the HostLoopStep half -------------------------------------------------


class TestMicrobatchPlanValidation:
    def _host(self, **kw):
        import jax.numpy as jnp

        from pytorch_distributed_tpu.train import build_train_step

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean((batch["x"] @ params["w"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        kw.setdefault("accum_steps", 4)
        return build_train_step(loss_fn, overlap_accum=True, **kw)

    def test_bounds_validated(self):
        h = self._host()
        with pytest.raises(ValueError, match="local"):
            h.set_microbatch_plan(0, 4)
        with pytest.raises(ValueError, match="local"):
            h.set_microbatch_plan(5, 4)
        with pytest.raises(ValueError, match="offset"):
            h.set_microbatch_plan(3, 8, offset=6)

    def test_accum_one_cannot_rebalance(self):
        h = self._host(accum_steps=1)
        with pytest.raises(ValueError, match="accum_steps > 1"):
            h.set_microbatch_plan(1, 2)
        h.set_microbatch_plan(1, 1)  # the solo/even restore form is fine

    def test_microbatch_schedule_refused(self):
        h = self._host(reduce_schedule="microbatch")
        with pytest.raises(ValueError, match="microbatch"):
            h.set_microbatch_plan(3, 8)

    def test_int8_compression_refused(self):
        h = self._host(grad_compression="int8")
        with pytest.raises(ValueError, match="int8"):
            h.set_microbatch_plan(3, 8)

    def test_solo_run_requires_local_equals_total(self):
        import optax

        import jax.numpy as jnp

        from pytorch_distributed_tpu.train import (
            TrainState,
            build_train_step,
        )

        def loss_fn(params, batch_stats, batch, rng):
            loss = jnp.mean((batch["x"] @ params["w"]) ** 2)
            return loss, {"metrics": {"loss": loss},
                          "batch_stats": batch_stats}

        h = build_train_step(loss_fn, accum_steps=4, overlap_accum=True)
        h.set_microbatch_plan(2, 4)
        s = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w": np.ones((4, 2), np.float32)},
            tx=optax.sgd(0.125),
        )
        batch = {"x": np.ones((8, 4), np.float32)}
        with pytest.raises(RuntimeError, match="multiprocess ring"):
            h.begin(s, batch)

    def test_restore_clears_the_plan(self):
        """``local == total == accum_steps`` is the documented restore:
        it must be IDENTICAL to never having set a plan (review catch:
        a stored restore plan on a multi-rank ring would have scaled
        the reduced gradient by world — world/total != 1/A)."""
        h = self._host()  # accum_steps=4
        h.set_microbatch_plan(3, 8)
        assert h._mb_plan == (3, 8, 0)
        h.set_microbatch_plan(4, 4)
        assert h._mb_plan is None

    def test_local_equals_total_refused_on_a_ring(self, monkeypatch):
        """A stored ``local == total`` plan (a SOLO contract — only
        reachable with local != accum_steps) on a multi-rank ring would
        mean every rank duplicates every microbatch with the gradient
        silently scaled by world: begin() must refuse, never scale."""
        import optax

        from pytorch_distributed_tpu.runtime import distributed as dist
        from pytorch_distributed_tpu.train import TrainState

        h = self._host()  # accum_steps=4
        h.set_microbatch_plan(2, 2)  # solo contract, NOT the restore
        assert h._mb_plan == (2, 2, 0)

        class _FakeRing:
            world_size = 2

        monkeypatch.setattr(
            dist, "multiprocess_ring", lambda: _FakeRing()
        )
        s = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w": np.ones((4, 2), np.float32)},
            tx=optax.sgd(0.125),
        )
        batch = {"x": np.ones((8, 4), np.float32)}
        with pytest.raises(RuntimeError, match="duplicate every"):
            h.begin(s, batch)


def test_uneven_microbatch_plan_parity_over_ring():
    world = 2
    results = hostring_workers.run_ring_workers(
        world, hostring_workers.hetero_microbatch_worker, timeout=420.0
    )
    assert results == [(r, "ok") for r in range(world)], results
