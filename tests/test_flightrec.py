"""Collective flight recorder + cross-rank hang autopsy (r19).

The contract under test has three layers:

* the recorder itself — a fixed-slot always-on ring whose hot path
  (begin/start/complete) never allocates, survives wraparound, and
  keeps an O(1) last-completed summary for deadline error messages;
* the dump discipline — ``flight-rank<r>.json`` written atomically
  (tmp + ``os.replace``) with rank/world/clock metadata, a strict
  no-op while unconfigured (error paths call :func:`flightrec.dump`
  unconditionally, and the hundreds of tier-1 tests that provoke rc
  failures on purpose must not leave files), armed by
  ``PTD_FLIGHT_DUMP`` + SIGTERM in the environment path;
* the autopsy — N dumps merged by per-group occurrence index into a
  verdict (missing_rank / mismatch / straggler / inconclusive) with a
  per-rank evidence table, refusing duplicate-rank dump sets and
  skipping torn ``.tmp`` orphans with a warning.

The 2-proc class runs a REAL hang: one rank arms ``comm.hang
:mode=skip`` (the silent-desync fault this round adds to the
registry) and vanishes from an all_reduce; the survivor must deadline,
dump, raise with the last-completed clause, and the autopsy must name
the victim. The 4-proc version lives in ``scripts/chaos_drill.py
--drill hang``; the overhead budget in bench.py's ``flightrec`` phase.
"""

import contextlib
import json
import os
import signal
import subprocess
import sys
import time
import uuid

import numpy as np
import pytest

from pytorch_distributed_tpu.runtime import faults, flightrec, tracing
from pytorch_distributed_tpu.runtime.flightrec import FlightRecorder

from tests import flight_workers
from tests.hostring_workers import run_ring_workers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")

pytestmark = pytest.mark.flight


@contextlib.contextmanager
def ptd_caplog(caplog, level="WARNING"):
    """The package's namespace logger has propagate=False; pipe it into
    caplog, which only listens on the root logger (test_lint.py idiom)."""
    ns = __import__("logging").getLogger("pytorch_distributed_tpu")
    ns.addHandler(caplog.handler)
    try:
        with caplog.at_level(level, logger="pytorch_distributed_tpu"):
            yield caplog
    finally:
        ns.removeHandler(caplog.handler)


@pytest.fixture
def fresh(monkeypatch):
    """A private recorder + disarmed dump config: the process-wide
    RECORDER accumulates records from every other test in this run, and
    configure() is sticky by design — tests must not leak either."""
    rec = FlightRecorder(64)
    monkeypatch.setattr(flightrec, "RECORDER", rec)
    monkeypatch.setattr(flightrec, "_dump_dir", None)
    monkeypatch.setattr(flightrec, "_rank", None)
    monkeypatch.setattr(flightrec, "_world", None)
    return rec


def _triple(rec, kind="all_reduce", op="sum", group="g", count=8):
    seq = rec.begin(kind, op, "float32", count, count * 8, "shm", group)
    rec.start(seq)
    rec.complete(seq)
    return seq


class TestRecorder:
    def test_state_machine_and_schema(self, fresh):
        seq = fresh.begin("all_reduce", "sum", np.dtype(np.float32),
                          128, 1024, "shm", "world")
        assert seq == 0
        assert fresh.records()[-1]["state"] == "enqueued"
        fresh.start(seq)
        assert fresh.records()[-1]["state"] == "started"
        fresh.complete(seq)
        r = fresh.records()[-1]
        assert r["state"] == "completed"
        assert r["kind"] == "all_reduce" and r["op"] == "sum"
        assert r["dtype"] == "float32"  # stringified at snapshot time
        assert r["count"] == 128 and r["wire_bytes"] == 1024
        assert r["transport"] == "shm" and r["group"] == "world"
        assert 0 < r["t0_mono_s"] <= r["t1_mono_s"]

    def test_seq_monotonic_across_kinds(self, fresh):
        seqs = [_triple(fresh, k) for k in
                ("all_reduce", "all_gather", "barrier", "send")]
        assert seqs == [0, 1, 2, 3]
        assert [r["seq"] for r in fresh.records()] == seqs

    def test_wraparound_keeps_newest(self):
        rec = FlightRecorder(8)
        for _ in range(20):
            _triple(rec)
        recs = rec.records()
        assert len(recs) == 8
        assert [r["seq"] for r in recs] == list(range(12, 20))

    def test_stale_seq_after_wrap_is_ignored(self):
        rec = FlightRecorder(4)
        old = rec.begin("all_reduce", "sum", "f32", 1, 8, "shm", "g")
        for _ in range(4):  # old's slot is reclaimed
            _triple(rec, "barrier", "")
        rec.complete(old)  # must NOT corrupt the slot's new owner
        assert all(r["kind"] == "barrier" for r in rec.records())
        assert rec.last_completed()[1] == "barrier"

    def test_last_completed_is_newest_completed(self, fresh):
        assert fresh.last_completed() is None
        _triple(fresh, "all_reduce", "sum")
        hung = fresh.begin("all_gather", "", "f32", 4, 32, "shm", "g")
        fresh.start(hung)  # started, never completed
        assert fresh.last_completed() == (0, "all_reduce", "sum")

    def test_capacity_validation(self):
        with pytest.raises(ValueError):
            FlightRecorder(0)

    def test_env_slot_override_in_subprocess(self):
        out = subprocess.run(
            [sys.executable, "-c",
             "from pytorch_distributed_tpu.runtime import flightrec; "
             "print(flightrec.RECORDER.capacity)"],
            env={**os.environ, "PTD_FLIGHT_SLOTS": "17",
                 "JAX_PLATFORMS": "cpu"},
            cwd=REPO, capture_output=True, text=True, timeout=60,
        )
        assert out.returncode == 0, out.stderr
        assert out.stdout.strip() == "17"


class TestDump:
    def test_unconfigured_is_noop(self, fresh, tmp_path):
        _triple(fresh)
        assert flightrec.dump("should go nowhere") is None
        assert list(tmp_path.iterdir()) == []

    def test_dump_schema_and_atomicity(self, fresh, tmp_path):
        flightrec.configure(out_dir=str(tmp_path), rank=2, world=4)
        _triple(fresh, "all_reduce", "sum")
        _triple(fresh, "all_gather", "")
        path = flightrec.dump("unit test")
        assert path == str(tmp_path / "flight-rank2.json")
        assert not list(tmp_path.glob("*.tmp"))  # replace(), not rename-race
        with open(path) as f:
            payload = json.load(f)
        assert payload["version"] == flightrec.DUMP_VERSION
        assert payload["rank"] == 2 and payload["world_size"] == 4
        assert payload["reason"] == "unit test"
        assert payload["wall_unix_s"] > 0 and payload["monotonic_s"] > 0
        assert isinstance(payload["meta"], dict)
        kinds = [r["kind"] for r in payload["records"]]
        assert kinds == ["all_reduce", "all_gather"]
        assert all(r["state"] == "completed" for r in payload["records"])

    def test_redump_overwrites_in_place(self, fresh, tmp_path):
        flightrec.configure(out_dir=str(tmp_path), rank=0)
        _triple(fresh)
        flightrec.dump("first")
        _triple(fresh)
        flightrec.dump("second")
        with open(tmp_path / "flight-rank0.json") as f:
            payload = json.load(f)
        assert payload["reason"] == "second"
        assert len(payload["records"]) == 2

    def test_explicit_dir_overrides_configured(self, fresh, tmp_path):
        a, b = tmp_path / "a", tmp_path / "b"
        flightrec.configure(out_dir=str(a), rank=0)
        _triple(fresh)
        path = flightrec.dump("elsewhere", out_dir=str(b))
        assert path == str(b / "flight-rank0.json")
        assert not a.exists()

    def test_rank_precedence(self, fresh, monkeypatch, tmp_path):
        # tracing meta is the weakest source...
        monkeypatch.setattr(tracing, "_meta", {"rank": 5})
        assert flightrec._resolved_rank() == 5
        # ...the env var beats it...
        monkeypatch.setenv("PTD_FLIGHT_RANK", "7")
        assert flightrec._resolved_rank() == 7
        # ...and configure() beats both (membership stamps each view)
        flightrec.configure(rank=3)
        assert flightrec._resolved_rank() == 3

    def test_dump_never_raises(self, fresh):
        flightrec.configure(out_dir="/proc/definitely/not/writable")
        _triple(fresh)
        assert flightrec.dump("doomed") is None  # logged, not raised


class TestHangFaultSite:
    def test_seconds_option_parsed_and_validated(self):
        with faults.injected("comm.hang:mode=stall,seconds=0.25"):
            assert faults.hang_action("comm.hang") == ("stall", 0.25)
        try:
            with pytest.raises(ValueError):
                faults.configure("comm.hang:mode=stall,seconds=0")
        finally:
            faults.clear()

    def test_skip_mode_and_match(self):
        with faults.injected("comm.hang:mode=skip,match=all_gather"):
            assert faults.hang_action("comm.hang", "all_reduce") is None
            act = faults.hang_action("comm.hang", "all_gather")
            assert act is not None and act[0] == "skip"

    def test_disarmed_and_foreign_modes_return_none(self):
        assert faults.hang_action("comm.hang") is None  # nothing armed
        with faults.injected("comm.hang:mode=raise"):
            # raise/kill/... belong to check(); hang_action ignores them
            assert faults.hang_action("comm.hang") is None

    def test_check_ignores_hang_modes(self):
        with faults.injected("comm.hang:mode=skip"):
            faults.check("comm.hang")  # must not raise InjectedFault
        with faults.injected("comm.hang:mode=stall,seconds=9"):
            faults.check("comm.hang")


class TestHostRingIntegration:
    """world=1 ring: the cheapest real HostRingGroup — every collective
    still runs its full record/hang plumbing."""

    def _group(self):
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup
        return HostRingGroup(f"flt_{uuid.uuid4().hex[:8]}", 0, 1,
                             slot_bytes=4096)

    def test_collectives_leave_completed_records(self, fresh):
        with self._group() as g:
            g.all_reduce(np.ones(16, np.float32))
            g.all_gather(np.ones(4, np.float32))
            g.barrier()
        kinds = [r["kind"] for r in fresh.records()]
        # group-level records present (the shm transport may add its own)
        for want in ("all_reduce", "all_gather", "barrier"):
            assert want in kinds, kinds
        assert all(r["state"] == "completed" for r in fresh.records())
        assert "all_reduce/sum" in flightrec.last_completed_desc() or \
            "barrier" in flightrec.last_completed_desc()

    def test_skip_returns_local_and_records_nothing(self, fresh):
        with self._group() as g:
            x = np.arange(8, dtype=np.float32)
            with faults.injected("comm.hang:mode=skip"):
                y = g.all_reduce(x, op="sum")
            assert y.tobytes() == x.tobytes()  # local values, no wire
            # the silent desync leaves NO record — that absence is
            # exactly the evidence the missing_rank verdict keys on
            assert fresh.records() == []

    def test_stall_delays_then_proceeds(self, fresh):
        with self._group() as g:
            x = np.ones(8, np.float32)
            t0 = time.monotonic()
            with faults.injected("comm.hang:mode=stall,seconds=0.2"):
                y = g.all_reduce(x)
            assert time.monotonic() - t0 >= 0.2
            assert y.tobytes() == x.tobytes()
            # stall is a delay, not a desync: the collective still ran
            # and recorded
            assert any(r["kind"] == "all_reduce" and
                       r["state"] == "completed"
                       for r in fresh.records())

    def test_check_failure_names_last_completed_and_dumps(
            self, fresh, tmp_path):
        from pytorch_distributed_tpu.runtime.hostring import _check
        flightrec.configure(out_dir=str(tmp_path), rank=0)
        _triple(fresh, "all_reduce", "sum")
        with pytest.raises(RuntimeError) as ei:
            _check(-110, "all_gather")
        assert "last completed flight seq=0 all_reduce/sum" in str(ei.value)
        assert (tmp_path / "flight-rank0.json").exists()

    def test_check_failure_before_any_collective(self, fresh):
        from pytorch_distributed_tpu.runtime.hostring import _check
        with pytest.raises(RuntimeError) as ei:
            _check(-5, "barrier")
        assert "no collective completed yet" in str(ei.value)


# ---------------------------------------------------------------------------
# synthetic-dump autopsy: each verdict class from hand-built evidence
# ---------------------------------------------------------------------------

def _rec(seq, kind, op="sum", count=4, state="completed", t0=1.0,
         t1=2.0, group="g"):
    return {"seq": seq, "kind": kind, "op": op, "dtype": "float32",
            "count": count, "wire_bytes": 64, "transport": "shm",
            "group": group, "state": state, "t0_mono_s": t0,
            "t1_mono_s": t1}


def _payload(rank, world, recs, off=0.0, offs=None):
    meta = {"clock_offset_s": off}
    if offs is not None:
        meta["clock_offsets_s"] = offs
    return {"version": flightrec.DUMP_VERSION, "rank": rank,
            "world_size": world, "reason": "synthetic",
            "wall_unix_s": 1000.0, "monotonic_s": 0.0, "meta": meta,
            "records": recs}


class TestAutopsyVerdicts:
    def test_mismatch_names_minority(self):
        dumps = {
            0: _payload(0, 3, [_rec(0, "all_reduce"), _rec(1, "all_reduce")]),
            1: _payload(1, 3, [_rec(0, "all_reduce"), _rec(1, "all_reduce")]),
            2: _payload(2, 3, [_rec(0, "all_reduce"),
                               _rec(1, "all_gather", op="")]),
        }
        v = flightrec.autopsy(dumps)
        assert v["verdict"] == "mismatch"
        assert v["victim_rank"] == 2
        assert v["op"] == "all_gather"
        assert "PTD001" in v["detail"]
        assert {r["rank"] for r in v["evidence"]} == {0, 1, 2}

    def test_missing_rank_stream_exhausted(self):
        dumps = {
            0: _payload(0, 2, [_rec(0, "all_reduce"),
                               _rec(1, "all_reduce", state="started",
                                    t1=0.0)]),
            1: _payload(1, 2, [_rec(0, "all_reduce")]),
        }
        v = flightrec.autopsy(dumps)
        assert v["verdict"] == "missing_rank"
        assert v["victim_rank"] == 1
        assert v["seq"] == 1 and v["op"] == "all_reduce/sum"

    def test_missing_rank_absent_dump(self):
        dumps = {
            0: _payload(0, 3, [_rec(0, "all_reduce"),
                               _rec(1, "all_reduce", state="started")]),
            1: _payload(1, 3, [_rec(0, "all_reduce"),
                               _rec(1, "all_reduce", state="started")]),
        }
        v = flightrec.autopsy(dumps)
        assert v["verdict"] == "missing_rank"
        assert v["victim_rank"] == 2
        absent = [r for r in v["evidence"] if r["state"] == "absent"]
        assert [r["rank"] for r in absent] == [2]

    def test_straggler_beyond_budget(self):
        dumps = {
            0: _payload(0, 2, [_rec(0, "all_reduce", t0=1.0, t1=9.0)]),
            1: _payload(1, 2, [_rec(0, "all_reduce", t0=6.0, t1=9.0)]),
        }
        v = flightrec.autopsy(dumps)
        assert v["verdict"] == "straggler"
        assert v["victim_rank"] == 1
        assert "budget" in v["detail"]

    def test_clock_offset_absorbs_apparent_skew(self):
        # rank 1's stamps trail by 5s — but its wall clock leads by 5s
        # (r6 calibration), so on shared wall time the starts align
        dumps = {
            0: _payload(0, 2, [_rec(0, "all_reduce", t0=1.0, t1=9.0)]),
            1: _payload(1, 2, [_rec(0, "all_reduce", t0=6.0, t1=9.0)],
                        off=5.0),
        }
        assert flightrec.autopsy(dumps)["verdict"] == "inconclusive"

    def test_divergence_beats_straggler(self):
        # a straggler-looking early round must not mask a later hard
        # divergence: the op mismatch is the verdict, skew the footnote
        dumps = {
            0: _payload(0, 2, [_rec(0, "all_reduce", t0=1.0, t1=9.0),
                               _rec(1, "all_reduce")]),
            1: _payload(1, 2, [_rec(0, "all_reduce", t0=6.0, t1=9.0),
                               _rec(1, "broadcast", op="0")]),
        }
        assert flightrec.autopsy(dumps)["verdict"] == "mismatch"

    def test_inconclusive_on_single_or_empty(self):
        assert flightrec.autopsy({})["verdict"] == "inconclusive"
        one = {0: _payload(0, 1, [_rec(0, "all_reduce")])}
        assert flightrec.autopsy(one)["verdict"] == "inconclusive"


class TestLoadDumps:
    def _write(self, tmp_path, name, payload):
        with open(tmp_path / name, "w") as f:
            if isinstance(payload, str):
                f.write(payload)
            else:
                json.dump(payload, f)

    def test_torn_tmp_skipped_with_warning(self, tmp_path, caplog):
        self._write(tmp_path, "flight-rank0.json",
                    _payload(0, 2, [_rec(0, "barrier", op="")]))
        self._write(tmp_path, "flight-rank1.json.tmp", '{"rank": 1, "tru')
        with ptd_caplog(caplog):
            dumps = flightrec.load_dumps(str(tmp_path))
        assert set(dumps) == {0}
        assert any("torn" in r.getMessage() for r in caplog.records)
        with pytest.raises(ValueError, match="torn"):
            flightrec.load_dumps(str(tmp_path), strict=True)

    def test_unparseable_json_skipped_with_warning(self, tmp_path, caplog):
        self._write(tmp_path, "flight-rank0.json",
                    _payload(0, 2, [_rec(0, "barrier", op="")]))
        self._write(tmp_path, "flight-rank1.json", "not json at all {")
        with ptd_caplog(caplog):
            dumps = flightrec.load_dumps(str(tmp_path))
        assert set(dumps) == {0}
        with pytest.raises(ValueError):
            flightrec.load_dumps(str(tmp_path), strict=True)

    def test_duplicate_rank_refused_loudly(self, tmp_path):
        p = _payload(0, 2, [_rec(0, "barrier", op="")])
        self._write(tmp_path, "flight-rank0.json", p)
        self._write(tmp_path, "flight-rank00.json", p)  # same rank claim
        with pytest.raises(ValueError, match="duplicate"):
            flightrec.load_dumps(str(tmp_path))

    def test_version_mismatch_refused(self, tmp_path, caplog):
        bad = _payload(0, 2, [])
        bad["version"] = flightrec.DUMP_VERSION + 1
        self._write(tmp_path, "flight-rank0.json", bad)
        with ptd_caplog(caplog):
            assert flightrec.load_dumps(str(tmp_path)) == {}
        with pytest.raises(ValueError):
            flightrec.load_dumps(str(tmp_path), strict=True)


class TestRealHangTwoProc:
    def test_survivor_dumps_and_autopsy_names_victim(self, tmp_path):
        """One rank silently skips an all_reduce; the survivor must
        deadline with the last-completed clause, dump, and the merged
        autopsy must indict the silent rank (which left NO dump)."""
        results = run_ring_workers(
            2, flight_workers.hang_worker,
            extra_args=(str(tmp_path), 1, "comm.hang:mode=skip"),
            timeout=120,
        )
        by_rank = dict(results)
        assert by_rank[0]["role"] == "survivor", by_rank
        assert by_rank[1]["role"] == "victim", by_rank
        err = by_rank[0]["err"]
        assert "last completed flight seq=" in err
        dumps = flightrec.load_dumps(str(tmp_path))
        assert set(dumps) == {0}  # the victim's absence is the evidence
        v = flightrec.autopsy(dumps)
        assert v["verdict"] == "missing_rank"
        assert v["victim_rank"] == 1
        assert v["seq"] is not None and v["op"] == "all_reduce/sum"
        # the survivor got through the warm-up rounds before diverging
        assert len(dumps[0]["records"]) > flight_workers.WARMUP_ROUNDS


class TestEnvArming:
    def test_sigterm_dump_via_env(self, tmp_path):
        """PTD_FLIGHT_DUMP + SIGTERM: the import-time handler must dump
        the ring before the default SIGTERM disposition kills the
        process (exactly what an elastic agent's preemption delivers)."""
        proc = subprocess.run(
            [sys.executable, "-c",
             "import sys; sys.path.insert(0, %r); "
             "from tests.flight_workers import env_dump_worker; "
             "env_dump_worker(%r)" % (REPO, str(tmp_path))],
            env={**os.environ, "PTD_FLIGHT_DUMP": str(tmp_path),
                 "PTD_FLIGHT_RANK": "5", "JAX_PLATFORMS": "cpu"},
            cwd=REPO, capture_output=True, text=True, timeout=120,
        )
        assert proc.returncode == -signal.SIGTERM, (proc.returncode,
                                                    proc.stderr)
        path = tmp_path / "flight-rank5.json"
        assert path.exists(), list(tmp_path.iterdir())
        with open(path) as f:
            payload = json.load(f)
        assert payload["rank"] == 5
        assert f"signal {int(signal.SIGTERM)}" in payload["reason"]
        assert payload["records"][-1]["state"] == "completed"


class TestCliAndReport:
    def _dump_set(self, tmp_path):
        dumps = {
            0: _payload(0, 2, [_rec(0, "all_reduce"),
                               _rec(1, "all_reduce", state="started")]),
        }
        for r, p in dumps.items():
            with open(tmp_path / f"flight-rank{r}.json", "w") as f:
                json.dump(p, f)

    def test_hang_autopsy_cli_json(self, tmp_path, capsys):
        self._dump_set(tmp_path)
        sys.path.insert(0, SCRIPTS)
        try:
            import hang_autopsy
        finally:
            sys.path.remove(SCRIPTS)
        rc = hang_autopsy.main([str(tmp_path), "--json"])
        v = json.loads(capsys.readouterr().out)
        assert rc == 0
        assert v["verdict"] == "missing_rank" and v["victim_rank"] == 1

    def test_hang_autopsy_cli_human_report(self, tmp_path, capsys):
        self._dump_set(tmp_path)
        sys.path.insert(0, SCRIPTS)
        try:
            import hang_autopsy
        finally:
            sys.path.remove(SCRIPTS)
        rc = hang_autopsy.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== Hang autopsy ==" in out
        assert "verdict: missing_rank" in out
        assert "rank" in out and "state" in out  # evidence table header

    def test_hang_autopsy_cli_empty_dir(self, tmp_path):
        sys.path.insert(0, SCRIPTS)
        try:
            import hang_autopsy
        finally:
            sys.path.remove(SCRIPTS)
        assert hang_autopsy.main([str(tmp_path)]) == 1

    def test_obs_report_renders_hang_section(self, tmp_path, capsys):
        self._dump_set(tmp_path)
        sys.path.insert(0, SCRIPTS)
        try:
            import obs_report
        finally:
            sys.path.remove(SCRIPTS)
        rc = obs_report.main([str(tmp_path)])
        out = capsys.readouterr().out
        assert rc == 0
        assert "== Hang autopsy ==" in out
        assert "missing_rank" in out
        assert "scripts/hang_autopsy.py" in out
