"""KV-cache generation (generation.py): decode must equal full recompute.

The static-cache decode path recomputes nothing; the reference
implementation here recomputes the full prefix every step. Greedy outputs
must match exactly (same ops, same dtypes), which pins prefill cache
writes, rotary/learned position offsets, and the causal mask over the
unwritten cache tail.
"""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.generation import generate, sample_logits
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.models.llama import LlamaConfig, LlamaForCausalLM
from pytorch_distributed_tpu.runtime.mesh import MeshSpec


def _naive_greedy(model, params, ids, n):
    for _ in range(n):
        logits = model.apply({"params": params}, ids)
        nxt = jnp.argmax(logits[:, -1], axis=-1).astype(ids.dtype)
        ids = jnp.concatenate([ids, nxt[:, None]], axis=1)
    return ids


@pytest.fixture
def gpt2():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    cfg = GPT2Config(
        vocab_size=97, n_positions=48, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(97, size=(2, 7)).astype(np.int32))
    params = model.init(jax.random.key(0), ids)["params"]
    return model, params, ids


def test_gpt2_greedy_matches_full_recompute(gpt2):
    # 6 tokens: every decode bug class (cache write offset, position
    # offset, tail masking) shows by token 2-3; the naive reference
    # recompiles per length, so more tokens only buy compile time
    model, params, ids = gpt2
    want = _naive_greedy(model, params, ids, 6)
    got = generate(model, params, ids, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_gpt2_unrolled_layout_decodes_too(gpt2):
    _, _, ids = gpt2
    cfg = GPT2Config(
        vocab_size=97, n_positions=48, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0, scan_layers=False,
    )
    model = GPT2LMHead(cfg)
    params = model.init(jax.random.key(0), ids)["params"]
    want = _naive_greedy(model, params, ids, 6)
    got = generate(model, params, ids, max_new_tokens=6, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_llama_greedy_matches_full_recompute():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    cfg = LlamaConfig(
        vocab_size=89, hidden_size=32, num_layers=2, num_heads=4,
        num_kv_heads=2, intermediate_size=64, max_seq_len=64,
    )
    model = LlamaForCausalLM(cfg)
    rng = np.random.default_rng(1)
    ids = jnp.asarray(rng.integers(89, size=(2, 5)).astype(np.int32))
    params = model.init(jax.random.key(0), ids)["params"]
    want = _naive_greedy(model, params, ids, 10)
    got = generate(model, params, ids, max_new_tokens=10, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_generate_inside_jit(gpt2):
    model, params, ids = gpt2

    @jax.jit
    def run(params, ids):
        return generate(model, params, ids, max_new_tokens=5, temperature=0.0)

    got = run(params, ids)
    want = _naive_greedy(model, params, ids, 5)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_eos_pads_after_stop(gpt2):
    model, params, ids = gpt2
    ref = generate(model, params, ids, max_new_tokens=8, temperature=0.0)
    eos = int(np.asarray(ref)[0, ids.shape[1] + 2])  # force an early stop
    got = np.asarray(
        generate(
            model, params, ids, max_new_tokens=8, temperature=0.0,
            eos_id=eos, pad_id=0,
        )
    )
    row = got[0, ids.shape[1]:]
    stop = list(row).index(eos)
    assert np.all(row[stop + 1:] == 0), row


@pytest.mark.slow
def test_generate_with_sharded_params(gpt2):
    """Inference under FSDP+TP sharding: same greedy tokens as replicated."""
    from pytorch_distributed_tpu.models.gpt2 import gpt2_partition_rules
    from pytorch_distributed_tpu.parallel import FSDP

    model, params, ids = gpt2
    want = generate(model, params, ids, max_new_tokens=5, temperature=0.0)

    ptd.destroy_process_group()
    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, fsdp=2, tp=2))
    strategy = FSDP(extra_rules=gpt2_partition_rules())
    from pytorch_distributed_tpu.parallel.sharding import infer_tree_shardings

    sharded = jax.device_put(
        params, infer_tree_shardings(params, strategy.param_rules())
    )
    qkv = sharded["blocks"]["block"]["attn_qkv"]["kernel"]
    assert not qkv.sharding.is_fully_replicated
    got = generate(model, sharded, ids, max_new_tokens=5, temperature=0.0)
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_sampling_respects_top_k():
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 10.0]])
    for seed in range(8):
        tok = sample_logits(
            logits, jax.random.key(seed), temperature=1.0, top_k=2
        )
        assert int(tok[0]) in (3, 4)
    greedy = sample_logits(logits, None, temperature=0.0)
    assert int(greedy[0]) == 4


def test_sampling_top_k_clamped_to_vocab():
    """top_k >= vocab_size is clamped (HF behavior) instead of raising an
    opaque out-of-bounds index at trace time (ADVICE r2)."""
    logits = jnp.asarray([[0.0, 1.0, 2.0, 3.0, 10.0]])
    tok = sample_logits(logits, jax.random.key(0), temperature=1.0, top_k=99)
    assert 0 <= int(tok[0]) < 5
    import pytest

    with pytest.raises(ValueError):
        sample_logits(logits, jax.random.key(0), temperature=1.0, top_k=0)
    with pytest.raises(ValueError):
        # validated before the greedy early-return, like top_p
        sample_logits(logits, None, temperature=0.0, top_k=0)


def test_sampling_respects_top_p():
    # softmax of [0,0,0,0,10] puts ~99.99% mass on token 4: with top_p=0.9
    # the nucleus is {4} alone, so sampling must always return 4
    logits = jnp.asarray([[0.0, 0.0, 0.0, 0.0, 10.0]])
    for seed in range(8):
        tok = sample_logits(
            logits, jax.random.key(seed), temperature=1.0, top_p=0.9
        )
        assert int(tok[0]) == 4
    # near-uniform pair dominating the rest: nucleus of mass 0.9 is {3, 4}
    logits = jnp.asarray([[0.0, 0.0, 0.0, 9.9, 10.0]])
    seen = set()
    for seed in range(16):
        tok = sample_logits(
            logits, jax.random.key(seed), temperature=1.0, top_p=0.9
        )
        seen.add(int(tok[0]))
    assert seen <= {3, 4} and len(seen) == 2, seen
    # top_p=1.0 keeps everything (smoke: no crash, valid index)
    tok = sample_logits(logits, jax.random.key(0), temperature=1.0, top_p=1.0)
    assert 0 <= int(tok[0]) < 5
    # composed k-then-p (HF order): k=3 keeps {2,3,4}, renormalized p=0.9
    # nucleus of the kept set is {3,4}
    logits = jnp.asarray([[0.0, 0.0, 1.0, 9.9, 10.0]])
    for seed in range(8):
        tok = sample_logits(
            logits, jax.random.key(seed), temperature=1.0, top_k=3, top_p=0.9
        )
        assert int(tok[0]) in (3, 4)
    # out-of-range top_p is a loud error, not silent uniform sampling —
    # on the greedy path too (where the filter would otherwise be unused)
    with pytest.raises(ValueError):
        sample_logits(logits, jax.random.key(0), temperature=1.0, top_p=0.0)
    with pytest.raises(ValueError):
        sample_logits(logits, None, temperature=0.0, top_p=0.0)


def test_generate_with_top_p(gpt2):
    model, params, ids = gpt2
    out = generate(
        model, params, ids, max_new_tokens=3, temperature=0.8, top_p=0.95,
        rng=jax.random.key(3),
    )
    assert out.shape == (2, ids.shape[1] + 3)


def test_temperature_zero_needs_no_rng(gpt2):
    model, params, ids = gpt2
    out = generate(model, params, ids, max_new_tokens=3, temperature=0.0)
    assert out.shape == (2, ids.shape[1] + 3)


def test_cache_sized_to_generation_not_model_max(gpt2):
    """decode_cache buffers must be [B, P+new, H, D], not n_positions."""
    model, params, ids = gpt2  # n_positions=48
    _, state = model.apply(
        {"params": params}, ids, decode=True, cache_len=13,
        mutable=["cache"],
    )
    ck = state["cache"]["blocks"]["block"]["cached_key"]
    assert ck.shape[2] == 13, ck.shape  # [L, B, cache_len, H, hd]


def test_cache_len_above_model_max_raises(gpt2):
    model, params, ids = gpt2
    with pytest.raises(ValueError, match="cache_len"):
        model.apply(
            {"params": params}, ids, decode=True, cache_len=64,
            mutable=["cache"],
        )


def test_overflowing_max_positions_raises(gpt2):
    model, params, ids = gpt2  # n_positions=48, prompt len 7
    with pytest.raises(ValueError, match="maximum sequence length"):
        generate(model, params, ids, max_new_tokens=42, temperature=0.0)
    with pytest.raises(ValueError, match=">= 1"):
        generate(model, params, ids, max_new_tokens=0, temperature=0.0)


@pytest.mark.parametrize(
    "family",
    # ragged-prompt parity pinned fast on gpt2; the llama variant covers
    # the same machinery through RoPE/GQA and rides the slow profile
    ["gpt2", pytest.param("llama", marks=pytest.mark.slow)],
)
@pytest.mark.slow  # r5 profile refit: speculative ragged-prompts pin stays fast
def test_left_padded_ragged_batch_matches_unpadded(family):
    """prompt_mask (HF attention_mask idiom): a left-padded ragged batch
    must produce exactly the continuations each prompt gets alone —
    positions, cache masking, and prefill-logit selection all in one."""
    import numpy as np

    if family == "gpt2":
        from pytorch_distributed_tpu.models.gpt2 import (
            GPT2Config as Cfg, GPT2LMHead as Model,
        )
    else:
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig as Cfg, LlamaForCausalLM as Model,
        )
    cfg = Cfg.tiny()
    model = Model(cfg)
    rng = np.random.default_rng(5)
    p1 = rng.integers(1, cfg.vocab_size, size=5).astype(np.int32)
    p2 = rng.integers(1, cfg.vocab_size, size=9).astype(np.int32)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 9), jnp.int32)
    )["params"]

    NEW = 6
    solo = [
        np.asarray(
            generate(
                model, params, jnp.asarray(p[None, :]),
                max_new_tokens=NEW, temperature=0.0,
            )
        )[0, len(p):]
        for p in (p1, p2)
    ]

    P = 9
    ids = np.zeros((2, P), np.int32)
    mask = np.zeros((2, P), bool)
    ids[0, P - 5:] = p1
    mask[0, P - 5:] = True
    ids[1, :] = p2
    mask[1, :] = True
    out = np.asarray(
        generate(
            model, params, jnp.asarray(ids), max_new_tokens=NEW,
            temperature=0.0, prompt_mask=jnp.asarray(mask),
        )
    )
    np.testing.assert_array_equal(out[0, P:], solo[0])
    np.testing.assert_array_equal(out[1, P:], solo[1])


def _naive_beam(model, params, ids_row, n, K, eos_id=None, pad_id=0,
                length_penalty=1.0):
    """Exact reference beam search by full recompute (one batch row)."""
    beams = [(0.0, list(int(x) for x in ids_row), False)]
    P = len(beams[0][1])
    for _ in range(n):
        cand = []
        for score, seq, fin in beams:
            if fin:
                cand.append((score, seq + [pad_id], True))
                continue
            logits = model.apply(
                {"params": params}, jnp.asarray([seq], jnp.int32)
            )
            logp = np.asarray(
                jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
            )
            for v in np.argsort(logp)[::-1][:K]:
                cand.append((
                    score + float(logp[v]), seq + [int(v)],
                    eos_id is not None and int(v) == eos_id,
                ))
        cand.sort(key=lambda c: c[0], reverse=True)
        beams = cand[:K]
    def final(c):
        score, seq, fin = c
        gen = seq[P:]
        if eos_id is not None and eos_id in gen:
            L = gen.index(eos_id) + 1
        else:
            L = n
        return score / (L ** length_penalty)
    return max(beams, key=final)[1]


@pytest.mark.slow
@pytest.mark.parametrize("eos", [None, "auto"])
def test_beam_search_matches_naive_reference(gpt2, eos):
    from pytorch_distributed_tpu.generation import generate_beam

    model, params, ids = gpt2
    eos_id = None
    if eos == "auto":
        # pick a token the greedy path emits so finishing logic engages
        ref = generate(model, params, ids, max_new_tokens=4, temperature=0.0)
        eos_id = int(np.asarray(ref)[0, ids.shape[1] + 1])
    got = np.asarray(
        generate_beam(
            model, params, ids, max_new_tokens=5, num_beams=3,
            eos_id=eos_id,
        )
    )
    for b in range(ids.shape[0]):
        want = _naive_beam(
            model, params, np.asarray(ids)[b], 5, 3, eos_id=eos_id
        )
        np.testing.assert_array_equal(got[b], np.asarray(want), err_msg=f"row {b}")


@pytest.mark.slow
def test_beam_scores_are_self_consistent(gpt2):
    """The returned score must equal the recomputed (length-penalized)
    log-probability of the returned sequence — a property beam search DOES
    guarantee (unlike beating greedy, which pruning can legitimately
    lose)."""
    from pytorch_distributed_tpu.generation import generate_beam

    model, params, ids = gpt2

    def seq_logprob(seq):
        total = 0.0
        P = ids.shape[1]
        for t in range(P, seq.shape[0]):
            logits = model.apply(
                {"params": params}, jnp.asarray([seq[:t]], jnp.int32)
            )
            logp = jax.nn.log_softmax(logits[0, -1].astype(jnp.float32))
            total += float(logp[int(seq[t])])
        return total

    NEW = 4
    beams, scores = generate_beam(
        model, params, ids, max_new_tokens=NEW, num_beams=4,
        return_scores=True,
    )
    beams, scores = np.asarray(beams), np.asarray(scores)
    for b in range(ids.shape[0]):
        np.testing.assert_allclose(
            scores[b], seq_logprob(beams[b]) / NEW, rtol=1e-4,
        )


@pytest.mark.slow
def test_ragged_batch_with_repetition_penalty_matches_solo(gpt2):
    """prompt_mask + repetition_penalty compose: the left-padded batch
    still equals each prompt generated alone (pads are NOT counted as
    'seen' tokens — the invariant documented in generate())."""
    model, params, _ = gpt2
    rng = np.random.default_rng(11)
    p1 = rng.integers(1, 97, size=4).astype(np.int32)
    p2 = rng.integers(1, 97, size=7).astype(np.int32)
    NEW = 6
    solo = [
        np.asarray(
            generate(
                model, params, jnp.asarray(p[None, :]),
                max_new_tokens=NEW, temperature=0.0,
                repetition_penalty=1.6,
            )
        )[0, len(p):]
        for p in (p1, p2)
    ]
    P = 7
    ids = np.zeros((2, P), np.int32)
    mask = np.zeros((2, P), bool)
    ids[0, P - 4:] = p1
    mask[0, P - 4:] = True
    ids[1] = p2
    mask[1] = True
    out = np.asarray(
        generate(
            model, params, jnp.asarray(ids), max_new_tokens=NEW,
            temperature=0.0, prompt_mask=jnp.asarray(mask),
            repetition_penalty=1.6,
        )
    )
    np.testing.assert_array_equal(out[0, P:], solo[0])
    np.testing.assert_array_equal(out[1, P:], solo[1])


@pytest.mark.slow  # r5 profile refit: interop no-repeat-ngram HF token pin stays fast
def test_ngram_oversized_is_noop_and_ragged_composes(gpt2):
    """n > sequence length is a harmless no-op (HF behavior), and
    prompt_mask + no_repeat_ngram keeps ragged rows equal to solo runs
    (pads excluded from grams)."""
    model, params, ids = gpt2
    plain = np.asarray(
        generate(model, params, ids, max_new_tokens=4, temperature=0.0)
    )
    noop = np.asarray(
        generate(
            model, params, ids, max_new_tokens=4, temperature=0.0,
            no_repeat_ngram_size=99,
        )
    )
    np.testing.assert_array_equal(noop, plain)

    rng = np.random.default_rng(13)
    p1 = rng.integers(1, 97, size=4).astype(np.int32)
    NEW = 6
    solo = np.asarray(
        generate(
            model, params, jnp.asarray(p1[None]), max_new_tokens=NEW,
            temperature=0.0, no_repeat_ngram_size=2,
        )
    )[0, 4:]
    P = 7
    padded = np.zeros((1, P), np.int32)
    mask = np.zeros((1, P), bool)
    padded[0, P - 4:] = p1
    mask[0, P - 4:] = True
    out = np.asarray(
        generate(
            model, params, jnp.asarray(padded), max_new_tokens=NEW,
            temperature=0.0, prompt_mask=jnp.asarray(mask),
            no_repeat_ngram_size=2,
        )
    )
    np.testing.assert_array_equal(out[0, P:], solo)


def test_generate_with_tp_sharded_params():
    """Serving at scale: params TP-sharded by the model's partition
    rules decode through the SAME generate call, token-identically —
    GSPMD shards the per-token attention/MLP over the tp axis (this is
    how an 8B serves across a slice; no special decode path exists or
    is needed)."""
    import optax

    from pytorch_distributed_tpu.models.gpt2 import gpt2_partition_rules
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, tp=4))
    cfg = GPT2Config(
        vocab_size=128, n_positions=64, hidden_size=32, num_layers=2,
        num_heads=4, dropout_rate=0.0,
    )
    model = GPT2LMHead(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(128, size=(2, 6)).astype(np.int32)
    )
    params = model.init(jax.random.key(0), ids)["params"]
    want = generate(model, params, ids, max_new_tokens=8, temperature=0.0)
    strategy = DataParallel(extra_rules=gpt2_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    qkv = state.params["blocks"]["block"]["attn_qkv"]["kernel"]
    assert "tp" in str(qkv.sharding.spec)  # heads really shard
    got = generate(
        model, state.params, ids, max_new_tokens=8, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


def test_prompt_mask_rejects_all_pad_row(gpt2):
    # an all-False row would clamp to prompt_lens=1 and decode from a
    # fully-masked attention row (NaN softmax) — refused upfront, in the
    # shared helper both generate and generate_speculative use
    model, params, ids = gpt2
    bad = jnp.asarray([[True] * 7, [False] * 7])
    with pytest.raises(ValueError, match="no real tokens"):
        generate(model, params, ids, max_new_tokens=3, temperature=0.0,
                 prompt_mask=bad)


def test_ragged_prompt_state_edge_cases():
    """The serve engine's chunked prefill leans on these edges: the
    full-length prompt (every slot real), the zero-decode-tail cache
    (cache_len == P), and the refusals that keep garbage out."""
    from pytorch_distributed_tpu.generation import ragged_prompt_state

    B, P = 2, 4
    full = jnp.ones((B, P), jnp.bool_)
    # full-length prompt: positions count 0..P-1, every slot valid
    _, pos, lens, kv = ragged_prompt_state(full, B, P, P + 2)
    assert np.asarray(pos).tolist() == [list(range(P))] * B
    assert np.asarray(lens).tolist() == [P, P]
    assert np.asarray(kv).all() and kv.shape == (B, P + 2)
    # cache_len == P: the zero-width decode-tail concat stays valid
    _, pos, lens, kv = ragged_prompt_state(full, B, P, P)
    assert kv.shape == (B, P) and np.asarray(kv).all()
    # ragged row: pads share position 0 and are masked out of the cache
    m = jnp.asarray([[False, True, True, True], [True] * 4])
    _, pos, lens, kv = ragged_prompt_state(m, B, P, P + 1)
    assert np.asarray(lens).tolist() == [3, 4]
    assert np.asarray(pos)[0].tolist() == [0, 0, 1, 2]
    assert np.asarray(kv)[0].tolist() == [False, True, True, True, True]
    # an all-pad row would decode from a fully masked attention row
    bad = jnp.asarray([[False] * 4, [True] * 4])
    with pytest.raises(ValueError, match="no real tokens"):
        ragged_prompt_state(bad, B, P, P + 1)
    # right padding would sample from a pad-slot query
    rp = jnp.asarray([[True, True, False, False], [True] * 4])
    with pytest.raises(ValueError, match="LEFT-padded"):
        ragged_prompt_state(rp, B, P, P + 1)
    with pytest.raises(ValueError, match="prompt_mask must be"):
        ragged_prompt_state(jnp.ones((B, P + 1), jnp.bool_), B, P, P + 2)
