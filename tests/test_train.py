"""Trainer stack tests: step builder (accum, fp16), checkpoint, Trainer loop."""

import jax
import jax.numpy as jnp
import tempfile

import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import ArrayDataset, DataLoader, SyntheticImageDataset
from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
from pytorch_distributed_tpu.parallel import DataParallel, FSDP
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import (
    Trainer,
    TrainerConfig,
    TrainState,
    build_train_step,
    checkpoint_step,
    classification_eval_step,
    classification_loss_fn,
    restore_checkpoint,
    save_checkpoint,
)


def linear_loss_fn(params, batch_stats, batch, rng):
    loss = jnp.mean((batch["x"] @ params["w"] - batch["y"]) ** 2)
    return loss, {"metrics": {"loss": loss}, "batch_stats": batch_stats}


def linear_state(lr=0.1):
    return TrainState.create(
        apply_fn=lambda p, x: x @ p["w"],
        params={"w": jnp.ones((4, 2))},
        tx=optax.sgd(lr),
    )


def linear_batch(n=32):
    rng = np.random.default_rng(0)
    return {
        "x": rng.normal(size=(n, 4)).astype(np.float32),
        "y": rng.normal(size=(n, 2)).astype(np.float32),
    }


@pytest.fixture
def dp8():
    make_mesh(MeshSpec(dp=8))
    return DataParallel()


class TestBuildTrainStep:
    def test_accum_equals_full_batch(self, dp8):
        batch = linear_batch()
        s1, s4 = dp8.place(linear_state()), dp8.place(linear_state())
        step1 = dp8.compile(build_train_step(linear_loss_fn), s1)
        step4 = dp8.compile(build_train_step(linear_loss_fn, accum_steps=4), s4)
        n1, m1 = step1(s1, dp8.shard_batch(batch))
        n4, m4 = step4(s4, dp8.shard_batch(batch))
        assert m1["loss"] == pytest.approx(float(m4["loss"]), rel=1e-5)
        np.testing.assert_allclose(
            np.asarray(n1.params["w"]), np.asarray(n4.params["w"]), rtol=1e-5
        )

    def test_accum_indivisible_raises(self, dp8):
        state = dp8.place(linear_state())
        step = build_train_step(linear_loss_fn, accum_steps=3)
        with pytest.raises(ValueError, match="not divisible"):
            dp8.compile(step, state)(state, dp8.shard_batch(linear_batch(32)))

    def test_fp16_scaler_scale_and_skip(self, dp8):
        scaler = ptd.GradScaler(
            dtype=jnp.float16, init_scale=8.0, growth_interval=1
        )
        state = dp8.place(linear_state().replace(scaler_state=scaler.init_state()))
        step = dp8.compile(build_train_step(linear_loss_fn, scaler=scaler), state)
        batch = linear_batch()
        state, m = step(state, dp8.shard_batch(batch))
        assert float(m["grads_finite"]) == 1.0
        assert float(m["loss_scale"]) == 16.0  # grew
        w_before = np.asarray(state.params["w"])
        step_before = int(state.step)
        bad = {"x": np.full((32, 4), np.inf, np.float32), "y": batch["y"]}
        state, m = step(state, dp8.shard_batch(bad))
        assert float(m["grads_finite"]) == 0.0
        assert float(m["loss_scale"]) == 8.0  # backoff
        np.testing.assert_array_equal(np.asarray(state.params["w"]), w_before)
        assert int(state.step) == step_before + 1  # iteration still counts

    def test_step_metrics_present(self, dp8):
        state = dp8.place(linear_state())
        step = dp8.compile(build_train_step(linear_loss_fn), state)
        _, m = step(state, dp8.shard_batch(linear_batch()))
        assert "loss" in m


def tiny_resnet():
    return ResNet(
        stage_sizes=[1, 1], block_cls=BasicBlock, num_classes=10, width=8,
        stem="cifar",
    )


def tiny_image_state(model, seed=0, ema=False):
    v = model.init(
        jax.random.key(seed), jnp.zeros((1, 16, 16, 3)), train=False
    )
    return TrainState.create(
        apply_fn=model.apply,
        params=v["params"],
        tx=optax.sgd(0.1, momentum=0.9),
        batch_stats=v["batch_stats"],
        ema=ema,
    )


class TestTrainerLoop:
    @pytest.mark.slow
    def test_fit_reduces_loss_and_updates_bn(self, dp8):
        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=64, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 32, sharding=dp8.batch_sharding())
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            loader,
            config=TrainerConfig(epochs=2, log_every=0),
        )
        bn_before = np.asarray(
            jax.tree_util.tree_leaves(trainer.state.batch_stats)[0]
        ).copy()
        out = trainer.fit()
        assert int(out.step) == 4
        bn_after = np.asarray(jax.tree_util.tree_leaves(out.batch_stats)[0])
        assert not np.array_equal(bn_before, bn_after)  # stats really update

    @pytest.mark.slow
    def test_log_mfu_measures_step_flops(self, dp8):
        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 16, sharding=dp8.batch_sharding())
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            loader,
            config=TrainerConfig(epochs=1, log_every=1, log_mfu=True),
        )
        trainer.fit()
        # XLA's cost analysis priced the step; a tiny CNN fwd+bwd on a
        # 16-sample batch is at least a few MFLOPs
        assert trainer._step_flops and trainer._step_flops > 1e6

    @pytest.mark.slow
    def test_evaluate_runs(self, dp8):
        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=1)
        loader = DataLoader(ds, 16, shuffle=False, sharding=dp8.batch_sharding())
        trainer = Trainer(
            state, dp8, build_train_step(classification_loss_fn(model)), loader,
            eval_step=classification_eval_step(model), eval_loader=loader,
            config=TrainerConfig(epochs=1, log_every=0),
        )
        metrics = trainer.evaluate(0)
        assert 0.0 <= metrics["accuracy"] <= 1.0


class TestCheckpoint:
    def test_roundtrip_preserves_state(self, dp8, tmp_path):
        state = dp8.place(linear_state())
        step = dp8.compile(build_train_step(linear_loss_fn), state)
        state, _ = step(state, dp8.shard_batch(linear_batch()))
        path = save_checkpoint(str(tmp_path), state)
        assert checkpoint_step(str(tmp_path)) == 1
        restored = restore_checkpoint(
            str(tmp_path), linear_state(), dp8.state_shardings(linear_state())
        )
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), np.asarray(state.params["w"])
        )
        assert int(restored.step) == 1

    def test_restore_across_strategies(self, tmp_path):
        # save under DP, restore under FSDP: the sharded-checkpoint property
        mesh = make_mesh(MeshSpec(dp=8))
        dp = DataParallel(mesh)
        state = dp.place(linear_state())
        save_checkpoint(str(tmp_path), state)
        mesh2 = make_mesh(MeshSpec(dp=4, fsdp=2))
        fsdp = FSDP(mesh2)
        restored = restore_checkpoint(
            str(tmp_path), linear_state(), fsdp.state_shardings(linear_state())
        )
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), np.asarray(state.params["w"])
        )

    def test_missing_leaf_raises_strict(self, dp8, tmp_path):
        save_checkpoint(str(tmp_path), dp8.place(linear_state()))
        other = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w": jnp.ones((4, 2)), "b": jnp.zeros((2,))},
            tx=optax.sgd(0.1),
        )
        with pytest.raises(ValueError, match="not found in checkpoint"):
            restore_checkpoint(str(tmp_path), other)

    def test_missing_leaf_kept_nonstrict(self, dp8, tmp_path):
        saved = dp8.place(linear_state())
        save_checkpoint(str(tmp_path), saved)
        other = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w": jnp.zeros((4, 2)), "b": jnp.full((2,), 7.0)},
            tx=optax.sgd(0.1),
        )
        restored = restore_checkpoint(str(tmp_path), other, strict=False)
        # present path loads from the checkpoint...
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), np.asarray(saved.params["w"])
        )
        # ...absent path keeps the template value (new optimizer field case)
        np.testing.assert_allclose(np.asarray(restored.params["b"]), 7.0)

    def test_shape_mismatch_raises(self, dp8, tmp_path):
        save_checkpoint(str(tmp_path), dp8.place(linear_state()))
        other = TrainState.create(
            apply_fn=lambda p, x: x, params={"w": jnp.ones((5, 2))}, tx=optax.sgd(0.1)
        )
        with pytest.raises(ValueError, match="shape"):
            restore_checkpoint(str(tmp_path), other)

    def test_path_rename_detected(self, dp8, tmp_path):
        save_checkpoint(str(tmp_path), dp8.place(linear_state()))
        renamed = TrainState.create(
            apply_fn=lambda p, x: x,
            params={"w2": jnp.ones((4, 2))},  # same shape, different name
            tx=optax.sgd(0.1),
        )
        with pytest.raises(ValueError, match="not found in checkpoint"):
            restore_checkpoint(str(tmp_path), renamed)

    def test_fsdp_save_writes_shard_files_no_gather(self, tmp_path):
        """The pod-scale property: an FSDP-sharded leaf is written as one
        file per shard (each 1/N of the array), never a gathered whole."""
        import json
        import os

        mesh = make_mesh(MeshSpec(fsdp=8))
        fsdp = FSDP(mesh)
        state = fsdp.place(
            TrainState.create(
                apply_fn=lambda p, x: x,
                params={"w": jnp.ones((64, 16))},
                tx=optax.sgd(0.1),
            )
        )
        save_checkpoint(str(tmp_path), state)
        with open(os.path.join(str(tmp_path), "latest", "manifest.json")) as f:
            manifest = json.load(f)
        entry = {e["path"]: e for e in manifest["leaves"]}["params_w"]
        assert len(entry["shards"]) == 8  # one file per fsdp shard
        sizes = [
            tuple(b - a for a, b in zip(s["start"], s["stop"]))
            for s in entry["shards"]
        ]
        assert all(sz == (8, 16) for sz in sizes), sizes  # 1/8 each
        restored = restore_checkpoint(
            str(tmp_path),
            TrainState.create(
                apply_fn=lambda p, x: x,
                params={"w": jnp.zeros((64, 16))},
                tx=optax.sgd(0.1),
            ),
        )
        np.testing.assert_allclose(np.asarray(restored.params["w"]), 1.0)

    def test_fsdp_to_dp_and_back(self, tmp_path):
        """FSDP save -> DP restore and DP save -> FSDP restore, values
        bit-identical both ways (VERDICT r1 #7)."""
        rng = np.random.default_rng(5)
        w = rng.normal(size=(64, 16)).astype(np.float32)

        def mk_state():
            return TrainState.create(
                apply_fn=lambda p, x: x, params={"w": jnp.asarray(w)},
                tx=optax.adam(1e-3),
            )

        mesh_f = make_mesh(MeshSpec(dp=2, fsdp=4))
        fsdp = FSDP(mesh_f)
        state_f = fsdp.place(mk_state())
        save_checkpoint(str(tmp_path / "a"), state_f)

        mesh_d = make_mesh(MeshSpec(dp=8))
        dp = DataParallel(mesh_d)
        restored_d = restore_checkpoint(
            str(tmp_path / "a"), mk_state(), dp.state_shardings(mk_state())
        )
        np.testing.assert_array_equal(np.asarray(restored_d.params["w"]), w)

        save_checkpoint(str(tmp_path / "b"), restored_d)
        restored_f = restore_checkpoint(
            str(tmp_path / "b"), mk_state(), fsdp.state_shardings(mk_state())
        )
        np.testing.assert_array_equal(np.asarray(restored_f.params["w"]), w)

    @pytest.mark.slow
    def test_gigabyte_state_saves_in_seconds(self, tmp_path):
        """~1 GB FSDP state: sharded parallel save + sharded restore must
        be IO-bound seconds, not gather-bound minutes (VERDICT r1 #7)."""
        import time

        mesh = make_mesh(MeshSpec(fsdp=8))
        fsdp = FSDP(mesh)
        # 8 x 32M f32 = 1.0 GB across 8 leaves
        params = {
            f"w{i}": jnp.ones((4096, 8192), jnp.float32) for i in range(8)
        }
        state = fsdp.place(
            TrainState.create(
                apply_fn=lambda p, x: x, params=params, tx=optax.sgd(0.1)
            )
        )
        t0 = time.perf_counter()
        save_checkpoint(str(tmp_path), state)
        save_s = time.perf_counter() - t0
        template = TrainState.create(
            apply_fn=lambda p, x: x,
            params={
                f"w{i}": jnp.zeros((4096, 8192), jnp.float32)
                for i in range(8)
            },
            tx=optax.sgd(0.1),
        )
        t0 = time.perf_counter()
        restored = restore_checkpoint(
            str(tmp_path), template, fsdp.state_shardings(template)
        )
        jax.block_until_ready(restored.params)
        restore_s = time.perf_counter() - t0
        assert float(restored.params["w3"][0, 0]) == 1.0
        assert save_s < 60 and restore_s < 60, (save_s, restore_s)

    def test_async_checkpointer(self, dp8, tmp_path):
        from pytorch_distributed_tpu.train.checkpoint import AsyncCheckpointer

        state = dp8.place(linear_state())
        ck = AsyncCheckpointer()
        ck.save(str(tmp_path), state)
        ck.wait()
        assert checkpoint_step(str(tmp_path)) == 0
        restored = restore_checkpoint(str(tmp_path), linear_state())
        np.testing.assert_allclose(
            np.asarray(restored.params["w"]), np.asarray(state.params["w"])
        )

    def test_old_checkpoint_survives_overwrite(self, dp8, tmp_path):
        import os

        state = dp8.place(linear_state())
        save_checkpoint(str(tmp_path), state)
        save_checkpoint(str(tmp_path), state)  # second save replaces first
        assert checkpoint_step(str(tmp_path)) == 0
        assert not os.path.exists(os.path.join(str(tmp_path), "latest.old"))

    @pytest.mark.slow
    def test_mid_epoch_resume_skips_consumed_batches(self, dp8, tmp_path):
        # manufacture a preemption: checkpoint at step 3 of a 4-step epoch
        model = tiny_resnet()
        state = dp8.place(tiny_image_state(model))
        step = dp8.compile(
            build_train_step(classification_loss_fn(model)), state
        )
        ds = SyntheticImageDataset(n=128, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 32, sharding=dp8.batch_sharding())
        loader.set_epoch(0)
        for i, batch in enumerate(loader):
            if i == 3:
                break
            state, _ = step(state, batch)
        assert int(state.step) == 3
        save_checkpoint(str(tmp_path), state)

        t2 = Trainer(
            tiny_image_state(model), dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(ds, 32, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=0, ckpt_dir=str(tmp_path)
            ),
        )
        assert t2.restore_checkpoint()
        assert t2._resume_skip_batches == 3
        out = t2.fit()
        # finishes the epoch with exactly 1 more step: 4 total, not 3+4
        assert int(out.step) == 4

    def test_trainer_resume(self, dp8, tmp_path):
        def make_trainer():
            model = tiny_resnet()
            state = tiny_image_state(model)
            ds = SyntheticImageDataset(n=64, image_shape=(16, 16, 3), seed=0)
            loader = DataLoader(ds, 32, sharding=dp8.batch_sharding())
            return Trainer(
                state, dp8, build_train_step(classification_loss_fn(model)),
                loader,
                config=TrainerConfig(
                    epochs=2, log_every=0, ckpt_dir=str(tmp_path)
                ),
            )

        t1 = make_trainer()
        t1.fit()  # 2 epochs x 2 steps
        assert checkpoint_step(str(tmp_path)) == 4

        t2 = make_trainer()
        assert t2.restore_checkpoint()
        assert int(t2.state.step) == 4
        out = t2.fit()  # resumed at epoch 2 == done; no extra steps
        assert int(out.step) == 4


class TestModelEMA:
    def _fit(self, dp8, decay, tmp_path=None, **cfg_kw):
        model = tiny_resnet()
        state = tiny_image_state(model, ema=True)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        trainer = Trainer(
            state,
            dp8,
            build_train_step(
                classification_loss_fn(model), ema_decay=decay
            ),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            eval_step=classification_eval_step(model),
            eval_loader=DataLoader(
                ds, 16, shuffle=False, sharding=dp8.batch_sharding()
            ),
            config=TrainerConfig(
                epochs=1, log_every=0, handle_preemption=False, **cfg_kw
            ),
        )
        trainer.fit()
        return trainer

    def test_ema_edge_decays(self, dp8):
        import jax

        # decay=0: shadow tracks params exactly
        tr = self._fit(dp8, 0.0)
        for (pa, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(tr.state.ema_params),
            jax.tree_util.tree_leaves_with_path(tr.state.params),
        ):
            np.testing.assert_array_equal(
                np.asarray(a), np.asarray(b), err_msg=str(pa)
            )
        # d=1 would freeze the shadow at init (silent garbage evals) and
        # d>1 diverges — both rejected at build time
        for bad in (1.0, 1.5, -0.1):
            with pytest.raises(ValueError, match="ema_decay"):
                build_train_step(
                    classification_loss_fn(tiny_resnet()), ema_decay=bad
                )

    def test_eval_with_ema_and_guards(self, dp8):
        tr = self._fit(dp8, 0.9, eval_with_ema=True)
        assert tr.last_eval_metrics  # evaluated the shadow without error
        # missing shadow params fail loudly at both entry points
        model = tiny_resnet()
        state = tiny_image_state(model)  # no ema
        with pytest.raises(ValueError, match="ema"):
            step = jax.jit(
                build_train_step(
                    classification_loss_fn(model), ema_decay=0.9
                )
            )
            ds = SyntheticImageDataset(n=16, image_shape=(16, 16, 3))
            batch = next(iter(DataLoader(ds, 16)))
            step(state, batch)

    def test_eval_with_ema_requires_ema_step(self, dp8):
        """A builder step without ema_decay + eval_with_ema would silently
        evaluate the frozen init shadow — rejected at construction."""
        model = tiny_resnet()
        with pytest.raises(ValueError, match="ema_decay"):
            Trainer(
                tiny_image_state(model, ema=True),
                dp8,
                build_train_step(classification_loss_fn(model)),
                DataLoader(
                    SyntheticImageDataset(n=16, image_shape=(16, 16, 3)),
                    16, sharding=dp8.batch_sharding(),
                ),
                config=TrainerConfig(eval_with_ema=True),
            )

    def test_pre_ema_checkpoint_reseeds_shadow(self, dp8, tmp_path):
        """Restoring a checkpoint written WITHOUT ema into an EMA-enabled
        trainer reseeds the shadow from the restored params."""
        model = tiny_resnet()
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        pre = Trainer(
            tiny_image_state(model),
            dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=0, ckpt_dir=str(tmp_path),
                handle_preemption=False,
            ),
        )
        pre.fit()
        post = Trainer(
            tiny_image_state(model, ema=True),
            dp8,
            build_train_step(
                classification_loss_fn(model), ema_decay=0.9
            ),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=2, log_every=0, ckpt_dir=str(tmp_path),
                handle_preemption=False,
            ),
        )
        assert post.restore_checkpoint()
        for (path, e), (_, p) in zip(
            jax.tree_util.tree_leaves_with_path(post.state.ema_params),
            jax.tree_util.tree_leaves_with_path(post.state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(e), np.asarray(p, dtype=np.float32),
                rtol=1e-6, err_msg=str(path),
            )

    def test_ema_shards_like_params_under_fsdp(self):
        from pytorch_distributed_tpu.parallel import FSDP
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh

        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        model = tiny_resnet()
        state = tiny_image_state(model, ema=True)
        strategy = FSDP(mesh)
        placed = strategy.place(state)
        import jax

        for (path, p), (_, e) in zip(
            jax.tree_util.tree_leaves_with_path(placed.params),
            jax.tree_util.tree_leaves_with_path(placed.ema_params),
        ):
            assert p.sharding == e.sharding, (path, p.sharding, e.sharding)


class TestMaxStepsPerEpoch:
    class Stream:
        """Endless deterministic sample stream."""

        def __iter__(self):
            rng = np.random.default_rng(0)
            i = 0
            while True:
                yield {
                    "image": rng.normal(size=(16, 16, 3)).astype(
                        np.float32
                    ),
                    "label": np.int32(i % 4),
                }
                i += 1

    def _trainer(self, dp8, tmp_path=None, epochs=2):
        model = tiny_resnet()
        return Trainer(
            tiny_image_state(model),
            dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(self.Stream(), 16, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=epochs, log_every=0, max_steps_per_epoch=3,
                handle_preemption=False,
                ckpt_dir=str(tmp_path) if tmp_path else None,
            ),
        )

    def test_endless_stream_bounded_epochs(self, dp8):
        tr = self._trainer(dp8)
        tr.fit()  # must RETURN (3 steps x 2 epochs), not spin forever
        assert tr.host_step == 6

    @pytest.mark.slow  # demoted on this rig: reproducibly triggers the
    # XLA:CPU accumulated-jit-state abort when the FULL fast suite runs
    # in one process (passes solo and in run_full_suite.sh batches,
    # where it keeps running). Fast siblings:
    # test_endless_stream_bounded_epochs covers the stream epoch loop;
    # TestResume/test_* cover checkpoint-resume position math.
    def test_resume_position_reconstructed(self, dp8, tmp_path):
        tr = self._trainer(dp8, tmp_path, epochs=1)
        tr.fit()  # saves at epoch end, step 3
        tr2 = self._trainer(dp8, tmp_path, epochs=2)
        assert tr2.restore_checkpoint()
        assert tr2._first_epoch == 1 and tr2._resume_skip_batches == 0
        tr2.fit()
        assert tr2.host_step == 6


def _scalar_of(v):
    """TB 2.x writers migrate simple_value scalars to rank-0 tensors."""
    if v.HasField("simple_value"):
        return v.simple_value
    return v.tensor.float_val[0]


class TestMetricsWriter:
    def test_jsonl_train_and_eval_records(self, dp8, tmp_path):
        from pytorch_distributed_tpu.train.metrics import read_metrics

        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        path = str(tmp_path / "m" / "metrics.jsonl")
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            eval_step=classification_eval_step(model),
            eval_loader=DataLoader(
                ds, 16, shuffle=False, sharding=dp8.batch_sharding()
            ),
            config=TrainerConfig(
                epochs=1, log_every=1, metrics_path=path,
                handle_preemption=False,
            ),
        )
        trainer.fit()
        recs = read_metrics(path)
        train = [r for r in recs if r["split"] == "train"]
        evals = [r for r in recs if r["split"] == "eval"]
        assert len(train) == 2 and len(evals) == 1
        assert {"step", "wall_time", "loss"} <= set(train[0])
        assert "accuracy" in evals[0]
        # append across a second fit (restart durability)
        trainer2 = Trainer(
            trainer.state.replace(step=trainer.state.step),
            dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=1, metrics_path=path,
                handle_preemption=False,
            ),
        )
        trainer2.fit()
        assert len(read_metrics(path)) > len(recs)

    def test_tensorboard_events_written_and_teed(self, dp8, tmp_path):
        """TrainerConfig(tensorboard_dir=...) writes real TensorBoard event
        files (readable by tensorboard's own loader) alongside the JSONL."""
        import glob

        pytest.importorskip("tensorboard")
        from tensorboard.backend.event_processing.event_file_loader import (
            EventFileLoader,
        )

        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        jsonl = str(tmp_path / "metrics.jsonl")
        tb_dir = str(tmp_path / "tb")
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            DataLoader(ds, 16, sharding=dp8.batch_sharding()),
            eval_step=classification_eval_step(model),
            eval_loader=DataLoader(
                ds, 16, shuffle=False, sharding=dp8.batch_sharding()
            ),
            config=TrainerConfig(
                epochs=1, log_every=1, metrics_path=jsonl,
                tensorboard_dir=tb_dir, handle_preemption=False,
            ),
        )
        trainer.fit()
        files = glob.glob(tb_dir + "/events.out.tfevents.*")
        assert files, "no event file written"
        tags = {}
        for ev in EventFileLoader(files[0]).Load():
            for v in ev.summary.value:
                tags.setdefault(v.tag, []).append((ev.step, _scalar_of(v)))
        assert "train/loss" in tags and "eval/accuracy" in tags, tags.keys()
        assert len(tags["train/loss"]) == 2  # 2 logged steps
        # the tee kept the JSONL stream intact too
        from pytorch_distributed_tpu.train.metrics import read_metrics

        assert any(r["split"] == "train" for r in read_metrics(jsonl))

    def test_summary_writer_torch_shape(self, tmp_path):
        import glob

        pytest.importorskip("tensorboard")
        from tensorboard.backend.event_processing.event_file_loader import (
            EventFileLoader,
        )

        from pytorch_distributed_tpu.utils.tensorboard import SummaryWriter

        w = SummaryWriter(str(tmp_path))
        w.add_scalar("lr", 0.1, global_step=3)
        w.add_scalars("ab", {"a": 1.0, "b": 2.0}, global_step=4)
        w.close()
        files = glob.glob(str(tmp_path) + "/events.out.tfevents.*")
        assert files
        got = {}
        for ev in EventFileLoader(files[0]).Load():
            for v in ev.summary.value:
                got[v.tag] = (ev.step, round(_scalar_of(v), 4))
        assert got["lr"] == (3, 0.1)
        assert got["ab/a"] == (4, 1.0) and got["ab/b"] == (4, 2.0)


class TestCheckpointRetention:
    def test_step_tagged_saves_pruned_and_resumable(self, dp8, tmp_path):
        from pytorch_distributed_tpu.train import resolve_tag, step_tags

        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=64, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 16, sharding=dp8.batch_sharding())
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            loader,
            config=TrainerConfig(
                epochs=2, log_every=0, ckpt_dir=str(tmp_path),
                ckpt_every_steps=2, keep_checkpoints=2,
            ),
        )
        trainer.fit()  # 8 steps -> saves at 2,4,6,8, pruned to newest 2
        assert step_tags(str(tmp_path)) == [6, 8]
        # 'latest' is also written at epoch end; remove it to prove the
        # resolver falls back to the newest step tag
        import shutil

        shutil.rmtree(tmp_path / "latest")
        assert resolve_tag(str(tmp_path)) == "step-8"
        trainer2 = Trainer(
            tiny_image_state(model),
            dp8,
            build_train_step(classification_loss_fn(model)),
            loader,
            config=TrainerConfig(
                epochs=2, log_every=0, ckpt_dir=str(tmp_path),
            ),
        )
        assert trainer2.restore_checkpoint()
        assert trainer2.host_step == 8
        # an EXPLICIT absent tag must not silently substitute a step tag
        from pytorch_distributed_tpu.train import resolve_tag as rt

        assert rt(str(tmp_path), "best") is None
        # orphaned partial writes are swept by prune
        import os

        from pytorch_distributed_tpu.train import prune_checkpoints

        os.makedirs(tmp_path / "step-99.tmp" / "junk")
        removed = prune_checkpoints(str(tmp_path), keep=2)
        assert str(tmp_path / "step-99.tmp") in removed
        assert not (tmp_path / "step-99.tmp").exists()

    def test_keep_best_tracks_metric(self, dp8, tmp_path):
        from pytorch_distributed_tpu.train import checkpoint_step

        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=32, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 16, sharding=dp8.batch_sharding())
        trainer = Trainer(
            state,
            dp8,
            build_train_step(classification_loss_fn(model)),
            loader,
            eval_step=classification_eval_step(model),
            eval_loader=DataLoader(
                ds, 16, shuffle=False, sharding=dp8.batch_sharding()
            ),
            config=TrainerConfig(
                epochs=1, log_every=0, ckpt_dir=str(tmp_path),
                keep_best="loss", best_mode="min",
            ),
        )
        trainer.fit()
        assert (tmp_path / "best").is_dir()
        assert checkpoint_step(str(tmp_path), tag="best") >= 1
        # a WORSE metric must not overwrite best
        best_before = trainer._best_value
        trainer._maybe_save_best({"loss": best_before + 1.0})
        assert trainer._best_value == best_before
        # NaN never becomes (or displaces) best
        trainer._maybe_save_best({"loss": float("nan")})
        assert trainer._best_value == best_before
        # the best value survives a resume: a fresh trainer restoring this
        # dir must NOT let a worse first eval demote 'best'
        trainer2 = Trainer(
            tiny_image_state(tiny_resnet()),
            dp8,
            build_train_step(classification_loss_fn(tiny_resnet())),
            DataLoader(
                SyntheticImageDataset(n=32, image_shape=(16, 16, 3)),
                16, sharding=dp8.batch_sharding(),
            ),
            config=TrainerConfig(
                ckpt_dir=str(tmp_path), keep_best="loss", best_mode="min",
            ),
        )
        assert trainer2.restore_checkpoint()
        assert trainer2._best_value == pytest.approx(best_before)
        trainer2._maybe_save_best({"loss": best_before + 5.0})
        assert trainer2._best_value == pytest.approx(best_before)

    def test_resolve_latest_prefers_newest_step(self, dp8, tmp_path):
        # a stale 'latest' (earlier step) beside newer step tags must lose
        from pytorch_distributed_tpu.train import resolve_tag

        state = dp8.place(linear_state())
        step = dp8.compile(build_train_step(linear_loss_fn), state)
        save_checkpoint(str(tmp_path), state, tag="latest")  # step 0
        state, _ = step(state, dp8.shard_batch(linear_batch()))
        state, _ = step(state, dp8.shard_batch(linear_batch()))
        save_checkpoint(str(tmp_path), state, tag="step-2")
        assert resolve_tag(str(tmp_path)) == "step-2"

    def test_bad_best_mode_raises(self, dp8):
        model = tiny_resnet()
        with pytest.raises(ValueError, match="best_mode"):
            Trainer(
                tiny_image_state(model),
                dp8,
                build_train_step(classification_loss_fn(model)),
                DataLoader(
                    SyntheticImageDataset(n=16, image_shape=(16, 16, 3)),
                    16, sharding=dp8.batch_sharding(),
                ),
                config=TrainerConfig(best_mode="sideways"),
            )

    def test_keep_checkpoints_without_cadence_raises(self, dp8):
        """keep_checkpoints without ckpt_every_steps would be silently
        inert (no step tags are ever written to prune) — fail loudly at
        construction instead (ADVICE r2)."""
        model = tiny_resnet()
        with pytest.raises(ValueError, match="ckpt_every_steps"):
            Trainer(
                tiny_image_state(model),
                dp8,
                build_train_step(classification_loss_fn(model)),
                DataLoader(
                    SyntheticImageDataset(n=16, image_shape=(16, 16, 3)),
                    16, sharding=dp8.batch_sharding(),
                ),
                config=TrainerConfig(keep_checkpoints=2),
            )


class TestMixupCutmix:
    def test_mixup_is_exact_convex_combination(self):
        from pytorch_distributed_tpu.train.losses import mixup_cutmix

        rng = jax.random.key(3)
        imgs = jnp.asarray(
            np.random.default_rng(0).normal(size=(8, 6, 6, 3))
        ).astype(jnp.float32)
        mixed, perm, lam = jax.jit(
            lambda r, x: mixup_cutmix(r, x, mixup_alpha=0.4,
                                      cutmix_alpha=0.0)
        )(rng, imgs)
        lam_f = float(lam)
        assert 0.0 <= lam_f <= 1.0
        np.testing.assert_allclose(
            np.asarray(mixed),
            lam_f * np.asarray(imgs) + (1 - lam_f) * np.asarray(imgs)[np.asarray(perm)],
            rtol=1e-5, atol=1e-6,
        )

    def test_cutmix_pixels_come_from_exactly_one_source(self):
        from pytorch_distributed_tpu.train.losses import mixup_cutmix

        imgs = jnp.asarray(
            np.random.default_rng(1).normal(size=(8, 12, 12, 3))
        ).astype(jnp.float32)
        saw_box = False
        for seed in range(6):
            mixed, perm, lam = mixup_cutmix(
                jax.random.key(seed), imgs, mixup_alpha=0.0,
                cutmix_alpha=1.0,
            )
            a = np.asarray(imgs)
            b = a[np.asarray(perm)]
            m = np.asarray(mixed)
            from_a = np.isclose(m, a).all(axis=-1)
            from_b = np.isclose(m, b).all(axis=-1)
            assert (from_a | from_b).all()
            # lam == fraction NOT replaced (paper's area adjustment);
            # verify against the actual box for a non-self-paired row
            frac_b = from_b[0].mean() if np.asarray(perm)[0] != 0 else None
            if frac_b is not None and 0.0 < float(lam) < 1.0:
                assert abs((1.0 - float(lam)) - frac_b) < 0.35, (
                    lam, frac_b,
                )  # loose: from_a/from_b overlap where a==b coincidentally
                saw_box = True
        assert saw_box

    def test_loss_fn_trains_and_reports_lam(self):
        import flax.linen as nn
        from pytorch_distributed_tpu.train import (
            mixup_classification_loss_fn,
        )

        class Tiny(nn.Module):
            @nn.compact
            def __call__(self, x, train=False):
                return nn.Dense(4)(x.mean(axis=(1, 2)))

        m = Tiny()
        imgs = jnp.asarray(
            np.random.default_rng(0).normal(size=(16, 8, 8, 3))
        ).astype(jnp.float32)
        labels = jnp.asarray(np.random.default_rng(1).integers(4, size=16))
        v = m.init(jax.random.key(0), imgs[:1])
        state = TrainState.create(
            apply_fn=m.apply, params=v["params"], tx=optax.adam(5e-3)
        )
        step = jax.jit(build_train_step(mixup_classification_loss_fn(
            m, mixup_alpha=0.3, cutmix_alpha=1.0, switch_prob=0.5
        )))
        losses, lams = [], []
        batch = {"image": imgs, "label": labels}
        for _ in range(30):
            state, metrics = step(state, batch)
            losses.append(float(metrics["loss"]))
            lams.append(float(metrics["lam"]))
        assert min(lams) >= 0.0 and max(lams) <= 1.0
        assert len(set(round(x, 6) for x in lams)) > 5  # lam varies by step
        assert np.mean(losses[-5:]) < np.mean(losses[:5])

    def test_requires_some_alpha(self):
        from pytorch_distributed_tpu.train import (
            mixup_classification_loss_fn,
        )

        with pytest.raises(ValueError):
            mixup_classification_loss_fn(
                object(), mixup_alpha=0.0, cutmix_alpha=0.0
            )


def test_topk_accuracy():
    from pytorch_distributed_tpu.train.losses import topk_accuracy

    logits = jnp.asarray([
        [9.0, 5.0, 4.0, 3.0, 2.0, 1.0, 0.0],   # top5 = {0,1,2,3,4}
        [0.0, 1.0, 2.0, 3.0, 4.0, 5.0, 9.0],   # top5 = {6,5,4,3,2}
    ])
    labels = jnp.asarray([4, 1])
    assert float(topk_accuracy(logits, labels, k=5)) == 0.5
    assert float(topk_accuracy(logits, labels, k=7)) == 1.0
    assert float(topk_accuracy(logits, labels, k=99)) == 1.0  # clamps
    assert float(topk_accuracy(logits, jnp.asarray([0, 6]), k=1)) == 1.0


class TestDivergenceAndEarlyStop:
    def test_halt_on_persistent_nonfinite_loss(self, dp8):
        from pytorch_distributed_tpu.train import TrainingDiverged

        state = linear_state()

        def nan_step(state, batch):
            # weights are already NaN in spirit: loss never heals
            return state.apply_gradients(
                grads=jax.tree_util.tree_map(jnp.zeros_like, state.params)
            ), {"loss": jnp.float32(jnp.nan)}

        ds = ArrayDataset(
            x=np.zeros((64, 4), np.float32), y=np.zeros((64,), np.float32)
        )
        trainer = Trainer(
            dp8.place(state), dp8, nan_step,
            DataLoader(ds, 8, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=1, halt_on_nonfinite=3
            ),
        )
        with pytest.raises(TrainingDiverged, match="3 consecutive"):
            trainer.fit()
        assert trainer.host_step == 3  # halted, not end-of-data

    def test_transient_nonfinite_tolerated(self, dp8):
        state = linear_state()
        calls = {"n": 0}

        def flaky_step(state, batch):
            calls["n"] += 1  # trace-time only; use step count on device
            loss = jnp.where(
                state.step == 1, jnp.float32(jnp.inf), jnp.float32(0.5)
            )
            return state.apply_gradients(
                grads=jax.tree_util.tree_map(jnp.zeros_like, state.params)
            ), {"loss": loss}

        ds = ArrayDataset(
            x=np.zeros((64, 4), np.float32), y=np.zeros((64,), np.float32)
        )
        trainer = Trainer(
            dp8.place(state), dp8, flaky_step,
            DataLoader(ds, 8, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=1, halt_on_nonfinite=2
            ),
        )
        trainer.fit()  # one inf log (step 2), then finite: no halt
        assert trainer.host_step == 8
        assert trainer._nonfinite_logs == 0  # reset by the finite logs

    def test_early_stop_on_stale_eval(self, dp8):
        model = tiny_resnet()
        state = tiny_image_state(model)
        ds = SyntheticImageDataset(n=16, image_shape=(16, 16, 3), seed=0)
        loader = DataLoader(ds, 8, sharding=dp8.batch_sharding())

        def constant_eval(state, batch):
            return {"accuracy": jnp.float32(0.5), "n": jnp.float32(1.0)}

        with tempfile.TemporaryDirectory() as d:
            trainer = Trainer(
                state, dp8,
                build_train_step(classification_loss_fn(model)), loader,
                eval_step=constant_eval, eval_loader=loader,
                config=TrainerConfig(
                    epochs=10, log_every=0, ckpt_dir=d,
                    keep_best="accuracy", early_stop_patience=2,
                ),
            )
            trainer.fit()
        # epoch 0 sets the best; epochs 1-2 are stale; stop after epoch 2
        assert trainer.host_step == 3 * 2  # 3 epochs x 2 steps/epoch
        assert trainer._es_stale == 2

    def test_early_stop_requires_watched_metric(self, dp8):
        state = linear_state()
        ds = ArrayDataset(
            x=np.zeros((8, 4), np.float32), y=np.zeros((8,), np.float32)
        )
        with pytest.raises(ValueError, match="early_stop_patience requires"):
            Trainer(
                dp8.place(state), dp8,
                build_train_step(linear_loss_fn),
                DataLoader(ds, 8, sharding=dp8.batch_sharding()),
                config=TrainerConfig(early_stop_patience=2),
            )


class TestTraceWindow:
    @pytest.mark.slow  # r5 profile refit: profiler surface pinned in test_utils
    def test_trace_steps_capture_window(self, dp8, tmp_path):
        state = linear_state()

        def step_fn(state, batch):
            return state.apply_gradients(
                grads=jax.tree_util.tree_map(jnp.zeros_like, state.params)
            ), {"loss": jnp.float32(1.0)}

        ds = ArrayDataset(
            x=np.zeros((64, 4), np.float32), y=np.zeros((64,), np.float32)
        )
        trainer = Trainer(
            dp8.place(state), dp8, step_fn,
            DataLoader(ds, 8, sharding=dp8.batch_sharding()),
            config=TrainerConfig(
                epochs=1, log_every=0,
                trace_dir=str(tmp_path), trace_steps=(2, 4),
            ),
        )
        trainer.fit()
        assert not trainer._tracing  # window closed mid-epoch
        # the profiler wrote a plugin dir with at least one trace file
        files = list(tmp_path.rglob("*"))
        assert any(f.is_file() for f in files), files

    def test_trace_config_validation(self, dp8):
        state = linear_state()
        ds = ArrayDataset(
            x=np.zeros((8, 4), np.float32), y=np.zeros((8,), np.float32)
        )
        loader = DataLoader(ds, 8, sharding=dp8.batch_sharding())
        with pytest.raises(ValueError, match="come together"):
            Trainer(
                dp8.place(linear_state()), dp8,
                build_train_step(linear_loss_fn), loader,
                config=TrainerConfig(trace_steps=(1, 2)),
            )
        with pytest.raises(ValueError, match="start < stop"):
            Trainer(
                dp8.place(linear_state()), dp8,
                build_train_step(linear_loss_fn), loader,
                config=TrainerConfig(trace_dir="/tmp/x", trace_steps=(4, 2)),
            )


def test_average_checkpoints(dp8, tmp_path):
    from pytorch_distributed_tpu.train import (
        average_checkpoints,
        save_checkpoint,
    )

    # three checkpoints whose params are the constants 1, 2, 3
    for i, val in enumerate([1.0, 2.0, 3.0]):
        state = linear_state()
        state = state.replace(
            params=jax.tree_util.tree_map(
                lambda x: jnp.full_like(x, val), state.params
            ),
            step=jnp.int32(10 * (i + 1)),
        )
        save_checkpoint(str(tmp_path), state, tag=f"step-{10 * (i + 1)}")
    avg = average_checkpoints(
        str(tmp_path), linear_state(),
        [f"step-{s}" for s in (10, 20, 30)],
    )
    for leaf in jax.tree_util.tree_leaves(avg.params):
        np.testing.assert_allclose(np.asarray(leaf), 2.0, rtol=1e-6)
    assert int(avg.step) == 30  # everything else from the newest tag
    with pytest.raises(ValueError, match="at least one"):
        average_checkpoints(str(tmp_path), linear_state(), [])


def test_average_checkpoints_sharded_restore(dp8, tmp_path):
    from pytorch_distributed_tpu.train import (
        average_checkpoints,
        save_checkpoint,
    )

    for i, val in enumerate([1.0, 3.0]):
        state = linear_state()
        state = state.replace(
            params=jax.tree_util.tree_map(
                lambda x: jnp.full_like(x, val), state.params
            ),
            step=jnp.int32(i + 1),
        )
        save_checkpoint(str(tmp_path), state, tag=f"step-{i + 1}")
    template = dp8.place(linear_state())
    avg = average_checkpoints(
        str(tmp_path), linear_state(), ["step-1", "step-2"],
        shardings=dp8.state_shardings(template),
    )
    leaf = jax.tree_util.tree_leaves(avg.params)[0]
    assert hasattr(leaf, "sharding")  # mesh-placed, not host numpy
    np.testing.assert_allclose(np.asarray(leaf), 2.0, rtol=1e-6)


class TestF1Eval:
    def test_f1_finalize_hand_case(self):
        from pytorch_distributed_tpu.train import f1_finalize

        # 10 samples: tp=3 fp=1 fn=2 tn=4 -> prec .75, rec .6, f1 ~.667
        means = {"tp_rate": 0.3, "fp_rate": 0.1, "fn_rate": 0.2,
                 "tn_rate": 0.4, "accuracy": 0.7}
        out = f1_finalize(means)
        assert out["precision"] == pytest.approx(0.75)
        assert out["recall"] == pytest.approx(0.6)
        assert out["f1"] == pytest.approx(2 * 0.75 * 0.6 / 1.35)
        # MCC by the book: (tp*tn - fp*fn)/sqrt(...)
        import math
        want = (0.3 * 0.4 - 0.1 * 0.2) / math.sqrt(
            0.4 * 0.5 * 0.5 * 0.6
        )
        assert out["mcc"] == pytest.approx(want)
        # degenerate: never predicted positive -> sklearn's 0 convention
        z = f1_finalize({"tp_rate": 0.0, "fp_rate": 0.0,
                         "fn_rate": 0.5, "tn_rate": 0.5})
        assert z["precision"] == 0.0 and z["f1"] == 0.0
        # plain accuracy dict passes through untouched
        assert f1_finalize({"accuracy": 0.9}) == {"accuracy": 0.9}

    @pytest.mark.slow  # r5 profile refit: eval_finalize/metric machinery covered by other trainer eval tests
    def test_trainer_eval_reports_f1(self, dp8):
        from pytorch_distributed_tpu.models.bert import (
            BertConfig,
            BertForSequenceClassification,
        )
        from pytorch_distributed_tpu.train import (
            f1_finalize,
            text_classification_eval_step,
            text_classification_loss_fn,
        )

        cfg = BertConfig(
            vocab_size=64, hidden_size=32, num_layers=1, num_heads=2,
            intermediate_size=64, max_position_embeddings=32,
            dropout_rate=0.0,
        )
        model = BertForSequenceClassification(cfg, num_labels=2)
        rng = np.random.default_rng(0)
        ids = rng.integers(64, size=(32, 8)).astype(np.int32)
        labels = rng.integers(2, size=(32,)).astype(np.int32)
        params = model.init(
            jax.random.key(0), jnp.asarray(ids[:1])
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.0)
        )
        ds = ArrayDataset(input_ids=ids, label=labels)
        loader = DataLoader(
            ds, 16, shuffle=False, sharding=dp8.batch_sharding(),
            drop_last=False,
        )
        trainer = Trainer(
            dp8.place(state), dp8,
            build_train_step(text_classification_loss_fn(model)),
            loader,
            eval_step=text_classification_eval_step(
                model, binary_metrics=True
            ),
            eval_loader=loader,
            config=TrainerConfig(
                epochs=1, log_every=0, eval_finalize=f1_finalize,
                samples_axis="input_ids",
            ),
        )
        means = trainer.evaluate(0)
        for k in ("accuracy", "precision", "recall", "f1", "mcc"):
            assert k in means
        # the finalized f1 from aggregated rates equals the f1 computed
        # directly over the whole set with the same params
        logits = model.apply({"params": params}, jnp.asarray(ids))
        pred = np.asarray(jnp.argmax(logits, -1))
        tp = ((pred == 1) & (labels == 1)).sum()
        fp = ((pred == 1) & (labels == 0)).sum()
        fn = ((pred == 0) & (labels == 1)).sum()
        want = 2 * tp / max(2 * tp + fp + fn, 1)
        assert means["f1"] == pytest.approx(float(want), abs=1e-6)
