"""Phi-3: HF logit parity through the fused-projection split (the only
family-specific code is interop), export re-fuses exactly."""

import numpy as np
import pytest

import jax.numpy as jnp

from pytorch_distributed_tpu.models import Phi3Config, Phi3ForCausalLM
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _pair():
    torch.manual_seed(0)
    hf_cfg = transformers.Phi3Config(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10_000.0, rms_norm_eps=1e-5,
        max_position_embeddings=128, tie_word_embeddings=False,
        pad_token_id=0,  # HF default 32000 exceeds the tiny vocab
        attn_implementation="eager",
    )
    hf = transformers.Phi3ForCausalLM(hf_cfg).eval()
    cfg = Phi3Config(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
        rope_theta=10_000.0, rms_eps=1e-5,
    )
    return hf, cfg


def test_phi3_logits_match_hf():
    from pytorch_distributed_tpu.interop import load_phi3_weights

    hf, cfg = _pair()
    params = load_phi3_weights(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, cfg
    )
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 10)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = Phi3ForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)


@pytest.mark.slow  # budget: parity pins the split mapping fast
def test_phi3_export_refuses_nothing_and_roundtrips():
    from pytorch_distributed_tpu.interop import (
        export_phi3_weights,
        load_phi3_weights,
    )

    hf, cfg = _pair()
    params = load_phi3_weights(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, cfg
    )
    sd = export_phi3_weights(params, cfg)
    # no split keys may survive the re-fuse
    assert not any("q_proj" in k or "gate_proj" in k for k in sd)
    hf2 = transformers.Phi3ForCausalLM(hf.config).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(1).integers(2, 211, size=(1, 8)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )
