"""Host-dispatched 1F1B pipeline parallelism (marker: pp).

Three layers:

* the pure schedule math — hand-pinned 1F1B/GPipe tick tables, the
  warm-up formula, in-flight peaks (the memory claim ``min(S - s, M)``
  vs GPipe's ``M``), the analytic bubble ``(S-1)/(V*M + S-1)``, the
  interleaved virtual-stage mapping, and ``simulate_links`` replaying
  every schedule against the shm transport's one-slot mailbox model
  (deadlock freedom AND tag order, statically);
* the executor — the S == 1 ``HostPipelineStep`` against an inline dp
  scan-fold reference (same association: numpy left fold in microbatch
  order == ``lax.scan``), compile counts pinned at one program each;
* the ring — the 2-proc S == 2 1F1B run on the real hostring matches
  the solo executor (losses and merged params), and a deliberately
  desynced activation handoff trips the DETAIL fingerprint handshake
  on BOTH ends instead of delivering the wrong microbatch.

The performance half — 1F1B vs the SPMD GPipe's garbage-tick compute,
the measured-vs-analytic bubble, the exposed-link ratio — lives in
bench.py's ``pipeline`` phase (pinned by test_bench_contract); the
stage-death autopsy drill in ``scripts/chaos_drill.py --drill
pipeline``.
"""

import numpy as np
import pytest

from pytorch_distributed_tpu.parallel import pipeline_schedule as ps
from pytorch_distributed_tpu.parallel.pipeline_schedule import (
    BWD,
    FWD,
    RECV_ACT,
    RECV_GRAD,
    SEND_ACT,
    SEND_GRAD,
    ScheduleDeadlock,
    StageOp,
    bubble_fraction,
    peak_live_microbatches,
    schedule_1f1b,
    schedule_gpipe,
    schedule_interleaved,
    simulate_links,
    stage_depths,
    stage_layer_slices,
    virtual_stage,
)
from pytorch_distributed_tpu.runtime import faults

from tests import pipeline_workers

pytestmark = pytest.mark.pp


def _skeleton(program):
    return [(op.kind, op.mb) for op in program if op.kind in (FWD, BWD)]


# -- hand-pinned tick tables ------------------------------------------------

def test_1f1b_s2_m4_hand_table():
    # stage 0: one warm-up fwd, steady (F,B) x3, one cool-down bwd
    assert _skeleton(schedule_1f1b(0, 2, 4)) == [
        (FWD, 0), (FWD, 1), (BWD, 0), (FWD, 2), (BWD, 1), (FWD, 3),
        (BWD, 2), (BWD, 3),
    ]
    # stage 1 (last): no warm-up — strict 1F1B from the first microbatch
    assert _skeleton(schedule_1f1b(1, 2, 4)) == [
        (FWD, 0), (BWD, 0), (FWD, 1), (BWD, 1), (FWD, 2), (BWD, 2),
        (FWD, 3), (BWD, 3),
    ]


def test_1f1b_s2_m4_full_op_lists():
    assert schedule_1f1b(0, 2, 4) == (
        StageOp(FWD, 0), StageOp(SEND_ACT, 0),
        StageOp(FWD, 1), StageOp(SEND_ACT, 1),
        StageOp(RECV_GRAD, 0), StageOp(BWD, 0),
        StageOp(FWD, 2), StageOp(SEND_ACT, 2),
        StageOp(RECV_GRAD, 1), StageOp(BWD, 1),
        StageOp(FWD, 3), StageOp(SEND_ACT, 3),
        StageOp(RECV_GRAD, 2), StageOp(BWD, 2),
        StageOp(RECV_GRAD, 3), StageOp(BWD, 3),
    )
    assert schedule_1f1b(1, 2, 4) == (
        StageOp(RECV_ACT, 0), StageOp(FWD, 0),
        StageOp(BWD, 0), StageOp(SEND_GRAD, 0),
        StageOp(RECV_ACT, 1), StageOp(FWD, 1),
        StageOp(BWD, 1), StageOp(SEND_GRAD, 1),
        StageOp(RECV_ACT, 2), StageOp(FWD, 2),
        StageOp(BWD, 2), StageOp(SEND_GRAD, 2),
        StageOp(RECV_ACT, 3), StageOp(FWD, 3),
        StageOp(BWD, 3), StageOp(SEND_GRAD, 3),
    )


def test_1f1b_s3_m3_middle_stage_table():
    assert _skeleton(schedule_1f1b(1, 3, 3)) == [
        (FWD, 0), (FWD, 1), (BWD, 0), (FWD, 2), (BWD, 1), (BWD, 2),
    ]


def test_gpipe_hand_table():
    assert _skeleton(schedule_gpipe(0, 2, 3)) == [
        (FWD, 0), (FWD, 1), (FWD, 2), (BWD, 0), (BWD, 1), (BWD, 2),
    ]


# -- structural properties over an (S, M) grid ------------------------------

GRID = [(s, S, M) for S in (1, 2, 3, 4) for s in range(S)
        for M in (1, 2, 3, 4, 8)]


def test_schedule_is_pure_function_of_args():
    for s, S, M in GRID:
        assert schedule_1f1b(s, S, M) == schedule_1f1b(s, S, M)
        assert schedule_gpipe(s, S, M) == schedule_gpipe(s, S, M)


@pytest.mark.parametrize("maker", [schedule_1f1b, schedule_gpipe])
def test_every_microbatch_exactly_once_per_kind(maker):
    for s, S, M in GRID:
        program = maker(s, S, M)
        for kind in (FWD, BWD):
            mbs = sorted(op.mb for op in program if op.kind == kind)
            assert mbs == list(range(M)), (s, S, M, kind)


def test_1f1b_warmup_formula():
    for s, S, M in GRID:
        sk = _skeleton(schedule_1f1b(s, S, M))
        lead = 0
        for kind, _ in sk:
            if kind != FWD:
                break
            lead += 1
        # warm-up is min(S-1-stage, M) forwards; if any microbatches
        # remain, the first steady-state forward also precedes bwd 0
        warmup = min(S - 1 - s, M)
        assert lead == (warmup + 1 if warmup < M else warmup), (s, S, M)


def test_1f1b_backwards_complete_in_increasing_mb_order():
    # the left-fold == lax.scan association argument needs this
    for s, S, M in GRID:
        order = [op.mb for op in schedule_1f1b(s, S, M) if op.kind == BWD]
        assert order == sorted(order), (s, S, M)


def test_peak_live_1f1b_is_min_S_minus_stage_M():
    for s, S, M in GRID:
        assert peak_live_microbatches(
            schedule_1f1b(s, S, M)
        ) == min(S - s, M), (s, S, M)


def test_peak_live_gpipe_is_M():
    for s, S, M in GRID:
        assert peak_live_microbatches(schedule_gpipe(s, S, M)) == M


# -- the analytic bubble ----------------------------------------------------

def test_bubble_fraction_values():
    assert bubble_fraction(1, 4) == 0.0
    assert bubble_fraction(2, 4) == pytest.approx(0.2)
    assert bubble_fraction(4, 8) == pytest.approx(3 / 11)
    # interleaving with V chunks divides the bubble's share of the path
    assert bubble_fraction(4, 8, 2) == pytest.approx(3 / 19)
    assert bubble_fraction(2, 4, 4) < bubble_fraction(2, 4)


def test_bubble_fraction_rejects_degenerate_shapes():
    with pytest.raises(ValueError):
        bubble_fraction(0, 4)
    with pytest.raises(ValueError):
        bubble_fraction(2, 0)


# -- channel-model replay: deadlock freedom + tag order ---------------------

@pytest.mark.parametrize("maker", [schedule_1f1b, schedule_gpipe])
def test_schedules_drain_one_slot_mailboxes(maker):
    for S in (1, 2, 3, 4):
        for M in (1, 2, 4, 8):
            programs = [maker(s, S, M) for s in range(S)]
            assert simulate_links(programs, capacity=1) > 0, (S, M)


def test_simulate_links_raises_on_circular_wait():
    # both stages lead with a receive: nobody ever sends
    programs = [
        (StageOp(RECV_GRAD, 0), StageOp(BWD, 0)),
        (StageOp(RECV_ACT, 0), StageOp(FWD, 0)),
    ]
    with pytest.raises(ScheduleDeadlock):
        simulate_links(programs)


def test_simulate_links_raises_on_tag_reorder():
    # sender ships m0 then m1; receiver wants m1 first — the static form
    # of the DETAIL fingerprint mismatch
    programs = [
        (StageOp(FWD, 0), StageOp(SEND_ACT, 0),
         StageOp(FWD, 1), StageOp(SEND_ACT, 1)),
        (StageOp(RECV_ACT, 1), StageOp(FWD, 1),
         StageOp(RECV_ACT, 0), StageOp(FWD, 0)),
    ]
    with pytest.raises(ValueError, match="fingerprint"):
        simulate_links(programs)


# -- interleaved virtual stages ---------------------------------------------

def test_virtual_stage_mapping():
    # consecutive global stages land on consecutive ranks
    world = 2
    assert [virtual_stage(r, c, world)
            for c in range(2) for r in range(world)] == [0, 1, 2, 3]


def test_interleaved_each_chunk_mb_once_per_kind():
    for world, V, M in [(2, 2, 2), (2, 2, 4), (2, 3, 4), (4, 2, 4),
                        (3, 2, 6)]:
        for rank in range(world):
            program = schedule_interleaved(rank, world, V, M)
            for kind in (FWD, BWD):
                seen = sorted(
                    (op.chunk, op.mb) for op in program if op.kind == kind
                )
                assert seen == sorted(
                    (c, m) for c in range(V) for m in range(M)
                ), (world, V, M, rank, kind)


def test_interleaved_global_drain_respects_dependencies():
    # replay all ranks' programs against the data dependencies: fwd of
    # global stage g needs fwd of g-1 on the same microbatch; bwd of g
    # needs its own fwd plus bwd of g+1. A drain proves the warm-up
    # depth keeps every chunk fed.
    for world, V, M in [(2, 2, 2), (2, 2, 4), (4, 2, 4)]:
        S = world * V
        programs = [list(schedule_interleaved(r, world, V, M))
                    for r in range(world)]
        pcs = [0] * world
        done = set()
        while any(pc < len(programs[r]) for r, pc in enumerate(pcs)):
            progressed = False
            for r in range(world):
                while pcs[r] < len(programs[r]):
                    op = programs[r][pcs[r]]
                    g = virtual_stage(r, op.chunk, world)
                    if op.kind == FWD:
                        ready = g == 0 or (FWD, g - 1, op.mb) in done
                    else:
                        ready = (FWD, g, op.mb) in done and (
                            g == S - 1 or (BWD, g + 1, op.mb) in done
                        )
                    if not ready:
                        break
                    done.add((op.kind, g, op.mb))
                    pcs[r] += 1
                    progressed = True
            assert progressed, (world, V, M, pcs)


def test_interleaved_rejects_bad_shapes():
    with pytest.raises(ValueError, match="num_chunks >= 2"):
        schedule_interleaved(0, 2, 1, 4)
    with pytest.raises(ValueError, match="divisible"):
        schedule_interleaved(0, 2, 2, 3)


# -- layer apportionment ----------------------------------------------------

def test_stage_depths_even_split():
    assert stage_depths(8, 2) == (4, 4)
    assert stage_depths(12, 4) == (3, 3, 3, 3)


def test_stage_depths_hetero_gives_slow_rank_shallower_stage():
    # the hand-computed hetero pin: 8 layers, rank 1 at half speed
    assert stage_depths(8, 2, rank_rates=[1.0, 0.5]) == (5, 3)


def test_stage_depths_refuses_uneven_without_rates():
    with pytest.raises(ValueError, match="rank_rates"):
        stage_depths(7, 2)


def test_stage_depths_refuses_more_stages_than_layers():
    with pytest.raises(ValueError, match="cannot fill"):
        stage_depths(2, 4)


def test_stage_layer_slices():
    assert stage_layer_slices((5, 3)) == ((0, 5), (5, 8))
    assert stage_layer_slices((4, 4)) == ((0, 4), (4, 8))


# -- the stage_stall fault site ---------------------------------------------

def test_stage_stall_site_registered():
    assert "pipeline.stage_stall" in faults.KNOWN_SITES


def test_stage_stall_match_selects_exact_op():
    with faults.injected(
        "pipeline.stage_stall:mode=stall,seconds=0.5,match=s1.bwd.m2"
    ):
        assert faults.hang_action(
            "pipeline.stage_stall", "s1.bwd.m2"
        ) == ("stall", 0.5)
        assert faults.hang_action(
            "pipeline.stage_stall", "s0.fwd.m0"
        ) is None
        # stall sites never corrupt through check()
        faults.check("pipeline.stage_stall", "s1.bwd.m2")


# -- the executor: S == 1 vs an inline dp reference -------------------------

def _dp_reference(cfg, steps, batch, seq, M, seed, lr):
    """The dp baseline the pipeline must match: lax.scan fold over the
    same microbatch split, 1/M inside jit, sgd — the trainer's scanned
    accumulation shape with the same CE loss."""
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_tpu.models.gpt2 import GPT2LMHead

    model = GPT2LMHead(cfg)
    variables = model.init(
        jax.random.key(seed), jnp.zeros((1, seq), jnp.int32)
    )
    params = variables["params"]
    tx = optax.sgd(lr)
    opt = tx.init(params)

    def loss_fn(p, ids):
        logits = model.apply({"params": p}, ids)
        shift = logits[:, :-1].astype(jnp.float32)
        return optax.softmax_cross_entropy_with_integer_labels(
            shift, ids[:, 1:]
        ).mean()

    @jax.jit
    def step(p, o, ids_mbs):
        def body(acc, ids):
            loss, g = jax.value_and_grad(loss_fn)(p, ids)
            return jax.tree_util.tree_map(jnp.add, acc, g), loss

        zero = jax.tree_util.tree_map(
            lambda a: jnp.zeros_like(a), p
        )
        gsum, mb_losses = jax.lax.scan(body, zero, ids_mbs)
        g = jax.tree_util.tree_map(lambda a: a / M, gsum)
        updates, o2 = tx.update(g, o, p)
        return optax.apply_updates(p, updates), o2, mb_losses.mean()

    losses = []
    for b in pipeline_workers.make_batches(
        steps, batch, seq, cfg.vocab_size, seed + 1
    ):
        ids = np.stack(np.split(b["input_ids"], M, axis=0))
        params, opt, loss = step(params, opt, ids)
        losses.append(float(loss))
    return jax.tree_util.tree_map(np.asarray, params), losses


def test_solo_executor_matches_dp_reference():
    import jax
    import jax.numpy as jnp
    import optax

    from pytorch_distributed_tpu.models.gpt2 import GPT2LMHead
    from pytorch_distributed_tpu.parallel.pipeline_lm import (
        GPT2HostStagePrograms,
        host_stage_params,
    )
    from pytorch_distributed_tpu.parallel.pipeline_schedule import (
        HostPipelineStep,
    )

    opts = {"layers": 2, "hidden": 16, "vocab": 64, "n_positions": 16}
    steps, batch, seq, M, seed, lr = 2, 4, 8, 2, 0, 0.1
    cfg = pipeline_workers._tiny_cfg(opts)
    ref_params, ref_losses = _dp_reference(
        cfg, steps, batch, seq, M, seed, lr
    )

    model = GPT2LMHead(cfg)
    variables = model.init(
        jax.random.key(seed), jnp.zeros((1, seq), jnp.int32)
    )
    tx = optax.sgd(lr)
    host = HostPipelineStep(
        GPT2HostStagePrograms(cfg, stage=0, num_stages=1),
        stage=0, num_stages=1, num_microbatches=M, tx=tx,
    )
    params, _buffers = host_stage_params(
        variables["params"], stage=0, num_stages=1
    )
    opt = host.init_opt_state(params)
    losses = []
    for b in pipeline_workers.make_batches(
        steps, batch, seq, cfg.vocab_size, seed + 1
    ):
        params, opt, met = host.step(params, opt, b)
        losses.append(met["loss"])

    np.testing.assert_allclose(losses, ref_losses, rtol=2e-6)
    flat_ref = jax.tree_util.tree_leaves(ref_params)
    flat = jax.tree_util.tree_leaves(
        jax.tree_util.tree_map(np.asarray, params)
    )
    for a, b in zip(flat_ref, flat):
        # documented last-ulp class: regrouped f32 sums
        np.testing.assert_allclose(a, b, atol=5e-6, rtol=1e-5)
    # the HostLoopStep compile discipline: one program each, re-stepping
    # the same shape compiles nothing new
    assert host.compile_counts() == {"apply": 1, "loss_grad": 1}


# -- the real ring: 2-proc parity and the fingerprint handshake -------------

SMALL = {
    "layers": 2, "hidden": 16, "vocab": 64, "n_positions": 16,
    "steps": 2, "batch": 4, "seq": 8, "microbatches": 2, "seed": 0,
}


def test_two_stage_1f1b_ring_matches_solo():
    from pytorch_distributed_tpu.parallel.pipeline_lm import (
        host_merge_stage_params,
        host_stage_depths,
    )

    r1 = pipeline_workers.run_pipeline_world(
        1, pipeline_workers.pipeline_train_worker, (SMALL,), timeout=240
    )
    r2 = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_train_worker, (SMALL,), timeout=240
    )
    for rank, rep in r1 + r2:
        assert "error" not in rep, (rank, rep.get("error"))
    solo, (s0, s1) = r1[0][1], (r2[0][1], r2[1][1])

    # the last stage reports the loss stream; solo == pipelined
    np.testing.assert_allclose(s1["losses"], solo["losses"], rtol=1e-6)
    # compile counts: one fwd + one bwd (stage 0), fused loss_grad (last)
    assert s0["compile_counts"] == {"apply": 1, "bwd": 1, "fwd": 1}
    assert s1["compile_counts"] == {"apply": 1, "loss_grad": 1}

    depths = host_stage_depths(SMALL["layers"], 2)
    merged = host_merge_stage_params(
        [s0["stage_params"], s1["stage_params"]], depths
    )
    ref = host_merge_stage_params(
        [solo["stage_params"]], host_stage_depths(SMALL["layers"], 1)
    )
    import jax

    for a, b in zip(
        jax.tree_util.tree_leaves(ref), jax.tree_util.tree_leaves(merged)
    ):
        np.testing.assert_allclose(a, b, atol=5e-6, rtol=1e-5)


def test_handoff_mismatch_raises_on_both_ends():
    results = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_mismatch_worker, (), timeout=120
    )
    for rank, rep in results:
        assert "error" not in rep, (rank, rep.get("error"))
        assert rep["mismatch_error"] is not None, rank
        assert "P2P mismatch" in rep["mismatch_error"], rep


@pytest.mark.slow
def test_two_stage_gpipe_matches_1f1b():
    opts = dict(SMALL, schedule="gpipe")
    g = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_train_worker, (opts,), timeout=240
    )
    f = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_train_worker, (SMALL,), timeout=240
    )
    for rank, rep in g + f:
        assert "error" not in rep, (rank, rep.get("error"))
    # same math, different issue order: backwards still fold in mb order,
    # so GPipe and 1F1B land bit-identical params
    assert [rep["crc"] for _, rep in g] == [rep["crc"] for _, rep in f]


@pytest.mark.slow
def test_two_stage_hetero_depths_parity():
    # uneven stage depths (the hetero apportionment shape) change only
    # WHERE layers run, not the update math
    opts = dict(SMALL, layers=3, depths=(2, 1))
    r1 = pipeline_workers.run_pipeline_world(
        1, pipeline_workers.pipeline_train_worker,
        (dict(opts, depths=(3,)),), timeout=240
    )
    r2 = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_train_worker, (opts,), timeout=240
    )
    for rank, rep in r1 + r2:
        assert "error" not in rep, (rank, rep.get("error"))
    np.testing.assert_allclose(
        r2[1][1]["losses"], r1[0][1]["losses"], rtol=1e-6
    )


@pytest.mark.slow
def test_stage_death_leaves_survivor_dump(tmp_path):
    # the --drill pipeline shape at test scale: the last stage dies
    # mid-schedule (mode=kill), the survivor blocks at the ring deadline
    # and dumps its flight ring for the autopsy
    out = str(tmp_path)
    results = pipeline_workers.run_pipeline_world(
        2, pipeline_workers.pipeline_drill_worker,
        (out, 1, "pipeline.stage_stall:mode=kill,match=s1.bwd.m1"),
        timeout=240, expect=1,
    )
    by_rank = dict(results)
    # rank 1 was SIGKILLed via os._exit — only rank 0 reports
    assert len(by_rank) >= 1 and 0 in by_rank, results
    rep = by_rank[0]
    assert rep.get("role") == "survivor", rep
    assert rep["dumped"], rep
    assert "last completed flight" in rep["err"], rep["err"]
