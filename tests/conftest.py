"""Test harness: run everything on a virtual 8-device CPU mesh.

Multi-chip hardware is not available in CI; per the framework's test
strategy (SURVEY.md §4) all sharding/collective behavior is validated on
``--xla_force_host_platform_device_count=8`` CPU devices. The env must be
fixed before the first backend use: the container's sitecustomize registers
a TPU PJRT plugin at interpreter start, so we both set XLA_FLAGS and force
the platform via jax.config (which wins even after plugin registration).
"""

import os

os.environ["XLA_FLAGS"] = (
    os.environ.get("XLA_FLAGS", "") + " --xla_force_host_platform_device_count=8"
)

import jax

jax.config.update("jax_platforms", "cpu")

import numpy as np
import pytest

# Persistent executable cache — the SAME helper recipes/bench use, so the
# suite and production runs share one cache policy. The suite is
# compile-dominated on this 1-core box; a warm cache cuts re-runs ~30%.
# best_effort: an unwritable cache dir (read-only $HOME CI) must not stop
# the suite from collecting.
from pytorch_distributed_tpu.runtime.device import enable_compilation_cache

enable_compilation_cache(best_effort=True)


@pytest.fixture(autouse=True)
def _reset_global_state():
    """Isolate tests from each other's process-group/mesh globals."""
    yield
    from pytorch_distributed_tpu.runtime import distributed, mesh, prng

    distributed.destroy_process_group()
    mesh.set_current_mesh(None)
    prng._BASE_KEY = None


@pytest.fixture
def mesh8():
    """2x2x2 (dp, fsdp, tp) mesh over the 8 virtual CPU devices."""
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh

    return make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))


@pytest.fixture
def rng():
    return np.random.default_rng(0)
