"""Launcher layer: spawn() facade, the torchrun-equivalent CLI agent,
elastic restart policy, and env plumbing (SURVEY.md §2: torchrun /
mp.spawn -> SPMD launcher)."""

import os
import subprocess
import sys
import textwrap

import numpy as np
import pytest

from pytorch_distributed_tpu.launch import ElasticAgent, _worker_env
from tests import hostring_workers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def test_worker_env_shape():
    env = _worker_env(5, 8, "g1", node_rank=1, nproc_per_node=4)
    assert env["RANK"] == "5"
    assert env["WORLD_SIZE"] == "8"
    assert env["LOCAL_RANK"] == "1"
    assert env["LOCAL_WORLD_SIZE"] == "4"
    assert env["GROUP_RANK"] == "1"
    assert env["PTD_GROUP_NAME"] == "g1"
    assert env["JAX_PLATFORMS"] == "cpu"
    assert env["MASTER_ADDR"] == "127.0.0.1"


def test_spawn_facade(tmp_path):
    from pytorch_distributed_tpu.launch import spawn

    spawn(hostring_workers.spawn_worker, args=(str(tmp_path),), nprocs=2,
          timeout_s=300)
    for r in range(2):
        assert (tmp_path / f"rank{r}.ok").read_text() == "2"


@pytest.mark.slow
def test_ddp_invariant_across_ranks(tmp_path):
    """Multi-process DDP: grads average over the ring, loader shards by
    rank, params stay bit-identical on every rank after training."""
    from pytorch_distributed_tpu.launch import spawn

    spawn(hostring_workers.ddp_train_worker, args=(str(tmp_path),),
          nprocs=2, timeout_s=300)
    for r in range(2):
        assert (tmp_path / f"ddp{r}.ok").read_text() == "ok"


@pytest.mark.slow
def test_new_group_across_ranks(tmp_path):
    from pytorch_distributed_tpu.launch import spawn

    spawn(hostring_workers.subgroup_worker, args=(str(tmp_path),),
          nprocs=3, timeout_s=300)
    for r in range(3):
        assert (tmp_path / f"sg{r}.ok").read_text() == "ok"


@pytest.mark.slow
def test_iterable_loader_lockstep_across_ranks(tmp_path):
    from pytorch_distributed_tpu.launch import spawn

    spawn(hostring_workers.iterable_loader_worker, args=(str(tmp_path),),
          nprocs=2, timeout_s=300)
    for r in range(2):
        assert (tmp_path / f"it{r}.ok").read_text() == "ok"


@pytest.mark.slow
def test_grad_compression_bf16_across_ranks(tmp_path):
    """bf16-compressed gradient sync: exact single-rounding semantics on
    the wire, f32 results back in the step."""
    from pytorch_distributed_tpu.launch import spawn

    spawn(hostring_workers.grad_compress_worker, args=(str(tmp_path),),
          nprocs=2, timeout_s=300)
    for r in range(2):
        assert (tmp_path / f"gc{r}.ok").read_text() == "ok"


def test_spawn_propagates_failure():
    from pytorch_distributed_tpu.launch import spawn

    with pytest.raises(RuntimeError, match="nonzero"):
        spawn(hostring_workers.failing_worker, nprocs=2, timeout_s=60)


@pytest.mark.slow
def test_cli_end_to_end(tmp_path):
    """The torchrun-shaped CLI runs a real collective script, 2 procs."""
    script = tmp_path / "worker.py"
    script.write_text(textwrap.dedent(f"""
        import sys; sys.path.insert(0, {REPO!r})
        import numpy as np
        import jax; jax.config.update("jax_platforms", "cpu")
        import pytorch_distributed_tpu as ptd
        ptd.init_process_group("gloo")
        out = ptd.all_reduce(np.ones(3, np.float32))
        assert float(np.asarray(out)[0]) == ptd.get_world_size()
        print("WORKER_OK", ptd.get_rank())
    """))
    proc = subprocess.run(
        [sys.executable, "-m", "pytorch_distributed_tpu.run",
         "--nproc-per-node", "2", "--max-restarts", "0", str(script)],
        cwd=REPO, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]


def test_elastic_restart(tmp_path):
    """Agent re-rendezvouses after a worker failure (elastic recovery)."""
    marker = tmp_path / "attempts"
    script = tmp_path / "flaky.py"
    script.write_text(textwrap.dedent(f"""
        import os, sys
        attempt = int(os.environ["TORCHELASTIC_RESTART_COUNT"])
        rank = int(os.environ["RANK"])
        with open({str(marker)!r} + f"_a{{attempt}}_r{{rank}}", "w"):
            pass
        if attempt == 0 and rank == 1:
            sys.exit(13)  # simulated worker crash on first rendezvous
    """))
    agent = ElasticAgent(
        cmd=[sys.executable, str(script)], nproc_per_node=2, max_restarts=2
    )
    assert agent.run() == 0
    assert os.path.exists(str(marker) + "_a0_r1")  # crashed attempt ran
    assert os.path.exists(str(marker) + "_a1_r0")  # restarted cleanly
    assert not os.path.exists(str(marker) + "_a2_r0")  # no third round


def test_elastic_gives_up():
    agent = ElasticAgent(
        cmd=[sys.executable, "-c", "import sys; sys.exit(7)"],
        nproc_per_node=2, max_restarts=1,
    )
    assert agent.run() == 7


def test_multihost_env_routes_to_single_controller(monkeypatch):
    """PTD_MULTIHOST=1 (tpu pod launch): init_process_group rendezvouses
    via jax.distributed and stays single-controller — it must NOT join the
    host-local shm ring with the global world size."""
    from pytorch_distributed_tpu.runtime import distributed as dist

    called = []
    monkeypatch.setattr(
        "pytorch_distributed_tpu.launch.init_multihost",
        lambda: called.append(1),
    )
    monkeypatch.setattr(dist, "_MULTIHOST_DONE", False)
    monkeypatch.setenv("PTD_MULTIHOST", "1")
    monkeypatch.setenv("RANK", "0")
    monkeypatch.setenv("WORLD_SIZE", "2")
    g = dist.init_process_group()
    assert called == [1]
    assert g.ring is None


def test_init_multihost_env_mapping(monkeypatch):
    """torchrun-style env maps onto jax.distributed.initialize args."""
    import pytorch_distributed_tpu.launch as launch

    captured = {}

    def fake_init(coordinator_address=None, num_processes=None,
                  process_id=None):
        captured.update(addr=coordinator_address, n=num_processes,
                        pid=process_id)

    import jax

    monkeypatch.setattr(jax.distributed, "initialize", fake_init)
    monkeypatch.setenv("MASTER_ADDR", "10.0.0.1")
    monkeypatch.setenv("MASTER_PORT", "12345")
    monkeypatch.setenv("WORLD_SIZE", "16")
    monkeypatch.setenv("RANK", "3")
    launch.init_multihost()
    assert captured == {"addr": "10.0.0.1:12345", "n": 16, "pid": 3}


@pytest.mark.slow

def _run_multihost(worker, world, *extra_args, timeout=300):
    """Shared pod-test scaffolding: pick a free port, spawn ``world``
    jax.distributed controller processes, collect one queue result per
    rank (workers put (rank, "ok", ...) or (rank, error)), tear down, and
    assert every rank reported ok. Returns the results list."""
    import multiprocessing as mp
    import socket

    with socket.socket() as s:
        s.bind(("", 0))
        port = s.getsockname()[1]
    ctx = mp.get_context("spawn")
    q = ctx.Queue()
    procs = [
        ctx.Process(target=worker, args=(r, world, port, *extra_args, q))
        for r in range(world)
    ]
    for p in procs:
        p.start()
    try:
        results = [q.get(timeout=timeout) for _ in range(world)]
    finally:
        for p in procs:
            p.join(timeout=30)
            if p.is_alive():
                p.terminate()
                p.join(timeout=10)  # reap, no zombies until pytest exits
    bad = [r for r in results if r[1] != "ok"]
    assert not bad, bad
    return results


def test_init_multihost_real_two_process_world():
    """REAL jax.distributed rendezvous: 2 controller processes form one
    global device world and run a cross-process (DCN-story) collective.
    The strongest offline evidence for the pod path — not a mock."""
    import jax

    if tuple(map(int, jax.__version__.split(".")[:2])) < (0, 5):
        # this container's jax 0.4 CPU backend raises "Multiprocess
        # computations aren't implemented on the CPU backend" — the
        # feature needs a newer jaxlib, nothing the repo can shim
        pytest.skip("jax < 0.5: no cross-process collectives on CPU")
    _run_multihost(hostring_workers.multihost_worker, 2, timeout=180)


@pytest.mark.slow
def test_multihost_ddp_training_lockstep():
    """2-host DDP over jax.distributed: per-host batch slices assemble
    into the global batch (make_array_from_process_local_data path in
    Strategy.shard_batch); losses and params stay identical across hosts."""
    results = _run_multihost(
        hostring_workers.multihost_ddp_worker, 2, timeout=240
    )
    (r0, _, losses0, w0), (r1, _, losses1, w1) = sorted(results)
    assert losses0 == losses1, (losses0, losses1)
    assert w0 == w1  # bit-identical params across hosts
    assert losses0[-1] < losses0[0]  # and it actually learned


@pytest.mark.slow
def test_multihost_sharded_checkpoint_roundtrip(tmp_path):
    """2-host checkpoint: each process writes its own dp-shard files,
    process 0 merges+commits, restore reassembles per-host slices."""
    results = _run_multihost(
        hostring_workers.multihost_ckpt_worker, 2, str(tmp_path),
        timeout=240,
    )
    for _, _, procs_seen in results:
        assert procs_seen == [0, 1], procs_seen  # BOTH hosts wrote shards


@pytest.mark.slow
def test_multihost_trainer_full_stack(tmp_path):
    """Trainer + DataLoader + eval + metrics + checkpoint across 2
    jax.distributed controller processes — the pod path end to end with
    stock components and no recipe-code changes."""
    import json

    results = _run_multihost(
        hostring_workers.multihost_trainer_worker, 2, str(tmp_path),
    )
    (_, _, l0, s0, w0), (_, _, l1, s1, w1) = sorted(results)
    assert s0 == s1 == 32  # 8 epochs x 4 steps
    assert l0 == l1  # identical eval loss on both hosts
    assert w0 == w1  # bit-identical params
    assert l0 < 0.5  # learnable task actually learned
    # each host wrote its own metrics log; checkpoint committed once
    for r in range(2):
        recs = [
            json.loads(line)
            for line in open(tmp_path / f"metrics-p{r}.jsonl")
        ]
        assert any(rec["split"] == "eval" for rec in recs)
    assert (tmp_path / "ckpt" / "latest" / "manifest.json").exists()


@pytest.mark.slow
def test_multihost_2d_fsdp_mesh_across_4_processes():
    """dp=2 x fsdp=2 SPANNING 4 single-device hosts: params genuinely
    sharded over fsdp across processes (cross-host all-gathers inside the
    jitted step), batch sharded over dp x fsdp, two lockstep train steps,
    and every host's param-shard view assembles into ONE consistent
    global array (same loss everywhere; mirror-shard pairs identical)."""
    results = _run_multihost(hostring_workers.multihost_2d_fsdp_worker, 4)
    by_rank = {r[0]: r for r in results}
    losses = {r: by_rank[r][2] for r in by_rank}
    assert len({round(v, 6) for v in losses.values()}) == 1, losses
    # fsdp shards within a dp replica must differ (really sharded),
    # while the same fsdp coordinate across dp replicas must agree
    # exactly (replicated over dp). Mesh (2,2) row-major: processes
    # 0,1 = dp row 0 (fsdp 0,1); processes 2,3 = dp row 1.
    shard = {r: np.frombuffer(by_rank[r][3], np.float32) for r in by_rank}
    assert np.array_equal(shard[0], shard[2])
    assert np.array_equal(shard[1], shard[3])
    assert not np.array_equal(shard[0], shard[1])
