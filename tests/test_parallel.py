"""Parallelism strategy tests.

Core invariant (the whole point of SPMD): DDP / ZeRO-1 / FSDP / +TP are
*distributions* of the same math — every strategy must produce bit-comparable
training trajectories to single-device execution, while actually placing
shards where the strategy says.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest
from jax.sharding import PartitionSpec as P

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.parallel import (
    DataParallel,
    FSDP,
    PartitionRules,
    Strategy,
    ZeRO1,
    infer_tree_shardings,
    shard_along,
)
from pytorch_distributed_tpu.parallel.strategies import _augment_spec_with_axis
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import TrainState


def make_mlp_params(key, din=8, dh=16, dout=4):
    k1, k2 = jax.random.split(key)
    return {
        "dense1": {
            "kernel": jax.random.normal(k1, (din, dh)) * 0.1,
            "bias": jnp.zeros((dh,)),
        },
        "dense2": {
            "kernel": jax.random.normal(k2, (dh, dout)) * 0.1,
            "bias": jnp.zeros((dout,)),
        },
    }


def mlp_apply(params, x):
    h = jnp.tanh(x @ params["dense1"]["kernel"] + params["dense1"]["bias"])
    return h @ params["dense2"]["kernel"] + params["dense2"]["bias"]


def mse_step(state, batch):
    def loss_fn(params):
        pred = state.apply_fn(params, batch["x"])
        return jnp.mean((pred - batch["y"]) ** 2)

    loss, grads = jax.value_and_grad(loss_fn)(state.params)
    return state.apply_gradients(grads), {"loss": loss}


def make_state(tx=None):
    params = make_mlp_params(jax.random.key(0))
    return TrainState.create(
        apply_fn=mlp_apply, params=params, tx=tx or optax.adam(1e-2)
    )


def make_batches(n=4, b=16):
    rng = np.random.default_rng(0)
    return [
        {
            "x": rng.normal(size=(b, 8)).astype(np.float32),
            "y": rng.normal(size=(b, 4)).astype(np.float32),
        }
        for _ in range(n)
    ]


def run_trajectory(strategy, batches):
    state = strategy.place(make_state())
    step = strategy.compile(mse_step, state)
    losses = []
    for batch in batches:
        state, metrics = step(state, strategy.shard_batch(batch))
        losses.append(float(metrics["loss"]))
    return state, losses


TP_RULES = [
    ("dense1/kernel", P(None, "tp")),   # column parallel
    ("dense1/bias", P("tp")),
    ("dense2/kernel", P("tp", None)),   # row parallel
]


class TestShardingInference:
    def test_shard_along_largest_divisible(self, mesh8):
        spec = shard_along("tp")((8, 16), mesh8)
        assert spec == P(None, "tp")

    def test_shard_along_replicates_when_indivisible(self, mesh8):
        assert shard_along("tp")((3, 5), mesh8) == P()
        assert shard_along("tp")((), mesh8) == P()

    def test_shard_along_size1_axis(self):
        mesh = make_mesh(MeshSpec())  # all-dp mesh: tp size 1
        assert shard_along("tp")((8, 16), mesh) == P()

    def test_rules_first_match_wins(self, mesh8):
        rules = PartitionRules(
            [("kernel", P(None, "tp")), (".*", shard_along("fsdp"))]
        )
        tree = {
            "a": {"kernel": jnp.zeros((4, 8)), "bias": jnp.zeros((8,))},
        }
        sh = infer_tree_shardings(tree, rules)
        assert sh["a"]["kernel"].spec == P(None, "tp")
        assert sh["a"]["bias"].spec == P("fsdp")

    def test_extended_rules_priority(self, mesh8):
        base = PartitionRules([(".*", None)])
        ext = base.extended([("kernel", P("tp"))])
        assert ext.spec_for("x/kernel", (8,)) == P("tp")
        assert ext.spec_for("x/bias", (8,)) is None  # falls through -> replicated by caller

    def test_augment_spec(self, mesh8):
        from pytorch_distributed_tpu.runtime.mesh import current_mesh

        mesh = current_mesh()
        # (16, 8) with P(None, 'tp'): fsdp goes on dim0
        assert _augment_spec_with_axis(P(None, "tp"), "fsdp", (16, 8), mesh) == P(
            "fsdp", "tp"
        )
        # axis already used: unchanged
        assert _augment_spec_with_axis(P("fsdp"), "fsdp", (16,), mesh) == P("fsdp")
        # nothing divisible: unchanged
        assert _augment_spec_with_axis(P(), "fsdp", (3,), mesh) == P()


class TestStrategyNumerics:
    @pytest.fixture
    def reference_losses(self):
        # single-device trajectory on a 1-device mesh
        make_mesh(MeshSpec(dp=1, fsdp=1, tp=1), devices=jax.devices()[:1])
        batches = make_batches()
        state = make_state()
        losses = []
        step = jax.jit(mse_step)
        for b in batches:
            state, m = step(state, b)
            losses.append(float(m["loss"]))
        return losses, state

    @pytest.mark.parametrize(
        "strategy_fn",
        [
            lambda m: Strategy(m),
            lambda m: DataParallel(m),
            lambda m: ZeRO1(m),
            lambda m: FSDP(m),
            lambda m: FSDP(m, extra_rules=TP_RULES),
            lambda m: ZeRO1(m, extra_rules=TP_RULES),
        ],
        ids=["replicated", "ddp", "zero1", "fsdp", "fsdp+tp", "zero1+tp"],
    )
    def test_matches_single_device(self, reference_losses, strategy_fn):
        ref_losses, ref_state = reference_losses
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        state, losses = run_trajectory(strategy_fn(mesh), make_batches())
        np.testing.assert_allclose(losses, ref_losses, rtol=2e-5)
        for (pa, a), (pb, b) in zip(
            jax.tree_util.tree_leaves_with_path(state.params),
            jax.tree_util.tree_leaves_with_path(ref_state.params),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=2e-4, atol=1e-5, err_msg=str(pa)
            )

    def test_batchnorm_is_sync_batchnorm_under_dp(self):
        """torch's DDP recipes need SyncBatchNorm to normalize over the
        GLOBAL batch; under single-controller SPMD a BatchNorm mean over a
        dp-sharded batch axis IS a global mean (the compiler inserts the
        cross-replica reduction). Pin that: batch_stats after a DP step on
        a dp=8 mesh equal the single-device stats for the same global
        batch — cross-replica sync by construction, no wrapper needed."""
        from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
        from pytorch_distributed_tpu.train import (
            build_train_step,
            classification_loss_fn,
        )

        model = ResNet(
            stage_sizes=[1], block_cls=BasicBlock, num_classes=4, width=8,
            stem="cifar",
        )
        rng = np.random.default_rng(0)
        batch = {
            # batch entries are all DIFFERENT, so per-shard means differ
            # from the global mean unless the reduction is cross-replica
            "image": rng.normal(size=(16, 8, 8, 3)).astype(np.float32) * 3,
            "label": rng.integers(4, size=(16,)).astype(np.int32),
        }
        variables = model.init(
            jax.random.key(0), jnp.zeros((1, 8, 8, 3)), train=False
        )

        def mkstate():
            return TrainState.create(
                apply_fn=model.apply,
                params=variables["params"],
                tx=optax.sgd(0.1),
                batch_stats=variables["batch_stats"],
            )

        step_fn = build_train_step(classification_loss_fn(model))

        make_mesh(MeshSpec(dp=1), devices=jax.devices()[:1])
        ref, _ = jax.jit(step_fn)(mkstate(), batch)

        mesh = make_mesh(MeshSpec(dp=8))
        strategy = DataParallel(mesh)
        state = strategy.place(mkstate())
        state, _ = strategy.compile(step_fn, state)(
            state, strategy.shard_batch(batch)
        )
        for (path, a), (_, b) in zip(
            jax.tree_util.tree_leaves_with_path(state.batch_stats),
            jax.tree_util.tree_leaves_with_path(ref.batch_stats),
        ):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-5, atol=1e-6,
                err_msg=str(path),
            )

    def test_zero1_opt_state_is_sharded(self):
        mesh = make_mesh(MeshSpec(dp=4, fsdp=1, tp=2))
        state = ZeRO1(mesh).place(make_state())
        mu = state.opt_state[0].mu
        # (8,16) kernel: dp=4 divides 16 -> sharded somewhere over dp
        assert mu["dense1"]["kernel"].sharding.spec == P(None, "dp")
        # params stay replicated
        assert state.params["dense1"]["kernel"].sharding.spec == P()

    def test_fsdp_params_are_sharded(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=4, tp=1))
        state = FSDP(mesh).place(make_state())
        assert state.params["dense1"]["kernel"].sharding.spec == P(None, "fsdp")
        assert state.opt_state[0].mu["dense1"]["kernel"].sharding.spec == P(
            None, "fsdp"
        )

    def test_fsdp_tp_composition(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        state = FSDP(mesh, extra_rules=TP_RULES).place(make_state())
        # TP rule puts tp on dim1; FSDP augments dim0
        assert state.params["dense1"]["kernel"].sharding.spec == P("fsdp", "tp")
        assert state.params["dense2"]["kernel"].sharding.spec == P("tp", "fsdp")

    def test_zero1_tp_params_stay_tp_only(self):
        # regression: the dp augmentation must hit only optimizer state —
        # dp-sharded *params* would silently turn ZeRO-1 into FSDP
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        state = ZeRO1(mesh, extra_rules=TP_RULES).place(make_state())
        assert state.params["dense1"]["kernel"].sharding.spec == P(None, "tp")
        mu = state.opt_state[0].mu
        assert mu["dense1"]["kernel"].sharding.spec == P("dp", "tp")

    def test_batch_sharding_covers_data_axes(self, mesh8):
        s = DataParallel()
        assert s.batch_sharding().spec == P(("dp", "fsdp"))

    def test_donated_state_is_consumed(self, mesh8):
        strategy = DataParallel()
        state = strategy.place(make_state())
        step = strategy.compile(mse_step, state)
        batch = strategy.shard_batch(make_batches(1)[0])
        new_state, _ = step(state, batch)
        assert int(new_state.step) == 1


def test_no_sync_is_a_documented_noop():
    """torch's model.no_sync() shape: a context manager that exists, runs,
    and changes nothing (accumulation lives inside the jitted step)."""
    from pytorch_distributed_tpu.parallel import no_sync

    with no_sync():
        pass


def test_llama_partition_rules_replicate_ragged_gqa_kv():
    """ADVICE r5: a GQA model whose kv heads don't divide tp (Qwen2-7B:
    4 kv heads, tp=8) must REPLICATE k/v instead of crashing on an
    unshardable axis — and kv counts that do divide keep sharding.
    Torch-free on purpose: the HF-parity qwen2/gemma modules importorskip
    torch, and this placement logic must stay covered without it."""
    from pytorch_distributed_tpu.models.qwen2 import qwen2_partition_rules
    from pytorch_distributed_tpu.parallel.sharding import PartitionRules

    mesh = make_mesh(MeshSpec(dp=1, tp=8), set_current=False)
    rules = PartitionRules(qwen2_partition_rules())
    path = "layers/block/k/kernel"
    # Qwen2-7B-shaped stacked kernel: [L, D, 4 kv heads, hd] -> replicate
    assert rules.spec_for(path, (2, 64, 4, 16), mesh) == P(
        None, None, None, None
    )
    # unrolled layout too
    assert rules.spec_for(path, (64, 4, 16), mesh) == P(None, None, None)
    # a divisible kv count still shards
    assert rules.spec_for(path, (2, 64, 8, 16), mesh) == P(
        None, None, "tp", None
    )
    # q is untouched by the kv fallback
    assert rules.spec_for("layers/block/q/kernel", (2, 64, 8, 16), mesh) \
        == P(None, None, "tp", None)


def test_ragged_gqa_places_on_tp8_mesh():
    """End to end: a 4-kv-head model PLACES on a tp=8 mesh (the advice's
    crash repro) with q sharded and k/v replicated."""
    from pytorch_distributed_tpu.models.qwen2 import (
        Qwen2Config,
        Qwen2ForCausalLM,
        qwen2_partition_rules,
    )
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=1, tp=8))
    cfg = Qwen2Config(
        vocab_size=512, hidden_size=64, num_layers=2, num_heads=8,
        num_kv_heads=4, intermediate_size=128, max_seq_len=64,
    )
    model = Qwen2ForCausalLM(cfg)
    params = model.init(
        jax.random.key(0), jnp.zeros((1, 8), jnp.int32)
    )["params"]
    strategy = DataParallel(extra_rules=qwen2_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    block = state.params["layers"]["block"]
    assert "tp" in str(block["q"]["kernel"].sharding.spec)
    assert "tp" not in str(block["k"]["kernel"].sharding.spec)
    assert "tp" not in str(block["v"]["kernel"].sharding.spec)


def test_gemma_partition_rules_derive_from_config():
    """ADVICE r5: the rules take the CONFIG now — gemma_7b's 16 kv heads
    shard (the old =1 int default silently replicated them), gemma_2b's
    MQA replicates, and the bare call decides from the kernel shape."""
    from pytorch_distributed_tpu.models.gemma import (
        GemmaConfig,
        gemma_partition_rules,
    )
    from pytorch_distributed_tpu.parallel.sharding import PartitionRules

    mesh = make_mesh(MeshSpec(dp=1, tp=8), set_current=False)
    path = "layers/block/k/kernel"
    kv7b = (2, 3072, 16, 256)  # gemma_7b stacked k kernel
    kv2b = (2, 2048, 1, 256)   # gemma_2b (MQA)
    shard = P(None, None, "tp", None)
    repl = P(None, None, None, None)

    r7 = PartitionRules(
        gemma_partition_rules(config=GemmaConfig.gemma_7b())
    )
    assert r7.spec_for(path, kv7b, mesh) == shard
    r2 = PartitionRules(
        gemma_partition_rules(config=GemmaConfig.gemma_2b())
    )
    assert r2.spec_for(path, kv2b, mesh) == repl
    # bare call: shape-derived — BOTH variants place correctly
    rb = PartitionRules(gemma_partition_rules())
    assert rb.spec_for(path, kv7b, mesh) == shard
    assert rb.spec_for(path, kv2b, mesh) == repl
    with pytest.raises(ValueError, match="not both"):
        gemma_partition_rules(config=GemmaConfig.gemma_2b(),
                              num_kv_heads=1)
    # pre-r6 positional-int callers still mean the kv-head count
    r_old = PartitionRules(gemma_partition_rules(16))
    assert r_old.spec_for(path, kv7b, mesh) == shard
    r_mqa = PartitionRules(gemma_partition_rules(1))
    assert r_mqa.spec_for(path, kv7b, mesh) == repl  # forced MQA form
