"""Ring attention / Ulysses sequence parallelism vs single-device reference."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.attention import dot_product_attention
from pytorch_distributed_tpu.parallel.sequence import (
    disable_sequence_parallel,
    enable_sequence_parallel,
    ring_attention,
    ulysses_attention,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh


@pytest.fixture
def sp_mesh():
    """All 8 virtual devices on the sp axis."""
    return make_mesh(MeshSpec(dp=1, sp=8))


@pytest.fixture
def dp_sp_mesh():
    return make_mesh(MeshSpec(dp=2, sp=4))


def _qkv(rng, B=2, S=64, Hq=4, Hkv=2, D=16):
    q = jnp.asarray(rng.normal(size=(B, S, Hq, D)), jnp.float32)
    k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), jnp.float32)
    return q, k, v


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, sp_mesh, rng, causal):
        q, k, v = _qkv(rng)
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ring_attention(q, k, v, causal=causal, mesh=sp_mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.slow  # r5 final refit: matches_reference (both causal params) stays fast
    def test_with_dp_axis(self, dp_sp_mesh, rng):
        q, k, v = _qkv(rng)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, causal=True, mesh=dp_sp_mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_under_jit_with_grads(self, sp_mesh, rng):
        q, k, v = _qkv(rng, S=32, D=8)

        def loss_ring(q, k, v):
            return (ring_attention(q, k, v, causal=True, mesh=sp_mesh) ** 2).sum()

        def loss_ref(q, k, v):
            return (dot_product_attention(q, k, v, causal=True) ** 2).sum()

        gr = jax.jit(jax.grad(loss_ring, argnums=(0, 1, 2)))(q, k, v)
        ge = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(gr, ge):
            np.testing.assert_allclose(
                np.asarray(a), np.asarray(b), rtol=1e-4, atol=1e-4
            )

    @pytest.mark.slow  # r5 profile refit: ring matches_reference/with_dp/under_jit stay fast
    def test_mqa(self, sp_mesh, rng):
        q, k, v = _qkv(rng, Hq=4, Hkv=1)
        ref = dot_product_attention(q, k, v, causal=True)
        out = ring_attention(q, k, v, causal=True, mesh=sp_mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


class TestUlyssesAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, rng, causal):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, sp=2, tp=1))
        q, k, v = _qkv(rng, B=4, Hq=4, Hkv=2)
        ref = dot_product_attention(q, k, v, causal=causal)
        out = ulysses_attention(q, k, v, causal=causal, mesh=mesh)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_rejects_indivisible_heads(self, rng):
        mesh = make_mesh(MeshSpec(dp=1, sp=8))
        q, k, v = _qkv(rng, Hq=4, Hkv=2)  # 4 % 8 != 0
        with pytest.raises(ValueError, match="divisible"):
            ulysses_attention(q, k, v, mesh=mesh)


class TestModelTransparentSP:
    @pytest.mark.slow
    def test_llama_forward_sequence_parallel(self, rng):
        """Tiny Llama forward under sp=4: same logits as single-device."""
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig,
            LlamaForCausalLM,
        )

        make_mesh(MeshSpec(dp=2, sp=4))
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids = jnp.asarray(
            rng.integers(cfg.vocab_size, size=(2, 64)), jnp.int32
        )
        params = model.init(jax.random.key(0), ids)["params"]
        ref = model.apply({"params": params}, ids)
        enable_sequence_parallel("sp", "ring")
        try:
            out = model.apply({"params": params}, ids)
        finally:
            disable_sequence_parallel()
        # models compute in bf16 (precision policy), so the two attention
        # orderings differ by bf16 rounding; bound by bf16 eps, not f32
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=0.08, atol=0.08
        )

    @pytest.mark.slow
    def test_llama_forward_ulysses(self, rng):
        """Model-transparent ULYSSES: pins the dispatcher re-entrancy bug
        (r2: the inner attention recursed back into sequence-parallel mode
        with already-head-sharded shapes)."""
        from pytorch_distributed_tpu.models.llama import (
            LlamaConfig,
            LlamaForCausalLM,
        )

        make_mesh(MeshSpec(dp=4, sp=2))
        cfg = LlamaConfig.tiny()  # heads=4, kv=2: divisible by sp=2
        model = LlamaForCausalLM(cfg)
        ids = jnp.asarray(
            rng.integers(cfg.vocab_size, size=(4, 32)), jnp.int32
        )
        params = model.init(jax.random.key(0), ids)["params"]
        ref = model.apply({"params": params}, ids)
        enable_sequence_parallel("sp", "ulysses")
        try:
            out = model.apply({"params": params}, ids)
        finally:
            disable_sequence_parallel()
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=0.08, atol=0.08
        )

    @pytest.mark.slow  # r5 profile refit: ring/ulysses numerics tests pin SP fast; this is the dispatcher ergonomics
    def test_sequence_parallel_context_manager(self):
        from pytorch_distributed_tpu.parallel import sequence_parallel
        from pytorch_distributed_tpu.parallel.sequence import (
            sequence_parallel_mode,
        )

        assert sequence_parallel_mode()[0] is None
        with sequence_parallel("sp", "ring"):
            assert sequence_parallel_mode() == ("sp", "ring")
            with sequence_parallel("sp", "ulysses"):
                assert sequence_parallel_mode() == ("sp", "ulysses")
            assert sequence_parallel_mode() == ("sp", "ring")
        assert sequence_parallel_mode()[0] is None

    @pytest.mark.slow  # r5 profile refit: llama_forward SP/ulysses + ring numerics stay fast
    def test_mode_roundtrip(self):
        from pytorch_distributed_tpu.parallel.sequence import (
            sequence_parallel_mode,
        )

        assert sequence_parallel_mode()[0] is None
        enable_sequence_parallel("sp", "ulysses")
        assert sequence_parallel_mode() == ("sp", "ulysses")
        disable_sequence_parallel()
        assert sequence_parallel_mode()[0] is None
        with pytest.raises(ValueError):
            enable_sequence_parallel("sp", "flash-ring")


@pytest.mark.slow
def test_long_context_8k_train_step_end_to_end():
    """The long-context story, composed: a Llama train step at seq 8192
    under dp=2 x sp=4 ring attention, block rematerialization, AND the
    chunked-vocab loss — one jitted step, finite loss, grads applied.

    8K tokens would materialize an 8192^2 score matrix per head without
    ring attention; with sp=4 each shard holds 2048 queries and streams
    K/V around the ring. This is the capability the reference reaches via
    NCCL P2P ring attention implementations (SURVEY.md §5 long-context).
    (Seq is capped by CPU-test wall clock, not the mechanism — the same
    step ran at 16K in ~10 min; nothing in it is seq-quadratic in memory.)
    """
    import dataclasses

    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from pytorch_distributed_tpu.parallel import (
        DataParallel,
        sequence_parallel,
    )
    from pytorch_distributed_tpu.runtime.mesh import MeshSpec
    from pytorch_distributed_tpu.train import (
        TrainState,
        build_train_step,
        causal_lm_loss_fn,
    )

    ptd.destroy_process_group()
    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, sp=4))
    try:
        SEQ = 8192
        cfg = dataclasses.replace(
            LlamaConfig.tiny(), max_seq_len=SEQ, remat=True
        )
        model = LlamaForCausalLM(cfg)
        params = model.init(
            jax.random.key(0), jnp.zeros((1, 32), jnp.int32)
        )["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.adam(1e-3)
        )
        strategy = DataParallel()
        state = strategy.place(state)
        step = strategy.compile(
            build_train_step(
                causal_lm_loss_fn(model, vocab_chunk_size=128)
            ),
            state,
        )
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "input_ids": rng.integers(
                    cfg.vocab_size, size=(2, SEQ)
                ).astype(np.int32)
            }
        )
        # snapshot one param leaf BEFORE the step (state is donated into
        # it) so the optimizer update itself is checked — a NaN/zero
        # backward through ring+remat+chunked-loss would leave the loss
        # finite but the params unmoved or non-finite
        leaf_before = np.asarray(
            jax.tree_util.tree_leaves(state.params)[0]
        ).copy()
        with sequence_parallel("sp", "ring"):
            state, metrics = step(state, batch)
            jax.block_until_ready(state.params)
        assert np.isfinite(float(metrics["loss"]))
        leaf_after = np.asarray(jax.tree_util.tree_leaves(state.params)[0])
        assert np.all(np.isfinite(leaf_after))
        assert not np.array_equal(leaf_after, leaf_before), (
            "params did not move — zero/dead gradients"
        )
    finally:
        ptd.destroy_process_group()


class TestWindowedSequenceParallel:
    """Sliding-window attention under sequence parallelism (r5): the
    ring bands over TRUE GLOBAL positions (exact across shard
    boundaries — slot-index banding would widen/narrow the window at
    every boundary), and ulysses holds the full sequence per head
    subset so the band applies as-is. Windows chosen to CROSS shard
    boundaries: window=24 > the shard size in both meshes (ring:
    S=64 over sp=8 shards of 8; ulysses: sp=2 shards of 32 — there the
    band crosses the midpoint boundary)."""

    def test_ring_window_matches_reference(self, sp_mesh, rng):
        q, k, v = _qkv(rng)  # S=64 over sp=8 shards of 8
        w = 24  # spans three shard boundaries
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        out = ring_attention(q, k, v, causal=True, mesh=sp_mesh, window=w)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_ulysses_window_matches_reference(self, rng):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, sp=2, tp=1))
        q, k, v = _qkv(rng, B=4)  # 2 sp shards of 32; window crosses
        w = 24
        ref = dot_product_attention(q, k, v, causal=True, window=w)
        out = ulysses_attention(q, k, v, causal=True, mesh=mesh, window=w)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.slow  # model-level compose; the op-level pins run fast
    def test_mistral_forward_sequence_parallel_matches_plain(self):
        """A windowed model forwards identically under the
        model-transparent SP context — the dispatcher now routes
        window= into the sharded impls instead of refusing."""
        from pytorch_distributed_tpu.models import (
            MistralConfig,
            MistralForCausalLM,
        )
        from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh

        cfg = MistralConfig(
            vocab_size=256, hidden_size=64, num_layers=2, num_heads=4,
            num_kv_heads=2, intermediate_size=128, max_seq_len=128,
            sliding_window=24,
        )
        model = MistralForCausalLM(cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(2, 256, size=(2, 64)),
            jnp.int32,
        )
        params = model.init(jax.random.key(0), ids)["params"]
        want = model.apply({"params": params}, ids)
        make_mesh(MeshSpec(dp=2, sp=4))
        from pytorch_distributed_tpu.parallel.sequence import (
            sequence_parallel,
        )

        with sequence_parallel(axis="sp", impl="ring"):
            got = jax.jit(
                lambda p, i: model.apply({"params": p}, i)
            )(params, ids)
        # models compute in bf16 (precision policy): the ring's different
        # accumulation order moves logits by bf16 rounding, same bound
        # as the llama SP test above
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=0.08, atol=0.08
        )


class TestBiasFnSequenceParallel:
    """Position-computed bias (T5 buckets / ALiBi) under sequence
    parallelism (r5): ``bias_fn(q_pos, k_pos)`` evaluates per ring block
    from TRUE GLOBAL positions (nobody materializes the full [S, T]
    bias), and per head-subset under ulysses. Reference: the unsharded
    op with the same fn materialized over the full positions."""

    def _alibi_like(self, Hq=4):
        # position-dependent AND head-dependent (slope per head), so a
        # mis-sliced head subset or misaligned block positions both fail
        slopes = jnp.asarray([0.25 * (h + 1) for h in range(Hq)])

        def fn(q_pos, k_pos):
            rel = (k_pos[None, :] - q_pos[:, None]).astype(jnp.float32)
            return -jnp.abs(rel)[None] * slopes[:, None, None]

        return fn

    def test_ring_bias_fn_matches_reference(self, sp_mesh, rng):
        q, k, v = _qkv(rng)
        fn = self._alibi_like()
        ref = dot_product_attention(
            q, k, v, causal=True,
            bias=fn(jnp.arange(64), jnp.arange(64))[None],
        )
        out = ring_attention(
            q, k, v, causal=True, mesh=sp_mesh, bias_fn=fn
        )
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.slow  # r5 final refit: refusal semantics; ring reference stays fast
    def test_ulysses_bias_fn_refused_toward_ring(self, rng):
        # ulysses would materialize the GLOBAL-head [S, S] bias on every
        # chip before slicing — a tp*sp memory overshoot in the long-S
        # regime SP exists for; the refusal routes users to ring, which
        # evaluates per block
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, sp=2, tp=1))
        q, k, v = _qkv(rng, B=4)
        with pytest.raises(NotImplementedError, match="ring"):
            ulysses_attention(
                q, k, v, causal=True, mesh=mesh,
                bias_fn=self._alibi_like(),
            )

    @pytest.mark.slow  # r5 final refit: ring bias_fn reference + dispatcher materialization stay fast
    def test_ring_bias_fn_with_tp_head_slicing(self, rng):
        # heads sharded over tp as well: each tp shard must slice ITS
        # head subset out of the fn's global-head output
        mesh = make_mesh(MeshSpec(dp=1, sp=4, tp=2))
        q, k, v = _qkv(rng, Hq=4, Hkv=2)
        fn = self._alibi_like()
        ref = dot_product_attention(
            q, k, v, causal=True,
            bias=fn(jnp.arange(64), jnp.arange(64))[None],
        )
        out = ring_attention(q, k, v, causal=True, mesh=mesh, bias_fn=fn)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_dispatcher_materializes_bias_fn_unsharded(self, rng):
        from pytorch_distributed_tpu.ops.attention import attention

        q, k, v = _qkv(rng, S=16)
        fn = self._alibi_like()
        ref = dot_product_attention(
            q, k, v, causal=True,
            bias=fn(jnp.arange(16), jnp.arange(16))[None],
        )
        out = attention(q, k, v, causal=True, bias_fn=fn)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


@pytest.mark.slow  # model-level compose; op-level bias_fn pins run fast
def test_t5_forward_sequence_parallel_matches_plain():
    """The r5 payoff of bias_fn: a full T5 encoder-decoder forward under
    model-transparent ring SP matches the plain forward — the
    relative-position bias evaluates per ring block from true global
    positions (encoder bidirectional, decoder causal), and the
    bias-free scale=1.0 cross-attention rides the ring with S_dec
    queries against S_enc keys. Was a loud NotImplementedError from r4
    until this round."""
    from pytorch_distributed_tpu.models import (
        T5Config,
        T5ForConditionalGeneration,
    )
    from pytorch_distributed_tpu.parallel.sequence import sequence_parallel

    cfg = T5Config(
        vocab_size=256, d_model=64, d_kv=16, d_ff=128, num_layers=2,
        num_heads=4, relative_attention_num_buckets=8,
        relative_attention_max_distance=32, dropout_rate=0.0,
    )
    model = T5ForConditionalGeneration(cfg)
    rng_np = np.random.default_rng(0)
    enc = jnp.asarray(rng_np.integers(2, 256, size=(2, 64)), jnp.int32)
    dec = jnp.asarray(rng_np.integers(2, 256, size=(2, 64)), jnp.int32)
    params = model.init(jax.random.key(0), enc, dec)["params"]
    want = model.apply({"params": params}, enc, dec)
    make_mesh(MeshSpec(dp=2, sp=4))
    with sequence_parallel(axis="sp", impl="ring"):
        got = jax.jit(
            lambda p, e, d: model.apply({"params": p}, e, d)
        )(params, enc, dec)
    # bf16 compute policy: ring accumulation order differs by rounding
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(want), rtol=0.08, atol=0.08
    )
