"""Qwen2 family: HF logit parity (the QKV biases are the new surface),
export roundtrip, KV-cache decode."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import Qwen2Config, Qwen2ForCausalLM
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def _pair():
    torch.manual_seed(0)
    hf_cfg = transformers.Qwen2Config(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=1e6, rms_norm_eps=1e-5, max_position_embeddings=128,
        tie_word_embeddings=False, attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = Qwen2Config(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
        rope_theta=1e6, rms_eps=1e-5,
    )
    return hf, cfg


def test_qwen2_logits_match_hf():
    from pytorch_distributed_tpu.interop import load_qwen2_weights

    hf, cfg = _pair()
    # HF initializes q/k/v biases to zero — randomize so the bias path
    # is actually load-bearing in the parity check
    with torch.no_grad():
        for n, p in hf.named_parameters():
            if "bias" in n:
                p.normal_(0.0, 0.5)
    params = load_qwen2_weights(_sd(hf), cfg)
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 11)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = Qwen2ForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)


def test_qwen2_export_roundtrips_into_hf():
    from pytorch_distributed_tpu.interop import (
        export_qwen2_weights,
        load_qwen2_weights,
    )

    hf, cfg = _pair()
    with torch.no_grad():
        for n, p in hf.named_parameters():
            if "bias" in n:
                p.normal_(0.0, 0.5)
    params = load_qwen2_weights(_sd(hf), cfg)
    sd = export_qwen2_weights(params, cfg)
    hf2 = transformers.Qwen2ForCausalLM(hf.config).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(1).integers(2, 211, size=(1, 9)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )


@pytest.mark.slow  # the gpt2/mistral decode pins cover the machinery fast
def test_qwen2_cache_decode_equals_recompute():
    cfg = Qwen2Config.tiny()
    model = Qwen2ForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 6)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    got = ptd.generate(model, params, ids, max_new_tokens=4, temperature=0.0)
    seq = np.asarray(ids)
    for _ in range(4):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)


def test_qwen2_tied_embeddings_logits_match_hf():
    """Qwen2-0.5B-style tying: the LM head attends through the embed
    table (no lm_head leaf exists) and still matches HF exactly."""
    from pytorch_distributed_tpu.interop import load_qwen2_weights

    torch.manual_seed(1)
    hf_cfg = transformers.Qwen2Config(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=1e6, rms_norm_eps=1e-5, max_position_embeddings=128,
        tie_word_embeddings=True, attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    import dataclasses

    cfg = dataclasses.replace(
        Qwen2Config(
            vocab_size=211, hidden_size=48, intermediate_size=96,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
            rope_theta=1e6, rms_eps=1e-5,
        ),
        tie_word_embeddings=True,
    )
    params = load_qwen2_weights(_sd(hf), cfg)
    assert "lm_head" not in params  # tied: the leaf must not exist
    ids = np.random.default_rng(2).integers(2, 211, size=(2, 9)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = Qwen2ForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)


def test_tied_llama_chunked_loss_equals_full():
    """vocab_chunk_size must work on a TIED Llama body: the chunked loss
    resolves the projection from the embed table and equals the full
    [B,S,V] loss."""
    import dataclasses

    from pytorch_distributed_tpu.models import LlamaConfig, LlamaForCausalLM
    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = dataclasses.replace(
        LlamaConfig.tiny(), tie_word_embeddings=True
    )
    model = LlamaForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 12)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    assert "lm_head" not in params
    batch = {"input_ids": ids}
    full = causal_lm_loss_fn(model)(
        params, {}, batch, jax.random.key(1)
    )[0]
    chunked = causal_lm_loss_fn(model, vocab_chunk_size=128)(
        params, {}, batch, jax.random.key(1)
    )[0]
    # rtol spans XLA versions: chunking changes the logsumexp reduction
    # order, and this container's XLA:CPU lands ~4e-5 relative off the
    # full-logits path (f32-reduction noise, not a logic bug)
    np.testing.assert_allclose(
        float(full), float(chunked), rtol=2e-4, atol=2e-6
    )


def test_tied_export_roundtrips_into_hf():
    """The tied export branch (lm_head.weight emitted as the embedding,
    untransposed) must roundtrip — and a tied cfg must REFUSE a
    genuinely untied checkpoint instead of dropping its head."""
    import dataclasses

    from pytorch_distributed_tpu.interop import (
        export_qwen2_weights,
        load_qwen2_weights,
    )

    torch.manual_seed(3)
    hf_cfg = transformers.Qwen2Config(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=1e6, rms_norm_eps=1e-5, max_position_embeddings=128,
        tie_word_embeddings=True, attn_implementation="eager",
    )
    hf = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    cfg = dataclasses.replace(
        Qwen2Config(
            vocab_size=211, hidden_size=48, intermediate_size=96,
            num_layers=2, num_heads=4, num_kv_heads=2, max_seq_len=128,
            rope_theta=1e6, rms_eps=1e-5,
        ),
        tie_word_embeddings=True,
    )
    params = load_qwen2_weights(_sd(hf), cfg)
    sd = export_qwen2_weights(params, cfg)
    np.testing.assert_array_equal(
        sd["lm_head.weight"], sd["model.embed_tokens.weight"]
    )
    hf2 = transformers.Qwen2ForCausalLM(hf_cfg).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(3).integers(2, 211, size=(1, 7)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )
    # untied checkpoint + tied cfg: refused, not dropped
    torch.manual_seed(4)
    untied = transformers.Qwen2ForCausalLM(
        transformers.Qwen2Config(
            vocab_size=211, hidden_size=48, intermediate_size=96,
            num_hidden_layers=2, num_attention_heads=4,
            num_key_value_heads=2, rope_theta=1e6,
            tie_word_embeddings=False,
        )
    ).eval()
    with pytest.raises(ValueError, match="UNTIED"):
        load_qwen2_weights(_sd(untied), cfg)
