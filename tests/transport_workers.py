"""Spawn targets for the multi-host transport tests (r16).

Same contract as ``hostring_workers``: importable by ``multiprocessing``
spawn, every worker reports ``(rank, "ok")`` or ``(rank, traceback)``
through the queue, and the raw workers stay JAX-free — they exercise
``runtime/transport.py`` and ``runtime/hierarchy.py`` exactly the way a
spawned bench rank does. TCP listeners bind a parent-chosen free port
(passed as ``addr``) so two tests can't collide.
"""

import os
import socket
import sys
import traceback

import numpy as np

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def free_addr() -> str:
    """A ``host:port`` the next listener can bind: bound-then-released,
    the standard test-port idiom (the tiny reuse race is acceptable in a
    test harness; the transport would fail loudly, not wrongly)."""
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    addr = f"127.0.0.1:{s.getsockname()[1]}"
    s.close()
    return addr


def _fail(q, rank, e):
    q.put((rank, f"{type(e).__name__}: {e}\n{traceback.format_exc()}"))


def parity_worker(rank: int, world: int, name: str, q, addr: str) -> None:
    """THE transport parity matrix: every collective the shm ring
    offers, run over a ``TcpTransport``-backed group side by side with
    the native shm group on identical inputs — bit-identical results
    demanded for every (op, dtype) cell, q8 included (both transports
    fold through the one compiled ``hr_q8_dequant_add`` kernel, so this
    equality is by construction, and this worker keeps it checked)."""
    try:
        import ml_dtypes

        from pytorch_distributed_tpu.runtime.hostring import (
            HostRingGroup,
            algo_wire_bytes,
        )
        from pytorch_distributed_tpu.runtime.transport import TcpTransport

        rng = np.random.default_rng(1234 + rank)
        tcp = TcpTransport(name + "_t", rank, world, addr, slot_bytes=4096)
        with HostRingGroup(name, rank, world, slot_bytes=4096) as shm_g, \
                HostRingGroup(name + "_t", rank, world,
                              transport=tcp) as tcp_g:
            for op in ("sum", "avg", "prod", "max", "min"):
                for dt in (np.float32, np.float64):
                    x = rng.standard_normal(5000).astype(dt)
                    a = shm_g.all_reduce(x, op=op)
                    b = tcp_g.all_reduce(x, op=op)
                    assert a.tobytes() == b.tobytes(), (op, dt)
            xi = rng.integers(-100, 100, 3000).astype(np.int64)
            for op in ("sum", "max", "min", "avg"):
                a = shm_g.all_reduce(xi, op=op)
                b = tcp_g.all_reduce(xi, op=op)
                assert a.tobytes() == b.tobytes(), ("int64", op)
            # half types promote to f32 wire + round back — both paths
            for dt in (np.float16, ml_dtypes.bfloat16):
                xh = rng.standard_normal(4097).astype(dt)  # > 1 slot
                for op in ("sum", "avg"):
                    a = shm_g.all_reduce(xh, op=op)
                    b = tcp_g.all_reduce(xh, op=op)
                    assert a.tobytes() == b.tobytes(), ("half", dt, op)
            xq = (rng.standard_normal(7000) * 10).astype(np.float32)
            for op in ("sum", "avg"):
                a = shm_g.all_reduce_q8(xq, op=op)
                b = tcp_g.all_reduce_q8(xq, op=op)
                assert a.tobytes() == b.tobytes(), ("q8", op)
            xg = rng.standard_normal(333).astype(np.float32)
            assert (shm_g.all_gather(xg).tobytes()
                    == tcp_g.all_gather(xg).tobytes())
            xr = rng.standard_normal((world, 17)).astype(np.float32)
            assert (shm_g.reduce_scatter(xr).tobytes()
                    == tcp_g.reduce_scatter(xr).tobytes())
            assert (shm_g.broadcast(xg, src=1).tobytes()
                    == tcp_g.broadcast(xg, src=1).tobytes())
            if rank == 0:
                shm_g.send(xg, 2)
                tcp_g.send(xg * 2, 2)
            elif rank == 2:
                r1 = shm_g.recv(np.empty_like(xg), 0)
                r2 = tcp_g.recv(np.empty_like(xg), 0)
                assert (r1 * 2).tobytes() == r2.tobytes(), "p2p"
            shm_g.barrier()
            tcp_g.barrier()
            # the wire accounting the bench's exactness pin rests on:
            # data bytes only (the barriers above moved control tokens),
            # equal to the analytic formula on the shapes where equality
            # is promised — elems divisible by world, payload within one
            # slot (multi-chunk indivisible shapes split on chunk
            # boundaries and drift from the floored formula by a few
            # elements per chunk; the bench pins only divisible shapes)
            before = tcp.bytes_sent
            n = 256 * world  # one slot, divides evenly
            tcp_g.all_reduce(np.ones(n, np.float32), inplace=True)
            moved = tcp.bytes_sent - before
            want = algo_wire_bytes("all_reduce", n * 4, world)
            assert moved == want, (moved, want)
        q.put((rank, "ok"))
    except Exception as e:
        _fail(q, rank, e)


def hier_worker(rank: int, world: int, name: str, q, addr: str) -> None:
    """2x2 hierarchical group vs the flat ring: tcp-inter and shm-inter
    builds bit-identical to each other; hier == flat bitwise on
    integer-valued payloads (the one regime where regrouping float
    additions is exact); q8 inter leg bounded + cross-rank identical;
    inter-link byte counter exactly the H-way allreduce formula on
    leaders and zero elsewhere."""
    try:
        from pytorch_distributed_tpu.runtime.hierarchy import (
            build_hierarchical_group,
        )
        from pytorch_distributed_tpu.runtime.hostring import (
            HostRingGroup,
            algo_wire_bytes,
        )

        domains = [(0, 1), (2, 3)]
        flat = HostRingGroup(name + "_f", rank, world, slot_bytes=4096)
        hier_tcp = build_hierarchical_group(
            name + "_ht", rank, domains, inter_addr=addr, slot_bytes=4096
        )
        hier_shm = build_hierarchical_group(
            name + "_hs", rank, domains, slot_bytes=4096
        )
        with flat, hier_tcp, hier_shm:
            x = np.random.default_rng(100 + rank).standard_normal(
                5000
            ).astype(np.float32)
            ht = hier_tcp.all_reduce(x, op="avg")
            hs = hier_shm.all_reduce(x, op="avg")
            assert ht.tobytes() == hs.tobytes(), "tcp-inter != shm-inter"
            assert (hier_tcp.all_reduce(x, op="avg").tobytes()
                    == ht.tobytes()), "nondeterministic"
            rows = flat.all_gather(ht)
            assert all(rows[r].tobytes() == rows[0].tobytes()
                       for r in range(world)), "cross-rank divergence"
            for op in ("prod", "max", "min"):
                assert (hier_tcp.all_reduce(x, op=op).tobytes()
                        == hier_shm.all_reduce(x, op=op).tobytes()), op
            # integer-valued f32: regrouping is exact -> hier == flat
            xi = np.random.default_rng(200 + rank).integers(
                -1000, 1000, 4096
            ).astype(np.float32)
            assert (flat.all_reduce(xi).tobytes()
                    == hier_tcp.all_reduce(xi).tobytes()), "hier != flat"
            # q8 inter leg: deterministic, cross-rank identical, error
            # bounded vs the exact flat avg
            xq = np.random.default_rng(300 + rank).standard_normal(
                3000
            ).astype(np.float32)
            q1 = hier_tcp.all_reduce_q8(xq, op="avg")
            q2 = hier_shm.all_reduce_q8(xq, op="avg")
            assert q1.tobytes() == q2.tobytes(), "q8 inter tcp != shm"
            exact = flat.all_reduce(xq, op="avg")
            err = float(np.max(np.abs(q1 - exact)))
            assert err < 0.05, f"q8 error {err}"
            rows = flat.all_gather(q1)
            assert all(rows[r].tobytes() == rows[0].tobytes()
                       for r in range(world)), "q8 cross-rank"
            assert (hier_tcp.all_gather(x).tobytes()
                    == flat.all_gather(x).tobytes()), "all_gather"
            assert (hier_tcp.broadcast(x, src=3).tobytes()
                    == flat.broadcast(x, src=3).tobytes()), "broadcast"
            xr = np.random.default_rng(400 + rank).integers(
                -50, 50, (world, 33)
            ).astype(np.float32)
            assert (hier_tcp.reduce_scatter(xr).tobytes()
                    == flat.reduce_scatter(xr).tobytes()), "reduce_scatter"
            hier_tcp.barrier()
            before = hier_tcp.inter_bytes_sent
            n = 65536
            hier_tcp.all_reduce(np.ones(n, np.float32), inplace=True)
            moved = hier_tcp.inter_bytes_sent - before
            want = (algo_wire_bytes("all_reduce", n * 4, len(domains))
                    if hier_tcp.is_leader else 0)
            assert moved == want, (moved, want)
        q.put((rank, "ok"))
    except Exception as e:
        _fail(q, rank, e)


def link_lost_worker(rank: int, world: int, name: str, q,
                     addr: str) -> None:
    """The chaos contract for a severed inter-host link: rank 2 (a
    domain leader) arms ``transport.link_lost`` and dies at its first
    TCP exchange, the opposite leader sees the EOF cascade within one
    exchange, non-leaders hit their intra-ring deadline — everyone fails
    LOUDLY — and the survivors then re-mesh onto a fresh ring with
    re-numbered ranks (the r13 elastic recovery shape) and complete a
    collective bit-exactly."""
    import time

    try:
        from pytorch_distributed_tpu.runtime import faults
        from pytorch_distributed_tpu.runtime.hierarchy import (
            build_hierarchical_group,
        )
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        domains = [(0, 1), (2, 3)]
        g = build_hierarchical_group(
            name, rank, domains, inter_addr=addr, slot_bytes=4096,
            timeout_s=6.0,
        )
        x = np.ones(2048, np.float32) * (rank + 1)
        err = None
        try:
            if rank == 2:
                with faults.injected(
                    "transport.link_lost:mode=raise,count=1"
                ):
                    g.all_reduce(x)
            else:
                g.all_reduce(x)
        except (faults.InjectedFault, RuntimeError) as e:
            err = f"{type(e).__name__}: {e}"
        assert err is not None, "severed link did not fail loudly"
        # EVERY rank's group is now poisoned (the leaders by the TCP
        # EOF cascade, non-leaders by their intra deadline): the next
        # call must refuse INSTANTLY with the re-mesh pointer, not
        # wander back into the rings and hang
        t0 = time.monotonic()
        try:
            g.all_reduce(x)
            raise AssertionError("poisoned group accepted work")
        except RuntimeError as e:
            assert "poisoned" in str(e), e
        assert time.monotonic() - t0 < 1.0, "poison guard not instant"
        g.close()
        if rank == 2:  # the victim leaves the world
            q.put((rank, "ok"))
            return
        # survivors re-mesh: fresh ring name, ranks renumbered — exactly
        # what the elastic membership path does after a view commit
        new_rank = {0: 0, 1: 1, 3: 2}[rank]
        with HostRingGroup(name + "_v2", new_rank, 3,
                           slot_bytes=4096, timeout_s=30.0) as g2:
            # all three survivors reach this collective — the rank-2
            # early return above is the DEPARTED member, not a branch
            # ptdlint: disable=PTD001
            out = g2.all_reduce(np.ones(64, np.float32))
            assert float(out[0]) == 3.0, out[0]
        q.put((rank, "ok"))
    except Exception as e:
        _fail(q, rank, e)


def gradsync_tcp_worker(rank: int, world: int, name: str, q,
                        addr: str) -> None:
    """Verify-don't-fork: ``GradSyncEngine`` bound to a TCP-backed
    ``HostRingGroup`` produces bit-identical reduced grads to the same
    engine on the native shm ring — the overlap pipeline has no
    transport-specific branch, it routes through whatever group it is
    handed. JAX-free: ``reduce_shipped`` is the engine's numpy-level
    entry, the same one the jit callback feeds."""
    try:
        from pytorch_distributed_tpu.parallel.overlap import GradSyncEngine
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup
        from pytorch_distributed_tpu.runtime.transport import TcpTransport

        rng = np.random.default_rng(3 + rank)
        grads = [
            (rng.normal(size=(11 + i,)) * 2).astype(np.float32)
            for i in range(4)
        ]
        grads.append((rng.normal(size=(6000,)) * 2).astype(np.float32))
        qf = [False] * len(grads)
        tcp = TcpTransport(name + "_t", rank, world, addr, slot_bytes=4096)
        with HostRingGroup(name, rank, world, slot_bytes=4096) as shm_g, \
                HostRingGroup(name + "_t", rank, world,
                              transport=tcp) as tcp_g:
            e1 = GradSyncEngine(shm_g)
            e2 = GradSyncEngine(tcp_g)
            try:
                out1, _ = e1.reduce_shipped([a.copy() for a in grads], qf)
                out2, _ = e2.reduce_shipped([a.copy() for a in grads], qf)
                for a, b in zip(out1, out2):
                    assert a.tobytes() == b.tobytes(), "engine forked"
            finally:
                e1.close()
                e2.close()
        q.put((rank, "ok"))
    except Exception as e:
        _fail(q, rank, e)


def mismatch_worker(rank: int, world: int, name: str, q,
                    addr: str) -> None:
    """A TCP joiner whose parameters disagree with the mesh must be
    REJECTED at the handshake — the socket-mesh analogue of hr_init's
    segment-header validation."""
    try:
        from pytorch_distributed_tpu.runtime.transport import TcpTransport

        slot = 4096 if rank == 0 else 8192
        try:
            t = TcpTransport(name, rank, world, addr, slot_bytes=slot,
                             timeout_s=30.0)
            t.close()
            raise AssertionError("mismatched slot_bytes accepted")
        except RuntimeError as e:
            assert "slot_bytes" in str(e) or "mismatch" in str(e), e
        q.put((rank, "ok"))
    except Exception as e:
        _fail(q, rank, e)


def traced_tcp_worker(rank: int, world: int, name: str, q, addr: str,
                      trace_dir: str) -> None:
    """Armed tracing over a TCP-backed group: comm spans must carry
    ``transport="tcp"`` and the cumulative ``comm.bytes.tcp`` counter
    must track the transport's exact ``bytes_sent``."""
    try:
        from pytorch_distributed_tpu.runtime import tracing
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup
        from pytorch_distributed_tpu.runtime.transport import TcpTransport

        tracer = tracing.configure(trace_dir)
        tcp = TcpTransport(name, rank, world, addr, slot_bytes=4096)
        with HostRingGroup(name, rank, world, transport=tcp) as g:
            for _ in range(3):
                g.all_reduce(np.ones(4096, np.float32))
            moved = tcp.bytes_sent
        fname = "trace.json" if rank == 0 else f"trace-rank{rank}.json"
        tracer.export(os.path.join(trace_dir, fname))
        tracing.clear()
        q.put((rank, {"bytes_sent": moved}))
    except Exception as e:
        _fail(q, rank, e)


def rdzv_worker(wid: str, addr: str, q, kill_self: bool) -> None:
    """One elastic member over a ``tcp://`` rendezvous channel: genesis
    establish at world 3, then either die (SIGKILL — the server's
    connection lease reaps the record) or leave gracefully, and the
    survivors commit the shrunken view and reduce on its fresh ring."""
    import signal
    import time

    try:
        from pytorch_distributed_tpu.runtime.membership import (
            WorldMembership,
        )

        m = WorldMembership(addr, worker_id=wid, ring_timeout_s=5.0,
                            rendezvous_timeout_s=60.0)
        view, ring = m.establish(world_size=3)
        a = np.ones(16, np.float32) * (view.rank + 1)
        a = ring.all_reduce(a)
        q.put((wid, "v1", view.epoch, list(view.members), float(a[0])))
        if wid == "w2":
            if kill_self:
                time.sleep(1.0)  # let the queue feeder flush first
                os.kill(os.getpid(), signal.SIGKILL)
            m.leave()
            return
        deadline = time.monotonic() + 30.0
        while not m.poll_change():
            if time.monotonic() > deadline:
                raise RuntimeError("poll_change never fired")
            time.sleep(0.05)
        view, ring = m.next_view()
        a = np.ones(16, np.float32) * (view.rank + 1)
        a = ring.all_reduce(a)
        q.put((wid, "v2", view.epoch, list(view.members), float(a[0])))
        m.leave()
    except Exception as e:
        q.put((wid, "error", f"{type(e).__name__}: {e}", [], 0.0))
