"""Pipeline parallelism: GPipe schedule over ppermute (parallel/pipeline.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.parallel.pipeline import (
    merge_microbatches,
    pipeline_forward,
    split_microbatches,
    stage_sharding,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec


S, D, M, MB = 2, 8, 4, 4  # stages, width, microbatches, microbatch size


def _stage_fn(params, x):
    # one stage = one dense layer with tanh (x and y same shape)
    return jnp.tanh(x @ params["w"] + params["b"])


def _setup():
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1, pp=S))
    rng = np.random.default_rng(0)
    stacked = {
        "w": jnp.asarray(rng.normal(0, 0.5, size=(S, D, D)).astype(np.float32)),
        "b": jnp.asarray(rng.normal(0, 0.1, size=(S, D)).astype(np.float32)),
    }
    xs = jnp.asarray(rng.normal(size=(M, MB, D)).astype(np.float32))
    return stacked, xs


def _sequential(stacked, xs):
    out = xs
    for s in range(S):
        one = jax.tree_util.tree_map(lambda p: p[s], stacked)
        out = jax.vmap(lambda x: _stage_fn(one, x))(out)
    return out


def test_pipeline_matches_sequential():
    stacked, xs = _setup()
    got = pipeline_forward(_stage_fn, stacked, xs)
    want = _sequential(stacked, xs)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want), atol=1e-6)


def test_pipeline_under_jit_with_sharded_params():
    stacked, xs = _setup()
    stacked = jax.device_put(stacked, stage_sharding())

    @jax.jit
    def run(p, x):
        return pipeline_forward(_stage_fn, p, x)

    got = run(stacked, xs)
    np.testing.assert_allclose(
        np.asarray(got), np.asarray(_sequential(stacked, xs)), atol=1e-6
    )


@pytest.mark.slow
def test_pipeline_backprop_matches_sequential():
    stacked, xs = _setup()

    def loss_pp(p):
        return jnp.sum(pipeline_forward(_stage_fn, p, xs) ** 2)

    def loss_seq(p):
        return jnp.sum(_sequential(p, xs) ** 2)

    g_pp = jax.grad(loss_pp)(stacked)
    g_seq = jax.grad(loss_seq)(stacked)
    for k in stacked:
        np.testing.assert_allclose(
            np.asarray(g_pp[k]), np.asarray(g_seq[k]), atol=1e-5
        )


def test_pipeline_train_step_converges():
    """A few SGD steps through the pipeline reduce a regression loss."""
    stacked, xs = _setup()
    rng = np.random.default_rng(1)
    target = jnp.asarray(rng.normal(size=(M, MB, D)).astype(np.float32))

    @jax.jit
    def step(p):
        def loss(p):
            return jnp.mean((pipeline_forward(_stage_fn, p, xs) - target) ** 2)

        l, g = jax.value_and_grad(loss)(p)
        return jax.tree_util.tree_map(lambda a, b: a - 0.1 * b, p, g), l

    losses = []
    for _ in range(10):
        stacked, l = step(stacked)
        losses.append(float(l))
    assert losses[-1] < losses[0] * 0.9, losses


def test_stage_count_mismatch_raises():
    _setup()
    bad = {"w": jnp.zeros((S + 1, D, D)), "b": jnp.zeros((S + 1, D))}
    with pytest.raises(ValueError, match="stages"):
        pipeline_forward(_stage_fn, bad, jnp.zeros((M, MB, D)))


def test_split_merge_microbatches():
    batch = {"x": jnp.arange(24.0).reshape(12, 2)}
    split = split_microbatches(batch, 4)
    assert split["x"].shape == (4, 3, 2)
    merged = merge_microbatches(split)
    np.testing.assert_array_equal(np.asarray(merged["x"]),
                                  np.asarray(batch["x"]))
    with pytest.raises(ValueError, match="divisible"):
        split_microbatches(batch, 5)