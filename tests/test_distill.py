"""Knowledge distillation (train/losses.py distillation_loss_fn) and its
payoff: a distilled draft makes speculative decoding accept more.

The loss is pinned against its two analytic limits (alpha=1 is exactly
the hard-CE loss; student==teacher makes the KL term vanish), then the
end-to-end claim — distillation raises draft/target agreement, which IS
speculative acceptance — is demonstrated on a tiny pair.
"""

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models.gpt2 import GPT2Config, GPT2LMHead
from pytorch_distributed_tpu.parallel import DataParallel
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
    distillation_loss_fn,
)


def _pair(vocab=64, seq=16):
    ptd.init_process_group(mesh_spec=MeshSpec(dp=-1))
    tcfg = GPT2Config(
        vocab_size=vocab, n_positions=128, hidden_size=32, num_layers=2,
        num_heads=2, dropout_rate=0.0,
    )
    scfg = GPT2Config(
        vocab_size=vocab, n_positions=128, hidden_size=16, num_layers=1,
        num_heads=2, dropout_rate=0.0,
    )
    teacher, student = GPT2LMHead(tcfg), GPT2LMHead(scfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(vocab, size=(8, seq)).astype(
            np.int32
        )
    )
    tp = teacher.init(jax.random.key(0), ids)["params"]
    sp = student.init(jax.random.key(1), ids)["params"]
    return teacher, tp, student, sp, ids


def test_alpha_one_is_hard_ce():
    teacher, tp, student, sp, ids = _pair()
    batch = {"input_ids": ids}
    kd = distillation_loss_fn(student, teacher, tp, alpha=1.0)
    plain = causal_lm_loss_fn(student)
    key = jax.random.key(5)
    l1, out1 = kd(sp, None, batch, key)
    l2, out2 = plain(sp, None, batch, key)
    np.testing.assert_allclose(float(l1), float(l2), rtol=1e-6)
    assert float(out1["metrics"]["ce"]) == pytest.approx(float(l2), rel=1e-6)


def test_self_distillation_kl_is_zero():
    teacher, tp, _, _, ids = _pair()
    kd = distillation_loss_fn(teacher, teacher, tp, alpha=0.0)
    _, out = kd(tp, None, {"input_ids": ids}, jax.random.key(5))
    assert float(out["metrics"]["kl"]) < 1e-6


def test_distillation_validation():
    teacher, tp, student, sp, _ = _pair()
    with pytest.raises(ValueError, match="alpha"):
        distillation_loss_fn(student, teacher, tp, alpha=1.5)
    with pytest.raises(ValueError, match="temperature"):
        distillation_loss_fn(student, teacher, tp, temperature=0.0)


@pytest.mark.slow
def test_distilled_draft_speeds_up_speculation():
    teacher, tp, student, sp, ids = _pair()
    strategy = DataParallel()
    prompts = ids[:, :8]

    def acceptance(draft_params):
        _, stats = ptd.generate_speculative(
            teacher, tp, student, draft_params, prompts,
            max_new_tokens=12, num_draft_tokens=3, return_stats=True,
        )
        return stats["accepted"] / max(stats["drafted"], 1)

    before = acceptance(sp)

    # on-policy draft training (how serving drafts are actually built):
    # the training set is the TEACHER'S OWN continuations, so the
    # student learns the argmax behavior along real decode paths; pure
    # soft-target KD at T=1 matches the greedy acceptance criterion
    train_ids = ptd.generate(
        teacher, tp, prompts, max_new_tokens=12, temperature=0.0
    )
    state = strategy.place(TrainState.create(
        apply_fn=student.apply, params=sp, tx=optax.adam(3e-3)
    ))
    step = strategy.compile(
        build_train_step(
            distillation_loss_fn(
                student, teacher, tp, alpha=0.0, temperature=1.0
            )
        ),
        state,
    )
    batch = strategy.shard_batch({"input_ids": np.asarray(train_ids)})
    kl0 = None
    for _ in range(150):
        state, m = step(state, batch)
        # sync every step: a long unsynced chain of donated steps with
        # collectives can deadlock the in-process CPU communicator (the
        # Trainer bounds this the same way, trainer.py steps_since_sync)
        kl = float(m["kl"])
        kl0 = kl if kl0 is None else kl0
    assert kl < kl0 * 0.3  # the soft targets were learned
    after = acceptance(jax.device_get(state.params))
    # a draft that mimics the teacher gets its proposals accepted;
    # a random-init draft almost never does
    assert after > before + 0.2, (before, after)


@pytest.mark.slow  # r5 profile refit: alpha-one==CE stays fast; packing boundary math pinned in test_lm_loss
def test_packed_distillation_masks_boundaries():
    # packed semantics follow causal_lm_loss_fn: the loss over a packed
    # row equals the loss over the same tokens with the cross-document
    # and pad positions excluded — pinned by comparing against a
    # hand-masked computation
    from pytorch_distributed_tpu.data.packing import packed_loss_mask

    teacher, tp, student, sp, ids = _pair(seq=12)
    seg = jnp.asarray([[1] * 5 + [2] * 5 + [0] * 2] * ids.shape[0])
    batch = {"input_ids": ids[:, :12], "segment_ids": seg}
    kd = distillation_loss_fn(student, teacher, tp, alpha=0.3)
    loss, out = kd(sp, None, batch, jax.random.key(0))
    # the mask really removed positions: an unmasked run differs
    kd_unpacked = distillation_loss_fn(student, teacher, tp, alpha=0.3)
    loss_nomask, _ = kd_unpacked(
        sp, None, {"input_ids": ids[:, :12]}, jax.random.key(0)
    )
    assert float(loss) != pytest.approx(float(loss_nomask), rel=1e-6)
    valid = packed_loss_mask(seg)
    assert int(valid.sum()) < seg.size - seg.shape[0]  # boundaries masked


@pytest.mark.slow  # r5 profile refit: alpha-one==CE + quant decode pins stay fast
def test_distillation_from_quantized_teacher():
    # distilling FROM a deployed int8 model: the teacher slot takes any
    # .apply surface, so QuantizedModel drops in — pinned against
    # distilling from the explicitly-dequantized tree
    from pytorch_distributed_tpu.ops import QuantizedModel
    from pytorch_distributed_tpu.ops.quant import (
        dequantize_tree,
        quantize_tree_int8,
    )

    teacher, tp, student, sp, ids = _pair()
    q = quantize_tree_int8(tp, min_size=512)
    key = jax.random.key(0)
    kd_q = distillation_loss_fn(
        student, QuantizedModel(teacher), q, alpha=0.3
    )
    loss_q, out_q = kd_q(sp, None, {"input_ids": ids}, key)
    kd_deq = distillation_loss_fn(
        student, teacher, dequantize_tree(q), alpha=0.3
    )
    loss_d, out_d = kd_deq(sp, None, {"input_ids": ids}, key)
    np.testing.assert_allclose(
        float(loss_q), float(loss_d), rtol=1e-6
    )
    np.testing.assert_allclose(
        float(out_q["metrics"]["kl"]), float(out_d["metrics"]["kl"]),
        rtol=1e-5,
    )
