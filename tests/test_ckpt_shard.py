"""Sharded crash-consistent checkpoints (r17): the two-phase protocol.

Per-rank shards + per-rank COMMITs (phase 1), a WORLD_COMMIT quorum
marker written only after every rank commit verifies (phase 2), and THE
rule downstream of both: a sharded save without a WORLD_COMMIT reads as
ABSENT everywhere — checkpoint_step, restore_candidates, recovery, and
the loaders all agree a torn distributed save never happened. Restore is
re-shard aware (any world size reads any other's checkpoint), falls back
to the replication peer's copy on sole-copy loss, and walks back an
epoch when every copy of a leaf is gone.

The multi-process engine cases (save under one world, restore under
another; a rank killed mid-distributed-save) run 2-4 numpy workers with
short deadlines — tier-1 fast. The whole-world restart drill lives in
``scripts/chaos_drill.py --drill ckpt_shard``, and the bytes-per-rank
pricing in bench.py's ``ckpt_shard`` phase (both pinned by
test_bench_contract).
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pytorch_distributed_tpu.launch import ElasticWorldLauncher
from pytorch_distributed_tpu.runtime import faults
from pytorch_distributed_tpu.train import ckpt_io
from pytorch_distributed_tpu.train.elastic_world import (
    ElasticConfig,
    ElasticWorldEngine,
    leaf_owners,
    load_host_checkpoint,
    params_crc,
    reference_run,
)

pytestmark = pytest.mark.elastic

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _leaves(seed=0, n=6):
    rng = np.random.default_rng(seed)
    lv = {
        f"leaf_{i}": rng.standard_normal((8, 5)).astype(np.float32)
        for i in range(n)
    }
    lv["elastic_cursor"] = np.array([1, 2, 0, 7, 0], np.int64)
    return lv


def _write_sharded(
    ckpt_dir,
    leaves,
    *,
    step=7,
    world=3,
    replication=2,
    commit=True,
    swing=True,
):
    """The engine's save sequence, single-process: every rank's phase 1
    into ``step-<N>.tmp``, then (``commit``) the WORLD_COMMIT and
    (``swing``) the atomic rename — each switchable off to build the
    torn shapes the protocol must survive."""
    names = sorted(n for n in leaves if n != "elastic_cursor")
    tag = f"step-{step}"
    tmp = os.path.join(ckpt_dir, tag) + ".tmp"
    os.makedirs(tmp, exist_ok=True)
    for rank in range(world):
        owned = {
            n: leaves[n]
            for i, n in enumerate(names)
            if rank in leaf_owners(i, world, replication)
        }
        owned["elastic_cursor"] = leaves["elastic_cursor"]
        ckpt_io.save_rank_shards(
            tmp, rank, owned, step, world=world, replication=replication
        )
    if commit:
        ckpt_io.write_world_commit(
            tmp, step=step, world=world, replication=replication,
            expected_leaves=list(leaves),
        )
    if swing:
        ckpt_io._swing(ckpt_dir, tag, tmp)
        return os.path.join(ckpt_dir, tag)
    return tmp


def _corrupt(path):
    raw = bytearray(open(path, "rb").read())
    raw[len(raw) // 2] ^= 0xFF
    open(path, "wb").write(bytes(raw))


def _leaf_copies(final, name):
    """rank dirs holding a shard file of ``name``, rank order."""
    out = []
    for rdir in sorted(
        os.path.join(final, d) for d in os.listdir(final)
        if d.startswith("rank-")
    ):
        for f in os.listdir(rdir):
            if f.endswith(".npy") and name in f:
                out.append(os.path.join(rdir, f))
                break
    return out


# -- the happy path --------------------------------------------------------


class TestShardedRoundtrip:
    def test_save_restore_crc_and_verify(self, tmp_path):
        leaves = _leaves()
        final = _write_sharded(str(tmp_path), leaves)
        assert ckpt_io.is_sharded_checkpoint(final)
        assert ckpt_io.verify_checkpoint(str(tmp_path), "step-7") == []
        loaded = ckpt_io.load_checkpoint(final)
        assert loaded.sharded and loaded.world == 3 and loaded.step == 7
        assert loaded.peer_fetches == 0
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_step_and_tag_resolution(self, tmp_path):
        _write_sharded(str(tmp_path), _leaves(), step=7)
        assert ckpt_io.checkpoint_step(str(tmp_path), "step-7") == 7
        # the default 'latest' widens to the newest step tag
        assert ckpt_io.resolve_tag(str(tmp_path)) == "step-7"
        from pytorch_distributed_tpu.train.elastic_world import (
            host_checkpoint_exists,
        )

        assert host_checkpoint_exists(str(tmp_path))

    def test_restore_is_reader_world_agnostic(self, tmp_path):
        """A 3-rank save and a 5-rank save of the SAME state restore to
        identical leaves — nothing in the reader depends on either
        world size (the re-shard math is the OWNERSHIP map's job)."""
        leaves = _leaves()
        a = _write_sharded(
            str(tmp_path / "a"), leaves, world=3, replication=2
        )
        b = _write_sharded(
            str(tmp_path / "b"), leaves, world=5, replication=1
        )
        la, lb = ckpt_io.load_checkpoint(a), ckpt_io.load_checkpoint(b)
        assert params_crc(la.leaves) == params_crc(lb.leaves)


# -- the two-phase rule ----------------------------------------------------


class TestTwoPhaseRule:
    def test_no_world_commit_reads_as_absent(self, tmp_path):
        """Rank dirs without a WORLD_COMMIT in final position: every
        reader agrees the save never happened."""
        final = _write_sharded(str(tmp_path), _leaves())
        os.remove(os.path.join(final, ckpt_io._WORLD_COMMIT))
        assert ckpt_io.checkpoint_step(str(tmp_path), "step-7") is None
        assert ckpt_io.resolve_tag(str(tmp_path)) is None
        assert ckpt_io.restore_candidates(str(tmp_path)) == []
        problems = ckpt_io.verify_checkpoint(str(tmp_path), "step-7")
        assert any("WORLD_COMMIT" in p for p in problems)
        with pytest.raises(ckpt_io.CheckpointCorrupted, match="absent"):
            ckpt_io.load_checkpoint(final)

    def test_world_commit_refuses_missing_rank_commit(self, tmp_path):
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        os.remove(os.path.join(tmp, "rank-1", ckpt_io._COMMIT))
        with pytest.raises(
            ckpt_io.CheckpointCorrupted, match="no COMMIT"
        ):
            ckpt_io.write_world_commit(
                tmp, step=7, world=3, replication=2
            )
        assert not os.path.exists(
            os.path.join(tmp, ckpt_io._WORLD_COMMIT)
        )

    def test_world_commit_refuses_tampered_manifest(self, tmp_path):
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        man = os.path.join(tmp, "rank-0", ckpt_io._MANIFEST)
        with open(man, "a") as f:
            f.write(" ")
        with pytest.raises(
            ckpt_io.CheckpointCorrupted, match="does not match"
        ):
            ckpt_io.write_world_commit(
                tmp, step=7, world=3, replication=2
            )

    def test_world_commit_refuses_mixed_step(self, tmp_path):
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        ckpt_io.save_rank_shards(
            tmp, 1, {"leaf_1": np.ones(3, np.float32)}, 9,
            world=3, replication=2,
        )
        with pytest.raises(
            ckpt_io.CheckpointCorrupted, match="mixed-step"
        ):
            ckpt_io.write_world_commit(
                tmp, step=7, world=3, replication=2
            )

    def test_world_commit_refuses_dropped_leaf(self, tmp_path):
        """expected_leaves is the ownership-map audit: a leaf no rank
        committed fails the save instead of silently vanishing."""
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        with pytest.raises(
            ckpt_io.CheckpointCorrupted, match="no rank committed"
        ):
            ckpt_io.write_world_commit(
                tmp, step=7, world=3, replication=2,
                expected_leaves=["leaf_0", "leaf_ghost"],
            )


# -- copy loss: peer fallback and epoch walk-back --------------------------


class TestCopyLoss:
    def test_sole_copy_loss_restores_from_peer(self, tmp_path):
        leaves = _leaves()
        final = _write_sharded(str(tmp_path), leaves, replication=2)
        copies = _leaf_copies(final, "leaf_2")
        assert len(copies) == 2  # replication really put two on disk
        _corrupt(copies[0])  # the primary copy rots
        loaded = ckpt_io.load_checkpoint(final)
        assert loaded.peer_fetches == 1
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_missing_primary_file_also_falls_back(self, tmp_path):
        leaves = _leaves()
        final = _write_sharded(str(tmp_path), leaves, replication=2)
        os.remove(_leaf_copies(final, "leaf_3")[0])
        loaded = ckpt_io.load_checkpoint(final)
        assert loaded.peer_fetches == 1
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_both_copies_lost_walks_back_an_epoch(self, tmp_path):
        old = _leaves(seed=1)
        _write_sharded(str(tmp_path), old, step=3)
        final = _write_sharded(str(tmp_path), _leaves(seed=2), step=7)
        for p in _leaf_copies(final, "leaf_4"):
            _corrupt(p)
        with pytest.raises(
            ckpt_io.CheckpointCorrupted, match="copies failed"
        ):
            ckpt_io.load_checkpoint(final)
        loaded = ckpt_io.load_best_checkpoint(str(tmp_path))
        assert loaded.tag == "step-3" and loaded.walked_back == 1
        assert params_crc(loaded.leaves) == params_crc(old)

    def test_peer_fetch_fault_is_the_both_lost_drill(self, tmp_path):
        """``ckpt.peer_fetch`` mode=raise makes the peer copy unreadable
        too — the injected both-copies-lost case drives the same epoch
        walk-back the organic one does."""
        old = _leaves(seed=1)
        _write_sharded(str(tmp_path), old, step=3)
        final = _write_sharded(str(tmp_path), _leaves(seed=2), step=7)
        _corrupt(_leaf_copies(final, "leaf_1")[0])
        with faults.injected("ckpt.peer_fetch"):
            loaded = ckpt_io.load_best_checkpoint(str(tmp_path))
        assert loaded.tag == "step-3" and loaded.walked_back == 1
        assert params_crc(loaded.leaves) == params_crc(old)

    def test_read_shard_fault_drives_peer_fallback(self, tmp_path):
        """The r2 ``ckpt.read_shard`` site now exercises the replication
        fallback: an injected primary-read failure restores from the
        peer instead of failing the checkpoint."""
        leaves = _leaves()
        final = _write_sharded(str(tmp_path), leaves, replication=2)
        primary = os.path.basename(_leaf_copies(final, "leaf_0")[0])
        with faults.injected(
            f"ckpt.read_shard:count=1,match={primary}"
        ):
            loaded = ckpt_io.load_checkpoint(final)
        assert loaded.peer_fetches == 1
        assert params_crc(loaded.leaves) == params_crc(leaves)


# -- fault sites (satellite: KNOWN_SITES + torn shapes) --------------------


class TestFaultSites:
    def test_sites_registered(self):
        for site in (
            "ckpt.rank_commit", "ckpt.world_commit", "ckpt.peer_fetch"
        ):
            assert site in faults.KNOWN_SITES

    def test_rank_commit_fault_leaves_save_torn(self, tmp_path):
        tmp = str(tmp_path / "step-7.tmp")
        os.makedirs(tmp)
        with faults.injected("ckpt.rank_commit:count=1"):
            with pytest.raises(faults.InjectedFault):
                ckpt_io.save_rank_shards(
                    tmp, 0, _leaves(), 7, world=1, replication=1
                )
        rdir = os.path.join(tmp, "rank-0")
        assert os.path.exists(os.path.join(rdir, ckpt_io._MANIFEST))
        assert not os.path.exists(os.path.join(rdir, ckpt_io._COMMIT))
        # phase 2 refuses the torn rank — the protocol, not luck
        with pytest.raises(ckpt_io.CheckpointCorrupted):
            ckpt_io.write_world_commit(
                tmp, step=7, world=1, replication=1
            )

    def test_world_commit_fault_leaves_no_marker(self, tmp_path):
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        with faults.injected("ckpt.world_commit:count=1"):
            with pytest.raises(faults.InjectedFault):
                ckpt_io.write_world_commit(
                    tmp, step=7, world=3, replication=2
                )
        assert not os.path.exists(
            os.path.join(tmp, ckpt_io._WORLD_COMMIT)
        )

    def test_ptd003_covers_the_new_sites(self):
        """The registry lint (PTD003) checks the three r17 sites like
        any other: a typo'd literal is loud, the real names are clean."""
        from pytorch_distributed_tpu.analysis.core import ParsedModule
        from pytorch_distributed_tpu.analysis.rules import (
            FaultSiteRegistry,
        )

        def lint(src):
            rel = "pytorch_distributed_tpu/mod.py"
            module = ParsedModule("/" + rel, rel, src)
            rule = FaultSiteRegistry()
            assert rule.applies_to(module)
            return [
                f for f in rule.check(module)
                if not module.is_suppressed(f)
            ]

        src = (
            "from pytorch_distributed_tpu.runtime import faults\n"
            "def f(p):\n"
            "    faults.check('ckpt.rank_commit', path=p)\n"
            "    faults.check('ckpt.world_commit', path=p)\n"
            "    faults.check('ckpt.peer_fetch', path=p)\n"
        )
        assert lint(src) == []
        bad = src.replace("ckpt.rank_commit", "ckpt.rank_comit")
        assert [f.rule_id for f in lint(bad)] == ["PTD003"]


# -- recovery and prune (satellite) ----------------------------------------


class TestRecoverAndPrune:
    def test_world_complete_tmp_finishes_its_swing(self, tmp_path):
        leaves = _leaves()
        tmp = _write_sharded(str(tmp_path), leaves, swing=False)
        assert tmp.endswith(".tmp")
        recovered = ckpt_io.recover_stranded_checkpoints(str(tmp_path))
        assert recovered == ["step-7"]
        loaded = ckpt_io.load_best_checkpoint(str(tmp_path))
        assert loaded.step == 7
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_world_incomplete_tmp_is_garbage_collected(self, tmp_path):
        tmp = _write_sharded(
            str(tmp_path), _leaves(), commit=False, swing=False
        )
        recovered = ckpt_io.recover_stranded_checkpoints(str(tmp_path))
        assert recovered == []  # GC is not a recovery
        assert not os.path.exists(tmp)
        assert ckpt_io.load_best_checkpoint(str(tmp_path)) is None

    def test_prune_keeps_the_newest_epochs(self, tmp_path):
        for step in (3, 7, 11):
            _write_sharded(str(tmp_path), _leaves(seed=step), step=step)
        ckpt_io.prune_checkpoints(str(tmp_path), keep=2)
        assert ckpt_io.step_tags(str(tmp_path)) == [7, 11]

    def test_prune_spares_the_only_world_complete_epoch(self, tmp_path):
        """step-3 is world-complete, step-7 is torn (no WORLD_COMMIT):
        prune(keep=1) would keep only the unrestorable step-7 — the
        safety rule spares step-3 instead of leaving the run bare."""
        leaves = _leaves(seed=1)
        _write_sharded(str(tmp_path), leaves, step=3)
        final7 = _write_sharded(str(tmp_path), _leaves(seed=2), step=7)
        os.remove(os.path.join(final7, ckpt_io._WORLD_COMMIT))
        ckpt_io.prune_checkpoints(str(tmp_path), keep=1)
        loaded = ckpt_io.load_best_checkpoint(str(tmp_path))
        assert loaded.tag == "step-3"
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_prune_sweeps_orphaned_tmps(self, tmp_path):
        _write_sharded(str(tmp_path), _leaves(), step=7)
        stale = _write_sharded(
            str(tmp_path), _leaves(seed=3), step=5,
            commit=False, swing=False,
        )
        ckpt_io.prune_checkpoints(str(tmp_path), keep=2)
        assert not os.path.exists(stale)
        assert ckpt_io.step_tags(str(tmp_path)) == [7]


# -- multi-shard leaves (satellite: past the len(shards) != 1 refusal) -----


class TestMultiShardLeaves:
    def test_single_dir_chunked_roundtrip(self, tmp_path):
        leaves = _leaves()
        ckpt_io.save_single_checkpoint(
            str(tmp_path), leaves, 7, chunk_rows=3
        )
        manifest = ckpt_io._read_manifest(str(tmp_path / "latest"))
        counts = {
            e["path"]: len(e["shards"]) for e in manifest["leaves"]
        }
        assert counts["leaf_0"] == 3  # 8 rows in chunks of 3
        assert ckpt_io.verify_checkpoint(str(tmp_path)) == []
        loaded = ckpt_io.load_checkpoint(str(tmp_path / "latest"))
        assert params_crc(loaded.leaves) == params_crc(leaves)

    def test_load_host_checkpoint_assembles_multi_shard(self, tmp_path):
        """The r13 loader refused any leaf with more than one shard;
        it now assembles through the same ``_assemble`` the jax restore
        uses."""
        leaves = _leaves()
        ckpt_io.save_single_checkpoint(
            str(tmp_path), leaves, 7, chunk_rows=3
        )
        back, step = load_host_checkpoint(str(tmp_path))
        assert step == 7
        for k in leaves:
            np.testing.assert_array_equal(back[k], leaves[k])

    def test_loader_is_jax_free(self, tmp_path):
        """``ckpt_io``'s module graph must not need jax — a restore tool
        on a machine with no accelerator stack reads any checkpoint.
        A fresh interpreter BLOCKS jax imports outright, loads ckpt_io
        with the package ``__init__``s bypassed (they eagerly import the
        jax-backed layers), and round-trips a multi-shard-leaf save AND
        a sharded save."""
        script = (
            "import importlib, os, sys, types\n"
            "class _NoJax:\n"
            "    def find_spec(self, name, *a, **k):\n"
            "        if name == 'jax' or name.startswith('jax.'):\n"
            "            raise ImportError('jax is blocked')\n"
            "        return None\n"
            "sys.meta_path.insert(0, _NoJax())\n"
            "root = os.path.join(sys.argv[2],"
            " 'pytorch_distributed_tpu')\n"
            "for sub in ('', '.runtime', '.utils', '.train'):\n"
            "    name = 'pytorch_distributed_tpu' + sub\n"
            "    pkg = types.ModuleType(name)\n"
            "    pkg.__path__ = [os.path.join(root, *sub.split('.'))]\n"
            "    sys.modules[name] = pkg\n"
            "import numpy as np\n"
            "from pytorch_distributed_tpu.train import ckpt_io\n"
            "lv = {'a': np.arange(24, dtype=np.float32).reshape(8, 3),\n"
            "      'b': np.ones(5, np.float32)}\n"
            "ckpt_io.save_single_checkpoint(sys.argv[1], lv, 3,"
            " chunk_rows=3)\n"
            "back = ckpt_io.load_checkpoint(sys.argv[1] + '/latest')\n"
            "assert back.step == 3\n"
            "np.testing.assert_array_equal(back.leaves['a'], lv['a'])\n"
            "tmp = sys.argv[1] + '/step-5.tmp'\n"
            "import os; os.makedirs(tmp)\n"
            "ckpt_io.save_rank_shards(tmp, 0, lv, 5, world=1,"
            " replication=1)\n"
            "ckpt_io.write_world_commit(tmp, step=5, world=1,"
            " replication=1)\n"
            "ckpt_io._swing(sys.argv[1], 'step-5', tmp)\n"
            "sh = ckpt_io.load_checkpoint(sys.argv[1] + '/step-5')\n"
            "assert sh.sharded and sh.step == 5\n"
            "assert 'jax' not in sys.modules, 'loader pulled in jax'\n"
            "print('JAXFREE-OK')\n"
        )
        env = dict(os.environ)
        env.pop("XLA_FLAGS", None)
        proc = subprocess.run(
            [sys.executable, "-c", script, str(tmp_path), REPO],
            cwd=REPO, env=env, capture_output=True, text=True,
            timeout=60,
        )
        assert proc.returncode == 0, proc.stderr[-2000:]
        assert "JAXFREE-OK" in proc.stdout


# -- the engine: solo sharded saves + the audit trail ----------------------


class TestEngineSharded:
    def test_solo_sharded_resume_is_bit_exact(self, tmp_path):
        full = reference_run(ElasticConfig(total_steps=10))
        eng = ElasticWorldEngine(ElasticConfig(
            total_steps=6, ckpt_dir=str(tmp_path), ckpt_every=3,
        ))
        eng.start()
        r1 = eng.run()
        assert r1["ckpt"]["format"] == "sharded"
        assert r1["ckpt"]["saves"] >= 3  # genesis + step-3 + step-6
        # step-tagged dirs, each sealed by a WORLD_COMMIT
        tags = ckpt_io.step_tags(str(tmp_path))
        assert 6 in tags
        assert os.path.exists(
            os.path.join(tmp_path, "step-6", ckpt_io._WORLD_COMMIT)
        )
        eng2 = ElasticWorldEngine(ElasticConfig(
            total_steps=10, ckpt_dir=str(tmp_path), ckpt_every=0,
        ))
        eng2.start()
        assert eng2.step == 6
        res = eng2.run()
        assert res["params_crc"] == full["params_crc"]
        assert res["ckpt"]["restores"] == 1
        assert res["ckpt"]["walked_back"] == 0

    def test_full_format_is_the_pre_r17_baseline(self, tmp_path):
        full = reference_run(ElasticConfig(total_steps=10))
        eng = ElasticWorldEngine(ElasticConfig(
            total_steps=6, ckpt_dir=str(tmp_path), ckpt_every=3,
            ckpt_format="full",
        ))
        eng.start()
        eng.run()
        # the full format writes the single-dir 'latest' shape
        assert os.path.exists(
            os.path.join(tmp_path, "latest", ckpt_io._MANIFEST)
        )
        assert not ckpt_io.step_tags(str(tmp_path))
        eng2 = ElasticWorldEngine(ElasticConfig(
            total_steps=10, ckpt_dir=str(tmp_path), ckpt_every=0,
            ckpt_format="full",
        ))
        eng2.start()
        assert eng2.step == 6
        assert eng2.run()["params_crc"] == full["params_crc"]

    def test_prune_keeps_ckpt_keep_epochs(self, tmp_path):
        eng = ElasticWorldEngine(ElasticConfig(
            total_steps=12, ckpt_dir=str(tmp_path), ckpt_every=3,
        ))
        eng.start()
        eng.run()
        # saves at 0/3/6/9/12: keep=2 leaves the two newest epochs
        assert ckpt_io.step_tags(str(tmp_path)) == [9, 12]

    def test_config_validation(self):
        with pytest.raises(ValueError, match="ckpt_format"):
            ElasticConfig(ckpt_format="zip")
        with pytest.raises(ValueError, match="ckpt_keep"):
            ElasticConfig(ckpt_keep=0)

    def test_sole_copy_loss_on_disk_restores_via_peer(self, tmp_path):
        """Engine-level peer fallback: corrupt ONE copy of one leaf in
        the newest epoch — the restore pulls the replication peer's
        copy, counts it, and lands on the same bits."""
        full = reference_run(ElasticConfig(total_steps=10))
        eng = ElasticWorldEngine(ElasticConfig(
            total_steps=6, ckpt_dir=str(tmp_path), ckpt_every=3,
        ))
        eng.start()
        eng.run()
        # solo world => replication clamps to 1; rewrite the newest
        # epoch as a 2-rank replication-2 save of the SAME leaves so a
        # single corrupted copy is repairable
        loaded = ckpt_io.load_best_checkpoint(str(tmp_path))
        import shutil as _sh

        _sh.rmtree(os.path.join(tmp_path, loaded.tag))
        final = _write_sharded(
            str(tmp_path), loaded.leaves, step=loaded.step,
            world=2, replication=2,
        )
        _corrupt(_leaf_copies(final, "params_w1")[0])
        eng2 = ElasticWorldEngine(ElasticConfig(
            total_steps=10, ckpt_dir=str(tmp_path), ckpt_every=0,
        ))
        eng2.start()
        assert eng2.step == loaded.step
        res = eng2.run()
        assert res["params_crc"] == full["params_crc"]
        assert res["ckpt"]["peer_fetches"] == 1


# -- the engine: multi-process re-shard restore + mid-save kill ------------


def _launcher(tmp_path, sub, **overrides):
    defaults = {
        "--total-steps": "12",
        "--global-batch": "16",
        "--microshards": "4",
        "--ckpt-dir": str(tmp_path / "ckpt"),
        "--ckpt-every": "4",
        "--ring-timeout-s": "2.0",
        "--metrics-path": str(tmp_path / f"metrics_{sub}.jsonl"),
    }
    defaults.update(overrides)
    args = []
    for k, v in defaults.items():
        if v is not None:
            args += [k, str(v)]
    return ElasticWorldLauncher(
        str(tmp_path / f"rdv_{sub}"), worker_args=args
    )


def _run_world(tmp_path, sub, n, **overrides):
    launcher = _launcher(tmp_path, sub, **overrides)
    ids = [f"{sub}{i}" for i in range(n)]
    launcher.start_world(ids)
    codes = launcher.wait(120)
    assert all(codes[w] == 0 for w in ids), codes
    return launcher.results()


class TestReShardRestore:
    def test_shrink_and_grow_restore_bit_exact(self, tmp_path):
        """A 3-rank sharded save restored into worlds of 2 AND 4: every
        reader finishes bit-identical to the solo reference — the
        re-shard restore really is world-agnostic."""
        ref = reference_run(ElasticConfig(total_steps=12))
        # the writer world: 3 ranks to step 6, checkpointing at 4
        res_w = _run_world(
            tmp_path, "w", 3, **{
                "--total-steps": "6", "--ckpt-every": "4",
                "--replication": "2",
            }
        )
        assert all(
            r["ckpt"]["format"] == "sharded" for r in res_w.values()
        )
        tags = ckpt_io.step_tags(str(tmp_path / "ckpt"))
        assert 6 in tags  # the run-completion save
        for sub, n in (("s", 2), ("g", 4)):
            res = _run_world(
                tmp_path, sub, n, **{
                    "--total-steps": "12", "--ckpt-every": "0",
                    "--replication": "2",
                }
            )
            for wid, r in res.items():
                assert r["final_step"] == 12, (sub, r)
                assert r["params_crc"] == ref["params_crc"], (sub, wid)
                assert r["ckpt"]["restores"] == 1, (sub, r)

    def test_mid_save_kill_resizes_and_finishes(self, tmp_path):
        """One rank dies BETWEEN its shard files and its per-rank COMMIT
        (the canonical torn distributed save): survivors hit the save
        barrier's deadline, resize in-process, and finish bit-identical
        to the reference; the torn tmp never becomes restorable."""
        ref = reference_run(ElasticConfig(total_steps=12))
        launcher = _launcher(
            tmp_path, "k", **{
                "--total-steps": "12", "--ckpt-every": "4",
                "--replication": "2", "--step-delay-s": "0.05",
            }
        )
        ids = ["k0", "k1", "k2"]
        launcher.start_world(ids, env_overrides={"k2": {
            # hit 1 is the genesis save; fire on hit 2 = the step-4 save
            "PTD_FAULTS": "ckpt.rank_commit:mode=kill,count=1,after=1",
        }})
        codes = launcher.wait(120)
        results = launcher.results()
        assert codes["k2"] not in (0, None)
        for wid in ("k0", "k1"):
            assert codes[wid] == 0, codes
            assert results[wid]["final_step"] == 12
            assert results[wid]["params_crc"] == ref["params_crc"]
            assert any(
                v["world_size"] == 2
                for v in results[wid]["views"]
            )
        # the step-4 epoch died torn; whatever later epochs the shrunken
        # world wrote are world-complete — and step-4 reads as absent
        ckpt_dir = str(tmp_path / "ckpt")
        assert ckpt_io.checkpoint_step(ckpt_dir, "step-4") is None
        newest = ckpt_io.resolve_tag(ckpt_dir)
        assert newest is not None
        assert ckpt_io.verify_checkpoint(ckpt_dir, newest) == []


# -- observability: the ckpt audit trail ----------------------------------


class TestCkptObservability:
    def _section(self, events, records):
        sys.path.insert(0, os.path.join(REPO, "scripts"))
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        out = io.StringIO()
        summary = obs_report.checkpoint_section(events, records, out)
        return summary, out.getvalue()

    def test_section_renders_saves_and_restores(self):
        records = [
            {"split": "ckpt", "step": 0, "event": "save",
             "format": "sharded", "tag": "step-0", "world": 3,
             "replication": 2, "rank_bytes": 1000,
             "total_bytes": 3000},
            {"split": "ckpt", "step": 8, "event": "restore",
             "tag": "step-8", "ckpt_world": 3, "sharded": True,
             "peer_fetches": 1, "walked_back": 1,
             "recovered": ["step-4"], "restored_step": 8},
        ]
        summary, text = self._section([], records)
        assert summary["saves"] == 1 and summary["restores"] == 1
        assert summary["peer_fetches"] == 1
        assert summary["walked_back"] == 1
        assert "== Checkpoint ==" in text
        assert "step-0" in text and "repl 2" in text
        assert "replication peer" in text  # the sole-copy-loss flag
        assert "INVESTIGATE" in text      # the walk-back flag
        assert "recovered ['step-4']" in text

    def test_section_reports_per_rank_save_walls(self):
        events = [
            {"ph": "X", "name": "elastic.checkpoint", "pid": r,
             "dur": 1000.0 * (r + 1)}
            for r in range(3)
        ]
        summary, text = self._section(events, [])
        assert summary["save_wall_skew"] == pytest.approx(3.0)
        assert "save-wall skew" in text

    def test_section_absent_without_input(self):
        summary, text = self._section([], [{"split": "progress"}])
        assert summary is None and text == ""

    def test_engine_writes_the_audit_records(self, tmp_path):
        metrics = str(tmp_path / "m.jsonl")
        eng = ElasticWorldEngine(ElasticConfig(
            total_steps=4, ckpt_dir=str(tmp_path / "ckpt"),
            ckpt_every=2, metrics_path=metrics,
        ))
        eng.start()
        eng.run()
        eng2 = ElasticWorldEngine(ElasticConfig(
            total_steps=6, ckpt_dir=str(tmp_path / "ckpt"),
            ckpt_every=0, metrics_path=metrics,
        ))
        eng2.start()
        eng2.run()
        recs = [
            json.loads(line)
            for line in open(metrics)
            if line.strip()
        ]
        saves = [
            r for r in recs
            if r.get("split") == "ckpt" and r.get("event") == "save"
        ]
        restores = [
            r for r in recs
            if r.get("split") == "ckpt" and r.get("event") == "restore"
        ]
        assert saves and all(
            s["format"] == "sharded" and "rank_bytes" in s
            for s in saves
        )
        assert len(restores) == 1
        assert restores[0]["restored_step"] == 4
        assert restores[0]["walked_back"] == 0
