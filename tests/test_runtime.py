"""Core runtime tests: mesh construction, collectives facade, precision, PRNG."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import pytorch_distributed_tpu as ptd
from jax.sharding import PartitionSpec as P
from pytorch_distributed_tpu.runtime.mesh import AXES, MeshSpec, make_mesh


class TestMesh:
    def test_eight_cpu_devices(self):
        assert jax.device_count() == 8
        assert ptd.platform() == "cpu"

    def test_default_spec_all_dp(self):
        mesh = make_mesh()
        assert mesh.shape["dp"] == 8
        assert all(mesh.shape[a] == 1 for a in AXES if a != "dp")

    def test_wildcard_resolution(self):
        spec = MeshSpec(dp=-1, tp=4).resolve(8)
        assert spec.dp == 2 and spec.tp == 4

    def test_explicit_shape(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        assert mesh.shape["dp"] == 2
        assert mesh.shape["fsdp"] == 2
        assert mesh.shape["tp"] == 2

    def test_bad_shape_raises(self):
        with pytest.raises(ValueError):
            MeshSpec(dp=3, tp=3).resolve(8)
        with pytest.raises(ValueError):
            MeshSpec(dp=-1, fsdp=-1).resolve(8)

    def test_current_mesh_roundtrip(self):
        mesh = make_mesh(MeshSpec(dp=4, tp=2))
        assert ptd.current_mesh() is mesh
        assert ptd.mesh_axis_size("tp") == 2


class TestProcessGroupFacade:
    def test_init_defaults_cpu_backend(self):
        g = ptd.init_process_group()
        assert g.backend == "cpu"
        assert ptd.get_world_size() == 8
        assert ptd.get_rank() == 0
        assert ptd.is_initialized()

    def test_ici_requires_tpu(self):
        with pytest.raises(RuntimeError):
            ptd.init_process_group("ici")

    def test_world_size_restriction(self):
        g = ptd.init_process_group(world_size=4)
        assert g.size == 4

    def test_all_reduce_sum(self):
        ptd.init_process_group()
        x = np.arange(8, dtype=np.float32).reshape(8, 1) + 1.0
        out = ptd.all_reduce(x)
        np.testing.assert_allclose(np.asarray(out), [36.0])

    def test_flat_tensor_collective_variants(self):
        """torch>=1.13 all_gather_into_tensor (concat, not stack) and
        reduce_scatter_tensor under single-controller SPMD."""
        ptd.init_process_group()
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        flat = np.asarray(ptd.all_gather_into_tensor(x))
        assert flat.shape == (16,)  # 8 participants x 2 elems concatenated
        np.testing.assert_array_equal(flat, np.arange(16, dtype=np.float32))
        rs = ptd.reduce_scatter_tensor(np.ones((8, 8), np.float32))
        assert np.asarray(rs).shape == (8,)
        np.testing.assert_array_equal(np.asarray(rs), np.full(8, 8.0))

    def test_new_group_subset_collectives(self):
        """torch.distributed.new_group: collectives over a rank subset
        (single-controller semantics: member rows of the participant dim)."""
        ptd.init_process_group()
        g = ptd.new_group([1, 3, 5])
        assert g.size == 3
        x = np.arange(8, dtype=np.float32).reshape(8, 1) + 1.0
        np.testing.assert_allclose(
            np.asarray(ptd.all_reduce(x, group=g)), [2.0 + 4.0 + 6.0]
        )
        np.testing.assert_allclose(
            np.asarray(
                ptd.all_reduce(x, ptd.ReduceOp.MAX, group=g)
            ), [6.0],
        )
        gathered = ptd.all_gather(x, group=g)
        np.testing.assert_allclose(
            np.asarray(gathered), [[2.0], [4.0], [6.0]]
        )
        np.testing.assert_allclose(
            np.asarray(ptd.broadcast(x, src=3, group=g)), [4.0]
        )
        ptd.barrier(group=g)  # trivially synchronized, must not raise
        # torch-shaped wrappers forward the group too
        np.testing.assert_allclose(
            np.asarray(ptd.reduce(x, dst=1, group=g)), [12.0]
        )
        np.testing.assert_allclose(
            np.asarray(ptd.gather(x, dst=3, group=g)),
            [[2.0], [4.0], [6.0]],
        )
        with pytest.raises(ValueError, match="not in group"):
            ptd.broadcast(x, src=0, group=g)
        with pytest.raises(ValueError, match="out of range"):
            ptd.new_group([0, 99])
        with pytest.raises(ValueError, match="at least one"):
            ptd.new_group([])
        with pytest.raises(ValueError, match="unique"):
            ptd.new_group([0, 0, 1])
        with pytest.raises(ValueError, match="mutually exclusive"):
            ptd.all_reduce(x, axis="dp", group=g)

    def test_all_reduce_ops(self):
        ptd.init_process_group()
        x = np.arange(1, 9, dtype=np.float32).reshape(8, 1)
        assert np.asarray(ptd.all_reduce(x, ptd.ReduceOp.AVG))[0] == pytest.approx(4.5)
        assert np.asarray(ptd.all_reduce(x, ptd.ReduceOp.MAX))[0] == 8.0
        assert np.asarray(ptd.all_reduce(x, ptd.ReduceOp.MIN))[0] == 1.0
        x2 = np.full((8, 1), 2.0, np.float32)
        assert np.asarray(ptd.all_reduce(x2, ptd.ReduceOp.PRODUCT))[0] == 256.0

    def test_all_reduce_matrix_payload(self):
        ptd.init_process_group()
        x = np.random.default_rng(1).normal(size=(8, 4, 3)).astype(np.float32)
        out = ptd.all_reduce(x)
        np.testing.assert_allclose(np.asarray(out), x.sum(0), rtol=1e-5)

    def test_all_gather_identity(self):
        ptd.init_process_group()
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        out = ptd.all_gather(x)
        np.testing.assert_allclose(np.asarray(out), x)

    def test_broadcast(self):
        ptd.init_process_group()
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = ptd.broadcast(x, src=3)
        np.testing.assert_allclose(np.asarray(out), [3.0])

    def test_reduce_scatter(self):
        ptd.init_process_group()
        # 8 participants each contribute a (8*2,) vector; result: summed,
        # length-16, sharded over dp.
        x = np.ones((8, 16), np.float32) * np.arange(8, dtype=np.float32)[:, None]
        out = ptd.reduce_scatter(x)
        np.testing.assert_allclose(np.asarray(out), np.full((16,), 28.0))

    def test_all_to_all(self):
        ptd.init_process_group()
        w, c = 8, 2
        x = np.arange(w * w * c, dtype=np.float32).reshape(w, w * c)
        out = np.asarray(ptd.all_to_all(x))
        want = np.stack(
            [
                np.concatenate([x[j, p * c:(p + 1) * c] for j in range(w)])
                for p in range(w)
            ]
        )
        np.testing.assert_allclose(out, want)

    def test_all_to_all_indivisible_raises(self):
        ptd.init_process_group()
        with pytest.raises(ValueError, match="divisible"):
            ptd.all_to_all(np.ones((8, 3), np.float32))

    def test_permute_ring_shift(self):
        ptd.init_process_group()
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        perm = [(i, (i + 1) % 8) for i in range(8)]
        out = np.asarray(ptd.permute(x, perm))
        np.testing.assert_allclose(out[:, 0], np.roll(np.arange(8.0), 1))

    def test_permute_partial_pairs_zero_fill(self):
        ptd.init_process_group()
        x = np.ones((8, 1), np.float32)
        out = np.asarray(ptd.permute(x, [(0, 5)]))
        want = np.zeros((8, 1), np.float32)
        want[5] = 1.0
        np.testing.assert_allclose(out, want)

    def test_gather_and_scatter(self):
        ptd.init_process_group()
        x = np.arange(16, dtype=np.float32).reshape(8, 2)
        np.testing.assert_allclose(np.asarray(ptd.gather(x, dst=2)), x)
        out = ptd.scatter(x, src=0)
        np.testing.assert_allclose(np.asarray(out), x)
        # each device holds exactly its row
        assert out.sharding.spec == P(tuple(AXES))

    def test_leading_dim_mismatch_raises(self):
        ptd.init_process_group()
        with pytest.raises(ValueError):
            ptd.all_reduce(np.ones((3, 1), np.float32))

    def test_barrier(self):
        ptd.init_process_group()
        ptd.barrier()  # just must not hang/raise

    def test_subaxis_collective(self):
        ptd.init_process_group(mesh_spec=MeshSpec(dp=4, tp=2))
        x = np.arange(4, dtype=np.float32).reshape(4, 1)
        out = ptd.all_reduce(x, axis="dp")
        np.testing.assert_allclose(np.asarray(out), [6.0])

    def test_reduce_and_monitored_barrier(self):
        ptd.init_process_group()
        x = np.arange(8, dtype=np.float32).reshape(8, 1)
        out = np.asarray(ptd.reduce(x, dst=3))
        np.testing.assert_allclose(out, [28.0])
        out = np.asarray(ptd.reduce(x, dst=0, op=ptd.ReduceOp.MAX))
        np.testing.assert_allclose(out, [7.0])
        ptd.monitored_barrier()  # no peers to straggle; must not raise
        ptd.monitored_barrier(timeout_s=1.0)

    def test_object_collectives_single_controller(self):
        # one process drives the whole mesh, so the process world is 1:
        # all_gather_object returns this process's object alone and
        # broadcast is the identity
        ptd.init_process_group()
        obj = {"step": 7, "name": "rn50"}
        assert ptd.all_gather_object(obj) == [obj]
        assert ptd.broadcast_object_list([obj, 3], src=0) == [obj, 3]
        assert ptd.scatter_object_list([obj], src=0) == obj
        with pytest.raises(ValueError):
            ptd.broadcast_object_list([1], src=2)
        with pytest.raises(ValueError):
            ptd.scatter_object_list([1, 2], src=0)  # wrong length


class TestPrecision:
    def test_default_policy(self):
        p = ptd.current_policy()
        assert p.compute_dtype == jnp.bfloat16
        assert p.param_dtype == jnp.float32

    def test_autocast_context(self):
        with ptd.autocast(dtype=jnp.float16) as p:
            assert ptd.current_policy().compute_dtype == jnp.float16
        assert ptd.current_policy().compute_dtype == jnp.bfloat16
        with ptd.autocast(enabled=False):
            assert ptd.current_policy().compute_dtype == jnp.float32

    def test_policy_casting_skips_ints(self):
        p = ptd.Policy()
        tree = {"w": jnp.ones((2,), jnp.float32), "i": jnp.ones((2,), jnp.int32)}
        out = p.cast_to_compute(tree)
        assert out["w"].dtype == jnp.bfloat16
        assert out["i"].dtype == jnp.int32

    def test_gradscaler_bf16_noop(self):
        scaler = ptd.GradScaler()
        assert scaler.init_state() is None
        loss = jnp.float32(3.0)
        assert scaler.scale_value(loss, None) == loss
        state, ok = scaler.functional_update({"g": jnp.ones(2)}, None)
        assert state is None and bool(ok)

    def test_gradscaler_fp16_dynamic(self):
        scaler = ptd.GradScaler(init_scale=4.0, dtype=jnp.float16, growth_interval=1)
        st = scaler.init_state()
        assert float(st.scale) == 4.0
        # finite grads -> growth (interval 1)
        st2, ok = scaler.functional_update({"g": jnp.ones(2)}, st)
        assert bool(ok) and float(st2.scale) == 8.0
        # inf grads -> backoff, step skipped
        st3, ok = scaler.functional_update({"g": jnp.array([jnp.inf, 1.0])}, st2)
        assert not bool(ok) and float(st3.scale) == 4.0
        # unscale divides
        g = scaler.unscale_grads({"g": jnp.full((2,), 8.0)}, st2)
        np.testing.assert_allclose(np.asarray(g["g"]), [1.0, 1.0])


class TestPrng:
    def test_key_for_deterministic(self):
        ptd.seed_all(123)
        k1 = ptd.runtime.prng.key_for(5, 1)
        k2 = ptd.runtime.prng.key_for(5, 1)
        assert jnp.array_equal(jax.random.key_data(k1), jax.random.key_data(k2))
        k3 = ptd.runtime.prng.key_for(6, 1)
        assert not jnp.array_equal(jax.random.key_data(k1), jax.random.key_data(k3))

    def test_rngseq_advances(self):
        seq = ptd.RngSeq(0)
        a, b = seq.next(), seq.next()
        assert not jnp.array_equal(jax.random.key_data(a), jax.random.key_data(b))
