"""Sliding-window attention + the Mistral family.

Pins, strongest first: HF ``MistralForCausalLM`` logit parity with a
BINDING window (window < sequence length, so the band mask actually
changes the answer); band-mask semantics against a numpy reference;
KV-cache greedy decode == full-recompute argmax with the window active
across the cache boundary.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import MistralConfig, MistralForCausalLM
from pytorch_distributed_tpu.ops.attention import dot_product_attention
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def test_window_band_mask_matches_reference():
    """attention(window=w) == softmax over keys j with 0 <= i-j < w."""
    rng = np.random.default_rng(0)
    B, S, H, D = 2, 12, 2, 8
    q = rng.normal(size=(B, S, H, D)).astype(np.float32)
    k = rng.normal(size=(B, S, H, D)).astype(np.float32)
    v = rng.normal(size=(B, S, H, D)).astype(np.float32)
    w = 4
    got = dot_product_attention(
        jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True,
        window=w,
    )
    logits = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
    i = np.arange(S)[:, None]
    j = np.arange(S)[None, :]
    keep = (i >= j) & (i - j < w)
    logits = np.where(keep[None, None], logits, -1e30)
    p = np.exp(logits - logits.max(-1, keepdims=True))
    p = p / p.sum(-1, keepdims=True)
    want = np.einsum("bhst,bthd->bshd", p, v)
    np.testing.assert_allclose(np.asarray(got), want, rtol=2e-5, atol=2e-5)


def test_window_excludes_key_exactly_window_back():
    """HF convention: a key exactly `window` positions back is masked.
    Perturbing it must not move the query's output; perturbing the
    newest in-window key must."""
    rng = np.random.default_rng(1)
    S, w, qi = 10, 3, 9  # query at position 9 sees keys 7, 8, 9
    q = rng.normal(size=(1, S, 1, 8)).astype(np.float32)
    k = rng.normal(size=(1, S, 1, 8)).astype(np.float32)
    v = rng.normal(size=(1, S, 1, 8)).astype(np.float32)

    def out_at(k_arr):
        return np.asarray(
            dot_product_attention(
                jnp.asarray(q), jnp.asarray(k_arr), jnp.asarray(v),
                causal=True, window=w,
            )
        )[0, qi]

    base = out_at(k)
    k_out = k.copy()
    k_out[0, qi - w] += 10.0  # position 6: out of window
    np.testing.assert_array_equal(out_at(k_out), base)
    k_in = k.copy()
    k_in[0, qi - w + 1] += 10.0  # position 7: newest masked boundary in
    assert not np.allclose(out_at(k_in), base)


def _pair():
    torch.manual_seed(0)
    hf_cfg = transformers.MistralConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10_000.0, rms_norm_eps=1e-5,
        max_position_embeddings=128, sliding_window=5,
        attn_implementation="eager",
    )
    hf = transformers.MistralForCausalLM(hf_cfg).eval()
    cfg = MistralConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=128,
        rope_theta=10_000.0, rms_eps=1e-5, sliding_window=5,
    )
    return hf, cfg


def test_mistral_logits_match_hf_with_binding_window():
    from pytorch_distributed_tpu.interop import load_mistral_weights

    hf, cfg = _pair()
    params = load_mistral_weights(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, cfg
    )
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 11)).astype(
        np.int32
    )  # S=11 > window=5: the band mask is binding
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = MistralForCausalLM(cfg).apply(
            {"params": params}, jnp.asarray(ids)
        )
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)


def test_mistral_cache_decode_equals_recompute_across_window():
    cfg = MistralConfig.tiny()  # window=8
    model = MistralForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, 500, size=(2, 6)), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    # 4 new tokens cross the window boundary (6+4 > 8), so late steps
    # must FORGET early keys identically in both paths; each recompute
    # length is a fresh compile, so the loop stays short
    new = 4
    got = ptd.generate(model, params, ids, max_new_tokens=new,
                       temperature=0.0)
    seq = np.asarray(ids)
    for _ in range(new):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)


# --------------------------------------------------------------------------
# RoPE context-window scaling (Llama-3.1 long context) — lives here with
# the other Llama-body extension semantics
# --------------------------------------------------------------------------


def test_rope_llama3_scaling_matches_hf_inv_freq():
    """Our llama3 frequency transform == HF's _compute_llama3_parameters
    (the function Llama-3.1 checkpoints were trained against)."""
    from transformers.modeling_rope_utils import ROPE_INIT_FUNCTIONS

    from pytorch_distributed_tpu.models import LlamaConfig, RopeScaling
    from pytorch_distributed_tpu.ops.attention import rope_frequencies

    hf_cfg = transformers.LlamaConfig(
        hidden_size=64, num_attention_heads=4, rope_theta=10_000.0,
        max_position_embeddings=64,
        rope_scaling={
            "rope_type": "llama3", "factor": 4.0,
            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": 32,
        },
    )
    hf_inv, _ = ROPE_INIT_FUNCTIONS["llama3"](hf_cfg, device=None)
    scaling = RopeScaling(
        type="llama3", factor=4.0, low_freq_factor=1.0,
        high_freq_factor=4.0, original_max_position_embeddings=32,
    )
    cos, sin = rope_frequencies(16, 64, 10_000.0, scaling=scaling)
    # recover inv_freq from the tables: freqs[1] = 1 * inv
    ours = np.arctan2(np.asarray(sin)[1], np.asarray(cos)[1])
    np.testing.assert_allclose(ours, hf_inv.numpy(), rtol=1e-6, atol=1e-7)


def test_rope_linear_scaling_is_position_interpolation():
    from pytorch_distributed_tpu.models import RopeScaling
    from pytorch_distributed_tpu.ops.attention import rope_frequencies

    cos_s, sin_s = rope_frequencies(
        16, 32, 10_000.0,
        scaling=RopeScaling(type="linear", factor=2.0),
    )
    cos, sin = rope_frequencies(16, 32, 10_000.0)
    # scaled table at position 2t == unscaled at position t
    np.testing.assert_allclose(
        np.asarray(cos_s)[::2], np.asarray(cos)[:16], rtol=1e-6
    )
    np.testing.assert_allclose(
        np.asarray(sin_s)[::2], np.asarray(sin)[:16], rtol=1e-6
    )


def test_llama31_rope_scaling_logits_match_hf():
    """End-to-end: a converted HF checkpoint with llama3 rope scaling
    scores identically — positions past original_max included."""
    from pytorch_distributed_tpu.interop import load_llama_weights
    from pytorch_distributed_tpu.models import (
        LlamaConfig,
        LlamaForCausalLM,
        RopeScaling,
    )

    torch.manual_seed(0)
    hf_cfg = transformers.LlamaConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        rope_theta=10_000.0, rms_norm_eps=1e-5,
        max_position_embeddings=64,
        rope_scaling={
            "rope_type": "llama3", "factor": 4.0,
            "low_freq_factor": 1.0, "high_freq_factor": 4.0,
            "original_max_position_embeddings": 16,
        },
        attn_implementation="eager",
    )
    hf = transformers.LlamaForCausalLM(hf_cfg).eval()
    cfg = LlamaConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, max_seq_len=64,
        rope_theta=10_000.0, rms_eps=1e-5,
        rope_scaling=RopeScaling(
            type="llama3", factor=4.0, low_freq_factor=1.0,
            high_freq_factor=4.0, original_max_position_embeddings=16,
        ),
    )
    params = load_llama_weights(
        {k: v.detach().numpy() for k, v in hf.state_dict().items()}, cfg
    )
    # S=24 > original_max=16: the scaled frequencies are binding
    ids = np.random.default_rng(0).integers(2, 211, size=(2, 24)).astype(
        np.int32
    )
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = LlamaForCausalLM(cfg).apply({"params": params}, jnp.asarray(ids))
    np.testing.assert_allclose(np.asarray(got), want, atol=3e-4, rtol=2e-4)
