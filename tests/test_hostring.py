"""Native shared-memory collectives backend (the gloo equivalent).

SURVEY.md §2: the reference's CPU smoke path is real multi-process training
over the gloo process group; our native equivalent is
``native/hostring.cpp``. These tests spawn genuine OS processes (spawn
context, no fork of the JAX runtime) and validate both the raw ctypes layer
and the ``init_process_group`` facade on top of it.
"""

import os
import uuid

import pytest

from tests import hostring_workers


_run = hostring_workers.run_ring_workers  # THE shared spawn harness


def test_build_library():
    from pytorch_distributed_tpu.runtime.hostring import build_library

    path = build_library()
    assert os.path.exists(path)


@pytest.mark.slow
def test_raw_collectives_4proc():
    results = _run(4, hostring_workers.raw_worker)
    assert results == [(r, "ok") for r in range(4)], results


@pytest.mark.slow  # r5 profile refit: the 4proc variant exercises a strict superset of ring paths
def test_raw_collectives_2proc():
    results = _run(2, hostring_workers.raw_worker)
    assert results == [(r, "ok") for r in range(2)], results


@pytest.mark.slow
def test_facade_multiprocess():
    results = _run(4, hostring_workers.facade_worker, timeout=300.0)
    assert results == [(r, "ok") for r in range(4)], results


@pytest.mark.slow
def test_rapid_reinit_same_group_name():
    """destroy + immediate re-init on the SAME group name, three cycles,
    no inter-cycle barrier: the per-init generation suffix must keep each
    rendezvous on a fresh shm segment (ADVICE r1 #2 re-init race)."""
    results = _run(3, hostring_workers.reinit_worker, timeout=300.0)
    assert results == [(r, "ok") for r in range(3)], results


@pytest.mark.slow
def test_p2p_send_recv_with_bystanders():
    """send/recv between two ranks must complete while other ranks do
    nothing (true P2P mailbox, not a barrier-gated group collective)."""
    results = _run(3, hostring_workers.p2p_worker)
    assert results == [(r, "ok") for r in range(3)], results


def test_collective_mismatch_detected():
    """PTD_DISTRIBUTED_DEBUG=DETAIL analogue: divergent collective calls
    across ranks raise instead of corrupting data (SURVEY.md §5)."""
    results = _run(2, hostring_workers.mismatch_worker)
    assert results == [(r, "ok") for r in range(2)], results


def test_single_process_group_direct():
    """HostRingGroup degenerates correctly at world_size=1."""
    import numpy as np

    from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

    with HostRingGroup(f"ptdtest_{uuid.uuid4().hex[:8]}", 0, 1) as g:
        x = np.arange(5, dtype=np.float32)
        assert np.all(g.all_reduce(x) == x)
        assert np.all(g.all_gather(x) == x[None])
        assert np.all(g.broadcast(x) == x)
        g.barrier()


def test_half_dtypes_supported():
    """bf16/f16 (the TPU compute dtypes) reduce via the f32 round trip."""
    import ml_dtypes
    import numpy as np

    from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

    with HostRingGroup(f"ptdtest_{uuid.uuid4().hex[:8]}", 0, 1) as g:
        x = np.ones(4, ml_dtypes.bfloat16) * 1.5
        out = g.all_reduce(x)
        assert out.dtype == x.dtype and np.all(out == x)
        h = np.ones(4, np.float16)
        assert g.all_reduce(h, op="avg").dtype == np.float16
        gathered = g.all_gather(x)  # raw-byte gather path
        assert gathered.dtype == x.dtype and gathered.shape == (1, 4)
        rs = g.reduce_scatter(x[None])
        assert rs.dtype == x.dtype


def test_bad_dtype_rejected():
    import numpy as np

    from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

    with HostRingGroup(f"ptdtest_{uuid.uuid4().hex[:8]}", 0, 1) as g:
        with pytest.raises(TypeError):
            g.all_reduce(np.ones(3, np.complex64))
