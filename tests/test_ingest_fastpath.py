"""The default uint8 ingest path (docs/DESIGN.md §3d).

Covers the PR-level contract of the u8-by-default flip:

* numerics parity — uint8 feed + ``device_normalizer`` INSIDE the jitted
  step matches the host-f32 normalize path within tolerance, for both
  the native array pipeline and the PIL folder pipeline;
* the fused on-device flip augmentation (``make_device_normalizer(flip=
  True)``) through ``build_train_step``'s 2-arg batch_transform hook;
* staging-ring reuse: active only for device-fed loaders, buffers rotate
  without corrupting already-placed batches, host-fed consumers keep
  fresh arrays;
* rank-aware-sampler auto-detect still prevents double-sharding with
  the new default fetch.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
import optax

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.data import (
    ArrayDataset,
    DataLoader,
    ImageBatchPipeline,
    SyntheticImageDataset,
)
from pytorch_distributed_tpu.data.native_pipeline import (
    HostStagingRing,
    make_device_normalizer,
)

N, H, W, C = 64, 12, 12, 3


def _dataset(seed=0):
    rng = np.random.default_rng(seed)
    return ArrayDataset(
        image=rng.integers(0, 256, size=(N, H, W, C)).astype(np.uint8),
        label=rng.integers(4, size=(N,)).astype(np.int64),
    )


def _tiny_classifier(image=8):
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.train import TrainState

    model = ResNet(
        stage_sizes=[1], block_cls=BasicBlock, num_classes=4, width=8,
        stem="cifar",
    )
    v = model.init(
        jax.random.key(0), jnp.zeros((1, image, image, 3)), train=False
    )
    state = TrainState.create(
        apply_fn=model.apply, params=v["params"], tx=optax.sgd(0.1),
        batch_stats=v["batch_stats"],
    )
    return model, state


class TestJittedStepParity:
    """u8 feed + on-device normalize == host f32, measured where it
    matters: through the jitted eval/train step, not just the transform."""

    def test_array_pipeline_eval_metrics_match(self):
        from pytorch_distributed_tpu.train import classification_eval_step

        ptd.init_process_group()
        ds = _dataset(3)
        model, state = _tiny_classifier()
        idx = np.arange(16)
        f32 = ImageBatchPipeline(
            crop=8, train=False, seed=7, device_normalize=False
        )
        u8 = ImageBatchPipeline(crop=8, train=False, seed=7)
        eval_f32 = jax.jit(classification_eval_step(model))
        eval_u8 = jax.jit(
            classification_eval_step(
                model, batch_transform=u8.device_normalizer()
            )
        )
        a = eval_f32(state, f32(ds, idx))
        batch_u8 = u8(ds, idx)
        assert batch_u8["image"].dtype == np.uint8
        b = eval_u8(state, batch_u8)
        for k in a:
            np.testing.assert_allclose(
                float(a[k]), float(b[k]), atol=1e-5, err_msg=k
            )

    def test_array_pipeline_train_loss_matches(self):
        from pytorch_distributed_tpu.parallel import DataParallel
        from pytorch_distributed_tpu.train import (
            build_train_step,
            classification_loss_fn,
        )

        ptd.init_process_group()
        ds = _dataset(5)
        model, state = _tiny_classifier()
        strategy = DataParallel()
        idx = np.arange(16)
        # identical augmentation stream: same (seed, epoch, indices)
        f32 = ImageBatchPipeline(
            crop=8, train=True, seed=9, device_normalize=False
        )
        u8 = ImageBatchPipeline(crop=8, train=True, seed=9)
        loss_fn = classification_loss_fn(model)
        step_f32 = strategy.compile(
            build_train_step(loss_fn), strategy.place(state)
        )
        state8 = strategy.place(
            jax.tree_util.tree_map(jnp.array, state)
        )
        step_u8 = strategy.compile(
            build_train_step(
                loss_fn, batch_transform=u8.device_normalizer()
            ),
            state8,
        )
        _, m_f32 = step_f32(
            strategy.place(state), strategy.shard_batch(f32(ds, idx))
        )
        _, m_u8 = step_u8(state8, strategy.shard_batch(u8(ds, idx)))
        np.testing.assert_allclose(
            float(m_f32["loss"]), float(m_u8["loss"]), atol=1e-5
        )

    def test_synthetic_uint8_matches_manual_normalize(self):
        ds = SyntheticImageDataset(n=8, dtype=np.uint8, seed=2)
        mean = np.asarray((0.4, 0.5, 0.6), np.float32) * 255.0
        stdinv = 1.0 / (np.asarray((0.2, 0.25, 0.3), np.float32) * 255.0)
        norm = jax.jit(make_device_normalizer(mean, stdinv))
        batch = {
            "image": np.stack([ds[i]["image"] for i in range(8)]),
            "label": np.zeros(8, np.int32),
        }
        got = np.asarray(norm(batch)["image"])
        want = (batch["image"].astype(np.float32) - mean) * stdinv
        np.testing.assert_allclose(got, want, atol=1e-5)

    def test_folder_pipeline_eval_metrics_match(self, tmp_path):
        pytest.importorskip("PIL")
        from PIL import Image

        from pytorch_distributed_tpu.data import (
            FolderImagePipeline,
            ImageFolderDataset,
        )
        from pytorch_distributed_tpu.train import classification_eval_step

        ptd.init_process_group()
        rng = np.random.default_rng(0)
        for ci, cls in enumerate(["a", "b"]):
            d = tmp_path / cls
            d.mkdir(parents=True)
            for i in range(3):
                arr = rng.integers(0, 256, size=(40, 40, 3)).astype(np.uint8)
                Image.fromarray(arr).save(d / f"img{i}.png")
        ds = ImageFolderDataset(str(tmp_path))
        model, state = _tiny_classifier()
        idx = np.arange(6)
        host = FolderImagePipeline(
            8, train=False, resize=16, device_normalize=False
        )
        dev = FolderImagePipeline(8, train=False, resize=16)
        eval_f32 = jax.jit(classification_eval_step(model))
        eval_u8 = jax.jit(
            classification_eval_step(
                model, batch_transform=dev.device_normalizer()
            )
        )
        a = eval_f32(state, host(ds, idx))
        batch_u8 = dev(ds, idx)
        assert batch_u8["image"].dtype == np.uint8
        b = eval_u8(state, batch_u8)
        for k in a:
            np.testing.assert_allclose(
                float(a[k]), float(b[k]), atol=1e-4, err_msg=k
            )


class TestFusedDeviceFlip:
    def test_flip_transform_is_deterministic_and_flips(self):
        rng = np.random.default_rng(1)
        img = rng.integers(0, 256, size=(32, 6, 6, 3)).astype(np.uint8)
        tr = jax.jit(
            make_device_normalizer(
                np.zeros(3, np.float32), np.ones(3, np.float32), flip=True
            )
        )
        key = jax.random.key(3)
        a = np.asarray(tr({"image": img}, key)["image"])
        b = np.asarray(tr({"image": img}, key)["image"])
        np.testing.assert_array_equal(a, b)  # same key -> same flips
        src = img.astype(np.float32)
        flipped = 0
        for i in range(32):
            if np.allclose(a[i], src[i]):
                continue
            np.testing.assert_allclose(a[i], src[i][:, ::-1, :])
            flipped += 1
        assert 0 < flipped < 32  # both outcomes occurred

    def test_build_train_step_feeds_rng_to_two_arg_transform(self):
        from pytorch_distributed_tpu.parallel import DataParallel
        from pytorch_distributed_tpu.train import (
            build_train_step,
            classification_loss_fn,
        )

        ptd.init_process_group()
        model, state = _tiny_classifier()
        strategy = DataParallel()
        state = strategy.place(state)
        mean = np.full(3, 127.5, np.float32)
        stdinv = np.full(3, 1 / 127.5, np.float32)
        step = strategy.compile(
            build_train_step(
                classification_loss_fn(model),
                batch_transform=make_device_normalizer(
                    mean, stdinv, flip=True
                ),
            ),
            state,
        )
        rng = np.random.default_rng(0)
        batch = strategy.shard_batch(
            {
                "image": rng.integers(
                    0, 256, size=(16, 8, 8, 3)
                ).astype(np.uint8),
                "label": rng.integers(4, size=(16,)).astype(np.int32),
            }
        )
        state, metrics = step(state, batch)
        assert np.isfinite(float(metrics["loss"]))


class TestStagingRing:
    def test_ring_rotates_and_reuses(self):
        ring = HostStagingRing(depth=2)
        a = ring.get((4, 3), np.uint8)
        b = ring.get((4, 3), np.uint8)
        assert a is not b
        # unreleased (busy) buffers are never handed out again — the
        # wrap falls back to fresh one-shots
        c = ring.get((4, 3), np.uint8)
        assert c is not a and c is not b
        # released buffers rotate (the host-fed reuse contract); the
        # busy fallback above consumed one rotation step, so b is next
        ring.release([a, b])
        assert ring.get((4, 3), np.uint8) is b
        assert ring.get((4, 3), np.uint8) is a
        ring.release([a, b])
        # distinct shapes get distinct slots
        d = ring.get((2, 3), np.uint8)
        assert d is not a and d is not b
        # buffers are deliberately off 64-byte alignment (defeats XLA
        # CPU zero-copy aliasing — the reuse-safety precondition)
        for buf in (a, b, c, d):
            assert buf.ctypes.data % 64 != 0

    def test_pipeline_staging_gated_by_device_feeding(self):
        ds = _dataset()
        pipe = ImageBatchPipeline(8, train=True, seed=1)
        # host-fed: fresh buffers per batch (consumers may hold them)
        a = pipe(ds, np.arange(8))["image"]
        b = pipe(ds, np.arange(8))["image"]
        assert a is not b and not np.shares_memory(a, b)
        assert not pipe.staging_active
        # device-fed on the CPU backend: the loader marks the pipeline,
        # but auto mode STAYS on fresh buffers (XLA:CPU zero-copy
        # aliases them — faster than the ring's forced copy, and safe
        # for never-rewritten buffers)
        from pytorch_distributed_tpu.parallel import DataParallel

        ptd.init_process_group()
        strategy = DataParallel()
        loader = DataLoader(
            ds, 16, sharding=strategy.batch_sharding(), fetch=pipe
        )
        assert pipe._device_fed
        assert not pipe.staging_active  # auto defers to fresh on cpu

    def test_staging_ring_batches_survive_wrap_through_loader(self):
        """Forced ring reuse through a sharded loader: the fence +
        alias-eviction must keep already-placed batches intact when the
        ring wraps (on CPU, where device_put may alias, this exercises
        the eviction path)."""
        from pytorch_distributed_tpu.parallel import DataParallel

        ptd.init_process_group()
        ds = _dataset()
        strategy = DataParallel()
        pipe = ImageBatchPipeline(8, train=True, seed=1, reuse_staging=True)
        loader = DataLoader(
            ds, 16, sharding=strategy.batch_sharding(), fetch=pipe
        )
        assert pipe.staging_active
        batches = list(loader)
        assert len(batches) == N // 16
        # placed batches must survive the ring wrapping: values intact
        # and distinct per batch (a corrupting reuse would repeat the
        # last batch's pixels)
        imgs = [np.asarray(b["image"]) for b in batches]
        assert len({arr.tobytes() for arr in imgs}) == len(imgs)
        # parity with a fresh-buffer pipeline on the same seed/epoch
        pipe_fresh = ImageBatchPipeline(
            8, train=True, seed=1, reuse_staging=False
        )
        loader_fresh = DataLoader(
            ds, 16, sharding=strategy.batch_sharding(), fetch=pipe_fresh
        )
        for got, want in zip(batches, loader_fresh):
            np.testing.assert_array_equal(
                np.asarray(got["image"]), np.asarray(want["image"])
            )

    def test_explicit_reuse_returns_ring_buffers(self):
        ds = _dataset()
        pipe = ImageBatchPipeline(8, train=True, seed=1, reuse_staging=True)
        a = pipe(ds, np.arange(8))["image"]
        b = pipe(ds, np.arange(8))["image"]
        c = pipe(ds, np.arange(8))["image"]
        assert a is not b
        assert c is a  # depth-2 ring wraps


class TestShardAutoDetect:
    """Rank-aware sampler + the new default fetch must not double-shard."""

    class RankAwareSampler:
        """Minimal DistributedSampler-shaped batch sampler: yields this
        rank's HALF of every global batch (num_replicas=2)."""

        num_replicas = 2

        def __init__(self, n, batch):
            self.n, self.batch = n, batch

        def __iter__(self):
            for start in range(0, self.n - self.batch + 1, self.batch):
                yield np.arange(start, start + self.batch)[::2]

        def __len__(self):
            return self.n // self.batch

    def test_rank_aware_sampler_disables_loader_slice(self):
        ds = _dataset()
        pipe = ImageBatchPipeline(8, train=True, seed=1)
        dl = DataLoader(ds, 16, sampler=self.RankAwareSampler(N, 16),
                        fetch=pipe)
        assert dl.shard is False  # auto-detected rank-aware sampler
        batches = list(dl)
        # the sampler already halved the batch; the loader must not
        # halve it again (double-sharding would yield 4 samples)
        assert all(b["image"].shape[0] == 8 for b in batches)

    def test_plain_sampler_keeps_loader_slice(self):
        ds = _dataset()
        pipe = ImageBatchPipeline(8, train=True, seed=1)
        dl = DataLoader(ds, 16, fetch=pipe)
        assert dl.shard is True

    def test_force_flag_overrides(self):
        ds = _dataset()
        dl = DataLoader(ds, 16, sampler=self.RankAwareSampler(N, 16),
                        shard=True)
        assert dl.shard is True
