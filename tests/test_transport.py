"""Multi-host transports (r16): pluggable Transport under the ring,
TCP rendezvous, hierarchical collectives, per-transport pricing.

The parity contract is the whole point: a ``TcpTransport``-backed group
must be indistinguishable from the native shm ring — same collectives,
same bits (q8 included: both fold through the one compiled
``hr_q8_dequant_add`` kernel), same fingerprint-handshake rejections,
same loud poison-on-peer-death. The hierarchical group's claim is
byte-structural: exactly ``2(H-1)/H x payload`` crosses the inter-host
link per allreduce, counted by an exact integer counter, with the flat
ring as the bit-reference on integer-valued payloads.

Process tests spawn genuine OS processes via the shared
``hostring_workers.run_ring_workers`` harness; TCP listeners bind
parent-chosen free ports so parallel tests can't collide.
"""

import io
import json
import os
import subprocess
import sys

import numpy as np
import pytest

from pytorch_distributed_tpu.autoplan import pricing
from pytorch_distributed_tpu.runtime import costmodel, rendezvous
from pytorch_distributed_tpu.runtime.hostring import algo_wire_bytes

from tests import hostring_workers, transport_workers

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")

_run = hostring_workers.run_ring_workers
pytestmark = pytest.mark.multihost


class TestTcpTransportParity:
    def test_full_collective_matrix_vs_shm(self):
        """Every collective x dtype x op cell bit-identical between the
        shm ring and the TCP mesh on a 3-rank world (odd world: chunk
        remainders exercised), plus exact wire accounting."""
        results = _run(
            3, transport_workers.parity_worker,
            extra_args=(transport_workers.free_addr(),),
        )
        assert results == [(r, "ok") for r in range(3)], results

    def test_handshake_rejects_mismatched_params(self):
        """A joiner with different slot_bytes is refused at the hello —
        the socket-mesh analogue of hr_init's header validation."""
        results = _run(
            2, transport_workers.mismatch_worker,
            extra_args=(transport_workers.free_addr(),),
        )
        assert results == [(r, "ok") for r in range(2)], results

    def test_traced_spans_carry_transport_and_bytes_counter(self, tmp_path):
        """Armed comm spans record ``transport="tcp"`` and the
        cumulative ``comm.bytes.tcp`` counter equals the transport's own
        exact ``bytes_sent`` — the source for obs_report's Cross-host
        bytes line."""
        results = _run(
            2, transport_workers.traced_tcp_worker,
            extra_args=(transport_workers.free_addr(), str(tmp_path)),
        )
        bad = [r for r in results if not isinstance(r[1], dict)]
        assert not bad, bad
        want = 3 * algo_wire_bytes("all_reduce", 4096 * 4, 2)
        assert all(d["bytes_sent"] == want for _, d in results), results
        for rank in range(2):
            fname = "trace.json" if rank == 0 else f"trace-rank{rank}.json"
            doc = json.load(open(os.path.join(str(tmp_path), fname)))
            evs = doc if isinstance(doc, list) else doc["traceEvents"]
            ar = [e for e in evs if e.get("ph") == "X"
                  and e.get("name") == "comm.all_reduce"]
            assert len(ar) == 3, [e.get("name") for e in evs]
            assert all(e["args"]["transport"] == "tcp" for e in ar), ar
            ctr = [e for e in evs if e.get("ph") == "C"
                   and e.get("name") == "comm.bytes.tcp"]
            assert ctr, "comm.bytes.tcp counter never emitted"
            assert ctr[-1]["args"]["value"] == want, ctr[-1]


class TestHierarchicalGroup:
    def test_2x2_hierarchy_parity_and_inter_bytes(self):
        """tcp-inter == shm-inter bitwise; hier == flat bitwise on
        integer payloads; q8 inter bounded + cross-rank identical; the
        inter-link counter exactly 2(H-1)/H x payload on leaders, 0
        elsewhere."""
        results = _run(
            4, transport_workers.hier_worker,
            extra_args=(transport_workers.free_addr(),),
        )
        assert results == [(r, "ok") for r in range(4)], results

    def test_severed_link_poisons_loudly_then_remesh(self):
        """The chaos contract: an injected ``transport.link_lost`` on a
        leader fails EVERY rank loudly (poison + EOF cascade on the TCP
        leg, deadline on the intra rings), and survivors recover on a
        fresh re-meshed ring — the r13 elastic recovery shape."""
        results = _run(
            4, transport_workers.link_lost_worker,
            extra_args=(transport_workers.free_addr(),), timeout=120.0,
        )
        assert results == [(r, "ok") for r in range(4)], results


class TestGradSyncOverTcp:
    def test_engine_routes_through_handed_group(self):
        """Verify-don't-fork: GradSyncEngine on a TCP-backed group is
        bit-identical to the same engine on the shm ring — the overlap
        pipeline has no transport-specific branch."""
        results = _run(
            2, transport_workers.gradsync_tcp_worker,
            extra_args=(transport_workers.free_addr(),),
        )
        assert results == [(r, "ok") for r in range(2)], results


class TestTcpRendezvous:
    def test_channel_records_roundtrip_and_connection_lease(self):
        """In-process unit: announce/read/leave/view RPCs round-trip,
        and dropping a client connection reaps its member record — the
        liveness lease that replaces pid polling."""
        srv = rendezvous.RendezvousServer("127.0.0.1:0")
        try:
            c1 = rendezvous.open_channel("tcp://" + srv.addr)
            c2 = rendezvous.open_channel("tcp://" + srv.addr)
            assert isinstance(c1, rendezvous.TcpRendezvousChannel)
            assert c1.key() == "tcp://" + srv.addr == c2.key()
            c1.write_member({"worker_id": "a", "pid": 1, "bid": 1})
            c2.write_member({"worker_id": "b", "pid": 2, "bid": 1})
            ids = sorted(r["worker_id"] for r in c1.read_members())
            assert ids == ["a", "b"], ids
            assert c1.last_committed_epoch() == 0
            c1.write_view_record({"epoch": 3, "members": ["a", "b"],
                                  "world_size": 2})
            assert c2.last_committed_epoch() == 3
            assert [v["epoch"] for v in srv.views()] == [3]
            # the lease: close c2's socket without a leave RPC
            c2.close()
            deadline = 50
            while deadline and any(
                r["worker_id"] == "b" for r in c1.read_members()
            ):
                deadline -= 1
                import time

                time.sleep(0.05)
            assert deadline, "dropped connection's record never reaped"
            c1.remove_member("a")
            assert c1.read_members() == []
            c1.close()
        finally:
            srv.close()

    def test_channel_raises_on_dead_server(self):
        srv = rendezvous.RendezvousServer("127.0.0.1:0")
        ch = rendezvous.open_channel("tcp://" + srv.addr)
        ch.write_member({"worker_id": "a", "pid": 1, "bid": 1})
        srv.close()
        with pytest.raises(RuntimeError, match="closed|unreachable"):
            for _ in range(10):  # close() races the in-flight reply
                ch.read_members()
        ch.close()
        with pytest.raises(RuntimeError, match="unreachable"):
            rendezvous.TcpRendezvousChannel(
                "tcp://" + srv.addr, timeout_s=0.3
            )

    def test_open_channel_selects_by_scheme(self, tmp_path):
        ch = rendezvous.open_channel(str(tmp_path / "rdzv"))
        assert isinstance(ch, rendezvous.FileRendezvousChannel)
        assert ch.key() == str(tmp_path / "rdzv")

    @pytest.mark.parametrize("kill_self", [False, True],
                             ids=["graceful-leave", "sigkill-lease-reap"])
    def test_membership_over_tcp_shrinks(self, kill_self):
        """WorldMembership over ``tcp://``: genesis establish at world
        3, lose one member (cleanly or by SIGKILL — the connection lease
        makes both visible), survivors commit the shrunken view on a
        fresh ring and reduce correctly; the server holds the audit
        trail."""
        import multiprocessing as mp

        srv = rendezvous.RendezvousServer("127.0.0.1:0")
        addr = "tcp://" + srv.addr
        ctx = mp.get_context("spawn")
        q = ctx.Queue()
        old = os.environ.get("JAX_PLATFORMS")
        os.environ["JAX_PLATFORMS"] = "cpu"
        try:
            procs = [
                ctx.Process(target=transport_workers.rdzv_worker,
                            args=(f"w{i}", addr, q, kill_self))
                for i in range(3)
            ]
            for p in procs:
                p.start()
        finally:
            if old is None:
                os.environ.pop("JAX_PLATFORMS", None)
            else:
                os.environ["JAX_PLATFORMS"] = old
        try:
            msgs = [q.get(timeout=90) for _ in range(5)]
        finally:
            for p in procs:
                p.join(timeout=30)
                if p.is_alive():
                    p.terminate()
        errs = [m for m in msgs if m[1] == "error"]
        assert not errs, errs
        v1 = sorted(m for m in msgs if m[1] == "v1")
        v2 = sorted(m for m in msgs if m[1] == "v2")
        assert len(v1) == 3 and len(v2) == 2, msgs
        assert all(m[4] == 6.0 for m in v1), v1  # 1+2+3 over world 3
        assert all(m[3] == ["w0", "w1"] for m in v2), v2
        assert all(m[4] == 3.0 for m in v2), v2  # 1+2 over world 2
        assert v2[0][2] > v1[0][2], (v1, v2)  # epoch advanced
        views = srv.views()
        assert [v["world_size"] for v in views] == [3, 2], views
        srv.close()


def _leg_model(transport, beta, *, alpha=0.0, worlds=(2, 3, 4)):
    fits = {}
    for op in ("all_reduce", "all_reduce_q8", "broadcast"):
        for w in worlds:
            fits[(op, w)] = costmodel.OpFit(
                op=op, world_size=w, alpha_s=alpha,
                beta_s_per_byte=beta, r2=1.0, n_samples=4,
                wire_bytes_min=0, wire_bytes_max=1 << 62,
            )
    return costmodel.CostModel(transport, fits)


class TestHierarchicalPricing:
    """hierarchical_allreduce_seconds: hand-computable leg prices."""

    def test_legs_priced_on_their_own_fits(self):
        # intra: shm at 1 ns/B; inter: tcp at 10 ns/B. payload 1 MB f32.
        intra = _leg_model("shm", 1e-9)
        inter = _leg_model("tcp", 10e-9)
        P = 1 << 20
        hp = pricing.hierarchical_allreduce_seconds(
            P, P // 4, [2, 2], intra, inter
        )
        # intra reduce leg: reduce-scatter shape of the 2-way allreduce
        # is priced as the intra model's all_reduce over the domain
        # world; the exact decomposition is the function's own — pin the
        # structural facts instead of re-deriving every constant:
        assert hp.seconds == (hp.intra_reduce_s + hp.inter_exchange_s
                              + hp.intra_bcast_s)
        # inter leg: H=2 allreduce at 10 ns/B over 2(H-1)/H x P wire
        want_inter = 10e-9 * algo_wire_bytes("all_reduce", P, 2)
        assert abs(hp.inter_exchange_s - want_inter) < 1e-12, hp
        assert hp.inter_wire_bytes == algo_wire_bytes("all_reduce", P, 2)
        assert not hp.extrapolated
        # the slow link dominates: inter leg must be ~10x an intra leg
        assert hp.inter_exchange_s > 4 * hp.intra_reduce_s, hp

    def test_q8_inter_leg_prices_q8_wire(self):
        intra = _leg_model("shm", 1e-9)
        inter = _leg_model("tcp", 10e-9)
        P, elems = 1 << 20, (1 << 20) // 4
        full = pricing.hierarchical_allreduce_seconds(
            P, elems, [2, 2], intra, inter
        )
        q8 = pricing.hierarchical_allreduce_seconds(
            P, elems, [2, 2], intra, inter, q8_inter=True
        )
        # q8 moves ~0.26x the f32 bytes over the slow link
        assert q8.inter_wire_bytes < 0.3 * full.inter_wire_bytes, (
            q8.inter_wire_bytes, full.inter_wire_bytes
        )
        assert q8.inter_exchange_s < full.inter_exchange_s
        # intra legs identical: quantization only touches the inter leg
        assert q8.intra_reduce_s == full.intra_reduce_s

    def test_single_domain_has_no_inter_leg(self):
        intra = _leg_model("shm", 1e-9)
        inter = _leg_model("tcp", 10e-9)
        hp = pricing.hierarchical_allreduce_seconds(
            1 << 20, (1 << 20) // 4, [4], intra, inter
        )
        assert hp.inter_exchange_s == 0.0
        assert hp.inter_wire_bytes == 0

    def test_bad_domains_raise(self):
        m = _leg_model("shm", 1e-9)
        with pytest.raises(ValueError):
            pricing.hierarchical_allreduce_seconds(
                1024, 256, [], m, m
            )
        with pytest.raises(ValueError):
            pricing.hierarchical_allreduce_seconds(
                1024, 256, [2, 0], m, m
            )


class TestTransportMismatchRefused:
    """Satellite 2: a model fit on one transport can never silently
    price another — every loader raises, not just the planner."""

    def test_load_raises_on_expected_transport_mismatch(self, tmp_path):
        path = str(tmp_path / "cm.json")
        _leg_model("tcp", 2e-9).save(path)
        loaded = costmodel.CostModel.load(path, expected_transport="tcp")
        assert loaded.transport == "tcp"
        with pytest.raises(costmodel.CostModelUnavailable,
                           match="tcp"):
            costmodel.CostModel.load(path, expected_transport="shm")

    def test_obs_report_refuses_cross_transport_model(self, tmp_path):
        """obs_report (the one loader that previously skipped the
        check): a trace whose comm spans ran on tcp vs a model fit on
        shm must RAISE, not print a confidently-wrong pred column."""
        sys.path.insert(0, SCRIPTS)
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        trace = str(tmp_path / "trace.json")
        span = {
            "ph": "X", "name": "comm.all_reduce", "ts": 0, "dur": 1000,
            "pid": 0, "tid": 1,
            "args": {"transport": "tcp", "payload_bytes": 4096,
                     "wire_bytes": 4096, "world": 2},
        }
        ctr = {"ph": "C", "name": "comm.bytes.tcp", "ts": 900, "pid": 0,
               "args": {"value": 4096}}
        json.dump({"traceEvents": [span, ctr]}, open(trace, "w"))
        shm_model = str(tmp_path / "cm_shm.json")
        _leg_model("shm", 1e-9).save(shm_model)
        with pytest.raises(costmodel.CostModelUnavailable,
                           match="refit per transport"):
            obs_report.report(trace, [], out=io.StringIO(),
                              costmodel_path=shm_model)
        # the matching fit renders, with the Cross-host bytes line
        tcp_model = str(tmp_path / "cm_tcp.json")
        _leg_model("tcp", 1e-9).save(tcp_model)
        buf = io.StringIO()
        obs_report.report(trace, [], out=buf, costmodel_path=tcp_model)
        text = buf.getvalue()
        assert "Cross-host bytes: 0.00 MB over tcp" in text, text
        assert "transport=tcp" in text, text

    def test_obs_report_accepts_hostring_alias_for_shm(self, tmp_path):
        """Facade-sweep models label the native shm ring "hostring";
        the ring's own spans say "shm" — same physical transport, so
        the mismatch check must NOT fire across the alias."""
        sys.path.insert(0, SCRIPTS)
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        trace = str(tmp_path / "trace.json")
        span = {
            "ph": "X", "name": "comm.all_reduce", "ts": 0, "dur": 1000,
            "pid": 0, "tid": 1,
            "args": {"transport": "shm", "payload_bytes": 4096,
                     "wire_bytes": 4096, "world": 2},
        }
        json.dump({"traceEvents": [span]}, open(trace, "w"))
        model = str(tmp_path / "cm.json")
        _leg_model("hostring", 1e-9).save(model)
        buf = io.StringIO()
        obs_report.report(trace, [], out=buf, costmodel_path=model)
        assert "transport=hostring" in buf.getvalue()


@pytest.mark.slow
def test_collective_bench_tcp_sweep_fits_tcp_model(tmp_path):
    """``collective_bench.py --transport tcp`` runs a raw 2-proc socket
    mesh (no jax in the workers) and writes a model whose transport tag
    then refuses an shm-expecting load — the per-transport fit flow the
    planner consumes."""
    out = str(tmp_path / "cm_tcp.json")
    metrics = str(tmp_path / "metrics.jsonl")
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "collective_bench.py"),
         "--transport", "tcp", "--world", "2", "--sizes", "0.5", "2",
         "--iters", "3", "--fit", out, "--metrics-path", metrics],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300,
    )
    assert proc.returncode == 0, proc.stderr[-2000:]
    m = costmodel.CostModel.load(out, expected_transport="tcp")
    assert m.transport == "tcp"
    assert ("all_reduce", 2) in m.fits
    with pytest.raises(costmodel.CostModelUnavailable):
        costmodel.CostModel.load(out, expected_transport="shm")
    recs = [json.loads(l) for l in open(metrics)]
    assert all(r["transport"] == "tcp" for r in recs), recs[:2]
