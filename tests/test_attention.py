"""Attention op tests: reference numerics, causality, GQA, RoPE."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from pytorch_distributed_tpu.ops.attention import (
    apply_rope,
    dot_product_attention,
    rope_frequencies,
)


def reference_attention(q, k, v, causal=False):
    """Naive f32 reference."""
    B, S, H, D = q.shape
    T = k.shape[1]
    kv_rep = H // k.shape[2]
    k = np.repeat(k, kv_rep, axis=2)
    v = np.repeat(v, kv_rep, axis=2)
    logits = np.einsum("bshd,bthd->bhst", q, k) / np.sqrt(D)
    if causal:
        mask = np.tril(np.ones((S, T), bool))
        logits = np.where(mask[None, None], logits, -1e30)
    w = np.exp(logits - logits.max(-1, keepdims=True))
    w = w / w.sum(-1, keepdims=True)
    return np.einsum("bhst,bthd->bshd", w, v)


class TestDotProductAttention:
    def test_matches_reference(self):
        rng = np.random.default_rng(0)
        q = rng.normal(size=(2, 8, 4, 16)).astype(np.float32)
        k = rng.normal(size=(2, 8, 4, 16)).astype(np.float32)
        v = rng.normal(size=(2, 8, 4, 16)).astype(np.float32)
        out = dot_product_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(
            np.asarray(out), reference_attention(q, k, v), rtol=2e-5, atol=2e-5
        )

    def test_gqa_matches_repeated_kv(self):
        rng = np.random.default_rng(1)
        q = rng.normal(size=(2, 8, 8, 16)).astype(np.float32)
        k = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        v = rng.normal(size=(2, 8, 2, 16)).astype(np.float32)
        out = dot_product_attention(jnp.asarray(q), jnp.asarray(k), jnp.asarray(v))
        np.testing.assert_allclose(
            np.asarray(out), reference_attention(q, k, v), rtol=2e-5, atol=2e-5
        )

    def test_causal_no_future_leakage(self):
        rng = np.random.default_rng(2)
        q = rng.normal(size=(1, 8, 2, 8)).astype(np.float32)
        k = rng.normal(size=(1, 8, 2, 8)).astype(np.float32)
        v = rng.normal(size=(1, 8, 2, 8)).astype(np.float32)
        base = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True
        )
        # perturb the future: outputs at positions < 5 must not move
        k2, v2 = k.copy(), v.copy()
        k2[:, 5:] += 100.0
        v2[:, 5:] -= 50.0
        pert = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), causal=True
        )
        np.testing.assert_allclose(
            np.asarray(base)[:, :5], np.asarray(pert)[:, :5], rtol=1e-5, atol=1e-6
        )
        assert not np.allclose(np.asarray(base)[:, 5:], np.asarray(pert)[:, 5:])

    def test_q_offset_shifts_causality(self):
        # a 1-token query block at offset 3 sees keys 0..3 only
        rng = np.random.default_rng(3)
        q = rng.normal(size=(1, 1, 2, 8)).astype(np.float32)
        k = rng.normal(size=(1, 8, 2, 8)).astype(np.float32)
        v = rng.normal(size=(1, 8, 2, 8)).astype(np.float32)
        out3 = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), causal=True, q_offset=3
        )
        v2 = v.copy()
        v2[:, 4:] += 99.0  # beyond position 3: invisible
        out3b = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v2), causal=True, q_offset=3
        )
        np.testing.assert_allclose(np.asarray(out3), np.asarray(out3b), rtol=1e-5)

    def test_padding_mask(self):
        rng = np.random.default_rng(4)
        q = rng.normal(size=(1, 4, 2, 8)).astype(np.float32)
        k = rng.normal(size=(1, 4, 2, 8)).astype(np.float32)
        v = rng.normal(size=(1, 4, 2, 8)).astype(np.float32)
        mask = np.array([[True, True, False, False]])
        out = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k), jnp.asarray(v), mask=jnp.asarray(mask)
        )
        # masked keys must not affect output: zero them instead and compare
        k2, v2 = k.copy(), v.copy()
        k2[:, 2:] = 7.0
        v2[:, 2:] = -7.0
        out2 = dot_product_attention(
            jnp.asarray(q), jnp.asarray(k2), jnp.asarray(v2), mask=jnp.asarray(mask)
        )
        np.testing.assert_allclose(np.asarray(out), np.asarray(out2), rtol=1e-5)

    def test_bad_head_ratio_raises(self):
        x = jnp.zeros((1, 4, 3, 8))
        kv = jnp.zeros((1, 4, 2, 8))
        with pytest.raises(ValueError, match="heads"):
            dot_product_attention(x, kv, kv)

    def test_bf16_inputs_stable(self):
        rng = np.random.default_rng(5)
        q = jnp.asarray(rng.normal(size=(1, 16, 2, 32)), jnp.bfloat16)
        out = dot_product_attention(q, q, q, causal=True)
        assert out.dtype == jnp.bfloat16
        assert bool(jnp.all(jnp.isfinite(out.astype(jnp.float32))))


class TestRope:
    def test_rotation_preserves_norm(self):
        cos, sin = rope_frequencies(16, 32)
        x = jax.random.normal(jax.random.key(0), (1, 8, 2, 16))
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(
            np.linalg.norm(np.asarray(x), axis=-1),
            np.linalg.norm(np.asarray(y), axis=-1),
            rtol=1e-5,
        )

    def test_position_zero_is_identity(self):
        cos, sin = rope_frequencies(8, 16)
        x = jax.random.normal(jax.random.key(1), (1, 1, 1, 8))
        y = apply_rope(x, cos, sin)
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=1e-6)

    def test_relative_property(self):
        # <rope(q,m), rope(k,n)> depends only on m-n
        cos, sin = rope_frequencies(8, 64)
        q = jax.random.normal(jax.random.key(2), (1, 1, 1, 8))
        k = jax.random.normal(jax.random.key(3), (1, 1, 1, 8))

        def dot_at(m, n):
            qm = apply_rope(q, cos, sin, positions=jnp.array([[m]]))
            kn = apply_rope(k, cos, sin, positions=jnp.array([[n]]))
            return float(jnp.sum(qm * kn))

        assert dot_at(5, 3) == pytest.approx(dot_at(12, 10), rel=1e-4)
        assert dot_at(5, 3) != pytest.approx(dot_at(5, 4), rel=1e-2)

    def test_explicit_positions_match_arange(self):
        cos, sin = rope_frequencies(8, 32)
        x = jax.random.normal(jax.random.key(4), (2, 6, 2, 8))
        auto = apply_rope(x, cos, sin)
        manual = apply_rope(
            x, cos, sin, positions=jnp.broadcast_to(jnp.arange(6), (2, 6))
        )
        np.testing.assert_allclose(np.asarray(auto), np.asarray(manual), rtol=1e-6)


class TestFlashAttention:
    """Pallas kernel (interpret mode on the CPU test mesh) vs XLA path."""

    def _qkv(self, B=2, S=128, Hq=4, Hkv=2, D=64, dtype=np.float32):
        rng = np.random.default_rng(1)
        q = jnp.asarray(rng.normal(size=(B, S, Hq, D)), dtype)
        k = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
        v = jnp.asarray(rng.normal(size=(B, S, Hkv, D)), dtype)
        return q, k, v

    @pytest.mark.parametrize("causal", [False, True])
    def test_forward_matches_xla(self, causal):
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv()
        ref = dot_product_attention(q, k, v, causal=causal)
        out = flash_attention(q, k, v, causal=causal, block_q=64, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    @pytest.mark.parametrize("causal", [False, True])
    def test_grads_match_xla(self, causal):
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(S=64, D=32)

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        ref = jax.grad(
            loss(lambda q, k, v: dot_product_attention(q, k, v, causal=causal)),
            argnums=(0, 1, 2),
        )(q, k, v)
        got = jax.grad(
            loss(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=causal, block_q=32, block_k=32
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(ref, got):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4
            )

    @pytest.mark.parametrize("causal", [False, True])
    def test_padding_mask_matches_xla_fwd_and_grads(self, causal):
        """kv_mask (BERT padding) in-kernel: forward AND grads match the
        einsum path, including ragged lengths crossing block boundaries
        and a fully-masked k-block."""
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(S=64, D=32)
        B = q.shape[0]
        # lengths start at exactly one block (32): sequence 0's block
        # [32, 64) is FULLY masked, exercising the online-softmax carry
        # for all-masked blocks; later lengths cross block boundaries
        lengths = np.linspace(32, 64, B).astype(np.int64)
        mask = jnp.asarray(np.arange(64)[None, :] < lengths[:, None])

        want = dot_product_attention(q, k, v, causal=causal, mask=mask)
        got = flash_attention(
            q, k, v, causal=causal, kv_mask=mask, block_q=32, block_k=32
        )
        valid = np.asarray(mask)[:, :, None, None]  # padded q rows are
        np.testing.assert_allclose(       # undefined on both paths
            np.asarray(got) * valid, np.asarray(want) * valid,
            rtol=2e-5, atol=2e-6,
        )

        def loss(fn):
            def f(q, k, v):
                out = fn(q, k, v) * valid  # grade only defined rows
                return (out ** 2).sum()

            return f

        ref = jax.grad(
            loss(
                lambda q, k, v: dot_product_attention(
                    q, k, v, causal=causal, mask=mask
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        gotg = jax.grad(
            loss(
                lambda q, k, v: flash_attention(
                    q, k, v, causal=causal, kv_mask=mask,
                    block_q=32, block_k=32,
                )
            ),
            argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(ref, gotg):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4
            )

    def test_mqa_single_kv_head(self):
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(Hq=4, Hkv=1, S=64, D=32)
        ref = dot_product_attention(q, k, v, causal=True)
        out = flash_attention(q, k, v, causal=True, block_q=32, block_k=32)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )

    def test_uneven_block_sizes_are_clamped(self):
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        # S=96 not divisible by 64 -> block picker drops to 48/32
        q, k, v = self._qkv(S=96, D=32)
        ref = dot_product_attention(q, k, v)
        out = flash_attention(q, k, v, block_q=64, block_k=64)
        np.testing.assert_allclose(
            np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
        )


    @pytest.mark.parametrize("causal", [False, True])
    def test_segment_ids_match_xla_fwd_and_grads(self, causal):
        """Packed-sequence (segment-id) attention in-kernel matches the
        einsum path, fwd and grads, with boundaries off block edges."""
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        q, k, v = self._qkv(S=64, D=32)
        B = q.shape[0]
        rng = np.random.default_rng(0)
        # 3 segments per row, ragged boundaries (never multiples of 32)
        seg = np.zeros((B, 64), np.int32)
        for b in range(B):
            cuts = sorted(rng.choice(np.arange(5, 60), size=2, replace=False))
            seg[b, :cuts[0]] = 1
            seg[b, cuts[0]:cuts[1]] = 2
            seg[b, cuts[1]:] = 3
        seg = jnp.asarray(seg)

        want = dot_product_attention(q, k, v, causal=causal, segment_ids=seg)
        got = flash_attention(
            q, k, v, causal=causal, segment_ids=seg, block_q=32, block_k=32
        )
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
        )

        def loss(fn):
            return lambda q, k, v: (fn(q, k, v) ** 2).sum()

        ref = jax.grad(
            loss(lambda q, k, v: dot_product_attention(
                q, k, v, causal=causal, segment_ids=seg
            )), argnums=(0, 1, 2),
        )(q, k, v)
        gotg = jax.grad(
            loss(lambda q, k, v: flash_attention(
                q, k, v, causal=causal, segment_ids=seg,
                block_q=32, block_k=32,
            )), argnums=(0, 1, 2),
        )(q, k, v)
        for a, b in zip(ref, gotg):
            np.testing.assert_allclose(
                np.asarray(b), np.asarray(a), rtol=1e-4, atol=1e-4
            )

    def test_packed_equals_separate_sequences(self):
        """Packing two docs in one row with segment_ids reproduces each
        doc attended alone — the invariant packing exists to provide."""
        from pytorch_distributed_tpu.ops.flash_attention import flash_attention

        rng = np.random.default_rng(1)
        d1 = rng.normal(size=(1, 24, 2, 16)).astype(np.float32)
        d2 = rng.normal(size=(1, 40, 2, 16)).astype(np.float32)
        packed = jnp.asarray(np.concatenate([d1, d2], axis=1))
        seg = jnp.asarray(
            np.concatenate([np.full(24, 1), np.full(40, 2)])[None, :]
        )
        out = flash_attention(
            packed, packed, packed, causal=True, segment_ids=seg,
            block_q=16, block_k=16,
        )
        a1 = dot_product_attention(
            jnp.asarray(d1), jnp.asarray(d1), jnp.asarray(d1), causal=True
        )
        a2 = dot_product_attention(
            jnp.asarray(d2), jnp.asarray(d2), jnp.asarray(d2), causal=True
        )
        np.testing.assert_allclose(
            np.asarray(out[:, :24]), np.asarray(a1), rtol=2e-5, atol=2e-6
        )
        np.testing.assert_allclose(
            np.asarray(out[:, 24:]), np.asarray(a2), rtol=2e-5, atol=2e-6
        )


class TestAttentionDispatch:
    def test_default_is_xla_on_cpu(self):
        import pytorch_distributed_tpu.ops.attention as A

        assert A.get_attention_impl() == "auto"
        q = jnp.ones((1, 8, 2, 16))
        out = A.attention(q, q, q, causal=True)
        assert out.shape == q.shape

    def test_forced_flash_dispatch(self):
        import pytorch_distributed_tpu.ops.attention as A

        A.set_attention_impl("flash")
        try:
            q = jnp.ones((1, 32, 2, 16), jnp.float32)
            out = A.attention(q, q, q, causal=True)
            ref = A.dot_product_attention(q, q, q, causal=True)
            np.testing.assert_allclose(
                np.asarray(out), np.asarray(ref), rtol=2e-5, atol=2e-5
            )
        finally:
            A.set_attention_impl("auto")

    def test_4d_mask_falls_back_to_xla(self):
        import pytorch_distributed_tpu.ops.attention as A

        A.set_attention_impl("flash")
        try:
            q = jnp.ones((2, 8, 2, 16))
            mask = jnp.ones((2, 1, 8, 8), bool)
            out = A.attention(q, q, q, mask=mask)  # must not hit the kernel
            assert out.shape == q.shape
        finally:
            A.set_attention_impl("auto")

    def test_2d_padding_mask_dispatches_to_flash(self):
        """BERT-style [B, T] masks are in-kernel now: forced-flash output
        with a padding mask matches the XLA path."""
        import pytorch_distributed_tpu.ops.attention as A

        rng = np.random.default_rng(3)
        q = jnp.asarray(rng.normal(size=(2, 16, 2, 16)).astype(np.float32))
        mask = jnp.asarray(
            np.arange(16)[None, :] < np.array([[11], [16]])
        )
        want = A.dot_product_attention(q, q, q, mask=mask)
        A.set_attention_impl("flash")
        try:
            got = A.attention(q, q, q, mask=mask)
        finally:
            A.set_attention_impl("auto")
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-6
        )

    def test_bad_impl_rejected(self):
        import pytorch_distributed_tpu.ops.attention as A

        with pytest.raises(ValueError):
            A.set_attention_impl("cudnn")


class TestWeightDropoutAndFlashScale:
    """Post-softmax weight dropout (HF/torch attn_dropout semantics) and
    the dispatcher letting custom scales ride the flash kernel."""

    def _qkv(self, seed=0, shape=(2, 8, 4, 16)):
        rng = np.random.default_rng(seed)
        return tuple(
            jnp.asarray(rng.normal(size=shape).astype(np.float32))
            for _ in range(3)
        )

    def test_dropout_single_key_is_inverted_bernoulli(self):
        # T=1: softmax weight is exactly 1, so each output row is either
        # v/(1-p) (kept) or 0 (dropped) — pins the inverted scaling.
        # S=64 rows so "both outcomes appear" is robust to PRNG
        # bit-stream changes (P[all same] ~ 2*0.5^64)
        q = jnp.ones((1, 64, 1, 8))
        k = jnp.ones((1, 1, 1, 8))
        v = jnp.full((1, 1, 1, 8), 3.0)
        p = 0.5
        out = np.asarray(
            dot_product_attention(
                q, k, v, dropout_rate=p, dropout_rng=jax.random.key(0)
            )
        )
        kept = np.isclose(out, 3.0 / (1 - p))
        dropped = np.isclose(out, 0.0)
        assert np.all(kept | dropped)
        assert kept.any() and dropped.any()  # both outcomes at p=0.5

    def test_dropout_requires_rng(self):
        q, k, v = self._qkv()
        with pytest.raises(ValueError, match="dropout_rng"):
            dot_product_attention(q, k, v, dropout_rate=0.1)

    def test_dropout_zero_identical_to_base(self):
        q, k, v = self._qkv(3)
        base = dot_product_attention(q, k, v)
        zero = dot_product_attention(
            q, k, v, dropout_rate=0.0, dropout_rng=jax.random.key(0)
        )
        np.testing.assert_array_equal(np.asarray(base), np.asarray(zero))

    def test_dispatcher_flash_takes_custom_scale(self, monkeypatch):
        # a non-None scale (T5's 1.0) must ride the flash kernel when
        # selected, not silently fall back to einsum (ADVICE r4) —
        # interpret mode on CPU, numerics vs the einsum path
        import pytorch_distributed_tpu.ops.attention as attn_mod

        q, k, v = self._qkv(4, (1, 64, 2, 16))
        want = dot_product_attention(q, k, v, scale=1.0)
        monkeypatch.setattr(attn_mod, "_IMPL", "flash")
        called = {}
        import importlib

        fa_mod = importlib.import_module(
            "pytorch_distributed_tpu.ops.flash_attention"
        )

        real = fa_mod.flash_attention

        def spy(*a, **kw):
            called["sm_scale"] = kw.get("sm_scale")
            return real(*a, **kw)

        monkeypatch.setattr(fa_mod, "flash_attention", spy)
        got = attn_mod.attention(q, k, v, scale=1.0)
        assert called["sm_scale"] == 1.0  # flash path actually taken
        np.testing.assert_allclose(
            np.asarray(got), np.asarray(want), rtol=2e-5, atol=2e-5
        )


class TestInt8KVCache:
    """int8 KV cache: exact machinery pin (the resident buffer holds
    round-to-nearest int8 + per-token scales, and the read returns
    exactly dequant(quant(x))), error bound, and end-to-end decode."""

    def _run_cache(self, quantize, k, v, max_len=16):
        import flax.linen as nn

        from pytorch_distributed_tpu.ops.attention import decode_cache

        class M(nn.Module):
            @nn.compact
            def __call__(self, k, v):
                return decode_cache(self, k, v, max_len, quantize=quantize)

        m = M()
        # init IS the first write (flax runs the module); its outputs
        # and cache are the single-write state the asserts reason about
        (k_all, v_all, _), vars1 = m.init_with_output(
            jax.random.key(0), k, v
        )
        return np.asarray(k_all), np.asarray(v_all), vars1["cache"]

    def test_int8_read_is_exact_dequant_of_quant(self):
        rng = np.random.default_rng(0)
        k = jnp.asarray(rng.normal(size=(2, 5, 3, 8)).astype(np.float32))
        v = jnp.asarray(rng.normal(size=(2, 5, 3, 8)).astype(np.float32))
        k_all, v_all, cache = self._run_cache("int8", k, v)
        assert cache["cached_key"].dtype == jnp.int8  # resident = int8
        assert cache["cached_value"].dtype == jnp.int8
        # manual quant-dequant reference
        for x, got in ((np.asarray(k), k_all), (np.asarray(v), v_all)):
            amax = np.abs(x).max(-1, keepdims=True)
            scale = np.where(amax > 0, amax / 127.0, 1.0)
            q = np.clip(np.round(x / scale), -127, 127).astype(np.int8)
            np.testing.assert_array_equal(got[:, :5], q * scale)
            np.testing.assert_array_equal(got[:, 5:], 0.0)  # unwritten
        # error bound: half a quantization step per element
        err = np.abs(k_all[:, :5] - np.asarray(k))
        bound = np.abs(np.asarray(k)).max(-1, keepdims=True) / 127.0
        assert (err <= bound / 2 + 1e-6).all()

    def test_int8_cache_quarters_resident_bytes(self):
        rng = np.random.default_rng(1)
        k = jnp.asarray(rng.normal(size=(1, 4, 2, 64)).astype(np.float32))
        _, _, exact = self._run_cache(None, k, k)
        _, _, q8 = self._run_cache("int8", k, k)
        exact_b = exact["cached_key"].nbytes + exact["cached_value"].nbytes
        q8_b = sum(np.asarray(q8[n]).nbytes for n in (
            "cached_key", "cached_value",
            "cached_key_scale", "cached_value_scale",
        ))
        assert q8_b < exact_b / 3  # 4x payload - scale overhead

    def test_int8_rejects_unknown_kind(self):
        with pytest.raises(ValueError, match="int8"):
            self._run_cache(
                "int4",
                jnp.zeros((1, 2, 1, 4)), jnp.zeros((1, 2, 1, 4)),
            )

    def test_llama_decode_with_int8_cache_mostly_agrees(self):
        """End-to-end on a tiny Llama: the int8 cache drives generate
        through the normal machinery and greedy tokens mostly agree
        with the exact cache (lossy by design, not bitwise)."""
        import dataclasses

        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.models import (
            LlamaConfig,
            LlamaForCausalLM,
        )

        cfg = LlamaConfig.tiny()
        ids = jnp.asarray(
            np.random.default_rng(0).integers(2, 500, size=(4, 6)),
            jnp.int32,
        )
        params = LlamaForCausalLM(cfg).init(jax.random.key(0), ids)[
            "params"
        ]
        exact = ptd.generate(
            LlamaForCausalLM(cfg), params, ids, max_new_tokens=8,
            temperature=0.0,
        )
        q8 = ptd.generate(
            LlamaForCausalLM(
                dataclasses.replace(cfg, kv_cache_quantize="int8")
            ),
            params, ids, max_new_tokens=8, temperature=0.0,
        )
        agree = float(
            (np.asarray(exact)[:, 6:] == np.asarray(q8)[:, 6:]).mean()
        )
        # random-init logits are chaotic, the WORST case for a lossy
        # cache; trained models agree far more. >=half is the loose
        # machinery pin — a broken cache scores ~1/vocab
        assert agree >= 0.5, agree

    def test_beam_search_carries_int8_cache_scales(self):
        """generate_beam replicates/reorders the scale buffers in
        lockstep with their int8 payloads (before r5 the scales were
        skipped: trace-time crash on the first beam step)."""
        import dataclasses

        from pytorch_distributed_tpu.generation import generate_beam
        from pytorch_distributed_tpu.models import (
            LlamaConfig,
            LlamaForCausalLM,
        )

        cfg = dataclasses.replace(
            LlamaConfig.tiny(), kv_cache_quantize="int8"
        )
        model = LlamaForCausalLM(cfg)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(2, 500, size=(2, 5)),
            jnp.int32,
        )
        params = model.init(jax.random.key(0), ids)["params"]
        out = generate_beam(
            model, params, ids, max_new_tokens=5, num_beams=3
        )
        assert out.shape == (2, 10)
        assert bool((np.asarray(out) >= 0).all())
