"""Comms observability: wire-level collective accounting, cross-rank
trace merge, and the calibrated α–β cost model.

The contracts under test: every HostRingGroup collective records a
``comm.*`` span whose wire bytes follow the NCCL convention EXACTLY
(q8 counts its real int8+scales payload — the ~4x reduction is a
recorded fact); disarmed collectives stay on the shared no-op object;
``scripts/trace_merge.py`` aligns per-rank traces into one Perfetto
timeline with temporally-consistent tracks; the cost model recovers a
synthetic α–β within tolerance and ``collective_bench --fit`` emits a
``costmodel.json`` whose predictions hold within 2x on its own sweep;
coalesced ``sync_grads`` is bit-identical to per-leaf (world 2) with
the span counts proving the collective-count drop; and DETAIL debug
mode now names barrier/P2P divergence instead of hanging.
"""

import json
import os
import subprocess
import sys
import uuid

import numpy as np
import pytest

from pytorch_distributed_tpu.runtime import costmodel, tracing
from pytorch_distributed_tpu.runtime.hostring import (
    Q8_BLOCK,
    _COMM_CUM,
    algo_wire_bytes,
    q8_wire_payload,
)
from tests import hostring_workers

pytestmark = pytest.mark.obs

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCRIPTS = os.path.join(REPO, "scripts")


_run = hostring_workers.run_ring_workers  # THE shared spawn harness


# -- wire-byte accounting --------------------------------------------------
class TestWireBytes:
    def test_nccl_convention_factors(self):
        # per-participant algorithmic bytes, the NCCL-tests busbw basis
        assert algo_wire_bytes("all_reduce", 1000, 4) == 1500  # 2(n-1)/n
        assert algo_wire_bytes("all_gather", 1000, 4) == 750  # (n-1)/n
        assert algo_wire_bytes("reduce_scatter", 1000, 4) == 750
        assert algo_wire_bytes("broadcast", 1000, 4) == 1000
        assert algo_wire_bytes("send", 1000, 4) == 1000
        assert algo_wire_bytes("recv", 1000, 4) == 1000
        assert algo_wire_bytes("permute", 1000, 4) == 1000
        assert algo_wire_bytes("barrier", 0, 4) == 0
        # a one-rank world moves nothing, whatever the op
        assert algo_wire_bytes("all_reduce", 1000, 1) == 0
        with pytest.raises(ValueError):
            algo_wire_bytes("gossip", 1000, 4)

    def test_q8_wire_payload_is_the_real_bytes(self):
        # one int8 per element + one f32 scale per 256-element block
        assert Q8_BLOCK == 256
        assert q8_wire_payload(256) == 256 + 4
        assert q8_wire_payload(257) == 257 + 8  # ragged tail block
        n = 6_400_000  # the ROADMAP gradient size
        ratio = q8_wire_payload(n) / (n * 4)
        assert ratio == pytest.approx(0.2539, abs=0.0005)
        # the acceptance bound: ~0.26x f32 at >= 4096-element sizes
        for n in (4096, 65536, 1 << 20):
            assert q8_wire_payload(n) / (n * 4) < 0.26

    def test_disarmed_comm_sites_stay_shared_noop(self):
        from pytorch_distributed_tpu.runtime.hostring import HostRingGroup

        tracing.clear()
        before = dict(_COMM_CUM)
        with HostRingGroup(f"ptdobs_{uuid.uuid4().hex[:8]}", 0, 1) as g:
            g.all_reduce(np.ones(64, np.float32))
            g.barrier()
            g.broadcast(np.ones(4, np.float32))
        # disarmed collectives never touch the cumulative comm tracks
        assert dict(_COMM_CUM) == before
        # and the armed-path builder is unreachable: the site pattern is
        # `tracing._NULL_SPAN if tracing._tracer is None else ...`
        assert tracing._tracer is None
        assert tracing.span("comm.all_reduce") is tracing._NULL_SPAN

    def test_counter_tracks_reset_per_tracer(self):
        """A re-armed tracing window starts its comm.<op> counter
        tracks from zero — not from the previous window's totals."""
        from pytorch_distributed_tpu.runtime.hostring import (
            HostRingGroup,
            reset_comm_counters,
        )

        def last_calls(t):
            vals = [
                e["args"]["value"] for e in t._events
                if e["ph"] == "C" and e["name"] == "comm.all_reduce.calls"
            ]
            return vals[-1] if vals else None

        with HostRingGroup(f"ptdobs_{uuid.uuid4().hex[:8]}", 0, 1) as g:
            with tracing.enabled() as t1:
                g.all_reduce(np.ones(8, np.float32))
                g.all_reduce(np.ones(8, np.float32))
                assert last_calls(t1) == 2
            with tracing.enabled() as t2:  # fresh window, fresh totals
                g.all_reduce(np.ones(8, np.float32))
                assert last_calls(t2) == 1
                reset_comm_counters()  # explicit window reset (bench)
                g.all_reduce(np.ones(8, np.float32))
                assert last_calls(t2) == 1

    def test_comm_spans_multiprocess(self):
        """2-proc ring: every op's span schema + exact wire bytes +
        counter tracks + rollup GB/s + clock-sync metadata."""
        results = _run(2, hostring_workers.comm_span_worker)
        assert results == [(r, "ok") for r in range(2)], results


# -- debug-mode coverage (barrier + P2P) -----------------------------------
class TestDebugFingerprints:
    def test_barrier_mismatch_detected(self):
        results = _run(2, hostring_workers.debug_barrier_mismatch_worker)
        assert results == [(r, "ok") for r in range(2)], results

    def test_p2p_mismatch_detected_both_sides(self):
        results = _run(3, hostring_workers.debug_p2p_worker)
        assert results == [(r, "ok") for r in range(3)], results


# -- cross-rank trace merge ------------------------------------------------
class TestTraceMerge:
    def test_merged_timeline_is_consistent(self, tmp_path):
        world = 3
        results = _run(
            world, hostring_workers.trace_export_worker,
            extra_args=(str(tmp_path),),
        )
        assert results == [(r, "ok") for r in range(world)], results

        sys.path.insert(0, SCRIPTS)
        try:
            import trace_merge
        finally:
            sys.path.pop(0)
        rc = trace_merge.main([str(tmp_path)])
        assert rc == 0
        out = os.path.join(str(tmp_path), "merged_trace.json")
        doc = json.load(open(out))
        events = doc["traceEvents"]
        # one named process track per rank
        names = {
            e["pid"]: e["args"]["name"] for e in events
            if e.get("ph") == "M" and e["name"] == "process_name"
        }
        assert names == {r: f"rank{r}" for r in range(world)}
        assert set(doc["otherData"]["ranks"]) == {
            str(r) for r in range(world)
        }
        # per-rank tracks are monotonically consistent: the k-th
        # collective starts after the (k-1)-th ended
        per_rank = {}
        for e in events:
            if e.get("ph") == "X" and e["name"] == "comm.all_reduce":
                per_rank.setdefault(e["pid"], []).append(e)
        assert set(per_rank) == set(range(world))
        for r, evs in per_rank.items():
            evs.sort(key=lambda e: e["ts"])
            assert len(evs) == 4
            for a, b in zip(evs, evs[1:]):
                assert a["ts"] + a["dur"] <= b["ts"] + 1, (r, a, b)
        # the k-th occurrence is the SAME collective on every rank
        # (barrier lockstep), so the aligned intervals must OVERLAP —
        # the merged-clock consistency claim, not just per-rank order
        tol_us = 2000.0  # barrier-exit jitter bound on this 1-core box
        for k in range(4):
            start = max(per_rank[r][k]["ts"] for r in range(world))
            end = min(
                per_rank[r][k]["ts"] + per_rank[r][k]["dur"]
                for r in range(world)
            )
            assert start <= end + tol_us, (k, start, end)
        # straggler skew was summarized for obs_report (rank r sleeps
        # 2ms x r before issuing, so skew is real and visible)
        skew = doc["otherData"]["comm_skew"]
        assert "comm.all_reduce" in skew
        assert skew["comm.all_reduce"]["ranks"] == world
        assert skew["comm.all_reduce"]["skew_ms_max"] > 0.5

        # obs_report renders the comms section from the merged trace
        sys.path.insert(0, SCRIPTS)
        try:
            import obs_report
        finally:
            sys.path.pop(0)
        import io

        buf = io.StringIO()
        obs_report.report(out, [], out=buf)
        text = buf.getvalue()
        assert "== Comms ==" in text
        assert "comm.all_reduce" in text
        assert "straggler skew" in text

    def test_merge_refuses_duplicate_ranks(self, tmp_path):
        sys.path.insert(0, SCRIPTS)
        try:
            import trace_merge
        finally:
            sys.path.pop(0)
        doc = {"traceEvents": [], "otherData": {"wall_start_unix_s": 1.0,
                                                "meta": {"rank": 0}}}
        a, b = str(tmp_path / "a.json"), str(tmp_path / "b.json")
        for p in (a, b):
            json.dump(doc, open(p, "w"))
        with pytest.raises(ValueError, match="duplicate ranks"):
            trace_merge.merge([a, b])

    def test_merge_refuses_traces_without_wall_anchor(self, tmp_path):
        """A trace with no wall_start_unix_s cannot be clock-aligned;
        defaulting it to 0 would shift real ranks decades apart —
        refuse loudly instead of emitting silent garbage."""
        sys.path.insert(0, SCRIPTS)
        try:
            import trace_merge
        finally:
            sys.path.pop(0)
        good = {"traceEvents": [], "otherData": {
            "wall_start_unix_s": 1.0, "meta": {"rank": 0}}}
        bare = [{"name": "x", "ph": "X", "ts": 1.0, "dur": 1.0,
                 "pid": 1, "tid": 1}]  # bare-array form: no anchor
        a, b = str(tmp_path / "a.json"), str(tmp_path / "bare.json")
        json.dump(good, open(a, "w"))
        json.dump(bare, open(b, "w"))
        with pytest.raises(ValueError, match="wall_start_unix_s"):
            trace_merge.merge([a, b])


# -- cost model ------------------------------------------------------------
class TestCostModel:
    def _synthetic(self, alpha, beta, op="all_reduce", world=4, noise=0.0):
        rng = np.random.default_rng(0)
        records = []
        for payload in (1e4, 1e5, 1e6, 4e6, 1.6e7):
            wire = algo_wire_bytes(op, int(payload), world)
            t = alpha + beta * wire
            records.append({
                "op": op, "payload_bytes": int(payload), "world": world,
                "seconds": t * (1.0 + noise * rng.normal()),
            })
        return records

    def test_fit_recovers_synthetic_alpha_beta(self):
        alpha, beta = 250e-6, 0.8e-9  # 250us latency, 1.25 GB/s
        model = costmodel.fit(
            self._synthetic(alpha, beta, noise=0.02), "test"
        )
        f = model.fits[("all_reduce", 4)]
        assert f.alpha_s == pytest.approx(alpha, rel=0.25)
        assert f.beta_s_per_byte == pytest.approx(beta, rel=0.1)
        assert f.r2 > 0.99
        assert f.bandwidth_gb_s == pytest.approx(1.25, rel=0.1)
        # predictions on the calibration range are tight
        p = model.predict("all_reduce", 1_000_000, 4)
        want = alpha + beta * algo_wire_bytes("all_reduce", 1_000_000, 4)
        assert p.seconds == pytest.approx(want, rel=0.1)
        assert not p.extrapolated
        # the acceptance bar: within 2x across the whole sweep
        worst = costmodel.validate(
            model, self._synthetic(alpha, beta, noise=0.02)
        )
        assert worst["all_reduce"] < 2.0

    def test_predict_flags_extrapolation(self):
        model = costmodel.fit(self._synthetic(1e-4, 1e-9), "test")
        # outside the calibrated size range
        assert model.predict("all_reduce", int(1e9), 4).extrapolated
        # unbenched world: β carries, α scales by barrier phases
        p = model.predict("all_reduce", 1_000_000, 8)
        assert p.extrapolated
        f = model.fits[("all_reduce", 4)]
        want = f.alpha_s * 7 / 3 + f.beta_s_per_byte * algo_wire_bytes(
            "all_reduce", 1_000_000, 8
        )
        assert p.seconds == pytest.approx(want)
        # an op it never saw must refuse, not guess
        with pytest.raises(KeyError):
            model.predict("all_to_all", 1000, 4)

    def test_save_load_roundtrip(self, tmp_path):
        model = costmodel.fit(self._synthetic(1e-4, 1e-9), "spmd:cpu")
        path = model.save(str(tmp_path / "costmodel.json"))
        loaded = costmodel.CostModel.load(path)
        assert loaded.transport == "spmd:cpu"
        assert loaded.fits == model.fits
        doc = json.load(open(path))
        assert doc["format_version"] == costmodel.FORMAT_VERSION
        doc["format_version"] = 99
        with pytest.raises(ValueError, match="format"):
            costmodel.CostModel.from_dict(doc)

    def test_fit_from_metrics_records(self):
        recs = [
            {"split": "comm_bench", "event": "collective", **r,
             "transport": "spmd:cpu"}
            for r in self._synthetic(2e-4, 2e-9)
        ] + [{"split": "train", "loss": 1.0}]  # foreign records ignored
        model = costmodel.fit_from_metrics(recs)
        assert model.transport == "spmd:cpu"
        assert ("all_reduce", 4) in model.fits
        # mixed transports refuse without an explicit pick
        recs.append({"split": "comm_bench", "event": "collective",
                     "op": "all_reduce", "payload_bytes": 1000,
                     "world": 4, "seconds": 1.0,
                     "transport": "hostring"})
        with pytest.raises(ValueError, match="transports"):
            costmodel.fit_from_metrics(recs)
        model = costmodel.fit_from_metrics(recs, transport="spmd:cpu")
        assert model.fits[("all_reduce", 4)].n_samples == 5

    def test_single_size_degenerates_to_pure_bandwidth(self):
        model = costmodel.fit([{
            "op": "all_gather", "payload_bytes": 1_000_000, "world": 2,
            "seconds": 1e-3,
        }], "test")
        f = model.fits[("all_gather", 2)]
        assert f.alpha_s == 0.0
        wire = algo_wire_bytes("all_gather", 1_000_000, 2)
        assert f.beta_s_per_byte == pytest.approx(1e-3 / wire)


# -- collective_bench integration ------------------------------------------
def test_collective_bench_metrics_and_fit(tmp_path):
    """The CLI writes JSONL records and a calibrated costmodel.json
    whose predictions hold within 2x on its own sweep (the acceptance
    bar) — on the virtual 8-device CPU mesh."""
    from pytorch_distributed_tpu.train.metrics import read_metrics

    metrics = str(tmp_path / "comm.jsonl")
    model_path = str(tmp_path / "costmodel.json")
    env = dict(os.environ, JAX_PLATFORMS="cpu", PALLAS_AXON_POOL_IPS="",
               PTD_BENCH_LOCK_PATH=str(tmp_path / "bench.lock"))
    env["XLA_FLAGS"] = "--xla_force_host_platform_device_count=8"
    proc = subprocess.run(
        [sys.executable, os.path.join(SCRIPTS, "collective_bench.py"),
         "--sizes", "0.02", "0.08", "0.32", "--iters", "5",
         "--metrics-path", metrics, "--fit", model_path],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=600,
    )
    assert proc.returncode == 0, proc.stderr[-2000:] + proc.stdout[-2000:]
    recs = [
        r for r in read_metrics(metrics)
        if r.get("split") == "comm_bench"
    ]
    assert len(recs) == 12, recs  # 4 ops x 3 sizes
    ops = {r["op"] for r in recs}
    assert ops == {"all_reduce", "all_gather", "reduce_scatter",
                   "permute"}
    for r in recs:
        assert r["world"] == 8
        assert r["seconds"] > 0
        assert r["transport"] == "spmd:cpu"
        assert r["wire_bytes"] > 0
    model = costmodel.CostModel.load(model_path)
    assert model.transport == "spmd:cpu"
    assert set(model.ops()) == ops
    # acceptance: predictions within 2x of measured across the sweep
    worst = costmodel.validate(model, recs)
    assert worst and max(worst.values()) < 2.0, worst


# -- coalesced sync_grads --------------------------------------------------
class TestCoalescedSyncGrads:
    def test_bit_identical_and_fewer_collectives(self):
        """world 2: 6 tiny + 1 big leaf -> exactly 2 collectives, flat
        result bit-identical to per-leaf, q8 keeps the flat exact."""
        results = _run(
            2, hostring_workers.coalesce_worker, timeout=300.0
        )
        assert results == [(r, "ok") for r in range(2)], results

    def test_single_controller_is_noop(self):
        """Without a multi-process ring sync_grads stays the identity —
        the coalescing path must not perturb the SPMD case."""
        from pytorch_distributed_tpu.parallel.ddp import sync_grads

        grads = {"a": np.ones(10, np.float32),
                 "b": np.ones(5, np.float32)}
        out = sync_grads(grads)
        assert out is grads
