"""Mixtral sparse-MoE decoder: HF parity, expert math, decode, sharding.

Correctness pins, strongest first:

* HF ``MixtralForCausalLM`` logit parity through converted weights
  (scan and unrolled layouts) — routing renormalization, per-expert
  SwiGLU, and the drop-free dispatch all have to be exact;
* export -> HF load -> logits match (the mapping is invertible);
* drop-free MoE output == a per-token dense reference computed straight
  from the params (dispatch/combine einsums pinned independently of HF);
* KV-cache greedy decode == full-recompute argmax;
* the load-balance aux loss flows gradients into the router through the
  scanned stack.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

import pytorch_distributed_tpu as ptd
from pytorch_distributed_tpu.models import (
    MixtralConfig,
    MixtralForCausalLM,
    mixtral_partition_rules,
)
from pytorch_distributed_tpu.runtime.mesh import MeshSpec
from pytorch_distributed_tpu.runtime.precision import autocast

torch = pytest.importorskip("torch")
transformers = pytest.importorskip("transformers")


def _sd(model):
    return {k: v.detach().numpy() for k, v in model.state_dict().items()}


def _pair(scan_layers: bool):
    torch.manual_seed(0)
    hf_cfg = transformers.MixtralConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96,
        num_hidden_layers=2, num_attention_heads=4, num_key_value_heads=2,
        num_local_experts=4, num_experts_per_tok=2, rope_theta=1e6,
        rms_norm_eps=1e-5, max_position_embeddings=128,
    )
    hf = transformers.MixtralForCausalLM(hf_cfg).eval()
    cfg = MixtralConfig(
        vocab_size=211, hidden_size=48, intermediate_size=96, num_layers=2,
        num_heads=4, num_kv_heads=2, num_experts=4, top_k=2,
        max_seq_len=128, rope_theta=1e6, rms_eps=1e-5,
        scan_layers=scan_layers,
    )
    return hf, cfg


def _logits_match(hf, cfg, atol=3e-4):
    from pytorch_distributed_tpu.interop import load_mixtral_weights

    params = load_mixtral_weights(_sd(hf), cfg)
    rng = np.random.default_rng(0)
    ids = rng.integers(2, 211, size=(2, 11)).astype(np.int32)
    with torch.no_grad():
        want = hf(torch.tensor(ids.astype(np.int64))).logits.numpy()
    with autocast(enabled=False):
        got = MixtralForCausalLM(cfg).apply(
            {"params": params}, jnp.asarray(ids)
        )
    np.testing.assert_allclose(np.asarray(got), want, atol=atol, rtol=2e-4)
    return params


def test_mixtral_logits_match_hf_scan():
    hf, cfg = _pair(scan_layers=True)
    _logits_match(hf, cfg)


@pytest.mark.slow  # r5 profile refit: scan-layout HF parity + export roundtrip stay fast
def test_mixtral_logits_match_hf_unrolled():
    hf, cfg = _pair(scan_layers=False)
    _logits_match(hf, cfg)


def test_mixtral_export_roundtrips_into_hf():
    from pytorch_distributed_tpu.interop import (
        export_mixtral_weights,
        load_mixtral_weights,
    )

    hf, cfg = _pair(scan_layers=True)
    params = load_mixtral_weights(_sd(hf), cfg)
    sd = export_mixtral_weights(params, cfg)
    hf2 = transformers.MixtralForCausalLM(hf.config).eval()
    hf2.load_state_dict({k: torch.tensor(v) for k, v in sd.items()})
    ids = torch.tensor(
        np.random.default_rng(1).integers(2, 211, size=(1, 9)).astype(
            np.int64
        )
    )
    with torch.no_grad():
        np.testing.assert_allclose(
            hf2(ids).logits.numpy(), hf(ids).logits.numpy(),
            atol=1e-5, rtol=1e-5,
        )


def test_moe_dropfree_swiglu_matches_dense_reference():
    """Drop-free top-k dispatch == per-token dense computation straight
    from the params: y_t = sum_k gate_k * w_out[e_k]^T(silu(w_gate[e_k]
    x_t) * w_in[e_k] x_t), gates renormalized over the selected k.
    Pins the one-hot dispatch/combine einsums and the SwiGLU expert
    independently of HF."""
    from pytorch_distributed_tpu.ops.moe import MoEMLP

    D, F, E, K, T = 16, 24, 4, 2, 10
    m = MoEMLP(
        num_experts=E, d_ff=F, k=K, capacity_factor=None,
        activation="swiglu",
    )
    x = jnp.asarray(
        np.random.default_rng(0).normal(size=(T, D)), jnp.float32
    )
    with autocast(enabled=False):  # f32 compute to match the reference
        params = m.init(jax.random.key(0), x)["params"]
        got = np.asarray(m.apply({"params": params}, x))

    router = np.asarray(params["router"]["kernel"])  # [D, E]
    w_in = np.asarray(params["w_in"])  # [E, D, F]
    w_gate = np.asarray(params["w_gate"])
    w_out = np.asarray(params["w_out"])  # [E, F, D]
    xs = np.asarray(x)

    def silu(a):
        return a / (1.0 + np.exp(-a))

    probs = np.exp(xs @ router)
    probs = probs / probs.sum(-1, keepdims=True)
    want = np.zeros((T, D), np.float32)
    for t in range(T):
        top = np.argsort(-probs[t])[:K]
        gates = probs[t][top]
        gates = gates / gates.sum()
        for g, e in zip(gates, top):
            h = silu(xs[t] @ w_gate[e]) * (xs[t] @ w_in[e])
            want[t] += g * (h @ w_out[e])
    np.testing.assert_allclose(got, want, atol=2e-5, rtol=2e-4)


@pytest.mark.slow  # r5 profile refit: gpt2/t5 cache==recompute pins stay fast; HF parity pins this family
def test_mixtral_cache_decode_equals_recompute():
    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    rng = np.random.default_rng(0)
    ids = jnp.asarray(rng.integers(2, 500, size=(2, 6)), jnp.int32)
    params = model.init(jax.random.key(0), ids)["params"]
    got = ptd.generate(model, params, ids, max_new_tokens=8, temperature=0.0)
    # full-recompute greedy reference
    seq = np.asarray(ids)
    for _ in range(8):
        logits = model.apply({"params": params}, jnp.asarray(seq))
        nxt = np.asarray(jnp.argmax(logits[:, -1], -1))
        seq = np.concatenate([seq, nxt[:, None].astype(seq.dtype)], 1)
    np.testing.assert_array_equal(np.asarray(got), seq)  # prompt + new


@pytest.mark.slow  # r5 profile refit: moe aux-sown + HF parity + recipe smoke (slow) cover aux training
def test_mixtral_aux_loss_trains_router():
    """causal_lm_loss_fn(moe_aux_weight=...) must flow gradients into
    BOTH the experts and the router through the scanned stack (the
    router only gets gradient via the gate values / aux loss)."""
    import optax

    from pytorch_distributed_tpu.train import causal_lm_loss_fn

    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 12)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    loss_fn = causal_lm_loss_fn(model, moe_aux_weight=0.01)
    (loss, out), grads = jax.value_and_grad(loss_fn, has_aux=True)(
        params, {}, {"input_ids": ids}, jax.random.key(1)
    )
    assert np.isfinite(float(loss))
    assert float(out["metrics"]["moe_aux_loss"]) > 0.0
    block = grads["layers"]["block"]
    g_router = np.asarray(block["moe"]["router"]["kernel"])
    g_expert = np.asarray(block["moe"]["w_gate"])
    assert np.abs(g_router).max() > 0.0
    assert np.abs(g_expert).max() > 0.0
    # and a step applies cleanly
    tx = optax.adam(1e-3)
    opt_state = tx.init(params)
    updates, _ = tx.update(grads, opt_state, params)
    optax.apply_updates(params, updates)


def test_mixtral_generate_with_ep_tp_sharded_params():
    """Expert-parallel serving: params sharded by mixtral_partition_rules
    (experts over ep, expert hidden over tp) decode token-identically
    through the same generate call."""
    import optax

    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.train import TrainState

    ptd.init_process_group(mesh_spec=MeshSpec(dp=2, ep=2, tp=2))
    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    want = ptd.generate(model, params, ids, max_new_tokens=6, temperature=0.0)
    strategy = DataParallel(extra_rules=mixtral_partition_rules())
    state = strategy.place(TrainState.create(
        apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
    ))
    w_in = state.params["layers"]["block"]["moe"]["w_in"]
    spec = str(w_in.sharding.spec)
    assert "ep" in spec and "tp" in spec  # experts really shard
    got = ptd.generate(
        model, state.params, ids, max_new_tokens=6, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(got), np.asarray(want))


@pytest.mark.slow
def test_mixtral_recipe_smoke():
    import os
    import sys

    sys.path.insert(
        0, os.path.join(os.path.dirname(__file__), "..", "recipes")
    )
    import mixtral_moe

    state = mixtral_moe.main(
        [
            "--epochs", "1", "--steps-per-epoch", "2", "--batch-size", "8",
            "--seq-len", "8", "--eval-rows", "8", "--log-every", "1",
        ]
    )
    assert int(state.step) == 2


@pytest.mark.slow  # r5 final refit: HF parity + dense-ref stay fast; the decode variant is slow-tier
def test_mixtral_int4_scan_dequant_serving():
    """Quantized MoE serving: quantize_for_scan_dequant now reaches the
    expert tensors (w_in/w_gate/w_out — a sparse-MoE model's dominant
    payload, not named 'kernel') while the ROUTER stays full precision
    (its quantization error flips routing decisions). Per-layer
    scan-dequant forward must equal the whole-tree dequant forward
    bitwise — the same pin the dense families carry."""
    import dataclasses

    from pytorch_distributed_tpu.ops import (
        QuantizedModel,
        quantize_for_scan_dequant,
    )

    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    qmodel = MixtralForCausalLM(
        dataclasses.replace(cfg, scan_dequant=True)
    )
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 8)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    q = quantize_for_scan_dequant(params, "int4", min_size=512)

    block = q["layers"]["block"]
    # expert tensors quantized...
    assert set(block["moe"]["w_in"].keys()) == {"q4", "scale"}
    assert set(block["moe"]["w_gate"].keys()) == {"q4", "scale"}
    assert set(block["moe"]["w_out"].keys()) == {"q4", "scale"}
    # ...router (and everything outside the scan) untouched
    assert hasattr(block["moe"]["router"]["kernel"], "dtype")
    assert hasattr(q["embed"]["embedding"], "dtype")

    a = QuantizedModel(model).apply({"params": q}, ids)
    b = qmodel.apply({"params": q}, ids)
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


@pytest.mark.slow  # serving-path pin; the forward equality runs fast
def test_mixtral_int4_scan_dequant_decode():
    """Greedy decode through the per-layer scan-dequant MoE serving
    path == decode through whole-tree dequant over the SAME quantized
    tree — the bitwise pin the dense families carry, on sparse."""
    import dataclasses

    from pytorch_distributed_tpu.ops import (
        QuantizedModel,
        quantize_for_scan_dequant,
    )

    cfg = MixtralConfig.tiny()
    model = MixtralForCausalLM(cfg)
    qmodel = MixtralForCausalLM(
        dataclasses.replace(cfg, scan_dequant=True)
    )
    ids = jnp.asarray(
        np.random.default_rng(0).integers(2, 500, size=(2, 5)), jnp.int32
    )
    params = model.init(jax.random.key(0), ids)["params"]
    q = quantize_for_scan_dequant(params, "int4", min_size=512)
    a = ptd.generate(
        qmodel, q, ids, max_new_tokens=6, temperature=0.0
    )
    b = ptd.generate(
        QuantizedModel(model), q, ids, max_new_tokens=6, temperature=0.0
    )
    np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
