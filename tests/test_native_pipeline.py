"""Native batch assembly: threaded gather + fused image augment
(native/prefetch.cpp via data/native_pipeline.py)."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    ArrayDataset,
    DataLoader,
    ImageBatchPipeline,
    gather_rows,
)

N, H, W, C = 64, 12, 12, 3


def _dataset(seed=0):
    rng = np.random.default_rng(seed)
    return ArrayDataset(
        image=rng.integers(0, 256, size=(N, H, W, C)).astype(np.uint8),
        label=rng.integers(10, size=(N,)).astype(np.int64),
    )


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(50, 7, 3)).astype(np.float32)
    idx = rng.integers(0, 50, size=20)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])
    # 1-D rows too
    v = rng.integers(0, 100, size=(50,)).astype(np.int64)
    np.testing.assert_array_equal(gather_rows(v, idx), v[idx])


def test_gather_rows_rejects_out_of_range():
    with pytest.raises(RuntimeError):
        gather_rows(np.zeros((4, 2), np.float32), [0, 7])


def test_eval_pipeline_center_crop_normalize():
    ds = _dataset()
    crop = 8
    mean, std = (0.4, 0.5, 0.6), (0.2, 0.25, 0.3)
    pipe = ImageBatchPipeline(
        crop, train=False, mean=mean, std=std
    )
    idx = np.arange(10)
    batch = pipe(ds, idx)
    assert batch["image"].shape == (10, crop, crop, C)
    assert batch["image"].dtype == np.float32
    assert batch["label"].dtype == np.int32
    o = (H - crop) // 2
    want = ds.arrays["image"][idx, o:o + crop, o:o + crop, :].astype(
        np.float32
    ) / 255.0
    want = (want - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    np.testing.assert_allclose(batch["image"], want, atol=1e-6)
    np.testing.assert_array_equal(
        batch["label"], ds.arrays["label"][idx].astype(np.int32)
    )


def test_train_pipeline_crops_flips_deterministic():
    ds = _dataset()
    pipe = ImageBatchPipeline(8, train=True, seed=5)
    idx = np.arange(16)
    b1, b2 = pipe(ds, idx), pipe(ds, idx)
    # same (seed, indices) -> identical augmentation (resume contract)
    np.testing.assert_array_equal(b1["image"], b2["image"])
    # different index window -> different crops with overwhelming odds
    b3 = pipe(ds, idx + 1)
    assert not np.array_equal(b1["image"][:8], b3["image"][:8])
    # every output pixel value must exist in the source normalization LUT
    assert np.isfinite(b1["image"]).all()


def test_train_flip_is_a_real_flip():
    ds = _dataset()
    # crop == source size (after no pad): only flip varies
    pipe = ImageBatchPipeline(H, train=True, flip=True, seed=0,
                              mean=(0, 0, 0), std=(1, 1, 1))
    idx = np.arange(32)
    batch = pipe(ds, idx)
    src = ds.arrays["image"].astype(np.float32) / 255.0
    flipped = 0
    for i in range(32):
        if np.allclose(batch["image"][i], src[i], atol=1e-6):
            continue
        np.testing.assert_allclose(
            batch["image"][i], src[i][:, ::-1, :], atol=1e-6
        )
        flipped += 1
    assert 0 < flipped < 32  # both outcomes occurred


def test_padded_cifar_style_crop():
    ds = _dataset()
    pipe = ImageBatchPipeline(H, train=True, pad=2, seed=3)
    batch = pipe(ds, np.arange(4))
    assert batch["image"].shape == (4, H, H, C)
    assert np.isfinite(batch["image"]).all()


def test_dataloader_fetch_integration():
    ds = _dataset()
    pipe = ImageBatchPipeline(8, train=True, seed=1)
    loader = DataLoader(ds, 16, seed=0, fetch=pipe)
    batches = list(loader)
    assert len(batches) == N // 16
    for b in batches:
        assert b["image"].shape == (16, 8, 8, C)
        assert b["label"].shape == (16,)