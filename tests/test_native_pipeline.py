"""Native batch assembly: threaded gather + fused image augment
(native/prefetch.cpp via data/native_pipeline.py)."""

import numpy as np
import pytest

from pytorch_distributed_tpu.data import (
    ArrayDataset,
    DataLoader,
    ImageBatchPipeline,
    gather_rows,
)

N, H, W, C = 64, 12, 12, 3


def _dataset(seed=0):
    rng = np.random.default_rng(seed)
    return ArrayDataset(
        image=rng.integers(0, 256, size=(N, H, W, C)).astype(np.uint8),
        label=rng.integers(10, size=(N,)).astype(np.int64),
    )


def test_gather_rows_matches_numpy():
    rng = np.random.default_rng(1)
    src = rng.normal(size=(50, 7, 3)).astype(np.float32)
    idx = rng.integers(0, 50, size=20)
    np.testing.assert_array_equal(gather_rows(src, idx), src[idx])
    # 1-D rows too
    v = rng.integers(0, 100, size=(50,)).astype(np.int64)
    np.testing.assert_array_equal(gather_rows(v, idx), v[idx])


def test_gather_rows_rejects_out_of_range():
    with pytest.raises(RuntimeError):
        gather_rows(np.zeros((4, 2), np.float32), [0, 7])


def test_eval_pipeline_center_crop_normalize():
    ds = _dataset()
    crop = 8
    mean, std = (0.4, 0.5, 0.6), (0.2, 0.25, 0.3)
    pipe = ImageBatchPipeline(
        crop, train=False, mean=mean, std=std, device_normalize=False
    )
    idx = np.arange(10)
    batch = pipe(ds, idx)
    assert batch["image"].shape == (10, crop, crop, C)
    assert batch["image"].dtype == np.float32
    assert batch["label"].dtype == np.int32
    o = (H - crop) // 2
    want = ds.arrays["image"][idx, o:o + crop, o:o + crop, :].astype(
        np.float32
    ) / 255.0
    want = (want - np.asarray(mean, np.float32)) / np.asarray(std, np.float32)
    np.testing.assert_allclose(batch["image"], want, atol=1e-6)
    np.testing.assert_array_equal(
        batch["label"], ds.arrays["label"][idx].astype(np.int32)
    )


def test_train_pipeline_crops_flips_deterministic():
    ds = _dataset()
    pipe = ImageBatchPipeline(8, train=True, seed=5, device_normalize=False)
    idx = np.arange(16)
    b1, b2 = pipe(ds, idx), pipe(ds, idx)
    # same (seed, indices) -> identical augmentation (resume contract)
    np.testing.assert_array_equal(b1["image"], b2["image"])
    # different index window -> different crops with overwhelming odds
    b3 = pipe(ds, idx + 1)
    assert not np.array_equal(b1["image"][:8], b3["image"][:8])
    # every output pixel value must exist in the source normalization LUT
    assert np.isfinite(b1["image"]).all()


def test_train_flip_is_a_real_flip():
    ds = _dataset()
    # crop == source size (after no pad): only flip varies
    pipe = ImageBatchPipeline(H, train=True, flip=True, seed=0,
                              mean=(0, 0, 0), std=(1, 1, 1),
                              device_normalize=False)
    idx = np.arange(32)
    batch = pipe(ds, idx)
    src = ds.arrays["image"].astype(np.float32) / 255.0
    flipped = 0
    for i in range(32):
        if np.allclose(batch["image"][i], src[i], atol=1e-6):
            continue
        np.testing.assert_allclose(
            batch["image"][i], src[i][:, ::-1, :], atol=1e-6
        )
        flipped += 1
    assert 0 < flipped < 32  # both outcomes occurred


def test_padded_cifar_style_crop():
    ds = _dataset()
    pipe = ImageBatchPipeline(H, train=True, pad=2, seed=3)
    batch = pipe(ds, np.arange(4))
    assert batch["image"].shape == (4, H, H, C)
    assert np.isfinite(batch["image"]).all()


def test_device_normalize_u8_path_matches_f32_path():
    """uint8 ship + on-device normalize == host-LUT f32, exactly the same
    crops/flips (same (seed, epoch, indices) augmentation stream)."""
    import jax

    ds = _dataset(3)
    idx = np.arange(16)
    f32 = ImageBatchPipeline(crop=8, train=True, seed=7, device_normalize=False)
    u8 = ImageBatchPipeline(crop=8, train=True, seed=7, device_normalize=True)
    a = f32(ds, idx)
    b = u8(ds, idx)
    assert b["image"].dtype == np.uint8
    normalized = jax.jit(u8.device_normalizer())(
        {k: np.asarray(v) for k, v in b.items()}
    )
    np.testing.assert_allclose(
        np.asarray(normalized["image"]), a["image"], atol=1e-6
    )
    np.testing.assert_array_equal(np.asarray(normalized["label"]), a["label"])


def test_device_normalize_through_train_step():
    """u8 batches flow through build_train_step(batch_transform=...) and
    train the same model the f32 path does."""
    import jax
    import jax.numpy as jnp
    import optax

    import pytorch_distributed_tpu as ptd
    from pytorch_distributed_tpu.models.resnet import BasicBlock, ResNet
    from pytorch_distributed_tpu.parallel import DataParallel
    from pytorch_distributed_tpu.train import (
        TrainState,
        build_train_step,
        classification_loss_fn,
    )

    ptd.init_process_group()
    model = ResNet(stage_sizes=[1], block_cls=BasicBlock, num_classes=4,
                   width=8, stem="cifar")
    v = model.init(jax.random.key(0), jnp.zeros((1, 8, 8, 3)), train=False)
    state = TrainState.create(
        apply_fn=model.apply, params=v["params"], tx=optax.sgd(0.1),
        batch_stats=v["batch_stats"],
    )
    pipe = ImageBatchPipeline(crop=8, train=True, device_normalize=True)
    ds = ArrayDataset(
        image=np.random.default_rng(0).integers(
            0, 256, size=(32, 10, 10, 3)
        ).astype(np.uint8),
        label=np.random.default_rng(1).integers(4, size=(32,)).astype(np.int64),
    )
    strategy = DataParallel()
    state = strategy.place(state)
    step = strategy.compile(
        build_train_step(
            classification_loss_fn(model),
            batch_transform=pipe.device_normalizer(),
        ),
        state,
    )
    loader = DataLoader(ds, 16, sharding=strategy.batch_sharding(), fetch=pipe)
    for batch in loader:
        assert batch["image"].dtype == jnp.uint8
        state, metrics = step(state, batch)
    assert np.isfinite(float(metrics["loss"]))


def test_dataloader_fetch_integration():
    ds = _dataset()
    pipe = ImageBatchPipeline(8, train=True, seed=1)
    loader = DataLoader(ds, 16, seed=0, fetch=pipe)
    batches = list(loader)
    assert len(batches) == N // 16
    for b in batches:
        assert b["image"].shape == (16, 8, 8, C)
        assert b["label"].shape == (16,)