"""PTD003 known-good twins: the pipeline stall site as registered."""
from pytorch_distributed_tpu.runtime import faults


def drill_spec():
    with faults.injected("pipeline.stage_stall:mode=kill,match=s1.bwd.m1"):
        pass


def stall_env(env):
    env["PTD_FAULTS"] = "pipeline.stage_stall:mode=stall,seconds=0.5,count=1"
