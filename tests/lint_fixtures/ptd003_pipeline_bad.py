"""PTD003 known-bad: typo'd pipeline stall-site names never fire."""
from pytorch_distributed_tpu.runtime import faults


def drill_spec():
    with faults.injected("pipeline.stall:mode=kill,match=s1.bwd.m1"):  # expect: PTD003
        pass


def stall_env(env):
    env["PTD_FAULTS"] = "pipeline.stage_stal:mode=stall,seconds=0.5"  # expect: PTD003
