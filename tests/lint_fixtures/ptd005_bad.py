"""PTD005 known-bad: one key, two draws, no split between."""
import jax


def double_draw(key, shape):
    a = jax.random.normal(key, shape)
    b = jax.random.uniform(key, shape)  # expect: PTD005
    return a + b


def consumed_by_split(key, shape):
    k1, k2 = jax.random.split(key)
    noise = jax.random.normal(key, shape)  # expect: PTD005
    return k1, k2, noise


def loop_reuse(key, xs):
    out = []
    for x in xs:
        out.append(x + jax.random.normal(key, x.shape))  # expect: PTD005
    return out
