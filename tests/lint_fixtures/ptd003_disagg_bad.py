"""PTD003 known-bad: typo'd serving-fleet site names."""
from pytorch_distributed_tpu.runtime import faults


def router_step(engine_id):
    faults.check("serve.engine_los", path=engine_id)  # expect: PTD003


def pack_frames(request_id):
    faults.check("serve.kv_migate", path=request_id)  # expect: PTD003


def loss_drill():
    with faults.injected("serve.engineloss:mode=raise,count=1"):  # expect: PTD003
        pass


def env_spec(env):
    env["PTD_FAULTS"] = "serve.kv_migrate_:count=1"  # expect: PTD003
