"""PTD001 known-bad: pipeline stage handoffs with a dropped direction.

The r20 host pipeline makes stage == ring rank, so every boundary
handoff sits under a stage guard — the exact shape PTD001 exists for.
A send whose matching recv got edited away deadlocks the neighbor at
its handoff deadline.
"""


def forward_handoff(group, num_stages, act):
    stage = group.rank
    if stage < num_stages - 1:
        group.send(act, stage + 1, tag="act.m0.s1")  # expect: PTD001


def grad_handoff(group, grad):
    stage = group.rank
    if stage == 0:
        group.recv(grad, 1, tag="grad.m0.s0")  # expect: PTD001
    else:
        group.all_reduce(grad)  # expect: PTD001
