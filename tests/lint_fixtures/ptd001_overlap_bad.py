"""PTD001 known-bad: a rank-conditional bucket skip in a drain loop.

The round-14 grad-sync pipeline's safety argument is that every rank
drains the SAME deterministic bucket queue — a rank-guarded skip breaks
lockstep exactly like a guarded collective (the skipping rank's peers
block at the ring until the group deadline). The loop-carried shape is
the one the comm thread actually runs, so the rule must keep seeing
through it.
"""


def drain_with_rank_skip(ring, rank, buckets):
    for i, bucket in enumerate(buckets):
        if rank == 0 and i % 2:
            continue  # rank 0 silently drops odd buckets...
        for item in bucket:
            ring.all_reduce(item)  # expect: PTD001


def tainted_skip(ring, buckets):
    fast_rank = ring.rank != 0
    for bucket in buckets:
        if fast_rank:
            continue  # taint through the local: same divergence
        ring.all_reduce(bucket)  # expect: PTD001
