"""PTD002 known-good twins: the disarmed-cost disciplines that pass."""
from pytorch_distributed_tpu.runtime import faults, tracing


def fetch(dataset, indices):
    # the repo's canonical guarded form: args evaluate only when armed
    span = (
        tracing._NULL_SPAN if tracing._tracer is None
        else tracing.span("ingest.fetch", n=len(indices))
    )
    with span:
        return [dataset[i] for i in indices]


def step():
    # kwarg-free span: one is-None test, the shared no-op
    with tracing.span("train.step"):
        pass


def trivial_args(h, status):
    # constants / names / attribute chains are the documented cheap tier
    with tracing.span("serve.evict", request=h.request_id,
                      status=status.value, attempt=1):
        pass


def active_gate(decoding):
    if tracing.active():
        tracing.instant("serve.tick", active=len(decoding))


def not_none_gate(tr, decoding):
    if tr is not None:
        tracing.counter("queue_depth", len(decoding) + 1)


def shard_write(path):
    faults.check("ckpt.write_shard", path=path)  # bare name: trivial
