"""PTD003 known-bad: typo'd hang-site names never fire."""
from pytorch_distributed_tpu.runtime import faults


def collective_entry(kind):
    return faults.hang_action("comm.hng", kind)  # expect: PTD003


def drill_spec():
    with faults.injected("comms.hang:mode=skip"):  # expect: PTD003
        pass


def stall_spec(env):
    env["PTD_FAULTS"] = "comm.hang_:mode=stall,seconds=0.5"  # expect: PTD003
