"""PTD001 known-bad: rank-conditional control flow in a rebalance.

Two anti-shapes of the r15 balancer: a "leader" computing the new
assignment and broadcasting only from its own branch (ranks != 0 never
reach the collective → the world deadlocks at the ring deadline), and a
slow rank opting out of the rate allgather it feels it doesn't need
(its peers block forever waiting for its row).
"""


def leader_decides_assignment(ring, rate, derive):
    if ring.rank == 0:
        rows = ring.all_gather(rate)  # expect: PTD001
        return derive(rows)
    return None


def slow_rank_skips_the_allgather(ring, rank, busy, rate, derive):
    overloaded = rank == 2 and busy
    if overloaded:
        return None  # opts out: peers block at the ring
    rows = ring.all_gather(rate)  # expect: PTD001
    return derive(rows)
