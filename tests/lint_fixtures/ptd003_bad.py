"""PTD003 known-bad: fault-site names missing from KNOWN_SITES."""
from pytorch_distributed_tpu.runtime import faults


def save_shard(path):
    faults.check("ckpt.writ_shard", path=path)  # expect: PTD003


def poll():
    return faults.fires("step.nan_typo")  # expect: PTD003


def drill_spec():
    with faults.injected("ckpt.swing:count=1;data.deocde:p=0.5"):  # expect: PTD003
        pass


def env_spec(env):
    env["PTD_FAULTS"] = "serve.prefil:count=1"  # expect: PTD003
