"""PTD003 known-good twins: hang-site names all in the registry."""
from pytorch_distributed_tpu.runtime import faults


def collective_entry(kind):
    return faults.hang_action("comm.hang", kind)


def drill_spec():
    with faults.injected("comm.hang:mode=skip,match=all_gather"):
        pass


def stall_spec(env):
    env["PTD_FAULTS"] = "comm.hang:mode=stall,seconds=0.5,count=1"
