"""PTD005 known-good twins: split/fold_in discipline that must pass."""
import jax


def split_first(key, shape):
    k_a, k_b = jax.random.split(key)
    a = jax.random.normal(k_a, shape)
    b = jax.random.uniform(k_b, shape)
    return a + b


def chain_reassign(key, shape):
    # the generate() idiom: consume-and-rebind per step
    key, sub = jax.random.split(key)
    a = jax.random.normal(sub, shape)
    key, sub = jax.random.split(key)
    b = jax.random.uniform(sub, shape)
    return a + b


def fold_in_derivation(key, shape):
    # fold_in is a derivation, not a consumption — per-index streams
    # off one base key are the idiom (train/losses.py cutmix boxes)
    cy = jax.random.uniform(key)
    cx = jax.random.uniform(jax.random.fold_in(key, 1))
    return cy, cx, shape


def branch_exclusive(key, shape, greedy):
    # mutually exclusive arms: only one draw executes
    if greedy:
        return jax.random.normal(key, shape)
    return jax.random.uniform(key, shape)


def loop_rebind(key, xs):
    out = []
    for x in xs:
        key, sub = jax.random.split(key)
        out.append(x + jax.random.normal(sub, x.shape))
    return out
