"""PTD006 known-bad: donated buffers read after the donating call."""
import jax

step = jax.jit(lambda state, batch: state, donate_argnums=(0,))


def run(state, batch):
    new_state = step(state, batch)
    norm = state.sum()  # expect: PTD006
    return new_state, norm


class Engine:
    def __init__(self, fn):
        self._decode = jax.jit(fn, donate_argnums=(1, 2))

    def tick(self, params):
        cache, toks = self._decode(params, self.cache, self.toks)
        stale = self.toks + 1  # expect: PTD006
        self.cache, self.toks = cache, toks
        return stale
