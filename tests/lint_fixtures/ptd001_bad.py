"""PTD001 known-bad: collectives under rank guards with no match."""
import numpy as np


def owner_only_broadcast(ring, rank, vec):
    if rank == 0:
        return ring.broadcast(vec, src=0)  # expect: PTD001
    return vec


def tainted_guard(ring, x):
    is_src = ring.rank == 0
    if is_src:
        ring.all_reduce(x)  # expect: PTD001


def mismatched_branches(ring, rank):
    if rank == 0:
        ring.barrier()  # expect: PTD001
    else:
        ring.all_gather(np.ones(4))  # expect: PTD001


def lonely_send(ring, rank, x):
    if rank == 0:
        ring.send(x, dst=1)  # expect: PTD001
