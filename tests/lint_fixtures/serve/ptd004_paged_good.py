"""PTD004 known-good twins: the per-page write fused under jit — the
forms the real ops/paged_attention.paged_write reaches production in
(traced inside the engine's jitted decode programs)."""
import jax
import jax.numpy as jnp


def _paged_write(pool, new, page_tables, write_pos, keep):
    # not wrapped itself, but called from the jitted tick below: the
    # one-module call-graph closure covers it
    P1, ps = pool.shape[0], pool.shape[1]
    B, W = new.shape[0], new.shape[1]
    pos = write_pos[:, None] + jnp.arange(W)[None, :]
    page = jnp.take_along_axis(page_tables, pos // ps, axis=1)
    dst = jnp.where(keep[:, None], page * ps + pos % ps, P1 * ps)
    flat = pool.reshape((P1 * ps,) + pool.shape[2:])
    flat = flat.at[dst.reshape(-1)].set(
        new.reshape((B * W,) + new.shape[2:]), mode="drop",
    )
    return flat.reshape(pool.shape)


def _decode_tick_fn(pool, new, page_tables, write_pos, keep):
    return _paged_write(pool, new, page_tables, write_pos, keep)


decode_tick = jax.jit(_decode_tick_fn)


@jax.jit
def park_rejected_tail(pool_flat, dst):
    return pool_flat.at[dst].set(0.0, mode="drop")
