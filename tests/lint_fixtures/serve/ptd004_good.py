"""PTD004 known-good twins: the same updates fused into jit."""
import functools

import jax


@jax.jit
def configure_slot(temps, slot, temp):
    return temps.at[slot].set(temp)


@functools.partial(jax.jit, static_argnums=(2,))
def advance(lengths, slot, stride):
    return lengths.at[slot].add(stride)


def _admit_rows_fn(temps, top_ks, slot, temp, top_k):
    # wrapped below via jax.jit(_admit_rows_fn): the engine.py idiom
    return temps.at[slot].set(temp), top_ks.at[slot].set(top_k)


def _persist_row(keys, slot, pair):
    # not wrapped itself, but called from a jitted function in this
    # module: traced under the same jit
    return keys.at[slot].set(pair)


admit_rows = jax.jit(_admit_rows_fn)


class Engine:
    def __init__(self):
        # the bound-method form the serve engine uses
        self._decode = jax.jit(self._decode_fn, donate_argnums=())

    def _decode_fn(self, keys, slot, pair):
        return _persist_row(keys, slot, pair)


park_cursor = jax.jit(lambda lengths, slot: lengths.at[slot].set(0))
