"""PTD004 known-bad: the per-page KV write (round 12's paged_write
shape — flat pool scatter with drop semantics) run EAGERLY."""
import jax.numpy as jnp


def paged_write_eager(pool, new, page_tables, write_pos, keep):
    P1, ps = pool.shape[0], pool.shape[1]
    B, W = new.shape[0], new.shape[1]
    pos = write_pos[:, None] + jnp.arange(W)[None, :]
    page = jnp.take_along_axis(page_tables, pos // ps, axis=1)
    dst = jnp.where(keep[:, None], page * ps + pos % ps, P1 * ps)
    flat = pool.reshape((P1 * ps,) + pool.shape[2:])
    flat = flat.at[dst.reshape(-1)].set(  # expect: PTD004
        new.reshape((B * W,) + new.shape[2:]), mode="drop",
    )
    return flat.reshape(pool.shape)


def park_rejected_tail(pool_flat, dst):
    # the spec tick's rewind helper, eagerly: same dispatch-cost bug
    return pool_flat.at[dst].set(0.0, mode="drop")  # expect: PTD004
