"""PTD004 known-bad: eager scatter updates on the serving hot path."""
import jax.numpy as jnp


def configure_slot(temps, slot, temp):
    # eager dispatch: ~2.4 ms each on this box
    return temps.at[slot].set(temp)  # expect: PTD004


def advance(lengths, slot):
    return lengths.at[slot].add(1)  # expect: PTD004


MODULE_LEVEL = jnp.zeros(8).at[0].set(1.0)  # expect: PTD004
