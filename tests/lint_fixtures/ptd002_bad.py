"""PTD002 known-bad: span/fault-site args computed while disarmed."""
from pytorch_distributed_tpu.runtime import faults, tracing


def fetch(dataset, indices):
    with tracing.span("ingest.fetch", n=len(indices)):  # expect: PTD002
        return [dataset[i] for i in indices]


def decode_tick(decoding):
    tracing.instant("serve.tick", active=len(decoding))  # expect: PTD002


def report(meter):
    tracing.counter("queue_depth", meter.depth() + 1)  # expect: PTD002


def guarded_but_wrong_side(tr, indices):
    # args on the is-None side still evaluate when DISARMED
    span = (
        tracing.span("x", n=len(indices))  # expect: PTD002
        if tr is None
        else tracing._NULL_SPAN
    )
    return span


def shard_write(path, shard_id):
    faults.check("ckpt.write_shard", path=f"{path}/{shard_id}")  # expect: PTD002
