"""PTD001 known-good twin: deterministic queue drains stay silent.

The pipelined engine's real shapes (parallel/overlap.py): FIFO bucket
drains, payload-dependent (NOT rank-dependent) dispatch between the
plain and quantized reduce, and an error-guard that refuses work on
every rank identically.
"""


def drain_fifo(ring, buckets):
    # the comm thread's loop: every rank drains the same queue in the
    # same order — rank never appears in the control flow
    for bucket in buckets:
        for item in bucket:
            ring.all_reduce(item)


def drain_dispatch_by_payload(ring, buckets):
    for bucket in buckets:
        for item, quantized in bucket:
            # per-item DISPATCH on a plan property shared by all ranks
            if quantized:
                ring.all_reduce_q8(item)
            else:
                ring.all_reduce(item)


def drain_with_uniform_error_guard(ring, failed, buckets):
    for bucket in buckets:
        if failed:
            # a poisoned pipeline skips identically on EVERY rank (the
            # abort flag propagates through the shm segment)
            continue
        ring.all_reduce(bucket)
