"""PTD001 known-good twins: lockstep shapes that must stay silent."""
import numpy as np


def uniform_broadcast(ring, rank, vec):
    # rank-dependent PAYLOAD, rank-independent ISSUE order: every rank
    # enters the same collective
    payload = vec if rank == 0 else np.zeros_like(vec)
    return ring.broadcast(payload, src=0)


def p2p_pair(ring, rank, x):
    # the canonical P2P shape: src sends, the peer receives
    if rank == 0:
        ring.send(x, dst=1)
    elif rank == 1:
        return ring.recv(x, src=0)


def p2p_exchange(ring, rank, x):
    # a guarded group doing a full exchange among its own members:
    # bystander ranks are free (P2P blocks only its endpoints)
    if rank in (0, 1):
        if rank == 0:
            ring.send(x, dst=1)
            return ring.recv(x, src=1)
        got = ring.recv(x, src=0)
        ring.send(got, dst=0)
        return got


def matched_branches(ring, rank, x):
    # both branches issue the SAME collective (different args is fine:
    # payload may differ, issue order may not)
    if rank == 0:
        return ring.all_reduce(x, op="sum")
    return ring.all_reduce(np.zeros_like(x), op="sum")


def world_guard(ring, x):
    # world-size guards are not rank guards: every rank agrees on them
    if ring.world_size > 1:
        ring.barrier()
    return x


def subgroup_members(ptd, sub, rank, x):
    # explicit-subgroup collective: membership IS rank-dependent by
    # contract, only the group's ranks participate
    if rank in (0, 2):
        return ptd.all_reduce(x, group=sub)
