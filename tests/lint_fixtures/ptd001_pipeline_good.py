"""PTD001 known-good twins: pipeline boundary handoffs pairwise-complete.

The send<->recv pair across the stage-guard branches (each endpoint
takes one side), and the interior stage's own send+recv set (P2P blocks
only its two endpoints — the hostring contract — so a guarded group
doing a full exchange owes the other branch nothing).
"""


def boundary_handoff(group, act):
    stage = group.rank
    if stage == 0:
        group.send(act, 1, tag="act.m0.s1")
    else:
        group.recv(act, 0, tag="act.m0.s1")


def steady_state_tick(group, num_stages, act, grad):
    stage = group.rank
    if 0 < stage < num_stages - 1:
        group.recv(act, stage - 1, tag="act.m1.s1")
        group.send(grad, stage - 1, tag="grad.m0.s0")
