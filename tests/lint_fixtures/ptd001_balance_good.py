"""PTD001 known-good twin: the rebalance protocol's lockstep shape.

The r15 balancer's safety argument (train/elastic_world.py:_rebalance):
every rank allgathers its rate, then derives the new shard->rank map as
a PURE function of the identical allgathered vector — the allgather IS
the synchronization, and rank appears only in VALUES (which row is
mine), never in the control flow around a collective.
"""


def rebalance_from_allgather(ring, rate, derive):
    # every rank contributes one rate and derives the identical map
    rows = ring.all_gather(rate)
    assignment = derive(rows)
    return assignment


def rebalance_gated_on_shared_step(ring, step, every, rate, derive):
    # the interval gate reads the STEP COUNTER every rank holds
    # identically — all ranks enter (or skip) the collective together
    if every and step % every == 0:
        rows = ring.all_gather(rate)
        return derive(rows)
    return None


def apply_owned_shards(ring, assignment, rank, shards, grads):
    # ownership is rank-dependent DATA (which shards I compute), while
    # the collective itself is issued unconditionally on every rank
    local = [grads[s] for s in shards if assignment[s] == rank]
    return ring.all_gather(local)
