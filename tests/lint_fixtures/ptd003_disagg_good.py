"""PTD003 known-good twins for the r18 serving-fleet sites."""
from pytorch_distributed_tpu.runtime import faults


def router_step(engine_id):
    faults.check("serve.engine_loss", path=engine_id)


def pack_frames(request_id):
    faults.check("serve.kv_migrate", path=request_id)


def loss_drill():
    with faults.injected("serve.engine_loss:mode=raise,count=1,match=d0"):
        pass


def env_spec(env):
    env["PTD_FAULTS"] = "serve.kv_migrate:count=1;serve.engine_loss:after=4"
