"""PTD006 known-good twins: donated buffers rebound before any read."""
import jax

step = jax.jit(lambda state, batch: state, donate_argnums=(0,))
eager_step = jax.jit(lambda state, batch: state)  # no donation


def run(state, batch):
    state = step(state, batch)  # rebind kills the stale reference
    return state, state.sum()


def no_donation(state, batch):
    out = eager_step(state, batch)
    return out, state.sum()  # state was not donated


class Engine:
    def __init__(self, fn):
        self._decode = jax.jit(fn, donate_argnums=(1, 2))

    def tick(self, params):
        # the engine idiom: every donated row rebinds in the call's own
        # assignment, reads come after
        self.cache, self.toks = self._decode(params, self.cache, self.toks)
        return self.toks + 1
