"""PTD003 known-good twins: every site name is in the registry."""
from pytorch_distributed_tpu.runtime import faults


def save_shard(path):
    faults.check("ckpt.write_shard", path=path)


def poll():
    return faults.fires("step.nan")


def drill_spec():
    with faults.injected("ckpt.swing:count=1;data.decode:p=0.5"):
        pass


def env_spec(env):
    env["PTD_FAULTS"] = "serve.prefill:count=1;serve.decode:p=0.1"


def dynamic_site(site, path):
    # non-literal site names are out of the static envelope — the
    # runtime's own registry check covers them when armed
    faults.check(site, path=path)
