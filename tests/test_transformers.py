"""Transformer model tests: shapes, param counts (via eval_shape — no
materialization of the big configs), causality, TP rules, tiny train steps."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest
import optax
from jax.sharding import PartitionSpec as P

from pytorch_distributed_tpu.models import (
    BertConfig,
    BertForSequenceClassification,
    BertModel,
    GPT2Config,
    GPT2LMHead,
    LlamaConfig,
    LlamaForCausalLM,
    bert_partition_rules,
    gpt2_partition_rules,
    llama_partition_rules,
)
from pytorch_distributed_tpu.parallel import FSDP, ZeRO1
from pytorch_distributed_tpu.runtime.mesh import MeshSpec, make_mesh
from pytorch_distributed_tpu.train import (
    TrainState,
    build_train_step,
    causal_lm_loss_fn,
    text_classification_loss_fn,
)


def abstract_param_count(model, *args, **kwargs):
    shapes = jax.eval_shape(lambda: model.init(jax.random.key(0), *args, **kwargs))
    return sum(
        int(np.prod(x.shape)) for x in jax.tree_util.tree_leaves(shapes["params"])
    )


class TestParamCounts:
    def test_bert_base_110m(self):
        model = BertModel(BertConfig.base())
        n = abstract_param_count(model, jnp.zeros((1, 16), jnp.int32))
        # HF bert-base-uncased: 109,482,240 (incl. pooler)
        assert 108e6 < n < 111e6, n

    def test_gpt2_medium_355m(self):
        model = GPT2LMHead(GPT2Config.medium())
        n = abstract_param_count(model, jnp.zeros((1, 16), jnp.int32))
        # HF gpt2-medium: 354,823,168 (tied head)
        assert 350e6 < n < 360e6, n

    def test_llama3_8b(self):
        model = LlamaForCausalLM(LlamaConfig.llama3_8b())
        n = abstract_param_count(model, jnp.zeros((1, 16), jnp.int32))
        # Meta Llama-3-8B: 8,030,261,248
        assert 7.9e9 < n < 8.1e9, n


class TestForward:
    @pytest.mark.slow
    def test_bert_shapes(self):
        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg, num_labels=3)
        ids = jnp.ones((2, 16), jnp.int32)
        v = model.init(jax.random.key(0), ids)
        logits = model.apply(v, ids)
        assert logits.shape == (2, 3)
        assert logits.dtype == jnp.float32

    @pytest.mark.slow  # r5 profile refit: bert HF logit parity exercises the mask
    def test_bert_attention_mask_effect(self):
        cfg = BertConfig.tiny()
        model = BertModel(cfg)
        ids = jnp.ones((1, 8), jnp.int32)
        v = model.init(jax.random.key(0), ids)
        seq_full, _ = model.apply(v, ids, jnp.ones((1, 8), jnp.bool_))
        ids2 = ids.at[:, 4:].set(99)  # tokens behind the mask
        seq_masked, _ = model.apply(
            v, ids2, jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.bool_)
        )
        seq_masked_same, _ = model.apply(
            v, ids, jnp.asarray([[1, 1, 1, 1, 0, 0, 0, 0]], jnp.bool_)
        )
        # visible positions only depend on visible tokens
        np.testing.assert_allclose(
            np.asarray(seq_masked)[:, :4],
            np.asarray(seq_masked_same)[:, :4],
            rtol=2e-2, atol=2e-2,
        )

    def test_gpt2_causal_lm_shapes(self):
        cfg = GPT2Config.tiny()
        model = GPT2LMHead(cfg)
        ids = jnp.ones((2, 12), jnp.int32)
        v = model.init(jax.random.key(0), ids)
        logits = model.apply(v, ids)
        assert logits.shape == (2, 12, cfg.vocab_size)

    def test_gpt2_causality(self):
        cfg = GPT2Config.tiny()
        model = GPT2LMHead(cfg)
        ids = jnp.arange(24, dtype=jnp.int32).reshape(2, 12) % cfg.vocab_size
        v = model.init(jax.random.key(0), ids)
        base = model.apply(v, ids)
        ids2 = ids.at[:, 8:].set(7)
        pert = model.apply(v, ids2)
        np.testing.assert_allclose(
            np.asarray(base)[:, :8], np.asarray(pert)[:, :8], rtol=1e-4, atol=1e-4
        )

    def test_gpt2_seq_too_long_raises(self):
        cfg = GPT2Config.tiny()
        model = GPT2LMHead(cfg)
        ids = jnp.ones((1, cfg.n_positions + 1), jnp.int32)
        with pytest.raises(ValueError, match="n_positions"):
            model.init(jax.random.key(0), ids)

    @pytest.mark.slow  # r5 profile refit: causality pinned by attention + generation suites
    def test_llama_shapes_and_causality(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids = jnp.arange(32, dtype=jnp.int32).reshape(2, 16) % cfg.vocab_size
        v = model.init(jax.random.key(0), ids)
        logits = model.apply(v, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        pert = model.apply(v, ids.at[:, 10:].set(3))
        np.testing.assert_allclose(
            np.asarray(logits)[:, :10], np.asarray(pert)[:, :10],
            rtol=1e-4, atol=1e-4,
        )

    def test_llama_gqa_config(self):
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        v = model.init(jax.random.key(0), jnp.ones((1, 8), jnp.int32))
        k_kernel = v["params"]["layers"]["block"]["k"]["kernel"]
        assert k_kernel.shape == (
            cfg.num_layers, cfg.hidden_size, cfg.num_kv_heads, cfg.head_dim
        )


class TestTrainSteps:
    @pytest.mark.slow
    def test_gpt2_zero1_accum_step(self):
        # the recipe-4 shape: ZeRO-1 + grad accumulation (BASELINE.json:10)
        mesh = make_mesh(MeshSpec(dp=4, fsdp=1, tp=2))
        cfg = GPT2Config.tiny()
        model = GPT2LMHead(cfg)
        ids = np.random.default_rng(0).integers(
            cfg.vocab_size, size=(8, 16)
        ).astype(np.int32)
        v = model.init(jax.random.key(0), jnp.asarray(ids[:1]))
        state = TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=optax.adamw(1e-3)
        )
        strategy = ZeRO1(mesh, extra_rules=gpt2_partition_rules())
        state = strategy.place(state)
        step = strategy.compile(
            build_train_step(causal_lm_loss_fn(model), accum_steps=2), state
        )
        batch = strategy.shard_batch({"input_ids": ids})
        state, m1 = step(state, batch)
        state, m2 = step(state, batch)
        assert float(m2["loss"]) < float(m1["loss"])
        # ZeRO-1 placement: opt state sharded, params TP-only
        mu = state.opt_state[0].mu
        assert "dp" in str(mu["blocks"]["block"]["mlp_up"]["kernel"].sharding.spec)

    @pytest.mark.slow
    def test_llama_fsdp_tp_step(self):
        # the recipe-5 shape: FSDP full-shard (BASELINE.json:11) + TP
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        ids = np.random.default_rng(1).integers(
            cfg.vocab_size, size=(8, 16)
        ).astype(np.int32)
        v = model.init(jax.random.key(0), jnp.asarray(ids[:1]))
        state = TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=optax.adamw(1e-3)
        )
        strategy = FSDP(mesh, extra_rules=llama_partition_rules())
        state = strategy.place(state)
        # TP+FSDP composition on the gate kernel [hidden, ffn]
        spec = state.params["layers"]["block"]["gate"]["kernel"].sharding.spec
        assert spec == P(None, "fsdp", "tp")  # [L, hidden, ffn]: tp rule + fsdp augment
        step = strategy.compile(build_train_step(causal_lm_loss_fn(model)), state)
        state, m = step(state, strategy.shard_batch({"input_ids": ids}))
        assert np.isfinite(float(m["loss"]))

    def test_create_sharded_never_replicates(self):
        mesh = make_mesh(MeshSpec(dp=2, fsdp=2, tp=2))
        cfg = LlamaConfig.tiny()
        model = LlamaForCausalLM(cfg)
        strategy = FSDP(mesh, extra_rules=llama_partition_rules())

        def make_state(key):
            v = model.init(key, jnp.zeros((1, 8), jnp.int32))
            return TrainState.create(
                apply_fn=model.apply, params=v["params"], tx=optax.adamw(1e-3)
            )

        state = strategy.create_sharded(make_state, jax.random.key(0))
        spec = state.params["layers"]["block"]["gate"]["kernel"].sharding.spec
        assert spec == P(None, "fsdp", "tp")  # [L, hidden, ffn]: tp rule + fsdp augment
        mu = state.opt_state[0].mu  # adamw: (ScaleByAdamState, ...)
        assert (
            mu["layers"]["block"]["gate"]["kernel"].sharding.spec
            == P(None, "fsdp", "tp")
        )

    @pytest.mark.slow
    def test_bert_ddp_amp_step(self):
        # the recipe-3 shape: DDP + autocast bf16 (BASELINE.json:9)
        import pytorch_distributed_tpu as ptd
        from pytorch_distributed_tpu.parallel import DataParallel

        mesh = make_mesh(MeshSpec(dp=8))
        cfg = BertConfig.tiny()
        model = BertForSequenceClassification(cfg, num_labels=2)
        rng = np.random.default_rng(2)
        batch = {
            "input_ids": rng.integers(cfg.vocab_size, size=(16, 12)).astype(np.int32),
            "label": rng.integers(2, size=(16,)).astype(np.int32),
        }
        with ptd.autocast():  # bf16 compute; GradScaler is identity
            v = model.init(jax.random.key(0), jnp.asarray(batch["input_ids"][:1]))
            state = TrainState.create(
                apply_fn=model.apply, params=v["params"], tx=optax.adamw(1e-4)
            )
            strategy = DataParallel(mesh, extra_rules=bert_partition_rules())
            state = strategy.place(state)
            step = strategy.compile(
                build_train_step(text_classification_loss_fn(model)), state
            )
        state, m = step(state, strategy.shard_batch(batch))
        assert np.isfinite(float(m["loss"]))
        assert 0.0 <= float(m["accuracy"]) <= 1.0


@pytest.mark.slow
def test_remat_policies_are_numerically_identical():
    """remat changes WHEN activations are computed, never WHAT: loss and
    grads must match the no-remat baseline bitwise-closely for every
    policy (full recompute, save-dots, save-dots-no-batch)."""
    import dataclasses

    import optax

    from pytorch_distributed_tpu.models.llama import (
        LlamaConfig,
        LlamaForCausalLM,
    )
    from pytorch_distributed_tpu.train import (
        build_train_step,
        causal_lm_loss_fn,
        TrainState,
    )

    ids = jnp.asarray(
        np.random.default_rng(0).integers(512, size=(2, 16)).astype(np.int32)
    )
    results = {}
    for label, kw in {
        "none": dict(remat=False),
        "full": dict(remat=True, remat_policy="full"),
        "dots": dict(remat=True, remat_policy="dots"),
        "dots_no_batch": dict(remat=True, remat_policy="dots_no_batch"),
    }.items():
        cfg = dataclasses.replace(LlamaConfig.tiny(), **kw)
        model = LlamaForCausalLM(cfg)
        params = model.init(jax.random.key(0), ids)["params"]
        state = TrainState.create(
            apply_fn=model.apply, params=params, tx=optax.sgd(0.1)
        )
        step = jax.jit(build_train_step(causal_lm_loss_fn(model)))
        new_state, metrics = step(state, {"input_ids": ids})
        results[label] = (
            float(metrics["loss"]),
            np.asarray(jax.tree_util.tree_leaves(new_state.params)[0]),
        )
    base_loss, base_w = results["none"]
    for label, (loss, w) in results.items():
        assert loss == pytest.approx(base_loss, rel=1e-5), label
        np.testing.assert_allclose(w, base_w, rtol=1e-5, atol=1e-6,
                                   err_msg=label)


def test_bad_remat_policy_raises():
    from pytorch_distributed_tpu.models.scan import remat_policy

    with pytest.raises(ValueError, match="remat_policy"):
        remat_policy("everything")
    assert remat_policy("full") is None
    assert remat_policy("dots") is not None


class TestMaskedLM:
    def test_mask_tokens_80_10_10_and_protection(self):
        from pytorch_distributed_tpu.models import mask_tokens

        rng = jax.random.key(0)
        ids = jnp.asarray(
            np.random.default_rng(0).integers(5, 1000, size=(64, 128))
        ).astype(jnp.int32)
        special = jnp.zeros_like(ids, dtype=bool).at[:, 0].set(True)
        masked, labels = jax.jit(
            lambda r, x, s: mask_tokens(
                r, x, mask_token_id=4, vocab_size=1000, mask_prob=0.15,
                special_mask=s,
            )
        )(rng, ids, special)
        sel = np.asarray(labels) != -100
        # selection rate ~15%
        assert 0.12 < sel.mean() < 0.18, sel.mean()
        # protected column never selected, never altered
        assert not sel[:, 0].any()
        np.testing.assert_array_equal(
            np.asarray(masked)[:, 0], np.asarray(ids)[:, 0]
        )
        # unselected positions unchanged
        np.testing.assert_array_equal(
            np.asarray(masked)[~sel], np.asarray(ids)[~sel]
        )
        # labels at selected positions are the ORIGINAL ids
        np.testing.assert_array_equal(
            np.asarray(labels)[sel], np.asarray(ids)[sel]
        )
        # of selected: ~80% [MASK], ~10% random, ~10% unchanged
        m = np.asarray(masked)[sel]
        orig = np.asarray(ids)[sel]
        frac_mask = (m == 4).mean()
        frac_keep = (m == orig).mean()
        assert 0.72 < frac_mask < 0.88, frac_mask
        assert 0.05 < frac_keep < 0.16, frac_keep

    def test_mlm_head_ties_embeddings(self):
        from pytorch_distributed_tpu.models import (
            BertConfig, BertForMaskedLM, BertModel,
        )

        cfg = BertConfig.tiny()
        model = BertForMaskedLM(cfg)
        ids = jnp.zeros((2, 16), jnp.int32)
        v = model.init(jax.random.key(0), ids)
        # no separate [H, V] decoder matrix: total params ~= trunk + MLM
        # transform (H*H + 2H) + bias (V) — i.e. tying holds
        trunk = BertModel(cfg).init(jax.random.key(0), ids)
        n_trunk = sum(x.size for x in jax.tree_util.tree_leaves(trunk))
        n_mlm = sum(x.size for x in jax.tree_util.tree_leaves(v))
        h, vv = cfg.hidden_size, cfg.vocab_size
        expected_extra = h * h + h + 2 * h + vv  # dense + ln + bias
        assert n_mlm - n_trunk == expected_extra, (n_mlm, n_trunk)
        logits = model.apply(v, ids)
        assert logits.shape == (2, 16, cfg.vocab_size)
        assert logits.dtype == jnp.float32

    @pytest.mark.slow
    def test_tiny_bert_mlm_learns(self):
        """Dynamic-masking MLM over a tiny corpus: loss falls, masked
        accuracy rises well above chance."""
        import optax

        from pytorch_distributed_tpu.models import BertConfig, BertForMaskedLM
        from pytorch_distributed_tpu.train import (
            TrainState, build_train_step, masked_lm_loss_fn,
        )

        cfg = BertConfig.tiny()
        model = BertForMaskedLM(cfg)
        rng = np.random.default_rng(0)
        # highly structured corpus: token t+1 follows t (mod 50, offset 5)
        starts = rng.integers(5, 55, size=(32,))
        ids = ((starts[:, None] + np.arange(64)[None, :] - 5) % 50 + 5
               ).astype(np.int32)
        batch = {"input_ids": jnp.asarray(ids)}
        v = model.init(jax.random.key(0), batch["input_ids"])
        state = TrainState.create(
            apply_fn=model.apply, params=v["params"], tx=optax.adam(3e-3)
        )
        step = jax.jit(build_train_step(masked_lm_loss_fn(
            model, mask_token_id=4, vocab_size=cfg.vocab_size
        )))
        first = None
        for i in range(150):
            state, metrics = step(state, batch)
            if first is None:
                first = float(metrics["loss"])
        # chance CE over 1024-vocab ~= 6.9; chance accuracy ~= 0.001
        assert float(metrics["loss"]) < first / 3, (
            first, float(metrics["loss"])
        )
        assert float(metrics["accuracy"]) > 0.3, float(metrics["accuracy"])
        assert 0.10 < float(metrics["mask_frac"]) < 0.20
